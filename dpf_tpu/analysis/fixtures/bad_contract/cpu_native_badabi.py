"""Seeded drift: ctypes argtypes disagreeing with the C header.

dpfn_gen takes (alpha, log_n, seed0, seed1, ka, kb) — six parameters —
but this wiring drops the final key-output pointer.  Every call through
it would push the wrong frame.  The surface-contract pass must report
the argtypes mismatch against the extern "C" declaration (plus, since
this file substitutes the whole ctypes surface, an unwired finding for
every other exported symbol).
"""

import ctypes

u8p = ctypes.POINTER(ctypes.c_uint8)

lib = None  # never executed — the pass reads this file as AST only

lib.dpfn_gen.restype = ctypes.c_int
# drift: the C side takes six parameters (..., u8p ka, u8p kb)
lib.dpfn_gen.argtypes = [ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p]
