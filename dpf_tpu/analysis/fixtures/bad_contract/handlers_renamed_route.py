"""Seeded drift: route id 1 renamed on the Python side only.

The Go bridge still says wire2RouteGen = 1, so the surface-contract
pass must report both halves of the tear: the renamed Python path has
no Go const, and the orphaned Go const names no Python route.
"""

ROUTE_IDS = {
    1: "/v1/generate",  # drift: the tree says /v1/gen
    2: "/v1/eval",
    3: "/v1/evalfull",
    4: "/v1/evalfull_batch",
    5: "/v1/eval_points_batch",
    6: "/v1/dcf_gen",
    7: "/v1/dcf_eval_points",
    8: "/v1/dcf_interval_gen",
    9: "/v1/dcf_interval_eval",
    10: "/v1/hh/gen",
    11: "/v1/hh/eval",
    12: "/v1/agg/submit",
    13: "/v1/pir/db",
    14: "/v1/pir/query",
    15: "/v1/warmup",
}

SINK_ROUTES = frozenset({"/v1/agg/submit", "/v1/pir/db"})
