"""Seeded drift: two wire2 frame types sharing a value.

T_RESP_DATA collides with T_RESP here — a demultiplexer could not tell
a response head from a response body chunk.  The surface-contract pass
must report the collision (and the resulting divergence from the Go
frame table).
"""

import struct

MAGIC = b"DPF2\x01\x00\x00\x00"

_HDR = struct.Struct("<IBBHI")
_RESP = struct.Struct("<HHdQ")

T_HEADERS = 1
T_DATA = 2
T_RESP = 3
T_RESP_DATA = 3  # drift: the tree (and Go) say 4
T_GOAWAY = 5
T_PING = 6
T_PONG = 7

F_END_STREAM = 1

_CLIENT_CHUNK = 1 << 20
