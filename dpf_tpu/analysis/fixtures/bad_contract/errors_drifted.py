"""Seeded drift: an error code renamed in the canonical table only.

"unavailable" becomes "overloaded" here while handlers.py still replies
with _reply_error("unavailable", ...) and the Go APIError doc still
maps "unavailable" (503) — the surface-contract pass must report both
the undeclared reply code and the Go-side orphan.
"""

CODES: dict[str, int] = {
    "shed": 429,
    "overloaded": 503,  # drift: the tree says "unavailable"
    "deadline": 504,
    "internal": 500,
    "bad_request": 400,
    "cold": 503,
    "breaker_open": 503,
    "profile_forbidden": 403,
    "profile_active": 409,
}
