"""Seeded violations for the host-sync pass (NEVER imported by
production code; excluded from real-tree scans)."""

import jax
import jax.numpy as jnp
import numpy as np


def hot_loop(xs, out):
    total = 0
    for x in xs:
        y = jnp.sum(x)
        y.block_until_ready()  # seeded: blocking sync in a loop
        total += int(jnp.max(x))  # seeded: device scalar pulled to host
    host = np.asarray(out)  # seeded: bare materialization, no annotation
    probe = jax.device_get(out)  # seeded: blocking D2H
    return total, host, probe


def aliased_probe(out):
    from jax import device_get

    return device_get(out)  # seeded: aliased-import D2H bypass


def sanctioned(words, xs):
    coerced = np.asarray(xs, dtype=np.uint64)  # CLEAN: host-side coercion
    # host-sync: fixture's sanctioned chunk D2H
    final = np.asarray(words)
    return coerced, final
