"""Seeded BUDGET-BUSTING toy dispatch bodies for the perf-contract
verifier.

Each entry is a miniature serving dispatch with exactly one
performance-contract violation the resource model must catch — the
regressions the budgets exist to stop:

  * an EXTRA per-chunk all-reduce on a fold that budgets one (the
    "someone added a second psum and halved agg throughput" regression),
  * a collective moved INSIDE the chunk scan (one all-reduce per
    iteration instead of per dispatch),
  * a dropped donation (the jit lost its ``donate_argnums`` — steady
    state silently re-allocates every carry),
  * a donated carry returned as a live output (the caller's handle is
    dead by the donation contract),
  * a host callback inside a dispatch body,
  * the chunk-index-as-Python-int retrace bomb (every chunk index
    compiles its own executable — the zero-retrace-after-warmup
    contract dies quietly).

``PERF_FIXTURES`` is consumed by tests/test_analysis.py: each entry is
``(name, build, expected_finding_kind)`` where ``build()`` returns
``(closed_jaxpr, PerfContract)`` for ``perf.certify.check_route``.  The
donation-site fixtures live in ``DONATION_FIXTURES``:
``(name, site, expected_kind)`` for ``perf.certify.check_donation_site``.

This file lives in ``dpf_tpu/analysis/fixtures/`` so it is EXCLUDED
from the AST passes' default scans and never imported by production
code — only the tests trace it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..perf.contracts import DonationSite, PerfContract


def _carry_rows():
    return (
        jnp.zeros(64, jnp.uint32), jnp.zeros((8 * 32, 64), jnp.uint32),
    )


def _mesh8():
    from ...parallel.sharding import make_mesh

    return make_mesh(8, 1)


def extra_allreduce_fold():
    """A sharded XOR fold that all-reduces TWICE per chunk — the second
    all_gather is pure waste the one-all-reduce budget must catch."""
    from ...parallel.sharding import KEYS_AXIS, shard_map_compat, xor_allreduce
    from jax.sharding import PartitionSpec as P

    mesh = _mesh8()

    def body(carry, rows):
        local = jax.lax.reduce(rows, np.uint32(0), jax.lax.bitwise_xor, (0,))
        once = xor_allreduce(local, KEYS_AXIS)
        twice = xor_allreduce(once, KEYS_AXIS)  # the seeded extra reduce
        return carry ^ twice

    fn = shard_map_compat(
        body, mesh=mesh, in_specs=(P(None), P(KEYS_AXIS, None)),
        out_specs=P(None), check_vma=False,
    )
    closed = jax.make_jaxpr(fn)(*_carry_rows())
    return closed, PerfContract(collectives={"all_gather": 1})


def loop_allreduce_fold():
    """The all-reduce moved INSIDE the chunk scan: one collective per
    iteration per dispatch — the budget says one per DISPATCH."""
    from ...parallel.sharding import KEYS_AXIS, shard_map_compat, xor_allreduce
    from jax.sharding import PartitionSpec as P

    mesh = _mesh8()

    def body(carry, rows):
        chunks = rows.reshape(4, -1, rows.shape[-1])

        def step(c, chunk):
            local = jax.lax.reduce(
                chunk, np.uint32(0), jax.lax.bitwise_xor, (0,)
            )
            return c ^ xor_allreduce(local, KEYS_AXIS), None

        out, _ = jax.lax.scan(step, carry, chunks)
        return out

    fn = shard_map_compat(
        body, mesh=mesh, in_specs=(P(None), P(KEYS_AXIS, None)),
        out_specs=P(None), check_vma=False,
    )
    closed = jax.make_jaxpr(fn)(*_carry_rows())
    return closed, PerfContract(collectives={"all_gather": 4})


def callback_in_dispatch():
    """A host callback (debug_print) in the dispatch body: a host round
    trip per dispatch that the sanctioned count (0) must catch."""

    def body(carry, rows):
        folded = carry ^ jax.lax.reduce(
            rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
        )
        jax.debug.print("folded[0]={x}", x=folded[0])  # the host crossing
        return folded

    closed = jax.make_jaxpr(body)(*_carry_rows())
    return closed, PerfContract()


def live_copy_donation():
    """The donated carry handed straight back as a second output: the
    caller's handle is dead by the donation contract."""

    def body(carry, rows):
        folded = carry ^ jax.lax.reduce(
            rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
        )
        return folded, carry  # the seeded live copy

    closed = jax.make_jaxpr(body)(*_carry_rows())
    return closed, PerfContract(donated=(0,))


def retrace_bomb_chunk():
    """The chunk index baked in as a Python int: the traced signature
    loses the operand, so every chunk index is its own XLA compile —
    the contract's declared chunk invar must not resolve."""
    j = 0  # Python int closure — THE bomb (jnp.int32 would be traced)

    def body(sel, db):
        sw = 4
        sel_j = jax.lax.dynamic_slice_in_dim(sel, j * sw, sw, axis=1)
        db_j = jax.lax.dynamic_slice_in_dim(db, j * 128, 128, axis=0)
        return (sel_j[:, :1] & db_j[:1, :1]).sum()

    closed = jax.make_jaxpr(body)(
        jnp.zeros((32, 16), jnp.uint32), jnp.zeros((512, 2), jnp.uint32)
    )
    return closed, PerfContract(chunk_invar=2)


PERF_FIXTURES = (
    ("extra_allreduce_fold", extra_allreduce_fold, "collective-budget"),
    ("loop_allreduce_fold", loop_allreduce_fold, "loop-collective"),
    ("callback_in_dispatch", callback_in_dispatch, "host-crossing"),
    ("live_copy_donation", live_copy_donation, "donation-live-copy"),
    ("retrace_bomb_chunk", retrace_bomb_chunk, "chunk-index-static"),
)


# ---------------------------------------------------------------------------
# Donation-site fixtures (for check_donation_site)
# ---------------------------------------------------------------------------


def _dropped_donation_site() -> DonationSite:
    """A 'donated twin' whose jit silently lost its donate_argnums —
    the declared donation never reaches the lowering."""

    def body(carry, rows):
        return carry ^ jax.lax.reduce(
            rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
        )

    def build():
        return jax.jit(body), body, _carry_rows()  # no donate_argnums!

    return DonationSite(
        "fixtures.dropped_donation", (), (), (0,), build,
    )


def _honored_donation_site() -> DonationSite:
    """The negative space: the same twin donating properly must verify
    clean (the fixture fires on the drop, not on the pattern)."""

    def body(carry, rows):
        return carry ^ jax.lax.reduce(
            rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
        )

    def build():
        return jax.jit(body, donate_argnums=(0,)), body, _carry_rows()

    return DonationSite(
        "fixtures.honored_donation", (), (), (0,), build,
    )


DONATION_FIXTURES = (
    ("dropped_donation", _dropped_donation_site, "donation-dropped"),
    ("honored_donation", _honored_donation_site, None),
)
