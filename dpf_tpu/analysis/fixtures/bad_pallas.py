"""Seeded violations for the pallas-jit pass (NEVER imported by
production code; excluded from real-tree scans — its namespace comes
from the pass's constant-assignment fallback, so nothing here runs)."""

import functools

import jax
from jax.experimental import pallas as pl

_VMEM_BUDGET = 1 << 20
_TILE = 128


def unannotated_kernel(x):
    # seeded: no footprint model annotation at all.
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)


def over_budget_kernel(x):
    # seeded: model evaluates fine but exceeds _VMEM_BUDGET.
    # vmem: 64 * _TILE * _TILE * 4
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)


def fitting_kernel(x):
    # CLEAN: within budget.
    # vmem: 2 * _TILE * _TILE * 4
    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)


# seeded: list static_argnums — the unhashable retrace hazard.
bad_jit = functools.partial(jax.jit, static_argnums=[0, 1])


# seeded: computed static_argnames via dict.
worse_jit = jax.jit(lambda cfg, x: x, static_argnames={"cfg": 1})


# CLEAN: tuple-of-int literals.
good_jit = functools.partial(jax.jit, static_argnums=(0, 1))

from jax import jit  # noqa: E402
from jax.experimental.pallas import pallas_call  # noqa: E402

# seeded: the ALIASED-import bypasses — a bare from-imported jit with a
# list spec, and a bare pallas_call with no footprint model.
aliased_jit = jit(lambda x: x, static_argnums=[0])


def aliased_kernel(x):
    return pallas_call(lambda r, o: None, out_shape=x)(x)
