"""Seeded-LEAKY toy evaluators for the oblivious-trace verifier.

Each function is a miniature "DPF evaluator" with exactly one
data-obliviousness violation the taint lattice must catch — the jaxpr-
level failure modes the verifier exists for (a secret-predicated
``lax.cond``, a secret-indexed ``dynamic_slice``, a secret control word
cast to float, a ``debug_print`` of a seed, a secret-bounded
``while_loop``, a secret VMEM index inside a Pallas kernel).  The tests
(tests/test_oblivious.py) trace each one through the real verifier and
assert >= 1 finding of the expected kind; the real production routes
must stay clean.

This file lives in ``dpf_tpu/analysis/fixtures/`` so it is EXCLUDED
from the AST passes' default scans and never imported by production
code — only the tests trace it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def leaky_cond_eval(seeds, xs):
    """Branches on the seed's low bit: the taken side is timing-visible.
    jnp.where would be fine; lax.cond is the leak."""
    return jax.lax.cond(
        (seeds[0] & 1) == 1, lambda: xs + 1, lambda: xs - 1
    )


def leaky_slice_eval(seeds, table):
    """Table lookup at a secret-derived index: the memory access pattern
    IS the secret (the classic cache-timing shape, on-device)."""
    start = (seeds[0] & 7).astype(jnp.int32)
    return jax.lax.dynamic_slice(table, (start,), (1,))


def leaky_gather_eval(seeds, table):
    """Same leak through gather (jnp fancy indexing with a traced secret
    index lowers to gather)."""
    idx = (seeds & 3).astype(jnp.int32)
    return table[idx]


def leaky_float_eval(seeds):
    """Secret words pushed through float32: float units are not
    constant-time everywhere, and NaN/inf payloads encode bits."""
    return seeds.astype(jnp.float32).sum()


def leaky_debug_eval(seeds, xs):
    """debug_print of a seed inside a jitted graph: the payload leaves
    the device for the host console."""
    jax.debug.print("seed word: {s}", s=seeds[0])
    return xs ^ seeds


def leaky_while_eval(seeds, xs):
    """Trip count depends on a seed word: wall time leaks its magnitude."""

    def cond(st):
        i, _ = st
        return i < (seeds[0] & jnp.uint32(15))

    def body(st):
        i, acc = st
        return i + 1, acc ^ xs

    _, acc = jax.lax.while_loop(cond, body, (jnp.uint32(0), xs))
    return acc


def leaky_kernel_eval(seeds, table):
    """Secret-indexed VMEM load inside a Pallas kernel (the accelerator
    form of leaky_slice_eval)."""
    from jax.experimental import pallas as pl

    def kernel(s_ref, t_ref, o_ref):
        i = s_ref[0] & 7
        o_ref[0] = pl.load(t_ref, (pl.dslice(i, 1),))[0]

    # vmem: 4 * (8 + 8 + 1) * 2  # knob-ok: fixture (excluded from scans)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1,), jnp.uint32),
        interpret=True,
    )(seeds, table)


def leaky_kernel_loop_eval(seeds, table):
    """The same secret-indexed VMEM load, hidden inside a fori_loop body
    — the kernel-mode Ref discipline must survive sub-jaxpr descent
    (a level-walk loop is exactly the shape the real kernels have)."""
    from jax.experimental import pallas as pl

    def kernel(s_ref, t_ref, o_ref):
        def body(j, acc):
            i = s_ref[j] & 7
            return acc ^ pl.load(t_ref, (pl.dslice(i, 1),))[0]

        o_ref[0] = jax.lax.fori_loop(0, 4, body, jnp.uint32(0))

    # vmem: 4 * (8 + 8 + 1) * 2  # knob-ok: fixture (excluded from scans)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1,), jnp.uint32),
        interpret=True,
    )(seeds, table)


def leaky_hh_descend_eval(counts, xs):
    """A heavy-hitters round that keeps descending while a SECRET count
    clears the threshold: the trip count — and so the number of
    candidate evaluations the device performs — leaks the count's
    magnitude.  The production driver (apps/heavy_hitters.py) thresholds
    on HOST over PUBLIC XOR-reconstructed counts (documented as such in
    DESIGN §13); this is the device-side shape it must never take."""

    def cond(st):
        c, _ = st
        return jnp.max(c) > jnp.uint32(3)

    def body(st):
        c, acc = st
        return c >> 1, acc ^ xs

    _, acc = jax.lax.while_loop(cond, body, (counts, xs))
    return acc


def leaky_shard_index_eval(seeds, table):
    """Slices a 'shard subtree' by a SECRET-derived index inside a
    shard_map body — the forbidden mesh-serving shape.  The public way
    a shard picks its slice is ``jax.lax.axis_index`` over the mesh
    axis (a trace-time-public coordinate, what the sharded evaluators in
    parallel/sharding.py do); deriving it from key material makes the
    partition layout itself key-dependent, observable as cross-chip
    traffic skew.  Built on a 1-device mesh so the fixture fires in any
    test environment — the leak is in the dataflow, not the topology."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from dpf_tpu.parallel.sharding import shard_map_compat

    mesh = Mesh(np.array(jax.devices()[:1]), ("keys",))

    def body(s, t):
        j = (s[0] & jnp.uint32(3)).astype(jnp.int32)
        return jax.lax.dynamic_slice_in_dim(t, j, 2, axis=0)

    return shard_map_compat(body, mesh, (P(), P()), P())(seeds, table)


def leaky_pir_chunk_eval(seeds, db):
    """Streams a PIR database scan from a SECRET-derived chunk index —
    the forbidden served-PIR shape.  The production streamed scan
    (models/pir.py ``_pir_stream_chunk_body``) selects the database slab
    by the PUBLIC chunk counter; deriving the slab index from key
    material makes the HBM access order — which chunk a scan touches
    when — a function of the query, observable as memory-traffic skew."""
    j = (seeds[0] & jnp.uint32(3)).astype(jnp.int32)
    chunk = jax.lax.dynamic_slice_in_dim(db, j * 2, 2, axis=0)
    return jnp.bitwise_xor.reduce(chunk, axis=0)


def leaky_frontier_index_eval(seeds, state):
    """Gathers a frontier-cache column by a SECRET-derived selector —
    the forbidden incremental-descent shape.  The production extend
    bodies (models/dpf_chacha ``_hh_extend_cc_body`` and the compat
    mirror) gather by ``sel``, the PUBLIC survivor positions both
    aggregators learn from the announced counts; deriving the column
    index from the carried seed state would make the frontier's memory
    access pattern — which cached prefixes a round touches — a function
    of key material, visible in HBM traffic."""
    sel = (seeds[:2] & jnp.uint32(3)).astype(jnp.int32)
    return jnp.take(state.reshape(2, -1), sel, axis=1)


def leaky_gen_alpha_eval(alphas, fcw):
    """A dealer that applies the leaf correction by WRITING at the
    secret point's index ON DEVICE — the forbidden gen shape.  The
    production tower (models/keys_gen.py) keeps every per-level alpha
    select as mask arithmetic (``msk = 0 - bit``) and applies the alpha
    leaf flip on HOST during output marshalling; a device-side scatter
    at alpha makes the write address — which HBM word the dealer
    touches — a function of the dealt point."""
    idx = (alphas[0] & jnp.uint32(7)).astype(jnp.int32)
    return fcw.at[idx].set(fcw[idx] ^ jnp.uint32(1))


#: (function, n secret leading args, total args builder) — the tests
#: iterate this to keep fixture and assertion lists in sync.
LEAKY = (
    ("leaky_cond_eval", leaky_cond_eval, "secret-branch"),
    ("leaky_slice_eval", leaky_slice_eval, "secret-index"),
    ("leaky_gather_eval", leaky_gather_eval, "secret-index"),
    ("leaky_float_eval", leaky_float_eval, "secret-float"),
    ("leaky_debug_eval", leaky_debug_eval, "callback"),
    ("leaky_while_eval", leaky_while_eval, "secret-branch"),
    ("leaky_kernel_eval", leaky_kernel_eval, "secret-index"),
    ("leaky_kernel_loop_eval", leaky_kernel_loop_eval, "secret-index"),
    ("leaky_hh_descend_eval", leaky_hh_descend_eval, "secret-branch"),
    ("leaky_shard_index_eval", leaky_shard_index_eval, "secret-index"),
    ("leaky_pir_chunk_eval", leaky_pir_chunk_eval, "secret-index"),
    ("leaky_frontier_index_eval", leaky_frontier_index_eval, "secret-index"),
    ("leaky_gen_alpha_eval", leaky_gen_alpha_eval, "secret-index"),
)
