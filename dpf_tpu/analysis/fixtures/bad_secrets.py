"""Seeded violations for the secret-hygiene pass (NEVER imported by
production code; excluded from real-tree scans)."""

import hashlib
import logging


def leak_to_log(kb):
    # Taint propagates through the assignment; logging is a sink.
    seeds = kb.seeds
    logging.info("debug: first seeds %r", seeds)


def leak_in_raise(scw):
    # Key material formatted into an exception string — which the
    # sidecar would relay to the client as an HTTP 400 body.
    raise ValueError(f"bad correction word {scw!r}")


def stats(blob):
    # A stats payload carrying raw key bytes (/v1/stats shape).
    return {"last_key": blob}


def leak_in_error_reply(handler, key_bytes):
    # Request key bytes echoed into an HTTP error body (the _bad()/500
    # reply path): the client on the other side is the OTHER party of
    # the secret-sharing, so this breaks the two-server trust split.
    handler._bad(f"cannot parse key {key_bytes!r}")


def sanctioned(blob):
    # CLEAN: the sha256 digest is the sanctioned way to index key bytes
    # (serving/keycache.py); len() is public metadata.
    logging.info(
        "cache key %s (%d bytes)",
        hashlib.sha256(blob).hexdigest(),
        len(blob),
    )
