"""Seeded violations for the secret-hygiene pass (NEVER imported by
production code; excluded from real-tree scans)."""

import hashlib
import logging


def leak_to_log(kb):
    # Taint propagates through the assignment; logging is a sink.
    seeds = kb.seeds
    logging.info("debug: first seeds %r", seeds)


def leak_in_raise(scw):
    # Key material formatted into an exception string — which the
    # sidecar would relay to the client as an HTTP 400 body.
    raise ValueError(f"bad correction word {scw!r}")


def stats(blob):
    # A stats payload carrying raw key bytes (/v1/stats shape).
    return {"last_key": blob}


def leak_in_error_reply(handler, key_bytes):
    # Request key bytes echoed into an HTTP error body (the _bad()/500
    # reply path): the client on the other side is the OTHER party of
    # the secret-sharing, so this breaks the two-server trust split.
    handler._bad(f"cannot parse key {key_bytes!r}")


def leak_in_span_attr(span, kb):
    # Key material attached as a span attribute: /v1/trace exports span
    # attrs verbatim, so this is the flight recorder leaking seeds.
    seeds = kb.seeds
    span.set_attrs(first_seed=seeds)


def leak_in_metric_label(writer, key_bytes):
    # A metric label built from raw key bytes: /v1/metrics exports label
    # values verbatim to every scraper.
    writer.sample("dpf_last_key", {"key": key_bytes}, 1)


def sanctioned_telemetry(span, blob):
    # CLEAN: shape/len reductions and digests are public metadata in
    # span attributes, same rules as logging.
    span.set_attrs(n_bytes=len(blob))


def sanctioned(blob):
    # CLEAN: the sha256 digest is the sanctioned way to index key bytes
    # (serving/keycache.py); len() is public metadata.
    logging.info(
        "cache key %s (%d bytes)",
        hashlib.sha256(blob).hexdigest(),
        len(blob),
    )
