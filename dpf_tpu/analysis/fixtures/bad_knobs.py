"""Seeded violations for the knob-registry pass (NEVER imported by
production code; excluded from real-tree scans)."""

import os

# R1/R2: a direct env read of a declared knob, bypassing the registry.
FUSE = os.environ.get("DPF_TPU_FUSE", "off")

# R2: subscript read.
SBOX = os.environ["DPF_TPU_SBOX"]

# R3: a typo'd knob name — the silent-failure mode the registry kills
# (the real knob is DPF_TPU_BATCH_WINDOW_US).
WINDOW = os.environ.get("DPF_TPU_BATCH_WINDOW_MS", "200")

# Legal: a WRITE of a declared knob (A/B scripts set knobs for children).
os.environ["DPF_TPU_POINTS"] = "xla"

from os import getenv  # noqa: E402

# R2 through the ALIASED import — the bypass that fully-qualified-only
# matching missed (`from os import getenv` then a bare getenv read).
FUSE2 = getenv("DPF_TPU_FUSE", "off")
