"""Seeded lock-discipline violations (analysis/concurrency/lock_pass.py).

Excluded from real scans (common.iter_py_files skips fixtures/); the
test suite points the pass at this file explicitly and asserts every
seeded violation fires.  The classes double as the DYNAMIC fixtures for
the deterministic interleaving harness (tests import them and drive the
bad shapes through sched.DetScheduler, so the statically-flagged
deadlock and torn read are also REPRODUCED, byte-for-byte, from a
seed).

Seeded violations, one per rule:

  R1  ``_UNDECLARED`` — a module lock with no registry declaration
      (every other lock here is declared in registry.FIXTURE_LOCKS).
  R2  ``BadOrder.inverted`` — acquires ``_b`` (rank 20) then ``_a``
      (rank 10): an acquisition-order inversion, and together with
      ``forward`` an order cycle (the classic AB/BA deadlock).
  R3  ``TornCounter.read`` — ``count`` is written under ``_lock`` in
      ``bump`` but read lock-free in ``read``.
  R4  ``HeldAcrossDispatch.fire`` — ``_lock`` held across a device
      dispatch; ``HeldAcrossRecv.pull`` — ``_lock`` held across
      ``sock.recv``.
"""

import threading

from dpf_tpu.core import plans

_UNDECLARED = threading.Lock()  # R1: not in the registry on purpose


class BadOrder:
    """AB/BA deadlock shape: ``forward`` takes a then b, ``inverted``
    takes b then a.  Two threads, one in each, deadlock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def inverted(self):
        with self._b:
            with self._a:  # R2: rank 10 under rank 20
                pass


class TornCounter:
    """The unguarded-counter torn read: ``bump`` guards the
    read-modify-write, ``read`` and ``torn_bump`` skip the lock.  The
    two-line read-then-write in ``torn_bump`` is the preemption window
    the deterministic scheduler widens on purpose."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def torn_bump(self):
        snapshot = self.count  # R3: lock-free read-modify-write
        self.count = snapshot + 1  # R3: lock-free write

    def read(self):
        return self.count  # R3: lock-free read


class HeldAcrossDispatch:
    """One wedged dispatch under this lock stalls every caller."""

    def __init__(self):
        self._lock = threading.Lock()

    def fire(self, profile, kb, xs):
        with self._lock:
            return plans.run_points(  # R4: dispatch under a lock
                "/v1/eval_points_batch", profile, kb, xs
            )


class HeldAcrossRecv:
    """A slow peer under this lock stalls every caller."""

    def __init__(self):
        self._lock = threading.Lock()

    def pull(self, sock):
        with self._lock:
            return sock.recv(4)  # R4: socket read under a lock
