"""Python-side surface extraction (AST only — nothing is imported).

Reads the five Python surface files and returns one dict the contract
pass cross-checks against the Go and C surfaces:

  routes         path -> wire2 route id        (handlers.ROUTE_IDS)
  sink_routes    streamed-upload paths         (handlers.SINK_ROUTES)
  http_only      GET/observability paths with no wire2 id (respond_get
                 ``path == "..."`` compares + ``route == "..."``
                 compares anywhere, minus the route table)
  reply_codes    code -> [line, ...] of every ``_reply_error("code",``
                 call in handlers.py/wire2.py (membership-checked
                 against the canonical table)
  error_codes    code -> HTTP status           (errors.CODES)
  class_codes    exception class -> code       (errors.py ClassDefs)
  headers        {"deadline","trace","retry_after"} -> header name
  params         {"deadline","trace"} -> wire2 pseudo-param name
  wire2          magic hex, header/resp struct formats + sizes, frame
                 types, flags, data chunk size
  metrics        dpf_* metric name -> kind     (obs/metrics.py
                 ``w.family(f"{ns}_...", kind, ...)`` calls)

Every extractor is tolerant of an ABSENT element only in fixture mode
(the seeded-drift fixtures are small single-surface files); on the real
tree a missing element is itself a finding (``missing`` list).
"""

from __future__ import annotations

import ast
import os
import struct
from typing import Any

# role -> the real tree's repo-relative path
SURFACES = {
    "handlers": "dpf_tpu/serving/handlers.py",
    "wire2": "dpf_tpu/serving/wire2.py",
    "errors": "dpf_tpu/serving/errors.py",
    "headers": "dpf_tpu/serving/headers.py",
    "metrics": "dpf_tpu/obs/metrics.py",
}

_HEADER_NAMES = {
    "DEADLINE_HEADER": "deadline",
    "TRACE_HEADER": "trace",
    "RETRY_AFTER_HEADER": "retry_after",
}
_PARAM_NAMES = {"DEADLINE_PARAM": "deadline", "TRACE_PARAM": "trace"}


def _parse(root: str, rel: str) -> ast.Module | None:
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=rel)


def _const_int(node: ast.AST) -> int | None:
    """Evaluate an int constant, allowing ``1 << 20``-style shifts."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is not None and right is not None:
            return left << right
    return None


def _module_assigns(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                yield tgt.id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                yield node.target.id, node.value


def _extract_handlers(tree: ast.Module, out: dict[str, Any]) -> None:
    for name, value in _module_assigns(tree):
        if name == "ROUTE_IDS" and isinstance(value, ast.Dict):
            routes: dict[str, int] = {}
            for k, v in zip(value.keys, value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, int)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    routes[v.value] = k.value
            out["routes"] = routes
        elif name == "SINK_ROUTES":
            strings = [
                n.value
                for n in ast.walk(value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            ]
            out["sink_routes"] = sorted(strings)
    # GET/observability routes: string compares against a ``path`` or
    # ``*.route`` operand (respond_get's dispatch plus the POST-side
    # "/v1/profile" special case).  Tuple-membership compares
    # (``route in ("/v1/warmup", ...)``) count too.
    compared: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        left = node.left
        is_path = isinstance(left, ast.Name) and left.id in ("path", "route")
        is_path = is_path or (
            isinstance(left, ast.Attribute) and left.attr == "route"
        )
        if not is_path:
            continue
        for comp in node.comparators:
            for n in ast.walk(comp):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    if n.value.startswith("/"):
                        compared.add(n.value)
    out["route_compares"] = compared


def _extract_reply_codes(tree: ast.Module, out: dict[str, Any]) -> None:
    codes: dict[str, list[int]] = out.setdefault("reply_codes", {})
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if fn_name != "_reply_error" or not node.args:
            continue
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
            codes.setdefault(arg0.value, []).append(node.lineno)


def _extract_errors(tree: ast.Module, out: dict[str, Any]) -> None:
    for name, value in _module_assigns(tree):
        if name == "CODES" and isinstance(value, ast.Dict):
            table: dict[str, int] = {}
            for k, v in zip(value.keys, value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                ):
                    table[k.value] = v.value
            out["error_codes"] = table
    class_codes: dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "code"
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)
            ):
                class_codes[node.name] = stmt.value.value
    if class_codes:
        out["class_codes"] = class_codes


def _extract_headers(tree: ast.Module, out: dict[str, Any]) -> None:
    headers: dict[str, str] = {}
    params: dict[str, str] = {}
    for name, value in _module_assigns(tree):
        if not (
            isinstance(value, ast.Constant) and isinstance(value.value, str)
        ):
            continue
        if name in _HEADER_NAMES:
            headers[_HEADER_NAMES[name]] = value.value
        elif name in _PARAM_NAMES:
            params[_PARAM_NAMES[name]] = value.value
    if headers:
        out["headers"] = headers
    if params:
        out["params"] = params


def _extract_wire2(tree: ast.Module, out: dict[str, Any]) -> None:
    w2: dict[str, Any] = {"frame_types": {}, "flags": {}}
    for name, value in _module_assigns(tree):
        if name == "MAGIC" and isinstance(value, ast.Constant) and isinstance(
            value.value, bytes
        ):
            w2["magic"] = value.value.hex()
        elif name in ("_HDR", "_RESP") and isinstance(value, ast.Call):
            if value.args and isinstance(value.args[0], ast.Constant):
                fmt = value.args[0].value
                key = "hdr" if name == "_HDR" else "resp"
                w2[f"{key}_format"] = fmt
                w2[f"{key}_len"] = struct.calcsize(fmt)
        elif name.startswith("T_"):
            v = _const_int(value)
            if v is not None:
                w2["frame_types"][name[2:]] = v
        elif name.startswith("F_"):
            v = _const_int(value)
            if v is not None:
                w2["flags"][name[2:]] = v
        elif name == "_CLIENT_CHUNK":
            v = _const_int(value)
            if v is not None:
                w2["data_chunk"] = v
    out["wire2"] = w2


def _extract_metrics(tree: ast.Module, out: dict[str, Any]) -> None:
    ns = "dpf"
    for name, value in _module_assigns(tree):
        if name == "_NAMESPACE" and isinstance(value, ast.Constant):
            ns = value.value
    metrics: dict[str, str] = {}
    duplicates: list[str] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "family"
            and len(node.args) >= 2
        ):
            continue
        name_arg, kind_arg = node.args[0], node.args[1]
        full: str | None = None
        if isinstance(name_arg, ast.JoinedStr):
            # f"{ns}_shed_total" — one FormattedValue + one Constant.
            parts: list[str] = []
            for v in name_arg.values:
                if isinstance(v, ast.FormattedValue):
                    parts.append(ns)
                elif isinstance(v, ast.Constant):
                    parts.append(str(v.value))
            full = "".join(parts)
        elif isinstance(name_arg, ast.Constant):
            full = str(name_arg.value)
        if full is None or not isinstance(kind_arg, ast.Constant):
            continue
        if full in metrics:
            duplicates.append(full)
        metrics[full] = str(kind_arg.value)
    out["metrics"] = metrics
    out["metric_namespace"] = ns
    if duplicates:
        out["metric_duplicates"] = duplicates


def extract(
    root: str, overrides: dict[str, str] | None = None
) -> dict[str, Any]:
    """The Python surface of ``root``.  ``overrides`` maps a role name
    (see :data:`SURFACES`) to an alternate repo-relative file — the
    seeded-drift fixtures substitute one small surface file at a time.
    ``missing`` lists (role, element) pairs absent from their file."""
    overrides = overrides or {}
    out: dict[str, Any] = {"missing": []}
    trees: dict[str, ast.Module | None] = {}
    for role, rel in SURFACES.items():
        use = overrides.get(role, rel)
        trees[role] = _parse(root, use)
        out.setdefault("files", {})[role] = use
        if trees[role] is None:
            out["missing"].append((role, "file"))

    if trees["handlers"] is not None:
        _extract_handlers(trees["handlers"], out)
        _extract_reply_codes(trees["handlers"], out)
    if trees["wire2"] is not None:
        _extract_wire2(trees["wire2"], out)
        _extract_reply_codes(trees["wire2"], out)
    if trees["errors"] is not None:
        _extract_errors(trees["errors"], out)
    if trees["headers"] is not None:
        _extract_headers(trees["headers"], out)
    if trees["metrics"] is not None:
        _extract_metrics(trees["metrics"], out)

    for role, element in (
        ("handlers", "routes"),
        ("handlers", "sink_routes"),
        ("errors", "error_codes"),
        ("headers", "headers"),
        ("headers", "params"),
        ("metrics", "metrics"),
    ):
        if trees[role] is not None and element not in out:
            out["missing"].append((role, element))
    if trees["wire2"] is not None:
        w2 = out.get("wire2", {})
        for element in ("magic", "hdr_format", "resp_format"):
            if element not in w2:
                out["missing"].append(("wire2", element))
        if not w2.get("frame_types"):
            out["missing"].append(("wire2", "frame_types"))

    if "routes" in out:
        out["http_only"] = sorted(
            out.pop("route_compares", set()) - set(out["routes"])
        )
    else:
        out.pop("route_compares", None)
    return out
