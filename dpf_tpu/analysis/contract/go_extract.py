"""Go-side surface extraction for the surface-contract pass.

Two paths to the same JSON shape:

  * ``bridge/go/cmd/contract-dump`` — a go/ast program emitting the
    surface as JSON on stdout.  Used when a Go toolchain is on PATH
    (CI's conformance job; ``bridge/go/conformance.sh contract`` step).
  * :func:`extract_fallback` — a regex scan over the SAME two files
    (``bridge/go/dpftpu/client.go`` / ``wire2.go``).  Used when the
    toolchain is absent (skip-with-warning, the staticcheck precedent):
    the lint lane still sees the Go constants, just through a dumber
    parser.

The two are pinned against each other by the committed golden dump
(``dpf_tpu/analysis/fixtures/bad_contract/go_dump_golden.json`` —
asserted equal to the fallback's output in tests/test_contract.py), so
the fallback cannot silently rot while CI runs the real parser.

Surface shape (both producers):

  routes        Go const suffix ("Gen", "HHEval", ...) -> route id
  client_paths  sorted "/v1/..." literals the HTTP client posts to
  frame_types   normalized name ("RESP_DATA") -> value
  flags         normalized name ("END_STREAM") -> value
  hdr_len / resp_head_len / data_chunk   ints
  magic         hex string of the 8-byte preface
  headers       sorted X-DPF-* / Retry-After literals
  error_codes   code -> status from the APIError doc comment
  params        sorted "_..." pseudo-param literals
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
from typing import Any

GO_DIR = os.path.join("bridge", "go")
GO_FILES = (
    os.path.join("bridge", "go", "dpftpu", "client.go"),
    os.path.join("bridge", "go", "dpftpu", "wire2.go"),
)

_CAMEL_SPLIT = re.compile(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def camel_to_upper_snake(name: str) -> str:
    """``RespData`` -> ``RESP_DATA``; ``EndStream`` -> ``END_STREAM``."""
    return _CAMEL_SPLIT.sub("_", name).upper()


def _read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel), encoding="utf-8") as f:
        return f.read()


def _int_expr(text: str) -> int:
    """``42`` or ``1 << 20`` from a Go const expression."""
    m = re.fullmatch(r"\s*(\d+)\s*(?:<<\s*(\d+)\s*)?", text)
    if not m:
        raise ValueError(f"unparseable Go int expression {text!r}")
    v = int(m.group(1))
    return v << int(m.group(2)) if m.group(2) else v


def extract_fallback(
    root: str, files: tuple[str, ...] = GO_FILES
) -> dict[str, Any]:
    """Regex extraction over the bridge sources — the no-toolchain
    twin of contract-dump's go/ast output."""
    srcs = {rel: _read(root, rel) for rel in files if
            os.path.isfile(os.path.join(root, rel))}
    all_src = "\n".join(srcs.values())

    routes = {
        m.group(1): int(m.group(2))
        for m in re.finditer(r"wire2Route(\w+)\s*=\s*(\d+)", all_src)
    }
    frame_types = {
        camel_to_upper_snake(m.group(1)): int(m.group(2))
        for m in re.finditer(r"\bwire2T([A-Z]\w*)\s*=\s*(\d+)", all_src)
    }
    flags = {
        camel_to_upper_snake(m.group(1)): int(m.group(2))
        for m in re.finditer(r"\bwire2F([A-Z]\w*)\s*=\s*(\d+)", all_src)
    }

    def named_int(name: str) -> int | None:
        m = re.search(rf"\b{name}\s*=\s*([^\n]+)", all_src)
        return _int_expr(m.group(1)) if m else None

    magic = None
    m = re.search(r"wire2Magic\s*=\s*\[\]byte\{([^}]*)\}", all_src)
    if m:
        vals = []
        for tok in m.group(1).split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok.startswith("'") and tok.endswith("'"):
                vals.append(ord(tok[1:-1]))
            else:
                vals.append(int(tok))
        magic = bytes(vals).hex()

    client_paths = sorted(
        {m.group(1) for m in re.finditer(r'"(/v1/[a-z_/]+)[?"]', all_src)}
    )
    headers = sorted(
        {
            m.group(1)
            for m in re.finditer(r'"(X-DPF-[\w-]+|Retry-After)"', all_src)
        }
    )
    params = sorted(
        {m.group(1) for m in re.finditer(r'Set\("(_\w+)"', all_src)}
    )

    # The APIError doc comment is the Go side's statement of the error
    # vocabulary: code "shed" (429, ...), "unavailable" (503, ...) ...
    error_codes: dict[str, int] = {}
    m = re.search(
        r"((?://[^\n]*\n)+)type APIError struct", all_src
    )
    if m:
        for cm in re.finditer(r'"(\w+)"\s*\((\d+)', m.group(1)):
            error_codes[cm.group(1)] = int(cm.group(2))

    return {
        "routes": routes,
        "client_paths": client_paths,
        "frame_types": frame_types,
        "flags": flags,
        "hdr_len": named_int("wire2HdrLen"),
        "resp_head_len": named_int("wire2RespHead"),
        "data_chunk": named_int("wire2DataChunk"),
        "magic": magic,
        "headers": headers,
        "error_codes": error_codes,
        "params": params,
    }


def toolchain_available() -> bool:
    return shutil.which("go") is not None


def extract_dump(root: str) -> dict[str, Any] | None:
    """Run contract-dump under the Go toolchain; None (with a stderr
    notice — the staticcheck skip idiom) when unavailable or failing."""
    if not toolchain_available():
        print(
            "surface-contract: no Go toolchain; using the regex "
            "fallback extractor (bridge/go/conformance.sh runs the "
            "go/ast contract-dump)",
            file=sys.stderr,
        )
        return None
    try:
        proc = subprocess.run(
            ["go", "run", "./cmd/contract-dump"],
            cwd=os.path.join(root, GO_DIR),
            capture_output=True,
            text=True,
            timeout=120,
            check=True,
        )
        return json.loads(proc.stdout)
    except (OSError, subprocess.SubprocessError, ValueError) as e:
        print(
            f"surface-contract: contract-dump failed ({e}); using the "
            "regex fallback extractor",
            file=sys.stderr,
        )
        return None


def extract(root: str) -> dict[str, Any]:
    """The Go surface: go/ast dump when possible, regex otherwise."""
    return extract_dump(root) or extract_fallback(root)


# Expected Go const-name suffix for a route path: "/v1/eval_points_batch"
# -> "EvalPointsBatch", "/v1/hh/gen" -> "HHGen".  The special cases are
# the Go bridge's own spellings — pinned here so a rename on either side
# is a visible diff, not a silent re-derivation.
_TOKEN_CASE = {"hh": "HH", "db": "DB", "evalfull": "EvalFull", "pir": "Pir"}


def const_name_for_path(path: str) -> str:
    tokens = [t for part in path.removeprefix("/v1/").split("/")
              for t in part.split("_")]
    return "".join(_TOKEN_CASE.get(t, t.capitalize()) for t in tokens)
