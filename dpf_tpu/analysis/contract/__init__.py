"""The cross-language surface contract (``surface-contract`` pass).

The system spans three language surfaces that must stay byte-compatible
with each other and with the reference: the Python sidecar (HTTP/1.1 +
wire2 fronts), the Go bridge (``bridge/go/dpftpu``), and the native CPU
baseline (``native/dpf_native.cc`` behind the ctypes wiring in
``backends/cpu_native.py``).  Every shared constant — the route_id
table, the wire2 frame types and 12-byte header layout, the
``{code, detail}`` error vocabulary, the ``X-DPF-*`` headers, the
``dpf_*`` metric names, and the ``dpfn_*`` ABI — used to be an
independent hand-written literal on each side; a one-character drift in
any mirror shipped silently until a conformance run happened to
exercise it.

This package extracts each surface STATICALLY:

  ``py_extract``   AST over serving/handlers.py, serving/wire2.py,
                   serving/errors.py, serving/headers.py, and
                   obs/metrics.py (routes, frames, codes, headers,
                   metrics).
  ``go_extract``   ``bridge/go/cmd/contract-dump`` (go/ast, JSON on
                   stdout) when a Go toolchain exists; a regex fallback
                   over bridge/go/dpftpu/*.go otherwise — same output
                   shape, pinned against each other by the committed
                   golden dump (tests/test_contract.py).
  ``c_abi``        the ``extern "C" dpfn_*`` declarations in
                   native/dpf_native.cc diffed against the ctypes
                   argtypes/restype wiring in backends/cpu_native.py.

All three project into ONE canonical committed ``docs/CONTRACT.json``
(+ human ``docs/CONTRACT.md``) with the OBLIVIOUS.md drift policy: any
mismatch BETWEEN surfaces, or between the surfaces and the committed
contract, is a finding; an intentional change re-certifies with
``python -m dpf_tpu.analysis --write-contract``.  Semantics and caveats:
docs/DESIGN.md §22.
"""

from __future__ import annotations

# Bump when the contract schema or extraction rules change materially
# (bench ledgers keyed on it re-measure — a contract-discipline change
# alters what the measured tree was allowed to serve).
CONTRACT_VERSION = "1"
