"""``python -m dpf_tpu.analysis.contract`` — contract utilities.

    python -m dpf_tpu.analysis.contract                  # run the pass
    python -m dpf_tpu.analysis.contract --check-go-dump -   # diff a
        contract-dump JSON (stdin, or a file path) against the committed
        docs/CONTRACT.json — the `contract` step of
        bridge/go/conformance.sh, where the REAL go/ast extractor runs
        instead of the Python regex fallback.

Exits 0 when coherent, 1 on any drift.  Re-certification lives on the
suite entrypoint: ``python -m dpf_tpu.analysis --write-contract``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

from ..common import repo_root
from . import contract_pass


def _py_view(contract: dict[str, Any]) -> dict[str, Any]:
    """The committed contract reshaped as the Python-surface dict the
    Go cross-check consumes — lets one checker serve both the lint pass
    (tree vs Go) and conformance.sh (contract vs contract-dump)."""
    w2 = contract["wire2"]
    return {
        "routes": {p: r["id"] for p, r in contract["routes"].items()},
        "wire2": {
            "frame_types": w2["frame_types"],
            "flags": w2["flags"],
            "hdr_len": w2["hdr_len"],
            "resp_len": w2["resp_head_len"],
            "data_chunk": w2["data_chunk"],
            "magic": w2["magic"],
        },
        "error_codes": contract["error_codes"],
        "headers": contract["headers"],
        "params": contract["wire2_params"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpf_tpu.analysis.contract", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--check-go-dump", metavar="FILE", default=None,
        help="diff a contract-dump JSON ('-' = stdin) against the "
        "committed docs/CONTRACT.json",
    )
    ap.add_argument(
        "--root", default=None,
        help="tree whose committed contract to use (default: this "
        "checkout)",
    )
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root) if args.root else repo_root()

    if args.check_go_dump is not None:
        committed = contract_pass.load_committed(root)
        if committed is None:
            print(
                f"{contract_pass.CONTRACT_JSON} missing — certify with "
                "'python -m dpf_tpu.analysis --write-contract'",
                file=sys.stderr,
            )
            return 1
        if args.check_go_dump == "-":
            dump = json.load(sys.stdin)
        else:
            with open(args.check_go_dump, encoding="utf-8") as f:
                dump = json.load(f)
        findings: list = []
        contract_pass._go_check(_py_view(committed), dump, findings)
        go_codes = sorted(dump.get("error_codes", {}))
        if go_codes != committed.get("go_error_codes", []):
            from ..common import Finding

            findings.append(Finding(
                "bridge/go/dpftpu/client.go", 1, contract_pass.PASS,
                f"Go error-code vocabulary {go_codes} differs from the "
                f"contract's {committed.get('go_error_codes')}",
            ))
        for f in findings:
            print(f)
        print(
            f"surface-contract go-dump check: {len(findings)} finding(s)",
            file=sys.stderr,
        )
        return 1 if findings else 0

    findings = contract_pass.run(root)
    for f in findings:
        print(f)
    print(
        f"surface-contract: {len(findings)} finding(s)", file=sys.stderr
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
