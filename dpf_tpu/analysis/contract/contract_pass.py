"""The ``surface-contract`` pass: cross-language drift detection.

Extracts the Python, Go, and native-ABI surfaces (:mod:`py_extract`,
:mod:`go_extract`, :mod:`c_abi`), cross-checks them against each other,
projects them into the canonical contract dict, and diffs that against
the committed ``docs/CONTRACT.json``.  Any mismatch — between surfaces,
or between the surfaces and the committed contract — is a finding; an
intentional change re-certifies with
``python -m dpf_tpu.analysis --write-contract`` (the OBLIVIOUS.md drift
policy).

Fixture mode: ``run(root, files=[...])`` maps each fixture file onto
the surface role its basename prefix names (``handlers_*`` substitutes
for serving/handlers.py, ``wire2_*`` for serving/wire2.py, ``errors_*``
for serving/errors.py, ``cpu_native_*`` for backends/cpu_native.py);
every OTHER surface still comes from the real tree, so a one-sided
drift fires exactly the cross-surface findings it would ship with.
"""

from __future__ import annotations

import json
import os
from typing import Any

from ..common import Finding
from . import CONTRACT_VERSION, c_abi, go_extract, py_extract

PASS = "surface-contract"
CONTRACT_JSON = os.path.join("docs", "CONTRACT.json")
CONTRACT_MD = os.path.join("docs", "CONTRACT.md")

_GO_WIRE2 = "bridge/go/dpftpu/wire2.go"
_GO_CLIENT = "bridge/go/dpftpu/client.go"

# Fixture basename prefix -> the surface role it substitutes for.
# Checked in order; first match wins (cpu_native_ before native_).
_FIXTURE_ROLES = (
    ("handlers_", "handlers"),
    ("wire2_", "wire2"),
    ("errors_", "errors"),
    ("headers_", "headers"),
    ("metrics_", "metrics"),
    ("cpu_native_", "ctypes"),
    ("native_", "c"),
)


def _fixture_overrides(files) -> dict[str, str]:
    out: dict[str, str] = {}
    for rel in files or ():
        base = os.path.basename(rel)
        for prefix, role in _FIXTURE_ROLES:
            if base.startswith(prefix):
                out[role] = rel
                break
    return out


def _surface_rel(role: str, overrides: dict[str, str]) -> str:
    if role == "c":
        return overrides.get("c", c_abi.C_FILE).replace(os.sep, "/")
    if role == "ctypes":
        return overrides.get("ctypes", c_abi.CTYPES_FILE).replace(
            os.sep, "/"
        )
    return overrides.get(role, py_extract.SURFACES[role]).replace(
        os.sep, "/"
    )


def _py_internal(
    py: dict[str, Any], overrides: dict[str, str], out: list[Finding]
) -> None:
    def f(role: str, msg: str, line: int = 1) -> None:
        out.append(Finding(_surface_rel(role, overrides), line, PASS, msg))

    role_of = {
        "handlers": "handlers", "wire2": "wire2", "errors": "errors",
        "headers": "headers", "metrics": "metrics",
    }
    for role, element in py.get("missing", []):
        f(role_of.get(role, "handlers"),
          f"surface element {element!r} not found in {role} surface")

    routes = py.get("routes", {})
    ids = sorted(routes.values())
    for rid in sorted({i for i in ids if ids.count(i) > 1}):
        dup = sorted(p for p, i in routes.items() if i == rid)
        f("handlers", f"route id {rid} assigned to multiple paths: {dup}")
    for path in py.get("sink_routes", []):
        if routes and path not in routes:
            f("handlers", f"SINK_ROUTES entry {path!r} is not in ROUTE_IDS")

    error_codes = py.get("error_codes", {})
    for code, lines in sorted(py.get("reply_codes", {}).items()):
        if error_codes and code not in error_codes:
            f("handlers",
              f"_reply_error uses code {code!r} absent from errors.CODES",
              line=lines[0])
    for cls, code in sorted(py.get("class_codes", {}).items()):
        if error_codes and code not in error_codes:
            f("errors",
              f"exception class {cls} declares code {code!r} absent "
              "from CODES (http_status derivation would fail at import)")

    w2 = py.get("wire2", {})
    for kind in ("frame_types", "flags"):
        table = w2.get(kind, {})
        by_val: dict[int, list[str]] = {}
        for name, val in table.items():
            by_val.setdefault(val, []).append(name)
        for val, names in sorted(by_val.items()):
            if len(names) > 1:
                f("wire2",
                  f"wire2 {kind.replace('_', ' ')} value {val} collides: "
                  f"{sorted(names)}")
    magic = w2.get("magic")
    if magic is not None and len(magic) != 16:
        f("wire2", f"wire2 MAGIC must be 8 bytes, got {len(magic) // 2}")

    ns = py.get("metric_namespace", "dpf")
    for name in sorted(py.get("metrics", {})):
        if not name.startswith(f"{ns}_"):
            f("metrics",
              f"metric {name!r} escapes the {ns}_* namespace")
    for name in py.get("metric_duplicates", []):
        f("metrics", f"metric {name!r} registered more than once")


def _go_check(
    py: dict[str, Any], go: dict[str, Any], out: list[Finding]
) -> None:
    def f(rel: str, msg: str) -> None:
        out.append(Finding(rel, 1, PASS, msg))

    routes = py.get("routes", {})
    go_routes = dict(go.get("routes", {}))
    for path, rid in sorted(routes.items()):
        const = go_extract.const_name_for_path(path)
        if const not in go_routes:
            f(_GO_WIRE2,
              f"route {path!r} (id {rid}) has no Go const "
              f"wire2Route{const}")
        elif go_routes[const] != rid:
            f(_GO_WIRE2,
              f"route {path!r}: Go wire2Route{const}={go_routes[const]} "
              f"but Python route_id is {rid}")
    known = {go_extract.const_name_for_path(p) for p in routes}
    for const in sorted(set(go_routes) - known):
        f(_GO_WIRE2,
          f"Go const wire2Route{const}={go_routes[const]} names no "
          "Python route")
    for path in go.get("client_paths", []):
        if routes and path not in routes:
            f(_GO_CLIENT,
              f"Go client posts to {path!r}, which is not in ROUTE_IDS")

    w2 = py.get("wire2", {})
    for py_key, go_key, label in (
        ("frame_types", "frame_types", "frame type table"),
        ("flags", "flags", "flag table"),
    ):
        if w2.get(py_key) != go.get(go_key) and w2.get(py_key) is not None:
            f(_GO_WIRE2,
              f"wire2 {label} differs: Python {w2.get(py_key)} vs "
              f"Go {go.get(go_key)}")
    for py_key, go_key, label in (
        ("hdr_len", "hdr_len", "frame header length"),
        ("resp_len", "resp_head_len", "RESP head length"),
        ("data_chunk", "data_chunk", "DATA chunk size"),
        ("magic", "magic", "connection preface"),
    ):
        if w2.get(py_key) is not None and w2.get(py_key) != go.get(go_key):
            f(_GO_WIRE2,
              f"wire2 {label} differs: Python {w2.get(py_key)!r} vs "
              f"Go {go.get(go_key)!r}")

    error_codes = py.get("error_codes", {})
    for code, status in sorted(go.get("error_codes", {}).items()):
        if error_codes and code not in error_codes:
            f(_GO_CLIENT,
              f"Go APIError documents code {code!r}, absent from "
              "errors.CODES")
        elif error_codes and error_codes[code] != status:
            f(_GO_CLIENT,
              f"error code {code!r}: Go documents HTTP {status}, "
              f"Python CODES says {error_codes[code]}")

    headers = py.get("headers", {})
    go_headers = set(go.get("headers", []))
    for key, name in sorted(headers.items()):
        if name not in go_headers:
            f(_GO_CLIENT,
              f"{key} header {name!r} does not appear in the Go bridge")

    params = py.get("params", {})
    if params and sorted(params.values()) != go.get("params", []):
        f(_GO_WIRE2,
          f"wire2 pseudo-params differ: Python "
          f"{sorted(params.values())} vs Go {go.get('params')}")


def _abi_check(
    c: dict[str, Any] | None,
    pyabi: dict[str, Any] | None,
    overrides: dict[str, str],
    out: list[Finding],
) -> None:
    c_rel = _surface_rel("c", overrides)
    py_rel = _surface_rel("ctypes", overrides)
    if c is None:
        out.append(Finding(c_rel, 1, PASS, "native ABI source not found"))
        return
    if pyabi is None:
        out.append(Finding(py_rel, 1, PASS, "ctypes wiring not found"))
        return
    for sym in sorted(set(c) - set(pyabi)):
        out.append(Finding(py_rel, 1, PASS,
                           f"C exports {sym} but cpu_native.py never "
                           "wires it"))
    for sym in sorted(set(pyabi) - set(c)):
        out.append(Finding(py_rel, 1, PASS,
                           f"ctypes wires {sym}, which native/"
                           "dpf_native.cc does not export"))
    for sym in sorted(set(c) & set(pyabi)):
        want, have = c[sym], pyabi[sym]
        if have["restype"] != want["restype"]:
            out.append(Finding(py_rel, 1, PASS,
                               f"{sym}: restype {have['restype']} vs C "
                               f"return {want['restype']}"))
        if have["args"] is None:
            if want["args"]:
                out.append(Finding(py_rel, 1, PASS,
                                   f"{sym}: C takes {len(want['args'])} "
                                   "parameter(s) but no argtypes are "
                                   "wired"))
        elif have["args"] != want["args"]:
            out.append(Finding(py_rel, 1, PASS,
                               f"{sym}: argtypes {have['args']} vs C "
                               f"parameters {want['args']}"))


def _canonical(
    py: dict[str, Any],
    go: dict[str, Any],
    c: dict[str, Any] | None,
) -> dict[str, Any]:
    routes = py.get("routes", {})
    sinks = set(py.get("sink_routes", []))
    client_paths = set(go.get("client_paths", []))
    w2 = py.get("wire2", {})
    return {
        "contract_version": CONTRACT_VERSION,
        "routes": {
            path: {
                "id": rid,
                "sink": path in sinks,
                "go_const": go_extract.const_name_for_path(path),
                "go_client": path in client_paths,
            }
            for path, rid in sorted(routes.items())
        },
        "http_only_routes": py.get("http_only", []),
        "wire2": {
            "magic": w2.get("magic"),
            "hdr_format": w2.get("hdr_format"),
            "hdr_len": w2.get("hdr_len"),
            "resp_format": w2.get("resp_format"),
            "resp_head_len": w2.get("resp_len"),
            "frame_types": dict(sorted(w2.get("frame_types", {}).items())),
            "flags": dict(sorted(w2.get("flags", {}).items())),
            "data_chunk": w2.get("data_chunk"),
        },
        "error_codes": dict(sorted(py.get("error_codes", {}).items())),
        "error_classes": dict(sorted(py.get("class_codes", {}).items())),
        "go_error_codes": sorted(go.get("error_codes", {})),
        "headers": dict(sorted(py.get("headers", {}).items())),
        "wire2_params": dict(sorted(py.get("params", {}).items())),
        "metrics": dict(sorted(py.get("metrics", {}).items())),
        "native_abi": {
            sym: {"restype": v["restype"], "args": v["args"]}
            for sym, v in sorted((c or {}).items())
        },
    }


def _diff_paths(a: Any, b: Any, prefix: str = "", limit: int = 8) -> list[str]:
    """Leaf paths where ``a`` and ``b`` differ (first ``limit``)."""
    out: list[str] = []

    def walk(x: Any, y: Any, at: str) -> None:
        if len(out) >= limit:
            return
        if isinstance(x, dict) and isinstance(y, dict):
            for k in sorted(set(x) | set(y)):
                walk(x.get(k), y.get(k), f"{at}.{k}" if at else str(k))
        elif x != y:
            out.append(f"{at}: {x!r} -> {y!r}")

    walk(a, b, prefix)
    return out


def load_committed(root: str) -> dict[str, Any] | None:
    path = os.path.join(root, CONTRACT_JSON)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def build(
    root: str, overrides: dict[str, str] | None = None
) -> tuple[dict[str, Any], list[Finding]]:
    """-> (canonical contract, cross-surface findings)."""
    overrides = overrides or {}
    findings: list[Finding] = []
    py = py_extract.extract(
        root,
        {r: p for r, p in overrides.items() if r in py_extract.SURFACES},
    )
    go = go_extract.extract(root)
    c = c_abi.extract_c(root, overrides.get("c", c_abi.C_FILE))
    pyabi = c_abi.extract_ctypes(
        root, overrides.get("ctypes", c_abi.CTYPES_FILE)
    )
    _py_internal(py, overrides, findings)
    _go_check(py, go, findings)
    _abi_check(c, pyabi, overrides, findings)
    return _canonical(py, go, c), findings


def run(root: str, files=None) -> list[Finding]:
    overrides = _fixture_overrides(files)
    if files is not None and not overrides:
        return []
    contract, findings = build(root, overrides)
    committed = load_committed(root)
    if committed is None:
        findings.append(Finding(
            CONTRACT_JSON.replace(os.sep, "/"), 1, PASS,
            "committed contract missing — certify with 'python -m "
            "dpf_tpu.analysis --write-contract'",
        ))
    elif committed != contract:
        drift = _diff_paths(committed, contract)
        findings.append(Finding(
            CONTRACT_JSON.replace(os.sep, "/"), 1, PASS,
            "committed contract is stale vs the tree ("
            + "; ".join(drift)
            + ") — if intentional, re-certify with 'python -m "
            "dpf_tpu.analysis --write-contract'",
        ))
    return findings


def render_markdown(contract: dict[str, Any]) -> str:
    """The human twin of CONTRACT.json (docs/CONTRACT.md)."""
    L: list[str] = []
    L.append("# Surface contract")
    L.append("")
    L.append(
        "Generated by `python -m dpf_tpu.analysis --write-contract` — "
        "do not edit by hand.  The `surface-contract` pass diffs the "
        "tree's Python, Go, and native-ABI surfaces against "
        "`docs/CONTRACT.json` (this file is the readable rendering) on "
        "every lint run; semantics in docs/DESIGN.md §22."
    )
    L.append("")
    L.append(f"Contract version: {contract['contract_version']}")
    L.append("")
    L.append("## Routes")
    L.append("")
    L.append("| id | path | Go const | sink | Go client |")
    L.append("|---:|------|----------|:----:|:---------:|")
    for path, r in sorted(
        contract["routes"].items(), key=lambda kv: kv[1]["id"]
    ):
        L.append(
            f"| {r['id']} | `{path}` | `wire2Route{r['go_const']}` | "
            f"{'y' if r['sink'] else ''} | "
            f"{'y' if r['go_client'] else ''} |"
        )
    L.append("")
    L.append(
        "HTTP-only (no wire2 route id): "
        + ", ".join(f"`{p}`" for p in contract["http_only_routes"])
    )
    L.append("")
    w2 = contract["wire2"]
    L.append("## wire2 framing")
    L.append("")
    L.append(f"- preface: `{w2['magic']}` (hex)")
    L.append(
        f"- frame header: `{w2['hdr_format']}` ({w2['hdr_len']} bytes); "
        f"RESP head: `{w2['resp_format']}` ({w2['resp_head_len']} bytes)"
    )
    L.append(f"- DATA chunk: {w2['data_chunk']} bytes")
    L.append(
        "- frame types: "
        + ", ".join(
            f"{name}={val}"
            for name, val in sorted(
                w2["frame_types"].items(), key=lambda kv: kv[1]
            )
        )
    )
    L.append(
        "- flags: "
        + ", ".join(
            f"{name}={val}" for name, val in sorted(w2["flags"].items())
        )
    )
    L.append("")
    L.append("## Error codes")
    L.append("")
    L.append("| code | HTTP | Go client |")
    L.append("|------|-----:|:---------:|")
    go_codes = set(contract["go_error_codes"])
    for code, status in sorted(
        contract["error_codes"].items(), key=lambda kv: (kv[1], kv[0])
    ):
        L.append(
            f"| `{code}` | {status} | {'y' if code in go_codes else ''} |"
        )
    L.append("")
    L.append(
        "Raising classes: "
        + ", ".join(
            f"`{cls}` -> `{code}`"
            for cls, code in sorted(contract["error_classes"].items())
        )
    )
    L.append("")
    L.append("## Headers and wire2 pseudo-params")
    L.append("")
    for key, name in sorted(contract["headers"].items()):
        L.append(f"- {key}: `{name}`")
    for key, name in sorted(contract["wire2_params"].items()):
        L.append(f"- wire2 {key} param: `{name}`")
    L.append("")
    L.append(f"## Metrics ({len(contract['metrics'])})")
    L.append("")
    for name, kind in sorted(contract["metrics"].items()):
        L.append(f"- `{name}` ({kind})")
    L.append("")
    L.append(
        f"## Native ABI ({len(contract['native_abi'])} `dpfn_*` symbols)"
    )
    L.append("")
    L.append("| symbol | returns | args |")
    L.append("|--------|---------|------|")
    for sym, sig in sorted(contract["native_abi"].items()):
        args = ", ".join(sig["args"]) if sig["args"] else "void"
        L.append(f"| `{sym}` | {sig['restype']} | {args} |")
    L.append("")
    return "\n".join(L)


def write(root: str) -> list[str]:
    """Re-certify: build from the real tree and write CONTRACT.json +
    CONTRACT.md.  Raises ValueError (without writing) when the surfaces
    disagree with each other — certification records a coherent tree,
    it does not bless a drift."""
    contract, findings = build(root)
    if findings:
        raise ValueError(
            "refusing to certify a tree whose surfaces disagree:\n"
            + "\n".join(str(f) for f in findings)
        )
    wrote: list[str] = []
    jpath = os.path.join(root, CONTRACT_JSON)
    os.makedirs(os.path.dirname(jpath), exist_ok=True)
    with open(jpath, "w", encoding="utf-8") as f:
        json.dump(contract, f, indent=2, sort_keys=True)
        f.write("\n")
    wrote.append(CONTRACT_JSON)
    mpath = os.path.join(root, CONTRACT_MD)
    with open(mpath, "w", encoding="utf-8") as f:
        f.write(render_markdown(contract))
    wrote.append(CONTRACT_MD)
    return wrote
