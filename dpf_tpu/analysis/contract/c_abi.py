"""Native ABI extraction: ``extern "C" dpfn_*`` vs the ctypes wiring.

The C side is the declarations in ``native/dpf_native.cc``; the Python
side is the ``lib.dpfn_*.restype`` / ``.argtypes`` assignments in
``backends/cpu_native.py``.  Both canonicalize to the same small type
vocabulary so the contract pass can diff them symbol-by-symbol:

  int        C ``int`` / ``ctypes.c_int``
  u64        C ``uint64_t`` / ``ctypes.c_uint64``
  u8p        C ``const uint8_t*`` / ``ctypes.POINTER(ctypes.c_uint8)``
  u64p       C ``const uint64_t*`` / ``ctypes.POINTER(ctypes.c_uint64)``

``(void)`` canonicalizes to an empty arg list; a symbol whose C side
takes no arguments may legitimately skip ``argtypes`` on the Python
side (ctypes' default calling convention is fine for niladic ints —
``dpfn_usable`` / ``dpfn_have_aesni``).  A symbol with C parameters but
no ``argtypes`` wiring is a finding: every call would go through
ctypes' guess-the-ABI path.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any

C_FILE = os.path.join("native", "dpf_native.cc")
CTYPES_FILE = os.path.join("dpf_tpu", "backends", "cpu_native.py")

# Declarations start at column 0 inside the extern "C" blocks; the
# param list may span lines, hence [^)]* with re.M only on the opener.
_C_DECL = re.compile(
    r"(?m)^(int|uint64_t|void)\s+(dpfn_\w+)\s*\(([^)]*)\)"
)

_C_TYPES = {
    "int": "int",
    "uint64_t": "u64",
    "uint8_t*": "u8p",
    "uint64_t*": "u64p",
}
_RET_TYPES = {"int": "int", "uint64_t": "u64", "void": "void"}

_CTYPES_NAMES = {"c_int": "int", "c_uint64": "u64", "c_uint8": "u8"}


def _canon_c_param(param: str) -> str:
    """``const uint8_t* seed0`` -> ``u8p``."""
    toks = param.replace("*", " * ").split()
    toks = [t for t in toks if t != "const"]
    # drop the trailing identifier when present: [type, ('*',) name?]
    if toks and toks[-1] not in ("*",) and toks[-1] not in _C_TYPES:
        star = "*" if "*" in toks[:-1] else ""
        base = toks[0]
    else:
        star = "*" if "*" in toks else ""
        base = toks[0]
    key = base + star
    if key not in _C_TYPES:
        raise ValueError(f"unrecognized C parameter type {param!r}")
    return _C_TYPES[key]


def extract_c(root: str, rel: str = C_FILE) -> dict[str, Any] | None:
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        src = f.read()
    out: dict[str, dict[str, Any]] = {}
    for m in _C_DECL.finditer(src):
        ret, name, params = m.group(1), m.group(2), m.group(3)
        params = params.strip()
        if params in ("", "void"):
            args: list[str] = []
        else:
            args = [_canon_c_param(p) for p in params.split(",")]
        out[name] = {"restype": _RET_TYPES[ret], "args": args}
    return out


def _ctype_name(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonicalize a ctypes type expression used in restype/argtypes."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    if isinstance(node, ast.Attribute) and node.attr in _CTYPES_NAMES:
        return _CTYPES_NAMES[node.attr]
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "POINTER"
        and len(node.args) == 1
    ):
        inner = _ctype_name(node.args[0], aliases)
        return f"{inner}p" if inner else None
    return None


def extract_ctypes(root: str, rel: str = CTYPES_FILE) -> dict[str, Any] | None:
    """``dpfn_*`` symbol -> {"restype": ..., "args": [...] | None} from
    the ``lib.<sym>.restype`` / ``.argtypes`` assignments (AST; the
    module is never imported)."""
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)

    # Local pointer aliases: u8p = ctypes.POINTER(ctypes.c_uint8), ...
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            resolved = _ctype_name(node.value, aliases)
            if resolved and resolved.endswith("p"):
                aliases[node.targets[0].id] = resolved

    out: dict[str, dict[str, Any]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and tgt.attr in ("restype", "argtypes")
            and isinstance(tgt.value, ast.Attribute)
            and tgt.value.attr.startswith("dpfn_")
        ):
            continue
        sym = tgt.value.attr
        entry = out.setdefault(sym, {"restype": None, "args": None})
        if tgt.attr == "restype":
            entry["restype"] = _ctype_name(node.value, aliases)
        elif isinstance(node.value, (ast.List, ast.Tuple)):
            entry["args"] = [
                _ctype_name(el, aliases) for el in node.value.elts
            ]
    return out
