"""``python -m dpf_tpu.analysis`` — run the static-analysis suite.

    python -m dpf_tpu.analysis                 # all passes, whole tree
    python -m dpf_tpu.analysis --pass host-sync
    python -m dpf_tpu.analysis --root /path/to/checkout
    python -m dpf_tpu.analysis --write-knobs-doc   # regenerate docs/KNOBS.md
    python -m dpf_tpu.analysis --check-knobs-doc   # fail when it is stale
    python -m dpf_tpu.analysis --write-oblivious   # re-certify: regenerate
                                                   # docs/OBLIVIOUS.md + json
    python -m dpf_tpu.analysis --write-perf-contracts  # re-certify the
                                                   # performance contracts
    python -m dpf_tpu.analysis --write-contract    # re-certify the
                                                   # cross-language
                                                   # surface contract

Exits 0 on a clean tree, 1 on any finding (CI contract:
``scripts/lint_all.sh`` / ``runtests.sh --lint``).
"""

from __future__ import annotations

import argparse
import os
import sys

from ..core import knobs
from . import LINT_SUITE_VERSION, PASSES, get_pass
from .common import repo_root

_KNOBS_DOC = os.path.join("docs", "KNOBS.md")


def _knobs_doc_path(root: str) -> str:
    return os.path.join(root, _KNOBS_DOC)


def _check_knobs_doc(root: str) -> int:
    want = knobs.render_markdown()
    try:
        with open(_knobs_doc_path(root), encoding="utf-8") as f:
            have = f.read()
    except OSError:
        have = ""
    if have != want:
        print(
            f"{_KNOBS_DOC} is stale — regenerate with "
            "'python -m dpf_tpu.analysis --write-knobs-doc'",
            file=sys.stderr,
        )
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpf_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--pass", dest="passes", action="append", choices=sorted(PASSES),
        help="run only this pass (repeatable; default: all)",
    )
    ap.add_argument(
        "--root", default=None,
        help="tree to scan (default: the checkout containing dpf_tpu/)",
    )
    ap.add_argument(
        "--write-knobs-doc", action="store_true",
        help="regenerate docs/KNOBS.md from the registry and exit",
    )
    ap.add_argument(
        "--check-knobs-doc", action="store_true",
        help="exit 1 when docs/KNOBS.md is stale vs the registry",
    )
    ap.add_argument(
        "--write-oblivious", action="store_true",
        help="re-certify: trace + verify every production route and "
        "regenerate docs/OBLIVIOUS.md + docs/oblivious.json (fails "
        "without writing when any route has findings)",
    )
    ap.add_argument(
        "--write-perf-contracts", action="store_true",
        help="re-certify the performance contracts: trace + budget-check "
        "every production route and donation site and regenerate "
        "docs/PERF_CONTRACTS.md + docs/perf_contracts.json (fails "
        "without writing when any budget is violated)",
    )
    ap.add_argument(
        "--write-contract", action="store_true",
        help="re-certify the cross-language surface contract: extract "
        "the Python/Go/C surfaces and regenerate docs/CONTRACT.json + "
        "docs/CONTRACT.md (fails without writing when the surfaces "
        "disagree with each other)",
    )
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root) if args.root else repo_root()

    if args.write_oblivious:
        if os.path.realpath(root) != os.path.realpath(repo_root()):
            # Same guard as trace_pass: the routes traced are always the
            # imported checkout's — writing their certificates into a
            # foreign --root would attest the wrong tree.
            print(
                "--write-oblivious certifies the checkout it is imported "
                "from; run it from the target tree (foreign --root "
                f"{root!r} refused)",
                file=sys.stderr,
            )
            return 1
        from .trace import certify

        certs, findings = certify.verify_routes()
        if findings:
            for route_name, f in findings:
                print(f"trace://{route_name}: [{f.kind}] {f.message}")
            print(
                f"{len(findings)} finding(s) — refusing to certify a "
                "leaky tree",
                file=sys.stderr,
            )
            return 1
        # Routes the visible topology cannot trace (the 8-shard mesh
        # routes on a single-device invocation) carry their committed
        # certificates forward — dropping them would desync the sidecar
        # from the matrix.  No committed certificate either -> refuse:
        # run under the 8-virtual-device env (runtests.sh / lint_all.sh).
        skipped = certify.skipped_routes()
        if skipped:
            committed = (certify.load_committed(root) or {}).get(
                "routes", {}
            )
            for r in skipped:
                old = committed.get(r.name)
                if old is None:
                    print(
                        f"route {r.name!r} needs >= {r.min_devices} "
                        "devices to certify and has no committed "
                        "certificate — re-run under the 8-virtual-"
                        "device CPU mesh (lint_all.sh forces it)",
                        file=sys.stderr,
                    )
                    return 1
                certs[r.name] = old
                print(
                    f"carried committed certificate for {r.name} "
                    f"(needs >= {r.min_devices} devices, have fewer)"
                )
        for rel in certify.write(root, certs):
            print(f"wrote {rel}")
        return 0

    if args.write_perf_contracts:
        if os.path.realpath(root) != os.path.realpath(repo_root()):
            print(
                "--write-perf-contracts certifies the checkout it is "
                "imported from; run it from the target tree (foreign "
                f"--root {root!r} refused)",
                file=sys.stderr,
            )
            return 1
        from .perf import certify as perf_certify

        certs, findings = perf_certify.verify_routes()
        if findings:
            for f in findings:
                print(f"perf://{f.where}: [{f.kind}] {f.message}")
            print(
                f"{len(findings)} finding(s) — refusing to certify a tree "
                "that busts its budgets",
                file=sys.stderr,
            )
            return 1
        # Same topology policy as --write-oblivious: routes the visible
        # device count cannot trace carry their committed certificates
        # forward (none committed -> refuse; run under the 8-virtual-
        # device env the sanctioned entry points force).
        committed = perf_certify.load_committed(root) or {}
        skipped = perf_certify.skipped_routes()
        if skipped:
            committed_routes = committed.get("routes", {})
            for r in skipped:
                old = committed_routes.get(r.name)
                if old is None:
                    print(
                        f"route {r.name!r} needs >= {r.min_devices} "
                        "devices to certify and has no committed perf "
                        "certificate — re-run under the 8-virtual-"
                        "device CPU mesh (lint_all.sh forces it)",
                        file=sys.stderr,
                    )
                    return 1
                certs[r.name] = old
                print(
                    f"carried committed perf certificate for {r.name} "
                    f"(needs >= {r.min_devices} devices, have fewer)"
                )
        # Same carry-forward for donation sites the topology cannot
        # build — a single-device re-certification must not silently
        # write a ledger missing the sharded carries.
        skipped_sites = perf_certify.skipped_donation_sites()
        if skipped_sites:
            committed_don = committed.get("donation_sites", {})
            donation = certs.setdefault("__donation__", {})
            for s in skipped_sites:
                old = committed_don.get(s.name)
                if old is None:
                    print(
                        f"donation site {s.name!r} needs >= "
                        f"{s.min_devices} devices to verify and has no "
                        "committed entry — re-run under the 8-virtual-"
                        "device CPU mesh (lint_all.sh forces it)",
                        file=sys.stderr,
                    )
                    return 1
                donation[s.name] = old
                print(
                    f"carried committed donation evidence for {s.name} "
                    f"(needs >= {s.min_devices} devices, have fewer)"
                )
        for rel in perf_certify.write(root, certs):
            print(f"wrote {rel}")
        return 0

    if args.write_contract:
        if os.path.realpath(root) != os.path.realpath(repo_root()):
            # Same guard as the other re-certifiers: the Go fallback
            # and ctypes extraction describe THIS checkout's sources;
            # writing their contract into a foreign --root would attest
            # the wrong tree.
            print(
                "--write-contract certifies the checkout it is imported "
                "from; run it from the target tree (foreign --root "
                f"{root!r} refused)",
                file=sys.stderr,
            )
            return 1
        from .contract import contract_pass

        try:
            wrote = contract_pass.write(root)
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1
        for rel in wrote:
            print(f"wrote {rel}")
        return 0

    if args.write_knobs_doc:
        path = _knobs_doc_path(root)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(knobs.render_markdown())
        print(f"wrote {os.path.relpath(path, root)}")
        return 0
    if args.check_knobs_doc:
        return _check_knobs_doc(root)

    names = args.passes or sorted(PASSES)
    findings = []
    for name in names:
        findings.extend(get_pass(name)(root))
    findings.sort(key=lambda f: (f.path, f.line))
    for f in findings:
        print(f)
    print(
        f"dpf_tpu.analysis v{LINT_SUITE_VERSION}: "
        f"{len(names)} pass(es), {len(findings)} finding(s)",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
