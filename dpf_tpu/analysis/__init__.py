"""Repo-native static analysis: the discipline the ROADMAP's production
north star needs, checked on every commit for free.

Eight file/AST-based passes plus two jaxpr-level passes over the whole
tree (one entrypoint: ``python -m dpf_tpu.analysis`` /
``scripts/lint_all.sh``; exits nonzero on any finding):

  knob-registry   every DPF_TPU_* env knob is declared once in
                  dpf_tpu/core/knobs.py and read only through it —
                  direct env reads and undeclared (typo'd) knob names
                  are findings; on whole-tree scans, declared knobs no
                  non-fixture module reads are findings too (dead knobs
                  rot into documentation lies — ``# knob-unused-ok`` on
                  the declaration is the reviewed escape hatch).
  secret-hygiene  key bytes / PRG seeds / correction words must never
                  flow into logging, f-strings in raised exceptions,
                  /v1/stats payloads, or bench ledgers (name-based
                  intra-function taint; the sha256 digest in
                  serving/keycache.py is the sanctioned sanitizer).
  host-sync       no silent device->host synchronization in the kernel
                  and serving hot paths (.block_until_ready(), .item(),
                  jax.device_get, bare np.asarray materialization)
                  except at ``# host-sync:``-annotated sync points.
  pallas-jit      every pl.pallas_call site carries a statically
                  evaluated ``# vmem:`` footprint model within the
                  module's declared VMEM budget, and every jax.jit's
                  static/donate argnum specs are hashable literals
                  (no list/dict retrace hazards).
  test-discipline the test surface stays wired: every test file named
                  in a runtests.sh lane exists, the tier-1 ``tests/``
                  glob lane is still present (so every on-disk test is
                  reachable), every ``pytest.mark.*`` used under tests/
                  is declared in pytest.ini (an undeclared marker makes
                  ``-m`` selections silently skip nothing), and the
                  collection-order hook's file references resolve.
  lock-discipline the serving plane's concurrency contract
                  (``analysis/concurrency/``): every threading primitive
                  declared with an owner + ordering rank in the lock
                  registry, acquisition-order inversions/cycles over the
                  AST ``with``-nesting graph, guarded-field inference
                  (written under a lock somewhere, touched lock-free
                  elsewhere — ``# lock-free-ok: <why>`` sanctions the
                  reviewed benign reads), and no lock held across a
                  device dispatch / socket I/O / sleep / thread join
                  (``# lock-held-ok: <why>`` is the escape hatch).  The
                  same package ships the deterministic interleaving
                  harness (``concurrency/sched.py``) the concurrency
                  scenario tests replay seeded schedules through.
  oblivious-trace the jaxpr-level oblivious-dataflow verifier
                  (``analysis/trace/``): every production route traced
                  to a ClosedJaxpr, the interprocedural taint lattice
                  run over it (secret-tainted branch predicates, memory
                  indices, callbacks, float casts, dynamic shapes; Ref
                  tracking inside Pallas kernels; VMEM block footprints
                  vs the ops budget), and the resulting obliviousness
                  certificates (docs/OBLIVIOUS.md + docs/oblivious.json)
                  checked for drift against the committed tree.
  tuned-defaults  the committed ``docs/TUNED.json`` autotuner output
                  validates against the schema/registry contract in
                  ``dpf_tpu/tune/tuned.py``: known routes/profiles,
                  config knobs on declared search-space axes with
                  allowed values, sane margins, and a ``knobs_digest``
                  fresh against the current tunable-knob declarations
                  (a stale file fails soft at serving time by design —
                  CI is where it must fail hard).
  surface-contract  the cross-language surface verifier
                  (``analysis/contract/``): the route/route_id table,
                  wire2 frame types + 12-byte header layout, the
                  ``{code, detail}`` error vocabulary, the ``X-DPF-*``
                  headers, the ``dpf_*`` metric names, and the
                  ``dpfn_*`` native ABI extracted statically from the
                  Python sidecar, the Go bridge (go/ast via
                  ``bridge/go/cmd/contract-dump`` when a toolchain
                  exists, a pinned regex fallback otherwise), and the
                  C/ctypes pair — cross-checked against each other and
                  against the committed ``docs/CONTRACT.json``
                  (``--write-contract`` re-certifies intentional
                  changes; same drift policy as OBLIVIOUS.md).
  perf-contract   the jaxpr-level performance-contract verifier
                  (``analysis/perf/``): the SAME route traces (shared
                  trace cache — each route traces once per lint run)
                  checked against per-route declared resource budgets:
                  collective census (one all-reduce per agg chunk / PIR
                  query batch, zero elsewhere), donation surviving into
                  the lowering with no live output copies, zero
                  unsanctioned host callbacks, chunk indices as traced
                  operands (no retrace bombs), plus a static FLOPs/HBM
                  cost model — certificates in docs/PERF_CONTRACTS.md +
                  docs/perf_contracts.json with the same drift policy.

Each pass ships fixture files with seeded violations
(``dpf_tpu/analysis/fixtures/``, excluded from real scans) and a test
asserting the pass catches them AND that the real tree is clean
(tests/test_analysis.py) — the suite is a tier-1 lane
(``runtests.sh --lint``).

``LINT_SUITE_VERSION`` names the discipline in force; bench_all.py
stamps it into the ledger key so benches record which suite vetted the
tree they measured.
"""

from __future__ import annotations

# Bump when a pass is added or materially tightened (bench ledgers keyed
# on it re-measure).  "2": the oblivious-trace jaxpr verifier joined the
# suite and host-sync grew the models/ + parallel/ scope.  "3": the
# perf-contract verifier and the test-discipline pass joined, and
# knob-registry grew unused-knob detection.  "4": the tuned-defaults
# pass joined (committed autotuner output validated every commit).
# "5": the lock-discipline pass joined (whole-repo lock registry,
# acquisition-order graph, guarded-field inference, held-across-blocking
# — the serving plane's concurrency contract checked every commit).
# "6": the surface-contract pass joined (routes, wire2 frames, error
# codes, headers, metrics, and the dpfn_* ABI cross-checked across the
# Python/Go/C surfaces against the committed docs/CONTRACT.json).
LINT_SUITE_VERSION = "6"

# name -> (module, callable); imported lazily so `import dpf_tpu.analysis`
# stays cheap for the bench harness's version stamp.  Passes run in
# sorted-name order, which puts oblivious-trace BEFORE perf-contract —
# the first populates the shared trace cache the second reads.
PASSES = {
    "knob-registry": ("dpf_tpu.analysis.knob_registry_pass", "run"),
    "secret-hygiene": ("dpf_tpu.analysis.secret_hygiene_pass", "run"),
    "host-sync": ("dpf_tpu.analysis.host_sync_pass", "run"),
    "pallas-jit": ("dpf_tpu.analysis.pallas_discipline_pass", "run"),
    "test-discipline": ("dpf_tpu.analysis.test_discipline_pass", "run"),
    "lock-discipline": ("dpf_tpu.analysis.concurrency.lock_pass", "run"),
    "tuned-defaults": ("dpf_tpu.analysis.tuned_pass", "run"),
    "surface-contract": ("dpf_tpu.analysis.contract.contract_pass", "run"),
    "oblivious-trace": ("dpf_tpu.analysis.trace_pass", "run"),
    "perf-contract": ("dpf_tpu.analysis.perf_pass", "run"),
}


def get_pass(name: str):
    """The pass callable for ``name`` (import on demand)."""
    import importlib

    mod_name, fn_name = PASSES[name]
    return getattr(importlib.import_module(mod_name), fn_name)
