"""Pass: committed tuned-defaults discipline (``tuned-defaults``).

``docs/TUNED.json`` is a committed artifact the plan cache consults at
warmup (``core/plans.py`` under ``DPF_TPU_TUNED``) — a broken or stale
file fails SOFT at serving time by design (the loader falls back to
registry defaults and surfaces the error only in ``/v1/stats``), which
is exactly why CI must fail HARD here: nothing else stops a bad commit
from silently serving untuned.  Rules:

  D1  the file parses as JSON.
  D2  it validates against the schema/registry/staleness contract in
      ``dpf_tpu/tune/tuned.py`` (schema version, provenance backend and
      head, per-entry route/profile/shape keys, every config knob on a
      declared search-space axis with an allowed value, margins in
      (0, 1), no duplicate keys, and ``knobs_digest`` fresh against the
      current tunable-knob declarations + search space — a changed knob
      default or axis means the measured winners no longer describe
      this tree and the sweep must be re-run with ``--write-tuned``).

An absent file is clean: the tuner simply has not been run (or its
winners were never committed), and the plan cache serves registry
defaults.  ``files`` may name fixture .json documents to scan instead
of the committed path (the lint suite's own tests use this).
"""

from __future__ import annotations

import json
import os

from .common import Finding

PASS = "tuned-defaults"

_DOC = os.path.join("docs", "TUNED.json")


def run(root: str, files=None) -> list[Finding]:
    rels = [f for f in files if f.endswith(".json")] if files else [_DOC]
    out: list[Finding] = []
    from ..tune import tuned

    for rel in rels:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue  # no tuned winners committed: registry defaults
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except ValueError as e:
            out.append(Finding(rel, 1, PASS, f"unparseable JSON: {e}"))
            continue
        out.extend(
            Finding(rel, 1, PASS, problem)
            for problem in tuned.validate(doc)
        )
    return out
