"""Pass 6: jaxpr-level performance-contract verification (the
``perf-contract`` pass).

Re-uses the oblivious-trace pass's route traces through the shared
trace cache (``trace/entrypoints.trace_route_cached`` — one lint run
traces each route once, not once per pass), runs the resource model
(``perf/model.py``) against every route's declared
:class:`~dpf_tpu.analysis.perf.contracts.PerfContract`, lowers the
production donated twins, and fails on

  * any budget violation (collective census, loop collectives, host
    crossings, donation live-copies, dropped donation, chunk-index
    retrace hazards), and
  * certificate drift: a route whose certificate no longer matches the
    committed ``docs/perf_contracts.json`` (re-certify with
    ``python -m dpf_tpu.analysis --write-perf-contracts``).

Same foreign-root policy as the oblivious-trace pass: the traced routes
are always the imported checkout's, so a foreign ``--root`` gets one
explanatory finding instead of a misleading verdict.
"""

from __future__ import annotations

import os

from .common import Finding, repo_root

PASS = "perf-contract"


def run(root: str, files=None) -> list[Finding]:
    if os.path.realpath(root) != os.path.realpath(repo_root()):
        return [
            Finding(
                "dpf_tpu/analysis/perf", 0, PASS,
                "the perf-contract verifier only certifies the checkout "
                "it is imported from; run it from the target tree",
            )
        ]
    from .perf import certify

    certs, perf_findings = certify.verify_routes()
    out: list[Finding] = []
    for f in perf_findings:
        out.append(
            Finding(f"perf://{f.where}", 0, PASS, f"[{f.kind}] {f.message}")
        )
    for msg in certify.drift(root, certs):
        out.append(Finding(certify.PERF_JSON, 0, PASS, msg))
    return out
