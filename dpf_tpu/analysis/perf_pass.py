"""Pass 6: jaxpr-level performance-contract verification (the
``perf-contract`` pass).

Re-uses the oblivious-trace pass's route traces through the shared
trace cache (``trace/entrypoints.trace_route_cached`` — one lint run
traces each route once, not once per pass), runs the resource model
(``perf/model.py``) against every route's declared
:class:`~dpf_tpu.analysis.perf.contracts.PerfContract`, lowers the
production donated twins, and fails on

  * any budget violation (collective census, loop collectives, host
    crossings, donation live-copies, dropped donation, chunk-index
    retrace hazards), and
  * certificate drift: a route whose certificate no longer matches the
    committed ``docs/perf_contracts.json`` (re-certify with
    ``python -m dpf_tpu.analysis --write-perf-contracts``).

The pass also owns the **wire-path budget** (the wire2 transport's
structural claim): the serving hot path must make ZERO ``bytes()`` /
``bytearray()`` / ``.tobytes()`` materializations of request-body
buffers — the whole point of the binary front is that bodies flow as
``memoryview`` slices from the socket's receive buffer straight into
``np.frombuffer``/``device_put``, and one stray ``bytes(body)`` quietly
restores the copy the transport exists to delete.  This budget is
AST-level (no tracing): it scans ``serving/wire2.py`` and
``serving/handlers.py`` for copy calls over body-buffer names, with
``# wire-copy-ok: <why>`` as the reviewed in-place escape hatch (the
warmup/profile JSON bodies, the client-side reply materialization).
Unlike the jaxpr budgets it runs on ANY --root, so the fixture tests
exercise it on synthetic trees.

Same foreign-root policy as the oblivious-trace pass for the jaxpr
budgets: the traced routes are always the imported checkout's, so a
foreign ``--root`` gets one explanatory finding instead of a misleading
verdict.
"""

from __future__ import annotations

import ast
import os

from .common import Finding, parse_file, pragma, repo_root

PASS = "perf-contract"

# The wire-path budget's scope: the transport and the shared handler
# core — the two modules request bodies flow through between socket
# buffer and dispatch operand.
WIRE_PATH_FILES = (
    "dpf_tpu/serving/wire2.py",
    "dpf_tpu/serving/handlers.py",
)

# Identifier / attribute names that carry request-body buffers in those
# modules (the same name-based auditability bargain as the secret-
# hygiene pass: pin the names the code actually uses).
_BODY_NAMES = frozenset(
    {"body", "view", "mv", "buf", "payload", "chunk", "blob", "dbv"}
)
_COPY_CALLS = frozenset({"bytes", "bytearray"})


def _mentions_body(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _BODY_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _BODY_NAMES:
            return True
    return False


def wire_path_findings(root: str) -> list[Finding]:
    """Zero ``bytes()`` materializations of request bodies on the wire
    hot path (files in :data:`WIRE_PATH_FILES` under ``root``; a
    missing file simply has no findings — synthetic test roots carry
    only the module under test)."""
    out: list[Finding] = []
    for rel in WIRE_PATH_FILES:
        if not os.path.isfile(os.path.join(root, rel)):
            continue
        try:
            tree, lines = parse_file(root, rel)
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS,
                               f"syntax error: {e}"))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = None
            if (
                isinstance(fn, ast.Name) and fn.id in _COPY_CALLS
                and node.args and _mentions_body(node.args[0])
            ):
                hit = f"{fn.id}()"
            elif (
                isinstance(fn, ast.Attribute) and fn.attr == "tobytes"
                and not node.args and _mentions_body(fn.value)
            ):
                hit = ".tobytes()"
            if hit is None:
                continue
            if pragma(lines, node.lineno, "wire-copy-ok:"):
                continue  # annotated (with a why): sanctioned copy
            out.append(Finding(
                rel, node.lineno, PASS,
                f"[wire-path] {hit} materializes a request-body buffer "
                "on the wire hot path — the zero-copy budget is zero "
                "intermediate bytes copies between socket buffer and "
                "dispatch operand; keep it a memoryview (np.frombuffer "
                "accepts views) or annotate the line with "
                "'# wire-copy-ok: <why>' if it is genuinely off the "
                "hot path",
            ))
    return out


def run(root: str, files=None) -> list[Finding]:
    # The wire-path budget is file-based and root-relative: it runs
    # everywhere, including the synthetic roots the fixture tests build.
    out: list[Finding] = wire_path_findings(root)
    if os.path.realpath(root) != os.path.realpath(repo_root()):
        out.append(
            Finding(
                "dpf_tpu/analysis/perf", 0, PASS,
                "the perf-contract verifier only certifies the checkout "
                "it is imported from; run it from the target tree "
                "(the wire-path budget above DID scan this root)",
            )
        )
        return out
    from .perf import certify

    certs, perf_findings = certify.verify_routes()
    for f in perf_findings:
        out.append(
            Finding(f"perf://{f.where}", 0, PASS, f"[{f.kind}] {f.message}")
        )
    for msg in certify.drift(root, certs):
        out.append(Finding(certify.PERF_JSON, 0, PASS, msg))
    return out
