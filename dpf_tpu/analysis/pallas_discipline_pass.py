"""Pass 4: Pallas VMEM-budget and jax.jit static-arg discipline.

VMEM rule — every ``pl.pallas_call`` site must carry a statically
computable footprint model: a ``# vmem: <expr>`` annotation on the call
(or the line above), evaluated against the OWNING MODULE's namespace
(tile constants, the budget model functions like ``fuse_vmem_bytes``),
and the result must fit the module's declared ``_VMEM_BUDGET``.  A
kernel whose modeled footprint silently outgrows the budget stops
lowering on real hardware with an opaque Mosaic error — this pass moves
that failure to lint time, and makes "how much VMEM does this kernel
think it uses" a reviewable, greppable fact next to the call.

jit rule — ``jax.jit`` (bare or through ``functools.partial``) must
spell ``static_argnums`` / ``static_argnames`` / ``donate_argnums`` as
hashable literals: an int/str or a tuple of them.  A list/dict/set (or
computed) spec is rejected — mutable static-arg plumbing is exactly the
retrace hazard PR 3's zero-retrace-after-warmup assertion can only
catch dynamically, on shapes the tests happened to exercise.

Module namespaces come from importing the real module when the file
lives in THIS checkout's ``dpf_tpu`` package (hermetic: CPU jax); files
outside — fixtures, or any ``--root`` pointing at another tree (whose
same-named modules would otherwise import from THIS checkout and
evaluate its pragmas against the wrong constants) — get a namespace of
their top-level constant assignments, so fixture tests run without
importing seeded-violation code and foreign-tree models that need
functions fail loudly as "failed to evaluate" rather than silently
passing against mismatched budgets.
"""

from __future__ import annotations

import ast
import importlib
import os

from .common import (
    Finding, dotted_module, import_aliases, in_scope, iter_py_files,
    parse_file, pragma, repo_root, resolve_dotted,
)

PASS = "pallas-jit"

_SCOPE = ("dpf_tpu",)
_BUDGET_NAME = "_VMEM_BUDGET"
_SPEC_KEYWORDS = ("static_argnums", "static_argnames", "donate_argnums")


def _const_namespace(tree: ast.Module) -> dict:
    """Top-level ``NAME = <literal int expr>`` bindings — the fallback
    namespace for files that are not importable package modules."""
    ns: dict = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            try:
                ns[stmt.targets[0].id] = eval(  # noqa: S307 — literals only
                    compile(ast.Expression(stmt.value), "<const>", "eval"),
                    {"__builtins__": {}},
                    {},
                )
            except Exception:  # noqa: BLE001 — non-constant, skip
                pass
    return ns


def _namespace(root: str, rel: str, tree: ast.Module) -> dict:
    mod = dotted_module(rel)
    if mod is not None and os.path.realpath(root) == os.path.realpath(
        repo_root()
    ):
        try:
            return vars(importlib.import_module(mod))
        except Exception:  # noqa: BLE001 — fall back to constants
            pass
    return _const_namespace(tree)


def _is_pallas_call(node: ast.Call, aliases: dict[str, str]) -> bool:
    """pallas_call in any spelling: ``pl.pallas_call`` (attribute on any
    base — the repo idiom), or a from-imported bare ``pallas_call``."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "pallas_call":
        return True
    resolved = resolve_dotted(fn, aliases)
    return resolved is not None and resolved.endswith(".pallas_call")


def _is_jit_expr(node: ast.AST, aliases: dict[str, str]) -> bool:
    """``jax.jit`` as a call target — jax.jit(...) directly, a
    from-imported bare ``jit``, or either through
    partial(jax.jit, ...)."""
    return resolve_dotted(node, aliases) == "jax.jit"


def _hashable_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str))
    if isinstance(node, ast.Tuple):
        return all(_hashable_literal(e) for e in node.elts)
    return False


def _check_jit_call(rel: str, node: ast.Call, out: list[Finding]) -> None:
    """``node`` is a call whose arguments configure jax.jit (either
    jax.jit(...) itself or partial(jax.jit, ...))."""
    for kw in node.keywords:
        if kw.arg in _SPEC_KEYWORDS:
            if not _hashable_literal(kw.value):
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        f"{kw.arg} must be an int/str literal or a tuple "
                        "of them — a list/dict/computed spec is a "
                        "retrace hazard the plan cache cannot see",
                    )
                )


def check_file(root: str, rel: str) -> list[Finding]:
    tree, lines = parse_file(root, rel)
    out: list[Finding] = []
    aliases = import_aliases(tree)
    ns: dict | None = None  # built lazily, only when a kernel site needs it

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue

        if _is_pallas_call(node, aliases):
            expr = pragma(lines, node.lineno, "vmem:")
            if expr is None or not expr:
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        "pl.pallas_call without a '# vmem: <expr>' "
                        "footprint model (statically computable, within "
                        f"the module's {_BUDGET_NAME})",
                    )
                )
                continue
            if ns is None:
                ns = _namespace(root, rel, tree)
            budget = ns.get(_BUDGET_NAME)
            if not isinstance(budget, int):
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        f"module declares no integer {_BUDGET_NAME} to "
                        "check its '# vmem:' models against",
                    )
                )
                continue
            try:
                est = eval(  # noqa: S307 — repo-authored pragma exprs
                    compile(ast.Expression(
                        ast.parse(expr, mode="eval").body
                    ), "<vmem>", "eval"),
                    {"__builtins__": {}},
                    dict(ns),
                )
            except Exception as e:  # noqa: BLE001
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        f"'# vmem: {expr}' failed to evaluate statically: "
                        f"{type(e).__name__}: {e}",
                    )
                )
                continue
            if not isinstance(est, (int, float)):
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        f"'# vmem: {expr}' evaluated to {type(est).__name__},"
                        " not bytes",
                    )
                )
            elif est > budget:
                out.append(
                    Finding(
                        rel, node.lineno, PASS,
                        f"modeled VMEM footprint {int(est)} B exceeds "
                        f"{_BUDGET_NAME} = {budget} B",
                    )
                )

        elif _is_jit_expr(node.func, aliases):
            _check_jit_call(rel, node, out)
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id == "partial"
            or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "partial"
            )
        ):
            if node.args and _is_jit_expr(node.args[0], aliases):
                _check_jit_call(rel, node, out)

    return out


def run(root: str, files=None) -> list[Finding]:
    if files is None:
        files = [f for f in iter_py_files(root) if in_scope(f, _SCOPE)]
    out: list[Finding] = []
    for rel in files:
        try:
            out.extend(check_file(root, rel))
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS, f"syntax error: {e}"))
    return out
