"""lock-discipline: static concurrency verifier for the serving plane.

Four rules over the threaded scope (serving/, obs/, apps/, tune/,
parallel/, backends/, core/plans.py):

  R1 (registry)      every ``threading.Lock/RLock/Condition/Event``
      creation is declared in ``concurrency/registry.py`` with an owner
      and an acquisition-order rank; an undeclared creation is a
      finding, and so is a declaration whose creation site no longer
      exists (whole-tree scans only — the table cannot rot).
  R2 (lock order)    the acquisition-order graph built from ``with``-
      block nesting, followed through resolved calls (import aliases,
      ``self.`` methods, annotated parameters): a nested acquisition
      must strictly increase the declared rank unless both locks are in
      the same declared group (the shared re-entrant stats family), and
      any cycle between declared locks is a finding.
  R3 (guarded field) a field written under a lock somewhere but read or
      written lock-free elsewhere is a torn read waiting for traffic;
      ``# lock-free-ok: <why>`` on the access line is the reviewed
      sanction for the genuinely benign ones.  Tracked per class
      (``self.attr``) and per module (globals written under a module
      lock).  A ``*_locked``-suffixed function is callers-hold-the-lock
      by convention and counts as guarded.
  R4 (held across)   no declared lock may be held across a device
      dispatch (``plans.run_*``), socket I/O (``recv/recv_into/sendall/
      sendmsg``), ``time.sleep``, a thread ``join``, or a ``wait`` on a
      DIFFERENT primitive — the exact shape that turns one wedged
      dispatch into a full serving stall.  Declared ``io_ok`` locks
      (the wire2 write-serialization locks) are sanctioned for the
      socket sends that are their whole purpose, nothing else.
      ``# lock-held-ok: <why>`` on the call line is the in-place escape
      hatch, mirroring ``# host-sync:``.

Call resolution is deliberately shallow-but-honest: exact targets
(same-module functions, ``self.`` methods, import-alias dotted names,
parameters with class annotations) propagate transitively; when a
method call cannot be resolved exactly, R2 falls back to matching the
method NAME against every scanned class's lock-acquiring methods (an
over-approximation that is safe for ordering — extra edges only
tighten the rank discipline), while R4 uses exact targets only (a
false "blocks" verdict would be noise).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

from .. import common
from .registry import FIXTURE_LOCKS, LOCKS, LockDecl

PASS = "lock-discipline"

_SCOPE = (
    "dpf_tpu/serving",
    "dpf_tpu/obs",
    "dpf_tpu/apps",
    "dpf_tpu/tune",
    "dpf_tpu/parallel",
    "dpf_tpu/backends",
    "dpf_tpu/core/plans.py",
    "dpf_tpu/analysis/fixtures",
)

_PRIMITIVES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",
    "threading.Event": "event",
}

# Socket ops named by the rule (method names — sockets are duck-typed
# at every call site in the tree).
_SOCKET_OPS = {"recv", "recv_into", "sendall", "sendmsg"}


def _mod_of(rel: str) -> str:
    """Repo-relative path -> dotted site prefix (works for fixture files
    too, unlike common.dotted_module — registry keys use this form)."""
    return rel.replace(os.sep, "/")[: -len(".py")].replace("/", ".")


def _aliases(tree: ast.Module, mod: str) -> dict[str, str]:
    """common.import_aliases plus RELATIVE from-imports resolved against
    this module's dotted name (the serving tree imports its siblings
    almost exclusively as ``from ..core import plans``)."""
    out = common.import_aliases(tree)
    pkg = mod.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level:
            base = pkg[: len(pkg) - (node.level - 1)]
            if not base:
                continue
            head = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{head}.{a.name}"
    return out


@dataclasses.dataclass
class _Acq:
    """One ``with``-acquisition of a declared lock."""

    site: str
    expr: str  # ast.dump of the context expr (same-object wait check)
    line: int


@dataclasses.dataclass
class _FuncInfo:
    qual: str  # "Class.method" or "func", module-local
    mod: str
    rel: str
    acquires: list[_Acq] = dataclasses.field(default_factory=list)
    # (held-stack snapshot, call node, exact targets "mod:qual", attr name)
    calls: list[tuple[tuple[_Acq, ...], ast.Call, list[str], str | None]] = (
        dataclasses.field(default_factory=list)
    )
    # direct blocking ops anywhere in the body: (kind, line)
    blocking: list[tuple[str, int]] = dataclasses.field(default_factory=list)


class _Scan:
    """Whole-scan state shared across files."""

    def __init__(self, decls: dict[str, LockDecl]):
        self.decls = decls
        self.findings: list[common.Finding] = []
        self.created: set[str] = set()  # declared sites actually seen
        self.funcs: dict[str, _FuncInfo] = {}  # "mod:qual" -> info
        # method name -> ["mod:qual", ...] for the R2 name fallback
        self.by_method: dict[str, list[str]] = {}
        # R2 edges: (outer site, inner site) -> (rel, line)
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}

    def finding(self, rel: str, line: int, msg: str) -> None:
        self.findings.append(common.Finding(rel, line, PASS, msg))


def _class_of(site: str) -> str:
    """'mod.Class.attr' -> 'mod.Class' ('' for module globals).  Class
    names may be private (``_Conn``), so strip underscores first."""
    head = site.rsplit(".", 1)[0]
    tail = head.rsplit(".", 1)[-1].lstrip("_")
    return head if tail[:1].isupper() else ""


# Method names the R2 name fallback must NOT match: they collide with
# dict/list/set/socket builtins, so an unresolved ``self._table.get(k)``
# under a lock would otherwise fabricate an edge to every scanned class
# that happens to define a lock-taking method of the same name.  Exact
# (type-resolved) calls are unaffected.
_FALLBACK_DENY = frozenset({
    "get", "pop", "clear", "items", "keys", "values", "setdefault",
    "append", "update", "add", "discard", "remove", "put", "join",
    "wait", "set", "copy", "sort", "extend", "index", "count", "close",
    "read", "write", "send", "recv", "acquire", "release", "start",
})


class _FileVisitor:
    """One file: creations, per-function acquisition structure, guarded
    fields.  Runs as an explicit recursive walk (not ast.NodeVisitor) so
    the held-lock stack threads through ``with`` bodies naturally."""

    def __init__(self, scan: _Scan, rel: str, tree: ast.Module,
                 lines: list[str]):
        self.scan = scan
        self.rel = rel
        self.mod = _mod_of(rel)
        self.tree = tree
        self.lines = lines
        self.aliases = _aliases(tree, self.mod)
        # class name -> {attr: [(write?, guarded?, lock site|None, line)]}
        self.fields: dict[str, dict[str, list]] = {}
        self.globals_: dict[str, list] = {}
        self.module_names: set[str] = set()
        # param/local name -> dotted class, per function (annotation typing)
        self._var_types: dict[str, str] = {}
        self._assigned_calls: set[int] = set()  # id()s of captured creations

    # -- entry ---------------------------------------------------------

    def run(self) -> None:
        for name in self._module_level_names():
            self.module_names.add(name)
        self._collect_creations()
        body_ctx = _Ctx(cls=None, func=None)
        self._walk_body(self.tree.body, body_ctx)
        self._check_stray_creations()
        self._report_fields()

    def _module_level_names(self) -> Iterable[str]:
        for node in self.tree.body:
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    yield t.id

    # -- R1: creations -------------------------------------------------

    def _primitive_kind(self, call: ast.AST) -> str | None:
        if not isinstance(call, ast.Call):
            return None
        dotted = common.resolve_dotted(call.func, self.aliases)
        return _PRIMITIVES.get(dotted or "")

    def _collect_creations(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if value is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for sub in ast.walk(value):
                kind = self._primitive_kind(sub)
                if kind is None:
                    continue
                self._assigned_calls.add(id(sub))
                site = self._site_for(targets, sub)
                if site is None:
                    self.scan.finding(
                        self.rel, sub.lineno,
                        f"{kind} created without a nameable site — bind it "
                        "to a module global or a self attribute so it can "
                        "be declared in analysis/concurrency/registry.py",
                    )
                    continue
                decl = self.scan.decls.get(site)
                if decl is None:
                    self.scan.finding(
                        self.rel, sub.lineno,
                        f"undeclared {kind} creation: declare '{site}' with "
                        "an owner and rank in "
                        "analysis/concurrency/registry.py",
                    )
                    continue
                self.scan.created.add(site)
                if decl.kind != kind:
                    self.scan.finding(
                        self.rel, sub.lineno,
                        f"'{site}' declared as {decl.kind} but created as "
                        f"{kind} — fix the registry entry",
                    )

    def _site_for(self, targets: list[ast.expr],
                  call: ast.AST) -> str | None:
        """Site name for a primitive assigned to the FIRST sane target:
        self.attr -> mod.Class.attr, NAME -> mod.NAME."""
        cls = self._enclosing_class(call)
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and cls):
                return f"{self.mod}.{cls}.{t.attr}"
            if isinstance(t, ast.Name):
                if cls and not self._at_module_level(call):
                    return f"{self.mod}.{cls}.{t.id}"
                return f"{self.mod}.{t.id}"
        return None

    def _enclosing_class(self, node: ast.AST) -> str | None:
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            for sub in ast.walk(cls):
                if sub is node:
                    return cls.name
        return None

    def _at_module_level(self, node: ast.AST) -> bool:
        for stmt in self.tree.body:
            for sub in ast.walk(stmt):
                if sub is node:
                    return isinstance(stmt, (ast.Assign, ast.AnnAssign))
        return False

    def _check_stray_creations(self) -> None:
        for node in ast.walk(self.tree):
            kind = self._primitive_kind(node)
            if kind is not None and id(node) not in self._assigned_calls:
                self.scan.finding(
                    self.rel, node.lineno,
                    f"{kind} created outside an assignment — bind it to a "
                    "declarable site (registry rule R1)",
                )

    # -- the recursive walk --------------------------------------------

    def _walk_body(self, body: list[ast.stmt], ctx: "_Ctx") -> None:
        for stmt in body:
            self._walk_stmt(stmt, ctx)

    def _walk_stmt(self, stmt: ast.stmt, ctx: "_Ctx") -> None:
        if isinstance(stmt, ast.ClassDef):
            self._walk_body(stmt.body, _Ctx(cls=stmt.name, func=None))
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{ctx.cls}.{stmt.name}" if ctx.cls else stmt.name
            if ctx.func is not None:  # nested def: parent.child
                qual = f"{ctx.func.qual}.{stmt.name}"
            info = _FuncInfo(qual=qual, mod=self.mod, rel=self.rel)
            self.scan.funcs[f"{self.mod}:{qual}"] = info
            self.scan.by_method.setdefault(stmt.name, []).append(
                f"{self.mod}:{qual}"
            )
            self._var_types = self._annotation_types(stmt)
            fctx = _Ctx(cls=ctx.cls, func=info, fname=stmt.name,
                        var_types=self._var_types)
            self._walk_body(stmt.body, fctx)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: list[_Acq] = []
            for item in stmt.items:
                self._walk_expr_tree(item.context_expr, ctx)
                site = self._resolve_lock(item.context_expr, ctx)
                if site is not None:
                    acq = _Acq(site=site,
                               expr=ast.dump(item.context_expr),
                               line=stmt.lineno)
                    self._note_acquire(acq, ctx)
                    ctx.held.append(acq)
                    acquired.append(acq)
            self._walk_body(stmt.body, ctx)
            for _ in acquired:
                ctx.held.pop()
            return
        # generic statement: expressions at THIS level, then child
        # statement bodies (so accesses/calls are classified against the
        # held-lock context actually in force where they appear)
        for field in ast.iter_fields(stmt):
            value = field[1]
            values = value if isinstance(value, list) else [value]
            for v in values:
                if isinstance(v, ast.stmt):
                    self._walk_stmt(v, ctx)
                elif isinstance(v, ast.expr):
                    self._walk_expr_tree(v, ctx)
                elif isinstance(v, (ast.excepthandler, ast.match_case)):
                    for sub in getattr(v, "body", []):
                        self._walk_stmt(sub, ctx)
        self._note_accesses(stmt, ctx)

    def _walk_expr_tree(self, expr: ast.expr | None, ctx: "_Ctx") -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._note_call(node, ctx)

    # -- lock resolution ----------------------------------------------

    def _annotation_types(self, fn: ast.FunctionDef |
                          ast.AsyncFunctionDef) -> dict[str, str]:
        """Param name -> dotted class for simple class annotations, so
        ``with cache._lock:`` resolves through ``cache: SessionCache``."""
        out: dict[str, str] = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) + list(
            fn.args.kwonlyargs
        )
        for a in args:
            ann = a.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                try:
                    ann = ast.parse(ann.value, mode="eval").body
                except SyntaxError:
                    continue
            if isinstance(ann, ast.Name):
                out[a.arg] = self.aliases.get(
                    ann.id, f"{self.mod}.{ann.id}"
                )
            elif isinstance(ann, ast.Attribute):
                dotted = common.resolve_dotted(ann, self.aliases)
                if dotted:
                    out[a.arg] = dotted
        return out

    def _resolve_lock(self, expr: ast.expr, ctx: "_Ctx") -> str | None:
        decls = self.scan.decls
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and ctx.cls:
                    site = f"{self.mod}.{ctx.cls}.{expr.attr}"
                    if site in decls:
                        return site
                typed = ctx.var_types.get(base.id)
                if typed:
                    site = f"{typed}.{expr.attr}"
                    if site in decls:
                        return site
            dotted = common.resolve_dotted(expr, self.aliases)
            if dotted and dotted in decls:
                return dotted
            return None
        if isinstance(expr, ast.Name):
            site = f"{self.mod}.{expr.id}"
            if site in decls:
                return site
            dotted = self.aliases.get(expr.id)
            if dotted and dotted in decls:
                return dotted
        return None

    def _note_acquire(self, acq: _Acq, ctx: "_Ctx") -> None:
        if ctx.func is not None:
            ctx.func.acquires.append(acq)
        for outer in ctx.held:
            key = (outer.site, acq.site)
            self.scan.edges.setdefault(key, (self.rel, acq.line))

    # -- R4 + call graph -----------------------------------------------

    def _note_call(self, call: ast.Call, ctx: "_Ctx") -> None:
        kind = self._blocking_kind(call, ctx)
        if kind is not None and ctx.func is not None:
            ctx.func.blocking.append((kind, call.lineno))
        if kind is not None and ctx.held:
            self._held_across(list(ctx.held), kind, call.lineno, direct=True)
        if ctx.func is None:
            return
        targets, attr = self._call_targets(call, ctx)
        ctx.func.calls.append((tuple(ctx.held), call, targets, attr))

    def _held_across(self, held: list[_Acq], kind: str, line: int,
                     direct: bool, via: str = "") -> None:
        if common.pragma(self.lines, line, "lock-held-ok") is not None:
            return
        for acq in held:
            decl = self.scan.decls[acq.site]
            if decl.io_ok and kind.startswith("socket "):
                continue
            suffix = f" (via {via})" if via else ""
            self.scan.finding(
                self.rel, line,
                f"lock '{acq.site}' held across {kind}{suffix} — release "
                "it first, or sanction with '# lock-held-ok: <why>'",
            )

    def _blocking_kind(self, call: ast.Call, ctx: "_Ctx") -> str | None:
        dotted = common.resolve_dotted(call.func, self.aliases)
        if dotted == "time.sleep":
            return "time.sleep"
        if dotted and ".plans.run_" in dotted:
            return f"device dispatch (plans.{dotted.rsplit('.', 1)[-1]})"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = call.func.value
        if attr in _SOCKET_OPS:
            return f"socket {attr}"
        if attr == "join":
            if isinstance(recv, (ast.Constant, ast.JoinedStr)):
                return None  # str.join
            if dotted and dotted.startswith("os.path"):
                return None
            return "thread join"
        if attr == "wait":
            # cond.wait() inside ``with cond:`` releases its own lock —
            # the sanctioned pattern.  wait on a DIFFERENT primitive
            # while holding a lock is the lost-wakeup stall.
            dump = ast.dump(call.func.value)
            if any(a.expr == dump for a in ctx.held):
                return None
            return "wait on a different primitive"
        return None

    def _call_targets(self, call: ast.Call,
                      ctx: "_Ctx") -> tuple[list[str], str | None]:
        """Exact targets ("mod:qual") plus the bare attr name for the
        R2 name fallback."""
        fn = call.func
        if isinstance(fn, ast.Name):
            dotted = self.aliases.get(fn.id)
            if dotted:
                mod, _, name = dotted.rpartition(".")
                return [f"{mod}:{name}"], None
            return [f"{self.mod}:{fn.id}"], None
        if not isinstance(fn, ast.Attribute):
            return [], None
        attr = fn.attr
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self" and ctx.cls:
                return [f"{self.mod}:{ctx.cls}.{attr}"], None
            typed = ctx.var_types.get(base.id)
            if typed:
                mod, _, cls = typed.rpartition(".")
                return [f"{mod}:{cls}.{attr}"], attr
        dotted = common.resolve_dotted(fn, self.aliases)
        if dotted:
            mod, _, name = dotted.rpartition(".")
            # Module-anchored call (``json.load(f)``): the target is
            # exact, so never fall back to matching bare method names
            # against the whole repo (that is how ``json.load`` would
            # impersonate ``PirRegistry.load``).
            root: ast.expr = base
            while isinstance(root, ast.Attribute):
                root = root.value
            exact = isinstance(root, ast.Name) and root.id in self.aliases
            return [f"{mod}:{name}"], None if exact else attr
        return [], attr

    # -- R3: guarded fields --------------------------------------------

    def _note_accesses(self, stmt: ast.stmt, ctx: "_Ctx") -> None:
        """Field/global accesses in one statement (expressions already
        walked for calls; here we classify reads/writes)."""
        if ctx.func is None:
            return  # module-level statements are construction
        init = ctx.fname in ("__init__", "__post_init__")
        guarded_cls = (
            any(_class_of(a.site) == f"{self.mod}.{ctx.cls}"
                for a in ctx.held)
            or (ctx.fname or "").endswith("_locked")
        )
        guarded_mod = (
            any(a.site in self.scan.decls
                and _class_of(a.site) == "" and a.site.startswith(self.mod)
                for a in ctx.held)
            or bool(ctx.held)
            or (ctx.fname or "").endswith("_locked")
        )
        lock_name = ctx.held[-1].site if ctx.held else None
        writes, reads = _accesses_in(stmt)
        for node, is_write in writes + reads:
            if isinstance(node, ast.Attribute):
                if not (isinstance(node.value, ast.Name)
                        and node.value.id == "self" and ctx.cls):
                    continue
                attr = node.attr
                site = f"{self.mod}.{ctx.cls}.{attr}"
                if site in self.scan.decls or attr.startswith("__"):
                    continue
                if init:
                    continue
                rec = self.fields.setdefault(ctx.cls, {}).setdefault(
                    attr, []
                )
                rec.append((is_write, guarded_cls, lock_name, node.lineno))
            elif isinstance(node, ast.Name):
                name = node.id
                if name not in self.module_names:
                    continue
                if f"{self.mod}.{name}" in self.scan.decls:
                    continue
                rec = self.globals_.setdefault(name, [])
                rec.append((is_write, guarded_mod, lock_name, node.lineno))

    def _report_fields(self) -> None:
        for cls, fields in self.fields.items():
            for attr, accesses in fields.items():
                self._report_one(f"{cls}.{attr}", accesses)
        for name, accesses in self.globals_.items():
            # a global only read in functions is config, not shared
            # mutable state — require a guarded WRITE to arm the rule
            self._report_one(name, accesses)

    def _report_one(self, label: str, accesses: list) -> None:
        guarded_writes = [a for a in accesses if a[0] and a[1]]
        if not guarded_writes:
            return
        lock = next((a[2] for a in guarded_writes if a[2]), "its lock")
        for is_write, guarded, _, line in accesses:
            if guarded:
                continue
            if common.pragma(self.lines, line, "lock-free-ok") is not None:
                continue
            what = "written" if is_write else "read"
            self.scan.finding(
                self.rel, line,
                f"'{label}' is written under {lock} but {what} lock-free "
                "here — take the lock, or sanction with "
                "'# lock-free-ok: <why>'",
            )


@dataclasses.dataclass
class _Ctx:
    cls: str | None
    func: _FuncInfo | None
    fname: str | None = None
    held: list[_Acq] = dataclasses.field(default_factory=list)
    var_types: dict[str, str] = dataclasses.field(default_factory=dict)


def _stmt_exprs(stmt: ast.stmt) -> tuple[list[ast.expr], list[ast.expr]]:
    """(write-target exprs, read exprs) at THIS statement's own level —
    never descends into nested statements, whose held-lock context
    differs (the walk classifies those when it reaches them)."""
    if isinstance(stmt, ast.Assign):
        return list(stmt.targets), [stmt.value]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.target], [stmt.value]
    if isinstance(stmt, ast.AnnAssign):
        return ([stmt.target], [stmt.value]) if stmt.value else ([], [])
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets), []
    if isinstance(stmt, ast.Expr):
        return [], [stmt.value]
    if isinstance(stmt, ast.Return):
        return [], [stmt.value] if stmt.value else []
    if isinstance(stmt, ast.Raise):
        return [], [e for e in (stmt.exc, stmt.cause) if e]
    if isinstance(stmt, ast.Assert):
        return [], [e for e in (stmt.test, stmt.msg) if e]
    if isinstance(stmt, (ast.If, ast.While)):
        return [], [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target], [stmt.iter]
    return [], []


def _accesses_in(stmt: ast.stmt) -> tuple[list, list]:
    """(writes, reads) of Attribute/Name nodes in one statement's own
    expressions.  Writes: assignment/loop targets, augmented targets,
    subscript-store bases.  Reads: Load-context accesses (including a
    mutating method's receiver — mutation through a read still needs
    the lock)."""
    writes: list = []
    reads: list = []
    write_roots: set[int] = set()
    target_exprs, read_exprs = _stmt_exprs(stmt)
    for t in target_exprs:
        base: ast.expr = t
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, (ast.Attribute, ast.Name)):
            writes.append((base, True))
            write_roots.add(id(base))
    for top in target_exprs + read_exprs:
        for node in ast.walk(top):
            if isinstance(node, (ast.Attribute, ast.Name)):
                if id(node) in write_roots:
                    continue
                ctx = getattr(node, "ctx", None)
                if isinstance(ctx, (ast.Store, ast.Del)):
                    writes.append((node, True))
                elif isinstance(ctx, ast.Load):
                    reads.append((node, False))
    return writes, reads


# ---------------------------------------------------------------------------
# Interprocedural closure + order checks
# ---------------------------------------------------------------------------


def _transitive(scan: _Scan) -> tuple[dict[str, set[str]],
                                      dict[str, list[tuple[str, int]]]]:
    """Fixpoint over the EXACT call graph: for every function, the set
    of declared locks it may acquire and the blocking ops it may reach."""
    acq: dict[str, set[str]] = {}
    blk: dict[str, list[tuple[str, int]]] = {}
    for key, info in scan.funcs.items():
        acq[key] = {a.site for a in info.acquires}
        blk[key] = list(info.blocking)
    changed = True
    while changed:
        changed = False
        for key, info in scan.funcs.items():
            for _, _, targets, _ in info.calls:
                for t in targets:
                    if t not in scan.funcs or t == key:
                        continue
                    if not acq[t] <= acq[key]:
                        acq[key] |= acq[t]
                        changed = True
                    for b in blk[t]:
                        if b not in blk[key]:
                            blk[key].append(b)
                            changed = True
    return acq, blk


def _order_and_blocking(scan: _Scan,
                        visitors: dict[str, _FileVisitor]) -> None:
    acq_trans, blk_trans = _transitive(scan)
    for key, info in scan.funcs.items():
        vis = visitors[info.rel]
        for held, call, targets, attr in info.calls:
            if not held:
                continue
            inner: set[str] = set()
            resolved = [t for t in targets if t in scan.funcs]
            for t in resolved:
                inner |= acq_trans[t]
                for kind, _ in blk_trans[t]:
                    label = t.split(":", 1)[1]
                    vis._held_across(list(held), kind, call.lineno,
                                     direct=False, via=label)
            if not resolved and attr and attr not in _FALLBACK_DENY:
                # R2 name fallback: every scanned class method with this
                # name that DIRECTLY acquires declared locks
                for cand in scan.by_method.get(attr, ()):
                    cinfo = scan.funcs[cand]
                    inner |= {a.site for a in cinfo.acquires}
            for outer in held:
                for site in inner:
                    key2 = (outer.site, site)
                    scan.edges.setdefault(key2, (info.rel, call.lineno))


def _check_edges(scan: _Scan) -> None:
    decls = scan.decls
    for (outer, inner), (rel, line) in sorted(scan.edges.items()):
        do, di = decls[outer], decls[inner]
        if do.kind == "event" or di.kind == "event":
            continue
        if outer == inner:
            if do.kind not in ("rlock", "cond"):
                scan.finding(
                    rel, line,
                    f"non-reentrant lock '{outer}' re-acquired while "
                    "already held — self-deadlock",
                )
            continue
        if do.group and do.group == di.group:
            continue  # shared re-entrant family
        if di.rank <= do.rank:
            scan.finding(
                rel, line,
                f"acquisition-order inversion: '{inner}' (rank {di.rank}) "
                f"acquired while holding '{outer}' (rank {do.rank}) — "
                "nested acquisition must increase rank "
                "(analysis/concurrency/registry.py)",
            )
    _check_cycles(scan)


def _check_cycles(scan: _Scan) -> None:
    graph: dict[str, set[str]] = {}
    for (outer, inner) in scan.edges:
        if outer == inner:
            continue
        do, di = scan.decls[outer], scan.decls[inner]
        if do.kind == "event" or di.kind == "event":
            continue
        if do.group and do.group == di.group:
            continue
        graph.setdefault(outer, set()).add(inner)
    seen: set[str] = set()
    reported: set[frozenset] = set()

    def dfs(node: str, stack: list[str], on_stack: set[str]) -> None:
        seen.add(node)
        stack.append(node)
        on_stack.add(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cycle = stack[stack.index(nxt):] + [nxt]
                cid = frozenset(cycle)
                if cid not in reported:
                    reported.add(cid)
                    rel, line = scan.edges[(node, nxt)]
                    scan.finding(
                        rel, line,
                        "lock-order cycle: " + " -> ".join(cycle),
                    )
            elif nxt not in seen:
                dfs(nxt, stack, on_stack)
        stack.pop()
        on_stack.discard(node)

    for node in sorted(graph):
        if node not in seen:
            dfs(node, [], set())


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def run(root: str | None = None,
        files: list[str] | None = None) -> list[common.Finding]:
    root = root or common.repo_root()
    whole_tree = files is None
    if files is None:
        files = [
            rel for rel in common.iter_py_files(root)
            if common.in_scope(rel, _SCOPE)
        ]
    else:
        files = [rel for rel in files if common.in_scope(rel, _SCOPE)]
    decls = dict(LOCKS)
    decls.update(FIXTURE_LOCKS)
    scan = _Scan(decls)
    visitors: dict[str, _FileVisitor] = {}
    for rel in files:
        try:
            tree, lines = common.parse_file(root, rel)
        except SyntaxError as e:
            scan.finding(rel, e.lineno or 1, f"syntax error: {e.msg}")
            continue
        vis = _FileVisitor(scan, rel, tree, lines)
        visitors[rel] = vis
        vis.run()
    _order_and_blocking(scan, visitors)
    _check_edges(scan)
    if whole_tree:
        for site in sorted(set(LOCKS) - scan.created):
            scan.finding(
                "dpf_tpu/analysis/concurrency/registry.py", 1,
                f"stale lock declaration: '{site}' has no creation site "
                "in the tree — remove or fix the registry entry",
            )
    return scan.findings
