"""The lock registry: every threading primitive the production tree
creates, declared with an owner and an acquisition-order rank.

Why a central table and not per-site pragmas: lock ORDER is a global
property — two locks deadlock because of how *different* modules nest
them, so the ranking has to live where both declarations are visible at
once.  The ``lock-discipline`` pass cross-checks this table against the
tree in both directions: a primitive creation with no declaration is a
finding (someone added a lock without ranking it), and a declaration
whose creation site no longer exists is a finding too (the table cannot
rot).

Site naming: ``<dotted module>.<Class>.<attr>`` for instance primitives
(``self._lock = threading.Lock()`` inside a class) and
``<dotted module>.<NAME>`` for module globals.  The pass derives the
same names from the AST, so the key IS the match.

Ranking discipline (docs/DESIGN.md section 21): nested acquisition must
strictly increase rank, except inside one ``group`` — a group names a
family that shares ONE re-entrant lock object at serving time (the
components take a ``lock=`` parameter and the serving state passes its
stats RLock to all of them), so nesting inside the family is re-entry,
not a second lock.  Events carry rank 0: they are signalled, never
held, so they take no part in ordering (but still must be declared —
an undeclared Event is usually a missed shutdown path).

``io_ok`` marks the write-serialization locks whose entire PURPOSE is
to be held across a gathered socket send (one request's frames must hit
the wire atomically between multiplexed streams).  The held-across-
blocking rule skips socket sends under an ``io_ok`` lock and still
flags everything else (a device dispatch or a sleep under a write lock
stalls every stream on the connection).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One declared primitive: who owns it, what it is, where it sits
    in the acquisition order, and what it is sanctioned to do."""

    owner: str  # subsystem responsible (matches the module's layer)
    kind: str  # "lock" | "rlock" | "cond" | "event"
    rank: int  # nested acquisition must strictly increase rank
    group: str = ""  # same-group nesting allowed (shared re-entrant family)
    io_ok: bool = False  # may be held across socket sends (write serialization)
    doc: str = ""


LOCKS: dict[str, LockDecl] = {
    # -- serving state singleton (rank 5: constructed first, builds
    # components whose constructors touch rank-30 module locks) --------
    "dpf_tpu.serving.handlers._STATE_LOCK": LockDecl(
        owner="serving", kind="lock", rank=5,
        doc="per-process _ServingState singleton construction",
    ),
    # -- the shared stats family (rank 10): ONE RLock at serving time.
    # handlers._ServingState passes stats_lock into the batcher, key
    # cache, breaker, metrics hub, and HH session cache so /v1/stats
    # snapshots are consistent across all of them; standalone instances
    # (unit tests) get their own object, same rank. ---------------------
    "dpf_tpu.serving.handlers._ServingState.stats_lock": LockDecl(
        owner="serving", kind="rlock", rank=10, group="stats",
        doc="the shared serving stats RLock (the group's one real object)",
    ),
    "dpf_tpu.serving.batcher.Batcher._lock": LockDecl(
        owner="serving", kind="lock", rank=10, group="stats",
        doc="lane queues + counters; the stats RLock when shared",
    ),
    "dpf_tpu.serving.breaker.CircuitBreaker._lock": LockDecl(
        owner="serving", kind="lock", rank=10, group="stats",
        doc="breaker state machine; the stats RLock when shared",
    ),
    "dpf_tpu.serving.keycache.KeyCache._lock": LockDecl(
        owner="serving", kind="lock", rank=10, group="stats",
        doc="repack LRU; builds run OUTSIDE it (misses overlap)",
    ),
    "dpf_tpu.obs.metrics.MetricsHub._lock": LockDecl(
        owner="obs", kind="rlock", rank=10, group="stats",
        doc="histogram/counter registry; the stats RLock when shared",
    ),
    "dpf_tpu.apps.hh_state.SessionCache._lock": LockDecl(
        owner="apps", kind="rlock", rank=10, group="stats",
        doc="descent-session registry; the stats RLock when shared",
    ),
    # -- module/loader locks reachable from under the stats lock
    # (stats_snapshot fans out to their stats() surfaces) ---------------
    "dpf_tpu.serving.faults._PLAN_LOCK": LockDecl(
        owner="serving", kind="lock", rank=20,
        doc="install/clear of the process fault plan",
    ),
    "dpf_tpu.apps.pir_store._REGISTRY_LOCK": LockDecl(
        owner="apps", kind="lock", rank=20,
        doc="per-process PirRegistry singleton construction",
    ),
    "dpf_tpu.core.plans.PlanCache._lock": LockDecl(
        owner="core", kind="lock", rank=30,
        doc="plan-key table; compiles happen outside it",
    ),
    "dpf_tpu.obs.trace.FlightRecorder._lock": LockDecl(
        owner="obs", kind="lock", rank=30,
        doc="flight-recorder ring buffer",
    ),
    "dpf_tpu.obs.profile._LOCK": LockDecl(
        owner="obs", kind="lock", rank=30,
        doc="one profiler capture at a time (admin path)",
    ),
    "dpf_tpu.parallel.serving_mesh._LOCK": LockDecl(
        owner="parallel", kind="lock", rank=30,
        doc="serving-mesh resolution cache",
    ),
    "dpf_tpu.tune.tuned._LOCK": LockDecl(
        owner="tune", kind="lock", rank=30,
        doc="TUNED.json load/validate cache (file I/O on first touch)",
    ),
    "dpf_tpu.serving.faults.FaultPlan._lock": LockDecl(
        owner="serving", kind="lock", rank=30,
        doc="fault-plan counters; injected sleeps happen outside it",
    ),
    "dpf_tpu.apps.pir_store.PirRegistry._lock": LockDecl(
        owner="apps", kind="lock", rank=30,
        doc="name -> PirDB table",
    ),
    "dpf_tpu.backends.cpu_native._lock": LockDecl(
        owner="backends", kind="lock", rank=30,
        doc="one-time native library build/load",
    ),
    "dpf_tpu.apps.pir_store.PirDB._lock": LockDecl(
        owner="apps", kind="lock", rank=40,
        doc="per-DB counters + server table; HBM placement outside it",
    ),
    "dpf_tpu.parallel.sharding._ShardedJits._lock": LockDecl(
        owner="parallel", kind="lock", rank=40,
        doc="sharded-jit registry (reached via plans trace_count)",
    ),
    # -- wire2: per-connection / per-client primitives (rank 50+; never
    # held while calling into serving, which runs lock-free from the
    # worker pool) ------------------------------------------------------
    "dpf_tpu.serving.wire2._Conn._lock": LockDecl(
        owner="wire2", kind="lock", rank=50,
        doc="server-side stream table + worker-pool accounting",
    ),
    "dpf_tpu.serving.wire2.Wire2Server._lock": LockDecl(
        owner="wire2", kind="lock", rank=50,
        doc="live-connection set",
    ),
    "dpf_tpu.serving.wire2.Wire2Client._slock": LockDecl(
        owner="wire2", kind="lock", rank=50,
        doc="client stream table + sid allocator",
    ),
    "dpf_tpu.serving.wire2._Conn._wlock": LockDecl(
        owner="wire2", kind="lock", rank=55, io_ok=True,
        doc="server write side: one reply's frames go out atomically",
    ),
    "dpf_tpu.serving.wire2.Wire2Client._wlock": LockDecl(
        owner="wire2", kind="lock", rank=55, io_ok=True,
        doc="client write side: one request's frames go out atomically",
    ),
    "dpf_tpu.serving.wire2._BufPool._lock": LockDecl(
        owner="wire2", kind="lock", rank=60,
        doc="pooled receive buffers",
    ),
    "dpf_tpu.serving.wire2._StreamBody._cond": LockDecl(
        owner="wire2", kind="cond", rank=60,
        doc="body fill/consume handshake; recv happens OUTSIDE it",
    ),
    # -- events (rank 0: signalled, never held) -------------------------
    "dpf_tpu.serving.batcher._Req.done": LockDecl(
        owner="serving", kind="event", rank=0,
        doc="per-request completion latch (leader -> follower)",
    ),
    "dpf_tpu.serving.wire2._Pending.event": LockDecl(
        owner="wire2", kind="event", rank=0,
        doc="client reply-complete latch (reader -> caller)",
    ),
}


# Declarations for the seeded-violation fixture
# (dpf_tpu/analysis/fixtures/bad_locks.py).  Real scans never see the
# fixtures directory, so these are reachable only when the test harness
# points the pass at a fixture file explicitly.  ``_UNDECLARED`` in the
# fixture is deliberately missing here — that omission IS the seeded
# undeclared-creation violation.
FIXTURE_LOCKS: dict[str, LockDecl] = {
    "dpf_tpu.analysis.fixtures.bad_locks.BadOrder._a": LockDecl(
        owner="fixture", kind="lock", rank=10,
        doc="seeded: outer lock of the inversion pair",
    ),
    "dpf_tpu.analysis.fixtures.bad_locks.BadOrder._b": LockDecl(
        owner="fixture", kind="lock", rank=20,
        doc="seeded: inner lock of the inversion pair",
    ),
    "dpf_tpu.analysis.fixtures.bad_locks.TornCounter._lock": LockDecl(
        owner="fixture", kind="lock", rank=10,
        doc="seeded: guards bump() but not read()",
    ),
    "dpf_tpu.analysis.fixtures.bad_locks.HeldAcrossDispatch._lock": LockDecl(
        owner="fixture", kind="lock", rank=10,
        doc="seeded: held across plans.run_points",
    ),
    "dpf_tpu.analysis.fixtures.bad_locks.HeldAcrossRecv._lock": LockDecl(
        owner="fixture", kind="lock", rank=10,
        doc="seeded: held across sock.recv",
    ),
}
