"""Concurrency discipline for the serving plane: the ``lock-discipline``
static pass plus the deterministic interleaving harness.

The serving plane is deeply threaded — batcher lanes, the breaker state
machine, wire2's per-connection frame readers and worker pool, the HH
session cache, the plan cache, and the shared stats lock — and a race
there is a correctness bug that no kernel differential can catch.  This
package is the discipline layer:

  ``registry``   the whole-repo lock registry: every ``Lock`` / ``RLock``
                 / ``Condition`` / ``Event`` the production tree creates,
                 declared with an owner, a kind, and an acquisition-order
                 rank (docs/DESIGN.md section 21 documents the ranking).
  ``lock_pass``  the static verifier (PASSES entry ``lock-discipline``):
                 undeclared primitive creations, acquisition-order
                 inversions/cycles over the AST ``with``-nesting graph,
                 guarded-field inference (written under a lock somewhere,
                 touched lock-free elsewhere), and the held-across-
                 blocking check (no lock across a device dispatch, socket
                 I/O, ``time.sleep``, or a thread join).
  ``sched``      the deterministic interleaving harness: a seeded
                 round-robin scheduler that serializes 2-4 scenario
                 threads at lock boundaries (``sys.setprofile`` C-call
                 events) and seeded line-granularity preemption points
                 (``sys.settrace``), so a deadlock or torn read found in
                 CI replays byte-for-byte from its seed.
"""

from __future__ import annotations

from .registry import FIXTURE_LOCKS, LOCKS, LockDecl
from .sched import DeadlockDetected, DetScheduler, stress_switch_interval

__all__ = [
    "FIXTURE_LOCKS",
    "LOCKS",
    "LockDecl",
    "DeadlockDetected",
    "DetScheduler",
    "stress_switch_interval",
]
