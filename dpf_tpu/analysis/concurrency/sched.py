"""Deterministic interleaving harness: replay thread schedules from a seed.

The static half of this package (``lock_pass``) proves properties over
the AST; this module is the dynamic half — it takes the 2-4 thread
shapes the serving plane actually runs (batcher lanes, breaker trips,
session-cache eviction vs eval, wire2 stream open/close) and drives
them through a SEEDED scheduler so that a deadlock or torn read found
once reproduces byte-for-byte in CI forever.

How it works — token passing, not time slicing:

  * Exactly one scenario thread owns the *token* (is granted) at any
    moment; everyone else waits on a grant Event or is blocked inside a
    C-level acquire.  Because scenario Python only executes under the
    token, the whole run is a total order, and the seeded RNG that
    picks each grant is the only choice point: seed -> schedule ->
    trace is a pure function.
  * Lock traffic is observed two ways, because CPython 3.10 shows it
    two ways.  Direct C-method calls (``lock.acquire()``,
    ``lock.release()``, the ``__exit__`` a ``with`` block runs, and
    everything ``threading.Condition``/``Event`` do internally) raise
    ``c_call``/``c_return`` profile events a per-thread
    ``sys.setprofile`` hook intercepts.  But the ``SETUP_WITH`` opcode
    calls ``__enter__`` straight from C with NO profile event — so for
    files named in ``trace_files`` the harness pre-parses every ``with``
    statement, and a ``sys.settrace`` line hook evaluates the context
    expression against the live frame to learn which lock is about to
    be acquired ("pending").  Any later event from that thread proves
    the acquire completed and converts pending into held.
  * At an acquire the thread logs what it wants, drops to "limbo", and
    falls into the C acquire (which may block).  When the acquire is
    known to have completed the thread goes "ready" and waits for the
    next grant.  Releases update the ledger at ``c_call`` time —
    BEFORE the C release wakes any waiter — so the trace order never
    races the kernel's wakeup order.
  * The caller's thread runs the scheduler loop: whenever no thread is
    running and none is about to wake ("transit": wants a lock the
    ledger says is free), it picks the next thread from the ready set
    with ``random.Random(seed)``.
  * Optional line-granularity preemption (``preempt_every=(lo, hi)``):
    the line hook yields every k-th line inside ``trace_files``, k
    drawn from the same RNG — this is what widens the read/write
    window of a torn counter so a seed can expose it.

Deadlock is a *state* the loop recognizes, not a timeout: no thread
running, ready, or in transit, and the wait-for edges (thread -> holder
of the lock it wants) contain a cycle.  The loop appends the cycle to
the trace and raises :class:`DeadlockDetected`; the C-blocked threads
are daemons and are abandoned.

Limits, by design: a ``with`` block in a file NOT listed in
``trace_files`` is invisible at entry (list the component's source file
to see it); a thread blocked on something the ledger cannot see (an
``Event.wait`` serviced by a non-scenario thread, a socket read)
eventually gets marked "parked" after a settle window and re-admitted
when it wakes — component scenarios that talk to real server threads
stay correct but their park/wake timing is wall-clock, so only
pure-lock fixtures (no external wakers) are byte-for-byte
deterministic.  Timed acquires are detected by a post-return ledger
check and never corrupt the ledger.
"""

from __future__ import annotations

import ast
import contextlib
import dis
import os
import random
import sys
import threading
import time
from typing import Any, Callable, Iterator

_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())
_LOCK_TYPES: tuple[type, ...] = (_LOCK_TYPE, _RLOCK_TYPE)

# C-method names on _thread lock types that move lock state.  The
# ``_release_save`` / ``_acquire_restore`` pair is Condition.wait's
# full-release / re-acquire of an RLock regardless of count.
_ACQ_NAMES = frozenset({"acquire", "acquire_lock", "__enter__", "_acquire_restore"})
_REL_NAMES = frozenset({"release", "release_lock", "__exit__", "_release_save"})


class DeadlockDetected(RuntimeError):
    """Raised by :meth:`DetScheduler.run` when the wait-for graph has a
    cycle.  ``trace`` is the full schedule that led there (the last
    line is the cycle); ``cycle`` is the thread names in cycle order."""

    def __init__(self, message: str, trace: list[str], cycle: list[str]):
        super().__init__(message)
        self.trace = list(trace)
        self.cycle = list(cycle)


def _pure_load(node: ast.expr) -> bool:
    """True for a side-effect-free Name/Attribute chain the line hook
    may safely re-evaluate against the frame."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name)


def _with_map(path: str) -> dict[int, list[Any]]:
    """lineno -> compiled context expressions for every ``with`` whose
    items are pure loads (the shape ``with self._lock:`` compiles to —
    the one acquire CPython hides from profile hooks)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out: dict[int, list[Any]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        codes = []
        for item in node.items:
            expr = item.context_expr
            if _pure_load(expr):
                codes.append(
                    compile(ast.Expression(expr), path, "eval")
                )
        if codes:
            out[node.lineno] = codes
    return out


class DetScheduler:
    """Seeded deterministic scheduler for 2-4 thread lock scenarios.

    Usage::

        sched = DetScheduler(seed=7, trace_files=(fixture.__file__,))
        sched.spawn(lambda: worker_a(obj), name="a")
        sched.spawn(lambda: worker_b(obj), name="b")
        trace = sched.run()          # list[str]; raises DeadlockDetected

    One instance drives one run; build a fresh instance (same seed) to
    replay.  List every source file whose ``with <lock>:`` blocks the
    scenario should observe in ``trace_files``.  ``name_lock`` attaches
    stable display names to lock objects before ``run`` (anonymous
    locks are named L0, L1, ... in first-touch order, which is itself
    deterministic)."""

    def __init__(
        self,
        seed: int,
        *,
        trace_files: tuple[str, ...] = (),
        preempt_every: tuple[int, int] | None = None,
        settle_s: float = 0.5,
        hang_s: float = 20.0,
        deadline_s: float = 120.0,
    ):
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._ctl = threading.Event()
        self._fns: list[tuple[str, Callable[[], Any]]] = []
        self._threads: list[threading.Thread] = []
        self._grants: list[threading.Event] = []
        self._status: list[str] = []  # ready|running|limbo|parked|done
        self._wants: list[int | None] = []
        self._pending: list[list[int] | None] = []  # with-entry acquires in flight
        self._countdown: list[int | None] = []
        self._locks: dict[int, Any] = {}  # key -> lock obj (keepalive: ids stay unique)
        self._lock_ids: dict[int, int] = {}  # id(obj) -> key
        self._names: dict[int, str] = {}  # key -> display name
        self._holders: dict[int, tuple[int, int]] = {}  # key -> (tid, count)
        self._trace: list[str] = []
        self._errors: dict[int, BaseException] = {}
        self._trace_files = {os.path.abspath(p) for p in trace_files}
        self._with_maps = {p: _with_map(p) for p in sorted(self._trace_files)}
        self._file_key: dict[str, str | None] = {}
        self._entry_offs: dict[Any, frozenset[int]] = {}  # code -> with-entry f_lasti
        self._preempt_every = preempt_every
        self._settle_s = settle_s
        self._hang_s = hang_s
        self._deadline_s = deadline_s
        self._started = False

    # ---- scenario assembly -------------------------------------------

    def spawn(self, fn: Callable[[], Any], name: str | None = None) -> int:
        """Register a scenario thread; returns its tid.  Threads start
        only when :meth:`run` is called."""
        if self._started:
            raise RuntimeError("scheduler already ran")
        tid = len(self._fns)
        self._fns.append((name or f"t{tid}", fn))
        self._grants.append(threading.Event())
        self._status.append("ready")
        self._wants.append(None)
        self._pending.append(None)
        self._countdown.append(None)
        return tid

    def name_lock(self, obj: Any, name: str) -> None:
        """Pre-register ``obj`` under a stable display name for traces."""
        with self._mu:
            key = self._key_locked(obj)
            self._names[key] = name

    # ---- bookkeeping (callers hold self._mu) -------------------------

    def _key_locked(self, obj: Any) -> int:
        i = id(obj)
        key = self._lock_ids.get(i)
        if key is None:
            key = len(self._locks)
            self._lock_ids[i] = key
            self._locks[key] = obj
            self._names[key] = f"L{key}"
        return key

    def _tn(self, tid: int) -> str:
        return self._fns[tid][0]

    def _hold_locked(self, tid: int, key: int) -> None:
        held = self._holders.get(key)
        if held is not None and held[0] == tid:
            self._holders[key] = (tid, held[1] + 1)
        else:
            self._holders[key] = (tid, 1)
        self._trace.append(f"{self._tn(tid)} acquired {self._names[key]}")

    def _transit_locked(self, tid: int) -> bool:
        """True if the lock ``tid`` is blocked on should wake it without
        any further scheduling (free per the ledger, or re-entrant
        self-acquisition)."""
        key = self._wants[tid]
        if key is None:
            return True
        held = self._holders.get(key)
        if held is None:
            return True
        return held[0] == tid and isinstance(self._locks[key], _RLOCK_TYPE)

    def _got_lock_locked(self, tid: int, obj: Any, key: int) -> bool:
        """Did this thread's just-returned acquire actually succeed?
        (A timed acquire can return empty-handed.)"""
        if isinstance(obj, _RLOCK_TYPE):
            try:
                return bool(obj._is_owned())
            except AttributeError:  # pragma: no cover - C RLock always has it
                return True
        held = self._holders.get(key)
        # Free per the ledger -> we took it.  Still charged to someone
        # (possibly ourselves: a Condition waiter re-lock that timed
        # out) -> we came back empty.
        return held is None

    # ---- worker side -------------------------------------------------

    def _pause(self, tid: int) -> None:
        g = self._grants[tid]
        g.wait()
        g.clear()

    def _resolve_pending(self, tid: int) -> None:
        """A new event from ``tid`` proves its with-entry acquire(s)
        completed: move pending to held and take the post-acquire
        grant point."""
        if self._pending[tid] is None:
            return
        with self._mu:
            keys = self._pending[tid]
            self._pending[tid] = None
            if keys:
                for key in keys:
                    self._hold_locked(tid, key)
            self._wants[tid] = None
            self._status[tid] = "ready"
            self._ctl.set()
        self._pause(tid)

    def _with_attempt(self, tid: int, frame: Any, codes: list[Any]) -> None:
        """Line hook is sitting on a ``with`` statement: learn which
        lock(s) it is about to acquire."""
        locks = []
        for code in codes:
            try:
                obj = eval(code, frame.f_globals, frame.f_locals)  # noqa: S307
            except Exception:  # noqa: BLE001 - stale map entry; not a lock
                continue
            if isinstance(obj, _LOCK_TYPES):
                locks.append(obj)
        if not locks:
            return
        with self._mu:
            keys = [self._key_locked(o) for o in locks]
            self._pending[tid] = keys
            # The interesting want is the first lock someone else holds.
            want = keys[0]
            for key in keys:
                held = self._holders.get(key)
                if held is not None and held[0] != tid:
                    want = key
                    break
            self._wants[tid] = want
            self._status[tid] = "limbo"
            self._trace.append(f"{self._tn(tid)} wants {self._names[want]}")
            self._ctl.set()
        # fall through into SETUP_WITH's C acquire; it may block

    def _acq_call(self, tid: int, obj: Any) -> None:
        with self._mu:
            key = self._key_locked(obj)
            self._wants[tid] = key
            self._status[tid] = "limbo"
            self._trace.append(f"{self._tn(tid)} wants {self._names[key]}")
            self._ctl.set()
        # fall through into the C acquire; it may block

    def _acq_return(self, tid: int, obj: Any) -> None:
        with self._mu:
            key = self._key_locked(obj)
            if self._got_lock_locked(tid, obj, key):
                self._hold_locked(tid, key)
            self._wants[tid] = None
            self._status[tid] = "ready"
            self._ctl.set()
        self._pause(tid)

    def _rel_call(self, tid: int, obj: Any, name: str) -> None:
        # Ledger updates happen BEFORE the C release executes, so a
        # blocked waiter can never log its wakeup ahead of this release.
        with self._mu:
            key = self._key_locked(obj)
            held = self._holders.get(key)
            if held is None:
                return
            htid, count = held
            full = (
                name == "_release_save"
                or count <= 1
                or not isinstance(obj, _RLOCK_TYPE)
            )
            if full:
                del self._holders[key]
                self._trace.append(f"{self._tn(tid)} released {self._names[key]}")
            else:
                self._holders[key] = (htid, count - 1)
            self._ctl.set()

    def _rel_return(self, tid: int) -> None:
        with self._mu:
            self._status[tid] = "ready"
            self._ctl.set()
        self._pause(tid)

    def _profiler(self, tid: int) -> Callable[[Any, str, Any], None]:
        def hook(frame: Any, event: str, arg: Any) -> None:
            # Any event proves forward progress past a pending with-entry.
            self._resolve_pending(tid)
            if event != "c_call" and event != "c_return":
                return
            name = getattr(arg, "__name__", None)
            if name in _ACQ_NAMES:
                obj = getattr(arg, "__self__", None)
                if isinstance(obj, _LOCK_TYPES):
                    if event == "c_call":
                        self._acq_call(tid, obj)
                    else:
                        self._acq_return(tid, obj)
            elif name in _REL_NAMES:
                obj = getattr(arg, "__self__", None)
                if isinstance(obj, _LOCK_TYPES):
                    if event == "c_call":
                        self._rel_call(tid, obj, name)
                    else:
                        self._rel_return(tid)

        return hook

    # A with-entry for the context exprs we track (pure Name/Attribute
    # loads) compiles to a straight chain of these ops ending in
    # SETUP_WITH.  Anything else between the event offset and the next
    # SETUP_WITH (the __exit__ call, a jump, a RERAISE) means the event
    # is NOT an entry.
    _ENTRY_CHAIN_OPS = frozenset(
        {
            "LOAD_FAST", "LOAD_ATTR", "LOAD_GLOBAL", "LOAD_NAME",
            "LOAD_DEREF", "LOAD_CLASSDEREF", "LOAD_CONST", "DUP_TOP",
            "NOP", "EXTENDED_ARG",
        }
    )

    def _with_entries(self, code: Any) -> frozenset[int]:
        """Offsets at which a 'line' event means execution is ENTERING a
        with statement (vs revisiting its line for the __exit__
        sequence).  Line events can land mid-run — the compiler
        duplicates a ``finally``/``except`` body's with statement and
        the exception path jumps straight to the copy — so this is
        every offset from which a pure load chain reaches the next
        SETUP_WITH, not just line-run starts."""
        cached = self._entry_offs.get(code)
        if cached is None:
            out = set()
            reaches = False  # scanning backwards: next-op reaches SETUP_WITH
            for ins in reversed(list(dis.get_instructions(code))):
                if ins.opname in ("SETUP_WITH", "BEFORE_WITH"):
                    reaches = True
                elif ins.opname not in self._ENTRY_CHAIN_OPS:
                    reaches = False
                if reaches:
                    out.add(ins.offset)
            cached = frozenset(out)
            self._entry_offs[code] = cached
        return cached

    def _preempt(self, tid: int, lineno: int) -> None:
        pause = False
        with self._mu:
            c = self._countdown[tid]
            if c is None:
                assert self._preempt_every is not None
                c = self._rng.randrange(*self._preempt_every)
            c -= 1
            if c > 0:
                self._countdown[tid] = c
            else:
                self._countdown[tid] = None
                self._status[tid] = "ready"
                self._trace.append(f"{self._tn(tid)} preempt :{lineno}")
                self._ctl.set()
                pause = True
        if pause:
            self._pause(tid)

    def _tracer(self, tid: int) -> Callable[..., Any]:
        def local_tracer(frame: Any, event: str, arg: Any) -> Any:
            self._resolve_pending(tid)
            if event == "line":
                fkey = self._file_key.get(frame.f_code.co_filename)
                if fkey is not None:
                    codes = self._with_maps[fkey].get(frame.f_lineno)
                    # The with-statement's LINE fires twice: at entry
                    # (SETUP_WITH) and again for the __exit__ sequence.
                    # Only the run that contains SETUP_WITH is an
                    # acquire attempt.
                    if codes is not None and frame.f_lasti in self._with_entries(
                        frame.f_code
                    ):
                        self._with_attempt(tid, frame, codes)
                        return local_tracer  # acquire is its own yield point
                if self._preempt_every is not None:
                    self._preempt(tid, frame.f_lineno)
            return local_tracer

        def global_tracer(frame: Any, event: str, arg: Any) -> Any:
            self._resolve_pending(tid)
            if event != "call":
                return None
            fname = frame.f_code.co_filename
            fkey = self._file_key.get(fname, "")
            if fkey == "":
                ap = os.path.abspath(fname)
                fkey = ap if ap in self._trace_files else None
                self._file_key[fname] = fkey
            return local_tracer if fkey is not None else None

        return global_tracer

    def _worker(self, tid: int, fn: Callable[[], Any]) -> None:
        self._pause(tid)  # first grant arrives before hooks exist
        sys.setprofile(self._profiler(tid))
        if self._trace_files:
            sys.settrace(self._tracer(tid))
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - reported via run()
            with self._mu:
                self._errors[tid] = e
        finally:
            sys.setprofile(None)
            sys.settrace(None)
            with self._mu:
                self._status[tid] = "done"
                self._trace.append(f"{self._tn(tid)} done")
                self._ctl.set()

    # ---- scheduler side (runs in the caller's thread) ----------------

    def _decide_locked(self) -> tuple[str, Any]:
        sts = self._status
        if all(s == "done" for s in sts):
            return ("done", None)
        if any(s == "running" for s in sts):
            return ("wait_run", None)
        transit = [
            t for t, s in enumerate(sts) if s == "limbo" and self._transit_locked(t)
        ]
        if transit:
            return ("wait_transit", transit)
        ready = [t for t, s in enumerate(sts) if s == "ready"]
        if ready:
            return ("grant", ready[self._rng.randrange(len(ready))])
        # Nobody runnable: limbo threads blocked on held locks, parked
        # threads awaiting external wakers.  Cycle -> deadlock verdict.
        edges: dict[int, int] = {}
        for t, s in enumerate(sts):
            if s != "limbo":
                continue
            key = self._wants[t]
            if key is None:
                continue
            held = self._holders.get(key)
            if held is not None and held[0] != t:
                edges[t] = held[0]
        cyc = _find_cycle(edges)
        if cyc:
            parts = []
            for t in cyc:
                key = self._wants[t]
                lname = self._names[key] if key is not None else "?"
                parts.append(
                    f"{self._tn(t)} waits {lname} held by {self._tn(edges[t])}"
                )
            return ("deadlock", ("deadlock: " + "; ".join(parts), cyc))
        return ("wait_hang", None)

    def run(self, *, raise_errors: bool = True) -> list[str]:
        """Drive the scenario to completion; returns the trace.

        Raises :class:`DeadlockDetected` on a wait-for cycle and
        ``RuntimeError`` on a hang (every thread waiting on something
        no scenario thread will ever provide) or deadline blowout.
        Worker exceptions re-raise here (lowest tid first) unless
        ``raise_errors=False`` — they stay in ``self.errors`` either
        way."""
        if self._started:
            raise RuntimeError("scheduler already ran")
        if not self._fns:
            raise RuntimeError("no scenario threads spawned")
        self._started = True
        for tid, (name, fn) in enumerate(self._fns):
            t = threading.Thread(
                target=self._worker, args=(tid, fn), name=f"det-{name}", daemon=True
            )
            self._threads.append(t)
            t.start()
        deadline = time.monotonic() + self._deadline_s
        while True:
            self._ctl.clear()
            grant: int | None = None
            with self._mu:
                kind, payload = self._decide_locked()
                if kind == "grant":
                    grant = payload
                    self._status[grant] = "running"
                    self._trace.append(f"grant {self._tn(grant)}")
                elif kind == "deadlock":
                    self._trace.append(payload[0])
            if kind == "done":
                break
            if kind == "deadlock":
                msg, cyc = payload
                raise DeadlockDetected(msg, self._trace, [self._tn(t) for t in cyc])
            if grant is not None:
                self._grants[grant].set()
            elif kind == "wait_transit":
                if not self._ctl.wait(self._settle_s):
                    # An expected wakeup never came: the thread is
                    # blocked on something outside the ledger (event
                    # waiter, socket).  Park it; its own hooks re-admit
                    # it when the external waker fires.
                    with self._mu:
                        for t in payload:
                            if self._status[t] == "limbo" and self._transit_locked(t):
                                self._status[t] = "parked"
                                self._trace.append(f"{self._tn(t)} parked")
            elif kind == "wait_run":
                self._ctl.wait(1.0)
            else:  # wait_hang
                if not self._ctl.wait(self._hang_s):
                    raise RuntimeError(
                        "interleaving hang: no scenario thread can make "
                        "progress and no wait-for cycle exists (external "
                        "waker missing?); trace:\n" + "\n".join(self._trace)
                    )
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "scenario deadline exceeded; trace:\n" + "\n".join(self._trace)
                )
        for t in self._threads:
            t.join(timeout=5.0)
        if raise_errors and self._errors:
            raise self._errors[min(self._errors)]
        return list(self._trace)

    @property
    def errors(self) -> dict[int, BaseException]:
        return dict(self._errors)


def _find_cycle(edges: dict[int, int]) -> list[int] | None:
    """A cycle in the wait-for graph (each node has at most one out
    edge, so chain-walking suffices), or None.  Iteration order is
    sorted, so the reported cycle is deterministic."""
    for start in sorted(edges):
        seen: list[int] = []
        t = start
        while t in edges and t not in seen:
            seen.append(t)
            t = edges[t]
        if t in seen:
            return seen[seen.index(t) :]
    return None


@contextlib.contextmanager
def stress_switch_interval(interval_s: float = 1e-5) -> Iterator[None]:
    """Shrink the interpreter's thread switch interval so free-running
    (non-DetScheduler) stress scenarios context-switch thousands of
    times more often — the cheap way to shake out torn state when a
    scenario's waker lives outside the scheduler's ledger."""
    old = sys.getswitchinterval()
    sys.setswitchinterval(interval_s)
    try:
        yield
    finally:
        sys.setswitchinterval(old)
