"""The production route matrix: every serving entrypoint traced to a
ClosedJaxpr with its key-material argument positions declared.

Each route traces the UNWRAPPED body of the corresponding module-level
jitted function (``fn.__wrapped__`` for decorated jits, the raw
``*_body`` functions where the repo keeps them separate) — the same
callables production dispatch lands on through ``core.plans`` — so the
verifier sees exactly the traced graph of the deployed route while
never touching a jit compile cache (``core.plans.trace_count`` counts
compiled executables; tracing adds none — asserted in
tests/test_oblivious.py).

Shapes are the smallest that still exercise the real kernels: the
Pallas routes need the kernel tile quanta (B % 128 for the plane
kernels, K % 8 / % 128 for the walk kernels, Kp % 8 for the compat
fused kernels), so those routes generate just enough keys to tile.  All
key batches come from the profile's own ``gen_batch`` under a seeded
rng — the traced shapes, and therefore the certificate hashes, are
deterministic.

Secret sources per route are the operands derived from key material:
seeds, control bits/words (ts / t_words / tcw / tl / tr), seed CWs
(scw), value CWs (vcw / fvcw), final CWs (fcw), the device-cached
per-key lane masks built from all of the above, and prefix-expansion
level state (S, T).  Query tensors (xs_hi / xs_lo, packed path words,
leaf selectors) are public: they are the *client's* input, known to the
server by definition.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

__all__ = [
    "Route", "ROUTES", "trace_route", "trace_route_cached", "vmem_budgets",
]


@dataclasses.dataclass(frozen=True)
class Route:
    name: str  # unique certificate key, e.g. "points/compat/walk/packed"
    entrypoint: str  # production entrypoint(s) this jaxpr underlies
    plan_route: str  # core.plans PlanKey.route ("-" when not plan-cached)
    knobs: tuple  # (("profile", ...), ("backend", ...), ...) — hashable
    build: Callable[[], tuple]  # () -> (closed_jaxpr, secret_invar_set)
    # Device floor: the mesh routes trace a REAL 8-shard shard_map (the
    # per-shard shapes — and so the certificate hash — depend on the
    # shard count, so it is pinned at 8, the virtual-CPU-mesh quantum
    # every sanctioned entry point forces).  Routes whose floor exceeds
    # the visible device count are SKIPPED, not failed (certify.
    # skipped_routes) — their committed certificates stand.
    min_devices: int = 1

    def knob_dict(self) -> dict:
        return dict(self.knobs)


def _trace(fn, args, static_argnums=(), secret=()):
    """make_jaxpr with per-ARGUMENT secrecy flags expanded to per-INVAR
    flags (pytree args flatten to multiple invars; None flattens to
    zero).  -> (ClosedJaxpr, set of secret invar indices)."""
    import jax

    static = set(static_argnums)
    flags: list[bool] = []
    for i, a in enumerate(args):
        if i in static:
            continue
        flags.extend([i in secret] * len(jax.tree_util.tree_leaves(a)))
    closed = jax.make_jaxpr(fn, static_argnums=tuple(sorted(static)))(*args)
    if len(flags) != len(closed.jaxpr.invars):  # pragma: no cover — guard
        raise AssertionError(
            f"secrecy map mismatch: {len(flags)} flags vs "
            f"{len(closed.jaxpr.invars)} invars"
        )
    return closed, {i for i, f in enumerate(flags) if f}


def _rng():
    return np.random.default_rng(2026)


# ---------------------------------------------------------------------------
# Compat (AES) profile
# ---------------------------------------------------------------------------


def _compat_batch(log_n: int, k: int):
    from ...core.keys import gen_batch

    alphas = np.arange(k, dtype=np.uint64) % (1 << min(log_n, 20))
    ka, _ = gen_batch(alphas, log_n, rng=_rng())
    return ka


def _compat_masks(kb):
    from ...models import dpf

    return dpf._point_masks(kb)


def _split32(k: int, q: int):
    import jax.numpy as jnp

    xs = np.zeros((k, q), np.uint64)
    xs_lo = jnp.asarray((xs & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    return xs_hi, xs_lo


def _points_compat_xla(packed: bool):
    from ...models import dpf

    kb = _compat_batch(9, 32)
    masks = _compat_masks(kb)
    xs_hi, xs_lo = _split32(32, 32)
    fn = dpf._eval_points_packed_body if packed else dpf._eval_points_body
    args = (kb.nu, kb.log_n, *masks, xs_hi, xs_lo, 1, "xla")
    return _trace(
        fn, args, static_argnums=(0, 1, 10, 11), secret=range(2, 8)
    )


def _points_compat_walk():
    from ...models import dpf

    kb = _compat_batch(9, 8)  # K % _PKT(8) == 0 — the kernel route
    masks = _compat_masks(kb)
    xs_hi, xs_lo = _split32(8, 32)
    args = (kb.nu, kb.log_n, *masks, xs_hi, xs_lo, 1)
    return _trace(
        dpf._eval_points_walk_body, args, static_argnums=(0, 1, 10),
        secret=range(2, 8),
    )


def _points_compat_grouped():
    from ...models import dpf

    log_n, G = 9, 8  # K = 1 * log_n * G = 72, % _PKT == 0
    kb = _compat_batch(log_n, log_n * G)
    masks = _compat_masks(kb)
    import jax.numpy as jnp

    xs = np.zeros((G, 32), np.uint64)
    xs_lo = jnp.asarray((xs & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    args = (kb.nu, log_n, 1, G, *masks, xs_hi, xs_lo, 1, True)
    return _trace(
        dpf._grouped_walk_body, args, static_argnums=(0, 1, 2, 3, 12, 13),
        secret=range(4, 10),
    )


def _evalfull_compat(log_n: int, k: int, backend: str):
    from ...models import dpf

    dk = dpf.DeviceKeys(_compat_batch(log_n, k))
    args = (
        dk.nu, dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes, backend,
    )
    return _trace(
        dpf._eval_full_jit.__wrapped__, args, static_argnums=(0, 7),
        secret=range(1, 7),
    )


def _evalfull_compat_fused():
    from ...models import dpf

    log_n = 16  # nu=9: levels beyond the fuse floor exist
    dk = dpf.DeviceKeys(_compat_batch(log_n, 256))  # Kp=8 tiles _FKT
    sched = dpf._fuse_schedule(dk.nu, 2)
    args = (
        dk.nu, dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes, "pallas_bm", sched,
    )
    return _trace(
        dpf._eval_full_fused_jit.__wrapped__, args, static_argnums=(0, 7, 8),
        secret=range(1, 7),
    )


def _evalfull_compat_chunked(single_chunk: bool):
    import jax.numpy as jnp

    from ...models import dpf

    log_n, k = 9, 32
    kb = _compat_batch(log_n, k)
    dk = dpf.DeviceKeys(kb)
    c = 1
    # Deterministic stand-in for the prefix level state (same avals the
    # real _expand_prefix_jit carries into the finish).
    kp = dk.k_padded // 32
    C = 1 << c
    S = jnp.zeros((128, C, kp), jnp.uint32)
    T = jnp.zeros((C, kp), jnp.uint32)
    if single_chunk:  # the streaming pipeline's per-chunk dispatch
        fn = dpf._finish_chunk_body
        args = (
            dk.nu - c, c, S[:, :1, :], T[:1], dk.scw_planes, dk.tl_words,
            dk.tr_words, dk.fcw_planes, "xla",
        )
    else:
        fn = dpf._finish_chunks_scan_body
        args = (
            dk.nu - c, c, S, T, dk.scw_planes, dk.tl_words, dk.tr_words,
            dk.fcw_planes, "xla",
        )
    return _trace(fn, args, static_argnums=(0, 1, 8), secret=range(2, 8))


def _ge_full_compat():
    import jax.numpy as jnp

    from ...models import fss

    words = jnp.zeros((8, 16), jnp.uint32)
    return _trace(
        fss._prefix_xor_words.__wrapped__, (words,), secret=(0,)
    )


# ---------------------------------------------------------------------------
# Protocol applications (apps/): heavy hitters + secure aggregation
# ---------------------------------------------------------------------------


def _hh_level_compat_walk():
    """The heavy-hitters round body on the compat kernel route: 16
    clients' level keys x 64 candidate prefixes (the shapes
    plans.run_hh_level dispatches after bucketing; the level itself is
    host-side query masking, so ONE certificate covers every level of a
    descent)."""
    from ...models import dpf

    kb = _compat_batch(10, 16)  # K % _PKT(8) == 0 — the kernel route
    masks = _compat_masks(kb)
    xs_hi, xs_lo = _split32(16, 64)
    args = (kb.nu, kb.log_n, *masks, xs_hi, xs_lo, 2)
    return _trace(
        dpf._eval_points_walk_body, args, static_argnums=(0, 1, 10),
        secret=range(2, 8),
    )


def _hh_level_compat_xla():
    from ...models import dpf

    kb = _compat_batch(10, 16)
    masks = _compat_masks(kb)
    xs_hi, xs_lo = _split32(16, 64)
    args = (kb.nu, kb.log_n, *masks, xs_hi, xs_lo, 2, "xla")
    return _trace(
        dpf._eval_points_packed_body, args, static_argnums=(0, 1, 10, 11),
        secret=range(2, 8),
    )


def _hh_level_fast():
    from ...models import dpf_chacha as dc

    kb = _fast_batch(16, 16)
    import jax.numpy as jnp

    xs_lo = jnp.zeros((64, 16), jnp.uint32)  # query-major [Q, K]
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    args = (kb.nu, 16, *kb.device_args(), xs_hi, xs_lo, 0, None)
    return _trace(
        dc._eval_points_cc_packed_body, args, static_argnums=(0, 1, 9),
        secret=range(2, 7),
    )


def _hh_state_fast(log_n: int, k: int, cb: int):
    """A fast-profile frontier-cache state tuple at column bucket ``cb``
    (apps/hh_state.FrontierState.reset's shapes) plus the key batch —
    the carried seed/control-bit arrays every extend dispatch consumes."""
    import jax.numpy as jnp

    kb = _fast_batch(log_n, k)
    seeds, ts, scw, tcw, fcw = kb.device_args()
    S = [jnp.tile(seeds[:, i : i + 1], (1, cb)) for i in range(4)]
    T = jnp.tile(ts[:, None], (1, cb))
    return kb, (scw, tcw, fcw), (*S, T)


def _hh_extend_fast(kind: str):
    """The incremental-descent dispatch bodies on the fast profile
    (core.plans.run_hh_extend -> models.dpf_chacha): the carried
    frontier state and every correction-word operand are secret; the
    survivor selector / child index is PUBLIC (survivors are announced
    to both aggregators by protocol — DESIGN §19)."""
    import jax.numpy as jnp

    from ...models import dpf_chacha as dc

    kb, (scw, tcw, fcw), state = _hh_state_fast(16, 16, 32)
    sel = jnp.zeros(16, jnp.int32)
    ibits = kb.log_n - kb.nu
    if kind == "tree":
        args = (
            *state, sel, scw[:, 0, 0], scw[:, 0, 1], scw[:, 0, 2],
            scw[:, 0, 3], tcw[:, 0, 0], tcw[:, 0, 1],
        )
        return _trace(
            dc._hh_extend_cc_body, args,
            secret=(0, 1, 2, 3, 4, 6, 7, 8, 9, 10, 11),
        )
    if kind == "leaf_first":
        args = (ibits, *state, sel, *(fcw[:, j] for j in range(16)))
        return _trace(
            dc._hh_leaf_first_cc_body, args, static_argnums=(0,),
            secret=tuple(range(1, 6)) + tuple(range(7, 23)),
        )
    P = jnp.zeros((16, 16, 16), jnp.uint32)  # resident leaf planes
    idx = jnp.zeros(64, jnp.int32)
    return _trace(
        dc._hh_leaf_fold_cc_body, (2, ibits, P, idx),
        static_argnums=(0, 1), secret=(2,),
    )


def _hh_state_compat(log_n: int, k: int, cb: int):
    """Compat mirror of :func:`_hh_state_fast`: bitsliced plane state
    [128, cb, Kp] / key-packed control words [cb, Kp]."""
    import jax.numpy as jnp

    from ...models import dpf

    dk = dpf.DeviceKeys(_compat_batch(log_n, k))
    S = jnp.tile(dk.seed_planes, (1, cb, 1))
    T = jnp.tile(dk.t_words, (cb, 1))
    return dk, (S, T)


def _hh_extend_compat(kind: str):
    import jax.numpy as jnp

    from ...models import dpf

    dk, (S, T) = _hh_state_compat(9, 32, 32)
    sel = jnp.zeros(16, jnp.int32)
    ibits = 9 - dk.nu
    if kind == "tree":
        args = (S, T, sel, dk.scw_planes[0], dk.tl_words[0], dk.tr_words[0])
        return _trace(dpf._hh_extend_body, args, secret=(0, 1, 3, 4, 5))
    if kind == "leaf_first":
        args = (ibits, S, T, sel, dk.fcw_planes)
        return _trace(
            dpf._hh_leaf_first_body, args, static_argnums=(0,),
            secret=(1, 2, 4),
        )
    C = jnp.zeros((128, 16, dk.k_padded // 32), jnp.uint32)
    idx = jnp.zeros(64, jnp.int32)
    return _trace(
        dpf._hh_leaf_fold_body, (2, ibits, C, idx),
        static_argnums=(0, 1), secret=(2,),
    )


def _hh_fold_mxu():
    """The MXU count fold (core.plans.run_hh_fold): only PUBLIC data —
    the driver XORs the two aggregators' rows before folding, so the
    matmul's operand is the reconstructed predicate matrix (models/
    hh_fold's module docstring; zero secret invars IS the claim)."""
    import jax.numpy as jnp

    from ...models import hh_fold

    x = jnp.zeros((64, 2), jnp.uint32)
    return _trace(hh_fold._count_fold_body, (x,), secret=())


def _agg_fold(op: str):
    """One streamed-aggregation fold chunk (apps/aggregation.py): the
    carry and the client share rows are both secret; the fold must be
    pure elementwise/reduction dataflow."""
    import jax.numpy as jnp

    from ...apps import aggregation as agg

    carry = jnp.zeros(64, jnp.uint32)
    rows = jnp.zeros((256, 64), jnp.uint32)
    return _trace(
        agg._fold_body, (op, carry, rows), static_argnums=(0,),
        secret=(1, 2),
    )


# ---------------------------------------------------------------------------
# Fast (ChaCha) profile
# ---------------------------------------------------------------------------


def _fast_batch(log_n: int, k: int):
    from ...models.keys_chacha import gen_batch

    alphas = np.arange(k, dtype=np.uint64) % (1 << min(log_n, 20))
    ka, _ = gen_batch(alphas, log_n, rng=_rng())
    return ka


def _points_fast_xla(packed: bool, level_groups: int = 0):
    from ...models import dpf_chacha as dc

    log_n = 10
    G = 4
    k = level_groups * log_n * G if level_groups else 32
    kb = _fast_batch(log_n, k)
    q = level_groups and G or k
    import jax.numpy as jnp

    xs_lo = jnp.zeros((32, q), jnp.uint32)  # query-major [Q, K or G]
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    fn = (
        dc._eval_points_cc_packed_body if packed else dc._eval_points_cc_body
    )
    args = (
        kb.nu, log_n, *kb.device_args(), xs_hi, xs_lo, level_groups, None
    )
    return _trace(
        fn, args, static_argnums=(0, 1, 9), secret=range(2, 7)
    )


def _points_fast_walk(packed: bool):
    from ...ops import chacha_pallas as cp

    kb = _fast_batch(10, 128)  # K % _KT(128) == 0 — the kernel route
    ops = cp.walk_operands(kb)  # (meta, seeds_t, scw_t, tcw_t, fcw_t)
    import jax.numpy as jnp

    xs_lo = jnp.zeros((32, 128), jnp.uint32)
    xs_hi = jnp.zeros((1, 128), jnp.uint32)
    args = (*ops, xs_lo, xs_hi, kb.log_n, kb.nu, cp._qtile(32), packed)
    return _trace(
        cp._walk_call.__wrapped__, args, static_argnums=(7, 8, 9, 10),
        secret=range(0, 5),  # meta carries the root control bits
    )


def _points_fast_walk_reduced():
    from ...ops import chacha_pallas as cp

    log_n, G = 8, 16  # K = 1 * 8 * 16 = 128
    kb = _fast_batch(log_n, log_n * G)
    ops = cp.walk_operands(kb, groups=1)
    import jax.numpy as jnp

    xs_lo = jnp.zeros((32, 128), jnp.uint32)
    xs_hi = jnp.zeros((1, 128), jnp.uint32)
    args = (*ops, xs_lo, xs_hi, log_n, kb.nu, cp._qtile(32), G, True)
    return _trace(
        cp._walk_call_reduced.__wrapped__, args,
        static_argnums=(7, 8, 9, 10, 11), secret=range(0, 5),
    )


def _dcf_points_xla(packed: bool, interval: bool = False):
    from ...models import dcf
    from ...models import dpf_chacha as dc

    log_n = 10
    alphas = np.arange(16, dtype=np.uint64)
    ka, _ = dcf.gen_lt_batch(alphas, log_n, rng=_rng())
    kb = dcf._concat_batches(ka, ka) if interval else ka
    seeds, ts, scw, tcw, vcw, fvcw = kb.device_args()
    import jax.numpy as jnp

    xs_lo = jnp.zeros((32, kb.k), jnp.uint32)
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    fn = (
        dc._eval_points_cc_packed_body if packed else dc._eval_points_cc_body
    )
    args = (kb.nu, log_n, seeds, ts, scw, tcw, fvcw, xs_hi, xs_lo, 0, vcw)
    return _trace(
        fn, args, static_argnums=(0, 1, 9),
        secret=(2, 3, 4, 5, 6, 10),
    )


def _dcf_points_walk():
    from ...models import dcf
    from ...ops import chacha_pallas as cp

    log_n = 10
    alphas = np.arange(128, dtype=np.uint64)
    ka, _ = dcf.gen_lt_batch(alphas, log_n, rng=_rng())
    ops = cp.dcf_walk_operands(ka)  # meta..fvcw_t, all key material
    import jax.numpy as jnp

    xs_lo = jnp.zeros((32, 128), jnp.uint32)
    xs_hi = jnp.zeros((1, 128), jnp.uint32)
    args = (*ops, xs_lo, xs_hi, log_n, ka.nu, cp._qtile(32), True)
    return _trace(
        cp._walk_call_dcf.__wrapped__, args, static_argnums=(8, 9, 10, 11),
        secret=range(0, 6),
    )


def _evalfull_fast_xla():
    from ...models import dpf_chacha as dc

    kb = _fast_batch(11, 8)
    args = (kb.nu, *kb.device_args())
    return _trace(
        dc._eval_full_cc_jit.__wrapped__, args, static_argnums=(0,),
        secret=range(1, 6),
    )


def _evalfull_fast_pallas():
    from ...models import dpf_chacha as dc
    from ...ops import chacha_pallas as cp

    kb = _fast_batch(16, 8)  # nu=7; K % _EKT(8) == 0
    first = kb.nu - cp._EXP_LEVELS
    seeds, ts, scw, tcw, _ = kb.device_args()
    scw_p, tcw_p, fcw_p = cp.expand_operands(kb, first)
    args = (kb.nu, first, seeds, ts, scw, tcw, scw_p, tcw_p, fcw_p)
    return _trace(
        dc._eval_full_pk_jit.__wrapped__, args, static_argnums=(0, 1),
        secret=range(2, 9),
    )


def _evalfull_fast_fused():
    from ...models import dpf_chacha as dc
    from ...ops import chacha_pallas as cp

    kb = _fast_batch(22, 8)  # nu=13: mid levels exist beyond floor+tail
    sched = dc._fuse_schedule_cc(kb.nu, 2)
    seeds, ts, scw, tcw, fcw = kb.device_args()
    scw_t, tcw_t, fcw_t = cp.expand_operands(kb, sched[2])
    args = (
        kb.nu, sched, seeds, ts, scw, tcw, fcw, scw_t, tcw_t, fcw_t
    )
    return _trace(
        dc._eval_full_fused_cc_jit.__wrapped__, args, static_argnums=(0, 1),
        secret=range(2, 10),
    )


def _evalfull_fast_chunked(single_chunk: bool):
    import jax.numpy as jnp

    from ...models import dpf_chacha as dc

    kb = _fast_batch(11, 8)
    seeds, ts, scw, tcw, fcw = kb.device_args()
    c = 1
    C = 1 << c
    S = [jnp.zeros((kb.k, C), jnp.uint32) for _ in range(4)]
    T = jnp.zeros((kb.k, C), jnp.uint32)
    if single_chunk:
        fn = dc._finish_chunk_cc_body
        args = (
            kb.nu - c, c, [s[:, :1] for s in S], T[:, :1], scw, tcw, fcw
        )
        return _trace(
            fn, args, static_argnums=(0, 1), secret=range(2, 7)
        )
    fn = dc._finish_chunks_cc_scan_body
    args = (kb.nu - c, c, *S, T, scw, tcw, fcw)
    return _trace(fn, args, static_argnums=(0, 1), secret=range(2, 10))


# ---------------------------------------------------------------------------
# Mesh-native serving routes (DPF_TPU_MESH): the shard_map dispatch
# bodies core.plans lands on when the serving mesh is resolved.  Each
# traces the UNJITTED ``*_sm`` callable from parallel/sharding.py over a
# pinned 8-shard keys-only mesh — the topology every sanctioned entry
# point (runtests.sh, lint_all.sh, tests/conftest.py) forces on CPU —
# so the per-shard shapes, and the certificate hashes, are
# deterministic.  The verifier descends the shard_map sub-jaxpr like
# any call-like primitive; the collectives (all_gather/psum in the agg
# folds) are data movement, not control flow, and must stay untainted
# of findings.
# ---------------------------------------------------------------------------

_MESH_SHARDS = 8


def _serving_mesh_8():
    from ...parallel.sharding import make_mesh

    return make_mesh(_MESH_SHARDS, 1)


def _points_sharded_compat():
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    kb = _compat_batch(9, 32)  # 4 keys per shard, XLA body
    masks = _compat_masks(kb)
    xs_hi, xs_lo = _split32(32, 32)
    fn = sharding._sharded_eval_points_sm(
        mesh, kb.nu, kb.log_n, 1, "xla", False, True
    )
    return _trace(fn, (*masks, xs_hi, xs_lo), secret=range(0, 6))


def _points_sharded_fast():
    import jax.numpy as jnp

    from ...parallel import sharding

    mesh = _serving_mesh_8()
    kb = _fast_batch(10, 32)
    xs_lo = jnp.zeros((32, 32), jnp.uint32)  # query-major [Q, K]
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    fn = sharding._sharded_eval_points_fast_sm(mesh, kb.nu, 10, 0, True)
    return _trace(
        fn, (*kb.device_args(), xs_hi, xs_lo), secret=range(0, 5)
    )


def _dcf_points_sharded():
    import jax.numpy as jnp

    from ...models import dcf
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    alphas = np.arange(16, dtype=np.uint64)
    ka, _ = dcf.gen_lt_batch(alphas, 10, rng=_rng())
    xs_lo = jnp.zeros((32, 16), jnp.uint32)
    xs_hi = jnp.zeros((1, 1), jnp.uint32)
    fn = sharding._sharded_dcf_points_sm(mesh, ka.nu, 10, 0, True)
    return _trace(
        fn, (*ka.device_args(), xs_hi, xs_lo), secret=range(0, 6)
    )


def _evalfull_sharded_compat():
    from ...models import dpf
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    dk = dpf.DeviceKeys(_compat_batch(11, 32), pad_to=32 * _MESH_SHARDS)
    fn = sharding._sharded_eval_full_sm(mesh, dk.nu, 0, "xla")
    args = (
        dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes,
    )
    return _trace(fn, args, secret=range(0, 6))


def _evalfull_sharded_fast():
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    kb = _fast_batch(11, 8)  # one key per shard, XLA pipeline
    fn = sharding._sharded_eval_full_fast_sm(mesh, kb.nu, 0, -1)
    return _trace(fn, kb.device_args(), secret=range(0, 5))


def _agg_fold_sharded(op: str):
    """One mesh aggregation fold chunk: shard-local fold + ONE
    all-reduce (XOR all-gather / psum).  Carry and rows both secret —
    the collective moves secret data but decides nothing by it."""
    import jax.numpy as jnp

    from ...parallel import sharding

    mesh = _serving_mesh_8()
    carry = jnp.zeros(64, jnp.uint32)
    rows = jnp.zeros((256, 64), jnp.uint32)  # 32 rows per shard
    fn = sharding._sharded_agg_fold_sm(mesh, op)
    return _trace(fn, (carry, rows), secret=(0, 1))


def _hh_extend_sharded_fast(kind: str):
    """The mesh-resident frontier extend (parallel/sharding hh
    factories): state and correction words shard over the client axis;
    the public selector replicates.  NO collective — each shard's
    clients expand locally and the rows stay client-sharded until the
    public fold."""
    import jax.numpy as jnp

    from ...parallel import sharding

    mesh = _serving_mesh_8()
    kb, (scw, tcw, fcw), state = _hh_state_fast(16, 32, 32)  # 4 keys/shard
    sel = jnp.zeros(16, jnp.int32)
    ibits = kb.log_n - kb.nu
    if kind == "tree":
        fn = sharding._sharded_hh_extend_fast_sm(mesh)
        args = (
            *state, sel, scw[:, 0, 0], scw[:, 0, 1], scw[:, 0, 2],
            scw[:, 0, 3], tcw[:, 0, 0], tcw[:, 0, 1],
        )
        return _trace(fn, args, secret=(0, 1, 2, 3, 4, 6, 7, 8, 9, 10, 11))
    if kind == "leaf_first":
        fn = sharding._sharded_hh_leaf_first_fast_sm(mesh, ibits)
        args = (*state, sel, *(fcw[:, j] for j in range(16)))
        return _trace(
            fn, args, secret=tuple(range(0, 5)) + tuple(range(6, 22))
        )
    fn = sharding._sharded_hh_leaf_fold_fast_sm(mesh, 2, ibits)
    P = jnp.zeros((32, 16, 16), jnp.uint32)
    idx = jnp.zeros(64, jnp.int32)
    return _trace(fn, (P, idx), secret=(0,))


def _hh_extend_sharded_compat(kind: str):
    import jax.numpy as jnp

    from ...parallel import sharding

    mesh = _serving_mesh_8()
    dk, (S, T) = _hh_state_compat(9, 256, 32)  # Kp = 8 words, 1/shard
    sel = jnp.zeros(16, jnp.int32)
    ibits = 9 - dk.nu
    if kind == "tree":
        fn = sharding._sharded_hh_extend_compat_sm(mesh)
        args = (S, T, sel, dk.scw_planes[0], dk.tl_words[0], dk.tr_words[0])
        return _trace(fn, args, secret=(0, 1, 3, 4, 5))
    if kind == "leaf_first":
        fn = sharding._sharded_hh_leaf_first_compat_sm(mesh, ibits)
        return _trace(
            fn, (S, T, sel, dk.fcw_planes), secret=(0, 1, 3)
        )
    fn = sharding._sharded_hh_leaf_fold_compat_sm(mesh, 2, ibits)
    C = jnp.zeros((128, 16, dk.k_padded // 32), jnp.uint32)
    idx = jnp.zeros(64, jnp.int32)
    return _trace(fn, (C, idx), secret=(0,))


def _hh_fold_sharded():
    """The mesh count fold: shard-local int8 matmuls + the ONE psum over
    the client axis (parallel/sharding.hh_count_fold_sharded).  Public
    operand, same trust argument as hh/fold_mxu."""
    import jax.numpy as jnp

    from ...parallel import sharding

    mesh = _serving_mesh_8()
    fn = sharding._sharded_hh_count_fold_sm(mesh)
    x = jnp.zeros((64, 2), jnp.uint32)  # 8 rows per shard
    return _trace(fn, (x,), secret=())


# ---------------------------------------------------------------------------
# Served 2-server PIR (models/pir.py; core.plans.run_pir, /v1/pir/query).
# Trust model (DESIGN §15): the DATABASE words are PUBLIC — both PIR
# servers hold identical copies by protocol — so the db operand is
# untainted; the QUERY is the secret (key material and everything
# derived from it, including the selection words and the carried
# accumulator).  The chunk index of the streamed scan is the public
# host loop counter.  The sharded routes trace a pinned (2 keys x 4
# leaf) 8-device mesh — rows shard over ``leaf``, the one collective is
# the final parity all-reduce.
# ---------------------------------------------------------------------------


def _pir_mesh_8():
    from ...parallel.sharding import make_mesh

    return make_mesh(2, 4)


def _pir_db_words(rows: int):
    import jax.numpy as jnp

    return jnp.zeros((rows, 2), jnp.uint32)  # 8-byte rows


def _pir_scan_compat():
    from ...models import dpf, pir

    dk = dpf.DeviceKeys(_compat_batch(9, 32))  # nu=2, dom=512
    fn = pir._pir_single_body(dk.nu, 128, 4, "xla")
    args = (
        dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes, _pir_db_words(512),
    )
    return _trace(fn, args, secret=range(0, 6))


def _pir_scan_fast():
    from ...models import pir

    kb = _fast_batch(9, 8)  # nu=0, dom=512
    fn = pir._pir_single_fast_body(kb.nu, 128, 4, -1)
    return _trace(
        fn, (*kb.device_args(), _pir_db_words(512)), secret=range(0, 5)
    )


def _pir_scan_sharded_compat():
    from ...models import dpf, pir

    mesh = _pir_mesh_8()
    dk = dpf.DeviceKeys(_compat_batch(9, 32), pad_to=64)  # 2 key shards
    fn = pir._pir_sharded_sm(mesh, dk.nu, 2, 128, 1, "xla")
    args = (
        dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes, _pir_db_words(512),
    )
    return _trace(fn, args, secret=range(0, 6))


def _pir_scan_sharded_fast():
    from ...models import pir

    mesh = _pir_mesh_8()
    kb = _fast_batch(12, 32)  # nu=3; leaf 4 -> subtree_levels=2
    fn = pir._pir_sharded_fast_sm(mesh, kb.nu, 2, 128, 8, -1)
    return _trace(
        fn, (*kb.device_args(), _pir_db_words(4096)), secret=range(0, 5)
    )


def _pir_stream_expand_compat(sharded: bool):
    from ...models import dpf, pir

    if sharded:
        mesh = _pir_mesh_8()
        dk = dpf.DeviceKeys(_compat_batch(9, 32), pad_to=64)
        fn = pir._pir_expand_sharded_sm(mesh, dk.nu, 2, "xla")
    else:
        dk = dpf.DeviceKeys(_compat_batch(9, 32))
        fn = pir._pir_expand_body(dk.nu, "xla")
    args = (
        dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, dk.fcw_planes,
    )
    return _trace(fn, args, secret=range(0, 6))


def _pir_stream_expand_fast(sharded: bool):
    from ...models import pir

    if sharded:
        mesh = _pir_mesh_8()
        kb = _fast_batch(12, 32)
        fn = pir._pir_expand_fast_sharded_sm(mesh, kb.nu, 2, -1)
    else:
        kb = _fast_batch(12, 32)
        fn = pir._pir_expand_fast_body(kb.nu, -1)
    return _trace(fn, kb.device_args(), secret=range(0, 5))


def _pir_stream_chunk(sharded: bool):
    """One streamed-scan chunk dispatch: selection words + carried
    accumulator secret; database slab and the chunk index ``j`` public
    (the host loop counter — the leaky twin derives it from a seed,
    ``bad_oblivious.leaky_pir_chunk_eval``)."""
    import jax.numpy as jnp

    from ...models import pir

    j = jnp.int32(0)
    if sharded:
        mesh = _pir_mesh_8()
        sel = jnp.zeros((32, 16), jnp.uint32)  # [K, dom/32], dom=512
        acc = jnp.zeros((4, 32, 2), jnp.uint32)  # leaf-major carry
        fn = pir._pir_stream_chunk_sharded_sm(mesh, 128, 1, 128)
    else:
        sel = jnp.zeros((32, 16), jnp.uint32)
        acc = jnp.zeros((32, 2), jnp.uint32)
        fn = pir._pir_stream_chunk_body(128, 1, 128)
    return _trace(fn, (sel, _pir_db_words(512), acc, j), secret=(0, 2))


def _pir_stream_combine():
    import jax.numpy as jnp

    from ...models import pir

    acc = jnp.zeros((4, 32, 2), jnp.uint32)
    fn = pir._pir_stream_combine_sm(_pir_mesh_8())
    return _trace(fn, (acc,), secret=(0,))


# ---------------------------------------------------------------------------
# Device-side dealer (models/keys_gen.py; core.plans.run_gen).  Gen is
# the one route family whose SECRET is the dealt point itself: the root
# seeds, root control bits, and the per-level alpha path bits (``bits``
# / the ``BM`` lane masks) are all secret-derived host operands, and
# every per-level select in the tower must be mask arithmetic — the
# certificates pin that no alpha bit ever reaches a branch or an index.
# The unrolled and scan-fused towers are BOTH production-reachable
# (DPF_TPU_FUSE defaults off; serving may pin it on), so both trace.
# ---------------------------------------------------------------------------


def _gen_cc_operands(dcf: bool, k: int = 32, log_n: int = 12):
    import jax.numpy as jnp

    from ...models import keys_gen
    from ...models.keys_chacha import _draw_roots

    nu = max(log_n - 9, 0)
    s0, t0, s1, t1 = _draw_roots(k, _rng())
    alphas = np.arange(k, dtype=np.uint64) % (1 << log_n)
    bits = keys_gen._alpha_bits(alphas, log_n, nu)
    return nu, (
        jnp.asarray(s0), jnp.asarray(s1),
        jnp.asarray(t0.astype(np.uint32)),
        jnp.asarray(t1.astype(np.uint32)),
        jnp.asarray(np.ascontiguousarray(bits)),
    )


def _gen_compat_operands(k: int = 32, log_n: int = 9):
    import jax.numpy as jnp

    from ...core.keys import _draw_roots
    from ...models import keys_gen
    from ...ops.aes_bitslice import pack_blocks_np

    nu = max(log_n - 7, 0)
    w = k // 32
    s0, t0, s1, _t1 = _draw_roots(k, _rng())
    alphas = np.arange(k, dtype=np.uint64) % (1 << log_n)
    bm = keys_gen._pack_lane_bits(
        keys_gen._alpha_bits(alphas, log_n, nu), w
    )
    t0_w = keys_gen._pack_lane_bits(t0.astype(np.uint32), w)
    return nu, (
        jnp.asarray(pack_blocks_np(s0)),
        jnp.asarray(pack_blocks_np(s1)),
        jnp.asarray(t0_w),
        jnp.asarray(t0_w ^ np.uint32(0xFFFFFFFF)),
        jnp.asarray(bm),
    )


def _gen_cc(dcf: bool, fused: bool):
    from ...models import keys_gen

    nu, args = _gen_cc_operands(dcf)
    return _trace(
        keys_gen._gen_cc_body, (nu, dcf, fused, *args),
        static_argnums=(0, 1, 2), secret=range(3, 8),
    )


def _gen_compat_tower(fused: bool):
    from ...models import keys_gen

    nu, args = _gen_compat_operands()
    return _trace(
        keys_gen._gen_compat_body, (nu, fused, *args),
        static_argnums=(0, 1), secret=range(2, 7),
    )


def _gen_sharded_cc(dcf: bool):
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    nu, args = _gen_cc_operands(dcf)  # 4 keys per shard
    fn = sharding._sharded_gen_cc_sm(mesh, nu, dcf, False)
    return _trace(fn, args, secret=range(0, 5))


def _gen_sharded_compat():
    from ...parallel import sharding

    mesh = _serving_mesh_8()
    nu, args = _gen_compat_operands(k=256)  # one lane word per shard
    fn = sharding._sharded_gen_compat_sm(mesh, nu, False)
    return _trace(fn, args, secret=range(0, 5))


# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------


def _route(name, entrypoint, plan_route, knobs, build, min_devices=1):
    return Route(name, entrypoint, plan_route, tuple(sorted(knobs.items())),
                 build, min_devices)


ROUTES: tuple[Route, ...] = (
    # -- pointwise, compat -------------------------------------------------
    _route(
        "points/compat/xla/bits", "models.dpf.eval_points", "points",
        {"profile": "compat", "backend": "xla", "packed": False},
        lambda: _points_compat_xla(False),
    ),
    _route(
        "points/compat/xla/packed", "models.dpf.eval_points", "points",
        {"profile": "compat", "backend": "xla", "packed": True},
        lambda: _points_compat_xla(True),
    ),
    _route(
        "points/compat/walk/packed-words", "models.dpf.eval_points",
        "points",
        {"profile": "compat", "backend": "pallas-walk", "packed": True},
        _points_compat_walk,
    ),
    _route(
        "points_grouped/compat/walk",
        "models.dpf.eval_points_level_grouped + models.fss.eval_lt_points",
        "points",
        {"profile": "compat", "backend": "pallas-walk", "packed": True,
         "reduce": True},
        _points_compat_grouped,
    ),
    # -- full-domain, compat ----------------------------------------------
    _route(
        "evalfull/compat/xla", "models.dpf.eval_full", "evalfull",
        {"profile": "compat", "backend": "xla", "fuse": "off"},
        lambda: _evalfull_compat(9, 32, "xla"),
    ),
    _route(
        "evalfull/compat/pallas_bm", "models.dpf.eval_full", "evalfull",
        {"profile": "compat", "backend": "pallas_bm", "fuse": "off"},
        lambda: _evalfull_compat(15, 32, "pallas_bm"),
    ),
    _route(
        "evalfull/compat/fused", "models.dpf.eval_full", "evalfull",
        {"profile": "compat", "backend": "pallas_bm", "fuse": "G=2"},
        _evalfull_compat_fused,
    ),
    _route(
        "evalfull_chunked/compat", "models.dpf.eval_full (chunked scan)",
        "evalfull",
        {"profile": "compat", "backend": "xla", "fuse": "off"},
        lambda: _evalfull_compat_chunked(False),
    ),
    _route(
        "evalfull_stream/compat", "models.dpf.eval_full_stream chunk body",
        "evalfull",
        {"profile": "compat", "backend": "xla", "stream": True},
        lambda: _evalfull_compat_chunked(True),
    ),
    _route(
        "ge_full/compat", "models.fss.ge_full_from_dpf prefix-XOR scan",
        "-",
        {"profile": "compat", "backend": "xla"},
        _ge_full_compat,
    ),
    # -- pointwise, fast ---------------------------------------------------
    _route(
        "points/fast/xla/bits", "models.dpf_chacha.eval_points", "points",
        {"profile": "fast", "backend": "xla", "packed": False},
        lambda: _points_fast_xla(False),
    ),
    _route(
        "points/fast/xla/packed", "models.dpf_chacha.eval_points", "points",
        {"profile": "fast", "backend": "xla", "packed": True},
        lambda: _points_fast_xla(True),
    ),
    _route(
        "points_grouped/fast/xla",
        "models.dpf_chacha.eval_points_level_grouped "
        "+ models.fss.eval_lt_points",
        "points",
        {"profile": "fast", "backend": "xla", "packed": True, "groups": 2},
        lambda: _points_fast_xla(True, level_groups=2),
    ),
    _route(
        "points/fast/walk/bits", "models.dpf_chacha.eval_points", "points",
        {"profile": "fast", "backend": "pallas-walk", "packed": False},
        lambda: _points_fast_walk(False),
    ),
    _route(
        "points/fast/walk/packed", "models.dpf_chacha.eval_points",
        "points",
        {"profile": "fast", "backend": "pallas-walk", "packed": True},
        lambda: _points_fast_walk(True),
    ),
    _route(
        "points_grouped/fast/walk-reduced",
        "models.dpf_chacha.eval_points_level_grouped "
        "+ models.fss.eval_lt_points / eval_interval_points",
        "points",
        {"profile": "fast", "backend": "pallas-walk", "packed": True,
         "reduce": True},
        _points_fast_walk_reduced,
    ),
    # -- DCF ---------------------------------------------------------------
    _route(
        "dcf_points/xla/bits", "models.dcf.eval_lt_points", "dcf_points",
        {"profile": "fast", "backend": "xla", "packed": False},
        lambda: _dcf_points_xla(False),
    ),
    _route(
        "dcf_points/xla/packed", "models.dcf.eval_lt_points", "dcf_points",
        {"profile": "fast", "backend": "xla", "packed": True},
        lambda: _dcf_points_xla(True),
    ),
    _route(
        "dcf_points/walk/packed", "models.dcf.eval_lt_points", "dcf_points",
        {"profile": "fast", "backend": "pallas-walk", "packed": True},
        _dcf_points_walk,
    ),
    _route(
        "dcf_interval/xla/packed", "models.dcf.eval_interval_points",
        "dcf_interval",
        {"profile": "fast", "backend": "xla", "packed": True},
        lambda: _dcf_points_xla(True, interval=True),
    ),
    # -- full-domain, fast -------------------------------------------------
    _route(
        "evalfull/fast/xla", "models.dpf_chacha.eval_full", "evalfull",
        {"profile": "fast", "backend": "xla", "fuse": "off"},
        _evalfull_fast_xla,
    ),
    _route(
        "evalfull/fast/pallas", "models.dpf_chacha.eval_full", "evalfull",
        {"profile": "fast", "backend": "pallas", "fuse": "off"},
        _evalfull_fast_pallas,
    ),
    _route(
        "evalfull/fast/fused", "models.dpf_chacha.eval_full", "evalfull",
        {"profile": "fast", "backend": "pallas", "fuse": "G=2"},
        _evalfull_fast_fused,
    ),
    _route(
        "evalfull_chunked/fast",
        "models.dpf_chacha.eval_full (chunked scan)", "evalfull",
        {"profile": "fast", "backend": "xla", "fuse": "off"},
        lambda: _evalfull_fast_chunked(False),
    ),
    _route(
        "evalfull_stream/fast",
        "models.dpf_chacha.eval_full_stream chunk body", "evalfull",
        {"profile": "fast", "backend": "xla", "stream": True},
        lambda: _evalfull_fast_chunked(True),
    ),
    # -- protocol applications (apps/) --------------------------------------
    _route(
        "hh/level_eval/compat/walk",
        "apps.heavy_hitters.eval_level_shares "
        "(core.plans.run_hh_level -> models.dpf.eval_points_level_grouped"
        "[levels] -> eval_points walk)",
        "hh_level",
        {"profile": "compat", "backend": "pallas-walk", "packed": True},
        _hh_level_compat_walk,
    ),
    _route(
        "hh/level_eval/compat/xla",
        "apps.heavy_hitters.eval_level_shares "
        "(core.plans.run_hh_level -> models.dpf.eval_points_level_grouped"
        "[levels] -> eval_points xla)",
        "hh_level",
        {"profile": "compat", "backend": "xla", "packed": True},
        _hh_level_compat_xla,
    ),
    _route(
        "hh/level_eval/fast/xla",
        "apps.heavy_hitters.eval_level_shares "
        "(core.plans.run_hh_level -> models.dpf_chacha."
        "eval_points_level_grouped[levels] -> eval_points)",
        "hh_level",
        {"profile": "fast", "backend": "xla", "packed": True},
        _hh_level_fast,
    ),
    _route(
        "hh/extend/fast",
        "apps.hh_state.FrontierState._tree_step "
        "(core.plans.run_hh_extend -> models.dpf_chacha._hh_extend_cc)",
        "hh_extend",
        {"profile": "fast", "phase": "tree"},
        lambda: _hh_extend_fast("tree"),
    ),
    _route(
        "hh/extend_leaf_first/fast",
        "apps.hh_state.FrontierState._leaf_first "
        "(core.plans.run_hh_extend -> models.dpf_chacha._hh_leaf_first_cc)",
        "hh_extend",
        {"profile": "fast", "phase": "leaf_first"},
        lambda: _hh_extend_fast("leaf_first"),
    ),
    _route(
        "hh/extend_leaf_fold/fast",
        "apps.hh_state.FrontierState._leaf_fold "
        "(core.plans.run_hh_extend -> models.dpf_chacha._hh_leaf_fold_cc)",
        "hh_extend",
        {"profile": "fast", "phase": "leaf_fold"},
        lambda: _hh_extend_fast("leaf_fold"),
    ),
    _route(
        "hh/extend/compat",
        "apps.hh_state.FrontierState._tree_step "
        "(core.plans.run_hh_extend -> models.dpf._hh_extend)",
        "hh_extend",
        {"profile": "compat", "phase": "tree"},
        lambda: _hh_extend_compat("tree"),
    ),
    _route(
        "hh/extend_leaf_first/compat",
        "apps.hh_state.FrontierState._leaf_first "
        "(core.plans.run_hh_extend -> models.dpf._hh_leaf_first)",
        "hh_extend",
        {"profile": "compat", "phase": "leaf_first"},
        lambda: _hh_extend_compat("leaf_first"),
    ),
    _route(
        "hh/extend_leaf_fold/compat",
        "apps.hh_state.FrontierState._leaf_fold "
        "(core.plans.run_hh_extend -> models.dpf._hh_leaf_fold)",
        "hh_extend",
        {"profile": "compat", "phase": "leaf_fold"},
        lambda: _hh_extend_compat("leaf_fold"),
    ),
    _route(
        "hh/fold_mxu",
        "apps.heavy_hitters.reconstruct_counts "
        "(core.plans.run_hh_fold -> models.hh_fold._count_fold)",
        "hh_fold",
        {"profile": "public", "backend": "mxu"},
        _hh_fold_mxu,
    ),
    _route(
        "agg/fold_xor",
        "apps.aggregation._fold_body (core.plans.run_agg_fold; "
        "/v1/agg/submit chunk dispatch)",
        "agg_xor",
        {"profile": "agg", "op": "xor"},
        lambda: _agg_fold("xor"),
    ),
    _route(
        "agg/fold_add",
        "apps.aggregation._fold_body (core.plans.run_agg_fold; "
        "/v1/agg/submit chunk dispatch)",
        "agg_add",
        {"profile": "agg", "op": "add"},
        lambda: _agg_fold("add"),
    ),
    # -- device-side dealer (models/keys_gen.py; /v1/gen, /v1/dcf_gen,
    # /v1/hh/gen when DPF_TPU_GEN resolves on) -------------------------------
    _route(
        "gen/compat/unrolled",
        "core.keys.gen_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_compat)",
        "gen",
        {"profile": "compat", "backend": "xla", "fuse": "off"},
        lambda: _gen_compat_tower(False),
    ),
    _route(
        "gen/compat/fused",
        "core.keys.gen_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_compat, lax.scan tower)",
        "gen",
        {"profile": "compat", "backend": "xla", "fuse": "scan"},
        lambda: _gen_compat_tower(True),
    ),
    _route(
        "gen/fast/unrolled",
        "models.keys_chacha.gen_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_cc)",
        "gen",
        {"profile": "fast", "backend": "xla", "fuse": "off"},
        lambda: _gen_cc(False, False),
    ),
    _route(
        "gen/fast/fused",
        "models.keys_chacha.gen_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_cc, lax.scan tower)",
        "gen",
        {"profile": "fast", "backend": "xla", "fuse": "scan"},
        lambda: _gen_cc(False, True),
    ),
    _route(
        "gen/dcf/unrolled",
        "models.dcf.gen_lt_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_cc with per-level value CWs)",
        "gen",
        {"profile": "dcf", "backend": "xla", "fuse": "off"},
        lambda: _gen_cc(True, False),
    ),
    _route(
        "gen/dcf/fused",
        "models.dcf.gen_lt_batch (core.plans.run_gen -> "
        "models.keys_gen._gen_cc, lax.scan tower)",
        "gen",
        {"profile": "dcf", "backend": "xla", "fuse": "scan"},
        lambda: _gen_cc(True, True),
    ),
    # -- mesh-native serving (DPF_TPU_MESH; parallel/sharding.py) -----------
    _route(
        "points_sharded/compat/xla/packed",
        "parallel.sharding.eval_points_sharded "
        "(core.plans.run_points mesh dispatch)",
        "points",
        {"profile": "compat", "backend": "xla", "packed": True, "mesh": 8},
        _points_sharded_compat, min_devices=_MESH_SHARDS,
    ),
    _route(
        "points_sharded/fast/xla/packed",
        "parallel.sharding.eval_points_sharded_fast "
        "(core.plans.run_points / run_hh_level mesh dispatch)",
        "points",
        {"profile": "fast", "backend": "xla", "packed": True, "mesh": 8},
        _points_sharded_fast, min_devices=_MESH_SHARDS,
    ),
    _route(
        "dcf_points_sharded/xla/packed",
        "parallel.sharding.eval_lt_points_sharded "
        "(core.plans.run_points / run_interval mesh dispatch)",
        "dcf_points",
        {"profile": "fast", "backend": "xla", "packed": True, "mesh": 8},
        _dcf_points_sharded, min_devices=_MESH_SHARDS,
    ),
    _route(
        "evalfull_sharded/compat/xla",
        "parallel.sharding.eval_full_sharded "
        "(core.plans.run_evalfull mesh dispatch)",
        "evalfull",
        {"profile": "compat", "backend": "xla", "mesh": 8},
        _evalfull_sharded_compat, min_devices=_MESH_SHARDS,
    ),
    _route(
        "evalfull_sharded/fast/xla",
        "parallel.sharding.eval_full_sharded_fast "
        "(core.plans.run_evalfull mesh dispatch)",
        "evalfull",
        {"profile": "fast", "backend": "xla", "mesh": 8},
        _evalfull_sharded_fast, min_devices=_MESH_SHARDS,
    ),
    _route(
        "agg_sharded/fold_xor",
        "parallel.sharding.fold_rows_sharded "
        "(core.plans.run_agg_fold mesh dispatch; one all-reduce/chunk)",
        "agg_xor",
        {"profile": "agg", "op": "xor", "mesh": 8},
        lambda: _agg_fold_sharded("xor"), min_devices=_MESH_SHARDS,
    ),
    _route(
        "agg_sharded/fold_add",
        "parallel.sharding.fold_rows_sharded "
        "(core.plans.run_agg_fold mesh dispatch; one all-reduce/chunk)",
        "agg_add",
        {"profile": "agg", "op": "add", "mesh": 8},
        lambda: _agg_fold_sharded("add"), min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/fast/tree",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "fast", "phase": "tree", "mesh": 8},
        lambda: _hh_extend_sharded_fast("tree"), min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/fast/leaf_first",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "fast", "phase": "leaf_first", "mesh": 8},
        lambda: _hh_extend_sharded_fast("leaf_first"),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/fast/leaf_fold",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "fast", "phase": "leaf_fold", "mesh": 8},
        lambda: _hh_extend_sharded_fast("leaf_fold"),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/compat/tree",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "compat", "phase": "tree", "mesh": 8},
        lambda: _hh_extend_sharded_compat("tree"), min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/compat/leaf_first",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "compat", "phase": "leaf_first", "mesh": 8},
        lambda: _hh_extend_sharded_compat("leaf_first"),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_extend_sharded/compat/leaf_fold",
        "parallel.sharding.hh_extend_fn_sharded "
        "(core.plans.run_hh_extend mesh dispatch)",
        "hh_extend",
        {"profile": "compat", "phase": "leaf_fold", "mesh": 8},
        lambda: _hh_extend_sharded_compat("leaf_fold"),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "hh_fold_sharded/mxu",
        "parallel.sharding.hh_count_fold_sharded "
        "(core.plans.run_hh_fold mesh dispatch; one psum/round)",
        "hh_fold",
        {"profile": "public", "backend": "mxu", "mesh": 8},
        _hh_fold_sharded, min_devices=_MESH_SHARDS,
    ),
    _route(
        "gen_sharded/compat",
        "parallel.sharding.gen_compat_sharded_fn "
        "(core.plans.run_gen mesh dispatch; zero collectives)",
        "gen",
        {"profile": "compat", "backend": "xla", "mesh": 8},
        _gen_sharded_compat, min_devices=_MESH_SHARDS,
    ),
    _route(
        "gen_sharded/fast",
        "parallel.sharding.gen_cc_sharded_fn "
        "(core.plans.run_gen mesh dispatch; zero collectives)",
        "gen",
        {"profile": "fast", "backend": "xla", "mesh": 8},
        lambda: _gen_sharded_cc(False), min_devices=_MESH_SHARDS,
    ),
    _route(
        "gen_sharded/dcf",
        "parallel.sharding.gen_cc_sharded_fn "
        "(core.plans.run_gen mesh dispatch; zero collectives)",
        "gen",
        {"profile": "dcf", "backend": "xla", "mesh": 8},
        lambda: _gen_sharded_cc(True), min_devices=_MESH_SHARDS,
    ),
    # -- served 2-server PIR (models/pir.py; /v1/pir/query) ------------------
    _route(
        "pir/scan/compat/xla",
        "models.pir.PirServer.answer one-shot pipeline "
        "(core.plans.run_pir -> _pir_single)",
        "pir",
        {"profile": "compat", "backend": "xla", "fuse": "off"},
        _pir_scan_compat,
    ),
    _route(
        "pir/scan/fast/xla",
        "models.pir.PirServer.answer one-shot pipeline "
        "(core.plans.run_pir -> _pir_single_fast)",
        "pir",
        {"profile": "fast", "backend": "xla"},
        _pir_scan_fast,
    ),
    _route(
        "pir/scan_sharded/compat/xla",
        "models.pir.PirServer.answer sharded pipeline "
        "(core.plans.run_pir -> _pir_sharded; rows over leaf, one "
        "parity all-reduce)",
        "pir",
        {"profile": "compat", "backend": "xla", "mesh": "2x4"},
        _pir_scan_sharded_compat, min_devices=_MESH_SHARDS,
    ),
    _route(
        "pir/scan_sharded/fast/xla",
        "models.pir.PirServer.answer sharded pipeline "
        "(core.plans.run_pir -> _pir_sharded_fast)",
        "pir",
        {"profile": "fast", "backend": "xla", "mesh": "2x4"},
        _pir_scan_sharded_fast, min_devices=_MESH_SHARDS,
    ),
    _route(
        "pir/stream_expand/compat/xla",
        "models.pir streamed scan expansion dispatch (_pir_expand)",
        "pir",
        {"profile": "compat", "backend": "xla", "stream": True},
        lambda: _pir_stream_expand_compat(False),
    ),
    _route(
        "pir/stream_expand/fast/xla",
        "models.pir streamed scan expansion dispatch (_pir_expand_fast)",
        "pir",
        {"profile": "fast", "backend": "xla", "stream": True},
        lambda: _pir_stream_expand_fast(False),
    ),
    _route(
        "pir/stream_expand_sharded/compat/xla",
        "models.pir streamed scan expansion dispatch "
        "(_pir_expand_sharded; selection words stay sharded keys x leaf)",
        "pir",
        {"profile": "compat", "backend": "xla", "stream": True,
         "mesh": "2x4"},
        lambda: _pir_stream_expand_compat(True),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "pir/stream_expand_sharded/fast/xla",
        "models.pir streamed scan expansion dispatch "
        "(_pir_expand_fast_sharded)",
        "pir",
        {"profile": "fast", "backend": "xla", "stream": True,
         "mesh": "2x4"},
        lambda: _pir_stream_expand_fast(True),
        min_devices=_MESH_SHARDS,
    ),
    _route(
        "pir/stream_chunk",
        "models.pir streamed scan chunk dispatch (_pir_stream_chunk; "
        "public chunk index, donated accumulator)",
        "pir",
        {"stream": True},
        lambda: _pir_stream_chunk(False),
    ),
    _route(
        "pir/stream_chunk_sharded",
        "models.pir streamed scan chunk dispatch "
        "(_pir_stream_chunk_sharded; zero collectives per chunk)",
        "pir",
        {"stream": True, "mesh": "2x4"},
        lambda: _pir_stream_chunk(True), min_devices=_MESH_SHARDS,
    ),
    _route(
        "pir/stream_combine_sharded",
        "models.pir streamed scan combine dispatch (_pir_stream_combine; "
        "the ONE parity all-reduce per query batch)",
        "pir",
        {"stream": True, "mesh": "2x4"},
        _pir_stream_combine, min_devices=_MESH_SHARDS,
    ),
)


def vmem_budgets() -> dict[str, int]:
    """kernel-name-fragment -> budget from the ops modules' declared
    ``_VMEM_BUDGET`` — the same bound the AST pallas-jit pass lints the
    ``# vmem:`` models against, now cross-checked against TRACED block
    shapes."""
    out: dict[str, int] = {}
    from ...ops import aes_pallas, chacha_pallas

    for frag, mod in (("aes", aes_pallas), ("chacha", chacha_pallas),
                      ("walk", chacha_pallas)):
        b = getattr(mod, "_VMEM_BUDGET", None)
        if isinstance(b, int):
            out[frag] = b
    return out


def trace_route(route: Route):
    """-> (ClosedJaxpr, secret invar set).  Separated for tests."""
    return route.build()


# One trace per route per process: the oblivious-trace pass (taint
# lattice + certificate drift) and the perf-contract pass (collective /
# donation / dispatch budgets + cost model) both consume the same
# ClosedJaxpr, so a lint run (`python -m dpf_tpu.analysis`) traces each
# route once, not once per pass — tracing is the dominant cost of both.
_TRACE_CACHE: dict[str, tuple] = {}


def trace_route_cached(route: Route):
    """Memoized :func:`trace_route` keyed on the route name.  Safe to
    share across passes: routes trace UNWRAPPED bodies with
    deterministic shapes, so the (jaxpr, secret-invar) pair is a pure
    function of the route and the jax version."""
    got = _TRACE_CACHE.get(route.name)
    if got is None:
        got = _TRACE_CACHE[route.name] = route.build()
    return got
