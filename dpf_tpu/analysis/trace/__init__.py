"""Jaxpr-level oblivious-dataflow verifier.

The AST passes (knob-registry, secret-hygiene, host-sync, pallas-jit)
see source text; this package sees what JAX actually *traces*.  A DPF
deployment's security story (BGI16 — PAPER.md) rests on each party's
evaluation being data-oblivious: no branch predicate, no memory index,
no output shape, and no host callback may depend on key material, or a
2-server PIR deployment leaks ``alpha`` through its timing and access
patterns.  ``jnp.where`` rewritten into a ``lax.cond`` by a refactor, a
secret-derived ``dynamic_slice`` start index, a ``debug_print`` left in
a jitted graph — none of those are visible to a source linter, all of
them are visible in the jaxpr.

Three modules:

  taint.py        the interprocedural taint lattice over ClosedJaxpr
                  equations: sources are the key-material operands,
                  taint propagates through every primitive including
                  ``scan``/``cond``/``while``/``pjit``/``pallas_call``
                  sub-jaxprs (with Ref write-back inside Pallas
                  kernels), findings fire on secret-tainted control
                  flow, secret-tainted memory indices, callbacks,
                  secret->float casts, and secret-dependent shapes.
                  Also computes the primitive census, a deterministic
                  structural hash of the jaxpr, and the traced
                  VMEM-block cross-check against the ops modules'
                  ``_VMEM_BUDGET``.
  entrypoints.py  the production route matrix: every serving entrypoint
                  (eval_points / eval_points_level_grouped / eval_full /
                  eval_full_stream chunk bodies, DCF eval_lt_points /
                  eval_interval_points, FSS gates, ge_full) x
                  {AES-compat, ChaCha-fast} x {packed, unpacked} x
                  {fuse off, fuse G} traced to a ClosedJaxpr under
                  ``JAX_PLATFORMS=cpu``, with the key-material argument
                  positions declared per route.  Routes trace the
                  UNWRAPPED jit bodies, so the verifier never populates
                  a compile cache (``core.plans.trace_count`` is
                  asserted unchanged in tests).
  certify.py      obliviousness certificates: a clean route emits
                  (entrypoint, route/knob tuple, jaxpr hash, primitive
                  census, verifier version) into docs/OBLIVIOUS.md + the
                  docs/oblivious.json sidecar; the pass fails when a
                  route's hash drifts from the committed certificate
                  without re-certification
                  (``python -m dpf_tpu.analysis --write-oblivious``).

The perf-contract pass (``analysis/perf/``) consumes the same route
traces through ``entrypoints.trace_route_cached`` — one lint run traces
each route once, and the two certificate ledgers pin the same hash.

Run as the ``oblivious-trace`` analysis pass under
``python -m dpf_tpu.analysis`` / ``scripts/lint_all.sh`` /
``runtests.sh --lint``.
"""

from __future__ import annotations

# Bump when the lattice rules, the route matrix, or the hash scheme
# change (committed certificates re-generate; bench ledgers keyed on it
# re-measure).
OBLIVIOUS_VERIFIER_VERSION = "1"
