"""Interprocedural taint lattice over jaxpr equations.

Two-point lattice per variable: PUBLIC (bottom) or SECRET (top).  Taint
enters at the key-material invars a route declares, joins upward through
every equation (any secret input -> all outputs secret), and descends
into sub-jaxprs:

  * ``pjit`` / ``closed_call`` / ``custom_jvp_call`` / ``remat`` — the
    sub-jaxpr's invars map 1:1 onto the equation's.
  * ``scan`` — consts + carry + per-iteration slices; the carry taints
    iterate to a fixpoint (a secret entering the carry on iteration k
    taints it for all iterations).
  * ``while`` — body carry to fixpoint; the cond sub-jaxpr's boolean
    output is a *finding* when tainted (secret-dependent trip count).
  * ``cond`` — a tainted branch index is a finding; operand taints run
    through every branch and the outputs join.
  * ``pallas_call`` — the kernel jaxpr's Ref invars take the operand
    taints; ``get``/``swap`` track taint through the Refs (a store of a
    secret value taints the Ref; loads read the Ref's taint) and any
    *dynamic index operand* of a Ref access that is tainted is a finding
    (a secret-dependent VMEM/HBM access pattern).

Findings (the data-obliviousness contract, docs/DESIGN.md §10):

  secret-branch     ``cond`` branch index / ``while`` predicate tainted
  secret-index      tainted index operand of ``dynamic_slice`` /
                    ``dynamic_update_slice`` / ``gather`` / ``scatter*``
                    or of a kernel Ref access
  callback          ``pure_callback`` / ``io_callback`` /
                    ``debug_callback`` / ``debug_print`` anywhere in a
                    traced graph (host round trip: timing channel, and
                    the payload leaves the device)
  secret-float      a tainted integer word converted to a float dtype
                    (float arithmetic is not constant-time on all
                    hardware paths, and NaN/inf payloads can leak bits)
  secret-shape      a tainted value whose aval shape is not static
  vmem-over-budget  a ``pallas_call``'s traced block footprint exceeds
                    the owning ops module's ``_VMEM_BUDGET`` (the bound
                    the AST pass lints the ``# vmem:`` models against)

The walk also produces the primitive census and a deterministic
structural hash of the jaxpr — the certificate identity in certify.py.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from collections import Counter
from typing import Any

import numpy as np

_CALLBACK_PRIMS = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "debug_print"}
)
# Pallas/state Ref access primitives (kernel-mode handling: taint flows
# through the Ref itself, and a tainted dynamic index is a finding).
_REF_PRIMS = frozenset(
    {"get", "swap", "masked_load", "masked_swap", "addupdate", "load",
     "store"}
)
# invar index ranges of index operands, per primitive: (first, None) means
# "from ``first`` to the end".
_INDEXED_PRIMS: dict[str, tuple[int, int | None]] = {
    "dynamic_slice": (1, None),
    "dynamic_update_slice": (2, None),
    "gather": (1, 2),
    "scatter": (1, 2),
    "scatter-add": (1, 2),
    "scatter_add": (1, 2),
    "scatter-mul": (1, 2),
    "scatter-min": (1, 2),
    "scatter-max": (1, 2),
}


@dataclasses.dataclass(frozen=True)
class TaintFinding:
    kind: str  # secret-branch | secret-index | callback | secret-float |
    #            secret-shape | vmem-over-budget
    where: str  # eqn path inside the jaxpr, e.g. "eqn 41 (pjit) / eqn 3"
    message: str


@dataclasses.dataclass
class TaintReport:
    findings: list[TaintFinding]
    census: Counter  # primitive name -> count, sub-jaxprs included
    n_eqns: int  # total equations walked


def _is_ref(aval) -> bool:
    """Pallas/state Ref avals (duck-typed: jax version drift tolerant)."""
    return type(aval).__name__ == "AbstractRef" or hasattr(aval, "inner_aval")


def _is_float(dtype) -> bool:
    try:
        return np.issubdtype(np.dtype(dtype), np.floating)
    except TypeError:
        return False


def _static_shape(aval) -> bool:
    shape = getattr(aval, "shape", ())
    return all(isinstance(d, (int, np.integer)) for d in shape)


def _sub_jaxprs(value):
    """Yield every open Jaxpr reachable inside one params value.
    ClosedJaxpr forwards ``.eqns`` to its jaxpr, so the unwrap check
    must come first."""
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        yield value.jaxpr  # ClosedJaxpr
    elif hasattr(value, "eqns"):  # Jaxpr
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


class _Analyzer:
    def __init__(self, vmem_budgets: dict[str, int] | None = None):
        self.findings: list[TaintFinding] = []
        self.census: Counter = Counter()
        self.n_eqns = 0
        # kernel-name-fragment -> budget bytes (the ops modules'
        # _VMEM_BUDGET values); empty disables the cross-check.
        self.vmem_budgets = vmem_budgets or {}
        # >0 while re-walking a loop body purely to reach the taint
        # fixpoint: taints still propagate, but findings and the census
        # are suppressed so each equation is reported/counted exactly
        # once (by the final, converged walk).
        self._mute = 0

    # -- helpers ----------------------------------------------------------

    def _emit(self, kind: str, path: str, msg: str) -> None:
        if not self._mute:
            self.findings.append(TaintFinding(kind, path, msg))

    def _count(self, prim: str) -> None:
        if not self._mute:
            self.census[prim] += 1
            self.n_eqns += 1

    @staticmethod
    def _read(env: dict, v) -> bool:
        # Literals are trace-time constants: public by construction.
        return env.get(id(v), False) if hasattr(v, "aval") and not hasattr(
            v, "val"
        ) else False

    # -- the walk ---------------------------------------------------------

    def run(
        self, jaxpr, in_taints: list[bool], path: str = "",
        kernel: bool = False,
    ) -> list[bool]:
        """Propagate taint through ``jaxpr`` (a Jaxpr, not Closed) with
        the given invar taints; -> outvar taints.  ``kernel`` marks a
        Pallas kernel context (Ref-aware handling), and is inherited by
        every sub-jaxpr walked from inside one — a ``fori_loop`` body
        inside a kernel gets the same Ref discipline as the kernel's top
        level."""
        env: dict[int, bool] = {}
        for v in jaxpr.constvars:
            env[id(v)] = False
        if len(in_taints) < len(jaxpr.invars):
            # conservative: unmapped trailing invars (e.g. kernel scratch
            # Refs) start public
            in_taints = list(in_taints) + [False] * (
                len(jaxpr.invars) - len(in_taints)
            )
        for v, t in zip(jaxpr.invars, in_taints):
            env[id(v)] = bool(t)

        for idx, eqn in enumerate(jaxpr.eqns):
            where = f"{path}eqn {idx} ({eqn.primitive.name})"
            if kernel and eqn.primitive.name in _REF_PRIMS:
                self._ref_access(env, eqn, where)
            else:
                self._eqn(env, eqn, where, kernel=kernel)

        return [self._read(env, v) for v in jaxpr.outvars]

    def _eqn(self, env: dict, eqn, where: str, kernel: bool = False) -> None:
        prim = eqn.primitive.name
        self._count(prim)
        in_t = [self._read(env, v) for v in eqn.invars]
        any_secret = any(in_t)

        # ---- unconditional structural findings --------------------------
        if prim in _CALLBACK_PRIMS:
            self._emit(
                "callback", where,
                f"{prim} in a jitted graph — host round trips are a "
                "timing channel and the payload leaves the device",
            )
        if prim == "pallas_call":
            self._check_vmem(eqn, where)

        # ---- secret-dependent control flow / memory indices -------------
        if prim == "cond" and in_t and in_t[0]:
            self._emit(
                "secret-branch", where,
                "lax.cond branch index is secret-tainted (the taken "
                "branch is observable through timing)",
            )
        if prim in _INDEXED_PRIMS and any_secret:
            first, last = _INDEXED_PRIMS[prim]
            idx_ts = in_t[first:last] if last is not None else in_t[first:]
            if any(idx_ts):
                self._emit(
                    "secret-index", where,
                    f"{prim} index operand is secret-tainted (memory "
                    "access pattern depends on key material)",
                )

        # ---- secret -> float --------------------------------------------
        if prim == "convert_element_type" and any_secret:
            new = eqn.params.get("new_dtype")
            if new is not None and _is_float(new):
                self._emit(
                    "secret-float", where,
                    f"secret word converted to {np.dtype(new).name} "
                    "(float paths are not constant-time and leak via "
                    "NaN/inf payloads)",
                )

        # ---- outputs + descent ------------------------------------------
        out_t = self._descend(env, eqn, in_t, where, kernel)
        if out_t is None:  # no sub-jaxpr handling: plain join
            out_t = [any_secret] * len(eqn.outvars)
        if kernel and any_secret:
            # A call-like sub-jaxpr (fori_loop body, nested scan) may
            # store a secret into any Ref it was handed; without per-Ref
            # effect metadata, join conservatively: every Ref operand of
            # a secret-fed equation becomes secret.
            for v in eqn.invars:
                if hasattr(v, "aval") and _is_ref(v.aval):
                    env[id(v)] = True
        for v, t in zip(eqn.outvars, out_t):
            env[id(v)] = bool(t)
            if t and not _static_shape(v.aval):
                self._emit(
                    "secret-shape", where,
                    "secret-tainted value has a non-static shape "
                    f"({getattr(v.aval, 'shape', '?')})",
                )

    def _ref_access(self, env: dict, eqn, where: str) -> None:
        """get/swap/load/store & co. inside a kernel context: taint flows
        through the Ref, and a tainted dynamic index operand is the
        secret-shaped-VMEM-traffic finding."""
        prim = eqn.primitive.name
        self._count(prim)
        in_t = [self._read(env, v) for v in eqn.invars]
        val_i = 1 if prim in ("swap", "masked_swap", "addupdate",
                              "store") else None
        idx_from = (val_i + 1) if val_i is not None else 1
        if any(in_t[idx_from:]):
            self._emit(
                "secret-index", where,
                f"kernel Ref access ({prim}) uses a secret-"
                "tainted dynamic index (VMEM access pattern "
                "depends on key material)",
            )
        ref_var = eqn.invars[0]
        t = self._read(env, ref_var)
        if val_i is not None and val_i < len(in_t):
            t = t or in_t[val_i]
            env[id(ref_var)] = t
        for v in eqn.outvars:
            env[id(v)] = t

    # -- per-primitive sub-jaxpr handling ---------------------------------

    def _descend(
        self, env, eqn, in_t, where, kernel: bool = False
    ) -> list[bool] | None:
        prim = eqn.primitive.name
        params = eqn.params

        if prim == "cond" and "branches" in params:
            branch_in = in_t[1:]
            outs = None
            for closed in params["branches"]:
                o = self.run(
                    closed.jaxpr, list(branch_in), where + " / ",
                    kernel=kernel,
                )
                outs = o if outs is None else [a or b for a, b in zip(outs, o)]
            return outs if outs is not None else []

        if prim == "while" and "body_jaxpr" in params:
            cn = params.get("cond_nconsts", 0)
            bn = params.get("body_nconsts", 0)
            cond_consts = in_t[:cn]
            body_consts = in_t[cn : cn + bn]
            carry = list(in_t[cn + bn :])
            self._mute += 1  # fixpoint re-walks: taint only, no reports
            try:
                for _ in range(len(carry) + 1):  # fixpoint: monotone joins
                    out = self.run(
                        params["body_jaxpr"].jaxpr, body_consts + carry,
                        where + " / ", kernel=kernel,
                    )
                    new = [a or b for a, b in zip(carry, out)]
                    if new == carry:
                        break
                    carry = new
            finally:
                self._mute -= 1
            # One converged walk with reporting on: each body equation
            # is counted and can fire exactly once.
            self.run(
                params["body_jaxpr"].jaxpr, body_consts + carry,
                where + " / ", kernel=kernel,
            )
            pred = self.run(
                params["cond_jaxpr"].jaxpr, cond_consts + carry,
                where + " / ", kernel=kernel,
            )
            if any(pred):
                self._emit(
                    "secret-branch", where,
                    "lax.while_loop predicate is secret-tainted (trip "
                    "count depends on key material)",
                )
            return carry

        if prim == "scan" and "jaxpr" in params:
            nc = params.get("num_consts", 0)
            ncar = params.get("num_carry", 0)
            consts = in_t[:nc]
            carry = list(in_t[nc : nc + ncar])
            xs = in_t[nc + ncar :]
            self._mute += 1
            try:
                for _ in range(len(carry) + 1):
                    out = self.run(
                        params["jaxpr"].jaxpr, consts + carry + xs,
                        where + " / ", kernel=kernel,
                    )
                    new_carry = [a or b for a, b in zip(carry, out[:ncar])]
                    if new_carry == carry:
                        break
                    carry = new_carry
            finally:
                self._mute -= 1
            out = self.run(
                params["jaxpr"].jaxpr, consts + carry + xs, where + " / ",
                kernel=kernel,
            )
            return carry + out[ncar:]

        if prim == "pallas_call" and "jaxpr" in params:
            return self._kernel(eqn, in_t, where)

        # Generic 1:1 call-like primitives (pjit, closed_call, remat,
        # custom_jvp/vjp, shard_map, ...): exactly one sub-jaxpr whose
        # invar count matches the equation's.
        subs = [j for v in params.values() for j in _sub_jaxprs(v)]
        if len(subs) == 1 and len(subs[0].invars) == len(eqn.invars):
            return self.run(subs[0], list(in_t), where + " / ", kernel=kernel)
        if subs:
            # Unknown call structure: walk for census/structural findings
            # with everything tainted iff any input is (conservative).
            outs = None
            t = any(in_t)
            for sub in subs:
                o = self.run(
                    sub, [t] * len(sub.invars), where + " / ", kernel=kernel
                )
                outs = o
            if outs is not None and len(outs) == len(eqn.outvars):
                return [a or t for a in outs]
            return [t or any(in_t)] * len(eqn.outvars)
        return None

    def _kernel(self, eqn, in_t, where) -> list[bool]:
        """pallas_call: walk the kernel jaxpr in Ref-aware kernel mode.
        Taint sources inside a kernel are its operands, so any secret
        operand conservatively taints every output."""
        kernel = eqn.params["jaxpr"]
        self.run(kernel, list(in_t), where + " / ", kernel=True)
        return [any(in_t)] * len(eqn.outvars)

    # -- VMEM cross-check --------------------------------------------------

    def _check_vmem(self, eqn, where) -> None:
        if not self.vmem_budgets:
            return
        gm = eqn.params.get("grid_mapping")
        mappings = getattr(gm, "block_mappings", None)
        if not mappings:
            return
        total = 0
        for bm in mappings:
            shape = getattr(bm, "block_shape", None)
            if shape is None:
                continue
            n = 1
            for d in shape:
                if isinstance(d, (int, np.integer)):
                    n *= int(d)
            total += n * 4  # every kernel operand in this tree is uint32
        total *= 2  # Mosaic double-buffers the I/O windows
        name = str(
            eqn.params.get("name_and_src_info", eqn.params.get("name", ""))
        )
        budget = max(self.vmem_budgets.values())
        for frag, b in self.vmem_budgets.items():
            if frag and frag in name:
                budget = b
                break
        if total > budget:
            self._emit(
                "vmem-over-budget", where,
                f"traced pallas_call block footprint ~{total} B exceeds "
                f"the ops _VMEM_BUDGET {budget} B (the bound the "
                "'# vmem:' models are linted against)",
            )


def analyze(
    closed_jaxpr, secret_invars, vmem_budgets: dict[str, int] | None = None
) -> TaintReport:
    """Run the lattice over ``closed_jaxpr`` with invar positions in
    ``secret_invars`` (indices into ``jaxpr.invars``) as taint sources."""
    a = _Analyzer(vmem_budgets)
    jaxpr = closed_jaxpr.jaxpr
    secret = set(int(i) for i in secret_invars)
    in_t = [i in secret for i in range(len(jaxpr.invars))]
    a.run(jaxpr, in_t)
    return TaintReport(a.findings, a.census, a.n_eqns)


# ---------------------------------------------------------------------------
# Deterministic structural hash (the certificate identity)
# ---------------------------------------------------------------------------

_ADDR = re.compile(r"0x[0-9a-fA-F]+")


def _const_token(value) -> str:
    """Deterministic token for a trace-time constant (ndarray/jax array
    contents included — a swapped lookup table must change the hash)."""
    if isinstance(value, np.ndarray) or (
        hasattr(value, "dtype") and hasattr(value, "shape")
        and hasattr(value, "__array__")
    ):
        arr = np.ascontiguousarray(np.asarray(value))
        return (
            f"ndarray:{arr.dtype}:{arr.shape}:"
            + hashlib.sha256(arr.tobytes()).hexdigest()[:16]
        )
    return _ADDR.sub("0x", repr(value))


def _param_token(value) -> str:
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        return "jaxpr:" + _jaxpr_token(  # ClosedJaxpr: consts included
            value.jaxpr, getattr(value, "consts", ())
        )
    if hasattr(value, "eqns"):
        return "jaxpr:" + _jaxpr_token(value)
    if isinstance(value, (tuple, list)):
        return "[" + ",".join(_param_token(v) for v in value) + "]"
    if callable(value):
        return "fn:" + getattr(value, "__qualname__", type(value).__name__)
    return _const_token(value)


def _var_token(v, nums: dict) -> str:
    """Canonical (de Bruijn) var token: vars are numbered in order of
    first appearance, so dataflow REWIRING changes the hash even when
    avals stay identical; inline Literals contribute their value."""
    if hasattr(v, "val"):  # Literal (same discrimination as _read)
        return "lit:" + _const_token(v.val)
    n = nums.setdefault(id(v), len(nums))
    aval = getattr(v, "aval", None)
    return (
        f"v{n}:{getattr(aval, 'dtype', '?')}{getattr(aval, 'shape', '?')}"
    )


def _jaxpr_token(jaxpr, consts=()) -> str:
    nums: dict[int, int] = {}
    parts = [
        "in:" + ";".join(_var_token(v, nums) for v in jaxpr.invars),
        "const:" + ";".join(_var_token(v, nums) for v in jaxpr.constvars),
        "constvals:" + ";".join(_const_token(c) for c in consts),
    ]
    for eqn in jaxpr.eqns:
        parts.append(
            eqn.primitive.name
            + "|"
            + ";".join(_var_token(v, nums) for v in eqn.invars)
            + "->"
            + ";".join(_var_token(v, nums) for v in eqn.outvars)
            + "|"
            + ";".join(
                f"{k}={_param_token(v)}" for k, v in sorted(eqn.params.items())
            )
        )
    parts.append("out:" + ";".join(_var_token(v, nums) for v in jaxpr.outvars))
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


def jaxpr_hash(closed_jaxpr) -> str:
    """Content hash of a ClosedJaxpr: primitives, canonically-numbered
    operand wiring, avals, literal values, closed-over constants, and
    params in equation order, with memory addresses and raw var names
    normalized out — stable across runs under a pinned jax version,
    which is exactly the staleness signal the certificates need."""
    return _jaxpr_token(closed_jaxpr.jaxpr, closed_jaxpr.consts)
