"""Pass 3: host-sync-in-hot-path check.

A silent device->host synchronization in the kernel or serving layers —
``.block_until_ready()``, ``.item()``, ``jax.device_get``, a bare
``np.asarray(device_array)`` — stalls the dispatch pipeline the serving
fast path exists to keep full (it is exactly what the streaming
EvalFull's overlap test guards dynamically; this pass guards it
statically, everywhere).

Scope: the kernel modules (``dpf_tpu/ops/``), the serving fast path
(``dpf_tpu/serving/``, ``core/plans.py``), the streaming pipeline
(``core/stream.py``), the models (``dpf_tpu/models/``), and the sharded
evaluators (``dpf_tpu/parallel/``).  The models' public eval routes DO
return host arrays by API contract — each of those boundaries is a
``# host-sync: final reply marshalling``-style annotated point, so the
sanctioned D2H crossings are enumerable by grep and everything else in
the eval pipelines is statically sync-free.

Flagged, unless the line (or the one above) carries a
``# host-sync: <why>`` annotation naming the sanctioned sync point:

  * ``<x>.block_until_ready()``
  * ``<x>.item()``
  * ``jax.device_get(...)``
  * ``np.asarray(x)`` / ``np.array(x)`` with a single argument and no
    dtype — in this tree that shape is always a device->host
    materialization (host-side coercions all pass ``dtype=``)
  * ``int(...)`` / ``float(...)`` over an expression mentioning
    ``jax``/``jnp`` (a device scalar pulled to host)

The annotations make every host sync explicit and reviewable: the chunk
D2H in ``core/stream.py`` and the packed-word marshalling in
``core/plans.py`` / the ops walk wrappers are the sanctioned points.
"""

from __future__ import annotations

import ast

from .common import (
    Finding, import_aliases, in_scope, iter_py_files, parse_file, pragma,
    resolve_dotted,
)

PASS = "host-sync"

_SCOPE = (
    "dpf_tpu/ops",
    "dpf_tpu/serving",
    "dpf_tpu/core/stream.py",
    "dpf_tpu/core/plans.py",
    "dpf_tpu/models",
    "dpf_tpu/parallel",
    "dpf_tpu/apps",
)

_SYNC_METHODS = {"block_until_ready", "item"}


def _mentions_jax(node: ast.AST, aliases: dict[str, str]) -> bool:
    """A name bound to jax (any import spelling: jax, jnp, a from-import
    of a jax submodule) appears under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            origin = aliases.get(sub.id)
            if origin is not None and (
                origin == "jax" or origin.startswith("jax.")
            ):
                return True
            if sub.id in ("jax", "jnp"):
                return True
    return False


def _violation(node: ast.Call, aliases: dict[str, str]) -> str | None:
    fn = node.func
    resolved = resolve_dotted(fn, aliases)
    if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
        if not node.args:
            return f".{fn.attr}() forces a device sync"
    if resolved == "jax.device_get":
        return "jax.device_get is a blocking D2H copy"
    if (
        resolved in ("numpy.asarray", "numpy.array")
        and len(node.args) == 1
        and not any(
            kw.arg == "dtype" or kw.arg is None for kw in node.keywords
        )
    ):
        return (
            f"bare np.{resolved.rsplit('.', 1)[1]}(x) materializes to "
            "host (blocking D2H on device arrays)"
        )
    if (
        isinstance(fn, ast.Name)
        and fn.id in ("int", "float")
        and len(node.args) == 1
        and _mentions_jax(node.args[0], aliases)
    ):
        return f"{fn.id}() over a jax expression pulls a device scalar"
    return None


def check_file(root: str, rel: str) -> list[Finding]:
    tree, lines = parse_file(root, rel)
    out: list[Finding] = []
    aliases = import_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        why = _violation(node, aliases)
        if why is None:
            continue
        if pragma(lines, node.lineno, "host-sync:"):
            continue  # annotated (with a non-empty why): sanctioned
        out.append(
            Finding(
                rel, node.lineno, PASS,
                f"{why} in a hot-path module — move it behind the "
                "allowlisted sync points or annotate the line with "
                "'# host-sync: <why>'",
            )
        )
    return out


def run(root: str, files=None) -> list[Finding]:
    if files is None:
        files = [f for f in iter_py_files(root) if in_scope(f, _SCOPE)]
    out: list[Finding] = []
    for rel in files:
        try:
            out.extend(check_file(root, rel))
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS, f"syntax error: {e}"))
    return out
