"""Pass 5: jaxpr-level oblivious-dataflow verification (the
``oblivious-trace`` pass).

Unlike the four AST passes this one runs the code's *traced* form: it
re-traces every production route in ``trace/entrypoints.py`` under the
hermetic CPU backend, runs the taint lattice (``trace/taint.py``), and
fails on

  * any lattice finding on any route (a secret-tainted branch, index,
    callback, float cast, dynamic shape, or an over-budget Pallas
    block), and
  * certificate drift: a route whose jaxpr hash no longer matches the
    committed ``docs/oblivious.json`` (re-certify with
    ``python -m dpf_tpu.analysis --write-oblivious``).

``files`` is accepted for CLI symmetry with the AST passes but ignored
— routes are traced callables, not files.  The pass only runs against
THIS checkout (tracing a foreign tree's routes would import this
checkout's modules and certify the wrong code); a foreign ``--root``
gets a single explanatory finding instead of a misleading pass.
"""

from __future__ import annotations

import os

from .common import Finding, repo_root

PASS = "oblivious-trace"


def run(root: str, files=None) -> list[Finding]:
    if os.path.realpath(root) != os.path.realpath(repo_root()):
        return [
            Finding(
                "dpf_tpu/analysis/trace", 0, PASS,
                "the jaxpr verifier only certifies the checkout it is "
                "imported from; run it from the target tree",
            )
        ]
    from .trace import certify

    certs, taint_findings = certify.verify_routes()
    out: list[Finding] = []
    for route_name, f in taint_findings:
        out.append(
            Finding(
                f"trace://{route_name}", 0, PASS,
                f"[{f.kind}] {f.message} (at {f.where})",
            )
        )
    for msg in certify.drift(root, certs):
        out.append(Finding(certify.OBLIVIOUS_JSON, 0, PASS, msg))
    return out
