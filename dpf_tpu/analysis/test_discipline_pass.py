"""Pass: test-suite wiring discipline (``test-discipline``).

The test surface is wired together by convention — runtests.sh lane
file lists, pytest.ini marker declarations, and the conftest
collection-order hook all reference test files and marker names as bare
strings — and every one of those references fails SILENTLY when it goes
stale: a renamed file just drops out of its lane, an undeclared marker
makes ``-m 'not slow'`` select nothing extra (pytest only warns), and
the PR 8 collection-order hook quietly stops reordering.  Four rules:

  T1  every ``tests/test_*.py`` named in a runtests.sh lane exists on
      disk (a stale lane reference means that lane silently stopped
      running the file — or errors on every invocation).
  T2  runtests.sh keeps a bare ``tests/`` tier-1 lane (the default
      ``set -- tests/ ...``): with it, every on-disk test file is
      reachable from at least one lane; without it, any file missing
      from the named lanes would silently never run.
  T3  every ``pytest.mark.<name>`` used under tests/ is either a pytest
      builtin or declared in pytest.ini's ``markers`` section — an
      undeclared marker is exactly how a "slow" test ends up inside the
      tier-1 wall-clock budget (the ``-m`` filter doesn't know it).
  T4  every ``test_*.py`` file name referenced in tests/conftest.py
      (the collection-order hook) exists — renaming the workload suite
      must not silently turn the hook into a no-op.

Scopes to the scanned root, so tests exercise it on synthetic trees; a
root without runtests.sh (a foreign --root) produces no findings (the
conventions under test are this repo's).
"""

from __future__ import annotations

import ast
import configparser
import os
import re

from .common import Finding, parse_file

PASS = "test-discipline"

_TEST_REF = re.compile(r"tests/test_[A-Za-z0-9_]+\.py")
_TIER1_GLOB = re.compile(r"set\s+--\s+tests/\s")
# Marks pytest owns (plus plugin marks the tree may legitimately use
# without declaring) — everything else must be declared in pytest.ini.
_BUILTIN_MARKS = frozenset(
    {
        "parametrize", "skip", "skipif", "xfail", "usefixtures",
        "filterwarnings", "tryfirst", "trylast",
    }
)


def _declared_markers(root: str) -> set[str] | None:
    """Marker names declared in pytest.ini (None when unreadable)."""
    path = os.path.join(root, "pytest.ini")
    cp = configparser.ConfigParser()
    try:
        with open(path, encoding="utf-8") as f:
            cp.read_file(f)
        raw = cp.get("pytest", "markers")
    except (OSError, configparser.Error):
        return None
    out = set()
    for line in raw.splitlines():
        line = line.strip()
        if line:
            out.add(line.split(":", 1)[0].split("(", 1)[0].strip())
    return out


def _mark_uses(tree: ast.Module) -> list[tuple[str, int]]:
    """(marker name, line) for every ``pytest.mark.<name>`` attribute
    chain (covers decorators, ``pytestmark = ...`` lists, and inline
    ``pytest.mark.slow`` applications)."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "mark"
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == "pytest"
        ):
            out.append((node.attr, node.lineno))
    return out


def run(root: str, files=None) -> list[Finding]:
    runtests = os.path.join(root, "runtests.sh")
    if not os.path.isfile(runtests):
        return []  # foreign root: these are THIS repo's conventions
    with open(runtests, encoding="utf-8") as f:
        lanes_src = f.read()
    out: list[Finding] = []

    # T1: lane references resolve.
    lane_refs = sorted(set(_TEST_REF.findall(lanes_src)))
    for rel in lane_refs:
        if not os.path.isfile(os.path.join(root, rel)):
            line = next(
                i for i, ln in enumerate(lanes_src.splitlines(), 1)
                if rel in ln
            )
            out.append(Finding(
                "runtests.sh", line, PASS,
                f"lane references {rel}, which does not exist — the lane "
                "silently dropped it (renamed or deleted without "
                "re-wiring)",
            ))

    # T2: the tier-1 tests/ glob lane still exists; with it every
    # on-disk file is reachable, without it unlisted files never run.
    disk = sorted(
        f"tests/{fn}" for fn in os.listdir(os.path.join(root, "tests"))
        if fn.startswith("test_") and fn.endswith(".py")
    ) if os.path.isdir(os.path.join(root, "tests")) else []
    if not _TIER1_GLOB.search(lanes_src):
        out.append(Finding(
            "runtests.sh", 0, PASS,
            "the tier-1 'set -- tests/' glob lane is gone — every test "
            "file not named in a specific lane now silently never runs",
        ))
        for rel in disk:
            if rel not in lane_refs:
                out.append(Finding(
                    rel, 0, PASS,
                    "not registered in any runtests.sh lane (and the "
                    "tier-1 tests/ glob is gone)",
                ))

    # T3: marker discipline.
    declared = _declared_markers(root)
    if declared is None:
        out.append(Finding(
            "pytest.ini", 0, PASS,
            "missing or unreadable markers section — every custom "
            "pytest.mark becomes an undeclared (silently ignored by "
            "-m) marker",
        ))
        declared = set()
    for rel in disk:
        try:
            tree, _ = parse_file(root, rel)
        except (OSError, SyntaxError):
            continue
        for name, line in _mark_uses(tree):
            if name not in _BUILTIN_MARKS and name not in declared:
                out.append(Finding(
                    rel, line, PASS,
                    f"pytest.mark.{name} is not declared in pytest.ini — "
                    "-m lane filters silently ignore it, so the marked "
                    "tests land in whatever lane collects them",
                ))

    # T4: conftest file references resolve (the collection-order hook).
    conftest = os.path.join(root, "tests", "conftest.py")
    if os.path.isfile(conftest):
        with open(conftest, encoding="utf-8") as f:
            src = f.read()
        for i, line in enumerate(src.splitlines(), 1):
            for ref in re.findall(r"test_[A-Za-z0-9_]+\.py", line):
                if not os.path.isfile(os.path.join(root, "tests", ref)):
                    out.append(Finding(
                        "tests/conftest.py", i, PASS,
                        f"references {ref}, which does not exist — the "
                        "collection-order hook is a silent no-op for it",
                    ))
    return out
