"""Pass 1: knob registry enforcement.

Three rules, tuned so the acceptance grep ("``environ``/``getenv``
outside knobs.py in dpf_tpu/ returns only allowlisted infra lines")
holds structurally:

  R1  inside the ``dpf_tpu`` package, any READ of ``os.environ`` /
      ``os.getenv`` outside ``core/knobs.py`` is a finding — modules
      read knobs through the registry's typed accessors (env WRITES
      stay legal here too, same as R2).  Allowlisted
      infra: ``parallel/multihost.py`` (multi-host LAUNCHER detection —
      TPU_WORKER_HOSTNAMES / SLURM / OMPI vars, not DPF knobs).
  R2  anywhere in the tree, a READ of a ``DPF_TPU_*`` string literal
      through ``os.environ.get`` / ``os.getenv`` / ``os.environ[...]``
      / ``<dict>.get`` is a finding.  WRITES stay legal everywhere
      (subscript stores, ``.pop``, ``del``, ``monkeypatch.setenv``):
      A/B scripts and tests set knobs for child code; only reading
      outside the registry re-creates scattered defaults.
  R3  anywhere in the tree, a string literal that IS a ``DPF_TPU_*``
      name but is not declared in the registry is a finding — the typo
      catcher (``DPF_TPU_BATCH_WINDOW_MS`` can no longer fail silent
      anywhere: not in code, not in tests, not in A/B scripts).

``# knob-ok`` on the line suppresses R2/R3 (the lint suite's own tests
must spell typo'd names on purpose).

One violating line usually trips several rules on nested AST nodes (the
``os.environ`` attribute, the enclosing ``.get`` call, the name
literal); findings collapse to the most specific rule per line
(R3 typo > R2 direct read > R1 generic access) so the CLI count matches
the violation count.
"""

from __future__ import annotations

import ast
import re

from ..core import knobs
from .common import (
    Finding, import_aliases, in_scope, iter_py_files, parse_file, pragma,
    resolve_dotted,
)

PASS = "knob-registry"

_KNOB_RE = re.compile(r"DPF_TPU_[A-Z0-9_]+")

# R1 scope and its allowlist (repo-relative, forward slashes).
_PACKAGE = ("dpf_tpu",)
_REGISTRY_FILE = "dpf_tpu/core/knobs.py"
_INFRA_ALLOWLIST = ("dpf_tpu/parallel/multihost.py",)

# Env-write method names: legal everywhere (setting knobs for child
# processes / subtests is how A/Bs work; reading them back is not).
_WRITE_METHODS = {"pop", "setdefault", "setenv", "delenv", "update"}


def _is_os_environ(node: ast.AST, aliases: dict[str, str]) -> bool:
    """os.environ in ANY imported spelling: ``os.environ``, ``o.environ``
    (import os as o), or a bare ``environ`` (from os import environ)."""
    return resolve_dotted(node, aliases) == "os.environ"


def _knob_literal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if _KNOB_RE.fullmatch(node.value):
            return node.value
    return None


def check_file(root: str, rel: str) -> list[Finding]:
    tree, lines = parse_file(root, rel)
    rel_fwd = rel.replace("\\", "/")
    if rel_fwd == _REGISTRY_FILE:
        return []
    # (specificity, finding); collapsed to the best rule per line below.
    raw: list[tuple[int, Finding]] = []
    in_package = in_scope(rel_fwd, _PACKAGE)
    allow_infra = rel_fwd in _INFRA_ALLOWLIST
    aliases = import_aliases(tree)

    # os.environ nodes in WRITE position (subscript store/del, .pop/
    # .update/... calls) — legal everywhere, R1 skips them.
    env_writes: set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Subscript)
            and not isinstance(node.ctx, ast.Load)
            and _is_os_environ(node.value, aliases)
        ):
            env_writes.add(id(node.value))
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _WRITE_METHODS
            and _is_os_environ(node.value, aliases)
        ):
            env_writes.add(id(node.value))

    for node in ast.walk(tree):
        # R1: os.environ / os.getenv READS anywhere in the package, in
        # any imported spelling (incl. `from os import environ/getenv`).
        if in_package and not allow_infra and id(node) not in env_writes:
            if _is_os_environ(node, aliases) or (
                resolve_dotted(node, aliases) == "os.getenv"
            ):
                raw.append((
                    2,
                    Finding(
                        rel, node.lineno, PASS,
                        "direct environment access inside dpf_tpu/ — "
                        "declare the knob in core/knobs.py and read it "
                        "through the typed accessors",
                    ),
                ))
                continue

        # R2: reads of DPF_TPU_* literals through env getters — attribute
        # getters on any object (`<dict>.get`) or a bare imported getenv.
        if isinstance(node, ast.Call):
            fn = node.func
            if node.args and (
                (isinstance(fn, ast.Attribute) and fn.attr in ("get", "getenv"))
                or resolve_dotted(fn, aliases) == "os.getenv"
            ):
                name = _knob_literal(node.args[0])
                if name and pragma(lines, node.lineno, "knob-ok") is None:
                    raw.append((
                        1,
                        Finding(
                            rel, node.lineno, PASS,
                            f"direct env read of {name} — go through "
                            "dpf_tpu.core.knobs",
                        ),
                    ))
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            if _is_os_environ(node.value, aliases):
                name = _knob_literal(node.slice)
                if name and pragma(lines, node.lineno, "knob-ok") is None:
                    raw.append((
                        1,
                        Finding(
                            rel, node.lineno, PASS,
                            f"direct env read of {name} — go through "
                            "dpf_tpu.core.knobs",
                        ),
                    ))

        # R3: undeclared DPF_TPU_* names anywhere (the typo catcher).
        name = _knob_literal(node)
        if name and name not in knobs.REGISTRY:
            if pragma(lines, node.lineno, "knob-ok") is None:
                raw.append((
                    0,
                    Finding(
                        rel, node.lineno, PASS,
                        f"{name} is not declared in dpf_tpu/core/knobs.py "
                        "(typo, or a new knob missing its declaration)",
                    ),
                ))

    best: dict[int, int] = {}
    for spec, f in raw:
        best[f.line] = min(best.get(f.line, spec), spec)
    return list(dict.fromkeys(
        f for spec, f in raw if spec == best[f.line]
    ))


def run(root: str, files=None) -> list[Finding]:
    files = list(files) if files is not None else list(iter_py_files(root))
    out: list[Finding] = []
    for rel in files:
        try:
            out.extend(check_file(root, rel))
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS, f"syntax error: {e}"))
    return out
