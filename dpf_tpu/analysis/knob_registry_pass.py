"""Pass 1: knob registry enforcement.

Three rules, tuned so the acceptance grep ("``environ``/``getenv``
outside knobs.py in dpf_tpu/ returns only allowlisted infra lines")
holds structurally:

  R1  inside the ``dpf_tpu`` package, any READ of ``os.environ`` /
      ``os.getenv`` outside ``core/knobs.py`` is a finding — modules
      read knobs through the registry's typed accessors (env WRITES
      stay legal here too, same as R2).  Allowlisted
      infra: ``parallel/multihost.py`` (multi-host LAUNCHER detection —
      TPU_WORKER_HOSTNAMES / SLURM / OMPI vars, not DPF knobs).
  R2  anywhere in the tree, a READ of a ``DPF_TPU_*`` string literal
      through ``os.environ.get`` / ``os.getenv`` / ``os.environ[...]``
      / ``<dict>.get`` is a finding.  WRITES stay legal everywhere
      (subscript stores, ``.pop``, ``del``, ``monkeypatch.setenv``):
      A/B scripts and tests set knobs for child code; only reading
      outside the registry re-creates scattered defaults.
  R3  anywhere in the tree, a string literal that IS a ``DPF_TPU_*``
      name but is not declared in the registry is a finding — the typo
      catcher (``DPF_TPU_BATCH_WINDOW_MS`` can no longer fail silent
      anywhere: not in code, not in tests, not in A/B scripts).

  R4  (whole-tree scans only) every knob DECLARED in the registry is
      READ somewhere: a declared ``DPF_TPU_*`` name that no non-fixture
      file in the tree mentions outside its declaration is a finding —
      dead knobs accumulate as the registry grows past 45 entries, and
      a knob nobody reads is a documentation lie (docs/KNOBS.md keeps
      advertising it).  ``# knob-unused-ok`` on (or above) the
      ``_declare(...)`` line in core/knobs.py is the reviewed escape
      hatch for knobs that are intentionally declaration-only.

``# knob-ok`` on the line suppresses R2/R3 (the lint suite's own tests
must spell typo'd names on purpose).

One violating line usually trips several rules on nested AST nodes (the
``os.environ`` attribute, the enclosing ``.get`` call, the name
literal); findings collapse to the most specific rule per line
(R3 typo > R2 direct read > R1 generic access) so the CLI count matches
the violation count.
"""

from __future__ import annotations

import ast
import re

from ..core import knobs
from .common import (
    Finding, import_aliases, in_scope, iter_py_files, parse_file, pragma,
    resolve_dotted,
)

PASS = "knob-registry"

_KNOB_RE = re.compile(r"DPF_TPU_[A-Z0-9_]+")

# R1 scope and its allowlist (repo-relative, forward slashes).
_PACKAGE = ("dpf_tpu",)
_REGISTRY_FILE = "dpf_tpu/core/knobs.py"
_INFRA_ALLOWLIST = ("dpf_tpu/parallel/multihost.py",)

# Env-write method names: legal everywhere (setting knobs for child
# processes / subtests is how A/Bs work; reading them back is not).
_WRITE_METHODS = {"pop", "setdefault", "setenv", "delenv", "update"}


def _is_os_environ(node: ast.AST, aliases: dict[str, str]) -> bool:
    """os.environ in ANY imported spelling: ``os.environ``, ``o.environ``
    (import os as o), or a bare ``environ`` (from os import environ)."""
    return resolve_dotted(node, aliases) == "os.environ"


def _knob_literal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if _KNOB_RE.fullmatch(node.value):
            return node.value
    return None


def check_file(root: str, rel: str) -> list[Finding]:
    tree, lines = parse_file(root, rel)
    return _check_tree(rel, tree, lines)


def _check_tree(rel: str, tree: ast.Module, lines: list[str]) -> list[Finding]:
    rel_fwd = rel.replace("\\", "/")
    if rel_fwd == _REGISTRY_FILE:
        return []
    # (specificity, finding); collapsed to the best rule per line below.
    raw: list[tuple[int, Finding]] = []
    in_package = in_scope(rel_fwd, _PACKAGE)
    allow_infra = rel_fwd in _INFRA_ALLOWLIST
    aliases = import_aliases(tree)

    # os.environ nodes in WRITE position (subscript store/del, .pop/
    # .update/... calls) — legal everywhere, R1 skips them.
    env_writes: set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Subscript)
            and not isinstance(node.ctx, ast.Load)
            and _is_os_environ(node.value, aliases)
        ):
            env_writes.add(id(node.value))
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _WRITE_METHODS
            and _is_os_environ(node.value, aliases)
        ):
            env_writes.add(id(node.value))

    for node in ast.walk(tree):
        # R1: os.environ / os.getenv READS anywhere in the package, in
        # any imported spelling (incl. `from os import environ/getenv`).
        if in_package and not allow_infra and id(node) not in env_writes:
            if _is_os_environ(node, aliases) or (
                resolve_dotted(node, aliases) == "os.getenv"
            ):
                raw.append((
                    2,
                    Finding(
                        rel, node.lineno, PASS,
                        "direct environment access inside dpf_tpu/ — "
                        "declare the knob in core/knobs.py and read it "
                        "through the typed accessors",
                    ),
                ))
                continue

        # R2: reads of DPF_TPU_* literals through env getters — attribute
        # getters on any object (`<dict>.get`) or a bare imported getenv.
        if isinstance(node, ast.Call):
            fn = node.func
            if node.args and (
                (isinstance(fn, ast.Attribute) and fn.attr in ("get", "getenv"))
                or resolve_dotted(fn, aliases) == "os.getenv"
            ):
                name = _knob_literal(node.args[0])
                if name and pragma(lines, node.lineno, "knob-ok") is None:
                    raw.append((
                        1,
                        Finding(
                            rel, node.lineno, PASS,
                            f"direct env read of {name} — go through "
                            "dpf_tpu.core.knobs",
                        ),
                    ))
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            if _is_os_environ(node.value, aliases):
                name = _knob_literal(node.slice)
                if name and pragma(lines, node.lineno, "knob-ok") is None:
                    raw.append((
                        1,
                        Finding(
                            rel, node.lineno, PASS,
                            f"direct env read of {name} — go through "
                            "dpf_tpu.core.knobs",
                        ),
                    ))

        # R3: undeclared DPF_TPU_* names anywhere (the typo catcher).
        name = _knob_literal(node)
        if name and name not in knobs.REGISTRY:
            if pragma(lines, node.lineno, "knob-ok") is None:
                raw.append((
                    0,
                    Finding(
                        rel, node.lineno, PASS,
                        f"{name} is not declared in dpf_tpu/core/knobs.py "
                        "(typo, or a new knob missing its declaration)",
                    ),
                ))

    best: dict[int, int] = {}
    for spec, f in raw:
        best[f.line] = min(best.get(f.line, spec), spec)
    return list(dict.fromkeys(
        f for spec, f in raw if spec == best[f.line]
    ))


def _declaration_lines(root: str) -> dict[str, tuple[int, list[str]]]:
    """knob name -> (declaration line in core/knobs.py, source lines) for
    every ``_declare("DPF_TPU_...", ...)`` call — where R4's findings
    anchor and where its ``# knob-unused-ok`` pragma is looked up."""
    try:
        tree, lines = parse_file(root, _REGISTRY_FILE)
    except (OSError, SyntaxError):
        return {}
    out: dict[str, tuple[int, list[str]]] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_declare"
            and node.args
        ):
            name = _knob_literal(node.args[0])
            if name:
                out[name] = (node.lineno, lines)
    return out


def _knob_mentions(tree: ast.Module) -> set[str]:
    """Every DPF_TPU_* string literal in one parsed file (comments do
    not count — a knob mentioned only in prose is still dead)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        name = _knob_literal(node)
        if name:
            out.add(name)
    return out


def unused_knobs(
    root: str, files: list[str], seen: set[str] | None = None
) -> list[Finding]:
    """R4: knobs the SCANNED TREE declares (parsed from its own
    core/knobs.py ``_declare`` calls — never the imported process
    registry, so a foreign --root is judged against its own
    declarations) that no scanned file reads.  A knob counts as used
    when ANY non-fixture file other than the registry itself mentions
    its name as a string literal (typed-accessor reads, ledger snapshot
    lists, A/B env writes — all legitimate liveness).  Trees without a
    core/knobs.py produce no R4 findings.  ``seen`` lets run() feed the
    mention set it already collected on its single parse of the tree."""
    decls = _declaration_lines(root)
    if not decls:
        return []
    if seen is None:
        seen = set()
        for rel in files:
            if rel.replace("\\", "/") == _REGISTRY_FILE:
                continue
            try:
                tree, _lines = parse_file(root, rel)
            except (OSError, SyntaxError):
                continue
            seen |= _knob_mentions(tree)
    out: list[Finding] = []
    for name in sorted(set(decls) - seen):
        lineno, lines = decls[name]
        if pragma(lines, lineno, "knob-unused-ok") is not None:
            continue
        out.append(Finding(
            _REGISTRY_FILE, lineno, PASS,
            f"{name} is declared but no non-fixture module reads it — "
            "delete the dead knob, or mark the declaration "
            "'# knob-unused-ok' with a reason",
        ))
    return out


def run(root: str, files=None) -> list[Finding]:
    whole_tree = files is None
    files = list(files) if files is not None else list(iter_py_files(root))
    out: list[Finding] = []
    seen: set[str] = set()
    for rel in files:
        try:
            tree, lines = parse_file(root, rel)
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS, f"syntax error: {e}"))
            continue
        out.extend(_check_tree(rel, tree, lines))
        if whole_tree and rel.replace("\\", "/") != _REGISTRY_FILE:
            # R4's mention set comes off the SAME parse as R1-R3 — one
            # whole-tree AST walk total, not one per rule family.
            seen |= _knob_mentions(tree)
    if whole_tree:
        # R4 is a registry-vs-tree property: it only means something when
        # the scan saw the whole tree (a fixture-subset scan would flag
        # every knob).
        out.extend(unused_knobs(root, files, seen=seen))
    return out
