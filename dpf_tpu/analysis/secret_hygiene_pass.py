"""Pass 2: secret-hygiene taint check.

Key material in this codebase — PRG seeds, GGM correction words, raw
request key bytes — is secret-shared cryptographic state: one byte of it
in a log line, an exception string, a stats payload, or a bench ledger
breaks the two-party privacy guarantee just as surely as a wrong kernel.
Like the constant-time discipline of cryptographic kernels, this is a
STRUCTURAL property, checkable statically on every commit.

Mechanics (deliberately simple so the result is auditable): name-based,
intra-function forward taint.

  sources    identifiers and attributes with secret names — ``seeds``,
             ``scw``/``tcw``/``vcw``/``fcw`` (and their packed/
             transposed variants), raw key blobs (``blob``,
             ``key_bytes``), parsed key batches (``ka``/``kb``/...).
             Assignments propagate: ``x = kb.seeds`` taints ``x``.
  sinks      logging/warnings/print calls; f-strings (or %/.format)
             inside ``raise``; return values of stats-shaped functions
             (``stats``/``stats_dict``/``stats_snapshot``/``as_dict``
             — the /v1/stats surface AND the /v1/trace payload, which
             is built from ``as_dict`` trees); calls whose name mentions
             the bench ``ledger``; error-reply calls (``_bad`` /
             ``_reply_error`` / ``send_error`` — the sidecar's 4xx/5xx
             bodies cross the bridge to the OTHER party, so request key
             bytes in one break the two-server trust split); telemetry
             calls (``set_attrs`` / ``add_span`` / ``add_event`` /
             ``child_span`` / ``observe_phase`` / ``observe_coalesce``
             and the metrics renderer's ``sample``/``histogram`` — span
             attributes and metric labels are exported verbatim by
             ``/v1/trace`` and ``/v1/metrics``).
  sanitizers subtrees that reduce a secret to public data stop the
             taint: ``len()``/``type()``, shape/count attributes
             (``.shape``, ``.k``, ``.log_n``, ...), and ``hashlib``
             digests — the sha256 key digest in ``serving/keycache.py``
             is the sanctioned way to index on key bytes.

False-negative honesty: this does not track flow through calls or
containers; it pins the failure modes the serving surface actually has
(a debug log of a key batch, a ValueError embedding request bytes, a
stats counter built from key material) and the fixture tests keep it
catching them.
"""

from __future__ import annotations

import ast

from .common import Finding, in_scope, iter_py_files, parse_file

PASS = "secret-hygiene"

# Scope: everything in the package (key material lives in core/keys,
# models/keys_chacha, models/dcf, and flows through serving + server).
_SCOPE = ("dpf_tpu",)

# Exact identifier / attribute names that ARE key material in this tree.
# Includes the device-cached per-key lane masks (models/dpf._point_masks)
# and the walk kernels' transposed operands — all derived from seeds/CWs
# and exactly as secret as the bytes they pack.
SECRET_NAMES = frozenset(
    {
        "seed", "seeds", "seed_planes", "seeds_t", "seeds_bm",
        "seed_masks", "t_masks",
        "scw", "scw_planes", "scw_t", "scw_bm", "scw_p", "scw_packed",
        "scw_masks",
        "tcw", "tcw_t", "tcw_p", "tlcw", "trcw", "tl_w", "tr_w",
        "tl_words", "tr_words", "t_words", "tl_masks", "tr_masks",
        "fcw", "fcw_planes", "fcw_t", "fcw_p", "fcw_canon", "fcw_masks",
        "vcw", "vcw_t", "fvcw", "fvcw_t",
        "key_bytes", "key_blob", "key_material", "raw_key", "blob",
        "ka", "kb", "kbp", "kb_s",
        # Frontier-cache resident state (apps/hh_state.FrontierState): the
        # carried seed/control-bit tuple and the converted leaf planes are
        # live PRG seeds at the surviving-prefix frontier — exactly as
        # secret as the key batch they were expanded from.
        "seed_state", "planes", "_seeds", "_ts", "_scw", "_tcw", "_fcw",
        "_fcw_words",
    }
)

# Attribute accesses that reduce a secret to public metadata.
PUBLIC_ATTRS = frozenset(
    {
        "shape", "dtype", "nbytes", "size", "ndim", "k", "log_n",
        "stats", "stats_dict", "as_dict",
    }
)
_SANITIZER_FUNCS = frozenset({"len", "type", "id", "bool"})
_STATS_FUNCS = frozenset({"stats", "stats_dict", "stats_snapshot", "as_dict"})
_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical",
     "log"}
)
# Error-reply surfaces (server.py): anything in their arguments becomes
# an HTTP error body on the wire.
_ERROR_REPLY_FUNCS = frozenset({"_bad", "_reply_error", "send_error"})
# Telemetry surfaces (dpf_tpu/obs): span attributes, recorded spans/
# events, and metric label/sample arguments are exported verbatim by
# GET /v1/trace and GET /v1/metrics — public metadata only.
_TELEMETRY_FUNCS = frozenset(
    {
        "set_attrs", "add_span", "add_event", "child_span",
        "observe_phase", "observe_coalesce", "sample", "histogram",
    }
)


def _is_sanitizer_call(node: ast.Call) -> bool:
    """len()/type()-style reductions and hashlib digests — e.g.
    ``hashlib.sha256(blob).digest()``, the keycache's sanctioned key
    index."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in _SANITIZER_FUNCS or fn.id in ("sha256", "blake2b")
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
    elif isinstance(fn, ast.Call) and _is_sanitizer_call(fn):
        return True
    return bool({"hashlib", "sha256", "blake2b"} & set(parts))


# Calls whose result IS their (secret) input in another shape — taint
# flows through these on assignment; any other call's result is treated
# as derived/public (a return code, a length, a parsed header), which
# keeps the pass auditable.  Sink checks descend through every call.
_PROPAGATING_CALLS = frozenset(
    {
        "bytes", "bytearray", "memoryview", "tobytes", "to_bytes",
        "asarray", "ascontiguousarray", "array", "frombuffer", "copy",
        "view", "reshape", "astype", "concatenate", "stack", "transpose",
        "hex", "join",
    }
)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _secret_in(
    node: ast.AST, tainted: set[str], through_calls: bool = True
) -> str | None:
    """The first secret name mentioned in ``node`` (skipping sanitized
    subtrees), or None.  ``through_calls=False`` is the assignment-
    propagation mode: taint survives only shape/byte-preserving calls."""
    if isinstance(node, ast.Call):
        if _is_sanitizer_call(node):
            return None
        if not through_calls and _call_name(node) not in _PROPAGATING_CALLS:
            return None
    if isinstance(node, ast.Attribute):
        if node.attr in PUBLIC_ATTRS:
            return None  # kb.k, kb.shape, cache.stats() — public metadata
        if node.attr in SECRET_NAMES:
            return node.attr
        return _secret_in(node.value, tainted, through_calls)
    if isinstance(node, ast.Name):
        if node.id in SECRET_NAMES or node.id in tainted:
            return node.id
        return None
    for child in ast.iter_child_nodes(node):
        hit = _secret_in(child, tainted, through_calls)
        if hit:
            return hit
    return None


def _is_log_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "print"
    if isinstance(fn, ast.Attribute):
        if fn.attr not in _LOG_METHODS:
            return False
        base = fn.value
        return isinstance(base, ast.Name) and (
            base.id in ("logging", "warnings")
            or "log" in base.id.lower()
        )
    return False


def _is_ledger_call(node: ast.Call) -> bool:
    fn = node.func
    name = ""
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    return "ledger" in name.lower()


def _formatted_secret(node: ast.AST, tainted: set[str]) -> str | None:
    """A secret inside a string-formatting expression (f-string, %, or
    .format) anywhere under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.JoinedStr):
            hit = _secret_in(sub, tainted)
            if hit:
                return hit
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod):
            hit = _secret_in(sub.right, tainted)
            if hit:
                return hit
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "format"
        ):
            hit = _secret_in(sub, tainted)
            if hit:
                return hit
    return None


def _taint_target(tgt: ast.AST, tainted: set[str]) -> None:
    """Taint the names an assignment target binds.  For ``arr[i] = s``
    the container ``arr`` is tainted, the index ``i`` is not."""
    if isinstance(tgt, ast.Name):
        tainted.add(tgt.id)
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            _taint_target(e, tainted)
    elif isinstance(tgt, ast.Starred):
        _taint_target(tgt.value, tainted)
    elif isinstance(tgt, ast.Subscript):
        _taint_target(tgt.value, tainted)
    # Attribute targets (self.x = ...) are covered by SECRET_NAMES on
    # the attribute read side.


def _scope_walk(body: list[ast.stmt]):
    """Every node of this scope, in source order, WITHOUT descending
    into nested function/class scopes (each gets its own taint set —
    sharing one across a whole class body cross-contaminates methods)."""
    stack = list(reversed(body))
    while stack:
        node = stack.pop()
        yield node
        for child in reversed(list(ast.iter_child_nodes(node))):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                continue
            stack.append(child)


def _check_scope(rel: str, body: list[ast.stmt], params: set[str],
                 func_name: str, out: list[Finding]) -> None:
    tainted = set(params & SECRET_NAMES)

    for sub in _scope_walk(body):
        # Propagate taint through simple assignments, in source order.
        if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = sub.value
            if value is not None and _secret_in(
                value, tainted, through_calls=False
            ):
                targets = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for tgt in targets:
                    _taint_target(tgt, tainted)

        elif isinstance(sub, ast.Call):
            if (
                _is_log_call(sub) or _is_ledger_call(sub)
                or _call_name(sub) in _ERROR_REPLY_FUNCS
                or _call_name(sub) in _TELEMETRY_FUNCS
            ):
                if _is_log_call(sub):
                    where = "logging/console"
                elif _is_ledger_call(sub):
                    where = "bench ledger"
                elif _call_name(sub) in _ERROR_REPLY_FUNCS:
                    where = "an error-reply body"
                else:
                    where = (
                        "telemetry (span attrs / metric labels are "
                        "exported by /v1/trace and /v1/metrics)"
                    )
                for arg in list(sub.args) + [
                    kw.value for kw in sub.keywords
                ]:
                    hit = _secret_in(arg, tainted)
                    if hit:
                        out.append(
                            Finding(
                                rel, sub.lineno, PASS,
                                f"secret {hit!r} flows into {where} "
                                "(key material must never leave the "
                                "computation)",
                            )
                        )
                        break

        elif isinstance(sub, ast.Raise) and sub.exc is not None:
            hit = _formatted_secret(sub.exc, tainted)
            if hit:
                out.append(
                    Finding(
                        rel, sub.lineno, PASS,
                        f"secret {hit!r} formatted into a raised "
                        "exception (error strings cross the bridge "
                        "as HTTP 400 bodies)",
                    )
                )

        elif (
            isinstance(sub, ast.Return)
            and sub.value is not None
            and func_name in _STATS_FUNCS
        ):
            hit = _secret_in(sub.value, tainted)
            if hit:
                out.append(
                    Finding(
                        rel, sub.lineno, PASS,
                        f"secret {hit!r} reaches the return value of "
                        f"stats surface {func_name}() "
                        "(/v1/stats payload)",
                    )
                )


def check_file(root: str, rel: str) -> list[Finding]:
    tree, _ = parse_file(root, rel)
    out: list[Finding] = []
    # Module level counts as one scope; every function is its own (the
    # scope walks descend into nested defs, so findings can repeat —
    # deduped below rather than complicating the walk).
    _check_scope(rel, tree.body, set(), "<module>", out)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _check_scope(rel, node.body, set(), "<class>", out)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            params = {
                a.arg
                for a in (
                    args.posonlyargs + args.args + args.kwonlyargs
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else [])
                )
            }
            _check_scope(rel, node.body, params, node.name, out)
    return list(dict.fromkeys(out))


def run(root: str, files=None) -> list[Finding]:
    if files is None:
        files = [f for f in iter_py_files(root) if in_scope(f, _SCOPE)]
    out: list[Finding] = []
    for rel in files:
        try:
            out.extend(check_file(root, rel))
        except SyntaxError as e:
            out.append(Finding(rel, e.lineno or 0, PASS, f"syntax error: {e}"))
    return out
