"""Shared plumbing for the analysis passes: findings, tree walking,
pragma comments.

Pragmas are how the passes express ALLOWLISTED exceptions in-place, next
to the code they cover (reviewable, greppable, and they travel with the
line in refactors — unlike a path/line table in the linter):

  ``# host-sync: <why>``  on (or immediately above) a host-sync call —
      an allowlisted synchronization point.
  ``# vmem: <expr>``      on (or immediately above) a pl.pallas_call —
      the statically-evaluated VMEM footprint model for that kernel.
  ``# knob-ok``           on a line mentioning a DPF_TPU_* name the
      knob-registry pass should skip (used by the lint suite's own
      tests, which must spell typo'd knob names on purpose).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    pass_name: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"


def repo_root() -> str:
    """The tree the passes scan by default: the directory containing the
    ``dpf_tpu`` package (repo root in a checkout)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


_SKIP_DIRS = {"__pycache__", ".git", ".claude", "tpu_logs", "node_modules"}
_FIXTURES = os.path.join("dpf_tpu", "analysis", "fixtures")


def iter_py_files(
    root: str, include_fixtures: bool = False
) -> Iterator[str]:
    """Yield repo-relative paths of every .py file under ``root``,
    skipping caches and (by default) the seeded-violation fixtures."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        rel_dir = os.path.relpath(dirpath, root)
        if not include_fixtures and rel_dir.startswith(_FIXTURES):
            continue
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.normpath(os.path.join(rel_dir, fn))


def parse_file(root: str, rel: str) -> tuple[ast.Module, list[str]]:
    """-> (ast.Module, source lines).  Syntax errors become a one-line
    finding upstream; here they just raise."""
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return ast.parse(src, filename=rel), src.splitlines()


def pragma(lines: list[str], lineno: int, tag: str) -> str | None:
    """The pragma payload for AST line ``lineno`` (1-based): looks on the
    node's own line then the line above, returns the text after the tag
    (may be empty) or None when absent.  The line above only counts when
    it is a comment-only line — a trailing pragma on the previous CODE
    line sanctions that line, not this one."""
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(lines):
            continue
        text = lines[ln - 1]
        if ln != lineno and not text.lstrip().startswith("#"):
            continue
        idx = text.find("# " + tag)
        if idx >= 0:
            return text[idx + len(tag) + 2 :].strip()
    return None


def import_aliases(tree: ast.Module) -> dict[str, str]:
    """Local name -> fully dotted origin for every import binding:
    ``import os`` (os -> os), ``import numpy as np`` (np -> numpy),
    ``from os import getenv as ge`` (ge -> os.getenv).  The passes
    resolve call targets through this so aliased forms (``from os import
    getenv``; ``from jax import device_get``) cannot slip past matching
    that only knew the fully-qualified spelling."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolve_dotted(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """The import-resolved dotted origin of a Name/Attribute chain
    (``ge`` -> 'os.getenv', ``pl.pallas_call`` ->
    'jax.experimental.pallas.pallas_call'), or None when the base name
    is not an import binding."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = aliases.get(node.id)
    if base is None:
        return None
    parts.append(base)
    return ".".join(reversed(parts))


def in_scope(rel: str, prefixes: tuple[str, ...]) -> bool:
    rel = rel.replace(os.sep, "/")
    return any(
        rel == p or rel.startswith(p if p.endswith("/") else p + "/")
        for p in prefixes
    )


def dotted_module(rel: str) -> str | None:
    """Repo-relative path -> importable dotted name, for files inside the
    dpf_tpu package; None for everything else (scripts, tests,
    fixtures)."""
    rel = rel.replace(os.sep, "/")
    if not rel.startswith("dpf_tpu/") or "fixtures/" in rel:
        return None
    mod = rel[: -len(".py")].replace("/", ".")
    return mod[: -len(".__init__")] if mod.endswith(".__init__") else mod
