"""DPF evaluation sidecar: the framework's serving / language bridge.

The reference is a Go library consumed in-process (dpf_main.go:6 imports
``github.com/dkales/dpf-go/dpf``).  The TPU framework's evaluator lives in a
Python/JAX process, so foreign-language clients (the reference's Go
programs, C++ services, ...) reach it through this sidecar instead: a tiny
HTTP/1.1 server speaking raw key bytes in and raw result bytes out — the
same keys-as-bytes wire contract as the reference (``type DPFkey []byte``,
dpf/dpf.go:7), so a Go client is ~20 lines of net/http with no codegen.

Endpoints (all POST, binary bodies, profile/params in the query string):

  /v1/gen?log_n=N[&alpha=A][&profile=fast]   -> key_a || key_b
  /v1/eval?log_n=N&x=X[&profile=fast]        body: one key  -> 1 byte (0/1)
  /v1/evalfull?log_n=N[&profile=fast][&stream=0|1]
        body: one key  -> bit-packed bytes.  ``stream=1`` (or the
        DPF_TPU_STREAM=on|auto default, auto streaming responses >=
        DPF_TPU_STREAM_MIN_BYTES) writes the response progressively from
        the double-buffered chunked expansion: each subtree chunk's
        bytes go onto the socket while the next chunk computes, so
        time-to-first-byte is ~one chunk instead of the whole tree.
        Content-Length is always exact; the byte stream is identical to
        the blocking reply, so clients need no changes.
  /v1/evalfull_batch?log_n=N&k=K[&profile=fast]
        body: K concatenated keys -> K concatenated expansions
  /v1/eval_points_batch?log_n=N&k=K&q=Q[&profile=fast][&format=packed]
        body: K concatenated keys || K*Q little-endian uint64 indices
        -> K*Q bytes of 0/1 bits (row-major [K, Q]); with format=packed,
           K rows of ceil(Q/8) bit-packed bytes instead (bit j of row i at
           byte j//8, bit j%8 LSB-first — the /v1/evalfull convention and
           the reference's, dpf/dpf.go:207-209; tail bits zero) — an 8x
           cut of the dominant serving-traffic response
  /v1/dcf_gen?log_n=N&k=K                     body: K uint64 alphas
        -> K DCF keys (party A) || K DCF keys (party B)  (fast profile)
  /v1/dcf_eval_points?log_n=N&k=K&q=Q[&format=packed]
        body: keys || uint64 indices
        -> K*Q comparison-share bits (models/dcf.py; one key per gate),
           or K * ceil(Q/8) packed bytes with format=packed
  /v1/dcf_interval_gen?log_n=N&k=K            body: K uint64 lo || K uint64 hi
        -> party A blob || party B blob, each 2K DCF keys (upper, lower)
           || K public const bytes
  /v1/dcf_interval_eval?log_n=N&k=K&q=Q[&format=packed]
        body: one party blob || indices
        -> K*Q interval-share bits (1{lo <= x <= hi} after XOR), or
           K * ceil(Q/8) packed bytes with format=packed
  /v1/hh/gen?log_n=N&k=K[&profile=fast]       body: K uint64 client values
        -> share blob A || share blob B (trusted-dealer helper for the
           prefix-tree heavy-hitters protocol, apps/heavy_hitters.py;
           each blob is K clients x log_n level keys, client-major)
  /v1/hh/eval?log_n=N&k=K&q=Q&level=L[&profile=fast][&format=packed]
        body: K level-L client keys (key_len bytes each) || Q uint64
        candidate prefixes (ONE shared set, depth L+1 shifted up to n
        bits — uploaded once, not per key)
        -> K*Q share bits [client, candidate] (packed: K rows of
           ceil(Q/8) bytes) — the single-aggregator round primitive;
           two aggregators' replies XOR+popcount into public counts
  /v1/agg/submit?op=xor|add&k=K&words=W       body: K rows x W uint32
        -> the W folded uint32 words (secure aggregation,
           apps/aggregation.py).  The body is read AND folded in
           DPF_TPU_AGG_CHUNK_BYTES chunks — a million-client upload
           never materializes on host.
  /v1/pir/db?name=X&rows=N&row_bytes=B[&profile=fast]
        body: N rows x B bytes — register (or replace) a named PIR
        database (apps/pir_store.py).  The body is read off the socket
        in DPF_TPU_PIR_DB_CHUNK_BYTES chunks straight into the packed
        host buffer; the rows then live device-resident — sharded over
        the chip mesh's HBM when DPF_TPU_MESH resolves — until replaced.
        Replies JSON {name, rows, row_bytes, log_n, db_bytes, shards,
        stream_chunks}.  The DB is PUBLIC protocol data (both PIR
        servers hold identical copies); the query is the secret.
  /v1/pir/query?db=X&k=K                      body: K concatenated DPF
        keys (the database's profile) -> K rows x row_bytes answer
        bytes: each query's XOR of the selected database rows, computed
        as chunked int8/int32 MXU matmuls over the resident DB
        (models/pir.py).  XOR the two servers' replies to reconstruct
        the rows.  Concurrent queries coalesce into ONE
        selection-matrix matmul (the scan cost is the database pass,
        so batch-mates ride it as extra MXU rows); databases past
        DPF_TPU_PIR_DB_CHUNK_BYTES answer through the streamed chunk
        scan, byte-identically.
  /v1/warmup                                  body: JSON
        {"shapes": [{"route": "points"|"dcf_points"|"dcf_interval"|
        "evalfull"|"hh_level"|"agg_xor"|"agg_add"|"pir", "profile":
        "compat"|"fast", "log_n": N, "k": K,
        "q": Q}, ...]} — compile the dispatch plans for those shapes NOW
        (core/plans.py) so first-request compile never lands on user
        traffic.  An evalfull spec with "stream": true also warms the
        streaming pipeline's per-chunk executables (distinct compiles);
        a pir spec names a REGISTERED database ({"route": "pir", "db":
        name, "k": K} — log_n/profile come from the registry) and warms
        its scan executables for the current mesh regime.
        Replies JSON with per-shape compile seconds.
  /healthz                                    -> "ok" (liveness ONLY:
        200 while the process serves, regardless of breaker/warmup)
  /readyz (GET)                               -> readiness: 200 "ready",
        or 503 {code:"breaker_open"} while the circuit breaker is not
        closed / {code:"cold"} until the first POST /v1/warmup — load
        generators (bridge/go/cmd/loadgen -wait-ready) hold fire on it
  /v1/stats (GET)                             -> JSON observability:
        plan-cache hit/miss + live trace count, micro-batcher
        coalescing (requests, dispatches, batch_coalesced mean/max,
        queue-wait, live queue_depth) plus load-survival counters
        (shed_depth/shed_age, expired_queue vs expired_flight, dispatch
        EWMA), key-repack LRU hits, circuit-breaker state
        (closed|open|half_open, trips, retries, fast-fails), active
        fault-injection clauses (when any), flight-recorder ring state,
        and per-phase timers (queue_wait, pack, dispatch, compute, d2h,
        reply — utils/profiling.PhaseTimer).  The whole payload is ONE
        critical section under a single stats lock — never a torn read.
  /v1/metrics (GET)                           -> the same snapshot in
        Prometheus text format (obs/metrics.py): counters (sheds,
        expirations, breaker transitions, plan compiles, keycache hits),
        gauges (queue depth, breaker state, per-device memory), and
        fixed-bucket histograms for per-phase latency + coalesce size
        (DPF_TPU_METRICS_BUCKETS_MS).  Counter equality with /v1/stats
        is structural: both render one snapshot dict.
  /v1/trace (GET)                             -> the flight recorder
        (obs/trace.py; DPF_TPU_TRACE / DPF_TPU_TRACE_RING): one span
        tree per recent request — ingress/admission/queue_wait/coalesce/
        dispatch/plan_lookup/compute/d2h/reply, with shed / expired /
        breaker-rejected outcomes recorded too.  Query params:
        ?n=N (recent N), ?slowest=1, ?id=<trace-id>, ?outcome=shed|....
        Trace ids arrive via the X-DPF-Trace request header (the Go
        client stamps one per request) or are generated at ingress.
  /v1/profile (POST, JSON)                    -> on-demand XProf capture
        of the LIVE process (obs/profile.py): {"action": "start"|"stop"|
        "status"[, "seconds": S][, "dir": path]}.  Refused (403) unless
        DPF_TPU_PROFILE_ALLOW is set; every capture auto-stops after
        min(S, DPF_TPU_PROFILE_MAX_S); the reply reports the trace
        directory for xprof/tensorboard.

Serving fast path (the request pipeline for the pointwise/DCF/interval
endpoints):

  parse/LRU repack (serving/keycache.py — repeated key bytes skip
  validation + packing + the key-material upload entirely)
    -> dynamic micro-batcher (serving/batcher.py — concurrent requests
       on the same (route, profile, log_n) lane coalesce into ONE device
       dispatch; DPF_TPU_BATCH_WINDOW_US / DPF_TPU_BATCH_MAX_KEYS;
       DPF_TPU_BATCH=off degrades to direct dispatch)
    -> plan cache (core/plans.py — K/Q bucketed to powers of two, padded
       + masked, so the steady state replays pre-traced executables)
    -> per-request slicing from the packed output words.

With DPF_TPU_MESH resolved (parallel/serving_mesh.py) the plan cache
dispatches land on the shard_map evaluators: one coalesced batch shards
its key axis across the chip mesh (DESIGN §14), /v1/stats grows a
``mesh`` block, /v1/metrics a ``dpf_mesh_shards`` gauge and mesh-
coordinate labels on the per-device memory gauges, and while the
circuit breaker is not closed every dispatch falls back byte-
identically to the single-device executables.  The wire contract is
unchanged in every mode.

Format negotiation: ``format=bits`` (the byte-per-bit default, for
back-compat) or ``format=packed``; anything else is a 400.  The server-side
default for requests that omit the param is the ``DPF_TPU_WIRE_FORMAT``
env knob (bits).  Packed responses follow the core/bitpack contract —
clients unpack with ``bitpack.unpack_bits`` / ``dpftpu.UnpackBits``.

Batched endpoints amortize the device dispatch exactly like the in-process
batch API; errors surface as structured ``{code, detail}`` JSON (clean
error propagation across the bridge — SURVEY §5.3 — never a crashed
server): 400 bad_request for validation, 429 shed past an admission
watermark, 503 unavailable while the device circuit breaker is open (both
with Retry-After derived from observed dispatch latency), 504 deadline
when a request's ``X-DPF-Deadline-Ms`` budget expires, 500 internal with
the exception TYPE only (reprs can embed key material; see DESIGN §11).

Run: ``python -m dpf_tpu.server --port 8990``.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import math
import socket
import struct
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from .core import bitpack, knobs, plans
from .obs import metrics as obs_metrics
from .obs import profile as obs_profile
from .obs import trace as obs_trace
from .serving import Batcher, IntervalWork, KeyCache, PointsWork, faults
from .serving.batcher import (
    HHWork,
    PirWork,
    dispatch_hh,
    dispatch_interval,
    dispatch_pir,
    dispatch_points,
)
from .serving.breaker import CircuitBreaker, is_transient
from .serving.errors import DeadlineError, ServingError
from .utils.profiling import PhaseTimer

# Per-request deadline header: remaining budget in milliseconds.  The
# ``DPF_TPU_DEADLINE_MS`` knob sets the server default for requests that
# omit it (0 = no default deadline).
DEADLINE_HEADER = "X-DPF-Deadline-Ms"

# Per-request trace id header (obs/trace.py): propagated from the client
# (the Go client stamps one per request) or generated at ingress.
TRACE_HEADER = "X-DPF-Trace"

# ServingError.code -> flight-recorder outcome (obs/trace.OUTCOMES).
_ERROR_OUTCOMES = {
    "shed": "shed",
    "deadline": "expired",
    "unavailable": "breaker_rejected",
}


def _wire_format(q: dict) -> bool:
    """Resolve the response format for a points endpoint -> packed? bool.
    Per-request ``format`` param wins; ``DPF_TPU_WIRE_FORMAT`` sets the
    server default; unknown values are a 400 (ValueError)."""
    fmt = q.get("format", knobs.get_str("DPF_TPU_WIRE_FORMAT"))
    if fmt not in ("bits", "packed"):
        raise ValueError(f"unknown format {fmt!r} (use bits|packed)")
    return fmt == "packed"


def _deadline_from(headers) -> float | None:
    """Resolve the request's absolute deadline (perf_counter seconds) or
    None: the ``X-DPF-Deadline-Ms`` header wins, the DPF_TPU_DEADLINE_MS
    knob is the server default, 0/absent means unbounded."""
    raw = headers.get(DEADLINE_HEADER)
    if raw is None:
        ms = knobs.get_float("DPF_TPU_DEADLINE_MS")
        if ms <= 0:
            return None
    else:
        ms = float(raw)
        if ms <= 0:
            raise ValueError(f"{DEADLINE_HEADER} must be a positive ms count")
    return time.perf_counter() + ms / 1e3


def _run_evalfull(profile: str, kb):
    faults.fire("dispatch.evalfull")
    return plans.run_evalfull(profile, kb)


def _profile_api(profile: str):
    if profile == "fast":
        from . import fast
        from .core.chacha_np import key_len
        from .models.keys_chacha import KeyBatchFast

        return fast, key_len, KeyBatchFast
    import dpf_tpu

    from .core.spec import key_len
    from .core.keys import KeyBatch

    return dpf_tpu, key_len, KeyBatch


class _ServingState:
    """Per-process serving machinery: micro-batcher, host-repack LRU and
    the thread-merged phase timers.  Built lazily on first request so env
    knobs set by tests/deployments before traffic take effect."""

    def __init__(self):
        # A DPF_TPU_FAULTS spec activates (or refuses loudly) before any
        # traffic; programmatic test installs are left untouched when the
        # knob is empty.
        faults.install_from_env()
        # ONE stats lock (re-entrant) shared by every counter surface —
        # batcher stats, breaker counters, key-cache LRU, phase timers,
        # metrics histograms — so ``stats_snapshot`` (and /v1/metrics,
        # rendered from the same snapshot) is a single consistent cut
        # across all of them, never a torn read of one component mid-
        # update.  Queue/state structure sharing the same lock is fine:
        # no component holds it across a dispatch, sleep, or socket op.
        self.stats_lock = threading.RLock()
        self.metrics = obs_metrics.MetricsHub(lock=self.stats_lock)
        self.batcher = Batcher(lock=self.stats_lock, metrics=self.metrics)
        self.keys = KeyCache(lock=self.stats_lock)
        self.phases = PhaseTimer()
        self.batch_enabled = knobs.get_bool("DPF_TPU_BATCH")
        # The breaker's background probe re-warms what was being served
        # (most recently used plans) so recovery never lands a recompile
        # on the half-open trial request.
        self.breaker = CircuitBreaker(
            probe=plans.rewarm_recent, lock=self.stats_lock
        )
        self.tracer = obs_trace.Tracer()
        # Readiness (GET /readyz): flipped by the first successful
        # POST /v1/warmup — a sidecar that never warmed serves traffic
        # but advertises not-ready so load generators hold fire.
        self.warmed = False

    def degraded(self) -> bool:
        """True while the breaker is not closed: the batcher is bypassed
        (a failing dispatch fans to ONE request, not a coalesced batch),
        streamed EvalFull falls back to buffered replies (failures
        surface as a clean status line, never a truncated body), and
        mesh dispatches fall back to single-device (a wedged chip must
        not be re-probed through an every-chip collective;
        ``parallel/serving_mesh.suspended``).  All degraded paths are
        byte-identical to the fast path."""
        return self.breaker.degraded()

    def _mesh_ctx(self):
        """Single-device override for degraded dispatches: inside this
        context every plan call ignores the serving mesh.  A no-op
        nullcontext while the breaker is closed."""
        if self.degraded():
            from .parallel import serving_mesh

            return serving_mesh.suspended()
        return contextlib.nullcontext()

    def _note_phase(self, name: str, dt: float, n: int = 1) -> None:
        """One phase observation into BOTH surfaces — the /v1/stats sum
        counters and the /v1/metrics latency histogram — under the single
        stats lock."""
        with self.stats_lock:
            self.phases.add(name, dt, n)
            self.metrics.observe_phase(name, dt)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._note_phase(name, time.perf_counter() - t0)

    def merge_timer(self, tm: PhaseTimer) -> None:
        # A streamed run's timer arrives pre-accumulated; each merged
        # phase is one histogram observation of its total.
        with self.stats_lock:
            for name, dt in tm.phases.items():
                self._note_phase(name, dt, tm.counts[name])

    def run(self, work, dispatch):
        """One request through the fast path: breaker admission ->
        micro-batcher (when enabled and healthy) -> plan cache ->
        per-request result rows.  Dispatches run under the breaker
        (transient retries + trip accounting); deadline checkpoints
        bracket the passthrough path the same way the batcher brackets
        its queue."""
        tr = getattr(work, "trace", None)
        with obs_trace.maybe_span(tr, "admission"):
            self.breaker.admit()

        def guarded(items):
            return self.breaker.call(lambda: dispatch(items))

        if self.batch_enabled and not self.breaker.degraded():
            res = self.batcher.submit(work, guarded)
        else:
            # Passthrough: batching disabled, or degraded while the
            # breaker recovers.
            if work.deadline is not None and (
                time.perf_counter() >= work.deadline
            ):
                self.batcher.note_expired("queue")
                raise DeadlineError(
                    "deadline expired before dispatch", where="queue"
                )
            t0 = time.perf_counter()
            with obs_trace.traced_dispatch(tr) as dspan, self._mesh_ctx():
                res = guarded([work])[0]
                if dspan is not None:
                    dspan.set_attrs(coalesced=work.n_keys)
            work.dispatch_s = time.perf_counter() - t0
            work.coalesced = work.n_keys
            if work.deadline is not None and (
                time.perf_counter() >= work.deadline
            ):
                self.batcher.note_expired("flight")
                raise DeadlineError(
                    "deadline expired in flight", where="flight"
                )
        self._note_phase("queue_wait", work.queue_wait)
        # A coalesced dispatch is shared: attribute each request its
        # key-row share so phases.compute sums to real device time
        # (the batcher's dispatch_seconds holds the per-dispatch
        # truth).
        self._note_phase(
            "compute",
            work.dispatch_s * work.n_keys / max(work.coalesced, 1),
        )
        return res

    def direct(self, fn, deadline: float | None = None, trace=None):
        """Breaker-guarded non-batched dispatch (the evalfull routes)
        with the same deadline checkpoints as the batcher path; expiry
        shares the batcher's /v1/stats counters."""
        with obs_trace.maybe_span(trace, "admission"):
            self.breaker.admit()
        if deadline is not None and time.perf_counter() >= deadline:
            self.batcher.note_expired("queue")
            raise DeadlineError(
                "deadline expired before dispatch", where="queue"
            )
        with obs_trace.traced_dispatch(trace), self._mesh_ctx():
            out = self.breaker.call(fn)
        if deadline is not None and time.perf_counter() >= deadline:
            self.batcher.note_expired("flight")
            raise DeadlineError("deadline expired in flight", where="flight")
        return out

    def stats_snapshot(self) -> dict:
        """Consistent /v1/stats payload, taken as ONE critical section
        under the single stats lock (the component stats() calls
        re-acquire the same RLock): batcher, breaker, and key-cache
        counters can never be torn against each other mid-update.
        /v1/metrics renders from this same snapshot, so the two surfaces
        cannot drift."""
        from .apps import pir_store
        from .parallel import serving_mesh

        with self.stats_lock:
            out = {
                "plans": plans.cache().stats(),
                "batcher": self.batcher.stats_dict(),
                "key_cache": self.keys.stats(),
                "phases": self.phases.as_dict(),
                "batch_enabled": self.batch_enabled,
                "breaker": self.breaker.stats(),
                "degraded": self.degraded(),
                "trace": self.tracer.stats(),
                "mesh": serving_mesh.stats(),
                "pir": pir_store.registry().stats(),
            }
        plan = faults.active()
        if plan is not None:
            # An injected run must never be mistakable for a healthy one.
            out["faults"] = plan.stats()
        return out

    def metrics_text(self) -> str:
        """The /v1/metrics body: stats + histogram state captured in one
        critical section, rendered outside it."""
        with self.stats_lock:
            snap = self.stats_snapshot()
            hists = self.metrics.snapshot()
        return obs_metrics.render(snap, hists)


_STATE: _ServingState | None = None
_STATE_LOCK = threading.Lock()


def _serving_state() -> _ServingState:
    global _STATE
    with _STATE_LOCK:
        if _STATE is None:
            _STATE = _ServingState()
        return _STATE


def reset_serving_state() -> None:
    """Drop the lazy serving singleton (tests/benches re-read the batching
    and cache env knobs on the next request)."""
    global _STATE
    with _STATE_LOCK:
        _STATE = None


def _evalfull_out_bytes(profile: str, log_n: int) -> int:
    """The models' output-row contract, in one place: 2^(log_n-3) bytes
    with the profile's leaf-width floor (compat 16, fast 64)."""
    return max((1 << log_n) >> 3, 64 if profile == "fast" else 16)


def _stream_mode(q: dict, out_bytes: int) -> bool:
    """Resolve streaming for /v1/evalfull: per-request ``stream`` param
    wins; DPF_TPU_STREAM=off|auto|on sets the default (auto streams
    responses >= DPF_TPU_STREAM_MIN_BYTES, default 1 MiB)."""
    v = q.get("stream")
    if v is not None:
        if v not in ("0", "1"):
            raise ValueError(f"unknown stream {v!r} (use 0|1)")
        return v == "1"
    raw = knobs.get_raw("DPF_TPU_STREAM")
    env = knobs.knob("DPF_TPU_STREAM").default if raw is None else raw.lower()
    if env in ("on", "1", "true"):
        return True
    if env in ("off", "0", "false", ""):
        return False
    if env != "auto":
        raise ValueError(f"DPF_TPU_STREAM={env!r} unknown (off|auto|on)")
    return out_bytes >= knobs.get_int("DPF_TPU_STREAM_MIN_BYTES")


class _Handler(BaseHTTPRequestHandler):
    server_version = "dpf-tpu-sidecar/1"
    # HTTP/1.1 so connections persist (BaseHTTPRequestHandler defaults to
    # 1.0, which closes after every response — that would defeat both the
    # Go client's pooled keep-alive Transport and the micro-batcher, whose
    # coalescing needs requests to ARRIVE concurrently, not serialized
    # behind per-request TCP handshakes).  Safe here: every response path
    # sends an exact Content-Length, including the streaming one.
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet by default
        pass

    def _reply(self, code: int, body: bytes, ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_error(
        self, status: int, code: str, detail: str,
        retry_after_s: float | None = None,
    ):
        """Structured error reply: ``{code, detail}`` JSON plus a
        Retry-After header (whole seconds, rounded up) when the error
        carries a backoff hint.  ``detail`` must be client-safe — the
        secret-hygiene lint treats this call as a taint sink."""
        body = json.dumps({"code": code, "detail": detail}).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after_s is not None:
            self.send_header(
                "Retry-After", str(max(1, math.ceil(retry_after_s)))
            )
        self.end_headers()
        self.wfile.write(body)

    def _bad(self, msg: str):
        self._reply_error(400, "bad_request", msg)

    def _abort_connection(self):
        """Hard-abort the connection: SO_LINGER(1, 0) + close sends a
        TCP RST, so a mid-stream failure is an unambiguous connection
        error at the client — never a silently truncated body that
        parses as a short-but-well-formed reply."""
        try:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            self.connection.close()
        except OSError:
            pass
        self.close_connection = True

    def do_GET(self):
        url = urlparse(self.path)
        path = url.path
        if path == "/healthz":
            # Liveness ONLY: "ok" while the process serves requests,
            # regardless of breaker state or warmup.  Readiness is
            # /readyz — a restart-the-pod signal must never be
            # conflated with a hold-the-traffic signal.
            self._reply(200, b"ok", "text/plain")
        elif path == "/readyz":
            st = _serving_state()
            if st.breaker.degraded():
                self._reply_error(
                    503, "breaker_open",
                    f"circuit breaker is {st.breaker.state}",
                    retry_after_s=st.breaker.cooldown_s,
                )
            elif not st.warmed:
                self._reply_error(
                    503, "cold",
                    "warmup has not run (POST /v1/warmup first)",
                )
            else:
                self._reply(200, b"ready", "text/plain")
        elif path == "/v1/stats":
            payload = _serving_state().stats_snapshot()
            self._reply(
                200, json.dumps(payload).encode(), "application/json"
            )
        elif path == "/v1/metrics":
            self._reply(
                200, _serving_state().metrics_text().encode(),
                "text/plain; version=0.0.4",
            )
        elif path == "/v1/trace":
            # Only the QUERY-PARAM parsing maps to 400 — a rendering
            # failure must stay a 500, not masquerade as a scraper
            # misconfiguration.
            try:
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                outcome = q.get("outcome")
                if outcome is not None and (
                    outcome not in obs_trace.OUTCOMES
                ):
                    raise ValueError(
                        f"unknown outcome {outcome!r} "
                        f"(one of {', '.join(obs_trace.OUTCOMES)})"
                    )
                n = int(q.get("n", 32))
            except ValueError as e:
                self._reply_error(400, "bad_request", str(e))
                return
            st = _serving_state()
            traces = st.tracer.recorder.query(
                n=n,
                slowest=q.get("slowest") == "1",
                trace_id=q.get("id"),
                outcome=outcome,
            )
            payload = {
                "enabled": st.tracer.enabled,
                "ring": st.tracer.recorder.stats(),
                "traces": [t.as_dict() for t in traces],
            }
            self._reply(
                200, json.dumps(payload).encode(), "application/json"
            )
        else:
            self._reply(404, b"not found", "text/plain")

    def _points_reply(self, words: np.ndarray, nq: int, packed: bool, st,
                      trace=None):
        with st.phase("reply"), obs_trace.maybe_span(trace, "reply"):
            faults.fire("reply.write")
            if packed:
                self._reply(200, bitpack.words_to_wire(words, nq))
            else:
                self._reply(
                    200,
                    np.ascontiguousarray(
                        bitpack.unpack_bits(words, nq)
                    ).tobytes(),
                )

    def _evalfull_stream(self, profile: str, kb, log_n: int, st,
                         deadline: float | None = None):
        """Write one key's expansion progressively from the streaming
        pipeline.  The first chunk is pulled BEFORE the status line so
        evaluation errors still surface as a clean 400.  Deadline
        checkpoints mirror the buffered path: expiry before the status
        line is a clean 504; expiry mid-stream aborts the connection
        (the body can no longer be completed honestly) and counts as
        expired-in-flight."""
        if deadline is not None and time.perf_counter() >= deadline:
            st.batcher.note_expired("queue")
            raise DeadlineError(
                "deadline expired before dispatch", where="queue"
            )
        tm = PhaseTimer()
        if profile == "fast":
            from .models.dpf_chacha import eval_full_stream

            gen = eval_full_stream(kb, timer=tm)
        else:
            from .models.dpf import eval_full_stream

            gen = eval_full_stream(kb, timer=tm)
        first = next(gen)
        declared = _evalfull_out_bytes(profile, log_n)
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(declared))
        self.end_headers()
        written = 0
        aborted = False
        try:
            # Only the socket writes belong to the "reply" phase — the
            # generator's resumption does device dispatch + D2H, which
            # the stream's own timer already records as dispatch/d2h.
            chunk = first
            while chunk is not None:
                if deadline is not None and (
                    time.perf_counter() >= deadline
                ):
                    st.batcher.note_expired("flight")
                    raise DeadlineError(
                        "deadline expired mid-stream", where="flight"
                    )
                faults.fire("stream.chunk")
                row = chunk[0].tobytes()
                with st.phase("reply"):
                    self.wfile.write(row)
                written += len(row)
                chunk = next(gen, None)
        except Exception:  # noqa: BLE001
            # The 200 status line is already on the wire: a second
            # response here would corrupt the client's payload.  The only
            # honest signal for a mid-stream failure is an aborted
            # connection.
            aborted = True
        finally:
            if aborted or written != declared:
                # Mid-stream failure or declared-length drift: RST the
                # connection so truncation is a loud client-side error
                # (and a keep-alive client can never read the next
                # response out of frame).
                self._abort_connection()
            st.merge_timer(tm)

    def _agg_submit(self, q: dict, st, trace):
        """POST /v1/agg/submit?op=xor|add&k=K&words=W — streamed secure
        aggregation.  Body: K client share rows of W uint32 words each
        (little-endian), read and folded in DPF_TPU_AGG_CHUNK_BYTES
        chunks so the [K, W] upload never materializes on host; reply:
        the W folded words.  Rides admission (breaker), deadlines (the
        checkpoint runs between chunks — a doomed upload stops burning
        device slots mid-body), and per-chunk transient retries like
        every other dispatch seam.  Any failure before the body is fully
        consumed aborts the connection (the unread remainder would
        misframe the next keep-alive request)."""
        from .apps import aggregation as agg_app

        clen = int(self.headers.get("Content-Length", 0))
        consumed = 0
        # EVERYTHING from parameter parsing on runs under the framing
        # guard: any error that leaves body bytes unread must close the
        # connection, or the next pipelined request parses mid-upload.
        try:
            op = q.get("op", "xor")
            if op not in agg_app.OPS:
                raise ValueError(f"unknown op {op!r} (use xor|add)")
            k, words = int(q["k"]), int(q["words"])
            if k <= 0 or words <= 0:
                raise ValueError("k and words must be positive")
            row_bytes = words * 4
            if clen != k * row_bytes:
                raise ValueError(
                    f"body must be {k}*{row_bytes} bytes of uint32 rows"
                )
            deadline = _deadline_from(self.headers)
            if trace is not None:
                trace.set_attrs(op=op, words=words, rows=k)
            with obs_trace.maybe_span(trace, "admission"):
                st.breaker.admit()
            step = agg_app.chunk_rows(words)
            carry = np.zeros(words, np.uint32)
            remaining = k
            with obs_trace.traced_dispatch(trace) as dspan:
                while remaining > 0:
                    if deadline is not None and (
                        time.perf_counter() >= deadline
                    ):
                        where = "queue" if consumed == 0 else "flight"
                        st.batcher.note_expired(where)
                        raise DeadlineError(
                            "deadline expired mid-upload", where=where
                        )
                    take = min(step, remaining)
                    # The socket read accounts to "pack" (host-side
                    # marshalling), NOT "dispatch": a slow uploader must
                    # never spike the device-health phase histogram.
                    with st.phase("pack"):
                        buf = self.rfile.read(take * row_bytes)
                        if len(buf) != take * row_bytes:
                            raise ValueError("upload truncated mid-chunk")
                        consumed += len(buf)
                        rows = np.frombuffer(buf, dtype="<u4").reshape(
                            take, words
                        )
                    # The fault seam fires INSIDE the breaker call, like
                    # every other dispatch.* site, so injected transients
                    # get the breaker's retry/classification treatment.
                    def fold_chunk(r=rows, c=carry):
                        faults.fire("dispatch.agg")
                        return plans.run_agg_fold(op, c, r)

                    # _mesh_ctx per chunk: a breaker trip mid-upload
                    # degrades the REMAINING chunks to single-device
                    # (the fold carry is placement-agnostic numpy).
                    with st.phase("dispatch"), st._mesh_ctx():
                        carry = st.breaker.call(fold_chunk)
                    remaining -= take
                if dspan is not None:
                    dspan.set_attrs(coalesced=k, chunks=-(-k // step))
        except BaseException:
            if consumed != clen:
                # The socket still holds unread upload bytes: a reply
                # now would leave the next pipelined request misframed.
                self.close_connection = True
            raise
        with st.phase("reply"), obs_trace.maybe_span(trace, "reply"):
            faults.fire("reply.write")
            self._reply(200, carry.astype("<u4").tobytes())

    def _pir_db_load(self, q: dict, st, trace):
        """POST /v1/pir/db?name=X&rows=N&row_bytes=B[&profile=] —
        register a named device-resident PIR database
        (apps/pir_store.py).  The body is read off the socket in
        DPF_TPU_PIR_DB_CHUNK_BYTES chunks straight into the packed host
        buffer (one copy, no giant intermediate bytes object), with
        deadline checkpoints between chunks; the same framing guard as
        /v1/agg/submit closes the connection when an error leaves body
        bytes unread.  On success the database is placed resident for
        the CURRENT mesh regime, so query traffic never pays the
        device transfer."""
        from .apps import pir_store

        clen = int(self.headers.get("Content-Length", 0))
        consumed = 0
        try:
            name = q.get("name", "")
            pir_store.validate_name(name)  # BEFORE reading a byte
            profile = q.get("profile", "compat")
            if profile not in ("compat", "fast"):
                raise ValueError(f"unknown profile {profile!r}")
            rows, row_bytes = int(q["rows"]), int(q["row_bytes"])
            if rows <= 0 or row_bytes <= 0:
                raise ValueError("rows and row_bytes must be positive")
            if row_bytes % 4:
                raise ValueError("row_bytes must be a multiple of 4")
            if clen != rows * row_bytes:
                raise ValueError(
                    f"body must be {rows}*{row_bytes} bytes of row data"
                )
            deadline = _deadline_from(self.headers)
            if trace is not None:
                trace.set_attrs(db=name, rows=rows, row_bytes=row_bytes)
            # Breaker admission before the buffer and the read loop: a
            # wedged/recovering device must shed a multi-GB upload (and
            # its residency placement) exactly like any other dispatch.
            with obs_trace.maybe_span(trace, "admission"):
                st.breaker.admit()
            db = np.empty((rows, row_bytes), np.uint8)
            step = pir_store.upload_chunk_rows(row_bytes)
            done = 0
            while done < rows:
                if deadline is not None and (
                    time.perf_counter() >= deadline
                ):
                    where = "queue" if consumed == 0 else "flight"
                    st.batcher.note_expired(where)
                    raise DeadlineError(
                        "deadline expired mid-upload", where=where
                    )
                take = min(step, rows - done)
                # The socket read accounts to "pack" (host marshalling),
                # like the agg upload — a slow uploader must never spike
                # the device-health phases.
                with st.phase("pack"):
                    faults.fire("pir.db_load")
                    buf = self.rfile.read(take * row_bytes)
                    if len(buf) != take * row_bytes:
                        raise ValueError("upload truncated mid-chunk")
                    consumed += len(buf)
                    db[done : done + take] = np.frombuffer(
                        buf, np.uint8
                    ).reshape(take, row_bytes)
                done += take
            entry = pir_store.registry().load(name, db, profile=profile)
        except BaseException:
            if consumed != clen:
                # Unread upload bytes would misframe the next pipelined
                # request: close instead of replying over them.
                self.close_connection = True
            raise
        # Place residency NOW (sharded over the mesh when resolved), so
        # the first query pays neither transfer nor layout.
        shards = entry.dispatch_shards()
        srv = entry.server(shards)
        info = {
            "name": entry.name,
            "rows": entry.n_rows,
            "row_bytes": entry.row_bytes,
            "log_n": entry.log_n,
            "profile": entry.profile,
            "db_bytes": entry.db_bytes,
            "shards": shards,
            "stream_chunks": srv.stream_chunks,
        }
        with st.phase("reply"), obs_trace.maybe_span(trace, "reply"):
            faults.fire("reply.write")
            self._reply(200, json.dumps(info).encode(), "application/json")

    def _pir_query(self, q: dict, body: bytes, st, trace):
        """POST /v1/pir/query?db=X&k=K — answer K PIR queries against a
        registered database through the batcher lane (concurrent
        queries coalesce into one selection-matrix matmul over the
        resident rows)."""
        from .apps import pir_store

        name = q["db"]  # KeyError -> 400 missing parameter
        try:
            db = pir_store.registry().get(name)
        except KeyError as e:
            raise ValueError(str(e.args[0])) from None
        k = int(q["k"])
        _, key_len, batch_cls = _profile_api(db.profile)
        kl = key_len(db.log_n)
        if len(body) != k * kl:
            raise ValueError(f"body must be {k}*{kl} key bytes")
        deadline = _deadline_from(self.headers)
        if trace is not None:
            trace.set_attrs(profile=db.profile, log_n=db.log_n, db=db.name)
        with st.phase("pack"), st._mesh_ctx():
            kb = st.keys.get(
                db.profile, db.log_n, bytes(body),
                lambda: batch_cls.from_bytes(
                    [bytes(body[i * kl : (i + 1) * kl]) for i in range(k)],
                    db.log_n,
                ),
            )
        rows = st.run(
            PirWork(db, kb, deadline=deadline, trace=trace), dispatch_pir
        )
        with st.phase("reply"), obs_trace.maybe_span(trace, "reply"):
            faults.fire("reply.write")
            self._reply(200, np.ascontiguousarray(rows).tobytes())

    def _profile_request(self, body: bytes):
        """POST /v1/profile: knob-gated, duration-bounded XProf capture
        (obs/profile.py).  Body: ``{"action": "start"|"stop"|"status"
        [, "seconds": S][, "dir": path]}``."""
        spec = json.loads(body or b"{}")
        action = spec.get("action", "start")
        try:
            if action == "start":
                out = obs_profile.start(
                    spec.get("dir"),
                    spec.get("seconds"),
                )
            elif action == "stop":
                out = obs_profile.stop()
            elif action == "status":
                out = obs_profile.status()
            else:
                raise ValueError(
                    f"unknown action {action!r} (start|stop|status)"
                )
        except obs_profile.ProfileForbidden as e:
            self._reply_error(403, "profile_forbidden", str(e))
            return
        except obs_profile.ProfileBusy as e:
            self._reply_error(409, "profile_active", str(e))
            return
        except obs_profile.ProfileError as e:
            self._reply_error(400, "bad_request", str(e))
            return
        self._reply(200, json.dumps(out).encode(), "application/json")

    def do_POST(self):
        trace = None
        st = None
        outcome = "ok"
        try:
            url = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            route = url.path
            st = _serving_state()

            if route == "/v1/agg/submit":
                # The aggregation upload is the one body that must NOT
                # be read whole: it streams off the socket in
                # DPF_TPU_AGG_CHUNK_BYTES chunks, one fold dispatch per
                # chunk (apps/aggregation.py).
                trace = st.tracer.begin(
                    self.headers.get(TRACE_HEADER), route
                )
                self._agg_submit(q, st, trace)
                return
            if route == "/v1/pir/db":
                # The other streamed upload: database rows read in
                # DPF_TPU_PIR_DB_CHUNK_BYTES chunks into the packed
                # host buffer (apps/pir_store.py).
                trace = st.tracer.begin(
                    self.headers.get(TRACE_HEADER), route
                )
                self._pir_db_load(q, st, trace)
                return
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))

            if route == "/v1/warmup":
                spec = json.loads(body or b"[]")
                shapes = spec.get("shapes", []) if isinstance(spec, dict) \
                    else spec
                warmed = plans.warmup(shapes)
                if warmed:
                    # /readyz flips to 200 — but only when this warmup
                    # actually compiled something: an empty spec must
                    # not advertise readiness over a cold plan cache.
                    st.warmed = True
                self._reply(
                    200,
                    json.dumps(
                        {
                            "warmed": warmed,
                            "trace_cache_entries": plans.trace_count(),
                        }
                    ).encode(),
                    "application/json",
                )
                return
            if route == "/v1/profile":
                self._profile_request(body)
                return

            # Flight-recorder trace for the serving routes (None when
            # DPF_TPU_TRACE=off): id from the client's X-DPF-Trace
            # header, or generated here at ingress.
            trace = st.tracer.begin(self.headers.get(TRACE_HEADER), route)

            if route == "/v1/pir/query":
                # Profile and domain come from the registered database,
                # not the query string — handled before the generic
                # profile/log_n parsing below.
                self._pir_query(q, body, st, trace)
                return

            profile = q.get("profile", "compat")
            api, key_len, batch_cls = _profile_api(profile)
            log_n = int(q["log_n"])
            deadline = _deadline_from(self.headers)
            if trace is not None:
                trace.set_attrs(profile=profile, log_n=log_n)

            def cached_keys(kind, blob, k, kl, cls=None):
                """Parse ``k`` concatenated keys through the repack LRU.
                Parsing runs under the SAME mesh context the dispatch
                will (``_mesh_ctx``), so the cache's placement-regime
                token — and the batch's device operand memos — always
                match the executable the batch is about to feed."""
                cls = cls or batch_cls
                with st.phase("pack"), st._mesh_ctx():
                    return st.keys.get(
                        kind, log_n, blob,
                        lambda: cls.from_bytes(
                            [
                                bytes(blob[i * kl : (i + 1) * kl])
                                for i in range(k)
                            ],
                            log_n,
                        ),
                    )

            if route == "/v1/gen":
                alpha = int(q.get("alpha", 0))
                ka, kb = api.Gen(alpha, log_n)
                self._reply(200, ka + kb)
            elif route == "/v1/eval":
                bit = api.Eval(bytes(body), int(q["x"]), log_n)
                self._reply(200, bytes([bit]))
            elif route == "/v1/evalfull":
                kl = key_len(log_n)
                if len(body) != kl:
                    raise ValueError(f"body must be one {kl}-byte key")
                kb = cached_keys(profile, bytes(body), 1, kl)
                if _stream_mode(
                    q, _evalfull_out_bytes(profile, log_n)
                ) and not st.degraded():
                    # (Degraded mode buffers: a dispatch error surfaces
                    # as a clean status line, never a truncated stream.)
                    with obs_trace.maybe_span(trace, "admission"):
                        st.breaker.admit()
                    self._evalfull_stream(
                        profile, kb, log_n, st, deadline
                    )
                else:
                    with st.phase("dispatch"):
                        out = st.direct(
                            lambda: _run_evalfull(profile, kb), deadline,
                            trace=trace,
                        )
                    with st.phase("reply"), obs_trace.maybe_span(
                        trace, "reply"
                    ):
                        self._reply(200, out[0].tobytes())
            elif route == "/v1/evalfull_batch":
                k = int(q["k"])
                kl = key_len(log_n)
                if len(body) != k * kl:
                    raise ValueError(f"body must be {k}*{kl} bytes")
                kb = cached_keys(profile, bytes(body), k, kl)
                with st.phase("dispatch"):
                    out = st.direct(
                        lambda: _run_evalfull(profile, kb), deadline,
                        trace=trace,
                    )
                with st.phase("reply"), obs_trace.maybe_span(
                    trace, "reply"
                ):
                    self._reply(200, np.ascontiguousarray(out).tobytes())
            elif route == "/v1/eval_points_batch":
                k, nq = int(q["k"]), int(q["q"])
                kl = key_len(log_n)
                if len(body) != k * kl + k * nq * 8:
                    raise ValueError(
                        f"body must be {k}*{kl} key bytes + {k}*{nq}*8 index bytes"
                    )
                packed = _wire_format(q)
                kb = cached_keys(profile, bytes(body[: k * kl]), k, kl)
                xs = np.frombuffer(body[k * kl :], dtype="<u8").reshape(k, nq)
                words = st.run(
                    PointsWork(
                        "points", profile, kb, xs, deadline=deadline,
                        trace=trace,
                    ),
                    dispatch_points,
                )
                self._points_reply(words, nq, packed, st, trace)
            elif route == "/v1/dcf_gen":
                from .models import dcf

                k = int(q["k"])
                if len(body) != k * 8:
                    raise ValueError(f"body must be {k}*8 alpha bytes")
                alphas = np.frombuffer(body, dtype="<u8")
                da, db = dcf.gen_lt_batch(alphas, log_n)
                self._reply(
                    200, b"".join(da.to_bytes()) + b"".join(db.to_bytes())
                )
            elif route == "/v1/dcf_eval_points":
                from .models import dcf

                k, nq = int(q["k"]), int(q["q"])
                kl = dcf.key_len(log_n)
                if len(body) != k * kl + k * nq * 8:
                    raise ValueError(
                        f"body must be {k}*{kl} key bytes + {k}*{nq}*8 index bytes"
                    )
                packed = _wire_format(q)
                kb = cached_keys(
                    "dcf", bytes(body[: k * kl]), k, kl, cls=dcf.DcfKeyBatch
                )
                xs = np.frombuffer(body[k * kl :], dtype="<u8").reshape(k, nq)
                words = st.run(
                    PointsWork(
                        "dcf_points", "fast", kb, xs, deadline=deadline,
                        trace=trace,
                    ),
                    dispatch_points,
                )
                self._points_reply(words, nq, packed, st, trace)
            elif route == "/v1/dcf_interval_gen":
                from .models import dcf

                k = int(q["k"])
                if len(body) != k * 16:
                    raise ValueError(f"body must be {k}*8 lo + {k}*8 hi bytes")
                bounds = np.frombuffer(body, dtype="<u8")
                ia, ib = dcf.gen_interval_batch(bounds[:k], bounds[k:], log_n)

                def blob(ik):
                    u, lo_, c = ik
                    return (
                        b"".join(u.to_bytes()) + b"".join(lo_.to_bytes())
                        + c.astype("<u1").tobytes()
                    )

                self._reply(200, blob(ia) + blob(ib))
            elif route == "/v1/dcf_interval_eval":
                from .models import dcf

                k, nq = int(q["k"]), int(q["q"])
                kl = dcf.key_len(log_n)
                blob_len = 2 * k * kl + k
                if len(body) != blob_len + k * nq * 8:
                    raise ValueError(
                        f"body must be {blob_len} interval-share bytes "
                        f"(2*{k}*{kl} keys + {k} consts) + {k}*{nq}*8 "
                        "index bytes"
                    )
                packed = _wire_format(q)

                def build_triple(blob=bytes(body[:blob_len])):
                    def keys_at(off):
                        return dcf.DcfKeyBatch.from_bytes(
                            [
                                bytes(blob[off + i * kl : off + (i + 1) * kl])
                                for i in range(k)
                            ],
                            log_n,
                        )

                    return (
                        keys_at(0),
                        keys_at(k * kl),
                        np.frombuffer(
                            blob[2 * k * kl :], dtype="<u1"
                        ).copy(),
                    )

                with st.phase("pack"), st._mesh_ctx():
                    triple = st.keys.get(
                        "dcf_interval", log_n, bytes(body[:blob_len]),
                        build_triple,
                    )
                xs = np.frombuffer(body[blob_len:], dtype="<u8").reshape(k, nq)
                words = st.run(
                    IntervalWork(triple, xs, deadline=deadline, trace=trace),
                    dispatch_interval,
                )
                self._points_reply(words, nq, packed, st, trace)
            elif route == "/v1/hh/gen":
                from .apps import heavy_hitters as hh_app

                k = int(q["k"])
                if len(body) != k * 8:
                    raise ValueError(f"body must be {k}*8 value bytes")
                values = np.frombuffer(body, dtype="<u8")
                sa, sb = hh_app.gen_shares(values, log_n, profile=profile)
                self._reply(
                    200,
                    hh_app.share_to_blob(sa) + hh_app.share_to_blob(sb),
                )
            elif route == "/v1/hh/eval":
                k, nq = int(q["k"]), int(q["q"])
                level = int(q["level"])
                if not 0 <= level < log_n:
                    raise ValueError(
                        f"level must be in [0, {log_n}), got {level}"
                    )
                kl = key_len(log_n)
                if len(body) != k * kl + nq * 8:
                    raise ValueError(
                        f"body must be {k}*{kl} level-key bytes + "
                        f"{nq}*8 candidate bytes"
                    )
                packed = _wire_format(q)
                kb = cached_keys(profile, bytes(body[: k * kl]), k, kl)
                cands = np.frombuffer(body[k * kl :], dtype="<u8")
                words = st.run(
                    HHWork(
                        profile, kb,
                        np.broadcast_to(cands[None, :], (k, nq)), level,
                        deadline=deadline, trace=trace,
                    ),
                    dispatch_hh,
                )
                self._points_reply(words, nq, packed, st, trace)
            else:
                # A misrouted client is a client error, not a healthy
                # request — its trace must not pollute ?outcome=ok.
                outcome = "bad_request"
                self._reply(404, b"not found", "text/plain")
        except ServingError as e:
            # Load-survival errors carry their own HTTP mapping: 429
            # shed, 503 open circuit, 504 missed deadline — plus a
            # Retry-After derived from observed dispatch latency.
            outcome = _ERROR_OUTCOMES.get(e.code, "error")
            self._reply_error(e.http_status, e.code, e.detail,
                              e.retry_after_s)
        except (ValueError, KeyError) as e:
            # Validation failures: our own parameter/shape messages (the
            # secret-hygiene pass keeps raises in this tree free of key
            # bytes, so str(e) is client-safe here).
            outcome = "bad_request"
            detail = (
                f"missing parameter {e}" if isinstance(e, KeyError)
                else str(e)
            )
            self._reply_error(400, "bad_request", detail)
        except Exception as e:  # noqa: BLE001 — bridge must not crash
            # NEVER echo arbitrary exception reprs: deep library errors
            # can embed operand values (key material).  Type name only;
            # transient device signatures map to 503 so clients back off
            # instead of hammering a wedged device.
            outcome = "error"
            if is_transient(e):
                self._reply_error(
                    503, "unavailable", type(e).__name__,
                    retry_after_s=_serving_state().breaker.cooldown_s,
                )
            else:
                self._reply_error(500, "internal", type(e).__name__)
        finally:
            # Shed/expired/breaker-rejected requests are recorded too —
            # an overload incident must be reconstructable from the
            # flight recorder after the fact.
            if st is not None:
                st.tracer.finish(trace, outcome)


def audit_knobs() -> list[str]:
    """Boot-time knob audit: warn about every DPF_TPU_* env var present
    but not declared in the registry (a typo'd knob — e.g.
    ``DPF_TPU_BATCH_WINDOW_MS`` — used to fail silent, quietly serving
    with the default).  Returns the unknown names (tests)."""
    unknown = knobs.audit_environ()
    for name in unknown:
        warnings.warn(
            f"unknown knob {name} is set but not declared in "
            "dpf_tpu/core/knobs.py — a typo? It has NO effect "
            "(see docs/KNOBS.md for the knob surface)",
            RuntimeWarning,
            stacklevel=2,
        )
    return unknown


class _Server(ThreadingHTTPServer):
    # A load-surviving sidecar must not drop SYNs at 4x offered load:
    # the stdlib default listen backlog (5) converts connection churn
    # into 1-3 s SYN-retransmit latency spikes at the CLIENT long before
    # the batcher's admission control ever sees the request.  Shedding
    # must happen in the application (429 + Retry-After), not in the
    # kernel's accept queue.
    request_queue_size = 128


def serve(port: int = 8990, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start the sidecar in a daemon thread; returns the server object
    (call ``.shutdown()`` to stop)."""
    audit_knobs()
    # A DPF_TPU_FAULTS spec in a non-test environment must be a BOOT
    # error with the full refusal message — not a mystery 500 on the
    # first request (the lazy serving state would strip the message).
    faults.install_from_env()
    srv = _Server((host, port), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8990)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()
    audit_knobs()  # warns (stderr) once per unknown DPF_TPU_* var
    faults.install_from_env()  # refuse a leaked fault spec AT BOOT
    print(f"dpf-tpu sidecar on {args.host}:{args.port}")
    _Server((args.host, args.port), _Handler).serve_forever()


if __name__ == "__main__":
    main()
