"""DPF evaluation sidecar: the framework's serving / language bridge.

The reference is a Go library consumed in-process (dpf_main.go:6 imports
``github.com/dkales/dpf-go/dpf``).  The TPU framework's evaluator lives in a
Python/JAX process, so foreign-language clients (the reference's Go
programs, C++ services, ...) reach it through this sidecar instead — now
over TWO fronts sharing one transport-neutral handler core
(``serving/handlers.py``):

  * this module's HTTP/1.1 front: raw key bytes in and raw result bytes
    out — the same keys-as-bytes wire contract as the reference
    (``type DPFkey []byte``, dpf/dpf.go:7), so a Go client is ~20 lines
    of net/http with no codegen.  Curl-able, debuggable, the default.
  * the wire2 front (``serving/wire2.py``, enabled with
    ``DPF_TPU_WIRE2=on``): length-prefixed binary frames over persistent
    multiplexed connections — HTTP/2-style streams, one connection
    carrying many concurrent requests — where request bodies flow as
    ``memoryview`` slices from a per-connection receive buffer straight
    into the dispatch path (zero intermediate ``bytes`` copies) and
    replies are written as gathered frames from the device-returned
    arrays.  Same routes, same params, byte-identical replies; built for
    million-client agg/HH campaigns where HTTP/1.1 marshalling is the
    wall.  DESIGN.md §17 documents the frame format and when to use
    which front.

Endpoints (all POST, binary bodies, profile/params in the query string;
wire2 sends the identical param string in its header block):

  /v1/gen?log_n=N[&alpha=A][&profile=fast]   -> key_a || key_b
  /v1/eval?log_n=N&x=X[&profile=fast]        body: one key  -> 1 byte (0/1)
  /v1/evalfull?log_n=N[&profile=fast][&stream=0|1]
        body: one key  -> bit-packed bytes.  ``stream=1`` (or the
        DPF_TPU_STREAM=on|auto default, auto streaming responses >=
        DPF_TPU_STREAM_MIN_BYTES) writes the response progressively from
        the double-buffered chunked expansion: each subtree chunk's
        bytes go onto the socket while the next chunk computes, so
        time-to-first-byte is ~one chunk instead of the whole tree.
        Content-Length is always exact; the byte stream is identical to
        the blocking reply, so clients need no changes.
  /v1/evalfull_batch?log_n=N&k=K[&profile=fast]
        body: K concatenated keys -> K concatenated expansions
  /v1/eval_points_batch?log_n=N&k=K&q=Q[&profile=fast][&format=packed]
        body: K concatenated keys || K*Q little-endian uint64 indices
        -> K*Q bytes of 0/1 bits (row-major [K, Q]); with format=packed,
           K rows of ceil(Q/8) bit-packed bytes instead (bit j of row i at
           byte j//8, bit j%8 LSB-first — the /v1/evalfull convention and
           the reference's, dpf/dpf.go:207-209; tail bits zero) — an 8x
           cut of the dominant serving-traffic response
  /v1/dcf_gen?log_n=N&k=K                     body: K uint64 alphas
        -> K DCF keys (party A) || K DCF keys (party B)  (fast profile)
  /v1/dcf_eval_points?log_n=N&k=K&q=Q[&format=packed]
        body: keys || uint64 indices
        -> K*Q comparison-share bits (models/dcf.py; one key per gate),
           or K * ceil(Q/8) packed bytes with format=packed
  /v1/dcf_interval_gen?log_n=N&k=K            body: K uint64 lo || K uint64 hi
        -> party A blob || party B blob, each 2K DCF keys (upper, lower)
           || K public const bytes
  /v1/dcf_interval_eval?log_n=N&k=K&q=Q[&format=packed]
        body: one party blob || indices
        -> K*Q interval-share bits (1{lo <= x <= hi} after XOR), or
           K * ceil(Q/8) packed bytes with format=packed
  /v1/hh/gen?log_n=N&k=K[&profile=fast]       body: K uint64 client values
        -> share blob A || share blob B (trusted-dealer helper for the
           prefix-tree heavy-hitters protocol, apps/heavy_hitters.py;
           each blob is K clients x log_n level keys, client-major)
  /v1/hh/eval?log_n=N&k=K&q=Q&level=L[&profile=fast][&format=packed]
        body: K level-L client keys (key_len bytes each) || Q uint64
        candidate prefixes (ONE shared set, depth L+1 shifted up to n
        bits — uploaded once, not per key)
        -> K*Q share bits [client, candidate] (packed: K rows of
           ceil(Q/8) bytes) — the single-aggregator round primitive;
           two aggregators' replies XOR+popcount into public counts
  /v1/agg/submit?op=xor|add&k=K&words=W       body: K rows x W uint32
        -> the W folded uint32 words (secure aggregation,
           apps/aggregation.py).  The body is read AND folded in
           DPF_TPU_AGG_CHUNK_BYTES chunks — a million-client upload
           never materializes on host.
  /v1/pir/db?name=X&rows=N&row_bytes=B[&profile=fast]
        body: N rows x B bytes — register (or replace) a named PIR
        database (apps/pir_store.py).  The body is read off the socket
        in DPF_TPU_PIR_DB_CHUNK_BYTES chunks straight into the packed
        host buffer; the rows then live device-resident — sharded over
        the chip mesh's HBM when DPF_TPU_MESH resolves — until replaced.
        Replies JSON {name, rows, row_bytes, log_n, db_bytes, shards,
        stream_chunks}.  The DB is PUBLIC protocol data (both PIR
        servers hold identical copies); the query is the secret.
  /v1/pir/query?db=X&k=K                      body: K concatenated DPF
        keys (the database's profile) -> K rows x row_bytes answer
        bytes: each query's XOR of the selected database rows, computed
        as chunked int8/int32 MXU matmuls over the resident DB
        (models/pir.py).  XOR the two servers' replies to reconstruct
        the rows.  Concurrent queries coalesce into ONE
        selection-matrix matmul (the scan cost is the database pass,
        so batch-mates ride it as extra MXU rows); databases past
        DPF_TPU_PIR_DB_CHUNK_BYTES answer through the streamed chunk
        scan, byte-identically.
  /v1/warmup                                  body: JSON
        {"shapes": [{"route": "points"|"dcf_points"|"dcf_interval"|
        "evalfull"|"hh_level"|"agg_xor"|"agg_add"|"pir", "profile":
        "compat"|"fast", "log_n": N, "k": K,
        "q": Q}, ...]} — compile the dispatch plans for those shapes NOW
        (core/plans.py) so first-request compile never lands on user
        traffic.  An evalfull spec with "stream": true also warms the
        streaming pipeline's per-chunk executables (distinct compiles);
        a pir spec names a REGISTERED database ({"route": "pir", "db":
        name, "k": K} — log_n/profile come from the registry) and warms
        its scan executables for the current mesh regime.
        Replies JSON with per-shape compile seconds.
  /healthz                                    -> "ok" (liveness ONLY:
        200 while the process serves, regardless of breaker/warmup)
  /readyz (GET)                               -> readiness: 200 "ready",
        or 503 {code:"breaker_open"} while the circuit breaker is not
        closed / {code:"cold"} until the first POST /v1/warmup — load
        generators (bridge/go/cmd/loadgen -wait-ready) hold fire on it
  /v1/stats (GET)                             -> JSON observability:
        plan-cache hit/miss + live trace count, micro-batcher
        coalescing (requests, dispatches, batch_coalesced mean/max,
        queue-wait, live queue_depth) plus load-survival counters
        (shed_depth/shed_age, expired_queue vs expired_flight, dispatch
        EWMA), key-repack LRU hits, circuit-breaker state
        (closed|open|half_open, trips, retries, fast-fails), active
        fault-injection clauses (when any), flight-recorder ring state,
        per-phase timers (queue_wait, pack, dispatch, compute, d2h,
        reply — utils/profiling.PhaseTimer), and the per-front ``wire``
        marshalling ledger (requests, body bytes, bytes COPIED between
        socket and dispatch operand — the wire2 hot path's entry stays
        at zero copied; the allocation probe in tests/test_wire2.py and
        the bench cfg-wire section read this).  The whole payload is
        ONE critical section under a single stats lock — never a torn
        read.
  /v1/metrics (GET)                           -> the same snapshot in
        Prometheus text format (obs/metrics.py): counters (sheds,
        expirations, breaker transitions, plan compiles, keycache hits),
        gauges (queue depth, breaker state, per-device memory), and
        fixed-bucket histograms for per-phase latency + coalesce size
        (DPF_TPU_METRICS_BUCKETS_MS).  Counter equality with /v1/stats
        is structural: both render one snapshot dict.
  /v1/trace (GET)                             -> the flight recorder
        (obs/trace.py; DPF_TPU_TRACE / DPF_TPU_TRACE_RING): one span
        tree per recent request — ingress/admission/queue_wait/coalesce/
        dispatch/plan_lookup/compute/d2h/reply, with shed / expired /
        breaker-rejected outcomes recorded too.  Query params:
        ?n=N (recent N), ?slowest=1, ?id=<trace-id>, ?outcome=shed|....
        Trace ids arrive via the X-DPF-Trace request header (the Go
        client stamps one per request) or are generated at ingress.
  /v1/profile (POST, JSON)                    -> on-demand XProf capture
        of the LIVE process (obs/profile.py): {"action": "start"|"stop"|
        "status"[, "seconds": S][, "dir": path]}.  Refused (403) unless
        DPF_TPU_PROFILE_ALLOW is set; every capture auto-stops after
        min(S, DPF_TPU_PROFILE_MAX_S); the reply reports the trace
        directory for xprof/tensorboard.

The request pipeline itself — admission, micro-batcher, plan cache,
deadlines, circuit breaker, tracing, degraded modes, format
negotiation, structured errors — is documented where it lives now:
``serving/handlers.py`` (the transport-neutral core both fronts call).
This module is only the HTTP/1.1 byte I/O around it.

Run: ``python -m dpf_tpu.server --port 8990`` (add
``DPF_TPU_WIRE2=on [DPF_TPU_WIRE2_PORT=8991]`` for the wire2 front).
"""

from __future__ import annotations

import argparse
import math
import socket
import struct
import threading
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

from .core import knobs
from .obs import trace as obs_trace
from .serving import faults, handlers
from .serving.handlers import (  # noqa: F401 — the sidecar's public surface
    reset_serving_state,
)
from .serving.headers import (  # noqa: F401 — shared wire vocabulary
    DEADLINE_HEADER,
    RETRY_AFTER_HEADER,
    TRACE_HEADER,
)

# Back-compat aliases: tests and benches reach the serving singleton
# through this module (the machinery itself lives in serving/handlers).
_serving_state = handlers.serving_state
_evalfull_out_bytes = handlers._evalfull_out_bytes


class _Handler(BaseHTTPRequestHandler):
    server_version = "dpf-tpu-sidecar/1"
    # HTTP/1.1 so connections persist (BaseHTTPRequestHandler defaults to
    # 1.0, which closes after every response — that would defeat both the
    # Go client's pooled keep-alive Transport and the micro-batcher, whose
    # coalescing needs requests to ARRIVE concurrently, not serialized
    # behind per-request TCP handshakes).  Safe here: every response path
    # sends an exact Content-Length, including the streaming one.
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet by default
        pass

    def _abort_connection(self):
        """Hard-abort the connection: SO_LINGER(1, 0) + close sends a
        TCP RST, so a mid-stream failure is an unambiguous connection
        error at the client — never a silently truncated body that
        parses as a short-but-well-formed reply."""
        try:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            self.connection.close()
        except OSError:
            pass
        self.close_connection = True

    def _write_reply(self, reply: handlers.Reply) -> None:
        """One buffered Reply onto the socket: status line, exact
        Content-Length, Retry-After when the error carries a backoff
        hint, then the gathered body chunks (buffer views write without
        an intermediate join)."""
        self.send_response(reply.status)
        self.send_header("Content-Type", reply.ctype)
        self.send_header("Content-Length", str(reply.body_len))
        if reply.retry_after_s is not None:
            self.send_header(
                RETRY_AFTER_HEADER,
                str(max(1, math.ceil(reply.retry_after_s))),
            )
        self.end_headers()
        for chunk in reply.chunks:
            self.wfile.write(chunk)
        if reply.close_connection:
            # The handler left body bytes unread (an error mid-upload):
            # the next pipelined request would parse mid-body.
            self.close_connection = True

    def _write_stream(self, reply: handlers.Reply, st) -> None:
        """A progressive Reply (streamed EvalFull): exact Content-Length
        up front, each generated chunk written as it arrives.  The
        status line is already committed when a mid-stream failure
        (deadline, injected chunk fault, dispatch error) surfaces, so
        the only honest signal is an aborted connection — truncation is
        a loud client-side error, and a keep-alive client can never
        read the next response out of frame."""
        self.send_response(reply.status)
        self.send_header("Content-Type", reply.ctype)
        self.send_header("Content-Length", str(reply.stream_len))
        self.end_headers()
        written = 0
        aborted = False
        try:
            # Only the socket writes belong to the "reply" phase — the
            # generator's resumption does device dispatch + D2H, which
            # the stream's own timer already records as dispatch/d2h.
            for chunk in reply.stream:
                with st.phase("reply"):
                    self.wfile.write(chunk)
                written += handlers._blen(chunk)
        except Exception:  # noqa: BLE001
            aborted = True
        finally:
            if aborted or written != reply.stream_len:
                self._abort_connection()

    def _send(self, reply: handlers.Reply, st) -> None:
        if reply.stream is not None:
            self._write_stream(reply, st)
        elif reply.timed:
            # Serving replies: the write is a "reply" phase observation,
            # a reply span on the request's trace, and the reply.write
            # fault site (injected write failures map like any other).
            with st.phase("reply"), obs_trace.maybe_span(
                reply.trace, "reply"
            ):
                faults.fire("reply.write")
                self._write_reply(reply)
        else:
            self._write_reply(reply)

    def do_GET(self):
        url = urlparse(self.path)
        reply = handlers.respond_get(
            url.path, handlers.parse_params(url.query), _serving_state()
        )
        self._write_reply(reply)

    def do_POST(self):
        st = _serving_state()
        url = urlparse(self.path)
        route = url.path
        try:
            clen = int(self.headers.get("Content-Length", 0))
        except ValueError:
            # A malformed header is a clean 400, never a dropped
            # connection with a server-side traceback.
            self._write_reply(handlers._reply_error(
                "bad_request", "Content-Length is not an integer"
            ))
            self.close_connection = True  # the body, if any, is unread
            return
        req = handlers.Request(
            route=route,
            params=handlers.parse_params(url.query),
            content_length=clen,
            deadline_ms=self.headers.get(DEADLINE_HEADER),
            trace_id=self.headers.get(TRACE_HEADER),
            front="http",
        )
        if route in handlers.SINK_ROUTES:
            # Streamed uploads: the handler pulls the body through the
            # short-read-robust reader in route-sized chunks (ONE
            # reusable scratch buffer — the copy the ledger charges).
            req.body_reader = handlers.FileBodyReader(self.rfile, clen)
        else:
            # The HTTP/1.1 front's structural marshalling copy: the
            # body materializes once between socket and handler (the
            # wire2 front exists to not pay this).
            req.body = self.rfile.read(clen)
        st.note_body("http", clen, clen)
        reply = handlers.respond(req, st)
        try:
            self._send(reply, st)
        except Exception as e:  # noqa: BLE001 — write-time failure
            # An injected reply.write fault (or a dispatch error inside
            # a timed write) maps exactly like a handler error; if the
            # socket itself is gone the error write below fails too and
            # http.server drops the connection.
            err = handlers.map_error(e, st)
            reply.outcome = err.outcome
            try:
                self._write_reply(err)
            except OSError:
                self.close_connection = True
        finally:
            # Shed/expired/breaker-rejected requests are recorded too —
            # an overload incident must be reconstructable from the
            # flight recorder after the fact.
            st.tracer.finish(reply.trace, reply.outcome)


def audit_knobs() -> list[str]:
    """Boot-time knob audit: warn about every DPF_TPU_* env var present
    but not declared in the registry (a typo'd knob — e.g.
    ``DPF_TPU_BATCH_WINDOW_MS`` — used to fail silent, quietly serving
    with the default).  Returns the unknown names (tests)."""
    unknown = knobs.audit_environ()
    for name in unknown:
        warnings.warn(
            f"unknown knob {name} is set but not declared in "
            "dpf_tpu/core/knobs.py — a typo? It has NO effect "
            "(see docs/KNOBS.md for the knob surface)",
            RuntimeWarning,
            stacklevel=2,
        )
    return unknown


class _Server(ThreadingHTTPServer):
    # A load-surviving sidecar must not drop SYNs at 4x offered load:
    # the stdlib default listen backlog (5) converts connection churn
    # into 1-3 s SYN-retransmit latency spikes at the CLIENT long before
    # the batcher's admission control ever sees the request.  Shedding
    # must happen in the application (429 + Retry-After), not in the
    # kernel's accept queue.
    request_queue_size = 128

    # The wire2 listener riding this sidecar's lifecycle (None when
    # DPF_TPU_WIRE2 is off); its ephemeral address is
    # ``srv.wire2.address`` for tests/benches.
    wire2 = None

    def shutdown(self):
        super().shutdown()
        if self.wire2 is not None:
            self.wire2.shutdown()


def _maybe_start_wire2(srv: _Server, host: str) -> None:
    """Start the wire2 binary front next to the HTTP one when
    DPF_TPU_WIRE2 resolves on — same serving state, same routes,
    byte-identical replies (serving/wire2.py)."""
    if not knobs.get_bool("DPF_TPU_WIRE2"):
        return
    from .serving import wire2

    srv.wire2 = wire2.serve(
        port=knobs.get_int("DPF_TPU_WIRE2_PORT"), host=host
    )


def serve(port: int = 8990, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start the sidecar in a daemon thread; returns the server object
    (call ``.shutdown()`` to stop — the wire2 front, when enabled, is
    torn down with it)."""
    audit_knobs()
    # A DPF_TPU_FAULTS spec in a non-test environment must be a BOOT
    # error with the full refusal message — not a mystery 500 on the
    # first request (the lazy serving state would strip the message).
    faults.install_from_env()
    srv = _Server((host, port), _Handler)
    _maybe_start_wire2(srv, host)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8990)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()
    audit_knobs()  # warns (stderr) once per unknown DPF_TPU_* var
    faults.install_from_env()  # refuse a leaked fault spec AT BOOT
    srv = _Server((args.host, args.port), _Handler)
    _maybe_start_wire2(srv, args.host)
    print(f"dpf-tpu sidecar on {args.host}:{args.port}")
    if srv.wire2 is not None:
        print(f"dpf-tpu wire2 front on {srv.wire2.address[0]}:"
              f"{srv.wire2.address[1]}")
    srv.serve_forever()


if __name__ == "__main__":
    main()
