"""DPF evaluation sidecar: the framework's serving / language bridge.

The reference is a Go library consumed in-process (dpf_main.go:6 imports
``github.com/dkales/dpf-go/dpf``).  The TPU framework's evaluator lives in a
Python/JAX process, so foreign-language clients (the reference's Go
programs, C++ services, ...) reach it through this sidecar instead: a tiny
HTTP/1.1 server speaking raw key bytes in and raw result bytes out — the
same keys-as-bytes wire contract as the reference (``type DPFkey []byte``,
dpf/dpf.go:7), so a Go client is ~20 lines of net/http with no codegen.

Endpoints (all POST, binary bodies, profile/params in the query string):

  /v1/gen?log_n=N[&alpha=A][&profile=fast]   -> key_a || key_b
  /v1/eval?log_n=N&x=X[&profile=fast]        body: one key  -> 1 byte (0/1)
  /v1/evalfull?log_n=N[&profile=fast]        body: one key  -> bit-packed bytes
  /v1/evalfull_batch?log_n=N&k=K[&profile=fast]
        body: K concatenated keys -> K concatenated expansions
  /v1/eval_points_batch?log_n=N&k=K&q=Q[&profile=fast][&format=packed]
        body: K concatenated keys || K*Q little-endian uint64 indices
        -> K*Q bytes of 0/1 bits (row-major [K, Q]); with format=packed,
           K rows of ceil(Q/8) bit-packed bytes instead (bit j of row i at
           byte j//8, bit j%8 LSB-first — the /v1/evalfull convention and
           the reference's, dpf/dpf.go:207-209; tail bits zero) — an 8x
           cut of the dominant serving-traffic response
  /v1/dcf_gen?log_n=N&k=K                     body: K uint64 alphas
        -> K DCF keys (party A) || K DCF keys (party B)  (fast profile)
  /v1/dcf_eval_points?log_n=N&k=K&q=Q[&format=packed]
        body: keys || uint64 indices
        -> K*Q comparison-share bits (models/dcf.py; one key per gate),
           or K * ceil(Q/8) packed bytes with format=packed
  /v1/dcf_interval_gen?log_n=N&k=K            body: K uint64 lo || K uint64 hi
        -> party A blob || party B blob, each 2K DCF keys (upper, lower)
           || K public const bytes
  /v1/dcf_interval_eval?log_n=N&k=K&q=Q[&format=packed]
        body: one party blob || indices
        -> K*Q interval-share bits (1{lo <= x <= hi} after XOR), or
           K * ceil(Q/8) packed bytes with format=packed
  /healthz                                    -> "ok"

Format negotiation: ``format=bits`` (the byte-per-bit default, for
back-compat) or ``format=packed``; anything else is a 400.  The server-side
default for requests that omit the param is the ``DPF_TPU_WIRE_FORMAT``
env knob (bits).  Packed responses follow the core/bitpack contract —
clients unpack with ``bitpack.unpack_bits`` / ``dpftpu.UnpackBits``.

Batched endpoints amortize the device dispatch exactly like the in-process
batch API; errors surface as HTTP 400 with a text reason (clean error
propagation across the bridge — SURVEY §5.3 — never a crashed server).

Run: ``python -m dpf_tpu.server --port 8990``.
"""

from __future__ import annotations

import argparse
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from .core import bitpack


def _wire_format(q: dict) -> bool:
    """Resolve the response format for a points endpoint -> packed? bool.
    Per-request ``format`` param wins; ``DPF_TPU_WIRE_FORMAT`` sets the
    server default; unknown values are a 400 (ValueError)."""
    fmt = q.get("format", os.environ.get("DPF_TPU_WIRE_FORMAT") or "bits")
    if fmt not in ("bits", "packed"):
        raise ValueError(f"unknown format {fmt!r} (use bits|packed)")
    return fmt == "packed"


def _profile_api(profile: str):
    if profile == "fast":
        from . import fast
        from .core.chacha_np import key_len
        from .models.keys_chacha import KeyBatchFast

        return fast, key_len, KeyBatchFast
    import dpf_tpu

    from .core.spec import key_len
    from .core.keys import KeyBatch

    return dpf_tpu, key_len, KeyBatch


class _Handler(BaseHTTPRequestHandler):
    server_version = "dpf-tpu-sidecar/1"

    def log_message(self, *a):  # quiet by default
        pass

    def _reply(self, code: int, body: bytes, ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _bad(self, msg: str):
        self._reply(400, msg.encode(), "text/plain")

    def do_GET(self):
        if urlparse(self.path).path == "/healthz":
            self._reply(200, b"ok", "text/plain")
        else:
            self._reply(404, b"not found", "text/plain")

    def do_POST(self):
        try:
            url = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            profile = q.get("profile", "compat")
            api, key_len, batch_cls = _profile_api(profile)
            log_n = int(q["log_n"])
            route = url.path

            if route == "/v1/gen":
                alpha = int(q.get("alpha", 0))
                ka, kb = api.Gen(alpha, log_n)
                self._reply(200, ka + kb)
            elif route == "/v1/eval":
                bit = api.Eval(bytes(body), int(q["x"]), log_n)
                self._reply(200, bytes([bit]))
            elif route == "/v1/evalfull":
                self._reply(200, api.EvalFull(bytes(body), log_n))
            elif route == "/v1/evalfull_batch":
                k = int(q["k"])
                kl = key_len(log_n)
                if len(body) != k * kl:
                    raise ValueError(f"body must be {k}*{kl} bytes")
                keys = [bytes(body[i * kl : (i + 1) * kl]) for i in range(k)]
                out = api.eval_full_batch(batch_cls.from_bytes(keys, log_n))
                self._reply(200, np.ascontiguousarray(out).tobytes())
            elif route == "/v1/eval_points_batch":
                k, nq = int(q["k"]), int(q["q"])
                kl = key_len(log_n)
                if len(body) != k * kl + k * nq * 8:
                    raise ValueError(
                        f"body must be {k}*{kl} key bytes + {k}*{nq}*8 index bytes"
                    )
                keys = [bytes(body[i * kl : (i + 1) * kl]) for i in range(k)]
                xs = np.frombuffer(body[k * kl :], dtype="<u8").reshape(k, nq)
                packed = _wire_format(q)
                out = api.eval_points_batch(
                    batch_cls.from_bytes(keys, log_n), xs, packed=packed
                )
                if packed:
                    self._reply(200, bitpack.words_to_wire(out, nq))
                else:
                    self._reply(200, np.ascontiguousarray(out).tobytes())
            elif route == "/v1/dcf_gen":
                from .models import dcf

                k = int(q["k"])
                if len(body) != k * 8:
                    raise ValueError(f"body must be {k}*8 alpha bytes")
                alphas = np.frombuffer(body, dtype="<u8")
                da, db = dcf.gen_lt_batch(alphas, log_n)
                self._reply(
                    200, b"".join(da.to_bytes()) + b"".join(db.to_bytes())
                )
            elif route == "/v1/dcf_eval_points":
                from .models import dcf

                k, nq = int(q["k"]), int(q["q"])
                kl = dcf.key_len(log_n)
                if len(body) != k * kl + k * nq * 8:
                    raise ValueError(
                        f"body must be {k}*{kl} key bytes + {k}*{nq}*8 index bytes"
                    )
                keys = [bytes(body[i * kl : (i + 1) * kl]) for i in range(k)]
                xs = np.frombuffer(body[k * kl :], dtype="<u8").reshape(k, nq)
                packed = _wire_format(q)
                out = dcf.eval_lt_points(
                    dcf.DcfKeyBatch.from_bytes(keys, log_n), xs, packed=packed
                )
                if packed:
                    self._reply(200, bitpack.words_to_wire(out, nq))
                else:
                    self._reply(200, np.ascontiguousarray(out).tobytes())
            elif route == "/v1/dcf_interval_gen":
                from .models import dcf

                k = int(q["k"])
                if len(body) != k * 16:
                    raise ValueError(f"body must be {k}*8 lo + {k}*8 hi bytes")
                bounds = np.frombuffer(body, dtype="<u8")
                ia, ib = dcf.gen_interval_batch(bounds[:k], bounds[k:], log_n)

                def blob(ik):
                    u, lo_, c = ik
                    return (
                        b"".join(u.to_bytes()) + b"".join(lo_.to_bytes())
                        + c.astype("<u1").tobytes()
                    )

                self._reply(200, blob(ia) + blob(ib))
            elif route == "/v1/dcf_interval_eval":
                from .models import dcf

                k, nq = int(q["k"]), int(q["q"])
                kl = dcf.key_len(log_n)
                blob_len = 2 * k * kl + k
                if len(body) != blob_len + k * nq * 8:
                    raise ValueError(
                        f"body must be {blob_len} interval-share bytes "
                        f"(2*{k}*{kl} keys + {k} consts) + {k}*{nq}*8 "
                        "index bytes"
                    )

                def keys_at(off):
                    return dcf.DcfKeyBatch.from_bytes(
                        [bytes(body[off + i * kl : off + (i + 1) * kl])
                         for i in range(k)],
                        log_n,
                    )

                upper = keys_at(0)
                lower = keys_at(k * kl)
                const = np.frombuffer(
                    body[2 * k * kl : blob_len], dtype="<u1"
                )
                xs = np.frombuffer(body[blob_len:], dtype="<u8").reshape(k, nq)
                packed = _wire_format(q)
                out = dcf.eval_interval_points(
                    (upper, lower, const), xs, packed=packed
                )
                if packed:
                    self._reply(200, bitpack.words_to_wire(out, nq))
                else:
                    self._reply(200, np.ascontiguousarray(out).tobytes())
            else:
                self._reply(404, b"not found", "text/plain")
        except Exception as e:  # noqa: BLE001 — bridge must not crash
            self._bad(f"{type(e).__name__}: {e}")


def serve(port: int = 8990, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start the sidecar in a daemon thread; returns the server object
    (call ``.shutdown()`` to stop)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8990)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()
    print(f"dpf-tpu sidecar on {args.host}:{args.port}")
    ThreadingHTTPServer((args.host, args.port), _Handler).serve_forever()


if __name__ == "__main__":
    main()
