"""dpf_tpu — a TPU-native 2-party Distributed Point Function framework.

Re-design of the capabilities of ``dkales/dpf-go`` (Go + x86 AES-NI asm) for
TPU: the GGM tree expansion runs level-synchronously as bitsliced fixed-key
AES-128-MMO on the VPU (JAX/XLA, optional Pallas kernel), batched over keys,
sharded over chip meshes.  Keys are byte-compatible with the reference
(layout: dpf/dpf.go:89-92,111-112,165).

Reference-parity scalar API (dpf/dpf.go: Gen, Eval, EvalFull):

    ka, kb = dpf_tpu.Gen(alpha, log_n)
    bit    = dpf_tpu.Eval(ka, x, log_n)
    shares = dpf_tpu.EvalFull(ka, log_n)

Batch-first TPU API (where the speedup lives):

    kba, kbb = dpf_tpu.gen_batch(alphas, log_n)       # host, vectorized
    out      = dpf_tpu.eval_full_batch(kba)           # [K, 2^(n-3)] uint8
    bits     = dpf_tpu.eval_points_batch(kba, xs)     # [K, Q] uint8

FSS gates layered on DPFs (``dpf_tpu.models.fss``):

    ca, cb = fss.gen_lt_batch(alphas, log_n)          # 1{x < alpha} shares
    ia, ib = fss.gen_interval_batch(lo, hi, log_n)    # 1{lo <= x <= hi}

TPU-native fast profile (``dpf_tpu.fast``): same API over a ChaCha12 PRG
with 512-bit leaves — not reference-key-compatible, ~20x faster on TPU.
"""

from __future__ import annotations

import numpy as np

from .core import spec
from .core.keys import KeyBatch, gen_batch
from .core.spec import key_len

__all__ = [
    "Gen",
    "Eval",
    "EvalFull",
    "KeyBatch",
    "gen_batch",
    "eval_full_batch",
    "eval_points_batch",
    "key_len",
    "fss",
    "fast",
]


def __getattr__(name):
    if name == "fss":
        from .models import fss as _fss

        return _fss
    if name == "fast":
        # NOT ``from . import fast``: that re-enters this __getattr__ via
        # _handle_fromlist and recurses.
        import importlib

        return importlib.import_module(".fast", __name__)
    raise AttributeError(f"module 'dpf_tpu' has no attribute {name!r}")


def Gen(alpha: int, log_n: int, rng=None) -> tuple[bytes, bytes]:
    """Generate a DPF key pair for point ``alpha`` in [0, 2^log_n).

    Host-side (CPU): O(log N) sequential AES plus CSPRNG draws, mirroring the
    reference Gen (dpf/dpf.go:71-169).  Keys serialize to the reference's
    byte layout."""
    return spec.gen(alpha, log_n, rng)


def Eval(key: bytes, x: int, log_n: int, backend: str = "auto") -> int:
    """Evaluate one share at a single point -> bit (reference dpf/dpf.go:171).

    A single point query does not amortize a device roundtrip, so the default
    backend is the host evaluator; pass ``backend="jax"`` to force the
    accelerated path (useful for differential testing)."""
    if backend in ("auto", "cpu"):
        return spec.eval_point(key, x, log_n)
    kb = KeyBatch.from_bytes([key], log_n)
    return int(eval_points_batch(kb, np.array([[x]], dtype=np.uint64))[0, 0])


def EvalFull(key: bytes, log_n: int, backend: str = "auto") -> bytes:
    """Full-domain evaluation of one key -> 2^(log_n-3) bit-packed bytes
    (16 bytes when log_n < 7), byte-identical to the reference EvalFull
    (dpf/dpf.go:243-262)."""
    if backend == "cpu":
        return spec.eval_full(key, log_n)
    kb = KeyBatch.from_bytes([key], log_n)
    return eval_full_batch(kb)[0].tobytes()


def eval_full_batch(kb: KeyBatch, **kwargs) -> np.ndarray:
    """Full-domain evaluation of a key batch on the accelerator:
    -> uint8[K, 2^(log_n-3)]."""
    from .models import dpf as _dpf

    return _dpf.eval_full(kb, **kwargs)


def eval_points_batch(
    kb: KeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Pointwise evaluation of a key batch at xs uint64[K, Q] -> uint8[K, Q].

    ``packed=True`` returns the evaluation's native bit-packed form
    instead — uint32[K, ceil(Q/32)] words, query q at word q//32 bit q%32
    (LSB-first, the reference's EvalFull bit order; bits >= Q zero) — with
    no device-side unpack, so the device->host transfer shrinks 32x.
    ``core.bitpack.unpack_bits(words, Q)`` recovers the byte-per-bit form."""
    from .models import dpf as _dpf

    return _dpf.eval_points(kb, xs, packed=packed)
