"""Multi-chip parallelism: mesh construction, sharded evaluation, and the
parity all-reduce collective.  Multi-HOST execution (DCN-coordinated
meshes, per-process input placement) lives in ``multihost``."""

from . import multihost, serving_mesh
from .sharding import (
    KEYS_AXIS,
    LEAF_AXIS,
    eval_full_sharded,
    eval_full_sharded_fast,
    eval_interval_points_sharded,
    eval_lt_points_sharded,
    eval_points_sharded,
    eval_points_sharded_fast,
    fold_rows_sharded,
    make_mesh,
    xor_allreduce,
)

__all__ = [
    "KEYS_AXIS",
    "LEAF_AXIS",
    "multihost",
    "serving_mesh",
    "eval_full_sharded",
    "eval_full_sharded_fast",
    "eval_interval_points_sharded",
    "eval_lt_points_sharded",
    "eval_points_sharded",
    "eval_points_sharded_fast",
    "fold_rows_sharded",
    "make_mesh",
    "xor_allreduce",
]
