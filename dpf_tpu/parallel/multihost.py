"""Multi-host execution: DCN-coordinated meshes + per-process key placement.

The reference is a single-process library (no NCCL/MPI — SURVEY §5.8); its
TPU-native equivalent is JAX's multi-controller runtime: every host runs
this same program, `jax.distributed.initialize` wires the processes over
DCN, `jax.devices()` becomes the GLOBAL device list, and the existing
`shard_map` evaluators (sharding.py) run unchanged — XLA routes collectives
over ICI within a slice and DCN across hosts.  The one genuinely new piece
multi-host needs is INPUT PLACEMENT: a host must materialize only the key
shards that live on its own devices.  `distribute_fast_batch` does that
with `jax.make_array_from_callback`, whose callback is invoked only for
addressable shards — on a 4-host pod each host touches 1/4 of the key
batch; in a single process it degrades to ordinary device_put, so the same
code path is exercised by the CPU-mesh tests.

Usage (same program on every host):

    from dpf_tpu.parallel import multihost as mh
    mh.init_multihost()                       # no-op single-process
    mesh = make_mesh(n_keys, n_leaf)          # global devices
    args = mh.distribute_fast_batch(kb, mesh)
    words = mh.eval_full_distributed(kb, mesh, args)
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import (
    KEYS_AXIS,
    LEAF_AXIS,
    _fast_pad_quantum,
    _pad_fast_batch,
    _sharded_eval_full_fast,
    _sharded_fast_entry_level,
    leaf_axis_levels,
)

# Environment markers of a managed multi-process launch.  An explicit
# coordinator address is always decisive; worker-list/job markers count
# only when they actually name MORE THAN ONE process — this round's
# single-chip driver env sets TPU_WORKER_HOSTNAMES=localhost, and treating
# that as a pod sends jax.distributed's auto-detection hunting for a
# coordinator it cannot define.
_COORDINATOR_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _managed_launch() -> bool:
    if any(os.environ.get(v) for v in _COORDINATOR_ENV):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    def _int_env(name: str) -> int:
        try:
            return int(os.environ.get(name, "1"))
        except ValueError:
            return 1

    if os.environ.get("SLURM_JOB_ID") and _int_env("SLURM_NTASKS") > 1:
        return True
    return _int_env("OMPI_COMM_WORLD_SIZE") > 1


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join the multi-controller runtime; returns this process's index.

    Three modes, so the same binary serves one chip or a pod:
    explicit arguments -> initialize with them; no arguments but a managed
    launch detected in the environment (Cloud TPU pod, Slurm, Open MPI) ->
    jax.distributed's cluster auto-detection; neither -> single-process
    no-op.  Must run before any other JAX API, once per process."""
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif _managed_launch():
        jax.distributed.initialize()
    return jax.process_index()


def _fast_in_shardings(mesh: Mesh):
    """NamedShardings matching _sharded_eval_full_fast's in_specs."""
    keys2 = NamedSharding(mesh, P(KEYS_AXIS, None))
    return (
        keys2,  # seeds [K, 4]
        NamedSharding(mesh, P(KEYS_AXIS)),  # ts [K]
        NamedSharding(mesh, P(KEYS_AXIS, None, None)),  # scw [K, nu, 4]
        NamedSharding(mesh, P(KEYS_AXIS, None, None)),  # tcw [K, nu, 2]
        keys2,  # fcw [K, 16]
    )


def distribute_fast_batch(kb, mesh: Mesh):
    """Materialize a fast-profile key batch as globally-sharded arrays.

    Each process's callback is invoked only for the shards on its own
    addressable devices, so on a multi-host pod a host touches only its
    slice of the key axis (the host-side analogue of the evaluators'
    zero-communication key-batch data parallelism).  The key batch is
    padded exactly as eval_full_sharded_fast pads it, so the returned
    arrays feed the same compiled evaluator."""
    c = leaf_axis_levels(mesh, kb.nu, kb.log_n)
    quantum = _fast_pad_quantum(mesh, kb.nu, c)
    padded = _pad_fast_batch(kb, (-kb.k) % quantum)
    host = (
        np.asarray(padded.seeds),  # host-sync: host-side key normalization
        np.asarray(padded.ts, dtype=np.uint32),
        np.asarray(padded.scw),  # host-sync: host-side key normalization
        np.asarray(padded.tcw, dtype=np.uint32),
        np.asarray(padded.fcw),  # host-sync: host-side key normalization
    )
    out = []
    for arr, sh in zip(host, _fast_in_shardings(mesh)):
        out.append(
            jax.make_array_from_callback(
                arr.shape, sh, lambda idx, a=arr: a[idx]
            )
        )
    return tuple(out)


def _compat_in_shardings(mesh: Mesh):
    """NamedShardings matching _sharded_eval_full's in_specs (compat
    profile: bit-plane tensors with the packed key-word axis LAST)."""
    keyed = NamedSharding(mesh, P(None, None, KEYS_AXIS))
    rowed = NamedSharding(mesh, P(None, KEYS_AXIS))
    return (keyed, rowed, keyed, rowed, rowed, keyed)


def distribute_compat_batch(kb, mesh: Mesh):
    """Compat-profile analogue of :func:`distribute_fast_batch`: the
    DeviceKeys plane tensors (models/dpf.DeviceKeys — packed 32 keys per
    lane word) materialized shard-locally over the global mesh.  Returns
    (args, k_padded)."""
    from ..models.dpf import DeviceKeys

    n_keys = mesh.shape[KEYS_AXIS]
    dk = DeviceKeys(kb, pad_to=32 * n_keys)
    host = (
        # host-sync: one-time D2H of the packed key planes for resharding
        np.asarray(dk.seed_planes), np.asarray(dk.t_words),
        # host-sync: one-time D2H of the packed key planes for resharding
        np.asarray(dk.scw_planes), np.asarray(dk.tl_words),
        # host-sync: one-time D2H of the packed key planes for resharding
        np.asarray(dk.tr_words), np.asarray(dk.fcw_planes),
    )
    out = []
    for arr, sh in zip(host, _compat_in_shardings(mesh)):
        out.append(
            jax.make_array_from_callback(
                arr.shape, sh, lambda idx, a=arr: a[idx]
            )
        )
    return tuple(out)


def eval_full_distributed_compat(
    kb, mesh: Mesh, args=None, backend: str | None = None
) -> np.ndarray:
    """Compat-profile sharded full-domain evaluation from pre-distributed
    plane operands -> uint8[K, out_bytes], fully materialized per process
    (cross-host gather as in :func:`eval_full_distributed`)."""
    from ..models.dpf import default_backend
    from .sharding import _sharded_eval_full

    if args is None:
        args = distribute_compat_batch(kb, mesh)
    backend = backend or default_backend()
    c = leaf_axis_levels(mesh, kb.nu, kb.log_n)
    fn = _sharded_eval_full(mesh, kb.nu, c, backend)
    words = fn(*args)
    if not words.is_fully_addressable:
        from jax.experimental import multihost_utils

        words = multihost_utils.process_allgather(words, tiled=True)
    words = np.asarray(words)  # host-sync: final reply marshalling
    return np.ascontiguousarray(words[: kb.k]).view("<u1").reshape(kb.k, -1)


def distribute_dcf_batch(kb, mesh: Mesh):
    """DCF analogue of :func:`distribute_fast_batch`: one comparison gate
    per key, sharded over the ``keys`` axis.  Pads the gate count to the
    sharded evaluator's quantum (the walk kernel's 128-key lane tile per
    shard when the kernel route is on).  Returns (args, padded_k)."""
    from ..models.dcf import DcfKeyBatch
    from ..ops import chacha_pallas as cp

    n_keys = mesh.shape[KEYS_AXIS]
    use_kernel = cp.points_backend() == "pallas"
    quantum = n_keys * cp._KT if use_kernel else n_keys
    pad = (-kb.k) % quantum
    if pad:

        def padk(a):
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

        kb = DcfKeyBatch(
            kb.log_n, padk(kb.seeds), padk(kb.ts), padk(kb.scw),
            padk(kb.tcw), padk(kb.vcw), padk(kb.fvcw),
        )
    host = (
        np.asarray(kb.seeds),  # host-sync: host-side key normalization
        np.asarray(kb.ts, dtype=np.uint32),
        np.asarray(kb.scw),  # host-sync: host-side key normalization
        np.asarray(kb.tcw, dtype=np.uint32),
        np.asarray(kb.vcw, dtype=np.uint32),
        np.asarray(kb.fvcw),  # host-sync: host-side key normalization
    )
    keys2 = NamedSharding(mesh, P(KEYS_AXIS, None))
    shardings = (
        keys2,
        NamedSharding(mesh, P(KEYS_AXIS)),
        NamedSharding(mesh, P(KEYS_AXIS, None, None)),
        NamedSharding(mesh, P(KEYS_AXIS, None, None)),
        keys2,
        keys2,
    )
    out = []
    for arr, sh in zip(host, shardings):
        out.append(
            jax.make_array_from_callback(
                arr.shape, sh, lambda idx, a=arr: a[idx]
            )
        )
    return tuple(out), kb.k


def eval_full_distributed(kb, mesh: Mesh, args=None) -> np.ndarray:
    """Sharded full-domain evaluation from pre-distributed operands ->
    uint8[K, out_bytes] of this batch's keys, fully materialized on every
    process.  Single-process, the output shards are all addressable and
    fetch directly; on a pod the per-host shards are exchanged once over
    DCN (``multihost_utils.process_allgather``) so each host holds the
    complete logical result — skip that cost by consuming the returned
    jax.Array of ``eval_full_distributed_device`` shard-locally instead.

    ``args`` defaults to ``distribute_fast_batch(kb, mesh)``; pass the
    cached tuple to amortize placement across calls."""
    words = eval_full_distributed_device(kb, mesh, args)
    if not words.is_fully_addressable:
        from jax.experimental import multihost_utils

        words = multihost_utils.process_allgather(words, tiled=True)
    words = np.asarray(words)  # host-sync: final reply marshalling
    return np.ascontiguousarray(words[: kb.k]).view("<u1").reshape(kb.k, -1)


def eval_lt_points_distributed(kb, mesh: Mesh, xs, args=None) -> np.ndarray:
    """Distributed DCF comparison evaluation: xs uint64[K, Q] -> uint8
    [K, Q] shares of ``1{x < alpha}``.  Queries are placed shard-locally
    with their gates (each host materializes only its own columns of the
    transposed query tensor); results gather per process as in
    :func:`eval_full_distributed`."""
    from ..ops import chacha_pallas as cp
    from .sharding import _sharded_dcf_points

    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dcf: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dcf: query index out of domain")
    if args is None:
        args = distribute_dcf_batch(kb, mesh)
    ops, kp = args
    K, Q = xs.shape
    use_kernel = cp.points_backend() == "pallas"
    xs_t = np.zeros((Q + ((-Q) % 8 if use_kernel else 0), kp), np.uint64)
    xs_t[:Q, :K] = xs.T
    qsh = NamedSharding(mesh, P(None, KEYS_AXIS))

    def place(a):
        return jax.make_array_from_callback(
            a.shape, qsh, lambda idx, arr=a: arr[idx]
        )

    xs_lo = place((xs_t & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if kb.log_n > 32:
        xs_hi = place((xs_t >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = place(np.zeros((1, kp), np.uint32))  # never read
    qt = cp._qtile(xs_t.shape[0]) if use_kernel else 0
    fn = _sharded_dcf_points(mesh, kb.nu, kb.log_n, qt)
    bits = fn(*ops, xs_hi, xs_lo)
    if not bits.is_fully_addressable:
        from jax.experimental import multihost_utils

        bits = multihost_utils.process_allgather(bits, tiled=True)
    return np.asarray(bits).T[:K, :Q]  # host-sync: final reply marshalling


def eval_full_distributed_device(kb, mesh: Mesh, args=None):
    """As :func:`eval_full_distributed`, but returns the globally-sharded
    ``jax.Array`` of leaf words [K_padded, 2^nu, 16] without any cross-host
    gather — the form a sharded consumer (e.g. a PIR parity matmul over the
    same mesh) wants."""
    if args is None:
        args = distribute_fast_batch(kb, mesh)
    n_keys = mesh.shape[KEYS_AXIS]
    c = leaf_axis_levels(mesh, kb.nu, kb.log_n)
    kp = args[0].shape[0]
    entry = _sharded_fast_entry_level(kb.nu, c, kp // n_keys)
    fn = _sharded_eval_full_fast(mesh, kb.nu, c, entry)
    return fn(*args)
