"""Multi-chip sharding for DPF evaluation: `jax.shard_map` over an ICI mesh.

The reference is single-threaded (SURVEY §2: no goroutines, no comms).  On a
TPU pod the natural parallel axes of full-domain DPF evaluation are:

  * ``keys``  — data parallelism over the key batch.  Keys are independent,
    so the bit-plane tensors shard on their lane-word axis (32 keys/word)
    with **zero** cross-chip communication.
  * ``leaf``  — domain parallelism over the output range of each key.  The
    GGM tree has no cross-subtree dependence below any level, so each chip
    replicates the first ``log2(leaf)`` levels (O(leaf) tiny nodes), keeps
    its own subtree, and expands it privately — again zero communication.
    This is how a single key with 2^30 leaves outgrows one chip's HBM.

The only collective in the whole framework is the parity all-reduce that
combines per-shard partial XOR answers in the PIR application
(:func:`xor_allreduce`), riding ICI.

Everything here also runs on a virtual CPU mesh
(``--xla_force_host_platform_device_count=N``) — that is how the test suite
and the driver's multi-chip dry-run validate the shardings without N chips.
"""

from __future__ import annotations

import threading
from functools import cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core import bitpack
from ..core.keys import KeyBatch
from ..models.dpf import (
    _BM_BACKENDS,
    DeviceKeys,
    _convert_leaves,
    _level_step,
    _to_bm,
    default_backend,
)

KEYS_AXIS = "keys"
LEAF_AXIS = "leaf"


class _ShardedJits:
    """Registry of every jitted sharded evaluator built in this module.

    The mesh-native serving fast path promises zero retraces after
    warmup, and ``core.plans.trace_count`` proves it by summing the jit
    cache sizes of module-level jitted callables — but the sharded
    executables live inside ``functools.cache`` closures, invisible to
    that scan.  This object IS module-level and exposes the same
    ``_cache_size`` duck type, summing over every sharded jit ever
    built, so a retrace in a mesh dispatch moves the counter exactly
    like a single-device one."""

    def __init__(self):
        self._jits: list = []
        self._lock = threading.Lock()

    def register(self, fn):
        with self._lock:
            self._jits.append(fn)
        return fn

    def _cache_size(self) -> int:
        total = 0
        with self._lock:
            jits = list(self._jits)
        for f in jits:
            cs = getattr(f, "_cache_size", None)
            if callable(cs):
                try:
                    total += int(cs())
                except Exception:  # noqa: BLE001 — counting is best-effort
                    pass
        return total


SHARDED_JITS = _ShardedJits()


def shard_map_compat(body, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across JAX versions — the single entry every
    shard_map in the framework goes through.  Newer JAX exposes it as
    ``jax.shard_map`` (replication checking via ``check_vma``); 0.4.x has
    only ``jax.experimental.shard_map.shard_map`` with the same knob
    named ``check_rep``."""
    kwargs = {}
    if check_vma is not None:
        kwargs["check_vma" if hasattr(jax, "shard_map") else "check_rep"] = (
            check_vma
        )
    fn = (
        jax.shard_map
        if hasattr(jax, "shard_map")
        else __import__(
            "jax.experimental.shard_map", fromlist=["shard_map"]
        ).shard_map
    )
    return fn(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def make_mesh(
    n_keys: int = 1, n_leaf: int = 1, devices: list | None = None
) -> Mesh:
    """Build a ``(keys, leaf)`` mesh over the first ``n_keys * n_leaf``
    devices (defaults to all of ``jax.devices()`` arranged ``(ndev, 1)``)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_keys * n_leaf == 1 and len(devices) > 1:
        n_keys = len(devices)
    if n_keys * n_leaf > len(devices):
        raise ValueError(
            f"mesh {n_keys}x{n_leaf} needs {n_keys * n_leaf} devices, "
            f"have {len(devices)}"
        )
    # host-sync: host-side device-handle array, not a device tensor
    devs = np.array(devices[: n_keys * n_leaf]).reshape(n_keys, n_leaf)
    return Mesh(devs, (KEYS_AXIS, LEAF_AXIS))


def xor_allreduce(x: jax.Array, axis_name: str) -> jax.Array:
    """Bitwise-XOR all-reduce across a mesh axis (inside shard_map).

    XLA has no native XOR collective; an ``all_gather`` + local lane-XOR is
    one ICI hop and the payloads here (PIR answers, KiB) are tiny."""
    g = jax.lax.all_gather(x, axis_name)  # [n_shards, ...]
    return jnp.bitwise_xor.reduce(g, axis=0)


# ---------------------------------------------------------------------------
# Sharded full-domain evaluation
# ---------------------------------------------------------------------------


def leaf_axis_levels(mesh: Mesh, nu: int, log_n: int) -> int:
    """Validate the leaf-axis size against domain 2^log_n and return
    ``subtree_levels`` = log2(leaf-axis size)."""
    n_leaf = mesh.shape.get(LEAF_AXIS, 1)
    if n_leaf & (n_leaf - 1):
        raise ValueError("leaf axis size must be a power of two")
    c = n_leaf.bit_length() - 1
    if c > nu:
        raise ValueError(
            f"leaf axis {n_leaf} exceeds 2^nu={1 << nu} subtrees at "
            f"log_n={log_n}; use a smaller leaf axis"
        )
    return c


def expand_subtree_local(
    seed_planes, t_words, scw_planes, tl_w, tr_w, nu: int, subtree_levels: int,
    backend: str = "xla",
):
    """Shard-local GGM expansion (inside shard_map): replicate the top
    ``subtree_levels`` levels, slice this shard's subtree by its
    ``LEAF_AXIS`` index, expand the remaining levels.  Single source of
    truth for the subtree-sharding idiom (also used by models/pir.py).

    With a bit-major backend (models/dpf._BM_BACKENDS) the returned S is in
    bit-major plane order (feed it only to a convert with the same
    backend)."""
    if backend in _BM_BACKENDS:
        seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
    c = subtree_levels
    S, T = seed_planes, t_words  # [128, 1, kp_local], [1, kp_local]
    for i in range(c):
        S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
    if c:
        j = jax.lax.axis_index(LEAF_AXIS)
        S = jax.lax.dynamic_slice_in_dim(S, j, 1, axis=1)
        T = jax.lax.dynamic_slice_in_dim(T, j, 1, axis=0)
    for i in range(c, nu):
        S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
    return S, T


def _sharded_eval_full_sm(
    mesh: Mesh, nu: int, subtree_levels: int, backend: str
):
    """The UNJITTED shard_map body of :func:`_sharded_eval_full` — the
    callable the oblivious-trace verifier certifies (tracing it adds
    nothing to any jit cache)."""

    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes):
        S, T = expand_subtree_local(
            seed_planes, t_words, scw_planes, tl_w, tr_w, nu, subtree_levels,
            backend,
        )
        return _convert_leaves(S, T, fcw_planes, backend)

    keyed = P(None, None, KEYS_AXIS)  # plane tensors: lane-word axis last
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            keyed,
            P(None, KEYS_AXIS),
            keyed,
            P(None, KEYS_AXIS),
            P(None, KEYS_AXIS),
            keyed,
        ),
        out_specs=P(KEYS_AXIS, LEAF_AXIS, None),
    )


@cache
def _sharded_eval_full(mesh: Mesh, nu: int, subtree_levels: int, backend: str):
    """Compile the sharded evaluator for a (mesh, domain, backend) bucket.

    ``subtree_levels`` = log2(leaf-axis size); each shard replicates that
    many top levels, then expands only its own subtree.
    """
    return SHARDED_JITS.register(
        jax.jit(_sharded_eval_full_sm(mesh, nu, subtree_levels, backend))
    )


def eval_full_sharded(
    kb: KeyBatch, mesh: Mesh, backend: str | None = None
) -> np.ndarray:
    """Full-domain evaluation of a key batch sharded over ``mesh`` ->
    uint8[K, 2^(log_n-3)] (16 bytes/key when log_n < 7).

    Key batch shards over the ``keys`` axis; each key's leaf range shards
    over the ``leaf`` axis (independent GGM subtrees, zero communication).
    The leaf-axis size must be a power of two and at most 2^nu; pass a
    keys-only mesh for tiny domains.  ``backend`` defaults to the platform's
    measured-fastest kernel set (models/dpf.default_backend).
    """
    backend = backend or default_backend()
    n_keys = mesh.shape[KEYS_AXIS]
    c = leaf_axis_levels(mesh, kb.nu, kb.log_n)
    dk = DeviceKeys(kb, pad_to=32 * n_keys)
    fn = _sharded_eval_full(mesh, kb.nu, c, backend)
    # host-sync: final reply marshalling (sharded full-domain words)
    words = np.asarray(
        fn(
            dk.seed_planes, dk.t_words, dk.scw_planes,
            dk.tl_words, dk.tr_words, dk.fcw_planes,
        )
    )
    return np.ascontiguousarray(words[: kb.k]).view("<u1").reshape(kb.k, -1)


# ---------------------------------------------------------------------------
# Sharded evaluation — ChaCha fast profile
# ---------------------------------------------------------------------------


def expand_subtree_local_cc(seeds, ts, scw, tcw, nu: int, subtree_levels: int):
    """Fast-profile shard-local GGM expansion (inside shard_map): replicate
    the top ``subtree_levels`` levels, slice this shard's subtree by its
    ``LEAF_AXIS`` index, expand the rest.  Word-oriented mirror of
    :func:`expand_subtree_local`; single source of truth for the fast
    profile's subtree-sharding idiom (also used by models/pir.py)."""
    from ..models.dpf_chacha import _level_step_cc

    c = subtree_levels
    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]

    def step(i, S, T):
        return _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )

    for i in range(c):
        S, T = step(i, S, T)
    if c:
        j = jax.lax.axis_index(LEAF_AXIS)
        S = [jax.lax.dynamic_slice_in_dim(s, j, 1, axis=1) for s in S]
        T = jax.lax.dynamic_slice_in_dim(T, j, 1, axis=1)
    for i in range(c, nu):
        S, T = step(i, S, T)
    return S, T


def _sharded_eval_full_fast_sm(
    mesh: Mesh, nu: int, subtree_levels: int, entry: int = -1
):
    """Sharded fast-profile evaluator for a (mesh, domain) bucket.

    The fast profile's state is word-oriented ([K, W] uint32 per seed word,
    models/dpf_chacha.py), so the key batch shards on axis 0 and the leaf
    axis slices each key's subtree on the node axis — same zero-comms
    decomposition as the bit-plane path.  ``entry >= 0`` finishes levels
    entry..nu-1 plus leaf conversion per shard in the VMEM expand kernel
    (models/dpf_chacha._finish_pk) — the same kernel the single-chip path
    runs; the per-shard CW operands are lane-padded in-graph."""
    from ..models.dpf_chacha import _convert_leaves_cc, _finish_pk

    def body(seeds, ts, scw, tcw, fcw):
        if entry < 0:
            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, nu, subtree_levels
            )
            return _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        S, T = expand_subtree_local_cc(
            seeds, ts, scw, tcw, entry, subtree_levels
        )
        from ..ops.chacha_pallas import cw_operands

        return _finish_pk(
            nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu)
        )

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None),
            P(KEYS_AXIS),
            P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None),
        ),
        out_specs=P(KEYS_AXIS, LEAF_AXIS, None),
        check_vma=False,
    )


@cache
def _sharded_eval_full_fast(
    mesh: Mesh, nu: int, subtree_levels: int, entry: int = -1
):
    return SHARDED_JITS.register(
        jax.jit(_sharded_eval_full_fast_sm(mesh, nu, subtree_levels, entry))
    )


def _sharded_fast_entry_level(
    nu: int, subtree_levels: int, k_per_shard: int
) -> int:
    """Expand-kernel entry level for a shard (or -1 for the XLA pipeline):
    the shard's kernel entry must be >= 128 nodes wide, which sits
    ``subtree_levels`` deeper than in the single-chip plan."""
    from ..ops import chacha_pallas as cp

    if cp.expand_backend() != "pallas" or not cp.kernel_usable(
        nu, k_per_shard, subtree_levels
    ):
        return -1
    return cp.entry_level(nu, subtree_levels + 7)


def _fast_pad_quantum(mesh: Mesh, nu: int, subtree_levels: int) -> int:
    """Key-axis padding quantum for the sharded fast evaluator: whole lane
    words per shard, times the expand kernel's 8-key sublane tile when the
    kernel route is structurally possible.  Single source for
    eval_full_sharded_fast AND multihost.distribute_fast_batch, so input
    placement and the compiled evaluator can never disagree on K."""
    from ..ops import chacha_pallas as cp

    n_keys = mesh.shape[KEYS_AXIS]
    if cp.expand_backend() == "pallas" and nu - subtree_levels >= 7:
        return n_keys * cp._EKT
    return n_keys


def eval_full_sharded_fast(kb, mesh: Mesh) -> np.ndarray:
    """Sharded full-domain evaluation of a fast-profile key batch ->
    uint8[K, out_bytes] (out_bytes = 2^(log_n-3), minimum 64).

    ``kb`` is a :class:`~dpf_tpu.models.keys_chacha.KeyBatchFast`; the key
    batch is zero-padded to a multiple of the ``keys`` axis (times the
    kernel's 8-key sublane tile when the kernel route is eligible)."""
    n_keys = mesh.shape[KEYS_AXIS]
    c = leaf_axis_levels(mesh, kb.nu, kb.log_n)
    quantum = _fast_pad_quantum(mesh, kb.nu, c)
    padded = _pad_fast_batch(kb, (-kb.k) % quantum)
    entry = _sharded_fast_entry_level(kb.nu, c, padded.k // n_keys)
    fn = _sharded_eval_full_fast(mesh, kb.nu, c, entry)
    # host-sync: final reply marshalling (sharded full-domain words)
    words = np.asarray(fn(*padded.device_args()))
    return np.ascontiguousarray(words[: kb.k]).view("<u1").reshape(kb.k, -1)


def _pad_fast_batch(kb, pad: int):
    """Zero-pad the key axis; memoized on ``kb`` so repeated sharded calls
    reuse the padded batch's device-resident operands."""
    from ..models.keys_chacha import KeyBatchFast

    if not pad:
        return kb
    cache = kb._padded or {}
    if pad in cache:
        return cache[pad]

    def padk(a):
        return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

    padded = KeyBatchFast(
        kb.log_n, padk(kb.seeds), padk(kb.ts), padk(kb.scw),
        padk(kb.tcw), padk(kb.fcw),
    )
    cache[pad] = padded
    kb._padded = cache
    return padded


def _pad_compat_batch(kb: KeyBatch, pad: int) -> KeyBatch:
    """Compat mirror of :func:`_pad_fast_batch` (same memoization reason —
    the padded copy carries the _point_masks device cache)."""
    if not pad:
        return kb
    cache = kb._padded or {}
    if pad in cache:
        return cache[pad]
    padded = KeyBatch(
        kb.log_n,
        *(
            np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            for a in (kb.seeds, kb.ts, kb.scw, kb.tcw, kb.fcw)
        ),
    )
    cache[pad] = padded
    kb._padded = cache
    return padded


# ---------------------------------------------------------------------------
# Sharded pointwise evaluation — key-batch data parallelism, no collectives
# ---------------------------------------------------------------------------


def _sharded_eval_points_sm(
    mesh: Mesh, nu: int, log_n: int, qp: int, backend: str,
    use_walk_kernel: bool = False, packed: bool = False,
):
    """Compat pointwise walk sharded over the ``keys`` axis.  Queries travel
    with their keys (each shard walks its own (key, query) lanes); meshes
    with a leaf axis recompute redundantly across it.  xs_hi shards with
    the keys when the domain needs the high index half (log_n > 32); below
    that it is the replicated [1, 1] dummy.  ``use_walk_kernel`` routes
    each shard through the VMEM whole-walk kernel (the single-chip TPU
    default; caller guarantees per-shard key counts tile it), returning
    the same unpacked uint8 bits.  ``packed`` keeps each shard's output
    bit-packed (the walk kernel's words pass through untouched; the XLA
    body packs shard-locally) so the cross-shard gather and the D2H move
    32x less data."""
    from ..models.dpf import _eval_points_body, _eval_points_walk_body

    def body(seed_m, t_m, scw_m, tl_m, tr_m, fcw_m, xs_hi, xs_lo):
        if use_walk_kernel:
            words = _eval_points_walk_body(
                nu, log_n, seed_m, t_m, scw_m, tl_m, tr_m, fcw_m,
                xs_hi, xs_lo, qp,
            )
            if packed:
                return words  # the kernel's native packed output
            k = words.shape[0]
            lane = jnp.arange(32, dtype=jnp.uint32)
            bits = (words[:, :, None] >> lane) & jnp.uint32(1)
            return bits.reshape(k, qp * 32).astype(jnp.uint8)
        bits = _eval_points_body(
            nu, log_n, seed_m, t_m, scw_m, tl_m, tr_m, fcw_m,
            xs_hi, xs_lo, qp, backend,
        )
        if packed:
            return bitpack.pack_bits_jnp(bits)  # shard-local pack
        return bits

    keyed = P(None, KEYS_AXIS)
    hi_spec = P(KEYS_AXIS, None) if log_n > 32 else P(None, None)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            keyed, P(KEYS_AXIS), P(None, None, KEYS_AXIS),
            keyed, keyed, keyed, hi_spec, P(KEYS_AXIS, None),
        ),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _sharded_eval_points(
    mesh: Mesh, nu: int, log_n: int, qp: int, backend: str,
    use_walk_kernel: bool = False, packed: bool = False,
):
    return SHARDED_JITS.register(
        jax.jit(
            _sharded_eval_points_sm(
                mesh, nu, log_n, qp, backend, use_walk_kernel, packed
            )
        )
    )


def eval_points_sharded(
    kb: KeyBatch, xs: np.ndarray, mesh: Mesh, backend: str | None = None,
    packed: bool = False,
) -> np.ndarray:
    """Sharded batched pointwise evaluation (compat profile):
    xs uint64[K, Q] -> uint8[K, Q], key batch sharded over the ``keys``
    axis — pure data parallelism, zero cross-chip communication (the
    reference Eval is one key / one point at a time, dpf/dpf.go:171).
    ``backend`` selects the PRG kernel set per shard (models/dpf).
    ``packed`` returns uint32[K, ceil(Q/32)] packed words, packed
    SHARD-LOCALLY before the output gather (core/bitpack contract)."""
    from ..models.dpf import _point_masks

    backend = backend or default_backend()

    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dpf: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dpf: query index out of domain")
    n_keys = mesh.shape[KEYS_AXIS]
    K, Q = xs.shape
    from ..ops import aes_pallas

    from ..models import dpf as mdpf

    use_walk = (
        (not mdpf._WALK_KERNEL_BROKEN or aes_pallas.walk_forced())
        and aes_pallas.walk_backend() == "pallas"
        and (backend in _BM_BACKENDS or aes_pallas.walk_forced())
    )
    # Per-shard key counts must tile the walk kernel's 8-key sublane tile.
    quantum = n_keys * (aes_pallas._PKT if use_walk else 1)
    pad = (-K) % quantum
    kbp = _pad_compat_batch(kb, pad)
    xsp = xs
    if pad:
        xsp = np.concatenate([xsp, np.zeros((pad, Q), np.uint64)])
    pad_q = (-Q) % 32
    if pad_q:
        xsp = np.concatenate(
            [xsp, np.zeros((xsp.shape[0], pad_q), np.uint64)], axis=1
        )
    qp = xsp.shape[1] // 32
    xs_lo = jnp.asarray((xsp & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if kbp.log_n > 32:
        xs_hi = jnp.asarray((xsp >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, 1), jnp.uint32)
    fn = _sharded_eval_points(
        mesh, kbp.nu, kbp.log_n, qp, backend, use_walk, packed
    )
    try:
        # host-sync: final reply marshalling (sharded pointwise rows)
        out = np.asarray(fn(*_point_masks(kbp), xs_hi, xs_lo))
    except Exception as e:  # noqa: BLE001
        if not use_walk:
            raise
        mdpf._walk_kernel_degraded(e)
        return eval_points_sharded(kb, xs, mesh, backend, packed)
    if packed:
        return bitpack.mask_tail(out[:K], Q)
    return out[:K, :Q]


def _sharded_eval_points_fast_sm(
    mesh: Mesh, nu: int, log_n: int, qt: int = 0, packed: bool = False
):
    """Fast-profile pointwise walk sharded over the ``keys`` axis.  State is
    query-major [Q, K] (models/dpf_chacha.py), so the key axis is LAST.

    ``qt > 0`` routes each shard's walk through the Pallas whole-walk
    kernel (ops/chacha_pallas._walk_raw) with that query tile — the same
    kernel the single-chip path runs; the per-shard key-minor operands
    (rows x K) are built in-graph from the sharded key material (tiny
    transposes against the walk itself).  ``packed`` packs each shard's
    bits into uint32[K_shard, Q/32] words before the output gather
    (core/bitpack; caller pads Q to 32), so the output's key axis moves
    FIRST."""
    from ..core import chacha_np as cc
    from ..models.dpf_chacha import _eval_points_cc_body

    def body(seeds, ts, scw, tcw, fcw, xs_hi, xs_lo):
        if not qt:
            bits = _eval_points_cc_body(
                nu, log_n, seeds, ts, scw, tcw, fcw, xs_hi, xs_lo
            )
            if packed:
                return bitpack.pack_bits_qmajor_jnp(bits)
            return bits
        from ..ops import chacha_pallas as cp

        k = seeds.shape[0]
        meta = jnp.stack(
            [
                ts,
                jnp.full((k,), log_n, jnp.uint32),
                jnp.full((k,), cc.LEAF_BITS - 1, jnp.uint32),
            ]
        )
        seeds_t = seeds.T
        if nu:
            scw_t = jnp.moveaxis(scw, 0, 2).reshape(4 * nu, k)
            tcw_t = jnp.moveaxis(tcw, 0, 2).reshape(2 * nu, k)
        else:
            scw_t = jnp.zeros((4, k), jnp.uint32)
            tcw_t = jnp.zeros((2, k), jnp.uint32)
        bits = cp._walk_raw(
            meta, seeds_t, scw_t, tcw_t, fcw.T, xs_lo, xs_hi,
            log_n, nu, qt,
        )
        if packed:
            return bitpack.pack_bits_qmajor_jnp(bits)  # shard-local pack
        return bits.astype(jnp.uint8)

    # Kernel routes shard the hi operand with the keys even when it is the
    # never-read [1, K] dummy (the kernel's block spec is key-minor).
    hi_spec = P(None, None) if (log_n <= 32 and not qt) else P(None, KEYS_AXIS)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None), P(KEYS_AXIS), P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None, None), P(KEYS_AXIS, None),
            hi_spec, P(None, KEYS_AXIS),
        ),
        out_specs=P(KEYS_AXIS, None) if packed else P(None, KEYS_AXIS),
        check_vma=False,
    )


@cache
def _sharded_eval_points_fast(
    mesh: Mesh, nu: int, log_n: int, qt: int = 0, packed: bool = False
):
    return SHARDED_JITS.register(
        jax.jit(_sharded_eval_points_fast_sm(mesh, nu, log_n, qt, packed))
    )


def eval_points_sharded_fast(
    kb, xs: np.ndarray, mesh: Mesh, packed: bool = False
) -> np.ndarray:
    """Sharded batched pointwise evaluation (fast profile):
    xs uint64[K, Q] -> uint8[K, Q], key batch sharded over ``keys``.
    Each shard walks via the Pallas whole-walk kernel when its key count
    tiles the kernel's 128-key lane quantum (pad target), else the XLA
    body.  ``packed`` returns uint32[K, ceil(Q/32)] packed words, packed
    SHARD-LOCALLY before the output gather (core/bitpack contract)."""
    from ..models.dpf_chacha import _split_queries
    from ..ops import chacha_pallas as cp

    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dpf-fast: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dpf-fast: query index out of domain")
    n_keys = mesh.shape[KEYS_AXIS]
    K, Q = xs.shape
    use_kernel = cp.points_backend() == "pallas"
    quantum = n_keys * cp._KT if use_kernel else n_keys
    pad = (-K) % quantum
    padded = _pad_fast_batch(kb, pad)
    if pad:
        xs = np.concatenate([xs, np.zeros((pad, Q), np.uint64)])
    pad_q = (-Q) % 32 if packed else ((-Q) % 8 if use_kernel else 0)
    if pad_q:
        xs = np.concatenate(
            [xs, np.zeros((xs.shape[0], pad_q), np.uint64)], axis=1
        )
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)  # [Qp, Kpad]
    qt = cp._qtile(xs_lo.shape[0]) if use_kernel else 0
    if use_kernel and kb.log_n <= 32:
        xs_hi = jnp.zeros((1, padded.k), jnp.uint32)  # never read
    fn = _sharded_eval_points_fast(mesh, kb.nu, kb.log_n, qt, packed)
    # host-sync: final reply marshalling (sharded pointwise rows)
    out = np.asarray(fn(*padded.device_args(), xs_hi, xs_lo))
    if packed:
        return bitpack.mask_tail(out[:K], Q)
    return out.T[:K, :Q]


def _sharded_dcf_points_sm(
    mesh: Mesh, nu: int, log_n: int, qt: int, packed: bool = False
):
    """DCF comparison walk sharded over the ``keys`` axis (one key per
    gate, models/dcf.py), via the whole-walk kernel's dcf mode per shard;
    key-minor operands built in-graph like the DPF route above.
    ``packed`` packs each shard's bits into uint32[K_shard, Q/32] words
    before the output gather (core/bitpack; caller pads Q to 32), so the
    output's key axis moves FIRST."""
    from ..core import chacha_np as cc
    from ..models.dpf_chacha import _eval_points_cc_body

    def body(seeds, ts, scw, tcw, vcw, fvcw, xs_hi, xs_lo):
        if not qt:
            bits = _eval_points_cc_body(
                nu, log_n, seeds, ts, scw, tcw, fvcw, xs_hi, xs_lo, 0, vcw
            )
            if packed:
                return bitpack.pack_bits_qmajor_jnp(bits)
            return bits
        from ..ops import chacha_pallas as cp

        k = seeds.shape[0]
        meta = jnp.stack(
            [
                ts,
                jnp.full((k,), log_n, jnp.uint32),
                jnp.full((k,), cc.LEAF_BITS - 1, jnp.uint32),
            ]
        )
        if nu:
            scw_t = jnp.moveaxis(scw, 0, 2).reshape(4 * nu, k)
            tcw_t = jnp.moveaxis(tcw, 0, 2).reshape(2 * nu, k)
            vcw_t = vcw.T
        else:
            scw_t = jnp.zeros((4, k), jnp.uint32)
            tcw_t = jnp.zeros((2, k), jnp.uint32)
            vcw_t = jnp.zeros((1, k), jnp.uint32)
        bits = cp._walk_raw(
            meta, seeds.T, scw_t, tcw_t, fvcw.T, xs_lo, xs_hi,
            log_n, nu, qt, vcw_t=vcw_t, dcf=True,
        )
        if packed:
            return bitpack.pack_bits_qmajor_jnp(bits)  # shard-local pack
        return bits.astype(jnp.uint8)

    hi_spec = P(None, None) if (log_n <= 32 and not qt) else P(None, KEYS_AXIS)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None), P(KEYS_AXIS), P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None, None), P(KEYS_AXIS, None),
            P(KEYS_AXIS, None), hi_spec, P(None, KEYS_AXIS),
        ),
        out_specs=P(KEYS_AXIS, None) if packed else P(None, KEYS_AXIS),
        check_vma=False,
    )


@cache
def _sharded_dcf_points(
    mesh: Mesh, nu: int, log_n: int, qt: int, packed: bool = False
):
    return SHARDED_JITS.register(
        jax.jit(_sharded_dcf_points_sm(mesh, nu, log_n, qt, packed))
    )


def eval_lt_points_sharded(
    kb, xs: np.ndarray, mesh: Mesh, packed: bool = False
) -> np.ndarray:
    """Sharded DCF comparison evaluation: xs uint64[K, Q] -> uint8[K, Q]
    shares of ``1{x < alpha}``, one gate per key, key batch sharded over
    the ``keys`` axis (zero cross-chip communication).  ``packed``
    returns uint32[K, ceil(Q/32)] packed words, packed SHARD-LOCALLY
    before the output gather (core/bitpack contract)."""
    from ..models.dcf import DcfKeyBatch
    from ..models.dpf_chacha import _split_queries
    from ..ops import chacha_pallas as cp

    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dcf: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dcf: query index out of domain")
    n_keys = mesh.shape[KEYS_AXIS]
    K, Q = xs.shape
    use_kernel = cp.points_backend() == "pallas"
    quantum = n_keys * cp._KT if use_kernel else n_keys
    pad = (-K) % quantum
    if pad:
        def padk(a):
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

        kb = DcfKeyBatch(
            kb.log_n, padk(kb.seeds), padk(kb.ts), padk(kb.scw),
            padk(kb.tcw), padk(kb.vcw), padk(kb.fvcw),
        )
        xs = np.concatenate([xs, np.zeros((pad, Q), np.uint64)])
    pad_q = (-Q) % 32 if packed else ((-Q) % 8 if use_kernel else 0)
    if pad_q:
        xs = np.concatenate(
            [xs, np.zeros((xs.shape[0], pad_q), np.uint64)], axis=1
        )
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)
    qt = cp._qtile(xs_lo.shape[0]) if use_kernel else 0
    if use_kernel and kb.log_n <= 32:
        xs_hi = jnp.zeros((1, kb.k), jnp.uint32)  # never read
    fn = _sharded_dcf_points(mesh, kb.nu, kb.log_n, qt, packed)
    # host-sync: final reply marshalling (sharded DCF shares)
    out = np.asarray(fn(*kb.device_args(), xs_hi, xs_lo))
    if packed:
        return bitpack.mask_tail(out[:K], Q)
    return out.T[:K, :Q]


def eval_interval_points_sharded(
    ik, xs: np.ndarray, mesh: Mesh, packed: bool = False
) -> np.ndarray:
    """Sharded DCF interval evaluation: the host-side upper^lower^const
    combine of ``models/dcf.eval_interval_points`` over the sharded
    comparison walk — the fused 2K-key batch shards on the ``keys``
    axis, so both gate sets of every interval still evaluate in ONE
    device program (now one per shard)."""
    from ..models import dcf

    return dcf.eval_interval_points(
        ik, xs, packed=packed,
        lt_eval=lambda both, qs, packed: eval_lt_points_sharded(
            both, qs, mesh, packed=packed
        ),
    )


# ---------------------------------------------------------------------------
# Sharded aggregation fold — shard-local fold, ONE all-reduce per chunk
# ---------------------------------------------------------------------------


def _sharded_agg_fold_sm(mesh: Mesh, op: str):
    """One streamed secure-aggregation fold chunk across the mesh
    (apps/aggregation.py semantics): client share rows shard over the
    ``keys`` axis, each shard folds its rows locally, and the shard
    partials meet in a single all-reduce — XOR via the all-gather +
    lane-XOR idiom (:func:`xor_allreduce`), add via ``psum`` — before
    the replicated carry joins.  Zero rows are the identity of both
    ops, so pad-to-mesh-multiple never changes the aggregate."""

    def body(carry, rows):
        if op == "xor":
            local = jax.lax.reduce(
                rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
            )
            return carry ^ xor_allreduce(local, KEYS_AXIS)
        local = jnp.sum(rows, axis=0, dtype=jnp.uint32)
        # uint32 addition wraps: mod 2^32 by construction, and psum of
        # the shard partials commutes with the wrap.
        return carry + jax.lax.psum(local, KEYS_AXIS)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(None), P(KEYS_AXIS, None)),
        out_specs=P(None),
        check_vma=False,
    )


# The donated carry position of the sharded fold jit below (the
# perf-contract analysis pass lowers the donate=True factory and
# verifies the carry actually reaches XLA donated — this constant is
# its declared expectation, kept next to the jit it describes).
AGG_FOLD_DONATE_ARGNUMS = (0,)


@cache
def _sharded_agg_fold(mesh: Mesh, op: str, donate: bool = False):
    fn = _sharded_agg_fold_sm(mesh, op)
    # The carry is dead after the fold (the caller rebinds it every
    # chunk) — donating it lets XLA reuse the replicated buffer in
    # place across a million-client upload's chunk sequence.
    jitted = jax.jit(fn, donate_argnums=(0,)) if donate else jax.jit(fn)
    return SHARDED_JITS.register(jitted)


def fold_rows_sharded(
    op: str, carry: np.ndarray, rows: np.ndarray, mesh: Mesh,
    donate: bool = False,
):
    """Mesh dispatch of one aggregation fold chunk: uint32[R, W] rows +
    uint32[W] carry -> the folded device vector (caller marshals).  R
    must be a multiple of the ``keys`` axis (the plan layer's bucket
    flooring guarantees it)."""
    R = int(rows.shape[0])
    n = int(mesh.shape[KEYS_AXIS])
    if R % n:
        raise ValueError(f"agg: rows {R} must tile the {n}-shard mesh")
    return _sharded_agg_fold(mesh, op, donate)(carry, rows)


# ---------------------------------------------------------------------------
# Sharded incremental heavy-hitter frontier extension (apps/hh_state.py)
#
# The frontier state shards over the ``keys`` axis (fast profile:
# key-major arrays; compat: the lane-word axis) and the one-level
# extend is embarrassingly parallel — ZERO collectives, the perf
# contract pins it; the public sel/idx operands replicate.  Only the
# MXU count fold (PUBLIC reconstructed rows) meets in a collective:
# shard-local matmul + ONE psum over the client shards.
# ---------------------------------------------------------------------------


def _sharded_hh_extend_fast_sm(mesh: Mesh):
    from ..models import dpf_chacha as dc

    return shard_map_compat(
        dc._hh_extend_cc_body,
        mesh=mesh,
        in_specs=(P(KEYS_AXIS, None),) * 5
        + (P(None),)
        + (P(KEYS_AXIS),) * 6,
        out_specs=(P(KEYS_AXIS, None),) * 6,
        check_vma=False,
    )


@cache
def _sharded_hh_extend_fast(mesh: Mesh, donate: bool = False):
    fn = _sharded_hh_extend_fast_sm(mesh)
    jitted = (
        jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4)) if donate
        else jax.jit(fn)
    )
    return SHARDED_JITS.register(jitted)


def _sharded_hh_leaf_first_fast_sm(mesh: Mesh, ibits: int):
    from functools import partial

    from ..models import dpf_chacha as dc

    return shard_map_compat(
        partial(dc._hh_leaf_first_cc_body, ibits),
        mesh=mesh,
        in_specs=(P(KEYS_AXIS, None),) * 5
        + (P(None),)
        + (P(KEYS_AXIS),) * 16,
        out_specs=(P(KEYS_AXIS, None, None), P(KEYS_AXIS, None)),
        check_vma=False,
    )


@cache
def _sharded_hh_leaf_first_fast(mesh: Mesh, ibits: int, donate: bool = False):
    fn = _sharded_hh_leaf_first_fast_sm(mesh, ibits)
    jitted = (
        jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4)) if donate
        else jax.jit(fn)
    )
    return SHARDED_JITS.register(jitted)


def _sharded_hh_leaf_fold_fast_sm(mesh: Mesh, m: int, ibits: int):
    from functools import partial

    from ..models import dpf_chacha as dc

    return shard_map_compat(
        partial(dc._hh_leaf_fold_cc_body, m, ibits),
        mesh=mesh,
        in_specs=(P(KEYS_AXIS, None, None), P(None)),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _sharded_hh_leaf_fold_fast(mesh: Mesh, m: int, ibits: int):
    return SHARDED_JITS.register(
        jax.jit(_sharded_hh_leaf_fold_fast_sm(mesh, m, ibits))
    )


def _sharded_hh_extend_compat_sm(mesh: Mesh):
    from ..models import dpf as dm

    return shard_map_compat(
        dm._hh_extend_body,
        mesh=mesh,
        in_specs=(
            P(None, None, KEYS_AXIS),
            P(None, KEYS_AXIS),
            P(None),
            P(None, KEYS_AXIS),
            P(KEYS_AXIS),
            P(KEYS_AXIS),
        ),
        out_specs=(
            P(None, None, KEYS_AXIS),
            P(None, KEYS_AXIS),
            P(KEYS_AXIS, None),
        ),
        check_vma=False,
    )


@cache
def _sharded_hh_extend_compat(mesh: Mesh, donate: bool = False):
    fn = _sharded_hh_extend_compat_sm(mesh)
    jitted = jax.jit(fn, donate_argnums=(0, 1)) if donate else jax.jit(fn)
    return SHARDED_JITS.register(jitted)


def _sharded_hh_leaf_first_compat_sm(mesh: Mesh, ibits: int):
    from functools import partial

    from ..models import dpf as dm

    return shard_map_compat(
        partial(dm._hh_leaf_first_body, ibits),
        mesh=mesh,
        in_specs=(
            P(None, None, KEYS_AXIS),
            P(None, KEYS_AXIS),
            P(None),
            P(None, None, KEYS_AXIS),
        ),
        out_specs=(P(None, None, KEYS_AXIS), P(KEYS_AXIS, None)),
        check_vma=False,
    )


@cache
def _sharded_hh_leaf_first_compat(
    mesh: Mesh, ibits: int, donate: bool = False
):
    fn = _sharded_hh_leaf_first_compat_sm(mesh, ibits)
    jitted = jax.jit(fn, donate_argnums=(0, 1)) if donate else jax.jit(fn)
    return SHARDED_JITS.register(jitted)


def _sharded_hh_leaf_fold_compat_sm(mesh: Mesh, m: int, ibits: int):
    from functools import partial

    from ..models import dpf as dm

    return shard_map_compat(
        partial(dm._hh_leaf_fold_body, m, ibits),
        mesh=mesh,
        in_specs=(P(None, None, KEYS_AXIS), P(None)),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _sharded_hh_leaf_fold_compat(mesh: Mesh, m: int, ibits: int):
    return SHARDED_JITS.register(
        jax.jit(_sharded_hh_leaf_fold_compat_sm(mesh, m, ibits))
    )


def hh_extend_fn_sharded(
    mesh: Mesh, profile: str, phase: str, *, ibits: int = 0, m: int = 0,
    donate: bool = False,
):
    """The sharded extend executable for one (profile, phase): plans
    dispatches through this exactly like the single-device jit twins in
    the model modules (same bodies under shard_map, byte-identical
    rows)."""
    if profile == "fast":
        if phase == "tree":
            return _sharded_hh_extend_fast(mesh, donate)
        if phase == "leaf_first":
            return _sharded_hh_leaf_first_fast(mesh, ibits, donate)
        return _sharded_hh_leaf_fold_fast(mesh, m, ibits)
    if phase == "tree":
        return _sharded_hh_extend_compat(mesh, donate)
    if phase == "leaf_first":
        return _sharded_hh_leaf_first_compat(mesh, ibits, donate)
    return _sharded_hh_leaf_fold_compat(mesh, m, ibits)


def _sharded_hh_count_fold_sm(mesh: Mesh):
    from ..models import hh_fold

    def body(x):
        return jax.lax.psum(hh_fold._count_fold_body(x), KEYS_AXIS)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(KEYS_AXIS, None),),
        out_specs=P(None),
        check_vma=False,
    )


@cache
def _sharded_hh_count_fold(mesh: Mesh):
    return SHARDED_JITS.register(jax.jit(_sharded_hh_count_fold_sm(mesh)))


def hh_count_fold_sharded(x: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Mesh dispatch of the MXU count fold: uint32[G, W] public
    reconstructed rows (G a mesh multiple) -> int64[W * 32] counts via
    shard-local int8 matmuls and ONE psum."""
    g = int(x.shape[0])
    n = int(mesh.shape[KEYS_AXIS])
    if g % n:
        raise ValueError(f"hh: rows {g} must tile the {n}-shard mesh")
    # host-sync: tiny per-round count vector
    return np.asarray(_sharded_hh_count_fold(mesh)(x), dtype=np.int64)


# ---------------------------------------------------------------------------
# Sharded key generation (models/keys_gen.py) — the dealer over the mesh
#
# Gen is pure key-batch data parallelism: each shard towers its slice of
# the drawn root seeds with its slice of the alpha bits — ZERO
# collectives (the perf contract pins it).  The ChaCha towers shard
# key-major (axis 0 / the trailing K axis of level-major operands); the
# compat planes tower shards its lane-word axis, i.e. contiguous 32-key
# groups, so per-shard plane unpacks concatenate back in global key
# order.  Leaf-axis meshes recompute redundantly across LEAF_AXIS, like
# the pointwise routes.
# ---------------------------------------------------------------------------


def _sharded_gen_cc_sm(mesh: Mesh, nu: int, dcf: bool, fused: bool):
    from functools import partial

    from ..models import keys_gen

    level = P(None, KEYS_AXIS)  # level-major [nu, K] operands/CWs
    return shard_map_compat(
        partial(keys_gen._gen_cc_body, nu, dcf, fused),
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None),  # s0 words
            P(KEYS_AXIS, None),  # s1 words
            P(KEYS_AXIS),  # t0
            P(KEYS_AXIS),  # t1
            level,  # alpha bits
        ),
        out_specs=(P(None, KEYS_AXIS, None), level, level, P(KEYS_AXIS, None))
        + ((level,) if dcf else ()),
        check_vma=False,
    )


@cache
def gen_cc_sharded_fn(
    mesh: Mesh, nu: int, dcf: bool, fused: bool, donate: bool = False
):
    """The sharded ChaCha gen tower (``fast`` / ``dcf``) for one
    (mesh, domain) bucket — the mesh twin of keys_gen._gen_cc_jit; the
    donated variant donates the root seed/control-bit operands exactly
    like the single-device twin."""
    fn = _sharded_gen_cc_sm(mesh, nu, dcf, fused)
    jitted = (
        jax.jit(fn, donate_argnums=(0, 1, 2, 3)) if donate else jax.jit(fn)
    )
    return SHARDED_JITS.register(jitted)


def _sharded_gen_compat_sm(mesh: Mesh, nu: int, fused: bool):
    from functools import partial

    from ..models import keys_gen

    lanes = P(None, KEYS_AXIS)  # [128, W] planes / [nu, W] lane masks
    return shard_map_compat(
        partial(keys_gen._gen_compat_body, nu, fused),
        mesh=mesh,
        in_specs=(lanes, lanes, P(KEYS_AXIS), P(KEYS_AXIS), lanes),
        out_specs=(
            P(KEYS_AXIS, None, None),  # per-key scw words
            lanes,  # tlcw lane words
            lanes,  # trcw lane words
            P(KEYS_AXIS, None),  # per-key fcw words
        ),
        check_vma=False,
    )


@cache
def gen_compat_sharded_fn(
    mesh: Mesh, nu: int, fused: bool, donate: bool = False
):
    """The sharded compat gen tower for one (mesh, domain) bucket — the
    mesh twin of keys_gen._gen_compat_jit (caller pads the key axis to
    32 lanes x shard count, keys_gen.gen_device_compat)."""
    fn = _sharded_gen_compat_sm(mesh, nu, fused)
    jitted = (
        jax.jit(fn, donate_argnums=(0, 1, 2, 3)) if donate else jax.jit(fn)
    )
    return SHARDED_JITS.register(jitted)
