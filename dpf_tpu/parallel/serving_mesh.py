"""The serving layer's mesh: one resolved ``(keys,)`` mesh, shared by
every plan-cached dispatch.

``parallel/sharding.py`` owns the shard_map evaluators; this module owns
the OPERATIONAL question — is the serving fast path running sharded
right now, and over how many chips?  The answer must be consistent
across the whole request pipeline (plan keys, key-cache identity,
batcher quanta, metrics labels), so everything reads it from here:

  * ``DPF_TPU_MESH`` (off|auto|on) gates the feature.  ``auto`` shards
    only on TPU (multi-device CPU is a test topology, not a deployment);
    ``on`` shards whenever >= 2 devices are visible — how the CPU test
    suite and the bench mesh section drive the 8-virtual-device mesh.
  * ``DPF_TPU_MESH_DEVICES`` budgets the mesh (0 = all visible).  The
    shard count is rounded DOWN to a power of two so the plan cache's
    pow2 K-buckets always divide evenly across shards — pad-to-mesh-
    multiple is free, never a reshard.
  * ``suspended()`` is the degraded-mode override: while the circuit
    breaker is not closed the serving state wraps dispatches in it, and
    every plan call inside falls back to the single-device executables
    (byte-identical by the mesh test contract) without touching the env.

The resolved mesh is cached (mesh identity is part of jit cache keys —
rebuilding it per request would retrace); ``reset()`` drops the cache
for tests/benches that flip the knobs mid-process.
"""

from __future__ import annotations

import threading
from typing import Any

from ..core import knobs

KEYS_AXIS = "keys"

_LOCK = threading.Lock()
# (resolved?, mesh | None) — resolution touches jax.devices(), so it is
# lazy and cached; None means "serving is single-device".
_RESOLVED: list = [False, None]

_TLS = threading.local()


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


def _resolve():
    """Build (or decline to build) the serving mesh from the knobs and
    the visible device topology.  -> Mesh | None."""
    raw = knobs.get_raw("DPF_TPU_MESH")
    mode = knobs.knob("DPF_TPU_MESH").default if raw is None else raw.lower()
    if mode in ("off", "0", "false", ""):
        return None
    if mode in ("on", "1", "true"):
        mode = "on"
    elif mode != "auto":
        raise ValueError(f"DPF_TPU_MESH={mode!r} unknown (off|auto|on)")
    import jax

    if mode == "auto" and jax.default_backend() != "tpu":
        return None
    devices = list(jax.devices())
    budget = knobs.get_int("DPF_TPU_MESH_DEVICES")
    if budget > 0:
        devices = devices[:budget]
    n = _pow2_floor(len(devices))
    if n < 2:
        return None
    from .sharding import make_mesh

    return make_mesh(n_keys=n, n_leaf=1, devices=devices[:n])


def serving_mesh():
    """The resolved serving mesh (None = single-device serving).  Cached;
    ``reset()`` re-reads the knobs."""
    with _LOCK:
        if not _RESOLVED[0]:
            _RESOLVED[1] = _resolve()
            _RESOLVED[0] = True
        return _RESOLVED[1]


def reset() -> None:
    """Drop the cached mesh so the next call re-reads DPF_TPU_MESH /
    DPF_TPU_MESH_DEVICES (tests and the bench mesh section flip them
    mid-process)."""
    with _LOCK:
        _RESOLVED[0] = False
        _RESOLVED[1] = None


def suspended():
    """Context manager: plan dispatches inside run single-device even
    when the mesh is on — the degraded-mode override the serving state
    engages while the circuit breaker is not closed (a recovering device
    must re-prove itself on the simplest executable, and a half-open
    trial must not fan a wedged collective across every chip)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        prev = getattr(_TLS, "suspended", 0)
        _TLS.suspended = prev + 1
        try:
            yield
        finally:
            _TLS.suspended = prev

    return _cm()


def is_suspended() -> bool:
    return bool(getattr(_TLS, "suspended", 0))


def active_mesh():
    """The mesh the CURRENT dispatch should use: the resolved serving
    mesh, unless this thread is inside ``suspended()`` (degraded mode).
    Every ``core.plans.run_*`` body consults this exactly once per call,
    so plan key and executable can never disagree."""
    if is_suspended():
        return None
    return serving_mesh()


def shards() -> int:
    """Shard count of the dispatch mesh (0 = single-device).  This is
    the ``mesh`` field of plan keys and the key-cache identity token."""
    mesh = active_mesh()
    if mesh is None:
        return 0
    return int(mesh.shape[KEYS_AXIS])


def coordinate(device) -> str | None:
    """Mesh coordinate label for a device ("keys:3"), or None when the
    device is not part of the serving mesh — the metrics layer labels
    per-device memory gauges with this so scrapes can tell partitioned
    state (per-shard operands) from replicated or off-mesh state."""
    mesh = serving_mesh()
    if mesh is None:
        return None
    for i, d in enumerate(mesh.devices.reshape(-1)):
        if d == device:
            return f"{KEYS_AXIS}:{i}"
    return None


def stats() -> dict[str, Any]:
    """The /v1/stats ``mesh`` block (and the dpf_mesh_shards gauge):
    resolved shard count plus the raw knob values, so a scrape can tell
    a deliberately-off mesh from a topology that could not support one."""
    mesh = serving_mesh()
    return {
        "shards": 0 if mesh is None else int(mesh.shape[KEYS_AXIS]),
        "mode": knobs.get_str("DPF_TPU_MESH"),
        "device_budget": knobs.get_int("DPF_TPU_MESH_DEVICES"),
    }
