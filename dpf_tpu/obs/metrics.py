"""Prometheus text-format exposition for the sidecar (``GET /v1/metrics``).

Design rule: every counter and gauge here is rendered FROM the single
consistent ``/v1/stats`` snapshot (one stats lock, server.py) — the two
surfaces read the same dict, so they cannot drift; the test suite pins
exact equality.  The only state this module owns is what Prometheus
needs and a JSON blob cannot carry: fixed-bucket histograms for
per-phase latency and coalesce size (``MetricsHub``), observed at the
same instrumentation points that feed the phase timers.

Exposition follows the Prometheus text format v0.0.4: ``# HELP`` /
``# TYPE`` per family, counters suffixed ``_total``, histograms with
cumulative ``_bucket{le=...}`` series, an ``le="+Inf"`` bucket equal to
``_count``, and a terminating newline.  ``dpf_tpu/obs/promtext.py`` is
the strict parser the tests (and ``scripts/scrape_metrics.py``) hold
this output against.

Metric labels are exported verbatim, so — like span attributes — label
values are secret-hygiene taint sinks: public metadata only.
"""

from __future__ import annotations

import bisect
import threading

from ..core import knobs

_NAMESPACE = "dpf"

# Coalesce-size buckets: key-rows per dispatch, powers of two up to the
# batcher's DPF_TPU_BATCH_MAX_KEYS default.
_COALESCE_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

_BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


def latency_bounds_s() -> tuple[float, ...]:
    """Histogram bucket bounds for per-phase latency, in seconds, parsed
    from the DPF_TPU_METRICS_BUCKETS_MS knob (comma-separated ms)."""
    raw = knobs.get_str("DPF_TPU_METRICS_BUCKETS_MS")
    # Deduplicated: a repeated bound would render two bucket samples
    # with the same le label, which every strict consumer rejects.
    bounds = sorted(
        {float(tok) / 1e3 for tok in raw.split(",") if tok.strip()}
    )
    if not bounds:
        raise ValueError("DPF_TPU_METRICS_BUCKETS_MS must name >= 1 bucket")
    return tuple(bounds)


class Histogram:
    """Fixed-bucket histogram; ``counts[i]`` is the NON-cumulative count
    of observations v with bounds[i-1] < v <= bounds[i] (counts[-1] is
    the overflow / +Inf bucket).  Rendering cumulates."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple[float, ...]):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def as_dict(self) -> dict:
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }


class MetricsHub:
    """The histogram state behind /v1/metrics.  ``lock`` is the serving
    state's single stats lock (an RLock) so histogram snapshots are
    taken in the same critical section as the counter snapshot."""

    def __init__(self, lock=None, bounds_s: tuple[float, ...] | None = None):
        self._lock = lock if lock is not None else threading.RLock()
        self._bounds = bounds_s if bounds_s is not None else latency_bounds_s()
        self._phase: dict[str, Histogram] = {}
        self._coalesce = Histogram(_COALESCE_BOUNDS)

    def observe_phase(self, name: str, dt_s: float) -> None:
        with self._lock:
            h = self._phase.get(name)
            if h is None:
                h = self._phase[name] = Histogram(self._bounds)
            h.observe(dt_s)

    def observe_coalesce(self, n_keys: int) -> None:
        with self._lock:
            self._coalesce.observe(n_keys)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "phase_latency": {
                    name: h.as_dict() for name, h in self._phase.items()
                },
                "coalesce_size": self._coalesce.as_dict(),
            }


def device_memory_gauges() -> list[tuple[str, str, str, float]]:
    """(device, stat, mesh_coord, value) tuples from
    ``jax.local_devices()`` memory stats — present on TPU backends,
    absent (empty list) on CPU where the runtime reports none.
    ``mesh_coord`` is the device's serving-mesh coordinate ("keys:3") or
    "off" for devices outside the mesh, so a scrape can tell partitioned
    state (per-shard operands, roughly 1/shards each) from replicated or
    off-mesh state instead of eyeballing raw device ids.  Never raises:
    metrics exposition must not depend on backend health."""
    out: list[tuple[str, str, str, float]] = []
    try:
        import jax

        from ..parallel import serving_mesh

        for d in jax.local_devices():
            ms_fn = getattr(d, "memory_stats", None)
            ms = ms_fn() if callable(ms_fn) else None
            if not ms:
                continue
            try:
                coord = serving_mesh.coordinate(d) or "off"
            except Exception:  # noqa: BLE001 — label only, never fatal
                coord = "off"
            for stat in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if stat in ms:
                    out.append(
                        (f"{d.platform}:{d.id}", stat, coord,
                         float(ms[stat]))
                    )
    except Exception:  # noqa: BLE001 — observability must not take traffic down
        return out
    return out


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Writer:
    def __init__(self):
        self.lines: list[str] = []

    def family(self, name: str, kind: str, help_text: str) -> None:
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(self, name: str, labels: dict | None, value) -> None:
        if labels:
            body = ",".join(
                f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
            )
            self.lines.append(f"{name}{{{body}}} {_fmt(value)}")
        else:
            self.lines.append(f"{name} {_fmt(value)}")

    def histogram(self, name: str, labels: dict | None, h: dict) -> None:
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lb = dict(labels or {})
            lb["le"] = _fmt(bound)
            self.sample(f"{name}_bucket", lb, cum)
        lb = dict(labels or {})
        lb["le"] = "+Inf"
        self.sample(f"{name}_bucket", lb, h["count"])
        self.sample(f"{name}_sum", labels, h["sum"])
        self.sample(f"{name}_count", labels, h["count"])

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def render(stats: dict, hists: dict,
           device_mem: list[tuple[str, str, str, float]] | None = None,
           ) -> str:
    """The /v1/metrics body: ``stats`` is the /v1/stats snapshot (the
    SAME dict — counter equality between the two surfaces is structural,
    not coincidental), ``hists`` is ``MetricsHub.snapshot()``."""
    w = _Writer()
    ns = _NAMESPACE
    b = stats["batcher"]
    br = stats["breaker"]
    pl = stats["plans"]
    kc = stats["key_cache"]
    tr = stats.get("trace", {})

    # -- counters ----------------------------------------------------------
    w.family(f"{ns}_requests_total", "counter",
             "Requests admitted to the serving fast path.")
    w.sample(f"{ns}_requests_total", None, b["requests"])
    w.family(f"{ns}_dispatches_total", "counter",
             "Device dispatches issued (coalesced batches count once).")
    w.sample(f"{ns}_dispatches_total", None, b["dispatches"])
    w.family(f"{ns}_keys_dispatched_total", "counter",
             "Key-rows dispatched across all batches.")
    w.sample(f"{ns}_keys_dispatched_total", None, b["keys_dispatched"])
    w.family(f"{ns}_shed_total", "counter",
             "Requests shed by admission control, by watermark kind.")
    w.sample(f"{ns}_shed_total", {"kind": "depth"}, b["shed_depth"])
    w.sample(f"{ns}_shed_total", {"kind": "age"}, b["shed_age"])
    w.family(f"{ns}_expired_total", "counter",
             "Deadline expirations, by where the deadline passed.")
    w.sample(f"{ns}_expired_total", {"where": "queue"}, b["expired_queue"])
    w.sample(f"{ns}_expired_total", {"where": "flight"}, b["expired_flight"])
    w.family(f"{ns}_queue_wait_seconds_total", "counter",
             "Cumulative in-queue wait across admitted requests.")
    w.sample(f"{ns}_queue_wait_seconds_total", None,
             b["queue_wait_seconds"])
    w.family(f"{ns}_dispatch_seconds_total", "counter",
             "Cumulative wall seconds inside device dispatches.")
    w.sample(f"{ns}_dispatch_seconds_total", None, b["dispatch_seconds"])

    w.family(f"{ns}_breaker_transitions_total", "counter",
             "Circuit-breaker transitions, by kind (trip = -> open, "
             "recovery = -> closed).")
    w.sample(f"{ns}_breaker_transitions_total", {"kind": "trip"},
             br["trips"])
    w.sample(f"{ns}_breaker_transitions_total", {"kind": "recovery"},
             br["recoveries"])
    w.family(f"{ns}_breaker_fast_fails_total", "counter",
             "Requests failed fast while the circuit was open/half-open.")
    w.sample(f"{ns}_breaker_fast_fails_total", None, br["fast_fails"])
    w.family(f"{ns}_breaker_retries_total", "counter",
             "Transparent transient-dispatch retries.")
    w.sample(f"{ns}_breaker_retries_total", None, br["retries"])
    w.family(f"{ns}_breaker_transient_failures_total", "counter",
             "Dispatch failures classified transient (pre-retry).")
    w.sample(f"{ns}_breaker_transient_failures_total", None,
             br["transient_failures"])
    w.family(f"{ns}_breaker_probe_runs_total", "counter",
             "Background re-warm probe executions while open.")
    w.sample(f"{ns}_breaker_probe_runs_total", None, br["probe_runs"])

    w.family(f"{ns}_plan_hits_total", "counter",
             "Dispatch-plan cache hits.")
    w.sample(f"{ns}_plan_hits_total", None, pl["hits"])
    w.family(f"{ns}_plan_compiles_total", "counter",
             "Dispatch-plan compiles (cache misses).")
    w.sample(f"{ns}_plan_compiles_total", None, pl["misses"])

    w.family(f"{ns}_keycache_hits_total", "counter",
             "Host-repack LRU hits.")
    w.sample(f"{ns}_keycache_hits_total", None, kc["hits"])
    w.family(f"{ns}_keycache_misses_total", "counter",
             "Host-repack LRU misses.")
    w.sample(f"{ns}_keycache_misses_total", None, kc["misses"])

    pir = stats.get("pir")
    if pir is not None:
        w.family(f"{ns}_pir_queries_total", "counter",
                 "PIR queries answered across registered databases.")
        w.sample(f"{ns}_pir_queries_total", None, pir["queries"])
        w.family(f"{ns}_pir_scans_total", "counter",
                 "Full-database PIR scan dispatches (coalesced query "
                 "batches count once).")
        w.sample(f"{ns}_pir_scans_total", None, pir["scans"])
        w.family(f"{ns}_pir_bytes_scanned_total", "counter",
                 "Database bytes read by PIR scans (padded resident "
                 "bytes per scan).")
        w.sample(f"{ns}_pir_bytes_scanned_total", None,
                 pir["bytes_scanned"])

    hhs = stats.get("hh_state")
    if hhs is not None:
        w.family(f"{ns}_hh_session_hits_total", "counter",
                 "Incremental heavy-hitters rounds served from a cached "
                 "device frontier.")
        w.sample(f"{ns}_hh_session_hits_total", None, hhs["hits"])
        w.family(f"{ns}_hh_session_misses_total", "counter",
                 "Descent rounds that found no (or a mismatched) cached "
                 "session and built a fresh frontier.")
        w.sample(f"{ns}_hh_session_misses_total", None, hhs["misses"])
        w.family(f"{ns}_hh_session_rebuilds_total", "counter",
                 "Stale cached frontiers replanted at the root and "
                 "replayed (byte-identical from-root recompute).")
        w.sample(f"{ns}_hh_session_rebuilds_total", None, hhs["rebuilds"])
        w.family(f"{ns}_hh_session_evictions_total", "counter",
                 "Descent sessions evicted (TTL, LRU budget, digest "
                 "mismatch, or poisoned state).")
        w.sample(f"{ns}_hh_session_evictions_total", None, hhs["evicted"])

    phases = stats.get("phases", {})
    w.family(f"{ns}_phase_seconds_total", "counter",
             "Cumulative wall seconds per request phase.")
    for name in sorted(phases):
        w.sample(f"{ns}_phase_seconds_total", {"phase": name},
                 phases[name]["seconds"])
    w.family(f"{ns}_phase_events_total", "counter",
             "Events recorded per request phase.")
    for name in sorted(phases):
        w.sample(f"{ns}_phase_events_total", {"phase": name},
                 phases[name]["count"])

    if tr:
        w.family(f"{ns}_traces_recorded_total", "counter",
                 "Traces recorded into the flight-recorder ring.")
        w.sample(f"{ns}_traces_recorded_total", None, tr["recorded"])
        w.family(f"{ns}_traces_evicted_total", "counter",
                 "Traces aged out of the flight-recorder ring.")
        w.sample(f"{ns}_traces_evicted_total", None, tr["evicted"])

    # -- gauges ------------------------------------------------------------
    w.family(f"{ns}_queue_depth", "gauge",
             "Requests currently queued across batcher lanes.")
    w.sample(f"{ns}_queue_depth", None, b.get("queue_depth", 0))
    w.family(f"{ns}_queue_wait_max_seconds", "gauge",
             "Worst admitted in-queue wait since the last reset_peak.")
    w.sample(f"{ns}_queue_wait_max_seconds", None,
             b["queue_wait_max_ms"] / 1e3)
    w.family(f"{ns}_breaker_state", "gauge",
             "Circuit-breaker state: 0 closed, 1 half_open, 2 open.")
    w.sample(f"{ns}_breaker_state", None,
             _BREAKER_STATE_CODE.get(br["state"], -1))
    w.family(f"{ns}_plan_cache_plans", "gauge",
             "Distinct dispatch plans in the cache.")
    w.sample(f"{ns}_plan_cache_plans", None, len(pl["plans"]))
    w.family(f"{ns}_keycache_entries", "gauge",
             "Key batches resident in the host-repack LRU.")
    w.sample(f"{ns}_keycache_entries", None, kc["entries"])
    if hhs is not None:
        w.family(f"{ns}_hh_sessions", "gauge",
                 "Descent sessions with a device-resident frontier.")
        w.sample(f"{ns}_hh_sessions", None, hhs["sessions"])
        w.family(f"{ns}_hh_session_bytes", "gauge",
                 "Device bytes held by cached descent frontiers.")
        w.sample(f"{ns}_hh_session_bytes", None, hhs["bytes"])
    if tr:
        w.family(f"{ns}_trace_ring_size", "gauge",
                 "Traces currently held by the flight recorder.")
        w.sample(f"{ns}_trace_ring_size", None, tr["size"])

    w.family(f"{ns}_mesh_shards", "gauge",
             "Serving-mesh shard count (0 = single-device serving): how "
             "many chips a coalesced dispatch partitions over.")
    w.sample(f"{ns}_mesh_shards", None,
             stats.get("mesh", {}).get("shards", 0))

    if pir is not None:
        w.family(f"{ns}_pir_dbs_resident", "gauge",
                 "PIR databases resident in device HBM.")
        w.sample(f"{ns}_pir_dbs_resident", None, pir["dbs_resident"])
        w.family(f"{ns}_pir_db_bytes_resident", "gauge",
                 "Padded database bytes resident across PIR databases.")
        w.sample(f"{ns}_pir_db_bytes_resident", None,
                 pir["db_bytes_resident"])

    tuned = stats.get("tuned")
    if tuned is not None:
        w.family(f"{ns}_tuned_configs", "gauge",
                 "Tuned per-plan configs loaded from docs/TUNED.json "
                 "(0 = file absent/invalid or DPF_TPU_TUNED gating it "
                 "off for this backend).")
        w.sample(f"{ns}_tuned_configs", None, tuned["entries"])
        w.family(f"{ns}_tuned_plans", "gauge",
                 "Dispatch plans in the cache compiled under a tuned "
                 "config — which plans actually run tuned right now.")
        w.sample(f"{ns}_tuned_plans", None, pl.get("tuned_plans", 0))

    mem = device_memory_gauges() if device_mem is None else device_mem
    if mem:
        w.family(f"{ns}_device_memory_bytes", "gauge",
                 "Per-device memory from jax.local_devices() stats, "
                 "labeled by serving-mesh coordinate (mesh=keys:i, or "
                 "off for devices outside the mesh).")
        for device, stat, coord, value in mem:
            w.sample(f"{ns}_device_memory_bytes",
                     {"device": device, "stat": stat, "mesh": coord},
                     value)

    # -- histograms --------------------------------------------------------
    phase_hists = hists.get("phase_latency", {})
    if phase_hists:
        w.family(f"{ns}_phase_latency_seconds", "histogram",
                 "Per-event phase latency (fixed buckets, "
                 "DPF_TPU_METRICS_BUCKETS_MS).")
        for name in sorted(phase_hists):
            w.histogram(f"{ns}_phase_latency_seconds", {"phase": name},
                        phase_hists[name])
    w.family(f"{ns}_coalesce_size", "histogram",
             "Key-rows coalesced per device dispatch.")
    w.histogram(f"{ns}_coalesce_size", None, hists["coalesce_size"])
    if pir is not None:
        w.family(f"{ns}_pir_scan_chunks", "histogram",
                 "Streamed chunk dispatches per PIR scan (1 = one-shot "
                 "scan; more = database past DPF_TPU_PIR_DB_CHUNK_BYTES).")
        w.histogram(f"{ns}_pir_scan_chunks", None, pir["scan_chunks"])

    return w.text()
