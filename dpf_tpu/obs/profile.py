"""On-demand XProf capture for a running sidecar (``POST /v1/profile``).

The wedge-plagued TPU history behind the bench ledger means a degraded
hardware run is precious evidence — and restarting the sidecar to wrap
it in ``utils.profiling.trace`` destroys the very state being debugged.
This module starts/stops a ``jax.profiler`` trace inside the live
process instead:

  * **gated** — refused (``ProfileForbidden`` -> HTTP 403) unless the
    operator set ``DPF_TPU_PROFILE_ALLOW``: profiling dumps op-level
    timelines to disk and costs real overhead, so it must be an explicit
    deployment decision, like fault injection;
  * **bounded** — every capture auto-stops after
    ``min(requested, DPF_TPU_PROFILE_MAX_S)`` seconds via a daemon
    timer, so a forgotten ``start`` can never profile a production
    sidecar for hours;
  * **exclusive** — one capture at a time (``ProfileBusy`` -> 409);
  * the reply always reports the trace **directory** so the operator
    can point xprof/tensorboard at it without guessing.
"""

from __future__ import annotations

import tempfile
import threading
import time

from ..core import knobs


class ProfileError(RuntimeError):
    """Capture lifecycle error (no capture active, ...) -> HTTP 400."""


class ProfileForbidden(ProfileError):
    """DPF_TPU_PROFILE_ALLOW is not set -> HTTP 403."""


class ProfileBusy(ProfileError):
    """A capture is already running -> HTTP 409."""


class _Capture:
    __slots__ = ("log_dir", "started_at", "max_s", "timer")

    def __init__(self, log_dir: str, max_s: float):
        self.log_dir = log_dir
        self.started_at = time.perf_counter()
        self.max_s = max_s
        self.timer: threading.Timer | None = None


_LOCK = threading.Lock()
_ACTIVE: _Capture | None = None


def start(log_dir: str | None = None,
          seconds: float | None = None) -> dict:
    """Begin a capture; returns ``{status, dir, max_seconds}``."""
    if not knobs.is_set("DPF_TPU_PROFILE_ALLOW"):
        raise ProfileForbidden(
            "profiling refused: set DPF_TPU_PROFILE_ALLOW on the sidecar "
            "to enable on-demand XProf capture"
        )
    cap_s = knobs.get_float("DPF_TPU_PROFILE_MAX_S")
    max_s = min(float(seconds), cap_s) if seconds else cap_s
    if max_s <= 0:
        raise ProfileError("profile duration must be positive")
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None:
            raise ProfileBusy(
                f"a capture is already running (dir {_ACTIVE.log_dir})"
            )
        if not log_dir:
            log_dir = tempfile.mkdtemp(prefix="dpf-tpu-xprof-")
        import jax

        jax.profiler.start_trace(log_dir)
        cap = _Capture(log_dir, max_s)
        cap.timer = threading.Timer(max_s, _auto_stop, args=(cap,))
        cap.timer.daemon = True
        cap.timer.start()
        _ACTIVE = cap
    return {"status": "started", "dir": log_dir,
            "max_seconds": round(max_s, 3)}


def stop() -> dict:
    """End the capture; returns ``{status, dir, seconds}``."""
    global _ACTIVE
    with _LOCK:
        cap = _ACTIVE
        if cap is None:
            raise ProfileError("no capture active")
        return _stop_locked(cap)


def _stop_locked(cap: _Capture) -> dict:
    global _ACTIVE
    if cap.timer is not None:
        cap.timer.cancel()
    # Clear the active slot BEFORE stop_trace: if the profiler raises
    # (backend died mid-capture), the endpoint must not wedge in a
    # permanent "running"/409 state with the auto-stop timer already
    # cancelled — a failed stop means the capture is over either way.
    _ACTIVE = None
    import jax

    jax.profiler.stop_trace()
    return {
        "status": "stopped",
        "dir": cap.log_dir,
        "seconds": round(time.perf_counter() - cap.started_at, 3),
    }


def _auto_stop(cap: _Capture) -> None:
    """Duration-bound enforcement: stop the capture iff it is still THE
    active one (a manual stop may have raced the timer)."""
    with _LOCK:
        if _ACTIVE is cap:
            try:
                _stop_locked(cap)
            except Exception:  # noqa: BLE001 — the timer thread must not die loud
                pass


def status() -> dict:
    with _LOCK:
        if _ACTIVE is None:
            return {"status": "idle"}
        return {
            "status": "running",
            "dir": _ACTIVE.log_dir,
            "seconds": round(time.perf_counter() - _ACTIVE.started_at, 3),
            "max_seconds": round(_ACTIVE.max_s, 3),
        }
