"""Per-request tracing: span recorder + bounded flight recorder.

Aggregate counters (/v1/stats) answer "how much"; when p99 degrades or
the breaker trips on hardware they cannot answer "which request waited
WHERE".  This module records one span tree per sidecar request —

    ingress -> admission -> queue_wait -> coalesce -> dispatch
            -> plan_lookup -> compute -> d2h -> reply

— and keeps the finished trees in a bounded ring buffer (the "flight
recorder", ``DPF_TPU_TRACE_RING`` entries) queryable at ``GET
/v1/trace``.  Shed, expired, and breaker-rejected requests are recorded
too, with their outcome, so an overload incident is reconstructable
after the fact from the sidecar alone.

Identity: the trace id arrives in the ``X-DPF-Trace`` request header
(the Go client stamps one per request) or is generated at ingress.  A
coalesced batch's requests each keep their own trace, but the device
dispatch is ONE shared ``Span`` object attached to every batch-mate's
tree — the span_id equality is how a cross-request incident ("these 14
requests all rode the slow dispatch") is established, and the
``coalesce`` span carries the batch-mates' trace ids.

Attribute discipline: span attributes and trace payloads leave the
process via ``/v1/trace``, so they are taint SINKS for the
secret-hygiene lint pass — only public metadata (ids, shapes, buckets,
counts, durations) may flow into ``set_attrs``/``add_span``/
``add_event``/``child_span``.  Key material never.

Overhead: with ``DPF_TPU_TRACE=off`` the tracer hands out ``None`` and
every instrumentation point is a single ``is None`` check; with tracing
on, a span is one small object append (no locks on the request path —
the only lock is the ring buffer's, taken once per request at finish).
The bench ledger records the measured on/off p50 delta
(``cfg-serving-latency``); the budget is <= 2% p50.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from collections import deque

from ..core import knobs

# The outcome vocabulary /v1/trace filters on.  "shed" (429 admission),
# "expired" (504 deadline), "breaker_rejected" (503 open circuit),
# "bad_request" (400), "error" (500), "ok".
OUTCOMES = (
    "ok", "shed", "expired", "breaker_rejected", "bad_request", "error",
)

_SPAN_IDS = itertools.count(1)

# Ordered span names of a full fast-path request — tests assert
# completeness against this list, keep it in sync with the docstring.
SPAN_NAMES = (
    "ingress", "admission", "queue_wait", "coalesce", "dispatch",
    "plan_lookup", "compute", "d2h", "reply",
)


class Span:
    """One named, timed tree node.  ``span_id`` is process-unique so a
    span SHARED between traces (the coalesced dispatch) is recognizably
    the same event in every tree it appears in."""

    __slots__ = ("span_id", "name", "t0", "dur_s", "attrs", "children")

    def __init__(self, name: str, t0: float | None = None):
        self.span_id = next(_SPAN_IDS)
        self.name = name
        self.t0 = time.perf_counter() if t0 is None else t0
        self.dur_s = 0.0
        self.attrs: dict = {}
        self.children: list[Span] = []

    def end(self) -> "Span":
        self.dur_s = time.perf_counter() - self.t0
        return self

    def set_attrs(self, **attrs) -> None:
        """Attach public metadata (secret-hygiene sink: attributes are
        exported verbatim by /v1/trace)."""
        self.attrs.update(attrs)

    def child(self, name: str, t0: float | None = None) -> "Span":
        sp = Span(name, t0)
        self.children.append(sp)
        return sp

    def as_dict(self, base_t0: float) -> dict:
        """JSON form, with times relative to the OWNING trace's ingress
        (a shared span renders a different start_ms in each tree)."""
        return {
            "span_id": self.span_id,
            "name": self.name,
            "start_ms": round((self.t0 - base_t0) * 1e3, 3),
            "duration_ms": round(self.dur_s * 1e3, 3),
            "attrs": dict(self.attrs),
            "children": [c.as_dict(base_t0) for c in self.children],
        }


class RequestTrace:
    """One request's span tree, rooted at ``ingress``."""

    __slots__ = ("trace_id", "route", "t0", "t0_unix", "outcome", "root")

    def __init__(self, trace_id: str, route: str):
        self.trace_id = trace_id
        self.route = route
        self.t0 = time.perf_counter()
        self.t0_unix = time.time()
        self.outcome = "ok"
        self.root = Span("ingress", t0=self.t0)

    @contextlib.contextmanager
    def span(self, name: str):
        """Timed child span of the root: ``with trace.span("reply"):``."""
        sp = self.root.child(name)
        try:
            yield sp
        finally:
            sp.end()

    def add_span(self, name: str, t0: float, dur_s: float, **attrs) -> Span:
        """Record a span measured elsewhere (the batcher's queue_wait is
        timed by the lane leader, not this thread)."""
        sp = Span(name, t0=t0)
        sp.dur_s = dur_s
        sp.attrs.update(attrs)
        self.root.children.append(sp)
        return sp

    def attach(self, span: Span) -> None:
        """Adopt an already-built span — THE shared-dispatch mechanism:
        every coalesced batch-mate attaches the same object."""
        self.root.children.append(span)

    def set_attrs(self, **attrs) -> None:
        self.root.attrs.update(attrs)

    def span_names(self) -> set[str]:
        out = set()
        stack = [self.root]
        while stack:
            sp = stack.pop()
            out.add(sp.name)
            stack.extend(sp.children)
        return out

    def duration_ms(self) -> float:
        return self.root.dur_s * 1e3

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "route": self.route,
            "outcome": self.outcome,
            "start_unix": round(self.t0_unix, 6),
            "duration_ms": round(self.duration_ms(), 3),
            "spans": [self.root.as_dict(self.t0)],
        }


class FlightRecorder:
    """Bounded ring of finished traces (newest last).  Eviction is the
    deque's: the ring NEVER grows past capacity, old incidents age out."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 1)
        self._ring: deque[RequestTrace] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.recorded = 0
        self.evicted = 0

    def record(self, trace: RequestTrace) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self.evicted += 1
            self._ring.append(trace)
            self.recorded += 1

    def query(
        self,
        n: int = 32,
        slowest: bool = False,
        trace_id: str | None = None,
        outcome: str | None = None,
    ) -> list[RequestTrace]:
        """Recent-N (default), slowest-N, by trace id, or by outcome —
        newest/slowest first."""
        with self._lock:
            traces = list(self._ring)
        if trace_id is not None:
            traces = [t for t in traces if t.trace_id == trace_id]
        if outcome is not None:
            traces = [t for t in traces if t.outcome == outcome]
        if slowest:
            traces.sort(key=lambda t: t.root.dur_s, reverse=True)
        else:
            traces.reverse()  # newest first
        return traces[: max(int(n), 0)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "size": len(self._ring),
                "recorded": self.recorded,
                "evicted": self.evicted,
            }


def _clean_id(raw: str | None) -> str | None:
    """Sanitize a client-supplied trace id: bounded length, URL/JSON-safe
    charset — anything else is replaced by a generated id (a hostile
    header must not inject junk into /v1/trace payloads)."""
    if not raw:
        return None
    raw = raw.strip()
    if 0 < len(raw) <= 64 and all(
        c.isalnum() or c in "-_.:" for c in raw
    ):
        return raw
    return None


class Tracer:
    """Per-serving-state trace factory + its flight recorder.  When
    disabled (``DPF_TPU_TRACE=off``), ``begin`` returns None and every
    downstream instrumentation point no-ops on the None check."""

    def __init__(self, enabled: bool | None = None,
                 ring: int | None = None):
        if enabled is None:
            enabled = knobs.get_bool("DPF_TPU_TRACE")
        if ring is None:
            ring = knobs.get_int("DPF_TPU_TRACE_RING")
        self.enabled = bool(enabled)
        self.recorder = FlightRecorder(ring)

    def begin(self, header_id: str | None, route: str) -> RequestTrace | None:
        if not self.enabled:
            return None
        tid = _clean_id(header_id) or uuid.uuid4().hex[:16]
        return RequestTrace(tid, route)

    def finish(self, trace: RequestTrace | None, outcome: str = "ok") -> None:
        if trace is None:
            return
        trace.root.end()
        trace.outcome = outcome
        self.recorder.record(trace)

    def stats(self) -> dict:
        out = self.recorder.stats()
        out["enabled"] = self.enabled
        return out


# ---------------------------------------------------------------------------
# Dispatch scope: how layers BELOW the batcher annotate the in-flight
# dispatch span without threading a trace handle through every call.
# The lane leader (or the passthrough path) sets the active span for the
# duration of the device dispatch; core/plans and the breaker then hang
# plan_lookup/compute/d2h/retry children on it.  Thread-local, so
# concurrent lanes never cross-contaminate.
# ---------------------------------------------------------------------------

_TLS = threading.local()


def maybe_span(trace: RequestTrace | None, name: str):
    """``trace.span(name)`` when tracing, a no-op context when the
    request is untraced — the one spelling of the conditional-span
    idiom every instrumentation site uses."""
    if trace is None:
        return contextlib.nullcontext()
    return trace.span(name)


@contextlib.contextmanager
def traced_dispatch(trace: RequestTrace | None):
    """The non-batched dispatch-span idiom: a ``dispatch`` span active
    for the body's duration (plans/breaker children land on it via the
    dispatch scope), ended and attached to ``trace`` even when the
    dispatch raises.  Yields the span (None when untraced) so callers
    can set attrs."""
    if trace is None:
        with dispatch_scope(None):
            yield None
        return
    sp = Span("dispatch")
    try:
        with dispatch_scope(sp):
            yield sp
    finally:
        sp.end()
        trace.attach(sp)


@contextlib.contextmanager
def dispatch_scope(span: Span | None):
    prev = getattr(_TLS, "span", None)
    _TLS.span = span
    try:
        yield span
    finally:
        _TLS.span = prev


def add_event(name: str, **attrs) -> None:
    """Zero-duration child of the active dispatch span (plan-cache
    lookups, breaker retries).  No-op outside a dispatch scope.
    Secret-hygiene sink: attrs are exported by /v1/trace."""
    sp = getattr(_TLS, "span", None)
    if sp is not None:
        ev = sp.child(name)
        if attrs:
            ev.attrs.update(attrs)


@contextlib.contextmanager
def child_span(name: str):
    """Timed child of the active dispatch span; yields None (and times
    nothing) outside a dispatch scope."""
    sp = getattr(_TLS, "span", None)
    if sp is None:
        yield None
        return
    c = sp.child(name)
    try:
        yield c
    finally:
        c.end()
