"""Strict Prometheus text-format (v0.0.4) parser.

This is the consumer-side half of the metrics plane: the test suite
parses ``/v1/metrics`` through it with ``strict=True`` (so the renderer
in ``obs/metrics.py`` is held to the format, not to "whatever our own
parser accepts" — the grammar below is written from the exposition
spec, and violations raise), and ``scripts/scrape_metrics.py`` +
``bench_all.py``'s serving sections use it to read counters back.

Strict mode enforces, beyond the line grammar:

  * a ``# TYPE`` line precedes a family's first sample, with a known
    type, at most once per family;
  * counter family names end in ``_total`` and never decrease below 0;
  * histogram families expose ``_bucket``/``_sum``/``_count`` series,
    cumulative buckets are monotonically non-decreasing, and the
    ``le="+Inf"`` bucket equals ``_count``;
  * no duplicate (name, labels) sample;
  * the exposition ends with a newline.

Import-light (stdlib only): bench harnesses import it before any
backend initializes.
"""

from __future__ import annotations

import re

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.*)$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (\w+)$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{(.*)\}})? "
    r"(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN)|[+-]Inf)$"
)
_LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\]|\\.)*)"')
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


class PromFormatError(ValueError):
    """The exposition violated the text format (strict mode)."""


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def _base_name(name: str, types: dict) -> str:
    """Map a histogram series name back to its family name."""
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return name


class Scrape:
    """One parsed exposition: ``samples`` maps (name, labels-tuple) ->
    float; ``value()`` / ``family()`` are the lookup helpers."""

    def __init__(self):
        self.types: dict[str, str] = {}
        self.help: dict[str, str] = {}
        self.samples: dict[tuple[str, tuple], float] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        return (name, tuple(sorted((labels or {}).items())))

    def value(self, name: str, labels: dict | None = None,
              default: float | None = None) -> float:
        key = self._key(name, labels)
        if key in self.samples:
            return self.samples[key]
        if default is not None:
            return default
        raise KeyError(f"no sample {name}{labels or ''}")

    def family(self, name: str) -> dict[tuple, float]:
        """Every (labels-tuple -> value) sample of one metric name."""
        return {
            lbl: v for (n, lbl), v in self.samples.items() if n == name
        }

    def counters(self) -> dict[tuple[str, tuple], float]:
        """Samples of counter-typed families (incl. histogram buckets'
        implicit counters are EXCLUDED — just explicit counter types)."""
        return {
            (n, lbl): v
            for (n, lbl), v in self.samples.items()
            if self.types.get(_base_name(n, self.types)) == "counter"
        }


def _parse_labels(raw: str | None, line: str) -> dict:
    if not raw:
        return {}
    labels: dict[str, str] = {}
    rest = raw
    while rest:
        m = _LABEL_RE.match(rest)
        if m is None:
            raise PromFormatError(f"bad label syntax: {line!r}")
        name, value = m.group(1), _unescape(m.group(2))
        if name in labels:
            raise PromFormatError(f"duplicate label {name!r}: {line!r}")
        labels[name] = value
        rest = rest[m.end():]
        if rest.startswith(","):
            rest = rest[1:]
        elif rest:
            raise PromFormatError(f"bad label separator: {line!r}")
    return labels


def _to_float(tok: str) -> float:
    if tok in ("Inf", "+Inf"):
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    if tok == "NaN":
        return float("nan")
    return float(tok)


def parse(text: str, strict: bool = True) -> Scrape:
    """Parse one exposition.  ``strict=False`` keeps the line grammar
    but skips the family-level conformance checks (useful for diffing
    foreign expositions)."""
    if strict and not text.endswith("\n"):
        raise PromFormatError("exposition must end with a newline")
    scrape = Scrape()
    seen_sample_of: set[str] = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            if strict:
                raise PromFormatError(f"line {lineno}: blank line")
            continue
        if line.startswith("#"):
            mh = _HELP_RE.match(line)
            if mh is not None:
                scrape.help[mh.group(1)] = mh.group(2)
                continue
            mt = _TYPE_RE.match(line)
            if mt is not None:
                name, kind = mt.group(1), mt.group(2)
                if kind not in _TYPES:
                    raise PromFormatError(
                        f"line {lineno}: unknown type {kind!r}"
                    )
                if strict and name in scrape.types:
                    raise PromFormatError(
                        f"line {lineno}: duplicate TYPE for {name}"
                    )
                if strict and name in seen_sample_of:
                    raise PromFormatError(
                        f"line {lineno}: TYPE for {name} after its samples"
                    )
                scrape.types[name] = kind
                continue
            if strict:
                raise PromFormatError(
                    f"line {lineno}: malformed comment {line!r}"
                )
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise PromFormatError(f"line {lineno}: bad sample {line!r}")
        name, raw_labels, raw_value = m.groups()
        labels = _parse_labels(raw_labels, line)
        base = _base_name(name, scrape.types)
        if strict and base not in scrape.types:
            raise PromFormatError(
                f"line {lineno}: sample {name} has no preceding TYPE"
            )
        seen_sample_of.add(base)
        key = Scrape._key(name, labels)
        if key in scrape.samples:
            raise PromFormatError(
                f"line {lineno}: duplicate sample {name}{labels}"
            )
        scrape.samples[key] = _to_float(raw_value)
    if strict:
        _conformance(scrape)
    return scrape


def _conformance(scrape: Scrape) -> None:
    for name, kind in scrape.types.items():
        if kind == "counter":
            if not name.endswith("_total"):
                raise PromFormatError(
                    f"counter {name} must end in _total"
                )
            for lbl, v in scrape.family(name).items():
                if v < 0:
                    raise PromFormatError(
                        f"counter {name}{dict(lbl)} is negative"
                    )
        elif kind == "histogram":
            _check_histogram(scrape, name)


def _check_histogram(scrape: Scrape, name: str) -> None:
    buckets = scrape.family(f"{name}_bucket")
    sums = scrape.family(f"{name}_sum")
    counts = scrape.family(f"{name}_count")
    if not buckets or not sums or not counts:
        raise PromFormatError(
            f"histogram {name} missing _bucket/_sum/_count series"
        )
    # Group bucket series by their non-le labels.
    grouped: dict[tuple, list[tuple[float, float]]] = {}
    for lbl, v in buckets.items():
        le = dict(lbl).get("le")
        if le is None:
            raise PromFormatError(
                f"histogram {name} bucket without le label"
            )
        rest = tuple(kv for kv in lbl if kv[0] != "le")
        grouped.setdefault(rest, []).append((_to_float(le), v))
    for rest, series in grouped.items():
        series.sort(key=lambda bv: bv[0])
        bounds = [b for b, _ in series]
        values = [v for _, v in series]
        if bounds[-1] != float("inf"):
            raise PromFormatError(
                f"histogram {name}{dict(rest)} lacks an le=+Inf bucket"
            )
        if any(b > a for a, b in zip(values[1:], values[:-1])):
            raise PromFormatError(
                f"histogram {name}{dict(rest)} buckets are not cumulative"
            )
        if rest not in counts:
            raise PromFormatError(
                f"histogram {name}{dict(rest)} lacks a _count sample"
            )
        if values[-1] != counts[rest]:
            raise PromFormatError(
                f"histogram {name}{dict(rest)} +Inf bucket != _count"
            )
        if rest not in sums:
            raise PromFormatError(
                f"histogram {name}{dict(rest)} lacks a _sum sample"
            )
