"""Observability plane for the serving stack: per-request tracing with a
bounded flight recorder (trace.py, ``GET /v1/trace``), Prometheus
text-format exposition rendered from the SAME consistent snapshot
``/v1/stats`` reads (metrics.py, ``GET /v1/metrics``), a strict
exposition parser for tests and scrape tooling (promtext.py), and
knob-gated on-demand XProf capture (profile.py, ``POST /v1/profile``).
All of it hangs off the serving state in ``dpf_tpu/server.py``; the
evaluators and kernels are untouched — instrumentation lives at the
request-pipeline seams (server, batcher, breaker, plan cache)."""

from . import metrics, profile, promtext, trace

__all__ = ["metrics", "profile", "promtext", "trace"]
