"""MXU-routed heavy-hitter count fold (ROADMAP item 3's second half).

A descent round's count reconstruction is an inner product over the
client axis: the driver XORs the two aggregators' packed share rows
(PUBLIC once reconstructed — exactly the per-candidate predicate bits)
and sums each candidate's column.  The host loop in
``apps/heavy_hitters.reconstruct_counts`` walks word x bit in Python;
here the same sum is one int8 MXU matmul, mirroring
``models/pir._parity_matmul``: unpack the packed words to int8 bits and
multiply by an all-ones row with ``preferred_element_type=jnp.int32``
so the MXU accumulates the int32 counts directly.

Only PUBLIC data flows through this body (the obliviousness certificate
for ``hh/fold_mxu`` records zero secret invars); the secret share rows
never reach it un-XORed — per-aggregator integer sums of XOR share bits
reconstruct nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _count_fold_body(x):
    """Packed XOR-reconstructed rows uint32[G, W] -> int32[W * 32]
    per-candidate counts (one matmul over the client axis)."""
    g, w = x.shape
    bits = (
        (x[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    ).astype(jnp.int8)
    ones = jnp.ones((1, g), jnp.int8)
    return jnp.matmul(
        ones, bits.reshape(g, w * 32), preferred_element_type=jnp.int32
    )[0]


_count_fold_jit = jax.jit(_count_fold_body)


def count_fold(x: np.ndarray) -> np.ndarray:
    """Host entry: uint32[G, W] packed public rows -> int64[W * 32]."""
    # host-sync: tiny per-round count vector (one word row per candidate)
    return np.asarray(_count_fold_jit(jnp.asarray(x)), dtype=np.int64)


__all__ = ["count_fold", "_count_fold_body", "_count_fold_jit"]
