"""Device-side batched key generation — the dealer on the TPU.

Gen was the last compute path still running as host NumPy AES/ChaCha:
across a K-key batch every one of the ``nu`` sequential tree levels is a
K-wide PRG expansion — exactly the batch shape the bitsliced-AES planes
(ops/aes_bitslice.py) and the ChaCha word kernels (models/dpf_chacha.py)
already own.  This module runs the per-level correction-word tower on
device, K-parallel, for all three key families:

  * ``fast``   — ChaCha12 tree (models/keys_chacha.gen_batch's math) on
    4x uint32[K] seed-word lanes via ``_prg_expand``/``_convert``;
  * ``dcf``    — the same tree plus the per-level value CW
    (models/dcf.gen_lt_batch) via ``_prg_expand_v``;
  * ``compat`` — fixed-key AES-128-MMO (core/keys.gen_batch) on
    bitsliced [128, K/32] planes, one ``prg_planes`` call per party per
    level, so the key axis lives in lane bits and shards cleanly.

The CSPRNG boundary stays on host: root seeds are drawn exactly where
and how the host gens draw them (``os.urandom`` / the injected rng, same
call order), because seed entropy is the ONLY part of Gen that needs a
CSPRNG — given identical root seeds the tower is deterministic, so the
device output is **byte-identical** to the host ``gen_batch`` by
construction (pinned by tests/test_gen_device.py under an injected rng).
Alpha bits and control bits ride as host-precomputed secret-derived
operands; on device every per-level select is mask arithmetic
(``msk = 0 - bit``), never a branch or a secret index — the gen routes
carry obliviousness certificates like every eval route.

Routing (``DPF_TPU_GEN`` off|auto|on; auto = device on TPU): the host
``gen_batch``/``gen_lt_batch`` entrypoints draw seeds, then hand the
tower to ``core/plans.run_gen`` (plan-bucketed, zero-retrace after
warmup, mesh-sharded over the key axis) when the device path is enabled.
Any device failure — and degraded serving under an open breaker
(``host_only()``) — falls back to the host tower **with the already-
drawn seeds**, so the fallback is byte-identical, not just
distribution-identical.

Level-carry donation: the root seed/control-bit operands are dead once
the first level expands, so the donated jit twins let XLA reuse their
buffers in place (``DONATED_TWINS`` is the perf-contract ledger's
evidence source, like models/dpf_chacha.py).  ``DPF_TPU_FUSE`` != off
additionally runs both towers as one ``lax.scan`` over levels (the
carries are shape-uniform), collapsing nu dispatch nodes into one fused
loop body — for the compat planes tower this also collapses nu copies
of the bitsliced AES circuit out of the traced graph, cutting compile
time from minutes to seconds at deep domains.
"""

from __future__ import annotations

import contextlib
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import knobs
from .dpf_chacha import _convert, _prg_expand, _prg_expand_v

# ---------------------------------------------------------------------------
# Routing: DPF_TPU_GEN + the degraded-mode override
# ---------------------------------------------------------------------------

_TLS = threading.local()

#: Count of device-gen dispatches that fell back to the host tower after
#: an exception (tests assert this stays 0 on healthy paths).
fallbacks = 0


@contextlib.contextmanager
def host_only():
    """Force the host gen tower on this thread (the serving layer wraps
    degraded/breaker-open gen dispatches in this, so an open circuit
    never routes key generation at a wedged device)."""
    prev = getattr(_TLS, "host_only", 0)
    _TLS.host_only = prev + 1
    try:
        yield
    finally:
        _TLS.host_only = prev


def device_enabled() -> bool:
    """Resolve DPF_TPU_GEN (off|auto|on; default auto = TPU only),
    honoring an active ``host_only()`` scope."""
    if getattr(_TLS, "host_only", 0):
        return False
    raw = knobs.get_raw("DPF_TPU_GEN")
    v = knobs.knob("DPF_TPU_GEN").default if not raw else raw.lower()
    if v in ("on", "1", "true"):
        return True
    if v in ("off", "0", "false"):
        return False
    if v != "auto":
        raise ValueError(f"DPF_TPU_GEN={v!r} unknown (off|auto|on)")
    return jax.default_backend() == "tpu"


def try_gen_device(kind, alphas, log_n, s0, t0, s1, t1):
    """Dispatch one drawn-seed batch through the plan-cached device
    tower; ``None`` on failure (the caller re-towers the SAME seeds on
    host, byte-identically — the degraded twin)."""
    if alphas.shape[0] == 0:
        return None
    from ..core import plans

    try:
        return plans.run_gen(kind, alphas, log_n, s0, t0, s1, t1)
    except Exception:  # noqa: BLE001 — any device failure degrades to host
        global fallbacks
        fallbacks += 1
        return None


def fused_enabled() -> bool:
    """Level-fused (lax.scan) ChaCha gen tower under DPF_TPU_FUSE."""
    return knobs.get_str("DPF_TPU_FUSE") != "off"


# ---------------------------------------------------------------------------
# ChaCha tower (fast + DCF): 4x uint32[K] seed-word lanes
# ---------------------------------------------------------------------------


def _level_gen_cc(s0w, s1w, t0, t1, bit, dcf):
    """One Gen level for both parties: expand, publish the level's CWs,
    descend alpha's KEEP child.  All selects are mask arithmetic on the
    secret alpha bit (``msk = 0 - bit``) — no branches, no indexing."""
    if dcf:
        l0, r0, v0 = _prg_expand_v(s0w)
        l1, r1, v1 = _prg_expand_v(s1w)
    else:
        l0, r0 = _prg_expand(s0w)
        l1, r1 = _prg_expand(s1w)
    one = jnp.uint32(1)
    t0l, t0r = l0[0] & one, r0[0] & one
    t1l, t1r = l1[0] & one, r1[0] & one
    clear = ~one
    l0 = [l0[0] & clear, l0[1], l0[2], l0[3]]
    r0 = [r0[0] & clear, r0[1], r0[2], r0[3]]
    l1 = [l1[0] & clear, l1[1], l1[2], l1[3]]
    r1 = [r1[0] & clear, r1[1], r1[2], r1[3]]

    msk = jnp.uint32(0) - bit  # all-ones when alpha descends right
    # LOSE child = the one alpha does NOT descend into.
    scw = [
        ((l0[i] ^ l1[i]) & msk) | ((r0[i] ^ r1[i]) & ~msk) for i in range(4)
    ]
    tlcw = t0l ^ t1l ^ bit ^ one
    trcw = t0r ^ t1r ^ bit
    vcw = ((v0 ^ v1 ^ bit) & one) if dcf else None

    keep0 = [(r0[i] & msk) | (l0[i] & ~msk) for i in range(4)]
    keep1 = [(r1[i] & msk) | (l1[i] & ~msk) for i in range(4)]
    kt0 = (t0r & msk) | (t0l & ~msk)
    kt1 = (t1r & msk) | (t1l & ~msk)
    ktcw = (trcw & msk) | (tlcw & ~msk)

    tm0 = jnp.uint32(0) - t0
    tm1 = jnp.uint32(0) - t1
    ns0 = [keep0[i] ^ (scw[i] & tm0) for i in range(4)]
    ns1 = [keep1[i] ^ (scw[i] & tm1) for i in range(4)]
    nt0 = kt0 ^ (t0 & ktcw)
    nt1 = kt1 ^ (t1 & ktcw)
    return ns0, ns1, nt0, nt1, scw, tlcw, trcw, vcw


def _gen_cc_body(nu, dcf, fused, s0, s1, t0, t1, bits):
    """ChaCha gen tower: cleared root seed words uint32[K, 4] x2, root
    control bits uint32[K] x2, alpha bits uint32[nu, K] (level-major) ->
    (scw uint32[nu, K, 4], tlcw/trcw uint32[nu, K], fcw uint32[K, 16]
    [, vcw uint32[nu, K]])."""
    K = s0.shape[0]
    s0w = [s0[:, i] for i in range(4)]
    s1w = [s1[:, i] for i in range(4)]

    if nu and fused:

        def step(carry, bit):
            c0, c1, ct0, ct1 = carry
            n0, n1, nt0, nt1, scw, tl, tr, vcw = _level_gen_cc(
                list(c0), list(c1), ct0, ct1, bit, dcf
            )
            ys = (jnp.stack(scw, axis=-1), tl, tr)
            if dcf:
                ys = ys + (vcw,)
            return (tuple(n0), tuple(n1), nt0, nt1), ys

        carry, ys = jax.lax.scan(
            step, (tuple(s0w), tuple(s1w), t0, t1), bits
        )
        s0w, s1w = list(carry[0]), list(carry[1])
        scw_all, tl_all, tr_all = ys[0], ys[1], ys[2]
        vcw_all = ys[3] if dcf else None
    else:
        scw_l, tl_l, tr_l, vcw_l = [], [], [], []
        for i in range(nu):
            s0w, s1w, t0, t1, scw, tl, tr, vcw = _level_gen_cc(
                s0w, s1w, t0, t1, bits[i], dcf
            )
            scw_l.append(jnp.stack(scw, axis=-1))
            tl_l.append(tl)
            tr_l.append(tr)
            if dcf:
                vcw_l.append(vcw)
        z = jnp.zeros((0, K), jnp.uint32)
        scw_all = (
            jnp.stack(scw_l) if nu else jnp.zeros((0, K, 4), jnp.uint32)
        )
        tl_all = jnp.stack(tl_l) if nu else z
        tr_all = jnp.stack(tr_l) if nu else z
        vcw_all = (jnp.stack(vcw_l) if nu else z) if dcf else None

    conv0 = _convert(s0w)
    conv1 = _convert(s1w)
    fcw = jnp.stack([conv0[i] ^ conv1[i] for i in range(16)], axis=-1)
    out = (scw_all, tl_all, tr_all, fcw)
    if dcf:
        out = out + (vcw_all,)
    return out


_gen_cc_jit = partial(jax.jit, static_argnums=(0, 1, 2))(_gen_cc_body)
# Donated twin: the root seed/control-bit carries are dead after level 0
# expands (plans.donation_enabled gates selection, like every other twin).
_gen_cc_donated_jit = partial(
    jax.jit, static_argnums=(0, 1, 2), donate_argnums=(3, 4, 5, 6)
)(_gen_cc_body)


# ---------------------------------------------------------------------------
# AES compat tower: bitsliced [128, K/32] planes per party
# ---------------------------------------------------------------------------


def _level_gen_compat(S0, S1, T0, T1, bm):
    """One compat Gen level on bitsliced planes.  Plane row 0 is every
    key's byte-0 LSB (the control bit); clearing it zeroes the row, and
    the per-key ``^ 1`` of tlcw is a lane-wide complement."""
    from ..ops.aes_bitslice import prg_planes

    W = S0.shape[1]
    ones = jnp.uint32(0xFFFFFFFF)
    L0, R0 = prg_planes(S0)
    L1, R1 = prg_planes(S1)
    t0l, t0r = L0[0], R0[0]
    t1l, t1r = L1[0], R1[0]
    zero = jnp.zeros((W,), jnp.uint32)
    L0, R0 = L0.at[0].set(zero), R0.at[0].set(zero)
    L1, R1 = L1.at[0].set(zero), R1.at[0].set(zero)

    scw = ((L0 ^ L1) & bm) | ((R0 ^ R1) & ~bm)  # LOSE side
    tlcw = t0l ^ t1l ^ bm ^ ones
    trcw = t0r ^ t1r ^ bm

    keep0 = (R0 & bm) | (L0 & ~bm)
    keep1 = (R1 & bm) | (L1 & ~bm)
    kt0 = (t0r & bm) | (t0l & ~bm)
    kt1 = (t1r & bm) | (t1l & ~bm)
    ktcw = (trcw & bm) | (tlcw & ~bm)
    S0 = keep0 ^ (scw & T0)
    S1 = keep1 ^ (scw & T1)
    T0 = kt0 ^ (T0 & ktcw)
    T1 = kt1 ^ (T1 & ktcw)
    return S0, S1, T0, T1, scw, tlcw, trcw


def _gen_compat_body(nu, fused, S0, S1, T0, T1, BM):
    """Compat gen tower on bitsliced planes: cleared root seed planes
    uint32[128, W] x2 (32 keys per lane word), root control-bit lane
    words uint32[W] x2, alpha-bit lane masks uint32[nu, W] ->
    (scw uint32[K, nu, 4] per-key words, tlcw/trcw uint32[nu, W] lane
    words, fcw uint32[K, 4])."""
    from ..ops.aes_bitslice import (
        RK_MASKS_L,
        aes128_mmo_planes,
        unpack_planes,
    )

    W = S0.shape[1]
    if nu and fused:

        def step(carry, bm):
            c0, c1, ct0, ct1 = carry
            n0, n1, nt0, nt1, scw, tl, tr = _level_gen_compat(
                c0, c1, ct0, ct1, bm
            )
            return (n0, n1, nt0, nt1), (scw, tl, tr)

        carry, ys = jax.lax.scan(step, (S0, S1, T0, T1), BM)
        S0, S1, T0, T1 = carry
        scw_stack = ys[0].transpose(1, 0, 2)  # [nu,128,W] -> [128,nu,W]
        tl_all, tr_all = ys[1], ys[2]
    elif nu:
        scw_l, tl_l, tr_l = [], [], []
        for i in range(nu):
            S0, S1, T0, T1, scw, tl, tr = _level_gen_compat(
                S0, S1, T0, T1, BM[i]
            )
            scw_l.append(scw)
            tl_l.append(tl)
            tr_l.append(tr)
        scw_stack = jnp.stack(scw_l, axis=1)
        tl_all = jnp.stack(tl_l)
        tr_all = jnp.stack(tr_l)
    else:
        scw_stack = None

    conv0 = aes128_mmo_planes(S0, RK_MASKS_L)
    conv1 = aes128_mmo_planes(S1, RK_MASKS_L)
    if nu:
        # [128, nu, W] -> per-key words uint32[K, nu, 4] on device.
        scw_words = unpack_planes(scw_stack)
    else:
        scw_words = jnp.zeros((W * 32, 0, 4), jnp.uint32)
        tl_all = jnp.zeros((0, W), jnp.uint32)
        tr_all = jnp.zeros((0, W), jnp.uint32)
    fcw_words = unpack_planes((conv0 ^ conv1)[:, None, :])[:, 0, :]
    return scw_words, tl_all, tr_all, fcw_words


_gen_compat_jit = partial(jax.jit, static_argnums=(0, 1))(_gen_compat_body)
_gen_compat_donated_jit = partial(
    jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4, 5)
)(_gen_compat_body)

#: jitted-twin evidence for the perf-contract ledger (same format as
#: models/dpf_chacha.DONATED_TWINS): name -> (static_argnums,
#: donate_argnums).
DONATED_TWINS = {
    "_gen_cc_donated_jit": ((0, 1, 2), (3, 4, 5, 6)),
    "_gen_compat_donated_jit": ((0, 1), (2, 3, 4, 5)),
}


# ---------------------------------------------------------------------------
# Host-side operand prep + output marshalling
# ---------------------------------------------------------------------------


def _pad_rows(a: np.ndarray, kp: int) -> np.ndarray:
    """Zero-pad the leading (key) axis to the plan bucket.  Seeds are
    drawn for the ACTUAL K first (host rng order is part of the byte-
    identity contract); the pad lanes tower garbage keys that are
    sliced back off."""
    k = a.shape[0]
    if k == kp:
        return a
    return np.concatenate([a, np.zeros((kp - k,) + a.shape[1:], a.dtype)])


def _alpha_bits(alphas: np.ndarray, log_n: int, nu: int) -> np.ndarray:
    """Level-major alpha path bits uint32[nu, K] (secret-derived host
    operand — the dealer knows alpha)."""
    shifts = np.uint64(log_n) - 1 - np.arange(nu, dtype=np.uint64)
    return ((alphas[None, :] >> shifts[:, None]) & np.uint64(1)).astype(
        np.uint32
    )


def _pack_lane_bits(bits: np.ndarray, w: int) -> np.ndarray:
    """0/1 rows [..., K] -> lane words uint32[..., w] (key k at word
    k//32 bit k%32 — the aes_bitslice plane lane order)."""
    k = bits.shape[-1]
    padded = np.zeros(bits.shape[:-1] + (w * 32,), np.uint32)
    padded[..., :k] = bits
    padded = padded.reshape(bits.shape[:-1] + (w, 32))
    return (padded << np.arange(32, dtype=np.uint32)).sum(
        -1, dtype=np.uint32
    )


def _unpack_lane_bits(words: np.ndarray, k: int) -> np.ndarray:
    """Inverse of _pack_lane_bits: uint32[..., W] -> uint8[..., k]."""
    bits = (
        words[..., :, None] >> np.arange(32, dtype=np.uint32)
    ) & np.uint32(1)
    flat = words.shape[:-1] + (words.shape[-1] * 32,)
    return bits.reshape(flat)[..., :k].astype(np.uint8)


def _fast_low(alphas: np.ndarray, log_n: int) -> np.ndarray:
    from ..core import chacha_np as cc

    if log_n >= cc.LEAF_LOG:
        return alphas & np.uint64(cc.LEAF_BITS - 1)
    return alphas


def gen_device_cc(
    kind: str,
    alphas: np.ndarray,
    log_n: int,
    s0: np.ndarray,
    t0: np.ndarray,
    s1: np.ndarray,
    t1: np.ndarray,
    kp: int,
    mesh=None,
    donate: bool = False,
):
    """ChaCha-tree device gen (``fast`` | ``dcf``): drawn roots ->
    (key_a, key_b) batch pair, byte-identical to the host tower."""
    K = alphas.shape[0]
    nu = max(log_n - 9, 0)
    dcf = kind == "dcf"
    bits = _pad_rows(_alpha_bits(alphas, log_n, nu).T, kp).T
    args = (
        jnp.asarray(_pad_rows(s0, kp)),
        jnp.asarray(_pad_rows(s1, kp)),
        jnp.asarray(_pad_rows(t0.astype(np.uint32), kp)),
        jnp.asarray(_pad_rows(t1.astype(np.uint32), kp)),
        jnp.asarray(np.ascontiguousarray(bits)),
    )
    if mesh is not None:
        from ..parallel import sharding

        fn = sharding.gen_cc_sharded_fn(
            mesh, nu, dcf, fused_enabled(), donate
        )
        out = fn(*args)
    else:
        fn = _gen_cc_donated_jit if donate else _gen_cc_jit
        out = fn(nu, dcf, fused_enabled(), *args)
    scw_d, tl_d, tr_d, fcw_d = out[0], out[1], out[2], out[3]

    scw = np.ascontiguousarray(
        np.asarray(scw_d).transpose(1, 0, 2)[:K]  # host-sync: gen marshalling (the keys ARE the reply)
    )
    tcw = np.ascontiguousarray(
        np.stack(
            [np.asarray(tl_d).T[:K], np.asarray(tr_d).T[:K]], axis=2  # host-sync: gen marshalling
        ).astype(np.uint8)
    )
    conv_diff = np.asarray(fcw_d)[:K].copy()  # host-sync: gen marshalling
    low = _fast_low(alphas, log_n)
    if dcf:
        from . import dcf as dcf_mod

        fvcw = conv_diff ^ dcf_mod._lt_leaf_mask(low)
        vcw = np.ascontiguousarray(
            np.asarray(out[4]).T[:K].astype(np.uint8)  # host-sync: gen marshalling
        )

        def mk(root, rt):
            return dcf_mod.DcfKeyBatch(
                log_n, root, rt, scw.copy(), tcw.copy(), vcw.copy(), fvcw
            )

        return mk(s0, t0), mk(s1, t1)
    from .keys_chacha import KeyBatchFast

    low_i = low.astype(np.int64)
    conv_diff[np.arange(K), low_i >> 5] ^= np.uint32(1) << (
        low_i & 31
    ).astype(np.uint32)

    def mk(root, rt):
        return KeyBatchFast(log_n, root, rt, scw.copy(), tcw.copy(),
                            conv_diff)

    return mk(s0, t0), mk(s1, t1)


def gen_device_compat(
    alphas: np.ndarray,
    log_n: int,
    s0: np.ndarray,
    t0: np.ndarray,
    s1: np.ndarray,
    t1: np.ndarray,
    kp: int,
    mesh=None,
    donate: bool = False,
):
    """AES-compat device gen on bitsliced planes: drawn roots (uint8
    [K, 16] seeds, uint8[K] control bits) -> (key_a, key_b)."""
    from ..ops.aes_bitslice import pack_blocks_np

    K = alphas.shape[0]
    nu = max(log_n - 7, 0)
    w = kp // 32
    bm = _pack_lane_bits(_alpha_bits(alphas, log_n, nu), w)
    t0_w = _pack_lane_bits(t0.astype(np.uint32), w)
    args = (
        jnp.asarray(pack_blocks_np(_pad_rows(s0, kp))),
        jnp.asarray(pack_blocks_np(_pad_rows(s1, kp))),
        jnp.asarray(t0_w),
        jnp.asarray(t0_w ^ np.uint32(0xFFFFFFFF)),
        jnp.asarray(bm),
    )
    if mesh is not None:
        from ..parallel import sharding

        fn = sharding.gen_compat_sharded_fn(
            mesh, nu, fused_enabled(), donate
        )
        out = fn(*args)
    else:
        fn = _gen_compat_donated_jit if donate else _gen_compat_jit
        out = fn(nu, fused_enabled(), *args)
    scw_d, tl_d, tr_d, fcw_d = out

    # host-sync: gen output marshalling (the keys ARE the reply)
    scw = np.ascontiguousarray(np.asarray(scw_d)[:K])
    tcw = np.stack(
        [
            _unpack_lane_bits(np.asarray(tl_d), K).T,  # host-sync: gen marshalling
            _unpack_lane_bits(np.asarray(tr_d), K).T,  # host-sync: gen marshalling
        ],
        axis=2,
    )
    fcw = np.asarray(fcw_d)[:K].copy().view(np.uint8).reshape(K, 16)  # host-sync: gen marshalling
    low = (alphas & np.uint64(127)).astype(np.int64)
    fcw[np.arange(K), low // 8] ^= (1 << (low % 8)).astype(np.uint8)
    fcw = fcw.view("<u4")

    from ..core.keys import KeyBatch

    def mk(root, rt):
        return KeyBatch(
            log_n, root.view("<u4"), rt, scw.copy(), tcw.copy(), fcw
        )

    return mk(s0, t0), mk(s1, t1)


# ---------------------------------------------------------------------------
# Warmup support (core/plans.warmup's "gen" branch)
# ---------------------------------------------------------------------------


def warm(kind: str, log_n: int, k: int, rng) -> None:
    """Compile the gen plan for one (kind, log_n, K-bucket): draw roots
    the way the host gen draws them, run the device route once."""
    from ..core import plans

    alphas = np.zeros(k, np.uint64)
    if kind == "compat":
        from ..core.keys import _draw_roots
    elif kind in ("fast", "dcf"):
        from .keys_chacha import _draw_roots
    else:
        raise ValueError(f"gen: unknown kind {kind!r} (compat|fast|dcf)")
    s0, t0, s1, t1 = _draw_roots(k, rng)
    plans.run_gen(kind, alphas, log_n, s0, t0, s1, t1)
