"""Distributed Comparison Function (DCF): one key per comparison gate.

The FSS gates in models/fss.py build ``1{x < alpha}`` from ``log_n``
independent DPF keys per gate — the construction available on top of a
plain point-function library like the reference (dpf/dpf.go exposes only
Gen/Eval/EvalFull).  The DCF (Boyle–Gilboa–Ishai, "Function Secret
Sharing: Improvements and Extensions", CCS 2016, §3.2; optimized in
Boyle et al., "Function Secret Sharing for Mixed-Mode and Fixed-Point
Secure Computation", EUROCRYPT 2021) shares the whole comparison in ONE
GGM tree: the key is a DPF-style key plus one extra correction bit per
level and a 512-bit leaf correction — ~log_n times smaller keys and
~log_n times less evaluation work than the per-level construction.

Construction (XOR shares, payload beta = 1, fast-profile tree shape —
ChaCha12 node PRG, 512-bit early-termination leaves):

  - The node PRG emits (left child, right child, v) where v is one extra
    pseudorandom word of the same ChaCha block (core/chacha_np.
    prg_expand_v) — the per-node value.
  - Gen walks alpha's path exactly like DPF Gen (same seed/control-bit
    correction words) and additionally publishes per level i
        VCW_i = v(s0_i) ^ v(s1_i) ^ alpha_i          (LSBs)
    where s0_i, s1_i are the two parties' on-path seeds.
  - Eval(x) walks x's path; at level i each party computes its node's
    (l, r, v) and, WHEN x_i = 0 (descending left), accumulates
        acc ^= v ^ t * VCW_i.
    On-path nodes (x and alpha agree so far) contribute
    v0 ^ v1 ^ VCW_i = alpha_i; off-path nodes cancel (identical seeds).
    Summing over levels: acc0 ^ acc1 = 1 exactly when the first
    differing bit j has x_j = 0 and alpha_j = 1 — i.e. 1{x < alpha} —
    decided at most once, at the first divergence.
  - The bottom LEAF_LOG bits resolve inside the leaf block: the final
    correction FVCW = convert(s0) ^ convert(s1) ^ LT(alpha_low) (bits
    j < alpha_low set), and each party accumulates bit x_low of
    convert(s) ^ t * FVCW.  On-path leaf -> share of 1{x_low <
    alpha_low}; off-path leaves cancel.

Key layout (to_bytes, per key): seed(16) | t(1) | nu * (sCW(16) | tL(1) |
tR(1) | VCW(1)) | FVCW(64)  ->  81 + 19 * nu bytes; one key per gate vs
``log_n * (81 + 18 nu)`` for the per-level construction.

Evaluation is a batched root-to-leaf walk with the same structure as
models/dpf_chacha._eval_points_cc_body plus the accumulator, and routes
through the Pallas whole-walk kernel on TPU (ops/chacha_pallas.py, dcf
mode).  The dcf_points/dcf_interval routes carry both certificate
kinds: obliviousness (docs/OBLIVIOUS.md) and a zero-collective /
zero-callback performance contract (docs/PERF_CONTRACTS.md) — a
comparison walk that grew a cross-device reduce or a host round trip
fails lint before it reaches a bench.  The compat (AES) profile has no DCF: its 2-call fixed-key MMO PRG
has no spare output word, and reference key compatibility pins its wire
format — comparison on compat keys stays the per-level construction in
models/fss.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core import bitpack
from ..core import chacha_np as cc
from .dpf_chacha import _split_queries


@dataclass
class DcfKeyBatch:
    """One party's share of K comparison gates ``1{x < alpha}``."""

    log_n: int
    seeds: np.ndarray  # uint32 [K, 4]
    ts: np.ndarray  # uint8  [K]
    scw: np.ndarray  # uint32 [K, nu, 4]
    tcw: np.ndarray  # uint8  [K, nu, 2]
    vcw: np.ndarray  # uint8  [K, nu]   (LSB per level)
    fvcw: np.ndarray  # uint32 [K, 16]
    _device_args: object = field(default=None, repr=False, compare=False)

    @property
    def k(self) -> int:
        return self.seeds.shape[0]

    @property
    def nu(self) -> int:
        return cc.nu_of(self.log_n)

    def to_bytes(self) -> list[bytes]:
        k, nu = self.k, self.nu
        cws = np.concatenate(
            [
                self.scw.view(np.uint8).reshape(k, nu, 16),
                self.tcw,
                self.vcw[:, :, None],
            ],
            axis=2,
        )
        out = np.concatenate(
            [
                self.seeds.view(np.uint8).reshape(k, 16),
                self.ts[:, None],
                cws.reshape(k, 19 * nu),
                self.fvcw.view(np.uint8).reshape(k, 64),
            ],
            axis=1,
        )
        return [bytes(row) for row in out]

    @classmethod
    def from_bytes(cls, keys: list[bytes], log_n: int) -> "DcfKeyBatch":
        nu = cc.nu_of(log_n)
        want = key_len(log_n)
        arr = np.empty((len(keys), want), dtype=np.uint8)
        for i, b in enumerate(keys):
            if len(b) != want:
                raise ValueError(f"dcf: key {i} length {len(b)} != {want}")
            # Buffer views (the wire2 front's zero-copy body slices)
            # parse without an intermediate bytes copy; the SoA
            # arrays below own their storage either way.
            arr[i] = np.frombuffer(b, dtype=np.uint8)
        seeds = arr[:, :16].copy().view("<u4")
        ts = arr[:, 16].copy()
        cws = arr[:, 17 : 17 + 19 * nu].reshape(len(keys), nu, 19)
        scw = np.ascontiguousarray(cws[:, :, :16]).view("<u4")
        tcw = cws[:, :, 16:18].copy()
        vcw = cws[:, :, 18].copy()
        fvcw = arr[:, -64:].copy().view("<u4")
        if (
            (ts > 1).any()
            or (tcw > 1).any()
            or (vcw > 1).any()
            or (seeds[:, 0] & 1).any()
            or (scw[:, :, 0] & 1).any()
        ):
            raise ValueError("dcf: non-canonical key")
        return cls(log_n, seeds, ts, scw, tcw, vcw, fvcw)

    def device_args(self):
        """Memoized device operands (control bytes widened to uint32)."""
        if self._device_args is not None:
            return self._device_args
        import jax.numpy as jnp

        args = (
            jnp.asarray(self.seeds),
            jnp.asarray(self.ts.astype(np.uint32)),
            jnp.asarray(self.scw),
            jnp.asarray(self.tcw.astype(np.uint32)),
            jnp.asarray(self.vcw.astype(np.uint32)),
            jnp.asarray(self.fvcw),
        )
        self._device_args = args
        return args


def key_len(log_n: int) -> int:
    """Serialized DCF key size: 17 + 19*nu + 64 bytes."""
    return 17 + 19 * cc.nu_of(log_n) + 64


def _lt_leaf_mask(low: np.ndarray) -> np.ndarray:
    """uint64[K] in-leaf thresholds -> uint32[K, 16] blocks with bits
    j < low set (LSB-first within words, ascending words)."""
    j = np.arange(cc.LEAF_BITS, dtype=np.uint64)
    bits = (j[None, :] < low[:, None]).astype(np.uint8)
    w = bits.reshape(-1, 16, 32).astype(np.uint32)
    return (w << np.arange(32, dtype=np.uint32)).sum(-1, dtype=np.uint32)


def gen_lt_batch(
    alphas: np.ndarray | list[int],
    log_n: int,
    rng: np.random.Generator | None = None,
) -> tuple[DcfKeyBatch, DcfKeyBatch]:
    """Vectorized DCF Gen for K gates ``1{x < alpha}`` -> (key_a, key_b).

    Identical walk to keys_chacha.gen_batch (the DPF seed/control-bit
    machinery is unchanged) plus the per-level value CW and the in-leaf
    comparison correction.  Seeds are drawn here; the tower runs on
    device through ``core/plans.run_gen`` when ``DPF_TPU_GEN`` resolves
    to the device, byte-identically."""
    alphas = np.asarray(alphas, dtype=np.uint64)
    K = alphas.shape[0]
    if log_n > 63 or log_n < 1 or (alphas >> np.uint64(log_n)).any():
        raise ValueError("dcf: invalid parameters")

    from .keys_chacha import _draw_roots

    s0, t0, s1, t1 = _draw_roots(K, rng)
    from . import keys_gen

    if keys_gen.device_enabled():
        out = keys_gen.try_gen_device("dcf", alphas, log_n, s0, t0, s1, t1)
        if out is not None:
            return out
    return _gen_lt_from_roots(alphas, log_n, s0, t0, s1, t1)


def _gen_lt_from_roots(
    alphas: np.ndarray,
    log_n: int,
    s0: np.ndarray,
    t0: np.ndarray,
    s1: np.ndarray,
    t1: np.ndarray,
) -> tuple[DcfKeyBatch, DcfKeyBatch]:
    """The host DCF tower (CPU/degraded twin)."""
    K = alphas.shape[0]
    nu = cc.nu_of(log_n)
    root0, rt0 = s0.copy(), t0.copy()
    root1, rt1 = s1.copy(), t1.copy()

    scw_all = np.zeros((K, nu, 4), dtype=np.uint32)
    tcw_all = np.zeros((K, nu, 2), dtype=np.uint8)
    vcw_all = np.zeros((K, nu), dtype=np.uint8)

    for i in range(nu):
        l0, r0, v0 = cc.prg_expand_v(s0)
        l1, r1, v1 = cc.prg_expand_v(s1)
        t0l, t0r = (l0[:, 0] & 1).astype(np.uint8), (r0[:, 0] & 1).astype(np.uint8)
        t1l, t1r = (l1[:, 0] & 1).astype(np.uint8), (r1[:, 0] & 1).astype(np.uint8)
        for a in (l0, r0, l1, r1):
            a[:, 0] &= ~np.uint32(1)

        bit = ((alphas >> np.uint64(log_n - 1 - i)) & np.uint64(1)).astype(np.uint8)
        vcw_all[:, i] = (v0 ^ v1 ^ bit.astype(np.uint32)) & 1
        b = bit[:, None].astype(bool)
        scw = np.where(b, l0 ^ l1, r0 ^ r1)  # LOSE side
        tlcw = (t0l ^ t1l ^ bit ^ 1).astype(np.uint8)
        trcw = (t0r ^ t1r ^ bit).astype(np.uint8)
        scw_all[:, i] = scw
        tcw_all[:, i, 0] = tlcw
        tcw_all[:, i, 1] = trcw

        keep_s0 = np.where(b, r0, l0)
        keep_s1 = np.where(b, r1, l1)
        keep_t0 = np.where(bit, t0r, t0l).astype(np.uint8)
        keep_t1 = np.where(bit, t1r, t1l).astype(np.uint8)
        keep_tcw = np.where(bit, trcw, tlcw).astype(np.uint8)

        s0 = keep_s0 ^ (t0[:, None].astype(np.uint32) * scw)
        s1 = keep_s1 ^ (t1[:, None].astype(np.uint32) * scw)
        t0 = keep_t0 ^ (t0 * keep_tcw)
        t1 = keep_t1 ^ (t1 * keep_tcw)

    conv0 = cc.convert_leaf(s0)
    conv1 = cc.convert_leaf(s1)
    low = alphas & np.uint64(cc.LEAF_BITS - 1) if log_n >= cc.LEAF_LOG else alphas
    fvcw = conv0 ^ conv1 ^ _lt_leaf_mask(low)

    def mk(root, rt):
        return DcfKeyBatch(
            log_n, root, rt, scw_all.copy(), tcw_all.copy(),
            vcw_all.copy(), fvcw,
        )

    return mk(root0, rt0), mk(root1, rt1)


def eval_points_np(kb: DcfKeyBatch, xs: np.ndarray) -> np.ndarray:
    """Pure-NumPy spec evaluation: xs uint64[K, Q] -> uint8[K, Q].
    Slow; the executable reference the device paths differential-test
    against."""
    xs = np.asarray(xs, dtype=np.uint64)
    K, Q = xs.shape
    if K != kb.k:
        raise ValueError("dcf: xs first axis must match key batch")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dcf: query index out of domain")
    n, nu = kb.log_n, kb.nu
    s = np.repeat(kb.seeds[:, None, :], Q, axis=1).reshape(K * Q, 4)
    t = np.repeat(kb.ts.astype(np.uint32)[:, None], Q, axis=1).reshape(-1)
    acc = np.zeros(K * Q, np.uint32)
    xf = xs.reshape(-1)
    kidx = np.repeat(np.arange(K), Q)
    for i in range(nu):
        l, r, v = cc.prg_expand_v(s)
        tl = l[:, 0] & 1
        tr = r[:, 0] & 1
        l[:, 0] &= ~np.uint32(1)
        r[:, 0] &= ~np.uint32(1)
        vcw = kb.vcw[kidx, i].astype(np.uint32)
        xbit = ((xf >> np.uint64(n - 1 - i)) & np.uint64(1)).astype(np.uint32)
        acc ^= (v ^ (t * vcw)) & np.uint32(1) & (1 - xbit)
        scw = kb.scw[kidx, i]
        tcw = kb.tcw[kidx, i].astype(np.uint32)
        go_r = xbit[:, None].astype(bool)
        s = np.where(go_r, r, l) ^ (t[:, None] * scw)
        t = np.where(xbit.astype(bool), tr, tl) ^ (t * np.where(
            xbit.astype(bool), tcw[:, 1], tcw[:, 0]
        ))
    block = cc.convert_leaf(s) ^ (t[:, None] * kb.fvcw[kidx])
    low = (xf & np.uint64(cc.LEAF_BITS - 1)).astype(np.int64)
    if n < cc.LEAF_LOG:
        low = xf.astype(np.int64)
    sel = block[np.arange(K * Q), low >> 5]
    acc ^= (sel >> (low & 31).astype(np.uint32)) & 1
    return acc.astype(np.uint8).reshape(K, Q)


def points_kernel_eligible(k: int) -> bool:
    """THE routing predicate of :func:`eval_lt_points` (and, through the
    fused 2K-key batch, :func:`eval_interval_points`): the Pallas
    whole-walk kernel in DCF mode when the key count tiles the kernel's
    lane quantum.  Exposed so benchmarks label their route rows from the
    same predicate production routes on."""
    from ..ops import chacha_pallas as cp

    return cp.points_backend() == "pallas" and cp.usable(k)


def eval_lt_points(
    kb: DcfKeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Batched comparison-share evaluation: xs uint64[K, Q] -> uint8[K, Q]
    with  eval(ka) ^ eval(kb) == 1{x < alpha}  per gate.

    Routes through the Pallas whole-walk kernel on TPU (DCF mode) when the
    key count tiles the kernel's lane quantum; else the XLA body.
    ``packed`` returns the shares as uint32[K, ceil(Q/32)] packed words
    (device-side pack, core/bitpack contract — 32x less D2H; XOR
    reconstruction works directly on the words)."""
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dcf: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dcf: query index out of domain")
    from ..ops import chacha_pallas as cp

    if points_kernel_eligible(kb.k):
        return cp.eval_points_walk_dcf(kb, xs, packed=packed)
    return _eval_points_xla(kb, xs, packed)


def _eval_points_xla(
    kb: DcfKeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    from .dpf_chacha import _eval_points_cc_jit, _eval_points_cc_packed_jit

    seeds, ts, scw, tcw, vcw, fvcw = kb.device_args()
    if packed:
        Q = xs.shape[1]
        pad_q = (-Q) % 32
        if pad_q:
            xs = np.concatenate(
                [xs, np.zeros((xs.shape[0], pad_q), np.uint64)], axis=1
            )
        xs_hi, xs_lo = _split_queries(xs, kb.log_n)
        words = _eval_points_cc_packed_jit(
            kb.nu, kb.log_n, seeds, ts, scw, tcw, fvcw, xs_hi, xs_lo, 0, vcw
        )
        # host-sync: final reply marshalling (DCF packed shares)
        return bitpack.mask_tail(np.asarray(words), Q)
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)
    bits = _eval_points_cc_jit(
        kb.nu, kb.log_n, seeds, ts, scw, tcw, fvcw, xs_hi, xs_lo, 0, vcw
    )
    return np.asarray(bits).T  # host-sync: final reply marshalling


def gen_interval_batch(
    lo: np.ndarray | list[int],
    hi: np.ndarray | list[int],
    log_n: int,
    rng: np.random.Generator | None = None,
):
    """K interval gates ``1{lo <= x <= hi}`` from TWO DCFs per gate
    (``lt_{hi+1} ^ lt_{lo}``; the ``hi = 2^n - 1`` wrap edge becomes an
    always-0 upper gate plus a public constant on party A — models/fss.py
    semantics, at DCF key sizes).  Returns two (upper, lower, const)
    triples; evaluate with :func:`eval_interval_points`."""
    lo = np.asarray(lo, dtype=np.uint64)
    hi = np.asarray(hi, dtype=np.uint64)
    if lo.shape != hi.shape or lo.ndim != 1:
        raise ValueError("dcf: lo/hi must be 1-D and equal length")
    if (lo > hi).any():
        raise ValueError("dcf: lo > hi")
    top = (np.uint64(1) << np.uint64(log_n)) - np.uint64(1)
    if (hi > top).any():
        raise ValueError("dcf: hi out of domain")
    wrap = hi == top
    upper_alpha = np.where(wrap, np.uint64(0), hi + np.uint64(1))
    ua, ub = gen_lt_batch(upper_alpha, log_n, rng=rng)
    la, lb = gen_lt_batch(lo, log_n, rng=rng)
    const_a = wrap.astype(np.uint8)
    const_b = np.zeros_like(const_a)
    return (ua, la, const_a), (ub, lb, const_b)


def _concat_batches(a: DcfKeyBatch, b: DcfKeyBatch) -> DcfKeyBatch:
    return DcfKeyBatch(
        a.log_n,
        np.concatenate([a.seeds, b.seeds]),
        np.concatenate([a.ts, b.ts]),
        np.concatenate([a.scw, b.scw]),
        np.concatenate([a.tcw, b.tcw]),
        np.concatenate([a.vcw, b.vcw]),
        np.concatenate([a.fvcw, b.fvcw]),
    )


def eval_interval_points(
    ik, xs: np.ndarray, packed: bool = False, lt_eval=None
) -> np.ndarray:
    """Evaluate interval shares at xs uint64[K, Q] -> uint8[K, Q]; ``ik``
    is one party's (upper, lower, const) triple from
    :func:`gen_interval_batch`.  Both gate sets evaluate in ONE device
    launch (a fused 2K-key batch, built lazily and reused — its
    device-resident operands amortize across calls).  ``packed`` returns
    uint32[K, ceil(Q/32)] packed words (core/bitpack contract); the
    upper^lower fold and the public wrap constant apply directly on the
    words.  ``lt_eval`` overrides the comparison evaluator (same
    signature as :func:`eval_lt_points`) — the mesh serving path injects
    the sharded walk here so the combine stays in one place."""
    upper, lower, const = ik[0], ik[1], ik[2]
    if lt_eval is None:
        lt_eval = eval_lt_points
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != upper.k:
        raise ValueError("dcf: xs must be [K, Q]")
    # The memo is keyed on the *pair*: reusing a fused batch built against a
    # different lower half would silently return wrong interval shares.
    cached = getattr(upper, "_interval_both", None)
    if cached is not None and cached[0] is lower:
        both = cached[1]
    else:
        both = _concat_batches(upper, lower)
        try:
            upper._interval_both = (lower, both)
        except AttributeError:
            pass
    k = upper.k
    if packed:
        words = lt_eval(both, np.concatenate([xs, xs]), packed=True)
        # const in {0, 1} complements a gate's whole row; re-mask the tail
        # the complement just set.
        cmask = (np.uint32(0) - const.astype(np.uint32))[:, None]
        return bitpack.mask_tail(words[:k] ^ words[k:] ^ cmask, xs.shape[1])
    bits = lt_eval(both, np.concatenate([xs, xs]), packed=False)
    return bits[:k] ^ bits[k:] ^ const[:, None]
