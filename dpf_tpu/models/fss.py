"""FSS comparison and interval gates built from batched DPFs.

The reference library stops at point functions (dpf/dpf.go: Gen/Eval/
EvalFull); comparison and interval gates are the canonical FSS application
layered on top (BGI 2016, sec. 3.2.2: an interval function is a union of at
most ``log N`` dyadic intervals, each of which is a *point* function on a
prefix domain).  This module realizes them entirely from the framework's own
batched DPF primitives, so the whole gate evaluates as ONE bitsliced
``eval_points`` launch on the accelerator.

Construction (comparison, ``1{x < alpha}`` over ``[0, 2^n)``):

    x < alpha  <=>  exists a unique level i in [0, n):
                    x and alpha agree on their top i bits,
                    bit i of alpha (MSB-first) is 1, and bit i of x is 0.

Level i's condition is the point function "top i+1 bits of x equal
(alpha's top i bits || 0)".  Rather than using a separate (i+1)-bit prefix
domain per level (ragged shapes -> one compile per level), every level is
embedded in the full n-bit domain: the level-i DPF's point is the prefix
*shifted back up* (low bits zero) and queries are masked the same way, so
all n levels form one uniform ``KeyBatch`` of ``n * G`` keys evaluated in a
single call.  Levels where alpha's bit is 0 contribute a constant 0: both
parties receive *identical* keys for a random point, whose evaluations
cancel under XOR (zero-sharing by key duplication — standard in the
trusted-dealer / semi-honest 2-server FSS model; a single key reveals
nothing about its point, so the per-party view is unchanged).

Since the matching level is unique, XOR over levels equals the union, and
the parties' outputs are XOR-shares of the predicate:

    eval_lt_points(ck_a, xs) ^ eval_lt_points(ck_b, xs) == (xs < alpha)

Interval gates ``1{lo <= x <= hi}`` are the XOR of two comparisons
(``lt_{hi+1} ^ lt_{lo}``) and evaluate as one fused launch over both gate
sets; the ``hi == 2^n - 1`` edge folds into a public constant on party A.

Also provided: ``ge_full_from_dpf`` — full-domain comparison shares from a
SINGLE ordinary DPF key via a carry-less prefix-XOR scan over the bit-packed
``EvalFull`` output (XOR_{y <= x} DPF(y) = 1{x >= alpha}), which turns the
already-computed leaf planes into a comparison table with one extra
device pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bitpack
from ..core.keys import KeyBatch, gen_batch
from .dpf import DeviceKeys, eval_full_device, eval_points


def _profile_funcs(profile: str):
    """(gen_batch, eval_points, key-batch class, key_len, grouped_eval) per
    profile.  ``grouped_eval(levels, xs, groups)`` evaluates level-major key
    groups with the dyadic-prefix masking done on device, or None when the
    profile only supports host-expanded queries."""
    if profile == "fast":
        from ..core.chacha_np import key_len as kl
        from .dpf_chacha import (
            eval_points as ep,
            eval_points_level_grouped as grouped,
        )
        from .keys_chacha import KeyBatchFast, gen_batch as gb

        return gb, ep, KeyBatchFast, kl, grouped
    if profile == "compat":
        from ..core.spec import key_len as kl
        from .dpf import eval_points_level_grouped as grouped_c

        return gen_batch, eval_points, KeyBatch, kl, grouped_c
    raise ValueError(f"fss: unknown profile {profile!r}")

__all__ = [
    "CmpKeyBatch",
    "IntervalKeyBatch",
    "gen_lt_batch",
    "eval_lt_points",
    "gen_interval_batch",
    "eval_interval_points",
    "ge_full_from_dpf",
]


@dataclass
class CmpKeyBatch:
    """One party's share of G comparison gates ``1{x < alpha_g}``.

    ``levels`` holds ``n * G`` full-domain DPF keys, level-major: key
    ``i * G + g`` is gate g's level-i DPF.  Serializes per gate as the
    concatenation of its n per-profile-layout DPF keys."""

    log_n: int
    levels: KeyBatch  # K = log_n * G keys on the n-bit domain
    profile: str = "compat"

    @property
    def g(self) -> int:
        return self.levels.k // self.log_n

    def to_bytes(self) -> list[bytes]:
        """-> G blobs, each ``log_n * key_len(log_n)`` bytes."""
        lv = self.levels.to_bytes()
        G = self.g
        return [b"".join(lv[i * G + g] for i in range(self.log_n)) for g in range(G)]

    @classmethod
    def from_bytes(
        cls, blobs: list[bytes], log_n: int, profile: str = "compat"
    ) -> "CmpKeyBatch":
        _, _, batch_cls, key_len, _ = _profile_funcs(profile)

        kl = key_len(log_n)
        keys: list[bytes] = []
        for i in range(log_n):
            for g, blob in enumerate(blobs):
                if len(blob) != log_n * kl:
                    raise ValueError(f"fss: gate {g} blob length != {log_n * kl}")
                keys.append(blob[i * kl : (i + 1) * kl])
        return cls(log_n, batch_cls.from_bytes(keys, log_n), profile)


@dataclass
class IntervalKeyBatch:
    """One party's share of G interval gates ``1{lo_g <= x <= hi_g}``:
    two comparison gate sets plus a public per-gate constant (non-zero only
    on party A, only for the ``hi == 2^n - 1`` edge)."""

    upper: CmpKeyBatch  # lt_{hi+1}
    lower: CmpKeyBatch  # lt_{lo}
    const: np.ndarray  # uint8 [G]
    # Fused upper||lower key batch, built lazily by eval_interval_points and
    # reused (with its device-resident operands) across calls.
    _both: object = field(default=None, repr=False, compare=False)


def _rand_points(rng: np.random.Generator, shape, log_n: int) -> np.ndarray:
    raw = rng.integers(0, 1 << 32, size=shape + (2,), dtype=np.uint64)
    v = (raw[..., 0] << np.uint64(32)) | raw[..., 1]
    return v & ((np.uint64(1) << np.uint64(log_n)) - np.uint64(1))


def gen_lt_batch(
    alphas: np.ndarray | list[int],
    log_n: int,
    rng: np.random.Generator | None = None,
    profile: str = "compat",
) -> tuple[CmpKeyBatch, CmpKeyBatch]:
    """Generate G comparison gate pairs for ``1{x < alpha}``.

    Host-side trusted-dealer step; one vectorized ``gen_batch`` over all
    ``log_n * G`` level-DPFs.  ``profile="fast"`` builds the gates from
    ChaCha-profile DPFs (both parties must evaluate with the same profile)."""
    gen, _, _, _, _ = _profile_funcs(profile)
    alphas = np.asarray(alphas, dtype=np.uint64)
    if log_n < 1 or log_n > 63:
        raise ValueError("fss: log_n out of range")
    if (alphas >> np.uint64(log_n)).any():
        raise ValueError("fss: alpha out of domain")
    G = alphas.shape[0]
    n = log_n
    point_rng = rng if rng is not None else np.random.default_rng()

    shifts = (n - 1 - np.arange(n, dtype=np.uint64))[:, None]  # [n, 1]
    pref = alphas[None, :] >> shifts  # top i+1 bits of alpha
    active = (pref & np.uint64(1)).astype(bool)  # bit i of alpha
    points = (pref & ~np.uint64(1)) << shifts  # (top-i bits || 0) << shift
    points = np.where(active, points, _rand_points(point_rng, (n, G), n))

    ka, kb = gen(points.reshape(n * G), n, rng=rng)
    # Zero-share inactive levels: party B gets party A's key verbatim.
    idx = np.flatnonzero(~active.reshape(n * G))
    for f in ("seeds", "ts", "scw", "tcw", "fcw"):
        getattr(kb, f)[idx] = getattr(ka, f)[idx]
    return CmpKeyBatch(n, ka, profile), CmpKeyBatch(n, kb, profile)


def _masked_prefix_queries(xs: np.ndarray, log_n: int) -> np.ndarray:
    """uint64[G, Q] -> uint64[n * G, Q]: per level, x with its low
    ``n - 1 - i`` bits zeroed (the level-i prefix, shifted back up)."""
    n = log_n
    shifts = (n - 1 - np.arange(n, dtype=np.uint64))[:, None, None]
    return ((xs[None, :, :] >> shifts) << shifts).reshape(n * xs.shape[0], -1)


def eval_lt_points(
    ck: CmpKeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Evaluate comparison shares at xs uint64[G, Q] -> uint8[G, Q].

    One device launch over all ``n * G`` level-DPFs; the level
    XOR-reduction collapses the unique matching level into the predicate.
    Both profiles mask the dyadic-prefix queries on device
    (eval_points_level_grouped) — the raw [G, Q] queries are all that
    crosses the wire; off-TPU the compat profile expands them host-side.
    ``packed`` returns the gate shares as uint32[G, ceil(Q/32)] packed
    words (core/bitpack contract): the level fold happens on packed words,
    so the selection vector never round-trips through uint8."""
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != ck.g:
        raise ValueError("fss: xs must be [G, Q]")
    _, ep, _, _, grouped = _profile_funcs(ck.profile)
    if grouped is not None:
        # Level XOR-fold happens on device (ops/chacha_pallas.py): only the
        # [G, Q] gate shares cross the host link, not [n*G, Q] level bits.
        return grouped(ck.levels, xs, groups=1, reduce=True, packed=packed)
    bits = ep(ck.levels, _masked_prefix_queries(xs, ck.log_n))
    out = np.bitwise_xor.reduce(bits.reshape(ck.log_n, ck.g, -1), axis=0)
    return bitpack.pack_bits(out) if packed else out


def gen_interval_batch(
    lo: np.ndarray | list[int],
    hi: np.ndarray | list[int],
    log_n: int,
    rng: np.random.Generator | None = None,
    profile: str = "compat",
) -> tuple[IntervalKeyBatch, IntervalKeyBatch]:
    """Generate G interval gate pairs for ``1{lo <= x <= hi}`` (inclusive).

    ``1{lo <= x <= hi} = 1{x < hi+1} ^ 1{x < lo}``; the ``hi = 2^n - 1``
    edge (where hi+1 leaves the domain) becomes an always-0 gate plus a
    public constant 1 on party A."""
    lo = np.asarray(lo, dtype=np.uint64)
    hi = np.asarray(hi, dtype=np.uint64)
    if lo.shape != hi.shape or lo.ndim != 1:
        raise ValueError("fss: lo/hi must be 1-D and equal length")
    if (lo > hi).any():
        raise ValueError("fss: lo > hi")
    top = (np.uint64(1) << np.uint64(log_n)) - np.uint64(1)
    if (hi > top).any():
        raise ValueError("fss: hi out of domain")
    wrap = hi == top
    # alpha = 0 has no set bits -> every level inactive -> lt_0 == 0 shares.
    upper_alpha = np.where(wrap, np.uint64(0), hi + np.uint64(1))
    ua, ub = gen_lt_batch(upper_alpha, log_n, rng=rng, profile=profile)
    la, lb = gen_lt_batch(lo, log_n, rng=rng, profile=profile)
    const_a = wrap.astype(np.uint8)
    const_b = np.zeros_like(const_a)
    return IntervalKeyBatch(ua, la, const_a), IntervalKeyBatch(ub, lb, const_b)


def eval_interval_points(
    ik: IntervalKeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Evaluate interval shares at xs uint64[G, Q] -> uint8[G, Q].

    Both comparison gate sets fuse into a single device launch (one
    ``KeyBatch`` of ``2 * n * G`` keys).  ``packed`` returns
    uint32[G, ceil(Q/32)] packed words (core/bitpack contract); the
    public wrap constant complements rows directly on the words."""
    _, ep, batch_cls, _, grouped = _profile_funcs(ik.upper.profile)
    xs = np.asarray(xs, dtype=np.uint64)
    G, n = ik.upper.g, ik.upper.log_n
    if xs.ndim != 2 or xs.shape[0] != G:
        raise ValueError("fss: xs must be [G, Q]")
    both = ik._both
    if both is None:
        u, lo = ik.upper.levels, ik.lower.levels
        both = batch_cls(
            n,
            np.concatenate([u.seeds, lo.seeds]),
            np.concatenate([u.ts, lo.ts]),
            np.concatenate([u.scw, lo.scw]),
            np.concatenate([u.tcw, lo.tcw]),
            np.concatenate([u.fcw, lo.fcw]),
        )
        ik._both = both  # fused batch reused (and device-cached) across calls
    if grouped is not None:
        # device XOR-fold (packed words stay packed end-to-end)
        out = grouped(both, xs, groups=2, reduce=True, packed=packed)
    else:
        q = _masked_prefix_queries(xs, n)  # [n*G, Q]
        bits = ep(both, np.concatenate([q, q]))
        out = np.bitwise_xor.reduce(bits.reshape(2, n, G, -1), axis=(0, 1))
        if packed:
            out = bitpack.pack_bits(out)
    if packed:
        cmask = (np.uint32(0) - ik.const.astype(np.uint32))[:, None]
        return bitpack.mask_tail(out ^ cmask, xs.shape[1])
    return out ^ ik.const[:, None]


# ---------------------------------------------------------------------------
# Full-domain comparison from a single ordinary DPF
# ---------------------------------------------------------------------------


@jax.jit
def _prefix_xor_words(w: jax.Array) -> jax.Array:
    """Bitwise prefix-XOR over uint32[K, M] in ascending LSB-first bit
    order: output bit j = XOR of input bits 0..j (per key)."""
    for sh in (1, 2, 4, 8, 16):
        w = w ^ (w << sh)
    par = (w >> 31) & jnp.uint32(1)  # full parity of each word
    carry = jax.lax.associative_scan(jnp.bitwise_xor, par, axis=1) ^ par
    return w ^ (jnp.uint32(0) - carry)  # complement words with odd carry-in


def ge_full_from_dpf(kb) -> np.ndarray:
    """Full-domain comparison table from plain DPF keys: for a key pair on
    point alpha, the two parties' outputs XOR to the bit-packed indicator
    ``1{x >= alpha}`` over the whole domain (``1{x < alpha}`` is its public
    complement).

    Uses the identity XOR_{y <= x} DPF_alpha(y) = 1{x >= alpha}: expand the
    key with the level-synchronous evaluator, then run one carry-less
    prefix-XOR scan over the packed leaf words on device.  Accepts either
    profile's key batch (KeyBatch or KeyBatchFast).  -> uint8[K, out_bytes]
    (out_bytes = 2^(log_n-3); minimum one leaf block), same packing as
    ``eval_full`` (bit x at byte x//8, bit x%8; reference dpf/dpf.go:207).
    """
    from .keys_chacha import KeyBatchFast

    if isinstance(kb, KeyBatchFast):
        from .dpf_chacha import eval_full_device as eval_full_device_cc

        # [K, W, 16], ascending bit order (VMEM expand kernel on TPU)
        words = eval_full_device_cc(kb)
    else:
        words = eval_full_device(DeviceKeys(kb))  # [Kpad, W, 4]
    scanned = _prefix_xor_words(words.reshape(words.shape[0], -1))
    # host-sync: final reply marshalling (comparison table)
    out = np.ascontiguousarray(np.asarray(scanned)[: kb.k])
    return out.view("<u1").reshape(kb.k, -1)
