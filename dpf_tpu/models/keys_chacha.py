"""Batched key handling for the ChaCha fast profile.

Struct-of-arrays mirror of ``core.keys`` for the fast-profile key layout
(core/chacha_np.py): 128-bit seeds, 18-byte per-level CWs (identical CW
shape to the reference, dpf/dpf.go:111-112), 64-byte final CW for the
512-bit leaf.  Gen is host-side and vectorized across the key batch, like
``core.keys.gen_batch`` (reference Gen loop: dpf/dpf.go:94-158)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core import chacha_np as cc


@dataclass
class KeyBatchFast:
    """K same-domain fast-profile DPF keys in struct-of-arrays form."""

    log_n: int
    seeds: np.ndarray  # uint32 [K, 4]
    ts: np.ndarray  # uint8  [K]
    scw: np.ndarray  # uint32 [K, nu, 4]
    tcw: np.ndarray  # uint8  [K, nu, 2]
    fcw: np.ndarray  # uint32 [K, 16]
    # Memoized device operands (see device_args).
    _device_args: object = field(default=None, repr=False, compare=False)
    # Zero-padded copies keyed by pad amount (parallel/sharding), so padding
    # to a mesh doesn't defeat the device_args memoization.
    _padded: object = field(default=None, repr=False, compare=False)

    @property
    def k(self) -> int:
        return self.seeds.shape[0]

    @property
    def nu(self) -> int:
        return cc.nu_of(self.log_n)

    @classmethod
    def from_bytes(cls, keys: list[bytes], log_n: int) -> "KeyBatchFast":
        nu = cc.nu_of(log_n)
        want = cc.key_len(log_n)
        arr = np.empty((len(keys), want), dtype=np.uint8)
        for i, k in enumerate(keys):
            if len(k) != want:
                raise ValueError(f"dpf-fast: key {i} length {len(k)} != {want}")
            # Buffer views (the wire2 front's zero-copy body slices)
            # parse without an intermediate bytes copy; the SoA
            # arrays below own their storage either way.
            arr[i] = np.frombuffer(k, dtype=np.uint8)
        seeds = arr[:, :16].copy().view("<u4")
        ts = arr[:, 16].copy()
        cws = arr[:, 17 : 17 + 18 * nu].reshape(len(keys), nu, 18)
        scw = np.ascontiguousarray(cws[:, :, :16]).view("<u4")
        tcw = cws[:, :, 16:].copy()
        fcw = arr[:, -64:].copy().view("<u4")
        if (
            (ts > 1).any()
            or (tcw > 1).any()
            or (seeds[:, 0] & 1).any()
            or (scw[:, :, 0] & 1).any()
        ):
            raise ValueError("dpf-fast: non-canonical key")
        return cls(log_n, seeds, ts, scw, tcw, fcw)

    def device_args(self):
        """The five device operands every fast-profile evaluator takes:
        (seeds, ts, scw, tcw, fcw) as jnp arrays, control bytes widened to
        uint32 lane masks.  Single source of truth for the marshaling.

        Memoized: key material is immutable once evaluated, and re-uploading
        it per call dominates serving-shaped workloads (an FSS gate batch is
        ~70 MB of keys vs ~1 ms of device work per call).  Callers that
        mutate the arrays (gen_lt_batch's zero-sharing) do so before the
        first evaluation."""
        if self._device_args is not None:
            return self._device_args
        import jax.numpy as jnp

        args = (
            jnp.asarray(self.seeds),
            jnp.asarray(self.ts.astype(np.uint32)),
            jnp.asarray(self.scw),
            jnp.asarray(self.tcw.astype(np.uint32)),
            jnp.asarray(self.fcw),
        )
        self._device_args = args
        return args

    def to_bytes(self) -> list[bytes]:
        k, nu = self.k, self.nu
        cws = np.concatenate(
            [self.scw.view(np.uint8).reshape(k, nu, 16), self.tcw], axis=2
        )
        out = np.concatenate(
            [
                self.seeds.view(np.uint8).reshape(k, 16),
                self.ts[:, None],
                cws.reshape(k, 18 * nu),
                self.fcw.view(np.uint8).reshape(k, 64),
            ],
            axis=1,
        )
        return [bytes(row) for row in out]


def _draw_roots(
    K: int, rng: np.random.Generator | None
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Draw + canonicalize both parties' root seeds (the CSPRNG
    boundary; one 2K draw, party A first — the draw order is part of
    the host/device byte-identity contract)."""
    raw = cc.gen_root_seeds(2 * K, rng)
    s0 = np.ascontiguousarray(raw[:K]).view("<u4")
    s1 = np.ascontiguousarray(raw[K:]).view("<u4")
    t0 = (s0[:, 0] & 1).astype(np.uint8)
    t1 = t0 ^ 1
    s0[:, 0] &= ~np.uint32(1)
    s1[:, 0] &= ~np.uint32(1)
    return s0, t0, s1, t1


def gen_batch(
    alphas: np.ndarray | list[int],
    log_n: int,
    rng: np.random.Generator | None = None,
) -> tuple[KeyBatchFast, KeyBatchFast]:
    """Fast-profile Gen: root seeds drawn on host, the correction-word
    tower on device through ``core/plans.run_gen`` when ``DPF_TPU_GEN``
    resolves to the device, else the vectorized host loop below —
    byte-identical either way (same drawn seeds, deterministic tower)."""
    alphas = np.asarray(alphas, dtype=np.uint64)
    K = alphas.shape[0]
    if log_n > 63 or (alphas >> np.uint64(log_n)).any():
        raise ValueError("dpf-fast: invalid parameters")

    s0, t0, s1, t1 = _draw_roots(K, rng)
    from . import keys_gen

    if keys_gen.device_enabled():
        out = keys_gen.try_gen_device("fast", alphas, log_n, s0, t0, s1, t1)
        if out is not None:
            return out
    return _gen_from_roots(alphas, log_n, s0, t0, s1, t1)


def _gen_from_roots(
    alphas: np.ndarray,
    log_n: int,
    s0: np.ndarray,
    t0: np.ndarray,
    s1: np.ndarray,
    t1: np.ndarray,
) -> tuple[KeyBatchFast, KeyBatchFast]:
    """The host tower (CPU/degraded twin): the reference Gen level loop
    (dpf/dpf.go:94-158) with the ChaCha node PRG, stopping 9 levels
    early (512-bit leaves), every step batched over all K keys."""
    K = alphas.shape[0]
    nu = cc.nu_of(log_n)
    root0, rt0 = s0.copy(), t0.copy()
    root1, rt1 = s1.copy(), t1.copy()

    scw_all = np.zeros((K, nu, 4), dtype=np.uint32)
    tcw_all = np.zeros((K, nu, 2), dtype=np.uint8)

    for i in range(nu):
        l0, r0 = cc.prg_expand(s0)
        l1, r1 = cc.prg_expand(s1)
        t0l, t0r = (l0[:, 0] & 1).astype(np.uint8), (r0[:, 0] & 1).astype(np.uint8)
        t1l, t1r = (l1[:, 0] & 1).astype(np.uint8), (r1[:, 0] & 1).astype(np.uint8)
        for a in (l0, r0, l1, r1):
            a[:, 0] &= ~np.uint32(1)

        bit = ((alphas >> np.uint64(log_n - 1 - i)) & np.uint64(1)).astype(np.uint8)
        b = bit[:, None].astype(bool)
        scw = np.where(b, l0 ^ l1, r0 ^ r1)  # LOSE side
        tlcw = (t0l ^ t1l ^ bit ^ 1).astype(np.uint8)
        trcw = (t0r ^ t1r ^ bit).astype(np.uint8)
        scw_all[:, i] = scw
        tcw_all[:, i, 0] = tlcw
        tcw_all[:, i, 1] = trcw

        keep_s0 = np.where(b, r0, l0)
        keep_s1 = np.where(b, r1, l1)
        keep_t0 = np.where(bit, t0r, t0l).astype(np.uint8)
        keep_t1 = np.where(bit, t1r, t1l).astype(np.uint8)
        keep_tcw = np.where(bit, trcw, tlcw).astype(np.uint8)

        s0 = keep_s0 ^ (t0[:, None].astype(np.uint32) * scw)
        s1 = keep_s1 ^ (t1[:, None].astype(np.uint32) * scw)
        t0 = keep_t0 ^ (t0 * keep_tcw)
        t1 = keep_t1 ^ (t1 * keep_tcw)

    conv0 = cc.convert_leaf(s0)
    conv1 = cc.convert_leaf(s1)
    fcw = conv0 ^ conv1
    low = (
        (alphas & np.uint64(cc.LEAF_BITS - 1)).astype(np.int64)
        if log_n >= cc.LEAF_LOG
        else alphas.astype(np.int64)
    )
    fcw[np.arange(K), low >> 5] ^= (np.uint32(1) << (low & 31).astype(np.uint32))

    def mk(root, rt):
        return KeyBatchFast(log_n, root, rt, scw_all.copy(), tcw_all.copy(), fcw)

    return mk(root0, rt0), mk(root1, rt1)
