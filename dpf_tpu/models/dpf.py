"""TPU-native DPF evaluation: level-synchronous GGM expansion on bit-planes.

This is the inversion of the reference's hot path (dpf/dpf.go:213-262): where
the reference walks the GGM tree by sequential depth-first recursion — one
AES-NI call at a time — the TPU evaluator expands the tree *breadth-first*:
level ``i`` holds all ``2^i`` nodes of all ``K`` keys as one bitsliced tensor
``uint32[128, W, K/32]`` (128 bit-planes, W nodes, keys packed 32/word), and
one fused batch of vector ops per level does

    PRG doubling (2 fixed-key bitsliced AES-MMO)     reference dpf.go:229
    control-bit extraction + clearing (plane 0)      reference dpf.go:62-67
    correction-word XOR masked by parent t-bits      reference dpf.go:230-238

so ``nu = log_n - 7`` tensor steps replace ``2^nu`` recursive calls.  Keys
are data-parallel all the way through; within a 32-bit lane word the 32 keys
advance in lockstep.

Outputs are byte-identical to the reference: leaves emit in ascending index
order (children interleave L,R like the DFS emit order), each leaf is the
MMO-converted seed XOR the final CW when the control bit is set
(dpf.go:214-224), and the bit-packed output layout (bit x at byte x//8, bit
x%8) falls out of the plane layout for free.

Domains too large to materialize in one level (single-key n >= ~26) are
split at an intermediate level into independent subtrees — the GGM tree has
no cross-subtree dependence — and each chunk finishes under the same
compiled function.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bitpack
from ..core import knobs
from ..core.keys import KeyBatch
from ..ops import aes_pallas
from ..ops.aes_bitslice import (
    RK_MASKS_L,
    aes128_mmo_planes,
    pack_padded_keys,
    prg_planes,
    unpack_planes,
)

# PRG/convert kernel implementations.  "xla" = fused elementwise DAG left to
# the XLA fuser; "pallas" = explicit VMEM-tiled Mosaic kernels
# (ops/aes_pallas.py; interpreted off-TPU); "pallas_bm" = the same kernels
# with the level state held in BIT-MAJOR plane order across the whole
# expansion (S-box reads contiguous sublane blocks; permutes only at the
# pipeline boundaries).  Selected per call via the ``backend`` argument,
# defaulting to $DPF_TPU_PRG or the measured-fastest for the platform.
_PRG_IMPLS = {
    "xla": prg_planes,
    "pallas": aes_pallas.prg_planes_pallas,
    "pallas_bm": aes_pallas.prg_planes_pallas_bm,
    # experimental: interleaved double-encrypt, bit-major state
    "pallas_bm_il": aes_pallas.prg_planes_pallas_bm_il,
}
_MMO_IMPLS = {
    "xla": lambda S: aes128_mmo_planes(S, RK_MASKS_L),
    "pallas": aes_pallas.mmo_planes_pallas,
    # converts back to canonical plane order on output
    "pallas_bm": aes_pallas.mmo_planes_pallas_bm_canon,
    "pallas_bm_il": aes_pallas.mmo_planes_pallas_bm_canon,
}
# Backends whose level state lives in bit-major plane order (need the
# canonical->bm permute of seeds/CWs at the pipeline entry).
_BM_BACKENDS = frozenset({"pallas_bm", "pallas_bm_il"})


def default_backend() -> str:
    env = knobs.get_raw("DPF_TPU_PRG")
    if env:
        if env not in _PRG_IMPLS:
            raise ValueError(
                f"DPF_TPU_PRG={env!r} unknown; choose from {sorted(_PRG_IMPLS)}"
            )
        return env
    # Measured end-to-end on v5e at the headline config
    # (scripts/bench_compat_ab.py): pallas_bm 27.1 > pallas 23.5 > xla 4.8
    # Gleaves/s.  Off-TPU the kernels would run interpreted (slow), so
    # CPU/GPU default to XLA.
    return "pallas_bm" if jax.default_backend() == "tpu" else "xla"

# ---------------------------------------------------------------------------
# Host-side packing of key material into plane/mask form
# ---------------------------------------------------------------------------


def _pack_bits_over_keys(bits: np.ndarray) -> np.ndarray:
    """uint8[..., K] 0/1 -> uint32[..., K//32] packed words."""
    K = bits.shape[-1]
    b = bits.reshape(bits.shape[:-1] + (K // 32, 32)).astype(np.uint32)
    return (b << np.arange(32, dtype=np.uint32)).sum(-1, dtype=np.uint32)


def _pack_words_over_keys(words: np.ndarray) -> np.ndarray:
    """uint32[K, N, 4] block words -> planes uint32[128, N, K//32].

    Single source of truth for this layout is the device-side bit-matrix
    transpose in ``aes_bitslice.pack_padded_keys`` (whose absolute bit
    semantics are pinned in tests)."""
    # host-sync: one-time key packing at batch build (not a serving path)
    return np.asarray(pack_padded_keys(jnp.asarray(words)))


class DeviceKeys:
    """Key material packed for the device evaluator.

    K is zero-padded to a multiple of ``pad_to`` (>= 32, itself a multiple of
    32): 32 is the lane-packing quantum; sharded evaluation passes
    ``32 * n_shards`` so every shard gets whole lane words."""

    def __init__(self, kb: KeyBatch, pad_to: int = 32):
        if pad_to % 32:
            raise ValueError("pad_to must be a multiple of 32")
        self.log_n = kb.log_n
        self.nu = kb.nu
        self.k = kb.k
        pad = (-kb.k) % pad_to
        self.k_padded = kb.k + pad

        def padk(a):  # zero-pad the key axis
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

        seeds = padk(kb.seeds)
        ts = padk(kb.ts)
        scw = padk(kb.scw)
        tcw = padk(kb.tcw)
        fcw = padk(kb.fcw)

        self.seed_planes = jnp.asarray(_pack_words_over_keys(seeds[:, None, :]))
        self.t_words = jnp.asarray(_pack_bits_over_keys(ts & 1)[None, :])  # [1, Kp]
        if self.nu:
            # scw [K, nu, 4] packs with levels as the "node" axis, then moves
            # levels to the front: [nu, 128, Kp] so scw_planes[i] is level i.
            scw_packed = np.moveaxis(
                _pack_words_over_keys(np.ascontiguousarray(scw)), 1, 0
            ).copy()
            scw_packed[:, 0] = 0  # plane 0 (the t bit) of every sCW is 0 by Gen
            self.scw_planes = jnp.asarray(scw_packed)
            self.tl_words = jnp.asarray(
                _pack_bits_over_keys(np.moveaxis(tcw[:, :, 0] & 1, 0, 1))
            )  # [nu, Kp]
            self.tr_words = jnp.asarray(
                _pack_bits_over_keys(np.moveaxis(tcw[:, :, 1] & 1, 0, 1))
            )
        else:
            self.scw_planes = jnp.zeros((0, 128, self.k_padded // 32), jnp.uint32)
            self.tl_words = jnp.zeros((0, self.k_padded // 32), jnp.uint32)
            self.tr_words = jnp.zeros((0, self.k_padded // 32), jnp.uint32)
        self.fcw_planes = jnp.asarray(_pack_words_over_keys(fcw[:, None, :]))


# ---------------------------------------------------------------------------
# Jitted cores
# ---------------------------------------------------------------------------


def _level_step(S, T, cw_plane, tl_w, tr_w, backend="xla"):
    """One level of the expansion: [128, W, Kp] -> [128, 2W, Kp]."""
    W = S.shape[1]
    L, R = _PRG_IMPLS[backend](S.reshape(128, -1))
    L = L.reshape(128, W, -1)
    R = R.reshape(128, W, -1)
    tl, tr = L[0], R[0]
    zero = jnp.zeros_like(tl)
    L, R = L.at[0].set(zero), R.at[0].set(zero)
    cw = cw_plane[:, None, :]  # [128, 1, Kp]
    mask = T[None, :, :]  # parent control bits as lane masks
    L = L ^ (cw & mask)
    R = R ^ (cw & mask)
    tl = tl ^ (tl_w[None, :] & T)
    tr = tr ^ (tr_w[None, :] & T)
    S = jnp.stack([L, R], axis=2).reshape(128, 2 * W, -1)
    T = jnp.stack([tl, tr], axis=1).reshape(2 * W, -1)
    return S, T


def _convert_leaves(S, T, fcw_planes, backend="xla"):
    """Leaf conversion + final CW: -> per-key output words [K, W, 4]."""
    C = _MMO_IMPLS[backend](S.reshape(128, -1)).reshape(S.shape)
    C = C ^ (fcw_planes & T[None, :, :])
    return unpack_planes(C)


def _scw_to_bm(scw_planes):
    """Canonical -> bit-major plane order for the per-level CW planes.
    THE single source of truth for permuting host-packed CWs to the
    bit-major pipeline (used by the unchunked entry, the chunk loop, and
    the sharded evaluators)."""
    return scw_planes[:, jnp.asarray(aes_pallas._TO_BM)]


def _to_bm(seed_planes, scw_planes):
    """Canonical -> bit-major plane order for the level-state inputs.  Runs
    on the tiny pre-expansion tensors ([128, 1, Kp] seeds, [nu, 128, Kp]
    CWs); the big leaf-level tensors never pay a standalone permute (the
    leaf-convert kernel emits canonical order from inside VMEM)."""
    return seed_planes[jnp.asarray(aes_pallas._TO_BM)], _scw_to_bm(scw_planes)


@partial(jax.jit, static_argnums=(0, 7))
def _eval_full_jit(
    n_levels, seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes,
    backend="xla",
):
    if backend in _BM_BACKENDS:
        seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
    S, T = seed_planes, t_words
    for i in range(n_levels):
        S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
    return _convert_leaves(S, T, fcw_planes, backend)


# ---------------------------------------------------------------------------
# Level-fused expansion (DPF_TPU_FUSE; ops/aes_pallas fused kernel family)
# ---------------------------------------------------------------------------

# Entry level of the fused tail: 2^7 nodes fill the kernel's 128-lane node
# tile.  Levels above run the per-level pipeline (they are a vanishing
# fraction of the work — the last two levels alone hold 3/4 of all nodes).
_FUSE_FLOOR = 7


def _fuse_schedule(n_levels, g, floor=_FUSE_FLOOR):
    """(first_fused_level, group sizes) tiling levels floor..n_levels-1
    into fused groups of <= g levels, or None when nothing can fuse.
    ``floor`` is parameterized for tests (narrow-entry interpret runs)."""
    mid = n_levels - floor
    if g <= 0 or mid <= 0:
        return None
    groups = []
    while mid > 0:
        t = min(g, mid)
        groups.append(t)
        mid -= t
    return floor, tuple(groups)


def _fused_groups(S, T, scw_planes, tl_w, tr_w, first, groups):
    """Run the fused groups from per-level bit-major state at level
    ``first`` (S [128, W, Kp], T [W, Kp]) -> fused-layout (node-minor)
    leaf-level state (S_f [128, Kp, W'], T_f [Kp, W'])."""
    Sf = jnp.swapaxes(S, 1, 2)
    Tf = jnp.swapaxes(T, 0, 1)
    lvl = first
    for g in groups:
        wt = min(Tf.shape[1], aes_pallas._FWT)
        Sf, Tf = aes_pallas.fused_levels_planes(
            Sf, Tf, scw_planes[lvl : lvl + g], tl_w[lvl : lvl + g],
            tr_w[lvl : lvl + g],
        )
        Sf = aes_pallas.fused_deinterleave(Sf, g, wt)
        Tf = aes_pallas.fused_deinterleave(Tf, g, wt)
        lvl += g
    return Sf, Tf


def _convert_leaves_fused(Sf, Tf, fcw_planes, backend):
    """Leaf conversion + final CW from the fused layout: the MMO kernel is
    elementwise over lanes so it runs on the node-minor flattening
    directly; the final CW broadcast is per-key ([128, Kp, 1]); ONE
    combined transpose restores the canonical [128, W, Kp] layout for the
    bit-packed output contract."""
    C = _MMO_IMPLS[backend](Sf.reshape(128, -1)).reshape(Sf.shape)
    C = C ^ (jnp.swapaxes(fcw_planes, 1, 2) & Tf[None])
    return unpack_planes(jnp.swapaxes(C, 1, 2))


@partial(jax.jit, static_argnums=(0, 7, 8))
def _eval_full_fused_jit(
    n_levels, seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes,
    backend, schedule,
):
    """Fused-backend full expansion: per-level steps to the schedule's
    entry level, then G-level fused groups with all intermediate node
    planes VMEM-resident, then leaf conversion from the fused layout."""
    first, groups = schedule
    seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
    S, T = seed_planes, t_words
    for i in range(first):
        S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
    Sf, Tf = _fused_groups(S, T, scw_planes, tl_w, tr_w, first, groups)
    return _convert_leaves_fused(Sf, Tf, fcw_planes, backend)


# Sticky failure latch for the fused expansion (same pattern as the walk
# kernel's _WALK_KERNEL_BROKEN): a Mosaic rejection on some hardware
# degrades auto-routed callers to the per-level pipeline ONCE; an explicit
# DPF_TPU_FUSE=<g> (or a fuse= argument) re-raises so A/Bs and tests never
# silently measure the fallback.
_FUSE_BROKEN = False


def _fuse_degraded(e: Exception) -> None:
    global _FUSE_BROKEN
    import warnings

    from ..ops import fuse_forced

    if fuse_forced():
        raise e
    _FUSE_BROKEN = True
    warnings.warn(
        f"fused expansion unavailable, using the per-level path: {e}",
        RuntimeWarning,
        stacklevel=3,
    )


def _fuse_plan(nu: int, backend: str, fuse: int | None):
    """Production routing decision for the fused backend: the resolved
    schedule, or None for the per-level pipeline.  ``fuse``: None = env
    (DPF_TPU_FUSE, honoring the sticky latch), else an explicit group
    size (0 disables).  Fused state is bit-major — other backends keep
    the per-level path."""
    if backend not in _BM_BACKENDS:
        return None
    if fuse is None:
        from ..ops import fuse_forced, fuse_request

        if _FUSE_BROKEN and not fuse_forced():
            return None
        g = fuse_request(
            aes_pallas.fuse_auto_levels() if aes_pallas.available() else 0
        )
    else:
        g = fuse
    return _fuse_schedule(nu, g) if g > 0 else None


@partial(jax.jit, static_argnums=(0, 6))
def _expand_prefix_jit(
    n_levels, seed_planes, t_words, scw_planes, tl_w, tr_w, backend="xla"
):
    """NB: with a bit-major backend (_BM_BACKENDS) the returned S is in
    bit-major plane order — feed it only to _finish_chunk_jit with the same
    backend."""
    if backend in _BM_BACKENDS:
        seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
    S, T = seed_planes, t_words
    for i in range(n_levels):
        S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
    return S, T


def _finish_chunk_body(
    n_levels, first, S, T, scw_planes, tl_w, tr_w, fcw_planes, backend
):
    """S and scw_planes must already be in the backend's plane order (the
    chunk scan in eval_full_device permutes the CWs once for pallas_bm, not
    once per chunk)."""
    for i in range(n_levels):
        S, T = _level_step(
            S, T, scw_planes[first + i], tl_w[first + i], tr_w[first + i], backend
        )
    return _convert_leaves(S, T, fcw_planes, backend)


def _finish_chunks_scan_body(
    n_levels, first, S, T, scw_planes, tl_w, tr_w, fcw_planes, backend="xla"
):
    """Finish ALL 2^c subtree chunks in ONE compiled function.

    A Python chunk loop costs 2 dispatches per chunk (slice + finish);
    through a high-RTT device tunnel that dominates big-domain expansions
    (the round-3 review's 'dispatch storm').  ``lax.scan`` keeps the
    per-chunk memory profile — one [128, Wc, kp] working set per
    iteration, outputs accumulating in the stacked result buffer exactly
    like the old jnp.concatenate — while issuing a single program.

    S: [128, C, kp] prefix state, T: [C, kp] -> uint32[Kpad, C * Wc, 4].
    """
    Sx = jnp.moveaxis(S, 1, 0)[:, :, None, :]  # [C, 128, 1, kp]
    Tx = T[:, None, :]  # [C, 1, kp]

    def body(_, st):
        Sj, Tj = st
        return None, _finish_chunk_body(
            n_levels, first, Sj, Tj, scw_planes, tl_w, tr_w, fcw_planes,
            backend,
        )

    _, ys = jax.lax.scan(body, None, (Sx, Tx))  # [C, Kpad, Wc, 4]
    return jnp.moveaxis(ys, 0, 1).reshape(ys.shape[1], -1, ys.shape[3])


_finish_chunks_scan_jit = partial(jax.jit, static_argnums=(0, 1, 8))(
    _finish_chunks_scan_body
)
# The donation surface of this module: twin name -> (static_argnums,
# donate_argnums), mirroring the jit declarations below.  The
# perf-contract analysis pass (dpf_tpu/analysis/perf/) lowers each twin
# and verifies the declared buffers actually reach XLA donated and are
# never returned live — so this table and the literals below cannot
# drift apart silently.
DONATED_TWINS = {
    "_finish_chunks_scan_donated_jit": ((0, 1, 8), (2, 3)),
    "_finish_chunk_donated_jit": ((0, 1, 8), (2, 3)),
}
# Donated twin (the serving fast path, core/plans.donation_enabled): the
# prefix level-state carries (S, T) are dead once the finish consumes
# them, so XLA may reuse their buffers in place — steady-state chunked
# expansion allocates no fresh level-state HBM per call.
_finish_chunks_scan_donated_jit = partial(
    jax.jit, static_argnums=(0, 1, 8), donate_argnums=(2, 3)
)(_finish_chunks_scan_body)

# Single-chunk finish: the streaming pipeline's unit of dispatch (one
# subtree chunk per call, so finished chunks can start their D2H while
# the next chunk computes).
_finish_chunk_jit = partial(jax.jit, static_argnums=(0, 1, 8))(
    _finish_chunk_body
)
_finish_chunk_donated_jit = partial(
    jax.jit, static_argnums=(0, 1, 8), donate_argnums=(2, 3)
)(_finish_chunk_body)


# ---------------------------------------------------------------------------
# Incremental heavy-hitter frontier extension (apps/hh_state.py) — the
# compat-profile mirror of models/dpf_chacha's hh extend bodies; see the
# block comment there for the control-bit-invariant derivation.  State
# stays in the bitsliced plane layout ([128, F, Kp] seeds, [F, Kp]
# key-packed control words); the emitted rows transpose to the
# client-major packed contract on device.
# ---------------------------------------------------------------------------


def _keywords_to_rows(Tq):
    """Key-packed bit words uint32[Q, Kp] (key k at word k // 32, bit
    k % 32) -> client-major packed rows uint32[K, Q // 32] (the
    core/bitpack output contract)."""
    bits = (Tq[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(
        1
    )
    return bitpack.pack_bits_qmajor_jnp(bits.reshape(Tq.shape[0], -1))


def hh_leaf_fold_planes(C, m, ibits):
    """Fold converted leaf planes to depth-``m`` intra-leaf predicate
    bits.  C uint32[128, A, Kp] (plane x = leaf value bit x, key-packed);
    only planes < 2**ibits are populated (ibits = log_n - nu <= 7).
    Returns uint32[2**m, A, Kp]: entry v = XOR of planes
    [v * s, (v + 1) * s), s = 2**(ibits - m) — key-packing is orthogonal
    to the plane axis, so the fold is a plain XOR reduction."""
    s = (1 << ibits) >> m
    w = C[: 1 << ibits].reshape(1 << m, s, C.shape[1], C.shape[2])
    return jax.lax.reduce(w, np.uint32(0), jax.lax.bitwise_xor, (1,))


def _hh_extend_body(S, T, sel, cw_plane, tl_w, tr_w):
    """One incremental frontier level (compat): gather the surviving
    parent columns (public ``sel`` int32[F]) from the carried
    [128, 2F, Kp] / [2F, Kp] state and expand one level -> new state +
    client-major packed rows uint32[K, 2F // 32]."""
    Sg = jnp.take(S, sel, axis=1)
    Tg = jnp.take(T, sel, axis=0)
    S2, T2 = _level_step(Sg, Tg, cw_plane, tl_w, tr_w, "xla")
    return S2, T2, _keywords_to_rows(T2)


def _hh_leaf_first_body(ibits, S, T, sel, fcw_planes):
    """Frontier crossing into the leaf (compat): convert the surviving
    depth-nu columns once -> resident plane state uint32[128, F, Kp] +
    the m=1 split rows uint32[K, 2F // 32]."""
    Sg = jnp.take(S, sel, axis=1)
    Tg = jnp.take(T, sel, axis=0)
    C = _MMO_IMPLS["xla"](Sg.reshape(128, -1)).reshape(Sg.shape)
    C = C ^ (fcw_planes & Tg[None, :, :])
    B = hh_leaf_fold_planes(C, 1, ibits)  # [2, F, Kp]
    rows = _keywords_to_rows(
        jnp.moveaxis(B, 0, 1).reshape(-1, B.shape[2])
    )  # (parent, bit) order
    return C, rows


def _hh_leaf_fold_body(m, ibits, C, idx):
    """Intra-leaf frontier level m >= 2 (compat): fold the resident
    plane state (NOT donated — reused by deeper rounds) and gather the
    requested children (public ``idx`` int32[Q] = anc * 2**m + v)."""
    B = hh_leaf_fold_planes(C, m, ibits)
    flat = jnp.moveaxis(B, 0, 1).reshape(-1, B.shape[2])
    return _keywords_to_rows(jnp.take(flat, idx, axis=0))


_hh_extend_jit = jax.jit(_hh_extend_body)
_hh_extend_donated_jit = partial(jax.jit, donate_argnums=(0, 1))(
    _hh_extend_body
)
_hh_leaf_first_jit = partial(jax.jit, static_argnums=(0,))(
    _hh_leaf_first_body
)
_hh_leaf_first_donated_jit = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(1, 2)
)(_hh_leaf_first_body)
_hh_leaf_fold_jit = partial(jax.jit, static_argnums=(0, 1))(
    _hh_leaf_fold_body
)
DONATED_TWINS["_hh_extend_donated_jit"] = ((), (0, 1))
DONATED_TWINS["_hh_leaf_first_donated_jit"] = ((0,), (1, 2))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

# Soft cap on W * Kp (words per plane) for a single compiled expansion; above
# this the tree is split into independent subtree chunks.  2^19 words/plane
# -> the [128, W, Kp] tensor is 256 MB; a few live at once during a step.
MAX_PLANE_WORDS = 1 << 19


def eval_full_device(
    dk: DeviceKeys,
    max_plane_words: int = MAX_PLANE_WORDS,
    backend: str | None = None,
    fuse: int | None = None,
):
    """Full-domain evaluation on device -> uint32[K_padded, n_leaves, 4].

    The returned words ARE the bit-packed output: word q of leaf w holds
    domain bits [128*w + 32*q, 128*w + 32*q + 32), LSB-first.

    ``fuse``: level-fused expansion group size for the bit-major backends
    (None = DPF_TPU_FUSE, 0 = off, g >= 1 = groups of <= g levels).  The
    fused route covers the unchunked path; domains split into subtree
    chunks keep the per-level pipeline.  An explicit ``fuse`` re-raises
    kernel failures; env-auto routing degrades via the sticky latch.
    """
    backend = backend or default_backend()
    nu = dk.nu
    kp = dk.k_padded // 32
    total = (1 << nu) * kp
    if total <= max_plane_words:
        sched = _fuse_plan(nu, backend, fuse)
        if sched is not None:
            try:
                return _eval_full_fused_jit(
                    nu, dk.seed_planes, dk.t_words, dk.scw_planes,
                    dk.tl_words, dk.tr_words, dk.fcw_planes, backend, sched,
                )
            except Exception as e:  # noqa: BLE001
                if fuse is not None:
                    raise
                _fuse_degraded(e)
        return _eval_full_jit(
            nu, dk.seed_planes, dk.t_words, dk.scw_planes,
            dk.tl_words, dk.tr_words, dk.fcw_planes, backend,
        )
    # Chunked: expand a prefix of c levels, then finish each of the 2^c
    # independent subtrees under one compiled function.  Minimal split:
    # c = ceil(log2(ceil(total / max))).
    n_chunks = -(-total // max_plane_words)
    c = min((n_chunks - 1).bit_length(), nu)
    S, T = _expand_prefix_jit(
        c, dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words, dk.tr_words,
        backend,
    )
    scw = dk.scw_planes
    if backend in _BM_BACKENDS:
        # One permute for all chunks; S from the prefix is already bit-major.
        scw = _scw_to_bm(scw)
    from ..core import plans

    fin = (
        _finish_chunks_scan_donated_jit
        if plans.donation_enabled()
        else _finish_chunks_scan_jit
    )
    return fin(
        nu - c, c, S, T, scw, dk.tl_words, dk.tr_words, dk.fcw_planes, backend
    )


def eval_full(
    kb: KeyBatch,
    max_plane_words: int = MAX_PLANE_WORDS,
    backend: str | None = None,
    fuse: int | None = None,
) -> np.ndarray:
    """Full-domain evaluation of a key batch -> uint8[K, out_bytes], where
    out_bytes = 2^(log_n-3) (16 when log_n < 7), byte-identical to
    ``spec.eval_full`` / the reference's EvalFull per key."""
    dk = _cached_device_keys(kb)
    # host-sync: final reply marshalling (full-domain words)
    words = np.asarray(
        eval_full_device(dk, max_plane_words, backend, fuse)
    )  # [Kpad, W, 4]
    out = np.ascontiguousarray(words[: kb.k]).view("<u1").reshape(kb.k, -1)
    return out


def _words_to_rows(words: np.ndarray, k: int) -> np.ndarray:
    """[Kpad, W, 4] chunk words -> uint8[k, W*16] output-byte rows."""
    return np.ascontiguousarray(words[:k]).view("<u1").reshape(k, -1)


def _cached_device_keys(kb: KeyBatch) -> DeviceKeys:
    """Memoized default-padding DeviceKeys: key material is immutable once
    evaluated, and a serving batch re-sent across requests (the keycache
    hit path) must not repack + re-upload its bit-planes per call."""
    dk = kb._device_keys
    if dk is None:
        dk = DeviceKeys(kb)
        kb._device_keys = dk
    return dk


def eval_full_stream(
    kb: KeyBatch,
    max_plane_words: int = MAX_PLANE_WORDS,
    backend: str | None = None,
    min_chunks: int = 2,
    events: list | None = None,
    timer=None,
):
    """Double-buffered streaming full-domain evaluation.

    Yields uint8[K, chunk_bytes] blocks whose axis-1 concatenation is
    byte-identical to :func:`eval_full`.  The chunked-scan finish is
    split into one dispatch per subtree chunk: chunk ``j+1``'s compute
    is dispatched BEFORE chunk ``j``'s device->host copy completes
    (``copy_to_host_async``), so on hardware the D2H of finished chunks
    overlaps the next chunk's compute and a streaming consumer (the
    sidecar's /v1/evalfull) gets its first bytes after ~one chunk
    instead of the whole tree.  Domains that fit one compiled expansion
    still split into ``min_chunks`` chunks (nu permitting) — streaming
    with a single chunk would be the blocking path with extra steps.

    ``events`` / ``timer`` follow the shared driver's protocol
    (core/stream.stream_chunks — the modeled-overlap check and the
    "dispatch"/"d2h" phases).  Donation follows
    core/plans.donation_enabled (each chunk's level-state slice is dead
    after its finish)."""
    from ..core import plans
    from ..core.stream import chunk_levels, stream_chunks

    backend = backend or default_backend()
    dk = _cached_device_keys(kb)
    nu = dk.nu
    kp = dk.k_padded // 32
    c = chunk_levels((1 << nu) * kp, max_plane_words, min_chunks, nu)

    def to_rows(out):
        return _words_to_rows(out, kb.k)

    if c == 0:
        yield from stream_chunks(
            0, lambda j: eval_full_device(dk, max_plane_words, backend),
            to_rows, events, timer,
        )
        return

    S, T = _expand_prefix_jit(
        c, dk.seed_planes, dk.t_words, dk.scw_planes, dk.tl_words,
        dk.tr_words, backend,
    )
    scw = dk.scw_planes
    if backend in _BM_BACKENDS:
        scw = _scw_to_bm(scw)
    fin = (
        _finish_chunk_donated_jit
        if plans.donation_enabled()
        else _finish_chunk_jit
    )

    def dispatch(j):
        return fin(
            nu - c, c, S[:, j : j + 1, :], T[j : j + 1], scw,
            dk.tl_words, dk.tr_words, dk.fcw_planes, backend,
        )

    yield from stream_chunks(c, dispatch, to_rows, events, timer)


def _point_masks(kb: KeyBatch):
    """Per-key lane masks (0 / ~0) for the pointwise walk, broadcast over
    the query axis on device.  Built once per key batch and cached on it —
    key material is immutable once evaluated, and rebuilding + re-uploading
    ~(nu+2)*128*K*4 bytes of masks per call would dominate serving calls."""
    if kb._point_masks is not None:
        return kb._point_masks
    K, nu = kb.k, kb.nu

    def bits_of_words(words):  # uint32[K, 4] -> uint8[128, K]
        b = (words[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
        return np.moveaxis(b.reshape(K, 128), 0, 1).astype(np.uint8)

    m = np.uint32(0xFFFFFFFF)
    seed_masks = jnp.asarray(bits_of_words(kb.seeds) * m)  # [128, K]
    fcw_masks = jnp.asarray(bits_of_words(kb.fcw) * m)
    t_masks = jnp.asarray((kb.ts & 1).astype(np.uint32) * m)  # [K]
    if nu:
        scw_b = (kb.scw[:, :, :, None] >> np.arange(32, dtype=np.uint32)) & 1
        scw_masks = jnp.asarray(
            np.moveaxis(scw_b.reshape(K, nu, 128), 0, 2).astype(np.uint32) * m
        )  # [nu, 128, K]
        tl_masks = jnp.asarray(np.moveaxis(kb.tcw[:, :, 0] & 1, 0, 1).astype(np.uint32) * m)
        tr_masks = jnp.asarray(np.moveaxis(kb.tcw[:, :, 1] & 1, 0, 1).astype(np.uint32) * m)
    else:
        scw_masks = jnp.zeros((0, 128, K), jnp.uint32)
        tl_masks = jnp.zeros((0, K), jnp.uint32)
        tr_masks = jnp.zeros((0, K), jnp.uint32)
    kb._point_masks = (
        seed_masks, t_masks, scw_masks, tl_masks, tr_masks, fcw_masks
    )
    return kb._point_masks


def eval_points(
    kb: KeyBatch, xs: np.ndarray, backend: str | None = None,
    packed: bool = False,
) -> np.ndarray:
    """Batched pointwise evaluation: xs uint64[K, Q] -> bits uint8[K, Q].

    ``packed=True`` returns the evaluation's NATIVE bit-packed form
    instead: uint32[K, ceil(Q/32)] words, query q at word q//32 bit q%32
    (LSB-first; bits >= Q zero — core/bitpack.py).  The whole-walk kernel
    already computes exactly these words, so the packed route skips the
    unpack entirely and the D2H transfer shrinks 32x (8x on the wire);
    the byte-per-bit return is a thin unpack of the same words.

    One root-to-leaf path walk per (key, query) lane, all lanes in lockstep:
    per level both PRG children are computed bitsliced and the path bit
    selects per lane (reference Eval, dpf/dpf.go:171-211, vectorized).
    Key masks are device-cached across calls; the per-call upload is the
    query indices themselves (split into uint32 halves — the domain index
    can exceed 2^32), from which the per-level packed path words are built
    on device.  ``backend`` picks the PRG kernel exactly as in eval_full
    (default: the platform's measured-fastest).

    On TPU the whole walk runs as ONE Pallas program per (key, query-word)
    tile with the bitsliced state resident in VMEM
    (ops/aes_pallas._walk_kernel_bm; DPF_TPU_POINTS_AES=xla to disable) —
    the XLA body round-trips the [128, K, qp] state through HBM at every
    level."""
    xs = np.asarray(xs, dtype=np.uint64)
    K, Q = xs.shape
    if K != kb.k:
        raise ValueError("xs first axis must match key batch")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dpf: query index out of domain")
    backend = backend or default_backend()
    # The whole-walk kernel replaces the per-level pipeline for the
    # TPU-default (bit-major) backend family; an explicit backend="xla"
    # keeps the XLA body (A/B and differential reference) unless
    # DPF_TPU_POINTS_AES=pallas forces the kernel outright.
    # A latched failure disables the kernel for the DEFAULT routing only:
    # DPF_TPU_POINTS_AES=pallas (walk_forced) keeps attempting it and
    # re-raises on failure, so A/Bs and hardware validation never
    # silently measure the XLA fallback.
    if (
        (not _WALK_KERNEL_BROKEN or aes_pallas.walk_forced())
        and aes_pallas.walk_backend() == "pallas"
        and (backend in _BM_BACKENDS or aes_pallas.walk_forced())
    ):
        try:
            return _eval_points_walk_compat(kb, xs, packed=packed)
        except Exception as e:  # noqa: BLE001
            _walk_kernel_degraded(e)
    pad_q = (-Q) % 32
    if pad_q:
        xs = np.concatenate([xs, np.zeros((K, pad_q), np.uint64)], axis=1)
    qp = xs.shape[1] // 32

    xs_lo = jnp.asarray((xs & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if kb.log_n > 32:
        xs_hi = jnp.asarray((xs >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, 1), jnp.uint32)  # never read when log_n <= 32

    if packed:
        words = _eval_points_packed_jit(
            kb.nu, kb.log_n, *_point_masks(kb), xs_hi, xs_lo, qp, backend
        )
        # host-sync: final reply marshalling (packed words)
        return bitpack.mask_tail(np.asarray(words), Q)
    bits = _eval_points_jit(
        kb.nu, kb.log_n, *_point_masks(kb), xs_hi, xs_lo, qp, backend
    )
    return np.asarray(bits)[:, :Q]  # host-sync: final reply marshalling


# Sticky failure latch for the compat walk kernel: a Mosaic lowering
# failure on some hardware should degrade the serving path to the XLA
# body ONCE (recompiling a failing kernel on every call is not a
# fallback), never kill it.
_WALK_KERNEL_BROKEN = False


def _walk_kernel_degraded(e: Exception) -> None:
    """Latch a walk-kernel failure so callers fall back to the XLA route.
    Forced experiments (DPF_TPU_POINTS_AES=pallas) re-raise so A/Bs and
    tests never silently measure the fallback."""
    global _WALK_KERNEL_BROKEN
    import warnings

    if aes_pallas.walk_forced():
        raise e
    _WALK_KERNEL_BROKEN = True
    warnings.warn(
        f"compat walk kernel unavailable, using the XLA body: {e}",
        RuntimeWarning,
        stacklevel=3,
    )


def _eval_points_walk_compat(
    kb: KeyBatch, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Whole-walk kernel route: pads keys to the kernel's 8-key sublane
    tile and queries to whole packed words, returns uint8[K, Q] — or, with
    ``packed``, the kernel's packed words uint32[K, ceil(Q/32)] DIRECTLY
    (the kernel's native output; the unpacked return below is the thin
    host-side unpack of the same words)."""
    K, Q = xs.shape
    kpad = (-kb.k) % aes_pallas._PKT
    if kpad:
        from ..parallel.sharding import _pad_compat_batch

        kb = _pad_compat_batch(kb, kpad)
    pad_q = (-Q) % 32
    if pad_q:
        xs = np.concatenate(
            [xs, np.zeros((K, pad_q), np.uint64)], axis=1
        )
    if kpad:
        xs = np.concatenate(
            [xs, np.zeros((kpad, xs.shape[1]), np.uint64)], axis=0
        )
    qp = xs.shape[1] // 32
    xs_lo = jnp.asarray((xs & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if kb.log_n > 32:
        xs_hi = jnp.asarray((xs >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, 1), jnp.uint32)
    # host-sync: final reply marshalling (walk-kernel words)
    words = np.asarray(_eval_points_walk_jit(
        kb.nu, kb.log_n, *_point_masks(kb), xs_hi, xs_lo, qp
    ))  # [Kpad, qp]
    if packed:
        return bitpack.mask_tail(words[:K], Q)
    return bitpack.unpack_bits(words[:K], Q)


def _eval_points_walk_body(
    nu, log_n, seed_masks, t_masks, scw_masks, tl_masks, tr_masks,
    fcw_masks, xs_hi, xs_lo, qp,
):
    """Operand prep for the whole-walk kernel: per-level packed descent
    words and the leaf-select one-hot masks are built HERE (plain XLA, one
    pass over the query tensor) so the kernel itself is log_n-agnostic."""
    K = seed_masks.shape[1]
    lane = jnp.arange(32, dtype=jnp.uint32)

    def packw(pb):  # 0/1 uint32[K, Q] -> packed uint32[K, qp]
        return (pb.reshape(K, qp, 32) << lane).sum(-1, dtype=jnp.uint32)

    pws = []
    for i in range(nu):
        b = log_n - 1 - i
        if b >= 32:
            pb = (xs_hi >> np.uint32(b - 32)) & np.uint32(1)
        else:
            pb = (xs_lo >> np.uint32(b)) & np.uint32(1)
        pws.append(packw(pb))
    pw = (
        jnp.stack(pws) if nu else jnp.zeros((0, K, qp), jnp.uint32)
    )
    low = xs_lo & np.uint32(127)
    sel = jnp.stack(
        [packw((low == np.uint32(p)).astype(jnp.uint32)) for p in range(128)]
    )  # [128, K, qp]
    perm = jnp.asarray(aes_pallas._TO_BM)
    return aes_pallas.eval_points_walk_planes(
        seed_masks[perm], t_masks, scw_masks[:, perm], tl_masks, tr_masks,
        fcw_masks, pw, sel, nu,
    )


_eval_points_walk_jit = partial(jax.jit, static_argnums=(0, 1, 10))(
    _eval_points_walk_body
)


def _masked_level_queries(
    xs: np.ndarray, log_n: int, levels, groups: int
) -> np.ndarray:
    """uint64[G, Q] raw queries -> uint64[groups * len(levels) * G, Q]:
    per selected level i, x with its low ``log_n - 1 - i`` bits zeroed
    (the dyadic-prefix query), level-major — the host-expansion twin of
    the device-side masking, shared by both profiles' ``levels=`` grouped
    paths (apps/heavy_hitters.py evaluates one level block per round)."""
    lv = np.asarray(levels, dtype=np.uint64)
    shifts = (np.uint64(log_n) - np.uint64(1) - lv)[:, None, None]
    qexp = ((xs[None] >> shifts) << shifts).reshape(
        lv.shape[0] * xs.shape[0], -1
    )
    if groups > 1:
        qexp = np.concatenate([qexp] * groups)
    return qexp


def eval_points_level_grouped(
    kb: KeyBatch, xs: np.ndarray, groups: int, reduce: bool = False,
    backend: str | None = None, packed: bool = False, levels=None,
) -> np.ndarray:
    """FSS-support pointwise evaluation over level-major key groups
    (compat profile; mirror of dpf_chacha.eval_points_level_grouped).

    ``kb`` holds ``groups * log_n * G`` keys arranged as ``groups``
    repeats of ``log_n`` level-major blocks of ``G`` gates (models/fss.py
    layout); ``xs`` is the RAW gate queries uint64[G, Q].  Key ``i*G + g``
    of each group is evaluated at xs[g] with its low ``log_n - 1 - i``
    bits zeroed (the dyadic-prefix query).  On TPU the masking folds into
    the whole-walk kernel's operand prep ON DEVICE — neither the host nor
    the wire sees the level-replicated query tensor; otherwise the masked
    queries are expanded host-side and walked by the XLA body.
    -> uint8[groups * log_n * G, Q], or uint8[G, Q] with ``reduce`` (the
    level/group XOR-fold happens on device on the kernel route).
    ``packed`` returns the same rows as uint32[., ceil(Q/32)] packed words
    (the kernel's native form — no unpack, 32x less D2H; bitpack.py).

    ``levels`` (optional tuple of level indices in [0, log_n)) selects a
    SUBSET of level blocks: ``kb`` then holds ``groups * len(levels) * G``
    keys whose block ``j`` is level ``levels[j]``, and block ``j``'s
    queries mask to that level's dyadic prefix.  The per-round eval of
    the heavy-hitters descent (apps/heavy_hitters.py) is this call with
    a single level: the round's candidate prefixes go in raw and the
    masking pins them to the round's depth.  The subset path masks the
    queries host-side and walks them through :func:`eval_points` (the
    same certified walk bodies; the query tensor is [len(levels)*G, Q],
    not log_n-replicated)."""
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2:
        raise ValueError("dpf: xs must be [G, Q]")
    G, Q = xs.shape
    n = kb.log_n
    if levels is not None:
        lv = tuple(int(i) for i in levels)
        if not lv or any(i < 0 or i >= n for i in lv):
            raise ValueError("dpf: levels must be non-empty, in [0, log_n)")
        if kb.k != groups * len(lv) * G:
            raise ValueError("dpf: key count != groups * len(levels) * G")
        if (xs >> np.uint64(n)).any():
            raise ValueError("dpf: query index out of domain")
        out = eval_points(
            kb, _masked_level_queries(xs, n, lv, groups),
            backend=backend, packed=packed,
        )
        if reduce:
            out = np.bitwise_xor.reduce(
                out.reshape(groups * len(lv), G, -1), axis=0
            )
        return out
    if kb.k != groups * n * G:
        raise ValueError("dpf: key count != groups * log_n * G")
    if (xs >> np.uint64(n)).any():
        raise ValueError("dpf: query index out of domain")
    backend = backend or default_backend()
    use_walk = (
        (not _WALK_KERNEL_BROKEN or aes_pallas.walk_forced())
        and aes_pallas.walk_backend() == "pallas"
        and (backend in _BM_BACKENDS or aes_pallas.walk_forced())
        and kb.k % aes_pallas._PKT == 0
    )
    if not use_walk:
        shifts = (
            np.uint64(n) - np.uint64(1)
            - np.arange(n, dtype=np.uint64)
        )[:, None, None]
        qexp = ((xs[None] >> shifts) << shifts).reshape(n * G, Q)
        if groups > 1:
            qexp = np.concatenate([qexp] * groups)
        bits = eval_points(kb, qexp, backend=backend)
        if reduce:
            bits = np.bitwise_xor.reduce(
                bits.reshape(groups * n, G, Q), axis=0
            )
        return bitpack.pack_bits(bits) if packed else bits
    pad_q = (-Q) % 32
    if pad_q:
        xs = np.concatenate([xs, np.zeros((G, pad_q), np.uint64)], axis=1)
    qp = xs.shape[1] // 32
    xs_lo = jnp.asarray((xs & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if n > 32:
        xs_hi = jnp.asarray((xs >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, 1), jnp.uint32)
    try:
        # host-sync: final reply marshalling (grouped walk words)
        words = np.asarray(_grouped_walk_jit(
            kb.nu, n, groups, G, *_point_masks(kb), xs_hi, xs_lo, qp, reduce
        ))
    except Exception as e:  # noqa: BLE001
        _walk_kernel_degraded(e)
        return eval_points_level_grouped(
            kb, xs[:, :Q], groups, reduce, backend, packed
        )
    if packed:
        return bitpack.mask_tail(words, Q)
    return bitpack.unpack_bits(words, Q)


def _grouped_walk_body(
    nu, log_n, groups, G, seed_masks, t_masks, scw_masks, tl_masks,
    tr_masks, fcw_masks, xs_hi, xs_lo, qp, reduce,
):
    """Kernel-route prep for level-grouped gates: per-block descent words
    are the raw path bits ANDed with the static ``walk level <= block
    level`` keep matrix, and the leaf-select masks use each block's
    statically masked low bits — the dyadic-prefix replication never
    materializes as query uploads."""
    n = log_n
    B = groups * n
    K = B * G
    lane = jnp.arange(32, dtype=jnp.uint32)

    def packw(pb, k):
        return (pb.reshape(k, qp, 32) << lane).sum(-1, dtype=jnp.uint32)

    pws = []
    for j in range(nu):
        b = n - 1 - j
        if b >= 32:
            pb = (xs_hi >> np.uint32(b - 32)) & np.uint32(1)
        else:
            pb = (xs_lo >> np.uint32(b)) & np.uint32(1)
        pw_raw = packw(pb, G)[None]  # [1, G, qp]
        keep = np.array(
            [1 if j <= (bi % n) else 0 for bi in range(B)], np.uint32
        )
        pws.append((pw_raw * keep[:, None, None]).reshape(K, qp))
    pw = jnp.stack(pws) if nu else jnp.zeros((0, K, qp), jnp.uint32)
    lowmask = np.array(
        [(~((1 << max(0, n - 1 - (bi % n))) - 1)) & 127 for bi in range(B)],
        np.uint32,
    )
    low_b = (xs_lo & np.uint32(127))[None] & lowmask[:, None, None]
    low_k = low_b.reshape(K, -1)
    sel = jnp.stack(
        [packw((low_k == np.uint32(p)).astype(jnp.uint32), K)
         for p in range(128)]
    )
    perm = jnp.asarray(aes_pallas._TO_BM)
    packed = aes_pallas.eval_points_walk_planes(
        seed_masks[perm], t_masks, scw_masks[:, perm], tl_masks, tr_masks,
        fcw_masks, pw, sel, nu,
    )  # [K, qp]
    if reduce:
        packed = jax.lax.reduce(
            packed.reshape(B, G, qp), np.uint32(0), jax.lax.bitwise_xor, (0,)
        )
    return packed


_grouped_walk_jit = partial(jax.jit, static_argnums=(0, 1, 2, 3, 12, 13))(
    _grouped_walk_body
)


def _eval_points_body(
    nu, log_n, seed_masks, t_masks, scw_masks, tl_masks, tr_masks,
    fcw_masks, xs_hi, xs_lo, qp, backend="xla",
):
    """Traceable core of the pointwise walk (shared by the single-chip jit
    and the shard_map'd evaluator in parallel/sharding.py).  The per-level
    PRG and the leaf convert go through the same kernel table as eval_full;
    with a bit-major backend the level state is held in bit-major plane
    order for the whole walk (plane 0 — the control-bit plane — is index 0
    in both orders, and the path-bit select is plane-order-agnostic), with
    the mask permutes done once on the small per-key tensors."""
    K = seed_masks.shape[1]
    lane = jnp.arange(32, dtype=jnp.uint32)

    def path_words(i):
        """Packed path-bit lane masks for level i: uint32[K, qp] where word
        w packs queries [32w, 32w+32)'s descent bits (LSB-first)."""
        b = log_n - 1 - i  # static per level
        if b >= 32:
            pb = (xs_hi >> np.uint32(b - 32)) & np.uint32(1)
        else:
            pb = (xs_lo >> np.uint32(b)) & np.uint32(1)
        return (pb.reshape(K, qp, 32) << lane).sum(-1, dtype=jnp.uint32)

    if backend in _BM_BACKENDS:
        perm = jnp.asarray(aes_pallas._TO_BM)
        seed_masks = seed_masks[perm]
        scw_masks = scw_masks[:, perm]
    S = jnp.broadcast_to(seed_masks[:, :, None], (128, K, qp))
    T = jnp.broadcast_to(t_masks[None, :, None], (1, K, qp)).reshape(K, qp)
    for i in range(nu):
        L, R = _PRG_IMPLS[backend](S.reshape(128, -1))
        L = L.reshape(128, K, qp)
        R = R.reshape(128, K, qp)
        tl, tr = L[0], R[0]
        zero = jnp.zeros_like(tl)
        L, R = L.at[0].set(zero), R.at[0].set(zero)
        cw = scw_masks[i][:, :, None] & T[None, :, :]
        L = L ^ cw
        R = R ^ cw
        tl = tl ^ (tl_masks[i][:, None] & T)
        tr = tr ^ (tr_masks[i][:, None] & T)
        go_r = path_words(i)  # [K, qp]
        S = (R & go_r) | (L & ~go_r)
        T = (tr & go_r) | (tl & ~go_r)
    # leaf convert emits CANONICAL plane order from any backend
    C = _MMO_IMPLS[backend](S.reshape(128, -1)).reshape(128, K, qp)
    C = C ^ (fcw_masks[:, :, None] & T[None, :, :])
    words = unpack_planes(C.reshape(128, 1, K * qp))  # [K*Q, 1, 4]
    words = words.reshape(K, qp * 32, 4)
    low = xs_lo & np.uint32(127)  # index within the 128-bit leaf
    qsel = ((low >> 5) & 3).astype(jnp.int32)  # which 32-bit word of the leaf
    w = jnp.take_along_axis(words, qsel[:, :, None], axis=2)[:, :, 0]
    return ((w >> (low & 31)) & 1).astype(jnp.uint8)


_eval_points_jit = partial(jax.jit, static_argnums=(0, 1, 10, 11))(
    _eval_points_body
)


def _eval_points_packed_body(
    nu, log_n, seed_masks, t_masks, scw_masks, tl_masks, tr_masks,
    fcw_masks, xs_hi, xs_lo, qp, backend="xla",
):
    """Packed twin of the XLA walk body: the per-query bits pack into
    uint32 words ON DEVICE (core/bitpack), so the D2H transfer is the
    packed words — same 32x cut the walk kernel's native output gets."""
    bits = _eval_points_body(
        nu, log_n, seed_masks, t_masks, scw_masks, tl_masks, tr_masks,
        fcw_masks, xs_hi, xs_lo, qp, backend,
    )
    return bitpack.pack_bits_jnp(bits)


_eval_points_packed_jit = partial(jax.jit, static_argnums=(0, 1, 10, 11))(
    _eval_points_packed_body
)
