"""2-server private information retrieval on top of batched DPF expansion.

Protocol (classic 2-server PIR, the headline application of DPFs — the
reference implements only the primitive, SURVEY §0): the client hides row
index ``alpha`` in a DPF key pair; each server expands its share over the
row domain and XORs together the database rows whose selection bit is 1;
the client XORs the two 1-row answers to recover row ``alpha``.

TPU mapping: the XOR-of-selected-rows is GF(2) linear algebra —
``answer = sel_bits[K, N] @ db_bits[N, B] (mod 2)`` — so it runs on the
**MXU** as an int8 matmul with int32 accumulation and a final parity bit,
chunked over rows so only row-chunks are ever unpacked to bits.  The
selection bits come straight from the level-synchronous DPF expansion
(models/dpf.py) without leaving HBM.

Multi-chip: database rows shard over the ``leaf`` mesh axis — each chip
expands only the GGM subtree covering its own rows (zero-communication
domain parallelism) — and the K queries shard over the ``keys`` axis.  The
only collective is one parity all-reduce of the [K, row_bytes] partial
answers over ICI (parallel/sharding.xor_allreduce).
"""

from __future__ import annotations

from functools import cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.keys import KeyBatch, gen_batch
from ..parallel.sharding import (
    KEYS_AXIS,
    LEAF_AXIS,
    expand_subtree_local,
    leaf_axis_levels,
    shard_map_compat,
    xor_allreduce,
)
from .dpf import (
    _BM_BACKENDS,
    DeviceKeys,
    _convert_leaves,
    _convert_leaves_fused,
    _fuse_plan,
    _fused_groups,
    _level_step,
    _to_bm,
    default_backend,
)

# Leaf width (log2 bits) per profile: compat = one AES block (reference
# dpf/dpf.go:251), fast = one ChaCha block (core/chacha_np.LEAF_LOG).
_LEAF_LOG = {"compat": 7, "fast": 9}


def row_domain(n_rows: int, profile: str = "compat") -> tuple[int, int]:
    """(log_n, padded domain size) for an ``n_rows``-row database.  Client
    and server must derive the domain identically — single source of truth."""
    log_n = max(int(n_rows - 1).bit_length(), 3)
    return log_n, 1 << max(log_n, _LEAF_LOG[profile])


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


def pir_query(
    indices: np.ndarray | list[int],
    n_rows: int,
    rng: np.random.Generator | None = None,
    profile: str = "compat",
):
    """Build the two servers' query key batches for a batch of row indices.

    ``profile="fast"`` uses the ChaCha profile (keys_chacha) — server and
    client must agree on the profile."""
    log_n, _ = row_domain(n_rows, profile)
    indices = np.asarray(indices, dtype=np.uint64)
    if (indices >= n_rows).any():
        raise ValueError("pir: row index out of range")
    if profile == "fast":
        from .keys_chacha import gen_batch as gen_fast

        return gen_fast(indices, log_n, rng=rng)
    return gen_batch(indices, log_n, rng=rng)


def pir_reconstruct(ans_a: np.ndarray, ans_b: np.ndarray) -> np.ndarray:
    """XOR the two servers' answers -> the requested rows [K, row_bytes]."""
    return np.bitwise_xor(ans_a, ans_b)


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


class PirServer:
    """One server's database, packed on device.

    ``db``: uint8[N, row_bytes]; both servers hold identical copies.
    ``mesh``: optional (keys, leaf) mesh; rows shard over ``leaf``.
    ``chunk_rows``: rows per parity-matmul chunk (int8 unpack granularity).
    """

    def __init__(
        self,
        db: np.ndarray,
        mesh: Mesh | None = None,
        chunk_rows: int = 1 << 16,
        profile: str = "compat",
    ):
        if profile not in _LEAF_LOG:
            raise ValueError(f"pir: unknown profile {profile!r}")
        db = np.ascontiguousarray(np.asarray(db, dtype=np.uint8))
        if db.ndim != 2:
            raise ValueError("db must be [n_rows, row_bytes]")
        self.profile = profile
        self.n_rows, self.row_bytes = db.shape
        if self.row_bytes % 4:
            raise ValueError("row_bytes must be a multiple of 4")
        self.log_n, dom = row_domain(self.n_rows, profile)
        self.nu = max(self.log_n - _LEAF_LOG[profile], 0)
        self.mesh = mesh
        self.n_leaf = mesh.shape.get(LEAF_AXIS, 1) if mesh else 1
        if mesh is not None:
            self.subtree_levels = leaf_axis_levels(mesh, self.nu, self.log_n)
        else:
            self.subtree_levels = 0
        # Pad the row count to a full leaf domain so selection words line up
        # 1:1 with expansion output words (and to whole shards/chunks).
        self.dom = dom
        self.chunk_rows = min(chunk_rows, max(dom // self.n_leaf, 128))
        if dom % (self.n_leaf * self.chunk_rows):
            raise ValueError("chunk_rows must divide the per-shard domain")
        padded = np.zeros((dom, self.row_bytes), np.uint8)
        padded[: self.n_rows] = db
        self.db_words = jnp.asarray(
            np.ascontiguousarray(padded).view("<u4")
        )  # [dom, row_bytes/4]

    def answer(self, queries) -> np.ndarray:
        """-> uint8[K, row_bytes]: per-query XOR of selected rows.

        ``queries``: KeyBatch (compat profile) or KeyBatchFast (fast)."""
        from .keys_chacha import KeyBatchFast

        want_fast = self.profile == "fast"
        if isinstance(queries, KeyBatchFast) != want_fast:
            raise ValueError(
                f"pir: {type(queries).__name__} queries sent to a "
                f"{self.profile!r}-profile server; client and server must "
                "agree on the profile"
            )
        if queries.log_n != self.log_n:
            raise ValueError(
                f"pir: query domain 2^{queries.log_n} != db domain 2^{self.log_n}"
            )
        n_chunks = self.dom // (self.n_leaf * self.chunk_rows)
        if self.profile == "fast":
            return self._answer_fast(queries, n_chunks)
        if self.mesh is None:
            k_shards = 1
        else:
            k_shards = self.mesh.shape[KEYS_AXIS]
        dk = DeviceKeys(queries, pad_to=32 * k_shards)
        backend = default_backend()
        args = (
            dk.seed_planes, dk.t_words, dk.scw_planes,
            dk.tl_words, dk.tr_words, dk.fcw_planes, self.db_words,
        )
        words = None
        if self.mesh is None:
            # Single-chip expansion follows the production fused routing
            # (DPF_TPU_FUSE); the sharded path keeps per-level steps (its
            # subtree split already changes the level schedule).
            sched = _fuse_plan(dk.nu, backend, None)
            if sched is not None:
                from . import dpf as _mdpf

                try:
                    # host-sync: final reply marshalling (PIR answer rows)
                    words = np.asarray(
                        _pir_single(
                            dk.nu, self.chunk_rows, n_chunks, backend, sched
                        )(*args)
                    )
                except Exception as e:  # noqa: BLE001
                    _mdpf._fuse_degraded(e)
            if words is None:
                # host-sync: final reply marshalling (PIR answer rows)
                words = np.asarray(
                    _pir_single(dk.nu, self.chunk_rows, n_chunks, backend)(
                        *args
                    )
                )
        else:
            fn = _pir_sharded(
                self.mesh, dk.nu, self.subtree_levels, self.chunk_rows,
                n_chunks, backend,
            )
            # host-sync: final reply marshalling (PIR answer rows)
            words = np.asarray(fn(*args))  # [Kpad, row_words]
        return (
            np.ascontiguousarray(words[: queries.k])
            .view("<u1")
            .reshape(queries.k, -1)
        )

    def _answer_fast(self, queries, n_chunks: int) -> np.ndarray:
        from .keys_chacha import KeyBatchFast

        if self.mesh is None:
            k_shards, pad = 1, 0
        else:
            from ..parallel.sharding import _fast_pad_quantum

            k_shards = self.mesh.shape[KEYS_AXIS]
            pad = (-queries.k) % _fast_pad_quantum(
                self.mesh, self.nu, self.subtree_levels
            )

        def padk(a):
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

        padded = KeyBatchFast(
            queries.log_n, padk(queries.seeds), padk(queries.ts),
            padk(queries.scw), padk(queries.tcw), padk(queries.fcw),
        )
        if self.mesh is None:
            fn = _pir_single_fast(
                self.nu, self.chunk_rows, n_chunks,
                _pir_fast_entry_level(self.nu, padded.k),
            )
        else:
            from ..parallel.sharding import _sharded_fast_entry_level

            fn = _pir_sharded_fast(
                self.mesh, self.nu, self.subtree_levels, self.chunk_rows,
                n_chunks,
                _sharded_fast_entry_level(
                    self.nu, self.subtree_levels, padded.k // k_shards
                ),
            )
        # host-sync: final reply marshalling (PIR answer rows)
        words = np.asarray(fn(*padded.device_args(), self.db_words))
        return (
            np.ascontiguousarray(words[: queries.k])
            .view("<u1")
            .reshape(queries.k, -1)
        )


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _unpack_bits_i8(words: jax.Array) -> jax.Array:
    """uint32[M, W] -> int8[M, 32*W] bits, LSB-first per word.  Used for
    both the selection rows and the db rows of the parity matmul — the
    ONLY place the packed pipeline widens to bytes, and only chunk-local
    inside the MXU kernel (int8 is the matmul's input type); everywhere
    else selection vectors stay packed uint32 words
    (core/bitpack contract)."""
    m = words.shape[0]
    b = (words[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    return b.reshape(m, -1).astype(jnp.int8)


def _pack_bits_u32(bits: jax.Array) -> jax.Array:
    """int32[..., 32*R] 0/1 -> uint32[..., R] (core/bitpack.pack_bits_jnp
    — the shared packed-word contract)."""
    from ..core import bitpack

    return bitpack.pack_bits_jnp(bits)


def _parity_matmul(sel_words, db_words, chunk_rows, n_chunks):
    """GF(2) product sel[K, N] x db[N, bits] via chunked int8 MXU matmuls.

    sel_words uint32[K, N/32], db_words uint32[N, R] -> uint32[K, R].
    """
    K = sel_words.shape[0]
    R = db_words.shape[1]
    cw = chunk_rows // 32

    def step(acc, i):
        sel = _unpack_bits_i8(
            jax.lax.dynamic_slice_in_dim(sel_words, i * cw, cw, axis=1)
        )  # int8[K, chunk]
        dbb = _unpack_bits_i8(
            jax.lax.dynamic_slice_in_dim(db_words, i * chunk_rows, chunk_rows)
        )  # int8[chunk, 32R]
        part = jnp.matmul(sel, dbb, preferred_element_type=jnp.int32)
        return acc ^ (part & 1), None

    acc0 = jnp.zeros((K, 32 * R), jnp.int32)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(n_chunks))
    return _pack_bits_u32(acc)


def _leaves_to_sel_words(words: jax.Array) -> jax.Array:
    """Expansion output uint32[K, W, 4] -> selection words uint32[K, W*4]
    in ascending row order (row 128*w + 32*q + bit, LSB-first)."""
    return words.reshape(words.shape[0], -1)


@cache
def _pir_single(
    nu: int, chunk_rows: int, n_chunks: int, backend: str = "xla",
    fuse_sched=None,
):
    """Single-chip PIR pipeline.  ``fuse_sched`` (models/dpf._fuse_plan
    output) routes the deep levels through the level-fused VMEM kernels —
    the selection words then come off the fused-layout leaf convert, same
    bytes, ~G x less HBM traffic on the expansion that feeds the parity
    matmul."""

    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes, db_words):
        if backend in _BM_BACKENDS:
            seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
        S, T = seed_planes, t_words
        if fuse_sched is not None:
            first, groups = fuse_sched
            for i in range(first):
                S, T = _level_step(
                    S, T, scw_planes[i], tl_w[i], tr_w[i], backend
                )
            Sf, Tf = _fused_groups(S, T, scw_planes, tl_w, tr_w, first, groups)
            leaves = _convert_leaves_fused(Sf, Tf, fcw_planes, backend)
        else:
            for i in range(nu):
                S, T = _level_step(
                    S, T, scw_planes[i], tl_w[i], tr_w[i], backend
                )
            leaves = _convert_leaves(S, T, fcw_planes, backend)
        sel = _leaves_to_sel_words(leaves)
        return _parity_matmul(sel, db_words, chunk_rows, n_chunks)

    return jax.jit(body)


def _fast_expand_sel(nu, entry, seeds, ts, scw, tcw, fcw):
    """Traceable fast-profile expansion -> selection words uint32[K, W*16]
    in ascending row order.  ``entry >= 0`` routes levels entry..nu-1 plus
    leaf conversion through the VMEM expand kernel (models/dpf_chacha
    _finish_pk; the kernel's lane-padded CW operands are built in-graph —
    a few tiny pad ops against ~GBs of leaf words); entry < 0 is the pure
    XLA pipeline."""
    from .dpf_chacha import _convert_leaves_cc, _finish_pk, _level_step_cc

    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(entry if entry >= 0 else nu):
        S, T = _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )
    if entry < 0:
        leaves = _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        return leaves.reshape(leaves.shape[0], -1)
    from ..ops.chacha_pallas import cw_operands

    K = seeds.shape[0]
    words = _finish_pk(nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu))
    return words.reshape(K, -1)


def _pir_fast_entry_level(nu: int, k: int) -> int:
    """Expand-kernel entry level for the PIR pipeline, or -1 for XLA."""
    from ..ops import chacha_pallas as cp

    if cp.expand_backend() != "pallas" or not cp.kernel_usable(nu, k):
        return -1
    return cp.entry_level(nu)


@cache
def _pir_single_fast(nu: int, chunk_rows: int, n_chunks: int, entry: int = -1):
    def body(seeds, ts, scw, tcw, fcw, db_words):
        sel = _fast_expand_sel(nu, entry, seeds, ts, scw, tcw, fcw)
        return _parity_matmul(sel, db_words, chunk_rows, n_chunks)

    return jax.jit(body)


@cache
def _pir_sharded_fast(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    entry: int = -1,
):
    from ..parallel.sharding import expand_subtree_local_cc
    from .dpf_chacha import _convert_leaves_cc, _finish_pk

    def body(seeds, ts, scw, tcw, fcw, db_words):
        if entry < 0:
            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, nu, subtree_levels
            )
            leaves = _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        else:  # VMEM expand kernel per shard (same route as eval_full)
            from ..ops.chacha_pallas import cw_operands

            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, entry, subtree_levels
            )
            leaves = _finish_pk(
                nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu)
            )
        sel = leaves.reshape(leaves.shape[0], -1)
        part = _parity_matmul(sel, db_words, chunk_rows, n_chunks)
        return xor_allreduce(part, LEAF_AXIS)

    return jax.jit(
        shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(
                P(KEYS_AXIS, None), P(KEYS_AXIS), P(KEYS_AXIS, None, None),
                P(KEYS_AXIS, None, None), P(KEYS_AXIS, None), P(LEAF_AXIS, None),
            ),
            out_specs=P(KEYS_AXIS, None),
            check_vma=False,
        )
    )


@cache
def _pir_sharded(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    backend: str = "xla",
):
    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes, db_words):
        S, T = expand_subtree_local(
            seed_planes, t_words, scw_planes, tl_w, tr_w, nu, subtree_levels,
            backend,
        )
        sel = _leaves_to_sel_words(_convert_leaves(S, T, fcw_planes, backend))
        part = _parity_matmul(sel, db_words, chunk_rows, n_chunks)
        return xor_allreduce(part, LEAF_AXIS)

    keyed = P(None, None, KEYS_AXIS)
    return jax.jit(
        shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(
                keyed, P(None, KEYS_AXIS), keyed, P(None, KEYS_AXIS),
                P(None, KEYS_AXIS), keyed, P(LEAF_AXIS, None),
            ),
            out_specs=P(KEYS_AXIS, None),
            check_vma=False,
        )
    )
