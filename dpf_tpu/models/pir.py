"""2-server private information retrieval on top of batched DPF expansion.

Protocol (classic 2-server PIR, the headline application of DPFs — the
reference implements only the primitive, SURVEY §0): the client hides row
index ``alpha`` in a DPF key pair; each server expands its share over the
row domain and XORs together the database rows whose selection bit is 1;
the client XORs the two 1-row answers to recover row ``alpha``.

TPU mapping: the XOR-of-selected-rows is GF(2) linear algebra —
``answer = sel_bits[K, N] @ db_bits[N, B] (mod 2)`` — so it runs on the
**MXU** as an int8 matmul with int32 accumulation and a final parity bit,
chunked over rows so only row-chunks are ever unpacked to bits.  The
selection bits come straight from the level-synchronous DPF expansion
(models/dpf.py) without leaving HBM.

Multi-chip: database rows shard over the ``leaf`` mesh axis — each chip
expands only the GGM subtree covering its own rows (zero-communication
domain parallelism) — and the K queries shard over the ``keys`` axis.  The
only collective is one parity all-reduce of the [K, row_bytes] partial
answers over ICI (parallel/sharding.xor_allreduce).

Production database sizes: a multi-GB database is bigger than a
comfortable single dispatch, so above ``DPF_TPU_PIR_DB_CHUNK_BYTES`` of
per-shard resident bytes the scan runs as a **streamed chunk scan**: the
selection vectors are expanded ONCE (one dispatch), then the parity
matmul is split into per-chunk dispatches over the HBM-resident database
— chunk j+1's dispatch is issued while chunk j computes (the async-
dispatch twin of core/stream.py's double buffering; nothing crosses back
to host mid-scan), each chunk XORs into a device-carried accumulator
whose buffer is donated (``DPF_TPU_DONATE``), and under a mesh the
per-shard partials meet in exactly ONE parity all-reduce per query
batch, after the last chunk.  The answer bytes are identical to the
one-shot scan's — pinned by tests/test_pir_serving.py.  The schedule
claims are performance contracts (docs/PERF_CONTRACTS.md, DESIGN §16):
zero collectives per streamed chunk, one all-reduce per query batch,
the accumulator donation surviving into the lowering, and the chunk
index a traced operand (one executable for every chunk) are verified
statically by the perf-contract lint pass, not just by these tests.
"""

from __future__ import annotations

from functools import cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core import knobs
from ..core.keys import KeyBatch, gen_batch
from ..parallel.sharding import (
    KEYS_AXIS,
    LEAF_AXIS,
    _ShardedJits,
    expand_subtree_local,
    leaf_axis_levels,
    shard_map_compat,
    xor_allreduce,
)
from .dpf import (
    _BM_BACKENDS,
    DeviceKeys,
    _convert_leaves,
    _convert_leaves_fused,
    _fuse_plan,
    _fused_groups,
    _level_step,
    _to_bm,
    default_backend,
)

# Leaf width (log2 bits) per profile: compat = one AES block (reference
# dpf/dpf.go:251), fast = one ChaCha block (core/chacha_np.LEAF_LOG).
_LEAF_LOG = {"compat": 7, "fast": 9}

# Every jitted PIR executable registers here so core.plans.trace_count —
# the zero-retrace-after-warmup detector — counts them like any other
# module-level jit (the executables themselves live inside functools
# caches, invisible to the module scan; same duck type as
# parallel.sharding.SHARDED_JITS).
PIR_JITS = _ShardedJits()


def _pow2_floor(n: int) -> int:
    return 1 << (int(n).bit_length() - 1) if n >= 1 else 0


def row_domain(n_rows: int, profile: str = "compat") -> tuple[int, int]:
    """(log_n, padded domain size) for an ``n_rows``-row database.  Client
    and server must derive the domain identically — single source of truth."""
    log_n = max(int(n_rows - 1).bit_length(), 3)
    return log_n, 1 << max(log_n, _LEAF_LOG[profile])


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


def pir_query(
    indices: np.ndarray | list[int],
    n_rows: int,
    rng: np.random.Generator | None = None,
    profile: str = "compat",
):
    """Build the two servers' query key batches for a batch of row indices.

    ``profile="fast"`` uses the ChaCha profile (keys_chacha) — server and
    client must agree on the profile."""
    log_n, _ = row_domain(n_rows, profile)
    indices = np.asarray(indices, dtype=np.uint64)
    if (indices >= n_rows).any():
        raise ValueError("pir: row index out of range")
    if profile == "fast":
        from .keys_chacha import gen_batch as gen_fast

        return gen_fast(indices, log_n, rng=rng)
    return gen_batch(indices, log_n, rng=rng)


def pir_reconstruct(ans_a: np.ndarray, ans_b: np.ndarray) -> np.ndarray:
    """XOR the two servers' answers -> the requested rows [K, row_bytes]."""
    return np.bitwise_xor(ans_a, ans_b)


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


class PirServer:
    """One server's database, packed on device.

    ``db``: uint8[N, row_bytes]; both servers hold identical copies.
    ``mesh``: optional (keys, leaf) mesh; rows shard over ``leaf`` (the
    database words are placed once, sharded, into mesh HBM).
    ``chunk_rows``: rows per parity-matmul chunk (int8 unpack
    granularity; default ``DPF_TPU_PIR_CHUNK_ROWS``).  Any value is
    auto-rounded down to the nearest power of two that divides the
    per-shard domain — chunking changes only the schedule, never the
    answer, so a non-divisor is a tuning input, not an error.
    ``db_chunk_bytes``: per-shard resident bytes above which the scan
    streams as per-chunk dispatches (default
    ``DPF_TPU_PIR_DB_CHUNK_BYTES``; 0 disables streaming).
    """

    def __init__(
        self,
        db: np.ndarray,
        mesh: Mesh | None = None,
        chunk_rows: int | None = None,
        profile: str = "compat",
        db_chunk_bytes: int | None = None,
    ):
        if profile not in _LEAF_LOG:
            raise ValueError(f"pir: unknown profile {profile!r}")
        db = np.ascontiguousarray(np.asarray(db, dtype=np.uint8))
        if db.ndim != 2:
            raise ValueError("db must be [n_rows, row_bytes]")
        self.profile = profile
        self.n_rows, self.row_bytes = db.shape
        if self.row_bytes % 4:
            raise ValueError("row_bytes must be a multiple of 4")
        self.log_n, dom = row_domain(self.n_rows, profile)
        self.nu = max(self.log_n - _LEAF_LOG[profile], 0)
        self.mesh = mesh
        self.n_leaf = mesh.shape.get(LEAF_AXIS, 1) if mesh else 1
        if mesh is not None:
            self.subtree_levels = leaf_axis_levels(mesh, self.nu, self.log_n)
        else:
            self.subtree_levels = 0
        # Pad the row count to a full leaf domain so selection words line up
        # 1:1 with expansion output words (and to whole shards/chunks).
        self.dom = dom
        local_dom = dom // self.n_leaf  # pow2, >= 2^_LEAF_LOG >= 128
        if chunk_rows is None:
            chunk_rows = knobs.get_int("DPF_TPU_PIR_CHUNK_ROWS")
        # Auto-round: pow2-floor (>= 128 — one packed uint32[4] leaf word
        # group) clamped to the per-shard domain; every such value
        # divides the pow2 per-shard domain, so the old hard
        # "must divide" ValueError cannot fire.
        self.chunk_rows = min(_pow2_floor(max(int(chunk_rows), 128)),
                              local_dom)
        # Streamed chunk scan: when a shard holds more resident DB bytes
        # than one comfortable dispatch, the scan splits into
        # ``stream_chunks`` dispatches of ``stream_rows`` rows each.
        if db_chunk_bytes is None:
            db_chunk_bytes = knobs.get_int("DPF_TPU_PIR_DB_CHUNK_BYTES")
        if db_chunk_bytes > 0 and local_dom * self.row_bytes > db_chunk_bytes:
            rows_per = _pow2_floor(max(db_chunk_bytes // self.row_bytes, 1))
            self.stream_rows = min(max(rows_per, 128), local_dom)
        else:
            self.stream_rows = local_dom
        self.stream_chunks = local_dom // self.stream_rows
        # The matmul chunk can never exceed one streamed slab.
        self.chunk_rows = min(self.chunk_rows, self.stream_rows)
        padded = np.zeros((dom, self.row_bytes), np.uint8)
        padded[: self.n_rows] = db
        words = np.ascontiguousarray(padded).view("<u4")  # [dom, rb/4]
        if mesh is not None:
            # Resident placement: rows sharded over the leaf axis ONCE at
            # load, so no dispatch ever re-lays the database out.
            from jax.sharding import NamedSharding

            self.db_words = jax.device_put(
                words, NamedSharding(mesh, P(LEAF_AXIS, None))
            )
        else:
            self.db_words = jnp.asarray(words)

    def answer(self, queries) -> np.ndarray:
        """-> uint8[K, row_bytes]: per-query XOR of selected rows.

        ``queries``: KeyBatch (compat profile) or KeyBatchFast (fast)."""
        from .keys_chacha import KeyBatchFast

        want_fast = self.profile == "fast"
        if isinstance(queries, KeyBatchFast) != want_fast:
            raise ValueError(
                f"pir: {type(queries).__name__} queries sent to a "
                f"{self.profile!r}-profile server; client and server must "
                "agree on the profile"
            )
        if queries.log_n != self.log_n:
            raise ValueError(
                f"pir: query domain 2^{queries.log_n} != db domain 2^{self.log_n}"
            )
        n_chunks = self.dom // (self.n_leaf * self.chunk_rows)
        if self.profile == "fast":
            return self._answer_fast(queries, n_chunks)
        if self.mesh is None:
            k_shards = 1
        else:
            k_shards = self.mesh.shape[KEYS_AXIS]
        dk = DeviceKeys(queries, pad_to=32 * k_shards)
        backend = default_backend()
        args = (
            dk.seed_planes, dk.t_words, dk.scw_planes,
            dk.tl_words, dk.tr_words, dk.fcw_planes, self.db_words,
        )
        if self.stream_chunks > 1:
            words = self._stream_compat(dk, backend, args[:-1])
            return (
                np.ascontiguousarray(words[: queries.k])
                .view("<u1")
                .reshape(queries.k, -1)
            )
        words = None
        if self.mesh is None:
            # Single-chip expansion follows the production fused routing
            # (DPF_TPU_FUSE); the sharded path keeps per-level steps (its
            # subtree split already changes the level schedule).
            sched = _fuse_plan(dk.nu, backend, None)
            if sched is not None:
                from . import dpf as _mdpf

                try:
                    # host-sync: final reply marshalling (PIR answer rows)
                    words = np.asarray(
                        _pir_single(
                            dk.nu, self.chunk_rows, n_chunks, backend, sched
                        )(*args)
                    )
                except Exception as e:  # noqa: BLE001
                    _mdpf._fuse_degraded(e)
            if words is None:
                # host-sync: final reply marshalling (PIR answer rows)
                words = np.asarray(
                    _pir_single(dk.nu, self.chunk_rows, n_chunks, backend)(
                        *args
                    )
                )
        else:
            fn = _pir_sharded(
                self.mesh, dk.nu, self.subtree_levels, self.chunk_rows,
                n_chunks, backend,
            )
            # host-sync: final reply marshalling (PIR answer rows)
            words = np.asarray(fn(*args))  # [Kpad, row_words]
        return (
            np.ascontiguousarray(words[: queries.k])
            .view("<u1")
            .reshape(queries.k, -1)
        )

    def _answer_fast(self, queries, n_chunks: int) -> np.ndarray:
        from .keys_chacha import KeyBatchFast

        if self.mesh is None:
            k_shards, pad = 1, 0
        else:
            from ..parallel.sharding import _fast_pad_quantum

            k_shards = self.mesh.shape[KEYS_AXIS]
            pad = (-queries.k) % _fast_pad_quantum(
                self.mesh, self.nu, self.subtree_levels
            )

        def padk(a):
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])

        padded = KeyBatchFast(
            queries.log_n, padk(queries.seeds), padk(queries.ts),
            padk(queries.scw), padk(queries.tcw), padk(queries.fcw),
        )
        if self.mesh is None:
            entry = _pir_fast_entry_level(self.nu, padded.k)
            if self.stream_chunks > 1:
                sel = _pir_expand_fast(self.nu, entry)(*padded.device_args())
                words = self._stream_scan(sel)
            else:
                fn = _pir_single_fast(
                    self.nu, self.chunk_rows, n_chunks, entry
                )
                # host-sync: final reply marshalling (PIR answer rows)
                words = np.asarray(fn(*padded.device_args(), self.db_words))
        else:
            from ..parallel.sharding import _sharded_fast_entry_level

            entry = _sharded_fast_entry_level(
                self.nu, self.subtree_levels, padded.k // k_shards
            )
            if self.stream_chunks > 1:
                sel = _pir_expand_fast_sharded(
                    self.mesh, self.nu, self.subtree_levels, entry
                )(*padded.device_args())
                words = self._stream_scan(sel)
            else:
                fn = _pir_sharded_fast(
                    self.mesh, self.nu, self.subtree_levels,
                    self.chunk_rows, n_chunks, entry,
                )
                # host-sync: final reply marshalling (PIR answer rows)
                words = np.asarray(fn(*padded.device_args(), self.db_words))
        return (
            np.ascontiguousarray(words[: queries.k])
            .view("<u1")
            .reshape(queries.k, -1)
        )

    # -- streamed chunk scan (DBs past DPF_TPU_PIR_DB_CHUNK_BYTES) ---------

    def _stream_compat(self, dk, backend, key_args) -> np.ndarray:
        """Compat-profile streamed answer: expand the selection words in
        ONE dispatch (fused routing like the one-shot path), then stream
        the parity matmul over the resident database."""
        if self.mesh is not None:
            sel = _pir_expand_sharded(
                self.mesh, dk.nu, self.subtree_levels, backend
            )(*key_args)
            return self._stream_scan(sel)
        sel = None
        sched = _fuse_plan(dk.nu, backend, None)
        if sched is not None:
            from . import dpf as _mdpf

            try:
                sel = _pir_expand(dk.nu, backend, sched)(*key_args)
            except Exception as e:  # noqa: BLE001
                _mdpf._fuse_degraded(e)
        if sel is None:
            sel = _pir_expand(dk.nu, backend)(*key_args)
        return self._stream_scan(sel)

    def _stream_scan(self, sel) -> np.ndarray:
        """Stream the parity matmul over the device-resident database:
        one dispatch per ``stream_rows`` chunk, each XORing into a
        donated device accumulator.  Dispatch is async, so chunk j+1 is
        issued while chunk j computes (double buffering without a host
        round trip); nothing leaves the device until the final carry.
        Under a mesh the per-(key-shard, row-shard) partials meet in ONE
        parity all-reduce after the last chunk.  -> host uint32[Kpad, R]."""
        from ..core.plans import donation_enabled

        donate = donation_enabled()
        K = int(sel.shape[0])
        R = int(self.db_words.shape[1])
        inner = self.stream_rows // self.chunk_rows
        if self.mesh is None:
            acc = jnp.zeros((K, R), jnp.uint32)
            step = _pir_stream_chunk(
                self.chunk_rows, inner, self.stream_rows, donate
            )
            for j in range(self.stream_chunks):
                acc = step(sel, self.db_words, acc, np.int32(j))
            # host-sync: final reply marshalling (PIR answer rows)
            return np.asarray(acc)
        from jax.sharding import NamedSharding

        acc = jax.device_put(
            np.zeros((self.n_leaf, K, R), np.uint32),
            NamedSharding(self.mesh, P(LEAF_AXIS, KEYS_AXIS, None)),
        )
        step = _pir_stream_chunk_sharded(
            self.mesh, self.chunk_rows, inner, self.stream_rows, donate
        )
        for j in range(self.stream_chunks):
            acc = step(sel, self.db_words, acc, np.int32(j))
        # host-sync: final reply marshalling (PIR answer rows)
        return np.asarray(_pir_stream_combine(self.mesh)(acc))


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _unpack_bits_i8(words: jax.Array) -> jax.Array:
    """uint32[M, W] -> int8[M, 32*W] bits, LSB-first per word.  Used for
    both the selection rows and the db rows of the parity matmul — the
    ONLY place the packed pipeline widens to bytes, and only chunk-local
    inside the MXU kernel (int8 is the matmul's input type); everywhere
    else selection vectors stay packed uint32 words
    (core/bitpack contract)."""
    m = words.shape[0]
    b = (words[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    return b.reshape(m, -1).astype(jnp.int8)


def _pack_bits_u32(bits: jax.Array) -> jax.Array:
    """int32[..., 32*R] 0/1 -> uint32[..., R] (core/bitpack.pack_bits_jnp
    — the shared packed-word contract)."""
    from ..core import bitpack

    return bitpack.pack_bits_jnp(bits)


def _parity_matmul(sel_words, db_words, chunk_rows, n_chunks):
    """GF(2) product sel[K, N] x db[N, bits] via chunked int8 MXU matmuls.

    sel_words uint32[K, N/32], db_words uint32[N, R] -> uint32[K, R].
    """
    K = sel_words.shape[0]
    R = db_words.shape[1]
    cw = chunk_rows // 32

    def step(acc, i):
        sel = _unpack_bits_i8(
            jax.lax.dynamic_slice_in_dim(sel_words, i * cw, cw, axis=1)
        )  # int8[K, chunk]
        dbb = _unpack_bits_i8(
            jax.lax.dynamic_slice_in_dim(db_words, i * chunk_rows, chunk_rows)
        )  # int8[chunk, 32R]
        part = jnp.matmul(sel, dbb, preferred_element_type=jnp.int32)
        return acc ^ (part & 1), None

    acc0 = jnp.zeros((K, 32 * R), jnp.int32)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(n_chunks))
    return _pack_bits_u32(acc)


def _leaves_to_sel_words(words: jax.Array) -> jax.Array:
    """Expansion output uint32[K, W, 4] -> selection words uint32[K, W*4]
    in ascending row order (row 128*w + 32*q + bit, LSB-first)."""
    return words.reshape(words.shape[0], -1)


def _expand_sel_planes(
    nu, backend, fuse_sched, seed_planes, t_words, scw_planes, tl_w, tr_w,
    fcw_planes,
):
    """Traceable compat-profile expansion -> selection words
    uint32[K, dom/32] in ascending row order.  ``fuse_sched``
    (models/dpf._fuse_plan output) routes the deep levels through the
    level-fused VMEM kernels — same bytes, ~G x less HBM traffic."""
    if backend in _BM_BACKENDS:
        seed_planes, scw_planes = _to_bm(seed_planes, scw_planes)
    S, T = seed_planes, t_words
    if fuse_sched is not None:
        first, groups = fuse_sched
        for i in range(first):
            S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
        Sf, Tf = _fused_groups(S, T, scw_planes, tl_w, tr_w, first, groups)
        leaves = _convert_leaves_fused(Sf, Tf, fcw_planes, backend)
    else:
        for i in range(nu):
            S, T = _level_step(S, T, scw_planes[i], tl_w[i], tr_w[i], backend)
        leaves = _convert_leaves(S, T, fcw_planes, backend)
    return _leaves_to_sel_words(leaves)


def _pir_single_body(
    nu: int, chunk_rows: int, n_chunks: int, backend: str = "xla",
    fuse_sched=None,
):
    """The UNJITTED one-shot compat pipeline body (what the oblivious-
    trace verifier certifies as ``pir/scan/compat``)."""

    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes,
             db_words):
        sel = _expand_sel_planes(
            nu, backend, fuse_sched, seed_planes, t_words, scw_planes,
            tl_w, tr_w, fcw_planes,
        )
        return _parity_matmul(sel, db_words, chunk_rows, n_chunks)

    return body


@cache
def _pir_single(
    nu: int, chunk_rows: int, n_chunks: int, backend: str = "xla",
    fuse_sched=None,
):
    """Single-chip PIR pipeline: expansion feeding the chunked parity
    matmul in one program."""
    return PIR_JITS.register(
        jax.jit(_pir_single_body(nu, chunk_rows, n_chunks, backend,
                                 fuse_sched))
    )


def _pir_expand_body(nu: int, backend: str = "xla", fuse_sched=None):
    """UNJITTED compat expansion-only body (``pir/stream_expand/compat``):
    the streamed scan's first dispatch — selection words stay on device."""

    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes):
        return _expand_sel_planes(
            nu, backend, fuse_sched, seed_planes, t_words, scw_planes,
            tl_w, tr_w, fcw_planes,
        )

    return body


@cache
def _pir_expand(nu: int, backend: str = "xla", fuse_sched=None):
    return PIR_JITS.register(
        jax.jit(_pir_expand_body(nu, backend, fuse_sched))
    )


def _pir_expand_sharded_sm(
    mesh: Mesh, nu: int, subtree_levels: int, backend: str = "xla"
):
    """UNJITTED sharded compat expansion (``pir/stream_expand`` sharded):
    each shard expands only its own subtree; the selection words come out
    sharded (keys x leaf) and FEED the streamed chunk scan in place —
    zero collectives, nothing replicated."""

    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes):
        S, T = expand_subtree_local(
            seed_planes, t_words, scw_planes, tl_w, tr_w, nu,
            subtree_levels, backend,
        )
        return _leaves_to_sel_words(_convert_leaves(S, T, fcw_planes,
                                                    backend))

    keyed = P(None, None, KEYS_AXIS)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            keyed, P(None, KEYS_AXIS), keyed, P(None, KEYS_AXIS),
            P(None, KEYS_AXIS), keyed,
        ),
        out_specs=P(KEYS_AXIS, LEAF_AXIS),
        check_vma=False,
    )


@cache
def _pir_expand_sharded(
    mesh: Mesh, nu: int, subtree_levels: int, backend: str = "xla"
):
    return PIR_JITS.register(
        jax.jit(_pir_expand_sharded_sm(mesh, nu, subtree_levels, backend))
    )


def _pir_expand_fast_body(nu: int, entry: int = -1):
    """UNJITTED fast-profile expansion-only body
    (``pir/stream_expand/fast``)."""

    def body(seeds, ts, scw, tcw, fcw):
        return _fast_expand_sel(nu, entry, seeds, ts, scw, tcw, fcw)

    return body


@cache
def _pir_expand_fast(nu: int, entry: int = -1):
    return PIR_JITS.register(jax.jit(_pir_expand_fast_body(nu, entry)))


def _pir_expand_fast_sharded_sm(
    mesh: Mesh, nu: int, subtree_levels: int, entry: int = -1
):
    from ..parallel.sharding import expand_subtree_local_cc
    from .dpf_chacha import _convert_leaves_cc, _finish_pk

    def body(seeds, ts, scw, tcw, fcw):
        if entry < 0:
            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, nu, subtree_levels
            )
            leaves = _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        else:
            from ..ops.chacha_pallas import cw_operands

            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, entry, subtree_levels
            )
            leaves = _finish_pk(
                nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu)
            )
        return leaves.reshape(leaves.shape[0], -1)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None), P(KEYS_AXIS), P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None, None), P(KEYS_AXIS, None),
        ),
        out_specs=P(KEYS_AXIS, LEAF_AXIS),
        check_vma=False,
    )


@cache
def _pir_expand_fast_sharded(
    mesh: Mesh, nu: int, subtree_levels: int, entry: int = -1
):
    return PIR_JITS.register(
        jax.jit(_pir_expand_fast_sharded_sm(mesh, nu, subtree_levels, entry))
    )


# The donated accumulator position of BOTH streamed-chunk jits below
# (single-device and sharded share the (sel, db, acc, j) signature).
# The perf-contract analysis pass lowers the donate=True factories and
# verifies the accumulator actually reaches XLA donated.
STREAM_CHUNK_DONATE_ARGNUMS = (2,)


def _pir_stream_chunk_body(chunk_rows: int, n_inner: int, stream_rows: int):
    """UNJITTED streamed-scan chunk body (``pir/stream_chunk``): one
    ``stream_rows``-row slab of the resident database XORed into the
    carried accumulator.  ``j`` is the PUBLIC chunk index — a traced
    scalar so every chunk of a scan lands on one executable."""

    def body(sel, db_words, acc, j):
        sw = stream_rows // 32
        sel_j = jax.lax.dynamic_slice_in_dim(sel, j * sw, sw, axis=1)
        db_j = jax.lax.dynamic_slice_in_dim(
            db_words, j * stream_rows, stream_rows, axis=0
        )
        return acc ^ _parity_matmul(sel_j, db_j, chunk_rows, n_inner)

    return body


@cache
def _pir_stream_chunk(
    chunk_rows: int, n_inner: int, stream_rows: int, donate: bool = False
):
    body = _pir_stream_chunk_body(chunk_rows, n_inner, stream_rows)
    # The accumulator is dead after each chunk (the loop rebinds it), so
    # donating its buffer lets XLA XOR in place across the whole scan.
    jitted = jax.jit(body, donate_argnums=(2,)) if donate else jax.jit(body)
    return PIR_JITS.register(jitted)


def _pir_stream_chunk_sharded_sm(
    mesh: Mesh, chunk_rows: int, n_inner: int, stream_rows: int
):
    """UNJITTED sharded streamed-scan chunk body: every (key-shard,
    row-shard) device scans its own ``stream_rows`` local rows against
    its own selection-word block — zero collectives; the accumulator
    stays per-device (leaf-major) until the final combine."""

    def body(sel_l, db_l, acc_l, j):
        sw = stream_rows // 32
        sel_j = jax.lax.dynamic_slice_in_dim(sel_l, j * sw, sw, axis=1)
        db_j = jax.lax.dynamic_slice_in_dim(
            db_l, j * stream_rows, stream_rows, axis=0
        )
        part = _parity_matmul(sel_j, db_j, chunk_rows, n_inner)
        return acc_l ^ part[None]

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, LEAF_AXIS), P(LEAF_AXIS, None),
            P(LEAF_AXIS, KEYS_AXIS, None), P(),
        ),
        out_specs=P(LEAF_AXIS, KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _pir_stream_chunk_sharded(
    mesh: Mesh, chunk_rows: int, n_inner: int, stream_rows: int,
    donate: bool = False,
):
    body = _pir_stream_chunk_sharded_sm(mesh, chunk_rows, n_inner,
                                        stream_rows)
    jitted = jax.jit(body, donate_argnums=(2,)) if donate else jax.jit(body)
    return PIR_JITS.register(jitted)


def _pir_stream_combine_sm(mesh: Mesh):
    """UNJITTED streamed-scan combine: the ONE parity all-reduce of a
    sharded query batch, folding the per-row-shard partial answers."""

    def body(acc_l):
        return xor_allreduce(acc_l[0], LEAF_AXIS)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(LEAF_AXIS, KEYS_AXIS, None),),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _pir_stream_combine(mesh: Mesh):
    return PIR_JITS.register(jax.jit(_pir_stream_combine_sm(mesh)))


def _fast_expand_sel(nu, entry, seeds, ts, scw, tcw, fcw):
    """Traceable fast-profile expansion -> selection words uint32[K, W*16]
    in ascending row order.  ``entry >= 0`` routes levels entry..nu-1 plus
    leaf conversion through the VMEM expand kernel (models/dpf_chacha
    _finish_pk; the kernel's lane-padded CW operands are built in-graph —
    a few tiny pad ops against ~GBs of leaf words); entry < 0 is the pure
    XLA pipeline."""
    from .dpf_chacha import _convert_leaves_cc, _finish_pk, _level_step_cc

    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(entry if entry >= 0 else nu):
        S, T = _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )
    if entry < 0:
        leaves = _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        return leaves.reshape(leaves.shape[0], -1)
    from ..ops.chacha_pallas import cw_operands

    K = seeds.shape[0]
    words = _finish_pk(nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu))
    return words.reshape(K, -1)


def _pir_fast_entry_level(nu: int, k: int) -> int:
    """Expand-kernel entry level for the PIR pipeline, or -1 for XLA."""
    from ..ops import chacha_pallas as cp

    if cp.expand_backend() != "pallas" or not cp.kernel_usable(nu, k):
        return -1
    return cp.entry_level(nu)


def _pir_single_fast_body(
    nu: int, chunk_rows: int, n_chunks: int, entry: int = -1
):
    """The UNJITTED one-shot fast pipeline body (``pir/scan/fast``)."""

    def body(seeds, ts, scw, tcw, fcw, db_words):
        sel = _fast_expand_sel(nu, entry, seeds, ts, scw, tcw, fcw)
        return _parity_matmul(sel, db_words, chunk_rows, n_chunks)

    return body


@cache
def _pir_single_fast(nu: int, chunk_rows: int, n_chunks: int, entry: int = -1):
    return PIR_JITS.register(
        jax.jit(_pir_single_fast_body(nu, chunk_rows, n_chunks, entry))
    )


def _pir_sharded_fast_sm(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    entry: int = -1,
):
    from ..parallel.sharding import expand_subtree_local_cc
    from .dpf_chacha import _convert_leaves_cc, _finish_pk

    def body(seeds, ts, scw, tcw, fcw, db_words):
        if entry < 0:
            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, nu, subtree_levels
            )
            leaves = _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])
        else:  # VMEM expand kernel per shard (same route as eval_full)
            from ..ops.chacha_pallas import cw_operands

            S, T = expand_subtree_local_cc(
                seeds, ts, scw, tcw, entry, subtree_levels
            )
            leaves = _finish_pk(
                nu, entry, S, T, *cw_operands(scw, tcw, fcw, entry, nu)
            )
        sel = leaves.reshape(leaves.shape[0], -1)
        part = _parity_matmul(sel, db_words, chunk_rows, n_chunks)
        return xor_allreduce(part, LEAF_AXIS)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            P(KEYS_AXIS, None), P(KEYS_AXIS), P(KEYS_AXIS, None, None),
            P(KEYS_AXIS, None, None), P(KEYS_AXIS, None), P(LEAF_AXIS, None),
        ),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _pir_sharded_fast(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    entry: int = -1,
):
    return PIR_JITS.register(
        jax.jit(
            _pir_sharded_fast_sm(
                mesh, nu, subtree_levels, chunk_rows, n_chunks, entry
            )
        )
    )


def _pir_sharded_sm(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    backend: str = "xla",
):
    def body(seed_planes, t_words, scw_planes, tl_w, tr_w, fcw_planes,
             db_words):
        S, T = expand_subtree_local(
            seed_planes, t_words, scw_planes, tl_w, tr_w, nu, subtree_levels,
            backend,
        )
        sel = _leaves_to_sel_words(_convert_leaves(S, T, fcw_planes, backend))
        part = _parity_matmul(sel, db_words, chunk_rows, n_chunks)
        return xor_allreduce(part, LEAF_AXIS)

    keyed = P(None, None, KEYS_AXIS)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            keyed, P(None, KEYS_AXIS), keyed, P(None, KEYS_AXIS),
            P(None, KEYS_AXIS), keyed, P(LEAF_AXIS, None),
        ),
        out_specs=P(KEYS_AXIS, None),
        check_vma=False,
    )


@cache
def _pir_sharded(
    mesh: Mesh, nu: int, subtree_levels: int, chunk_rows: int, n_chunks: int,
    backend: str = "xla",
):
    return PIR_JITS.register(
        jax.jit(
            _pir_sharded_sm(
                mesh, nu, subtree_levels, chunk_rows, n_chunks, backend
            )
        )
    )
