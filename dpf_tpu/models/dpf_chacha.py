"""TPU evaluator for the ChaCha fast profile: word-oriented, plane-free.

Where the AES-compat evaluator (models/dpf.py) must bitslice — AES is a
bit-permutation-heavy cipher — the ChaCha PRG is native 32-bit add/rotate/
xor, so the whole level-synchronous expansion works directly on seed WORDS:
state is four uint32[K, W] arrays (one per seed word), each ChaCha quarter
round is a handful of full-width elementwise VPU ops, and there is no
pack/transpose anywhere.  ~10x fewer VPU ops per output bit than the
bitsliced AES path (see core/chacha_np.py header).

Level step mirrors the reference's per-node work (dpf/dpf.go:229-238):
PRG-expand, extract+clear control bits, masked CW application; leaves
convert via one ChaCha block = 512 output bits directly in the bit-packed
output layout (word j of a leaf holds domain bits [512w + 32j, +32)).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bitpack
from ..core import chacha_np as cc
from .keys_chacha import KeyBatchFast

_C0, _C1, _C2, _C3 = (int(v) for v in cc._CONSTANTS)
_DSX = [int(v) for v in cc.DS_EXPAND]
_DSL = [int(v) for v in cc.DS_LEAF]


# Bench-only knob (scripts/bench_points_fast.py): unroll the ChaCha rounds
# inside the XLA pointwise walk.  Measured 1.3x there, but the Pallas walk
# kernel (ops/chacha_pallas.py) supersedes that path on TPU, so the
# default stays the cheap-to-compile loop.
_POINTS_UNROLL = False


def _chacha_core(seed, ds, n_out, unroll=False):
    """seed: 4 arrays; ds: 4 ints.  Runs the ChaCha12 permutation with the
    fast-profile state layout and returns the first n_out output words
    (permuted state + initial state, RFC 8439 feed-forward).

    The double-round body is shared with the spec and the Pallas walk
    kernel (core/chacha_np.double_round).  Default is a ``lax.fori_loop``
    over double rounds — shape-invariant, keeps XLA compile time sane (an
    unrolled pointwise graph measured minutes of XLA CPU compile);
    ``unroll=True`` unrolls the rounds instead."""
    z = jnp.zeros_like(seed[0])

    def const(v):
        return z + np.uint32(v)

    init = [
        const(_C0), const(_C1), const(_C2), const(_C3),
        seed[0], seed[1], seed[2], seed[3],
        const(ds[0]), const(ds[1]), const(ds[2]), const(ds[3]),
        z, z, z, z,
    ]

    def dbl_round(_, s):
        s = list(s)
        cc.double_round(s)
        return tuple(s)

    if unroll:
        s = tuple(init)
        for _ in range(cc.ROUNDS // 2):
            s = dbl_round(None, s)
    else:
        s = jax.lax.fori_loop(0, cc.ROUNDS // 2, dbl_round, tuple(init))
    return [s[i] + init[i] for i in range(n_out)]


def _prg_expand(seed, unroll=False):
    """4x[K, W] -> (left 4x, right 4x) child seed words."""
    out = _chacha_core(seed, _DSX, 8, unroll)
    return out[0:4], out[4:8]


def _prg_expand_v(seed, unroll=False):
    """4x[K, W] -> (left 4x, right 4x, value word) — the DCF node PRG
    (core/chacha_np.prg_expand_v semantics)."""
    out = _chacha_core(seed, _DSX, 9, unroll)
    return out[0:4], out[4:8], out[8]


def _convert(seed, unroll=False):
    """4x[K, W] -> 16 output words (the leaf's 512 bits)."""
    return _chacha_core(seed, _DSL, 16, unroll)


def _interleave(l, r):
    """[K, W] pairs -> [K, 2W] with children in L,R order per parent."""
    return jnp.stack([l, r], axis=2).reshape(l.shape[0], -1)


def _level_step_cc(S, T, scw_w, tlcw, trcw):
    """One expansion level.

    S: 4x uint32[K, W]; T: uint32[K, W] control bits (0/1);
    scw_w: 4x uint32[K]; tlcw/trcw: uint32[K]."""
    L, R = _prg_expand(S)
    tl = L[0] & np.uint32(1)
    tr = R[0] & np.uint32(1)
    L[0] = L[0] & ~np.uint32(1)
    R[0] = R[0] & ~np.uint32(1)
    msk = jnp.uint32(0) - T  # 0 / 0xFFFFFFFF
    L = [L[i] ^ (scw_w[i][:, None] & msk) for i in range(4)]
    R = [R[i] ^ (scw_w[i][:, None] & msk) for i in range(4)]
    tl = tl ^ (tlcw[:, None] & T)
    tr = tr ^ (trcw[:, None] & T)
    S2 = [_interleave(L[i], R[i]) for i in range(4)]
    T2 = _interleave(tl, tr)
    return S2, T2


def _convert_leaves_cc(S, T, fcw_w):
    """Leaf conversion + final CW -> uint32[K, W, 16] output words."""
    out = _convert(S)
    msk = jnp.uint32(0) - T
    out = [out[j] ^ (fcw_w[j][:, None] & msk) for j in range(16)]
    return jnp.stack(out, axis=2)


@partial(jax.jit, static_argnums=(0,))
def _eval_full_cc_jit(nu, seeds, ts, scw, tcw, fcw):
    """seeds uint32[K,4], ts uint32[K], scw uint32[K,nu,4],
    tcw uint32[K,nu,2], fcw uint32[K,16] -> uint32[K, 2^nu, 16]."""
    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(nu):
        S, T = _level_step_cc(
            S, T,
            [scw[:, i, w] for w in range(4)],
            tcw[:, i, 0], tcw[:, i, 1],
        )
    return _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])


@partial(jax.jit, static_argnums=(0,))
def _expand_prefix_cc_jit(n_levels, seeds, ts, scw, tcw):
    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(n_levels):
        S, T = _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )
    return S, T


def _finish_chunk_cc_body(n_levels, first, S, T, scw, tcw, fcw):
    for i in range(n_levels):
        j = first + i
        S, T = _level_step_cc(
            S, T, [scw[:, j, w] for w in range(4)], tcw[:, j, 0], tcw[:, j, 1]
        )
    return _convert_leaves_cc(S, T, [fcw[:, j] for j in range(16)])


def _finish_chunks_cc_scan_body(
    n_levels, first, s0, s1, s2, s3, T, scw, tcw, fcw
):
    """All subtree chunks in ONE compiled function (lax.scan over the node
    axis) — one dispatch instead of 2 per chunk; per-iteration working set
    unchanged (see models/dpf._finish_chunks_scan_jit for the rationale).

    s0..s3/T: uint32[K, C] prefix state -> uint32[K, C * Wc, 16]."""
    xs = tuple(jnp.moveaxis(s, 1, 0)[:, :, None] for s in (s0, s1, s2, s3, T))

    def body(_, st):
        *Sj, Tj = st
        return None, _finish_chunk_cc_body(
            n_levels, first, list(Sj), Tj, scw, tcw, fcw
        )

    _, ys = jax.lax.scan(body, None, xs)  # [C, K, Wc, 16]
    return jnp.moveaxis(ys, 0, 1).reshape(ys.shape[1], -1, ys.shape[3])


_finish_chunks_cc_scan_jit = partial(jax.jit, static_argnums=(0, 1))(
    _finish_chunks_cc_scan_body
)
# Donation surface (see models/dpf.DONATED_TWINS): twin name ->
# (static_argnums, donate_argnums), verified against the actual
# lowerings by the perf-contract analysis pass.
DONATED_TWINS = {
    "_finish_chunks_cc_scan_donated_jit": ((0, 1), (2, 3, 4, 5, 6)),
    "_finish_chunk_cc_donated_jit": ((0, 1), (2, 3)),
    "_finish_pk_chunks_donated_jit": ((0, 1, 2, 3), (4, 5, 6, 7, 8)),
}
# Donated twin (core/plans.donation_enabled): the prefix level-state
# carries are dead once the finish consumes them — see the compat
# mirror models/dpf._finish_chunks_scan_donated_jit.
_finish_chunks_cc_scan_donated_jit = partial(
    jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4, 5, 6)
)(_finish_chunks_cc_scan_body)

# Single-chunk finish — the streaming pipeline's unit of dispatch.
_finish_chunk_cc_jit = partial(jax.jit, static_argnums=(0, 1))(
    _finish_chunk_cc_body
)
_finish_chunk_cc_donated_jit = partial(
    jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3)
)(_finish_chunk_cc_body)


# ---------------------------------------------------------------------------
# Incremental heavy-hitter frontier extension (apps/hh_state.py)
#
# The GGM control-bit invariant makes a descent round a ONE-level PRG
# step instead of a from-root walk: for the client's LAST level key
# (point = the full value), the two aggregators' states at any tree node
# are equal off the value's path and differ exactly on it, so the
# control bit T at a depth-d node is a valid XOR share of "the value's
# d-bit prefix is this node".  The frontier cache carries (S, T) at the
# surviving prefixes across rounds; each round gathers the publicly
# surviving parent columns (sel is PUBLIC — survivors are announced to
# both aggregators by protocol) and expands both children in one
# dispatch.  Past the tree (depth > nu), leaves convert ONCE and deeper
# prefixes become XOR folds over intra-leaf bit ranges: after XOR
# reconstruction at most one leaf bit is set, so the range-OR the
# descent needs IS the XOR fold of the share bits.
# ---------------------------------------------------------------------------


def hh_leaf_fold_cc(P, m, ibits):
    """Fold converted leaf words to depth-``m`` intra-leaf predicate bits.

    P uint32[K, A, 16] leaf output words (value bit x at word x // 32,
    bit x % 32, LSB-first); only the low ``2**ibits`` bits are populated
    (ibits = log_n - nu <= 9).  Returns uint32[K, A, 2**m] 0/1 share
    bits: entry v is the XOR of the leaf bits in value range
    [v * s, (v + 1) * s), s = 2**(ibits - m)."""
    K, A = P.shape[0], P.shape[1]
    n_bits = 1 << ibits
    s = n_bits >> m
    if s >= 32:
        w = P[:, :, : n_bits // 32].reshape(K, A, 1 << m, s // 32)
        w = jax.lax.reduce(w, np.uint32(0), jax.lax.bitwise_xor, (3,))
        for sh in (16, 8, 4, 2, 1):
            w = w ^ (w >> sh)
        return w & np.uint32(1)
    # Sub-word ranges: in-word parity fold (shifts < s never cross a
    # range), then extract each range's LSB at bit c * s.
    p = P[:, :, : max(n_bits // 32, 1)]
    sh = s >> 1
    while sh:
        p = p ^ (p >> sh)
        sh >>= 1
    idx = np.arange(min(32, n_bits) // s, dtype=np.uint32) * np.uint32(s)
    b = (p[:, :, :, None] >> idx) & np.uint32(1)
    return b.reshape(K, A, -1)


def _hh_extend_cc_body(s0, s1, s2, s3, T, sel, c0, c1, c2, c3, tlcw, trcw):
    """One incremental frontier level: gather the surviving parent
    columns (public ``sel`` int32[F]) out of the carried uint32[K, 2F]
    state, expand each one level -> new [K, 2F] child state (children
    interleaved L,R per parent) + the children's control-bit share rows
    packed client-major uint32[K, 2F // 32]."""
    S = [jnp.take(s, sel, axis=1) for s in (s0, s1, s2, s3)]
    Tg = jnp.take(T, sel, axis=1)
    S2, T2 = _level_step_cc(S, Tg, [c0, c1, c2, c3], tlcw, trcw)
    return (*S2, T2, bitpack.pack_bits_jnp(T2))


def _hh_leaf_first_cc_body(ibits, s0, s1, s2, s3, T, sel, *fcw):
    """Frontier crossing into the leaf: gather the surviving depth-nu
    columns, convert their leaves ONCE (-> the session's resident
    uint32[K, F, 16] plane state) and emit the first intra-leaf split
    (m=1) as packed rows uint32[K, 2F // 32]."""
    S = [jnp.take(s, sel, axis=1) for s in (s0, s1, s2, s3)]
    Tg = jnp.take(T, sel, axis=1)
    P = _convert_leaves_cc(S, Tg, list(fcw))
    B = hh_leaf_fold_cc(P, 1, ibits)  # [K, F, 2], (parent, bit) order
    return P, bitpack.pack_bits_jnp(B.reshape(B.shape[0], -1))


def _hh_leaf_fold_cc_body(m, ibits, P, idx):
    """Intra-leaf frontier level m >= 2: fold the resident plane state
    (NOT donated — it is reused by every deeper round) and gather the
    requested children (public ``idx`` int32[Q] = anc * 2**m + v) ->
    packed rows uint32[K, Q // 32]."""
    B = hh_leaf_fold_cc(P, m, ibits)
    bits = jnp.take(B.reshape(B.shape[0], -1), idx, axis=1)
    return bitpack.pack_bits_jnp(bits)


_hh_extend_cc_jit = jax.jit(_hh_extend_cc_body)
_hh_extend_cc_donated_jit = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(
    _hh_extend_cc_body
)
_hh_leaf_first_cc_jit = partial(jax.jit, static_argnums=(0,))(
    _hh_leaf_first_cc_body
)
_hh_leaf_first_cc_donated_jit = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(1, 2, 3, 4, 5)
)(_hh_leaf_first_cc_body)
_hh_leaf_fold_cc_jit = partial(jax.jit, static_argnums=(0, 1))(
    _hh_leaf_fold_cc_body
)
DONATED_TWINS["_hh_extend_cc_donated_jit"] = ((), (0, 1, 2, 3, 4))
DONATED_TWINS["_hh_leaf_first_cc_donated_jit"] = ((0,), (1, 2, 3, 4, 5))


# Soft cap on K * 2^nu leaf nodes per compiled expansion (each leaf is 64 B
# plus transient children); above it the tree splits into independent
# subtree chunks, mirroring the compat path (models/dpf.py:MAX_PLANE_WORDS).
MAX_LEAF_NODES = 1 << 23  # 512 MB of leaf words per chunk


def _finish_pk(nu, first, S, T, scw_p, tcw_p, fcw_p):
    """Kernel tail shared by the one-shot and chunked paths: levels
    first..nu-1 + leaf conversion in the VMEM kernel, leaf order restored,
    words stacked to the [K, W, 16] output contract."""
    from ..ops import chacha_pallas as cp

    levels = nu - first
    wt = min(cp._EWT, T.shape[1])  # entry node-tile width (small trees < 128)
    outs = cp._expand_raw(
        S[0], S[1], S[2], S[3], T, scw_p, tcw_p, fcw_p, levels
    )
    outs = [cp.deinterleave_leaves(o, levels, wt) for o in outs]
    return jnp.stack(outs, axis=2)


@partial(jax.jit, static_argnums=(0, 1))
def _eval_full_pk_jit(nu, first, seeds, ts, scw, tcw, scw_p, tcw_p, fcw_p):
    """Hybrid expansion: XLA level steps for levels 0..first-1 (widths too
    small to tile), then ONE Pallas program per tile runs levels
    first..nu-1 plus leaf conversion with the ChaCha state resident in
    VMEM (ops/chacha_pallas.expand kernel) — the XLA round loop's ~12
    full-state HBM round trips per level collapse to state-in once,
    leaves out once.  -> uint32[K, 2^nu, 16]."""
    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(first):
        S, T = _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )
    return _finish_pk(nu, first, S, T, scw_p, tcw_p, fcw_p)


@partial(jax.jit, static_argnums=(0, 1))
def _finish_pk_jit(nu, first, s0, s1, s2, s3, T, scw_p, tcw_p, fcw_p):
    return _finish_pk(nu, first, [s0, s1, s2, s3], T, scw_p, tcw_p, fcw_p)


def _finish_pk_chunks_body(
    nu, first, n_chunks, wc, s0, s1, s2, s3, T, scw_p, tcw_p, fcw_p
):
    """Kernel tail over ALL node-range chunks in ONE compiled function
    (lax.scan; see models/dpf._finish_chunks_scan_jit for why).  State
    arrays are uint32[K, n_chunks * wc] -> uint32[K, n_chunks * Wc, 16]."""
    xs = tuple(
        jnp.moveaxis(a.reshape(a.shape[0], n_chunks, wc), 1, 0)
        for a in (s0, s1, s2, s3, T)
    )

    def body(_, st):
        *Sj, Tj = st
        return None, _finish_pk(nu, first, list(Sj), Tj, scw_p, tcw_p, fcw_p)

    _, ys = jax.lax.scan(body, None, xs)  # [C, K, Wc, 16]
    return jnp.moveaxis(ys, 0, 1).reshape(ys.shape[1], -1, ys.shape[3])


_finish_pk_chunks_jit = partial(jax.jit, static_argnums=(0, 1, 2, 3))(
    _finish_pk_chunks_body
)
_finish_pk_chunks_donated_jit = partial(
    jax.jit, static_argnums=(0, 1, 2, 3), donate_argnums=(4, 5, 6, 7, 8)
)(_finish_pk_chunks_body)


# ---------------------------------------------------------------------------
# Level-fused mid-tree expansion (DPF_TPU_FUSE; the ChaCha twin of
# models/dpf's fused backend).  The classic kernel route already fuses the
# LAST <= _EXP_LEVELS levels plus leaf conversion into one program; for
# deep trees (nu > 12) the levels between the 128-node entry and that tail
# still run one XLA level step each — ~12 full-state HBM round trips per
# level.  The fused backend covers them with G-level VMEM-resident groups
# (ops/chacha_pallas.fused_levels_raw), then hands ascending-order state
# to the unchanged tail kernel.
# ---------------------------------------------------------------------------

_FUSE_CC_FLOOR = 7  # 2^7-node entry width fills the kernel's lane tile


def _fuse_schedule_cc(nu, g, floor=_FUSE_CC_FLOOR, tail_cap=None):
    """(first, group sizes, tail entry level) for a fused fast-profile
    expansion, or None when no mid levels exist (the classic route already
    covers everything).  ``floor``/``tail_cap`` are parameterized for
    tests (small-domain interpret runs)."""
    from ..ops import chacha_pallas as cp

    if tail_cap is None:
        tail_cap = cp._EXP_LEVELS
    if g <= 0 or nu - floor <= 0:
        return None
    tail = min(tail_cap, nu - floor)
    mid = nu - floor - tail
    if mid <= 0:
        return None
    groups = []
    while mid > 0:
        t = min(g, mid)
        groups.append(t)
        mid -= t
    return floor, tuple(groups), nu - tail


@partial(jax.jit, static_argnums=(0, 1))
def _eval_full_fused_cc_jit(
    nu, schedule, seeds, ts, scw, tcw, fcw, scw_t, tcw_t, fcw_t
):
    """Fused expansion: XLA steps to the floor, G-level fused groups over
    the mid levels (state resident in VMEM per group, ascending node
    order restored by the static deinterleave gather per group), then the
    existing tail kernel (levels entry..nu-1 + leaf conversion).
    scw_t/tcw_t/fcw_t are the tail's expand_operands."""
    from ..ops import chacha_pallas as cp

    first, groups, entry = schedule
    S = [seeds[:, i : i + 1] for i in range(4)]
    T = ts[:, None]
    for i in range(first):
        S, T = _level_step_cc(
            S, T, [scw[:, i, w] for w in range(4)], tcw[:, i, 0], tcw[:, i, 1]
        )
    lvl = first
    for g in groups:
        wt = min(cp._EWT, T.shape[1])
        gscw, gtcw, _ = cp.cw_operands(
            scw[:, lvl : lvl + g], tcw[:, lvl : lvl + g], fcw, 0, g
        )
        outs = cp.fused_levels_raw(*S, T, gscw, gtcw, g)
        outs = [cp.deinterleave_leaves(o, g, wt) for o in outs]
        S, T = list(outs[:4]), outs[4]
        lvl += g
    return _finish_pk(nu, entry, S, T, scw_t, tcw_t, fcw_t)


def _eval_full_pallas_fused(kb: KeyBatchFast, schedule):
    from ..ops import chacha_pallas as cp
    from ..parallel.sharding import _pad_fast_batch

    pk = _pad_fast_batch(kb, (-kb.k) % cp._EKT)
    words = _eval_full_fused_cc_jit(
        pk.nu, schedule, *pk.device_args(),
        *cp.expand_operands(pk, schedule[2]),
    )
    return words[: kb.k]


# Sticky failure latch (mirror of models/dpf._FUSE_BROKEN): env-auto
# routing degrades to the classic plan once; DPF_TPU_FUSE=<g> or an
# explicit fuse= argument re-raises.
_FUSE_CC_BROKEN = False


def _fuse_cc_degraded(e: Exception) -> None:
    global _FUSE_CC_BROKEN
    import warnings

    from ..ops import fuse_forced

    if fuse_forced():
        raise e
    _FUSE_CC_BROKEN = True
    warnings.warn(
        f"fused fast-profile expansion unavailable, using the classic "
        f"plan: {e}",
        RuntimeWarning,
        stacklevel=3,
    )


def _fuse_plan_cc(nu: int, fuse: int | None):
    """Resolved fused schedule for production routing (None = classic)."""
    from ..ops import chacha_pallas as cp
    from ..ops import fuse_forced, fuse_request

    if fuse is None:
        if _FUSE_CC_BROKEN and not fuse_forced():
            return None
        g = fuse_request(cp.fuse_auto_levels() if cp._on_tpu() else 0)
    else:
        g = fuse
    return _fuse_schedule_cc(nu, g) if g > 0 else None


def _eval_full_pallas_device(kb: KeyBatchFast, entry_level: int):
    """Kernel-path full expansion: classic route (entry >= 7, 128-node-wide
    tiles) or the whole-tree entry-0 route for small domains
    (chacha_pallas.small_tree_entry).  Pads the key axis to the kernel's
    8-key sublane tile and slices the padding back off."""
    from ..ops import chacha_pallas as cp
    from ..parallel.sharding import _pad_fast_batch

    pk = _pad_fast_batch(kb, (-kb.k) % cp._EKT)
    seeds, ts, scw, tcw, _ = pk.device_args()
    words = _eval_full_pk_jit(
        pk.nu, entry_level, seeds, ts, scw, tcw,
        *cp.expand_operands(pk, entry_level),
    )
    return words[: kb.k]


def _eval_full_pallas_chunked(kb: KeyBatchFast, entry_level: int, n_chunks: int):
    """Kernel path for domains whose leaves exceed the materialization cap:
    one XLA prefix to ``entry_level``, then the kernel finishes node-range
    chunks of the entry state (independent GGM subtrees) under one compiled
    function per chunk shape.  Mirrors the XLA chunk loop below."""
    from ..ops import chacha_pallas as cp
    from ..parallel.sharding import _pad_fast_batch

    pk = _pad_fast_batch(kb, (-kb.k) % cp._EKT)
    nu, s = pk.nu, entry_level
    seeds, ts, scw, tcw, _ = pk.device_args()
    S, T = _expand_prefix_cc_jit(s, seeds, ts, scw, tcw)
    ops = cp.expand_operands(pk, s)
    wc = (1 << s) // n_chunks
    from ..core import plans

    fin = (
        _finish_pk_chunks_donated_jit
        if plans.donation_enabled()
        else _finish_pk_chunks_jit
    )
    words = fin(nu, s, n_chunks, wc, *S, T, *ops)
    return words[: kb.k]


def eval_full_device(
    kb: KeyBatchFast,
    max_leaf_nodes: int = MAX_LEAF_NODES,
    backend: str | None = None,
    fuse: int | None = None,
):
    """Full-domain evaluation on device -> uint32[K, 2^nu, 16] leaf words
    (word j of leaf w holds domain bits [512w + 32j, +32), LSB-first).

    ``backend``: 'pallas' (TPU default; env DPF_TPU_FAST) runs the deep
    levels + leaf convert in the VMEM-resident kernel; 'xla' is the
    fallback/reference pipeline.  A 'pallas' request degrades to 'xla'
    when the kernel is ineligible (nu < 7, or the padded-key leaf
    materialization would blow the cap and the chunked XLA pipeline must
    take over) — outputs are identical either way.

    ``fuse`` (None = DPF_TPU_FUSE, 0 = off, g >= 1): cover the mid levels
    between the 128-node entry and the tail kernel with G-level fused
    groups (deep trees, nu > 12).  Explicit ``fuse`` re-raises kernel
    failures; env-auto routing degrades via the sticky latch."""
    nu = kb.nu
    total = kb.k << nu
    from ..ops import chacha_pallas as cp

    backend = backend or cp.expand_backend()
    if backend not in ("xla", "pallas"):
        raise ValueError(f"dpf-fast: unknown backend {backend!r}")
    eligible, entry_level, _ = cp.expand_plan(nu, kb.k, max_leaf_nodes)
    if backend == "pallas":
        if eligible and entry_level == 0:
            # TPU-only whole-tree route, not coverable by interpreter
            # tests: degrade to the classic plan if Mosaic rejects it.
            try:
                return _eval_full_pallas_device(kb, entry_level)
            except Exception as e:  # noqa: BLE001
                cp.small_tree_degraded(e)
                return eval_full_device(kb, max_leaf_nodes, backend)
        if eligible:
            sched = _fuse_plan_cc(nu, fuse)
            if sched is not None:
                try:
                    return _eval_full_pallas_fused(kb, sched)
                except Exception as e:  # noqa: BLE001
                    if fuse is not None:
                        raise
                    _fuse_cc_degraded(e)
            return _eval_full_pallas_device(kb, entry_level)
        ok_c, s_c, _, n_chunks = cp.expand_plan_chunked(
            nu, kb.k, max_leaf_nodes
        )
        if ok_c:
            return _eval_full_pallas_chunked(kb, s_c, n_chunks)
    args = kb.device_args()
    if total <= max_leaf_nodes:
        return _eval_full_cc_jit(nu, *args)
    seeds, ts, scw, tcw, fcw = args
    n_chunks = -(-total // max_leaf_nodes)
    c = min((n_chunks - 1).bit_length(), nu)
    S, T = _expand_prefix_cc_jit(c, seeds, ts, scw, tcw)
    from ..core import plans

    fin = (
        _finish_chunks_cc_scan_donated_jit
        if plans.donation_enabled()
        else _finish_chunks_cc_scan_jit
    )
    return fin(nu - c, c, *S, T, scw, tcw, fcw)


def eval_full(
    kb: KeyBatchFast,
    max_leaf_nodes: int = MAX_LEAF_NODES,
    backend: str | None = None,
    fuse: int | None = None,
) -> np.ndarray:
    """Full-domain evaluation -> uint8[K, out_bytes] bit-packed
    (out_bytes = 2^(log_n-3), min 64), byte-identical to the spec
    ``chacha_np.eval_full`` per key.  Domains too large to materialize in
    one pass split into independent GGM subtree chunks."""
    # host-sync: final reply marshalling (full-domain words)
    words = np.asarray(eval_full_device(kb, max_leaf_nodes, backend, fuse))
    return np.ascontiguousarray(words).view("<u1").reshape(kb.k, -1)


def eval_full_stream(
    kb: KeyBatchFast,
    max_leaf_nodes: int = MAX_LEAF_NODES,
    min_chunks: int = 2,
    events: list | None = None,
    timer=None,
):
    """Fast-profile twin of models/dpf.eval_full_stream: double-buffered
    per-subtree-chunk finish with the D2H of finished chunks overlapping
    the next chunk's compute.  Yields uint8[K, chunk_bytes] blocks whose
    axis-1 concatenation is byte-identical to :func:`eval_full`.  The
    per-chunk finish runs the XLA level body (a W=1 chunk entry cannot
    grow inside the expand kernel off the TPU-only small-tree route —
    docs/DESIGN.md compile trap (b)); streaming trades peak device rate
    for time-to-first-byte, which on the 40 MB/s serving link is the
    binding constraint.  ``events`` / ``timer`` follow the shared
    driver's protocol (core/stream.stream_chunks)."""
    from ..core import plans
    from ..core.stream import chunk_levels, stream_chunks

    nu = kb.nu
    c = chunk_levels(kb.k << nu, max_leaf_nodes, min_chunks, nu)

    def to_rows(words):
        return np.ascontiguousarray(words).view("<u1").reshape(kb.k, -1)

    if c == 0:
        yield from stream_chunks(
            0, lambda j: eval_full_device(kb, max_leaf_nodes), to_rows,
            events, timer,
        )
        return

    seeds, ts, scw, tcw, fcw = kb.device_args()
    S, T = _expand_prefix_cc_jit(c, seeds, ts, scw, tcw)
    fin = (
        _finish_chunk_cc_donated_jit
        if plans.donation_enabled()
        else _finish_chunk_cc_jit
    )

    def dispatch(j):
        return fin(
            nu - c, c, [s[:, j : j + 1] for s in S], T[:, j : j + 1],
            scw, tcw, fcw,
        )

    yield from stream_chunks(c, dispatch, to_rows, events, timer)


def _eval_points_cc_body(
    nu, log_n, seeds, ts, scw, tcw, fcw, xs_hi, xs_lo, level_groups=0,
    vcw=None,
):
    """Query-major path walk: xs_hi/xs_lo uint32[Q, K] (the query index
    split in halves — JAX runs 32-bit by default and the domain index can
    exceed 2^32, log_n up to 63; for log_n <= 32 the caller passes a [1, 1]
    dummy xs_hi that is never read) -> uint8[Q, K] output bits.

    Layout choices that matter at config-3/5 scale: the per-level descent
    bit is extracted ON DEVICE with a static shift (the level loop is
    unrolled), and the in-leaf index comes from xs_lo's low bits — so the
    host uploads exactly ONE uint32[Q, K] query tensor per call instead of
    a [nu, K, Q] path-bit tensor plus two index tensors.  Host-side prep
    and H2D transfer through the device tunnel dominated this entry point
    before (seconds per call vs ~100 ms of device work); key material is
    uploaded once per batch (KeyBatchFast.device_args memoizes).

    ``level_groups`` (static) serves the FSS comparison gates (models/
    fss.py): nonzero means the K keys are ``level_groups`` level-major
    repeats of G underlying gates (K = level_groups * n_levels * G with
    levels arranged key-major blocks of G), xs is uint32[Q, G], and the
    level-i block's query is x with its low ``log_n - 1 - i`` bits zeroed.
    The masking collapses to ANDing the descent bit with the trace-time
    constant ``1{walk level j <= block level i}`` — so the host never
    replicates the query tensor n times (for n=32 gates that replication
    plus its upload cost more than the whole device walk).

    ``vcw`` (uint32[K, nu] per-level value CWs) switches the walk into DCF
    mode (models/dcf.py): the node PRG's value word accumulates on left
    descents and the leaf bit folds into the accumulator; ``fcw`` then
    carries the DCF's final value correction.  Mutually exclusive with
    ``level_groups``.
    """
    dcf = vcw is not None
    if dcf and level_groups:
        raise ValueError("dcf walk does not support level grouping")
    low = xs_lo & np.uint32(cc.LEAF_BITS - 1)
    if level_groups:
        K = seeds.shape[0]
        Q, G = xs_lo.shape
        # Per-key level index + in-leaf prefix mask, shared with the Pallas
        # walk kernel (core/chacha_np.grouped_masks) — host constants,
        # folded at trace time.
        key_level, lowmask = cc.grouped_masks(K, G, log_n)
        low = jnp.tile(low, (1, K // G)) & jnp.asarray(lowmask)[None, :]
        shp = (Q, K)
    else:
        shp = low.shape
    S = [jnp.broadcast_to(seeds[None, :, i], shp) for i in range(4)]
    T = jnp.broadcast_to(ts[None, :], shp)
    acc = jnp.zeros(shp, jnp.uint32)
    for i in range(nu):
        if dcf:
            L, R, v = _prg_expand_v(S, unroll=_POINTS_UNROLL)
        else:
            L, R = _prg_expand(S, unroll=_POINTS_UNROLL)
        tl = L[0] & np.uint32(1)
        tr = R[0] & np.uint32(1)
        L[0] = L[0] & ~np.uint32(1)
        R[0] = R[0] & ~np.uint32(1)
        msk = jnp.uint32(0) - T
        L = [L[w] ^ (scw[None, :, i, w] & msk) for w in range(4)]
        R = [R[w] ^ (scw[None, :, i, w] & msk) for w in range(4)]
        tl = tl ^ (tcw[None, :, i, 0] & T)
        tr = tr ^ (tcw[None, :, i, 1] & T)
        b = log_n - 1 - i  # static per level
        if b >= 32:
            pbit = (xs_hi >> np.uint32(b - 32)) & np.uint32(1)
        else:
            pbit = (xs_lo >> np.uint32(b)) & np.uint32(1)
        if level_groups:
            keep = jnp.asarray((key_level >= i).astype(np.uint32))  # [K//... G-tiled]
            pbit = jnp.tile(pbit, (1, K // G)) & keep[None, :]
        if dcf:
            acc = acc ^ (
                (v ^ (vcw[None, :, i] & T))
                & np.uint32(1)
                & (np.uint32(1) - pbit)
            )
        bm = jnp.uint32(0) - pbit
        S = [(R[w] & bm) | (L[w] & ~bm) for w in range(4)]
        T = (tr & bm) | (tl & ~bm)
    out = _convert(S, unroll=_POINTS_UNROLL)  # 16x [Q, K]
    msk = jnp.uint32(0) - T
    out = [out[j] ^ (fcw[None, :, j] & msk) for j in range(16)]
    widx = (low >> 5) & 15
    w = jnp.stack(out, axis=2)  # [Q, K, 16]
    sel = jnp.take_along_axis(w, widx[:, :, None].astype(jnp.int32), axis=2)[:, :, 0]
    bit = (sel >> (low & 31)) & 1
    return ((acc ^ bit) if dcf else bit).astype(jnp.uint8)


_eval_points_cc_jit = partial(jax.jit, static_argnums=(0, 1, 9))(
    _eval_points_cc_body
)


def _eval_points_cc_packed_body(
    nu, log_n, seeds, ts, scw, tcw, fcw, xs_hi, xs_lo, level_groups=0,
    vcw=None,
):
    """Packed twin of the XLA walk body (also the DCF XLA route via
    ``vcw``): the query-major [Q, K] bits pack into uint32[K, Q/32] words
    ON DEVICE (core/bitpack; the caller pads Q to 32), so the D2H
    transfer is the packed words — the same 32x cut the walk kernel's
    packed route gets."""
    bits = _eval_points_cc_body(
        nu, log_n, seeds, ts, scw, tcw, fcw, xs_hi, xs_lo, level_groups, vcw
    )
    return bitpack.pack_bits_qmajor_jnp(bits)


_eval_points_cc_packed_jit = partial(jax.jit, static_argnums=(0, 1, 9))(
    _eval_points_cc_packed_body
)


def _split_queries(xs: np.ndarray, log_n: int):
    """uint64[A, B] -> (xs_hi, xs_lo) device operands of the transposed
    queries (xs_hi is a never-read [1,1] dummy when log_n <= 32)."""
    xs_t = np.ascontiguousarray(xs.T)
    xs_lo = jnp.asarray((xs_t & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if log_n > 32:
        xs_hi = jnp.asarray((xs_t >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, 1), jnp.uint32)
    return xs_hi, xs_lo


def _use_walk_kernel(k: int) -> bool:
    from ..ops import chacha_pallas as cp

    return cp.points_backend() == "pallas" and cp.usable(k)


def eval_points(
    kb: KeyBatchFast, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """Batched pointwise evaluation: xs uint64[K, Q] -> uint8[K, Q].

    On TPU (key counts divisible by 128) the whole walk runs as one Pallas
    kernel (ops/chacha_pallas.py) — state in VMEM instead of an HBM round
    trip per fused op; the XLA body is the fallback and A/B reference
    (DPF_TPU_POINTS=xla).  ``packed=True`` returns bit-packed words
    uint32[K, ceil(Q/32)] instead (query q at word q//32, bit q%32,
    LSB-first, tail bits zero — core/bitpack.py), packed ON DEVICE so the
    D2H transfer shrinks 32x; the byte-per-bit return is a thin unpack of
    the same bits."""
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2 or xs.shape[0] != kb.k:
        raise ValueError("dpf-fast: xs must be [K, Q]")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dpf-fast: query index out of domain")
    if _use_walk_kernel(kb.k):
        from ..ops import chacha_pallas as cp

        return cp.eval_points_walk(kb, xs, packed=packed)
    if packed:
        return _eval_points_cc_packed(kb, xs)
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)
    bits = _eval_points_cc_jit(
        kb.nu, kb.log_n, *kb.device_args(), xs_hi, xs_lo
    )
    return np.asarray(bits).T  # host-sync: final reply marshalling


def _eval_points_cc_packed(
    kb, xs: np.ndarray, level_groups: int = 0, vcw=None
) -> np.ndarray:
    """XLA-body packed route shared by the DPF and DCF (``vcw``) walks:
    pad Q to whole words, pack on device, mask the tail bits."""
    Q = xs.shape[1]
    pad_q = (-Q) % 32
    if pad_q:
        xs = np.concatenate(
            [xs, np.zeros((xs.shape[0], pad_q), np.uint64)], axis=1
        )
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)
    words = _eval_points_cc_packed_jit(
        kb.nu, kb.log_n, *kb.device_args(), xs_hi, xs_lo, level_groups, vcw
    )
    # host-sync: final reply marshalling (packed words)
    return bitpack.mask_tail(np.asarray(words), Q)


def eval_points_level_grouped(
    kb: KeyBatchFast, xs: np.ndarray, groups: int, reduce: bool = False,
    packed: bool = False, levels=None,
) -> np.ndarray:
    """FSS-support pointwise evaluation over level-major key groups.

    ``kb`` holds ``groups * log_n * G`` keys arranged as ``groups`` repeats
    of ``log_n`` level-major blocks of ``G`` gates (models/fss.py layout);
    ``xs`` is the RAW gate queries uint64[G, Q].  Key ``i*G + g`` of each
    group is evaluated at xs[g] with its low ``log_n - 1 - i`` bits zeroed
    (the dyadic-prefix query) — the masking happens on device against
    trace-time constants, so neither the host nor the wire ever sees the
    level-replicated query tensor.  -> uint8[groups * log_n * G, Q]; with
    ``reduce`` the level/group blocks are XOR-folded into gate shares
    -> uint8[G, Q] (on device when the Pallas walk kernel is in use — the
    D2H transfer shrinks by groups * log_n).  ``packed`` returns the same
    rows as uint32[., ceil(Q/32)] packed words (device-side pack,
    core/bitpack contract).

    ``levels`` (optional tuple of level indices) selects a SUBSET of
    level blocks — ``kb`` holds ``groups * len(levels) * G`` keys and
    block ``j`` masks its queries to level ``levels[j]`` — the per-round
    heavy-hitters eval (apps/heavy_hitters.py; see the compat twin,
    models/dpf.py, for the contract).  The subset path masks host-side
    and delegates to :func:`eval_points` (the same certified bodies)."""
    xs = np.asarray(xs, dtype=np.uint64)
    if xs.ndim != 2:
        raise ValueError("dpf-fast: xs must be [G, Q]")
    if levels is not None:
        from .dpf import _masked_level_queries

        lv = tuple(int(i) for i in levels)
        if not lv or any(i < 0 or i >= kb.log_n for i in lv):
            raise ValueError(
                "dpf-fast: levels must be non-empty, in [0, log_n)"
            )
        if kb.k != groups * len(lv) * xs.shape[0]:
            raise ValueError(
                "dpf-fast: key count != groups * len(levels) * G"
            )
        if (xs >> np.uint64(kb.log_n)).any():
            raise ValueError("dpf-fast: query index out of domain")
        out = eval_points(
            kb, _masked_level_queries(xs, kb.log_n, lv, groups),
            packed=packed,
        )
        if reduce:
            out = np.bitwise_xor.reduce(
                out.reshape(groups * len(lv), xs.shape[0], -1), axis=0
            )
        return out
    if kb.k != groups * kb.log_n * xs.shape[0]:
        raise ValueError("dpf-fast: key count != groups * log_n * G")
    if (xs >> np.uint64(kb.log_n)).any():
        raise ValueError("dpf-fast: query index out of domain")
    G = xs.shape[0]
    if _use_walk_kernel(kb.k):
        from ..ops import chacha_pallas as cp

        return cp.eval_points_walk(
            kb, xs, groups=groups, reduce=reduce, packed=packed
        )
    if packed:
        words = _eval_points_cc_packed(kb, xs, level_groups=groups)
        if reduce:  # XOR-fold commutes with the packing — fold the words
            words = np.bitwise_xor.reduce(
                words.reshape(groups * kb.log_n, G, -1), axis=0
            )
        return words
    xs_hi, xs_lo = _split_queries(xs, kb.log_n)
    bits = _eval_points_cc_jit(
        kb.nu, kb.log_n, *kb.device_args(), xs_hi, xs_lo,
        level_groups=groups,
    )
    out = np.asarray(bits).T  # host-sync: final reply marshalling
    if reduce:
        return np.bitwise_xor.reduce(
            out.reshape(groups * kb.log_n, G, -1), axis=0
        )
    return out
