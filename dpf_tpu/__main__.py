"""CLI / profiling driver — the TPU-native analogue of dpf_main.go.

The reference driver parses one flag, optionally starts a pprof CPU
profile, runs Gen(123, 27) and 100 x EvalFull, and prints wall time
(dpf_main.go:13-31).  This driver does the equivalent end-to-end run on the
accelerator — batched, since a TPU amortizes launches over keys — with an
XProf trace dir in place of the pprof file and a per-phase breakdown in
place of the single wall-time print.

    python -m dpf_tpu [--trace DIR] [--log-n N] [--keys K] [--reps R]
"""

from __future__ import annotations

import argparse

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser(prog="dpf_tpu", description=__doc__)
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="write an XProf trace here (analogue of -cpuprofile)")
    p.add_argument("--log-n", type=int, default=20)
    p.add_argument("--keys", type=int, default=256)
    p.add_argument("--reps", type=int, default=10)
    args = p.parse_args()

    import jax

    from dpf_tpu.core.keys import gen_batch
    from dpf_tpu.models.dpf import DeviceKeys, eval_full_device
    from dpf_tpu.utils.profiling import PhaseTimer, leaves_per_sec, trace

    tm = PhaseTimer()
    rng = np.random.default_rng(123)
    with tm.phase("gen (host)"):
        alphas = rng.integers(0, 1 << args.log_n, size=args.keys, dtype=np.uint64)
        ka, _ = gen_batch(alphas, args.log_n, rng=rng)
    with tm.phase("pack + h2d"):
        dk = DeviceKeys(ka)
        jax.block_until_ready(dk.seed_planes)

    def run():
        # Chunked public evaluator: splits oversized domains into subtrees.
        return eval_full_device(dk)

    with tm.phase("compile + warmup"):
        jax.block_until_ready(run())
    with trace(args.trace):
        with tm.phase("evalfull (device)"):
            for _ in range(args.reps):
                out = run()
            jax.block_until_ready(out)
    with tm.phase("d2h"):
        np.asarray(out)

    per_rep = tm.phases["evalfull (device)"] / args.reps
    print(
        f"EvalFull time {per_rep * 1e3:.3f} ms "
        f"(K={args.keys}, n={args.log_n}, {args.reps} reps, "
        f"{leaves_per_sec(args.keys, args.log_n, per_rep) / 1e9:.2f} Gleaves/s "
        f"on {jax.devices()[0].platform})"
    )
    print(tm.report())


if __name__ == "__main__":
    main()
