"""Bitsliced AES-128 / AES-128-MMO for the TPU VPU (JAX).

TPUs have no AES instructions, so the DPF's fixed-key AES-MMO PRG (reference:
dpf/aes_amd64.s:51-82) is re-designed rather than translated: blocks live as
**128 bit-planes**, each plane a ``uint32`` tensor whose 32 lanes are 32
independent blocks.  One vector op then advances 32 blocks at once, and the
whole cipher is a fixed DAG of XOR/AND/NOT ops — exactly what the VPU's 8x128
lanes want, with no tables, no gathers, no data-dependent control flow.

Layout
------
State ``S``: ``uint32[128, B]``.  Plane index ``p = 8 * byte_pos + bit`` with
``bit`` LSB-first, i.e. plane ``p`` holds domain-bit ``p`` of each block.
Lane word ``S[p, b]`` packs blocks ``32b .. 32b+31`` (bit ``j`` = block
``32b + j``).

- AddRoundKey: round keys are *constants* (the DPF's two PRF keys are fixed,
  reference dpf/dpf.go:23-24), so each round key is a ``[128]`` mask of
  0/0xFFFFFFFF and AddRoundKey is one XOR of the state with a broadcast
  constant.
- SubBytes: Boyar-Peralta 113-gate circuit (`sbox_circuit.sbox_bp113`),
  vectorized over the 16 byte positions and the batch.
- ShiftRows: a static permutation of the byte axis — free at trace time.
- MixColumns: rolls along the row axis + xtime as a bit-axis rotation with
  two conditional plane XORs.

Packing between byte-blocks and bit-planes uses a vectorized 32x32
bit-matrix transpose (Hacker's Delight transpose32), ~0.8 ops/word, so
pack/unpack is <2% of the AES cost.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import aes_np
from .sbox_circuit import active_sbox

# ---------------------------------------------------------------------------
# Round-key plane masks (compile-time constants)
# ---------------------------------------------------------------------------


def round_key_masks(round_keys: np.ndarray) -> np.ndarray:
    """[11, 16]-byte round keys -> [11, 128] uint32 masks (0 / 0xFFFFFFFF)."""
    rk = np.asarray(round_keys, dtype=np.uint8).reshape(11, 16)
    bits = (rk[:, :, None] >> np.arange(8)) & 1  # [11, 16, 8]
    return (bits.reshape(11, 128) * np.uint32(0xFFFFFFFF)).astype(np.uint32)


RK_MASKS_L: np.ndarray = round_key_masks(aes_np.ROUND_KEYS_L)
RK_MASKS_R: np.ndarray = round_key_masks(aes_np.ROUND_KEYS_R)

# ShiftRows as a flat permutation of the 128 planes.
_SHIFT_PLANES = (
    np.repeat(aes_np.SHIFT_ROWS_PERM * 8, 8) + np.tile(np.arange(8), 16)
).astype(np.int32)

# Bit positions that absorb the carry in xtime (reduction poly 0x11B).
_XTIME_CARRY = np.zeros(8, dtype=bool)
_XTIME_CARRY[[1, 3, 4]] = True  # position 0 gets a7 straight from the rotation


# ---------------------------------------------------------------------------
# Cipher rounds on planes
# ---------------------------------------------------------------------------


def _sub_bytes(S: jax.Array) -> jax.Array:
    """S-box on all 16 bytes: [128, B] -> [128, B].  The circuit is the
    DPF_TPU_SBOX-selected schedule (sbox_circuit.active_sbox), read at
    trace time — shared with every Pallas kernel variant."""
    s = S.reshape(16, 8, -1)
    # Circuit wants MSB-first planes; our bit axis is LSB-first.
    x = [s[:, 7 - i] for i in range(8)]
    y = active_sbox()(x)
    return jnp.stack(y[::-1], axis=1).reshape(128, -1)


def _shift_rows(S: jax.Array) -> jax.Array:
    return S[_SHIFT_PLANES]


def _xtime(a: jax.Array) -> jax.Array:
    """Multiply by 0x02 in GF(2^8) on a [..., 8, B] bit axis."""
    rot = jnp.roll(a, 1, axis=-2)  # rot[..., k, :] = a[..., k-1, :]; k=0 gets a7
    a7 = a[..., 7:8, :]
    carry = jnp.where(_XTIME_CARRY[:, None], a7, jnp.uint32(0))
    return rot ^ carry


def _mix_columns(S: jax.Array) -> jax.Array:
    s = S.reshape(4, 4, 8, -1)  # [column, row, bit, B]
    r1 = jnp.roll(s, -1, axis=1)
    r2 = jnp.roll(s, -2, axis=1)
    r3 = jnp.roll(s, -3, axis=1)
    out = _xtime(s) ^ _xtime(r1) ^ r1 ^ r2 ^ r3  # 2*a_r + 3*a_{r+1} + a_{r+2} + a_{r+3}
    return out.reshape(128, -1)


def aes128_encrypt_planes(S: jax.Array, rk_masks: np.ndarray) -> jax.Array:
    """AES-128 on bitsliced state [128, B] with constant round-key masks."""
    rk = jnp.asarray(rk_masks)
    S = S ^ rk[0][:, None]
    for rnd in range(1, 10):
        S = _sub_bytes(S)
        S = _shift_rows(S)
        S = _mix_columns(S)
        S = S ^ rk[rnd][:, None]
    S = _sub_bytes(S)
    S = _shift_rows(S)
    return S ^ rk[10][:, None]


def aes128_mmo_planes(S: jax.Array, rk_masks: np.ndarray) -> jax.Array:
    """Matyas-Meyer-Oseas: ``E_k(x) ^ x`` on bitsliced state."""
    return aes128_encrypt_planes(S, rk_masks) ^ S


def prg_planes(S: jax.Array) -> tuple[jax.Array, jax.Array]:
    """DPF length-doubling PRG: both fixed-key MMO expansions of the same
    seeds (reference dpf/dpf.go:59-69, minus the t-bit handling which the
    evaluator owns).  Returns (left, right) children as planes."""
    return aes128_mmo_planes(S, RK_MASKS_L), aes128_mmo_planes(S, RK_MASKS_R)


# ---------------------------------------------------------------------------
# Bit-matrix transpose and pack/unpack
# ---------------------------------------------------------------------------


def _anti_transpose32(A: jax.Array) -> jax.Array:
    """Hacker's Delight fig. 7-3 in sliced form.  Under LSB-first bit
    indexing this computes the anti-transpose: out[i] bit j = A[31-j]
    bit (31-i).  It is an involution."""
    m = jnp.uint32(0x0000FFFF)
    j = 16
    B = A.shape[1:]
    while j:
        A = A.reshape((32 // (2 * j), 2, j) + B)
        t = (A[:, 0] ^ (A[:, 1] >> j)) & m
        A = jnp.stack([A[:, 0] ^ t, A[:, 1] ^ (t << j)], axis=1)
        A = A.reshape((32,) + B)
        j >>= 1
        m = m ^ (m << j)
    return A


def transpose32(A: jax.Array) -> jax.Array:
    """True 32x32 bit-matrix transpose on uint32[32, ...] rows, LSB-first:
    bit j of out[i] = bit i of A[j].  Vectorized over trailing axes."""
    return _anti_transpose32(A[::-1])[::-1]


def pack_padded_keys(blocks_words: jax.Array) -> jax.Array:
    """uint32[K, N, 4] block words (K multiple of 32) -> planes
    uint32[128, N, K//32] packed over the key axis."""
    K, N, _ = blocks_words.shape
    assert K % 32 == 0
    g = blocks_words.reshape(K // 32, 32, N, 4)
    g = jnp.moveaxis(g, 1, 0)  # [32, Kp, N, 4], rows = key-within-group j
    t = transpose32(g)  # t[i, kp, n, q]: bit j = bit i of key (32kp+j)'s word q
    t = jnp.moveaxis(t, (3, 0), (0, 1))  # [q, i, kp, n]
    t = t.reshape(128, K // 32, N)  # plane p = 32q + i
    return jnp.swapaxes(t, 1, 2)


def unpack_planes(planes: jax.Array) -> jax.Array:
    """planes uint32[128, N, Kp] -> per-key block words uint32[K, N, 4].

    Word q of key k at node n = planes[32q..32q+32, n, k // 32] bit (k % 32),
    i.e. four 32x32 bit transposes."""
    _, N, Kp = planes.shape
    p = planes.reshape(4, 32, N, Kp)  # [q, i, n, kp]
    t = jax.vmap(transpose32)(p)  # [q, j, n, kp]: bit i of t[q, j] = plane 32q+i of key j
    t = jnp.moveaxis(t, (3, 1), (0, 1))  # [kp, j, q=?...]
    # after moveaxis: axes (kp, j, q, n)
    t = t.reshape(Kp * 32, 4, N)
    return jnp.swapaxes(t, 1, 2)  # [K, N, 4]


# Host-side (NumPy) reference pack/unpack for tests and small inputs. -------


def pack_blocks_np(blocks: np.ndarray) -> np.ndarray:
    """uint8[N, 16] blocks -> planes uint32[128, ceil(N/32)] packed over the
    block axis (plane p bit j of word w = domain-bit p of block 32w+j)."""
    blocks = np.asarray(blocks, dtype=np.uint8)
    n = blocks.shape[0]
    pad = (-n) % 32
    if pad:
        blocks = np.concatenate([blocks, np.zeros((pad, 16), np.uint8)])
    bits = (blocks[:, :, None] >> np.arange(8)) & 1  # [N, 16, 8]
    bits = bits.reshape(-1, 128).T  # [128, N]
    bits = bits.reshape(128, -1, 32).astype(np.uint32)
    return (bits << np.arange(32, dtype=np.uint32)).sum(axis=2, dtype=np.uint32)


def unpack_blocks_np(planes: np.ndarray, n: int) -> np.ndarray:
    """planes uint32[128, W] -> uint8[n, 16] blocks (inverse of pack)."""
    planes = np.asarray(planes, dtype=np.uint32)
    bits = (planes[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1  # [128, W, 32]
    bits = bits.reshape(128, -1).T[:n]  # [n, 128]
    bytes_ = (bits.reshape(n, 16, 8) << np.arange(8)).sum(axis=2)
    return bytes_.astype(np.uint8)
