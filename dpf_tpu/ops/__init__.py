"""Kernel-layer shared knobs.

``DPF_TPU_FUSE`` selects the level-fused expansion backend for BOTH
profiles (models/dpf.py and models/dpf_chacha.py):

    off      per-level pipeline (current default until the on-hardware A/B
             promotes fused — tpu_when_up.sh's fused_ab step)
    auto     fused groups sized by the profile's VMEM-budget model on TPU,
             off elsewhere (interpret-mode fused kernels are for tests,
             which opt in explicitly)
    <int g>  fused groups of exactly <= g levels, FORCED: a lowering
             failure re-raises instead of latching the per-level fallback,
             so A/Bs never silently measure the fallback

The parse lives here (not in aes_pallas/chacha_pallas) because both
profiles share the knob but own separate budget models.
"""

from __future__ import annotations

from ..core import knobs


def fuse_request(auto_g: int = 0) -> int:
    """Requested fused-group size: 0 = off, g >= 1 = groups of <= g levels.
    ``auto_g`` is the caller's VMEM-budget cap (pass 0 off-TPU)."""
    env = knobs.get_str("DPF_TPU_FUSE")
    if env == "off":
        return 0
    if env == "auto":
        return auto_g
    try:
        g = int(env)
    except ValueError:
        raise ValueError(
            f"DPF_TPU_FUSE={env!r} invalid; use off|auto|<levels>"
        ) from None
    if g < 0:
        raise ValueError("DPF_TPU_FUSE must be >= 0")
    return g


def fuse_forced() -> bool:
    """True when DPF_TPU_FUSE names an explicit group size — the fused
    path must then re-raise on failure rather than latch the per-level
    fallback (mirrors aes_pallas.walk_forced)."""
    return knobs.get_str("DPF_TPU_FUSE") not in ("off", "auto")


def deinterleave_nodes(x, levels: int, wt: int):
    """Restore ascending node order on the LAST axis after a block-order
    expansion kernel (ONE implementation for both ciphers' kernels).

    Inside a tile the kernels emit children in block order [all-L|all-R]
    per level: local position = j' * wt + w with j' the level-choice bits
    in REVERSE significance; the true local child index is
    w * 2^levels + rev(j').  One static bit-reversal gather + axis swap
    per array fixes it.  ``wt`` is the kernel's ENTRY node-tile width.
    Leading dims ride along: [K, W] for the chacha word arrays
    (chacha_pallas.deinterleave_leaves), [128, Kp, W] / [Kp, W] for the
    compat fused layout (aes_pallas.fused_deinterleave)."""
    if levels == 0:
        return x
    import jax.numpy as jnp
    import numpy as np

    n2 = 1 << levels
    rev = np.zeros(n2, np.int32)
    for j in range(n2):
        rev[j] = int(format(j, f"0{levels}b")[::-1], 2)
    lead = x.shape[:-1]
    x = x.reshape(*lead, -1, n2, wt)[..., rev, :]
    return jnp.swapaxes(x, -2, -1).reshape(*lead, -1)
