"""Pallas TPU kernels for the fast profile: pointwise walk + expansion.

The XLA pointwise body (models/dpf_chacha._eval_points_cc_body) materializes
its [Q, K] lane state in HBM between fused ops: ~24 ChaCha cores per query
lane, each a separate read-modify-write of up to 16 state words (64 MB at
config-3 scale) — the walk runs at <10% of the op rate the expansion
sustains.  This kernel runs the ENTIRE root-to-leaf walk (all ``nu`` levels
plus leaf conversion and in-leaf bit selection — the reference's Eval loop,
dpf/dpf.go:171-211, vectorized over (query, key) lanes) inside one Pallas
program per [QT, KT] tile: seeds and correction words are read from
HBM once per tile, the 16-word ChaCha state lives in VMEM/registers, and
one uint32 0/1 bit per lane is written back.

Operand layout is key-minor (rows x K lanes) so every per-key constant is a
natural [rows, KT] VMEM block:

    meta   uint32[3, K]        rows: t bits | key_level | in-leaf low mask
    seeds  uint32[4, K]        seed words
    scw    uint32[max(4 nu,4), K]   row 4 i + w = level-i seed-CW word w
    tcw    uint32[max(2 nu,2), K]   rows 2 i / 2 i + 1 = level-i tL / tR CW
    fcw    uint32[16, K]       final-CW words
    xs     uint32[Q, K]        query indices (low words; high only n > 32)

``key_level``/``lowmask`` fold the FSS dyadic-prefix masking (models/fss.py)
into the same kernel: level-grouped gate batches set key_level[k] = the
key's level i (descent bits below it are ANDed away) and lowmask to the
level's in-leaf prefix mask; plain pointwise batches pass log_n / 511.

Off-TPU the kernel runs in interpreter mode (tests); the XLA body remains
the fallback for key counts not divisible by 128 and is selectable via
``DPF_TPU_POINTS=xla``.

The EXPANSION kernel (``expand_convert``) applies the same VMEM-residency
idea to full-domain evaluation (the reference's EvalFull loop,
dpf/dpf.go:213-262, restructured breadth-first): the XLA expansion's
ChaCha double-round loop carries 16 x [K, W] words through HBM per
iteration — ~12 full-state HBM round trips per level, which makes the
whole expansion memory-bound.  The kernel takes a [KT, WT] tile of
level-``s`` seeds and runs ALL remaining levels plus leaf conversion in
VMEM, so HBM sees only the level-``s`` state once in and the leaf words
once out.  State is [keys (sublanes), nodes (lanes)] — the evaluator's
native layout, no transposes anywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..core import bitpack
from ..core import chacha_np as cc
from ..core import knobs

_C = [int(v) for v in cc._CONSTANTS]
_DSX = [int(v) for v in cc.DS_EXPAND]
_DSL = [int(v) for v in cc.DS_LEAF]

_KT = 128  # key-tile (lane) width
_QT_CAP = 128  # max query-tile rows; actual tile = largest divisor of Q

# Module-wide bound the '# vmem:' kernel footprint models are linted
# against (python -m dpf_tpu.analysis, pallas-jit pass): ~16 MB/core
# minus Mosaic's double-buffered I/O windows, matching the compat
# profile's budget model (aes_pallas._FUSE_VMEM_BUDGET).
_VMEM_BUDGET = 8 << 20


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def points_backend() -> str:
    """'pallas' | 'xla' for the pointwise walk (env DPF_TPU_POINTS)."""
    env = knobs.get_enum("DPF_TPU_POINTS")
    if env != "auto":
        return env
    return "pallas" if _on_tpu() else "xla"


def usable(k: int) -> bool:
    return k % _KT == 0


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


# In-kernel loop structure knobs (A/B'd end-to-end on the device; see
# scripts/bench_points_fast.py): unrolled rounds give Mosaic the whole
# ChaCha DAG to schedule instead of a serial fori_loop carry.
_UNROLL_ROUNDS = True
_UNROLL_LEVELS = False


def _cc_core(S, ds, n_out):
    """ChaCha12 with the fast-profile state layout on [QT, KT] word arrays;
    state stays in VMEM/registers in-kernel.  The double-round body is the
    shared one (core/chacha_np.double_round)."""
    z = jnp.zeros_like(S[0])
    init = (
        [z + np.uint32(v) for v in _C]
        + list(S)
        + [z + np.uint32(v) for v in ds]
        + [z, z, z, z]
    )

    def dbl(_, s):
        s = list(s)
        cc.double_round(s)
        return tuple(s)

    s = tuple(init)
    if _UNROLL_ROUNDS:
        for _ in range(cc.ROUNDS // 2):
            s = dbl(None, s)
    else:
        s = lax.fori_loop(0, cc.ROUNDS // 2, dbl, s)
    return [s[j] + init[j] for j in range(n_out)]


def _walk_kernel(
    meta_ref, seeds_ref, scw_ref, tcw_ref, vcw_ref, fcw_ref, xs_lo_ref,
    xs_hi_ref, out_ref, *, nu, log_n, dcf=False,
):
    """Whole-walk kernel.  ``dcf`` adds the DCF value accumulator
    (models/dcf.py): the node PRG emits one extra word whose LSB,
    corrected by the per-level VCW (vcw_ref, row i) and the parent control
    bit, XOR-accumulates whenever the query descends left; the leaf bit
    then folds into the accumulator instead of being the output itself."""
    QT, KT = out_ref.shape
    one = np.uint32(1)
    ts = meta_ref[0:1, :]
    kl = meta_ref[1:2, :]
    lowmask = meta_ref[2:3, :]
    xs_lo = xs_lo_ref[:]
    S = tuple(
        jnp.broadcast_to(seeds_ref[w : w + 1, :], (QT, KT)) for w in range(4)
    )
    T = jnp.broadcast_to(ts, (QT, KT))
    acc = jnp.zeros((QT, KT), jnp.uint32)

    def level(i, carry):
        S0, S1, S2, S3, T, acc = carry
        out = _cc_core([S0, S1, S2, S3], _DSX, 9 if dcf else 8)
        L, R = out[:4], out[4:8]
        tl = L[0] & one
        tr = R[0] & one
        L[0] = L[0] & ~one
        R[0] = R[0] & ~one
        msk = jnp.uint32(0) - T
        cw = scw_ref[pl.ds(4 * i, 4), :]  # [4, KT]
        tlcw = tcw_ref[pl.ds(2 * i, 1), :]  # [1, KT]
        trcw = tcw_ref[pl.ds(2 * i + 1, 1), :]
        L = [L[w] ^ (cw[w : w + 1, :] & msk) for w in range(4)]
        R = [R[w] ^ (cw[w : w + 1, :] & msk) for w in range(4)]
        tl = tl ^ (tlcw & T)
        tr = tr ^ (trcw & T)
        iu = np.uint32(i) if isinstance(i, int) else i.astype(jnp.uint32)
        bu = np.uint32(log_n - 1) - iu  # descent bit index, MSB-first
        if log_n <= 32:
            pbit = (xs_lo >> bu) & one
        else:
            p_lo = (xs_lo >> jnp.minimum(bu, np.uint32(31))) & one
            p_hi = (xs_hi_ref[:] >> jnp.where(
                bu >= np.uint32(32), bu - np.uint32(32), np.uint32(0)
            )) & one
            pbit = jnp.where(bu >= np.uint32(32), p_hi, p_lo)
        keep = jnp.where(kl >= iu, one, np.uint32(0))
        pbit = pbit & keep
        if dcf:
            vcw_i = vcw_ref[pl.ds(i, 1), :]  # [1, KT]
            acc = acc ^ ((out[8] ^ (vcw_i & T)) & one & (one - pbit))
        bm = jnp.uint32(0) - pbit
        S0, S1, S2, S3 = ((R[w] & bm) | (L[w] & ~bm) for w in range(4))
        T = (tr & bm) | (tl & ~bm)
        return S0, S1, S2, S3, T, acc

    carry = (*S, T, acc)
    if _UNROLL_LEVELS:
        for i in range(nu):
            carry = level(i, carry)
    else:
        carry = lax.fori_loop(0, nu, level, carry)
    S0, S1, S2, S3, T, acc = carry
    out = _cc_core([S0, S1, S2, S3], _DSL, 16)
    msk = jnp.uint32(0) - T
    low = xs_lo & np.uint32(cc.LEAF_BITS - 1) & lowmask
    widx = (low >> np.uint32(5)) & np.uint32(15)
    sel = jnp.zeros_like(xs_lo)
    for j in range(16):
        oj = out[j] ^ (fcw_ref[j : j + 1, :] & msk)
        sel = sel | (oj & (jnp.uint32(0) - (widx == j).astype(jnp.uint32)))
    out_ref[:] = acc ^ ((sel >> (low & np.uint32(31))) & one)


def _walk_raw(
    meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi, log_n, nu, qt,
    vcw_t=None, dcf=False,
):
    Q, K = xs_lo.shape
    # Callers are responsible for padding; an indivisible shape here would
    # silently run a truncated (or empty) grid and return wrong shares.
    if K % _KT != 0 or qt <= 0 or Q % qt != 0:
        raise ValueError(
            f"_walk_raw needs K % {_KT} == 0 and Q % qt == 0, "
            f"got K={K}, Q={Q}, qt={qt} (caller padding mismatch)"
        )
    if vcw_t is None:  # never read when dcf=False
        vcw_t = jnp.zeros((1, K), jnp.uint32)
    qspec = pl.BlockSpec((qt, _KT), lambda q, k: (q, k))

    def rows(n):
        return pl.BlockSpec((n, _KT), lambda q, k: (0, k))

    kern = functools.partial(_walk_kernel, nu=nu, log_n=log_n, dcf=dcf)
    # Worst-case residency at nu=64 on [_QT_CAP, _KT] query tiles:
    # xs_lo/xs_hi/out query slabs + the per-level CW rows (scw 4/level,
    # tcw 2/level, DCF vcw 4/level) + fcw/meta/seed rows; 2x I/O windows.
    # vmem: 2 * 4 * _KT * (3 * _QT_CAP + 10 * 64 + 23)
    return pl.pallas_call(
        kern,
        grid=(Q // qt, K // _KT),
        in_specs=[
            rows(3), rows(4), rows(scw_t.shape[0]), rows(tcw_t.shape[0]),
            rows(vcw_t.shape[0]), rows(16), qspec,
            qspec if log_n > 32 else rows(1),
        ],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((Q, K), jnp.uint32),
        interpret=not _on_tpu(),
    )(meta, seeds_t, scw_t, tcw_t, vcw_t, fcw_t, xs_lo, xs_hi)


@functools.partial(jax.jit, static_argnums=(7, 8, 9, 10))
def _walk_call(
    meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi, log_n, nu, qt,
    packed=False,
):
    # uint8 on device: the result crosses the host link (4x smaller D2H).
    # ``packed`` packs the [Q, K] bits into uint32[K, Q/32] words on
    # device instead (core/bitpack; Q padded to 32 by the caller) — 32x
    # smaller D2H than the uint8 bits, and already in the wire layout.
    bits = _walk_raw(
        meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi, log_n, nu, qt
    )
    if packed:
        return bitpack.pack_bits_qmajor_jnp(bits)
    return bits.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(7, 8, 9, 10, 11))
def _walk_call_reduced(
    meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi, log_n, nu, qt, g,
    packed=False,
):
    """Walk + on-device XOR-reduction over the level (and group) blocks of
    an FSS gate batch: [Q, K] bits -> uint8[Q, g].  The reduction is why
    this exists — an FSS answer is the XOR over a gate's level-DPFs
    (models/fss.py), and reducing before D2H shrinks the transfer by
    K/g (= groups * log_n, 64x at BASELINE config 5).  ``packed`` packs
    the reduced gate bits into uint32[g, Q/32] words on device — the two
    cuts compound (K/g * 32 less D2H than raw uint8 level bits)."""
    bits = _walk_raw(
        meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi, log_n, nu, qt
    )
    q, k = bits.shape
    gates = jax.lax.reduce(
        bits.reshape(q, k // g, g), np.uint32(0), jax.lax.bitwise_xor, (1,)
    )
    if packed:
        return bitpack.pack_bits_qmajor_jnp(gates)
    return gates.astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Host wrappers
# ---------------------------------------------------------------------------


def _walk_common_operands(kb, key_level, lowmask):
    """(meta, seeds_t, scw_t, tcw_t) in the kernel's key-minor layout —
    shared by the DPF (walk_operands) and DCF (dcf_walk_operands) routes
    so the operand layout has one definition."""
    k, nu = kb.k, kb.nu
    meta = jnp.asarray(
        np.stack([kb.ts.astype(np.uint32), key_level, lowmask])
    )
    seeds_t = jnp.asarray(np.ascontiguousarray(kb.seeds.T))
    if nu:
        scw_t = jnp.asarray(
            np.moveaxis(kb.scw, 0, 2).reshape(4 * nu, k)
        )
        tcw_t = jnp.asarray(
            np.moveaxis(kb.tcw.astype(np.uint32), 0, 2).reshape(2 * nu, k)
        )
    else:  # never read by the kernel (level loop is empty)
        scw_t = jnp.zeros((4, k), jnp.uint32)
        tcw_t = jnp.zeros((2, k), jnp.uint32)
    return meta, seeds_t, scw_t, tcw_t


def walk_operands(kb, groups: int = 0):
    """Transposed device operands for the walk kernel, memoized per key
    batch (key material is immutable once evaluated; the FSS layouts also
    depend only on (k, log_n, groups))."""
    cache = getattr(kb, "_walk_ops", None)
    if cache is None:
        cache = {}
        try:
            kb._walk_ops = cache
        except AttributeError:  # frozen dataclass; recompute per call
            pass
    if groups in cache:
        return cache[groups]
    k = kb.k
    if groups:
        g = k // (groups * kb.log_n)
        key_level, lowmask = cc.grouped_masks(k, g, kb.log_n)
    else:
        key_level = np.full(k, kb.log_n, np.uint32)
        lowmask = np.full(k, cc.LEAF_BITS - 1, np.uint32)
    meta, seeds_t, scw_t, tcw_t = _walk_common_operands(kb, key_level, lowmask)
    fcw_t = jnp.asarray(np.ascontiguousarray(kb.fcw.T))
    ops = (meta, seeds_t, scw_t, tcw_t, fcw_t)
    cache[groups] = ops
    return ops


def _qtile(q: int) -> int:
    qt = 8
    while qt < _QT_CAP and q % (qt * 2) == 0:
        qt *= 2
    return qt


def eval_points_walk(
    kb, xs: np.ndarray, groups: int = 0, reduce: bool = False,
    packed: bool = False,
) -> np.ndarray:
    """Pointwise walk via the Pallas kernel.

    ``xs`` is uint64[K, Q] for plain batches (groups=0) or the RAW gate
    queries uint64[G, Q] for level-grouped FSS batches — same contracts as
    models/dpf_chacha.eval_points / eval_points_level_grouped, which route
    here on TPU.  -> uint8[K, Q]; with ``reduce`` (grouped only) the level/
    group blocks are XOR-folded on device -> uint8[G, Q].  ``packed``
    returns the rows as uint32[., ceil(Q/32)] packed words instead, the
    pack done on device (core/bitpack contract; 32x less D2H)."""
    k = kb.k
    meta, seeds_t, scw_t, tcw_t, fcw_t = walk_operands(kb, groups)
    xs_t = np.ascontiguousarray(xs.T)  # [Q, G or K]
    q = xs_t.shape[0]
    pad_q = (-q) % 32 if packed else (-q) % 8
    if pad_q:
        xs_t = np.concatenate(
            [xs_t, np.zeros((pad_q,) + xs_t.shape[1:], xs_t.dtype)]
        )
    xs_lo = jnp.asarray((xs_t & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    rep = k // xs_t.shape[1]
    if rep > 1:  # level-grouped: queries repeat across level blocks
        xs_lo = jnp.tile(xs_lo, (1, rep))
    if kb.log_n > 32:
        xs_hi = jnp.asarray((xs_t >> np.uint64(32)).astype(np.uint32))
        if rep > 1:
            xs_hi = jnp.tile(xs_hi, (1, rep))
    else:
        xs_hi = jnp.zeros((1, k), jnp.uint32)  # never read
    qt = _qtile(xs_lo.shape[0])
    if reduce:
        if not groups:
            raise ValueError("reduce requires a level-grouped batch")
        g = k // (groups * kb.log_n)
        out = _walk_call_reduced(
            meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi,
            kb.log_n, kb.nu, qt, g, packed,
        )
    else:
        out = _walk_call(
            meta, seeds_t, scw_t, tcw_t, fcw_t, xs_lo, xs_hi,
            kb.log_n, kb.nu, qt, packed,
        )
    if packed:
        # host-sync: final host marshalling of the walk output words
        return bitpack.mask_tail(np.asarray(out), q)
    # host-sync: final host marshalling of the walk output bits
    return np.asarray(out)[:q].T


# ---------------------------------------------------------------------------
# Expansion kernel: levels s..nu + leaf conversion, VMEM-resident
# ---------------------------------------------------------------------------

_EKT = 8  # key-tile (sublane) height
_EWT = 128  # node-tile (lane) width at kernel entry
# Max levels fused per kernel program: leaf tile = _EKT * _EWT * 2^L nodes,
# 16 output words each -> 2 MB of VMEM outputs at L=5 (plus ~2x transients).
_EXP_LEVELS = 5


def expand_backend() -> str:
    """'pallas' | 'xla' for the fast-profile expansion (env DPF_TPU_FAST)."""
    env = knobs.get_enum("DPF_TPU_FAST")
    if env != "auto":
        return env
    return "pallas" if _on_tpu() else "xla"


def _expand_levels_body(S, T, scw_ref, tcw_ref, levels):
    """The in-kernel level loop shared by the expand+convert kernel and
    the mid-tree fused-levels kernel: ``levels`` GGM steps on [KT, W]
    word state, CW rows read from the lane-padded operand blocks
    (cw_operands layout, indexed relative to the block's first level)."""
    one = np.uint32(1)

    def bcast(col, shape):  # [KT, 1] per-key constant -> [KT, W]
        return jnp.broadcast_to(col, shape)

    for i in range(levels):
        out = _cc_core(S, _DSX, 8)
        L, R = out[:4], out[4:]
        tl = L[0] & one
        tr = R[0] & one
        L[0] = L[0] & ~one
        R[0] = R[0] & ~one
        msk = jnp.uint32(0) - T
        for w in range(4):
            cw = bcast(scw_ref[:, 4 * i + w : 4 * i + w + 1], L[w].shape)
            L[w] = L[w] ^ (cw & msk)
            R[w] = R[w] ^ (cw & msk)
        tl = tl ^ (bcast(tcw_ref[:, 2 * i : 2 * i + 1], T.shape) & T)
        tr = tr ^ (bcast(tcw_ref[:, 2 * i + 1 : 2 * i + 2], T.shape) & T)
        # Children go in BLOCK order [all-L | all-R], not interleaved: a
        # strided lane-interleave between unrolled ChaCha cores sends the
        # XLA (interpret-mode) compiler into the weeds, and block order is
        # a pure concat.  The leaf order is restored by one static gather
        # outside the kernel (deinterleave_leaves).
        S = [jnp.concatenate([L[w], R[w]], axis=1) for w in range(4)]
        T = jnp.concatenate([tl, tr], axis=1)
    return S, T


def _expand_kernel(
    s0_ref, s1_ref, s2_ref, s3_ref, t_ref, scw_ref, tcw_ref, fcw_ref,
    *out_refs, levels,
):
    S = [s0_ref[:], s1_ref[:], s2_ref[:], s3_ref[:]]
    T = t_ref[:]
    S, T = _expand_levels_body(S, T, scw_ref, tcw_ref, levels)
    out = _cc_core(S, _DSL, 16)
    msk = jnp.uint32(0) - T
    for j in range(16):
        fj = jnp.broadcast_to(fcw_ref[:, j : j + 1], T.shape)
        out_refs[j][:] = out[j] ^ (fj & msk)


def _fused_levels_kernel(
    s0_ref, s1_ref, s2_ref, s3_ref, t_ref, scw_ref, tcw_ref, *out_refs,
    levels,
):
    """Mid-tree fused group: ``levels`` GGM steps in one program, NO leaf
    conversion — the ChaCha twin of aes_pallas._fused_levels_kernel_bm.
    Emits the four child seed-word arrays plus T, children in block order
    (fix with deinterleave_leaves)."""
    S = [s0_ref[:], s1_ref[:], s2_ref[:], s3_ref[:]]
    T = t_ref[:]
    S, T = _expand_levels_body(S, T, scw_ref, tcw_ref, levels)
    for w in range(4):
        out_refs[w][:] = S[w]
    out_refs[4][:] = T


def fused_levels_raw(s0, s1, s2, s3, T, scw_p, tcw_p, levels: int):
    """One fused mid-tree group: state 5 x uint32[K, W] (4 seed words +
    packed t bits), CW operands in the cw_operands lane-padded layout for
    exactly these ``levels`` -> 5 x uint32[K, W << levels], children in
    block order per node tile."""
    K, W = T.shape
    wt = min(_EWT, W)
    sspec = pl.BlockSpec((_EKT, wt), lambda k, w: (k, w))
    cw_spec = pl.BlockSpec((_EKT, 128), lambda k, w: (k, 0))
    out_spec = pl.BlockSpec((_EKT, wt << levels), lambda k, w: (k, w))
    kern = functools.partial(_fused_levels_kernel, levels=levels)
    # 5 word arrays in at [_EKT, _EWT], 2 CW operand blocks, 5 out at
    # <= _EWT << _EXP_LEVELS lanes; 2x I/O windows.
    # vmem: 2 * 4 * _EKT * (5 * _EWT + 2 * 128 + 5 * (_EWT << _EXP_LEVELS))
    return pl.pallas_call(
        kern,
        grid=(K // _EKT, W // wt),
        in_specs=[sspec] * 5 + [cw_spec] * 2,
        out_specs=[out_spec] * 5,
        out_shape=[jax.ShapeDtypeStruct((K, W << levels), jnp.uint32)] * 5,
        interpret=not _on_tpu(),
    )(s0, s1, s2, s3, T, scw_p, tcw_p)


def fuse_auto_levels() -> int:
    """VMEM-budget group size for DPF_TPU_FUSE=auto on the fast profile:
    a mid-tree fused program carries 5 word arrays (vs the tail kernel's
    16 output words), so the tail's measured-safe _EXP_LEVELS depth is
    safe here a fortiori."""
    return _EXP_LEVELS


# Whole-tree (entry-0) kernel coverage: one program per key tile runs ALL
# nu levels + leaf conversion with lanes filling as the tree doubles.  The
# leaf tile is 2^nu lanes, so the VMEM bound that allows _EXP_LEVELS=5 at a
# 128-lane entry (128 << 5 = 4096 leaf lanes) allows nu <= 12 here.
_EXP_SMALL_MAX_NU = 12

# Sticky failure latch: a Mosaic lowering failure of the narrow entry-0
# program on some hardware degrades small domains to the classic plan
# once, instead of recompiling a failing kernel per call.
_SMALL_TREE_BROKEN = False


def small_tree_degraded(e: Exception) -> None:
    """Latch an entry-0 route failure (callers re-plan and take the
    classic/XLA path).  An explicit DPF_TPU_EXPAND_ENTRY=small re-raises
    so A/B experiments never silently measure the fallback."""
    global _SMALL_TREE_BROKEN
    import warnings

    if knobs.get_raw("DPF_TPU_EXPAND_ENTRY") == "small":
        raise e
    _SMALL_TREE_BROKEN = True
    warnings.warn(
        f"whole-tree expand route unavailable, using the classic plan: {e}",
        RuntimeWarning,
        stacklevel=3,
    )


def small_tree_entry(nu: int):
    """Entry level for the whole-tree small-domain route, or None when the
    classic >=128-lane-entry route (or XLA) should be used instead.

    ``auto``: entry 0 only where the classic kernel is ineligible
    (nu < 7) — a single fused program beats nu separate XLA level
    launches for latency-bound tiny expansions (BASELINE config 1's
    failure mode).  ``small`` forces entry 0 for every nu <= 12 (A/B
    experiments); ``classic`` disables the small route entirely."""
    mode = knobs.get_enum("DPF_TPU_EXPAND_ENTRY")
    if mode == "classic" or not 1 <= nu <= _EXP_SMALL_MAX_NU:
        return None
    # A latched failure disables the route for AUTO mode only: an explicit
    # DPF_TPU_EXPAND_ENTRY=small must keep attempting the kernel (and
    # re-raise on failure, see small_tree_degraded) so A/Bs and hardware
    # validation never silently measure the classic fallback.
    if _SMALL_TREE_BROKEN and mode != "small":
        return None
    # TPU-only: XLA:CPU's compile time explodes exponentially in the
    # number of narrow-lane concat levels (W=1 entry, levels=2 exceeds
    # 8 minutes; measured 2026-07-30), so interpret mode cannot run this
    # route.  Its only small-route-specific math (deinterleave at
    # wt < 128) is covered host-side in tests; the kernel body is shared
    # with the classic route, which interpret mode does cover.
    if not _on_tpu():
        return None
    if mode == "auto" and nu >= 7:
        return None
    return 0


def expand_plan(nu: int, k: int, max_leaf_nodes: int):
    """Single source of the expansion-kernel routing decision: returns
    (eligible, entry_level, padded_k).  Eligible needs a >= 128-node-wide
    kernel entry (nu >= 7) OR the whole-tree small-domain route
    (small_tree_entry), and the PADDED key count's leaf materialization
    under the cap — the 8-key sublane padding is real memory, so the cap
    must see it.  Used by eval_full_device AND bench.py so the scoreboard
    times exactly the production routing."""
    kp = k + (-k) % _EKT
    fits = (kp << nu) <= max_leaf_nodes
    small = small_tree_entry(nu)
    if small is not None and fits:
        return True, small, kp
    eligible = kernel_usable(nu, kp) and fits
    return eligible, entry_level(nu), kp


def kernel_usable(nu: int, k: int, subtree_levels: int = 0) -> bool:
    """Structural eligibility for the expand kernel: the (shard-local)
    kernel entry must be >= 128 nodes wide and the key axis must tile the
    8-key sublane quantum.  Shared by every route (eval_full, chunked,
    sharded, PIR)."""
    return (nu - subtree_levels) >= 7 and k % _EKT == 0


def entry_level(nu: int, floor: int = 7) -> int:
    """The kernel's entry tree level: deep enough that at most
    _EXP_LEVELS levels are fused, never narrower than 2^floor nodes.
    Single source of the formula for every route."""
    return max(floor, nu - _EXP_LEVELS)


# Cap on padded-key lanes materialized at the kernel entry level by the
# chunked path's prefix expansion (kp * 2^s state words x 5 arrays).
_MAX_PREFIX_LANES = 1 << 24


def expand_plan_chunked(nu: int, k: int, max_leaf_nodes: int):
    """Routing plan for domains whose full leaf materialization exceeds the
    cap: expand an XLA prefix to ``entry_level``, then run the kernel over
    node-range chunks of the entry state (each chunk an independent set of
    GGM subtrees — zero cross-chunk dependence).  Returns (eligible,
    entry_level, padded_k, n_chunks).  The entry level rises with the
    chunk count so every chunk keeps a >= 128-node kernel entry."""
    kp = k + (-k) % _EKT
    total = kp << nu
    n_chunks = -(-total // max_leaf_nodes)
    chunk_bits = max(0, (n_chunks - 1).bit_length())
    s = entry_level(nu, 7 + chunk_bits)
    if not kernel_usable(nu, kp) or s > nu or (kp << s) > _MAX_PREFIX_LANES:
        return False, s, kp, 0
    return True, s, kp, 1 << chunk_bits


def _expand_raw(s0, s1, s2, s3, T, scw_p, tcw_p, fcw_p, levels):
    K, W = T.shape
    # Small trees (W < 128 at entry — the whole-tree entry-0 route) run one
    # narrower program per key tile; lanes fill as the levels double W.
    wt = min(_EWT, W)
    sspec = pl.BlockSpec((_EKT, wt), lambda k, w: (k, w))
    cw_spec = pl.BlockSpec((_EKT, 128), lambda k, w: (k, 0))
    out_spec = pl.BlockSpec((_EKT, wt << levels), lambda k, w: (k, w))
    kern = functools.partial(_expand_kernel, levels=levels)
    # 5 word arrays + 3 CW operand blocks in, 16 leaf word slabs out at
    # <= _EWT << _EXP_LEVELS lanes (the 2 MB output bound that sized
    # _EXP_LEVELS = 5); 2x I/O windows.
    # vmem: 2 * 4 * _EKT * (5 * _EWT + 3 * 128 + 16 * (_EWT << _EXP_LEVELS))
    return pl.pallas_call(
        kern,
        grid=(K // _EKT, W // wt),
        in_specs=[sspec] * 5 + [cw_spec] * 3,
        out_specs=[out_spec] * 16,
        out_shape=[jax.ShapeDtypeStruct((K, W << levels), jnp.uint32)] * 16,
        interpret=not _on_tpu(),
    )(s0, s1, s2, s3, T, scw_p, tcw_p, fcw_p)


def deinterleave_leaves(x, levels, wt: int = _EWT):
    """Restore ascending leaf order of one expand-kernel output word
    [K, W].  ``wt`` is the kernel's entry node-tile width (= _EWT for
    the classic route, the entry node count for small trees).  XLA fuses
    the gather into the output stack pass.  One shared implementation
    with the compat fused kernels — see ops.deinterleave_nodes for the
    block-order math."""
    from . import deinterleave_nodes

    return deinterleave_nodes(x, levels, wt)


def cw_operands(scw, tcw, fcw, first_level: int, nu: int):
    """Lane-padded per-key CW operands for kernel levels
    ``first_level..nu-1`` plus the final CWs — THE layout the kernel's
    128-wide cw blocks read (rows: 4*i+w seed-CW words, 2*i t-CWs, 16
    final-CW words).  Accepts numpy or traced jnp arrays ([K, nu, 4],
    [K, nu, 2], [K, 16] uint32), so the memoized host path
    (expand_operands) and the in-graph routes (PIR, sharded) share one
    definition."""
    k = fcw.shape[0]
    levels = nu - first_level
    scw_p = jnp.zeros((k, 128), jnp.uint32)
    tcw_p = jnp.zeros((k, 128), jnp.uint32)
    if levels:
        scw_p = scw_p.at[:, : 4 * levels].set(
            jnp.asarray(scw)[:, first_level:].reshape(k, 4 * levels)
        )
        tcw_p = tcw_p.at[:, : 2 * levels].set(
            jnp.asarray(tcw)[:, first_level:].reshape(k, 2 * levels)
        )
    fcw_p = jnp.zeros((k, 128), jnp.uint32).at[:, :16].set(jnp.asarray(fcw))
    return scw_p, tcw_p, fcw_p


def expand_operands(kb, first_level: int):
    """Per-key CW operands for kernel levels ``first_level..nu-1`` plus the
    final CWs, lane-padded to the 128-wide block the kernel reads.
    Memoized per (key batch, first_level)."""
    cache = getattr(kb, "_expand_ops", None)
    if cache is None:
        cache = {}
        try:
            kb._expand_ops = cache
        except AttributeError:
            pass
    if first_level in cache:
        return cache[first_level]
    ops = cw_operands(
        kb.scw, kb.tcw.astype(np.uint32), kb.fcw, first_level, kb.nu
    )
    cache[first_level] = ops
    return ops


# ---------------------------------------------------------------------------
# DCF (models/dcf.py) kernel route
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(8, 9, 10, 11))
def _walk_call_dcf(
    meta, seeds_t, scw_t, tcw_t, vcw_t, fvcw_t, xs_lo, xs_hi, log_n, nu, qt,
    packed=False,
):
    bits = _walk_raw(
        meta, seeds_t, scw_t, tcw_t, fvcw_t, xs_lo, xs_hi, log_n, nu, qt,
        vcw_t=vcw_t, dcf=True,
    )
    if packed:
        return bitpack.pack_bits_qmajor_jnp(bits)
    return bits.astype(jnp.uint8)


def dcf_walk_operands(kb):
    """Key-minor operands for the DCF walk kernel, memoized per batch."""
    ops = getattr(kb, "_walk_ops_dcf", None)
    if ops is not None:
        return ops
    k, nu = kb.k, kb.nu
    meta, seeds_t, scw_t, tcw_t = _walk_common_operands(
        kb,
        np.full(k, kb.log_n, np.uint32),  # keep: always
        np.full(k, cc.LEAF_BITS - 1, np.uint32),
    )
    if nu:
        vcw_t = jnp.asarray(
            np.ascontiguousarray(kb.vcw.astype(np.uint32).T)
        )
    else:
        vcw_t = jnp.zeros((1, k), jnp.uint32)
    fvcw_t = jnp.asarray(np.ascontiguousarray(kb.fvcw.T))
    ops = (meta, seeds_t, scw_t, tcw_t, vcw_t, fvcw_t)
    try:
        kb._walk_ops_dcf = ops
    except AttributeError:
        pass
    return ops


def eval_points_walk_dcf(
    kb, xs: np.ndarray, packed: bool = False
) -> np.ndarray:
    """DCF comparison-share walk via the Pallas kernel: xs uint64[K, Q] ->
    uint8[K, Q] (same contract as models/dcf.eval_lt_points, which routes
    here on TPU).  ``packed`` packs the shares on device ->
    uint32[K, ceil(Q/32)] (core/bitpack contract)."""
    k = kb.k
    ops = dcf_walk_operands(kb)
    xs_t = np.ascontiguousarray(xs.T)
    q = xs_t.shape[0]
    pad_q = (-q) % 32 if packed else (-q) % 8
    if pad_q:
        xs_t = np.concatenate(
            [xs_t, np.zeros((pad_q,) + xs_t.shape[1:], xs_t.dtype)]
        )
    xs_lo = jnp.asarray((xs_t & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    if kb.log_n > 32:
        xs_hi = jnp.asarray((xs_t >> np.uint64(32)).astype(np.uint32))
    else:
        xs_hi = jnp.zeros((1, k), jnp.uint32)  # never read
    out = _walk_call_dcf(
        *ops, xs_lo, xs_hi, kb.log_n, kb.nu, _qtile(xs_lo.shape[0]), packed
    )
    if packed:
        # host-sync: final host marshalling of the walk output words
        return bitpack.mask_tail(np.asarray(out), q)
    # host-sync: final host marshalling of the walk output bits
    return np.asarray(out)[:q].T
