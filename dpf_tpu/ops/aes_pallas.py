"""Pallas TPU kernels for the bitsliced fixed-key AES-MMO hot ops.

The XLA path (``aes_bitslice``) expresses the cipher as one fused elementwise
DAG; these kernels pin the same circuit into explicit VMEM tiles so the whole
PRG double-expansion (both fixed-key AES-MMO calls — the reference's two
``aes128MMO`` invocations per GGM node, dpf/aes_amd64.s:51-82 via
dpf/dpf.go:59-69) runs as ONE kernel per batch tile: the state planes are
read from HBM once, ~230 S-box circuit temporaries live entirely in
VMEM/registers, and both children are written back once.  Leaf conversion
(single MMO, reference dpf/dpf.go:54-57) gets the same treatment.

Layout matches ``aes_bitslice``: state ``uint32[128, B]``, planes on the
sublane axis, packed batch words on the lane axis.  The cipher's plane
wiring (ShiftRows, MixColumns/xtime) is re-expressed with *static* slicing
and concatenation — Pallas kernels cannot capture array constants, and
static wiring lowers to sublane moves instead of gathers.  Round keys enter
as a kernel operand.

Off-TPU the kernels run in interpreter mode so the full differential test
suite exercises them on CPU CI; ``available()`` reports whether the real
Mosaic path is in use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..core import aes_np
from .aes_bitslice import (
    RK_MASKS_L,
    RK_MASKS_R,
    _sub_bytes,
    aes128_mmo_planes,
    prg_planes,
)

# Lane tile: 2 * 128 lanes keeps the kernel's scoped VMEM (inputs + both
# outputs + live S-box temporaries) under a v5e core's 16 MB limit
# (1024 lanes -> 18.75 MB scoped, OOM) and measured fastest in the
# scripts/sweep_bt.py sweep (256 > 512 > 128 on v5e).
_BT = 256
# Minimum batch (in lane words) worth a kernel launch; below this the XLA
# path is used (levels near the tree root / tiny key batches).
_MIN_B = 128

# Both fixed-key round-key mask sets as one operand: uint32[2, 11, 128].
_RK_BOTH = np.stack([RK_MASKS_L, RK_MASKS_R])

_SHIFT_PERM = [int(p) for p in aes_np.SHIFT_ROWS_PERM]  # 16 static byte moves


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def available() -> bool:
    """True when the Mosaic (non-interpreted) kernels will run."""
    return _on_tpu()


# ---------------------------------------------------------------------------
# Constant-free circuit helpers (kernel-traceable)
# ---------------------------------------------------------------------------


def _shift_rows_k(S):
    s = S.reshape(16, 8, -1)
    return jnp.concatenate([s[p : p + 1] for p in _SHIFT_PERM]).reshape(128, -1)


def _xtime_k(a):
    """GF(2^8) doubling on [..., 8, B] bit axis, static wiring only.

    out0 = a7; out1 = a0^a7; out2 = a1; out3 = a2^a7; out4 = a3^a7;
    out5..7 = a4..6  (reduction polynomial 0x11B)."""
    a0, a1, a2, a3, a4, a5, a6, a7 = (a[..., i, :] for i in range(8))
    return jnp.stack(
        [a7, a0 ^ a7, a1, a2 ^ a7, a3 ^ a7, a4, a5, a6], axis=-2
    )


def _mix_columns_k(S):
    s = S.reshape(4, 4, 8, -1)  # [column, row, bit, B]
    r1 = jnp.concatenate([s[:, 1:], s[:, :1]], axis=1)
    r2 = jnp.concatenate([s[:, 2:], s[:, :2]], axis=1)
    r3 = jnp.concatenate([s[:, 3:], s[:, :3]], axis=1)
    out = _xtime_k(s) ^ _xtime_k(r1) ^ r1 ^ r2 ^ r3
    return out.reshape(128, -1)


def _encrypt_k(S, rk):
    """AES-128 on [128, B] with round keys rk uint32[11, 128].

    SubBytes is shared with the XLA path (``aes_bitslice._sub_bytes`` — no
    array constants); only the plane-wiring steps are re-expressed."""
    S = S ^ rk[0][:, None]
    for rnd in range(1, 10):
        S = _mix_columns_k(_shift_rows_k(_sub_bytes(S))) ^ rk[rnd][:, None]
    return _shift_rows_k(_sub_bytes(S)) ^ rk[10][:, None]


def _prg_kernel(s_ref, rk_ref, l_ref, r_ref):
    S = s_ref[:]
    rk = rk_ref[:]
    l_ref[:] = _encrypt_k(S, rk[0]) ^ S
    r_ref[:] = _encrypt_k(S, rk[1]) ^ S


def _mmo_kernel(s_ref, rk_ref, o_ref):
    S = s_ref[:]
    o_ref[:] = _encrypt_k(S, rk_ref[0]) ^ S


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(1, 2))
def _tiled_call(S, kernel, n_out):
    B = S.shape[1]
    bt = _BT if B % _BT == 0 else _MIN_B
    spec = pl.BlockSpec((128, bt), lambda i: (0, i))
    rk_spec = pl.BlockSpec((2, 11, 128), lambda i: (0, 0, 0))
    shapes = [jax.ShapeDtypeStruct((128, B), jnp.uint32)] * n_out
    return pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[spec, rk_spec],
        out_specs=[spec] * n_out if n_out > 1 else spec,
        out_shape=shapes if n_out > 1 else shapes[0],
        interpret=not _on_tpu(),
    )(S, jnp.asarray(_RK_BOTH))


def prg_planes_pallas(S: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Fused double-MMO PRG on planes uint32[128, B] -> (L, R).

    Falls back to the XLA expression when B is not tileable."""
    if S.shape[1] % _MIN_B:
        return prg_planes(S)
    L, R = _tiled_call(S, _prg_kernel, 2)
    return L, R


def mmo_planes_pallas(S: jax.Array) -> jax.Array:
    """Leaf-convert MMO (fixed key L) on planes uint32[128, B]."""
    if S.shape[1] % _MIN_B:
        return aes128_mmo_planes(S, RK_MASKS_L)
    return _tiled_call(S, _mmo_kernel, 1)
