"""Pallas TPU kernels for the bitsliced fixed-key AES-MMO hot ops.

The XLA path (``aes_bitslice``) expresses the cipher as one fused elementwise
DAG; these kernels pin the same circuit into explicit VMEM tiles so the whole
PRG double-expansion (both fixed-key AES-MMO calls — the reference's two
``aes128MMO`` invocations per GGM node, dpf/aes_amd64.s:51-82 via
dpf/dpf.go:59-69) runs as ONE kernel per batch tile: the state planes are
read from HBM once, ~230 S-box circuit temporaries live entirely in
VMEM/registers, and both children are written back once.  Leaf conversion
(single MMO, reference dpf/dpf.go:54-57) gets the same treatment.

Layout matches ``aes_bitslice``: state ``uint32[128, B]``, planes on the
sublane axis, packed batch words on the lane axis.  The cipher's plane
wiring (ShiftRows, MixColumns/xtime) is re-expressed with *static* slicing
and concatenation — Pallas kernels cannot capture array constants, and
static wiring lowers to sublane moves instead of gathers.  Round keys enter
as a kernel operand.

Off-TPU the kernels run in interpreter mode so the full differential test
suite exercises them on CPU CI; ``available()`` reports whether the real
Mosaic path is in use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..core import aes_np
from ..core import knobs
from .aes_bitslice import (
    RK_MASKS_L,
    RK_MASKS_R,
    _sub_bytes,
    aes128_mmo_planes,
    prg_planes,
)
from .sbox_circuit import active_sbox

# Lane tile.  128 lanes measured ~2x faster than 256 END-TO-END at the
# headline config (scripts/bench_compat_ab.py on v5e: 22.9 vs 11.7
# Gleaves/s) — the smaller tile halves the live S-box temporary footprint
# and its spill traffic.  (The earlier kernel-only sweep_bt.py microbench
# preferred 256; it mismeasured — the device shows per-process performance
# modes that swamp isolated kernel timings.)
_BT = 128
# Minimum batch (in lane words) worth a kernel launch; below this the XLA
# path is used (levels near the tree root / tiny key batches).
_MIN_B = 128

# Both fixed-key round-key mask sets as one operand: uint32[2, 11, 128].
_RK_BOTH = np.stack([RK_MASKS_L, RK_MASKS_R])

_SHIFT_PERM = [int(p) for p in aes_np.SHIFT_ROWS_PERM]  # 16 static byte moves

# Bit-major plane order p' = 16*bit + byte (canonical is p = 8*byte + bit).
# In this order every S-box input/output plane is a CONTIGUOUS 16-sublane
# block instead of a stride-8 slice, trading the per-gate relayout work for
# two static 128-row permutations at the pipeline boundaries.  Plane 0 (the
# control-bit plane, byte 0 bit 0) is index 0 in both orders, so the DPF
# evaluator's t-bit handling is order-agnostic.
_TO_BM = [8 * (p % 16) + p // 16 for p in range(128)]  # S_bm = S[_TO_BM]
_FROM_BM = [16 * (p % 8) + p // 8 for p in range(128)]  # S = S_bm[_FROM_BM]
_RK_BOTH_BM = np.ascontiguousarray(_RK_BOTH[:, :, _TO_BM])


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def available() -> bool:
    """True when the Mosaic (non-interpreted) kernels will run."""
    return _on_tpu()


# ---------------------------------------------------------------------------
# Constant-free circuit helpers (kernel-traceable)
# ---------------------------------------------------------------------------


def _shift_rows_k(S):
    s = S.reshape(16, 8, -1)
    return jnp.concatenate([s[p : p + 1] for p in _SHIFT_PERM]).reshape(128, -1)


def _xtime_k(a):
    """GF(2^8) doubling on [..., 8, B] bit axis, static wiring only.

    out0 = a7; out1 = a0^a7; out2 = a1; out3 = a2^a7; out4 = a3^a7;
    out5..7 = a4..6  (reduction polynomial 0x11B)."""
    a0, a1, a2, a3, a4, a5, a6, a7 = (a[..., i, :] for i in range(8))
    return jnp.stack(
        [a7, a0 ^ a7, a1, a2 ^ a7, a3 ^ a7, a4, a5, a6], axis=-2
    )


def _mix_columns_k(S):
    s = S.reshape(4, 4, 8, -1)  # [column, row, bit, B]
    r1 = jnp.concatenate([s[:, 1:], s[:, :1]], axis=1)
    r2 = jnp.concatenate([s[:, 2:], s[:, :2]], axis=1)
    r3 = jnp.concatenate([s[:, 3:], s[:, :3]], axis=1)
    out = _xtime_k(s) ^ _xtime_k(r1) ^ r1 ^ r2 ^ r3
    return out.reshape(128, -1)


def _encrypt_k(S, rk):
    """AES-128 on [128, B] with round keys rk uint32[11, 128].

    SubBytes is shared with the XLA path (``aes_bitslice._sub_bytes`` — no
    array constants); only the plane-wiring steps are re-expressed."""
    S = S ^ rk[0][:, None]
    for rnd in range(1, 10):
        S = _mix_columns_k(_shift_rows_k(_sub_bytes(S))) ^ rk[rnd][:, None]
    return _shift_rows_k(_sub_bytes(S)) ^ rk[10][:, None]


def _prg_kernel(s_ref, rk_ref, l_ref, r_ref):
    S = s_ref[:]
    rk = rk_ref[:]
    l_ref[:] = _encrypt_k(S, rk[0]) ^ S
    r_ref[:] = _encrypt_k(S, rk[1]) ^ S


def _mmo_kernel(s_ref, rk_ref, o_ref):
    S = s_ref[:]
    o_ref[:] = _encrypt_k(S, rk_ref[0]) ^ S


# --- bit-major variants (state and round keys in _TO_BM plane order) -------


def _permute_rows(S, perm):
    return jnp.concatenate([S[p : p + 1] for p in perm])


# SubBytes evaluation width: 16 = all bytes in one circuit instance (each
# boolean temp is [16, B] = 2 vregs at the 128-lane tile); 8 = two
# sequential half-circuits whose temps are single vregs — the BP113
# middle section keeps ~40+ values live, so halving the per-value
# footprint is the difference between fitting the register file and
# spilling to VMEM.  Selected by end-to-end A/B (scripts/bench_compat_ab).
_SBOX_SPLIT = True

# S-box circuit inside the bit-major kernels: "bp113" (113 gates, peak
# 29 live values under emission order) or "lowlive" (the register-budgeted
# rematerializing schedule — 156 ops, peak 24; see sbox_circuit and
# scripts/sbox_liveness.py).  Selected by end-to-end A/B on hardware; the
# registry and the DPF_TPU_SBOX selection live in sbox_circuit so ALL
# variants (XLA, canonical, bit-major, interleaved, walk, fused) switch
# together (sbox_circuit.set_sbox / active_sbox).


# The bit-major circuit helpers are rank-generic: the plane axis is axis 0
# (128), and any trailing dims ride along untouched — [128, B] in the
# 2D kernels, [128, KT, QT] in the pointwise walk kernel (where splitting
# only the plane axis keeps the (sublane, lane) block layout intact; a
# flat reshape would be a physical relayout per op).


def _rk_col(rk, rnd, tail_ndim):
    return rk[rnd].reshape((128,) + (1,) * tail_ndim)


def _sub_bytes_bm(S):
    sbox = active_sbox()
    tail = S.shape[1:]
    s = S.reshape(8, 16, *tail)
    if not _SBOX_SPLIT:
        y = sbox([s[7 - i] for i in range(8)])  # circuit is MSB-first
        return jnp.concatenate(y[::-1]).reshape(128, *tail)
    outs = []
    for h in (0, 8):
        y = sbox([s[7 - i, h : h + 8] for i in range(8)])
        outs.append(jnp.stack(y[::-1]))  # [8, 8, *tail]
    return jnp.concatenate(outs, axis=1).reshape(128, *tail)


def _shift_rows_bm(S):
    tail = S.shape[1:]
    s = S.reshape(8, 16, *tail)
    return jnp.concatenate(
        [s[:, p : p + 1] for p in _SHIFT_PERM], axis=1
    ).reshape(128, *tail)


def _xtime_bm(a):  # [8, 16, *tail] bit-rotate + carry (reduction poly 0x11B)
    a0, a1, a2, a3, a4, a5, a6, a7 = (a[i : i + 1] for i in range(8))
    return jnp.concatenate([a7, a0 ^ a7, a1, a2 ^ a7, a3 ^ a7, a4, a5, a6])


def _mix_columns_bm(S):
    tail = S.shape[1:]
    s = S.reshape(8, 4, 4, *tail)  # [bit, col, row, *tail]
    r1 = jnp.concatenate([s[:, :, 1:], s[:, :, :1]], axis=2)
    r2 = jnp.concatenate([s[:, :, 2:], s[:, :, :2]], axis=2)
    r3 = jnp.concatenate([s[:, :, 3:], s[:, :, :3]], axis=2)
    f = lambda x: _xtime_bm(x.reshape(8, 16, *tail)).reshape(s.shape)  # noqa: E731
    return (f(s) ^ f(r1) ^ r1 ^ r2 ^ r3).reshape(128, *tail)


def _encrypt_bm(S, rk):
    nd = S.ndim - 1
    S = S ^ _rk_col(rk, 0, nd)
    for rnd in range(1, 10):
        S = _mix_columns_bm(_shift_rows_bm(_sub_bytes_bm(S))) ^ _rk_col(rk, rnd, nd)
    return _shift_rows_bm(_sub_bytes_bm(S)) ^ _rk_col(rk, 10, nd)


def _prg_kernel_bm(s_ref, rk_ref, l_ref, r_ref):
    """Pure bit-major PRG: no permutes — the evaluator holds level state in
    bit-major order for the whole expansion."""
    S = s_ref[:]
    rk = rk_ref[:]
    l_ref[:] = _encrypt_bm(S, rk[0]) ^ S
    r_ref[:] = _encrypt_bm(S, rk[1]) ^ S


def _encrypt2_bm_interleaved(S, rk2):
    """Both fixed-key encryptions round-by-round in lockstep: halves the
    serial dependency depth at the cost of a doubled live state.  Whether
    that wins depends on Mosaic's scheduler/spills — selected only when the
    end-to-end A/B (scripts/bench_compat_ab.py) says so."""
    A = S ^ rk2[0, 0][:, None]
    B = S ^ rk2[1, 0][:, None]
    for rnd in range(1, 10):
        A = _mix_columns_bm(_shift_rows_bm(_sub_bytes_bm(A))) ^ rk2[0, rnd][:, None]
        B = _mix_columns_bm(_shift_rows_bm(_sub_bytes_bm(B))) ^ rk2[1, rnd][:, None]
    A = _shift_rows_bm(_sub_bytes_bm(A)) ^ rk2[0, 10][:, None]
    B = _shift_rows_bm(_sub_bytes_bm(B)) ^ rk2[1, 10][:, None]
    return A, B


def _prg_kernel_bm_il(s_ref, rk_ref, l_ref, r_ref):
    S = s_ref[:]
    A, B = _encrypt2_bm_interleaved(S, rk_ref[:])
    l_ref[:] = A ^ S
    r_ref[:] = B ^ S


def prg_planes_pallas_bm_il(S: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Experimental interleaved bit-major PRG (same contract as
    prg_planes_pallas_bm)."""
    if S.shape[1] % _MIN_B:
        return prg_planes_pallas_bm(S)  # shared non-tileable fallback
    L, R = _tiled_call(S, _prg_kernel_bm_il, 2, True)
    return L, R


def _mmo_canon_kernel_bm(s_ref, rk_ref, o_ref):
    """Leaf convert from bit-major state to CANONICAL-order output planes:
    the one boundary where the bit-major pipeline pays a permute (in-VMEM
    sublane moves), so the bit-packed output layout is unchanged."""
    S = s_ref[:]
    o_ref[:] = _permute_rows(_encrypt_bm(S, rk_ref[0]) ^ S, _FROM_BM)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _tiled_call(S, kernel, n_out, bm):
    B = S.shape[1]
    bt = _BT if B % _BT == 0 else _MIN_B
    spec = pl.BlockSpec((128, bt), lambda i: (0, i))
    rk_spec = pl.BlockSpec((2, 11, 128), lambda i: (0, 0, 0))
    shapes = [jax.ShapeDtypeStruct((128, B), jnp.uint32)] * n_out
    # One [128, _BT] state slab in, <= 2 out, round keys, 2x for Mosaic's
    # double-buffered I/O windows (S-box temporaries live in registers).
    # vmem: 2 * (1 + 2) * 128 * _BT * 4 + 2 * 11 * 128 * 4
    return pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[spec, rk_spec],
        out_specs=[spec] * n_out if n_out > 1 else spec,
        out_shape=shapes if n_out > 1 else shapes[0],
        interpret=not _on_tpu(),
    )(S, jnp.asarray(_RK_BOTH_BM if bm else _RK_BOTH))


def prg_planes_pallas(S: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Fused double-MMO PRG on planes uint32[128, B] -> (L, R).

    Falls back to the XLA expression when B is not tileable."""
    if S.shape[1] % _MIN_B:
        return prg_planes(S)
    L, R = _tiled_call(S, _prg_kernel, 2, False)
    return L, R


def mmo_planes_pallas(S: jax.Array) -> jax.Array:
    """Leaf-convert MMO (fixed key L) on planes uint32[128, B]."""
    if S.shape[1] % _MIN_B:
        return aes128_mmo_planes(S, RK_MASKS_L)
    return _tiled_call(S, _mmo_kernel, 1, False)


def prg_planes_pallas_bm(S: jax.Array) -> tuple[jax.Array, jax.Array]:
    """PRG on BIT-MAJOR planes uint32[128, B] -> (L, R), also bit-major.

    Non-tileable widths (levels near the tree root) detour through the
    canonical XLA expression; the permutes there are on tiny tensors."""
    if S.shape[1] % _MIN_B:
        perm = jnp.asarray(_FROM_BM)
        L, R = prg_planes(S[perm])
        to = jnp.asarray(_TO_BM)
        return L[to], R[to]
    L, R = _tiled_call(S, _prg_kernel_bm, 2, True)
    return L, R


def mmo_planes_pallas_bm_canon(S: jax.Array) -> jax.Array:
    """Leaf-convert MMO on BIT-MAJOR planes -> CANONICAL-order planes."""
    if S.shape[1] % _MIN_B:
        return aes128_mmo_planes(S[jnp.asarray(_FROM_BM)], RK_MASKS_L)
    return _tiled_call(S, _mmo_canon_kernel_bm, 1, True)


# ---------------------------------------------------------------------------
# Whole-walk pointwise kernel (compat profile)
#
# The VMEM-resident analogue of ops/chacha_pallas._walk_kernel for the
# reference-key-compatible cipher (the reference's Eval loop,
# dpf/dpf.go:171-211, batched): the XLA pointwise body round-trips the
# full bitsliced state ([128, K, qp], 16 MB at BASELINE config 3) through
# HBM at every level; here the state stays in VMEM for the whole walk.
#
# Layout: a program's state is [128 planes, KT keys, QT query-words] —
# keys on sublanes, packed query words on lanes, the plane axis vectorized
# over (KT, QT) vreg slabs (the rank-generic _encrypt_bm above).  The
# per-level descent masks (packed path words) and the leaf bit-select
# one-hot masks are precomputed on device OUTSIDE the kernel from the
# query indices — the kernel itself is log_n-agnostic (no 64-bit index
# handling inside).
# ---------------------------------------------------------------------------

_PKT = 8  # walk key tile (sublanes)
_PQT = 128  # max walk query-word tile (lanes)


def walk_backend() -> str:
    """'pallas' | 'xla' for the compat pointwise walk (env
    DPF_TPU_POINTS_AES)."""
    env = knobs.get_enum("DPF_TPU_POINTS_AES")
    if env != "auto":
        return env
    return "pallas" if _on_tpu() else "xla"


def walk_forced() -> bool:
    """True when DPF_TPU_POINTS_AES=pallas explicitly — an override that
    engages the walk kernel even for a non-bit-major ``backend`` argument
    (interpreter-mode tests and A/B runs)."""
    return knobs.get_raw("DPF_TPU_POINTS_AES") == "pallas"


def _walk_kernel_bm(
    seeds_ref, t_ref, scw_ref, tlcw_ref, trcw_ref, fcw_ref, pw_ref,
    sel_ref, rk_ref, o_ref, *, nu,
):
    kt, qt = o_ref.shape
    rk = rk_ref[:]
    S0 = jnp.broadcast_to(seeds_ref[:], (128, kt, qt))
    T0 = jnp.broadcast_to(t_ref[:][0], (kt, qt))

    def level(i, carry):
        S, T = carry
        L = _encrypt_bm(S, rk[0]) ^ S
        R = _encrypt_bm(S, rk[1]) ^ S
        # Plane 0 is the packed control-bit PLANE (bit j = instance j's t);
        # extract it whole and zero it whole — unlike the fast walk kernel,
        # whose lanes each hold one instance's literal state word.
        tl = L[0]
        tr = R[0]
        zero = jnp.zeros_like(L[0:1])
        L = jnp.concatenate([zero, L[1:]])
        R = jnp.concatenate([zero, R[1:]])
        # Mosaic can't lower dynamic_slice on VMEM *values*; dynamic
        # indexing on a ref's leading dim is the supported idiom, so the
        # per-level operands stay in their refs and are loaded per step.
        cw = scw_ref[i]  # [128, KT, 1]
        cwm = cw & T[None]
        L = L ^ cwm
        R = R ^ cwm
        tl = tl ^ (tlcw_ref[i] & T)
        tr = tr ^ (trcw_ref[i] & T)
        go = pw_ref[i]  # [KT, QT]
        S = (R & go[None]) | (L & ~go[None])
        T = (tr & go) | (tl & ~go)
        return S, T

    S, T = jax.lax.fori_loop(0, nu, level, (S0, T0))
    C = _encrypt_bm(S, rk[0]) ^ S
    C = _permute_rows(C, _FROM_BM)  # bit-major -> canonical plane order
    C = C ^ (fcw_ref[:] & T[None])
    # Leaf bit select: sel one-hot over planes per packed query bit.
    o_ref[:] = jax.lax.reduce(
        C & sel_ref[:], np.uint32(0), jax.lax.bitwise_or, (0,)
    )


def walk_qt(qp: int) -> int:
    """Largest query-word lane tile dividing qp (cap _PQT)."""
    qt = min(qp, _PQT)
    while qp % qt:
        qt -= 1
    return qt


def eval_points_walk_planes(
    seeds_bm, t_words, scw_bm, tl_w, tr_w, fcw_canon, pw, sel, nu: int
):
    """Pallas whole-walk pointwise evaluation from prepared operands.

    seeds_bm uint32[128, K] (bit-major root seed planes), t_words
    uint32[K] (0/1), scw_bm uint32[nu, 128, K] (bit-major), tl_w/tr_w
    uint32[nu, K], fcw_canon uint32[128, K] (canonical), pw uint32[nu, K,
    qp] packed per-level descent words, sel uint32[128, K, qp] leaf-select
    one-hot masks -> uint32[K, qp] packed output bits.  K % 8 == 0; the
    caller (models/dpf.eval_points) pads keys and queries."""
    K = seeds_bm.shape[1]
    qp = pw.shape[2] if nu else sel.shape[2]
    qt = walk_qt(qp)
    n1 = max(nu, 1)  # zero-level walks still need non-empty level refs

    def rows3(n):  # [n, K, 1] per-key column blocks
        return pl.BlockSpec((n, _PKT, 1), lambda k, q: (0, k, 0))

    def rows4(n):
        return pl.BlockSpec((n, 128, _PKT, 1), lambda k, q: (0, 0, k, 0))

    qblock = pl.BlockSpec((n1, _PKT, qt), lambda k, q: (0, k, q))
    planes_q = pl.BlockSpec((128, _PKT, qt), lambda k, q: (0, k, q))
    kern = functools.partial(_walk_kernel_bm, nu=nu)
    # Whole-walk residency at the worst case nu=64: per-level CW planes
    # (scw 128-plane + tl/tr words), the [128, _PKT, qt] selector slab,
    # path words, seeds/t/fcw columns, round keys; 2x I/O windows.
    # vmem: 2 * 4 * (64 * 128 * _PKT + 2 * 64 * _PKT + 2 * 128 * _PKT * _PQT + 64 * _PKT * _PQT + 130 * _PKT + 2 * 11 * 128)
    return pl.pallas_call(
        kern,
        grid=(K // _PKT, qp // qt),
        in_specs=[
            pl.BlockSpec((128, _PKT, 1), lambda k, q: (0, k, 0)),  # seeds
            rows3(1),  # t
            rows4(n1),  # scw
            rows3(n1),  # tlcw
            rows3(n1),  # trcw
            pl.BlockSpec((128, _PKT, 1), lambda k, q: (0, k, 0)),  # fcw
            qblock,  # pw
            planes_q,  # sel
            pl.BlockSpec((2, 11, 128), lambda k, q: (0, 0, 0)),  # rk
        ],
        out_specs=pl.BlockSpec((_PKT, qt), lambda k, q: (k, q)),
        out_shape=jax.ShapeDtypeStruct((K, qp), jnp.uint32),
        interpret=not _on_tpu(),
    )(
        seeds_bm[:, :, None],
        t_words[None, :, None],
        scw_bm[:, :, :, None] if nu else jnp.zeros((1, 128, K, 1), jnp.uint32),
        tl_w[:, :, None] if nu else jnp.zeros((1, K, 1), jnp.uint32),
        tr_w[:, :, None] if nu else jnp.zeros((1, K, 1), jnp.uint32),
        fcw_canon[:, :, None],
        pw if nu else jnp.zeros((1, K, qp), jnp.uint32),
        sel,
        jnp.asarray(_RK_BOTH_BM),
    )


# ---------------------------------------------------------------------------
# Level-fused expansion kernels (compat profile)
#
# The per-level expansion (models/dpf._level_step) round-trips every node
# plane through HBM at each of the nu levels: the PRG kernel reads the
# parent state and writes both children, then the XLA epilogue (t-bit
# clear, CW XOR, child interleave) reads and rewrites them.  The fused
# kernel runs G consecutive GGM levels — PRG double-expansion, control-bit
# extract/clear, CW XOR masked by parent t-bits — inside ONE program, so
# all intermediate node planes stay in VMEM and HBM sees the entry tile
# once in and the 2^G-wide child tile once out: per-leaf HBM traffic on
# the level loop drops ~G x (model in scripts/bench_kernels.py).
#
# Layout: the evaluator's level state [128, W, Kp] enters the fused
# pipeline TRANSPOSED as [128 planes, Kp key-words, W nodes] — key words
# on sublanes (tile _FKT = 8), nodes on lanes (tile _FWT = 128; at the
# headline config Kp = 32, so nodes are the only axis wide enough to fill
# lanes).  Each plane value is then one (8, 128) vreg slab, exactly the
# walk kernel's shape, and the rank-generic bit-major circuit helpers
# apply unchanged.  Children are emitted in BLOCK order [all-L | all-R]
# per level (a pure lane concat — the strided interleave of the canonical
# layout is exactly what chacha_pallas's expand kernel had to avoid);
# ascending node order is restored outside the kernel by one static
# bit-reversal gather per group (fused_deinterleave, the trailing-axis
# generalization of chacha_pallas.deinterleave_leaves).
# ---------------------------------------------------------------------------

_FKT = 8  # fused key-word sublane tile
_FWT = 128  # fused node lane tile at kernel entry
# VMEM-budget model cap: one fused program holds the entry tile plus the
# final level's L/R child slabs (the 2^g-node output tile is one of them),
# each node-word 128 planes x 4 B.  16 MB/core VMEM minus Mosaic's
# double-buffered I/O windows and the S-box temporaries leaves ~8 MB for
# the state slabs; auto group size is the largest g that fits.
_FUSE_VMEM_BUDGET = 8 << 20
_FUSE_MAX_G = 4
# Module-wide bound the '# vmem:' kernel footprint models are linted
# against (python -m dpf_tpu.analysis, pallas-jit pass).
_VMEM_BUDGET = _FUSE_VMEM_BUDGET


def fuse_vmem_bytes(g: int, kt: int = _FKT, wt: int = _FWT) -> int:
    """Modeled VMEM footprint of one fused program running ``g`` levels:
    (entry + 2 * 2^g child-slab) node-words x 128 planes x 4 B."""
    return 512 * kt * wt * (1 + 2 * (1 << g))


def fuse_auto_levels() -> int:
    """VMEM-budget group size for DPF_TPU_FUSE=auto (0 when even g=1 does
    not fit — cannot happen at the default tile)."""
    g = 0
    while g < _FUSE_MAX_G and fuse_vmem_bytes(g + 1) <= _FUSE_VMEM_BUDGET:
        g += 1
    return g


def _fused_levels_kernel_bm(
    s_ref, t_ref, scw_ref, tl_ref, tr_ref, rk_ref, so_ref, to_ref, *, glevels
):
    """``glevels`` consecutive GGM level steps on a [128, KT, WT] bit-major
    tile, state resident in VMEM throughout.  Children concatenate in
    block order on the node (lane) axis each level."""
    S = s_ref[:]  # [128, KT, WT]
    T = t_ref[:]  # [KT, WT]
    rk = rk_ref[:]
    for _i in range(glevels):
        L = _encrypt_bm(S, rk[0]) ^ S
        R = _encrypt_bm(S, rk[1]) ^ S
        # Plane 0 is the packed control-bit plane: extract whole, zero
        # whole (same idiom as the walk kernel).
        tl = L[0]
        tr = R[0]
        zero = jnp.zeros_like(L[0:1])
        L = jnp.concatenate([zero, L[1:]])
        R = jnp.concatenate([zero, R[1:]])
        cwm = scw_ref[_i] & T[None]  # [128, KT, 1] & [1, KT, W] -> bcast
        L = L ^ cwm
        R = R ^ cwm
        tl = tl ^ (tl_ref[_i] & T)  # [KT, 1] & [KT, W]
        tr = tr ^ (tr_ref[_i] & T)
        S = jnp.concatenate([L, R], axis=2)
        T = jnp.concatenate([tl, tr], axis=1)
    so_ref[:] = S
    to_ref[:] = T


def fused_qkt(kp: int) -> int:
    """Largest key-word sublane tile dividing kp (cap _FKT)."""
    kt = min(kp, _FKT)
    while kp % kt:
        kt -= 1
    return kt


def fused_levels_planes(S, T, scw_bm, tl_w, tr_w):
    """Run ``g = scw_bm.shape[0]`` consecutive levels in one kernel.

    S uint32[128, Kp, W] bit-major planes in the fused (node-minor)
    layout, T uint32[Kp, W] packed parent control bits, scw_bm
    uint32[g, 128, Kp] bit-major seed-CW planes, tl_w/tr_w uint32[g, Kp]
    -> (S', T') with W << g nodes, children in BLOCK order per node tile
    (pass through :func:`fused_deinterleave` before anything
    order-sensitive).  W must be a power of two (it is 2^level)."""
    g = scw_bm.shape[0]
    kp, W = T.shape
    kt = fused_qkt(kp)
    wt = min(W, _FWT)
    kern = functools.partial(_fused_levels_kernel_bm, glevels=g)
    # The declared budget model itself, at the auto group size (explicit
    # DPF_TPU_FUSE=<g> overrides are forced A/B runs outside the budget).
    # vmem: fuse_vmem_bytes(fuse_auto_levels())
    return pl.pallas_call(
        kern,
        grid=(kp // kt, W // wt),
        in_specs=[
            pl.BlockSpec((128, kt, wt), lambda k, w: (0, k, w)),  # S
            pl.BlockSpec((kt, wt), lambda k, w: (k, w)),  # T
            pl.BlockSpec((g, 128, kt, 1), lambda k, w: (0, 0, k, 0)),  # scw
            pl.BlockSpec((g, kt, 1), lambda k, w: (0, k, 0)),  # tlcw
            pl.BlockSpec((g, kt, 1), lambda k, w: (0, k, 0)),  # trcw
            pl.BlockSpec((2, 11, 128), lambda k, w: (0, 0, 0)),  # rk
        ],
        out_specs=[
            pl.BlockSpec((128, kt, wt << g), lambda k, w: (0, k, w)),
            pl.BlockSpec((kt, wt << g), lambda k, w: (k, w)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((128, kp, W << g), jnp.uint32),
            jax.ShapeDtypeStruct((kp, W << g), jnp.uint32),
        ],
        interpret=not _on_tpu(),
    )(
        S,
        T,
        scw_bm[:, :, :, None],
        tl_w[:, :, None],
        tr_w[:, :, None],
        jnp.asarray(_RK_BOTH_BM),
    )


def fused_deinterleave(x, levels: int, wt: int):
    """Restore ascending node order on the LAST axis after a fused group
    (the fused state is [128, Kp, W], its T is [Kp, W]; ``wt`` is the
    group's ENTRY node-tile width).  One shared implementation with the
    chacha kernels — see ops.deinterleave_nodes for the block-order
    math."""
    from . import deinterleave_nodes

    return deinterleave_nodes(x, levels, wt)
