"""AES S-box as a boolean circuit, for bitsliced evaluation on the TPU VPU.

Primary circuit: Boyar-Peralta's 113-gate / depth-16 forward S-box
(J. Boyar, R. Peralta, "A depth-16 circuit for the AES S-box", 2011 —
public-domain circuit, reproduced in many bitsliced AES implementations).
Both circuits are verified exhaustively (all 256 inputs) against the
from-first-principles S-box table of ``dpf_tpu.core.aes_np`` in
``tests/test_aes_bitslice.py::test_sbox_circuits_exhaustive``.

The circuit operates on 8 input "planes" and produces 8 output planes.  A
plane is a numpy/jnp unsigned-integer array (or any value supporting ``^``,
``&`` and ``~`` elementwise with two's-complement ``~``): every lane bit is
an independent S-box evaluation.  Note ``~`` means outputs are only defined
per-bit — with plain Python ints the out-of-lane high bits are garbage, so
mask with ``& 1`` per lane; fixed-width numpy/jnp dtypes need no masking.

Convention: ``x[0]`` is the **most significant bit** of the S-box input byte,
``out[0]`` the MSB of the output (Boyar-Peralta's ordering).  Callers using
LSB-first plane layouts must reverse on the way in and out.

This module also owns the **circuit selection** (``DPF_TPU_SBOX`` /
:func:`set_sbox`): every cipher path — the XLA expression
(``aes_bitslice._sub_bytes``), the canonical Pallas kernels, the bit-major
family (per-level, interleaved, walk, fused) — reads the active circuit
through :func:`active_sbox`, so an A/B flip switches ALL of them at once
and a route stamp (``bench.py``/``bench_all.py``) can name the variant
that actually ran.
"""

from __future__ import annotations

from ..core import knobs


def sbox_bp113(x):
    """Forward AES S-box on 8 planes, MSB-first. 113 gates (32 AND, 77 XOR,
    4 XNOR).  Returns 8 output planes, MSB-first."""
    (x0, x1, x2, x3, x4, x5, x6, x7) = x

    # --- top linear transform (input expansion to 22 shared signals) ---
    y14 = x3 ^ x5
    y13 = x0 ^ x6
    y9 = x0 ^ x3
    y8 = x0 ^ x5
    t0 = x1 ^ x2
    y1 = t0 ^ x7
    y4 = y1 ^ x3
    y12 = y13 ^ y14
    y2 = y1 ^ x0
    y5 = y1 ^ x6
    y3 = y5 ^ y8
    t1 = x4 ^ y12
    y15 = t1 ^ x5
    y20 = t1 ^ x1
    y6 = y15 ^ x7
    y10 = y15 ^ t0
    y11 = y20 ^ y9
    y7 = x7 ^ y11
    y17 = y10 ^ y11
    y19 = y10 ^ y8
    y16 = t0 ^ y11
    y21 = y13 ^ y16
    y18 = x0 ^ y16

    # --- middle non-linear section (GF(2^4) inversion tower) ---
    t2 = y12 & y15
    t3 = y3 & y6
    t4 = t3 ^ t2
    t5 = y4 & x7
    t6 = t5 ^ t2
    t7 = y13 & y16
    t8 = y5 & y1
    t9 = t8 ^ t7
    t10 = y2 & y7
    t11 = t10 ^ t7
    t12 = y9 & y11
    t13 = y14 & y17
    t14 = t13 ^ t12
    t15 = y8 & y10
    t16 = t15 ^ t12
    t17 = t4 ^ t14
    t18 = t6 ^ t16
    t19 = t9 ^ t14
    t20 = t11 ^ t16
    t21 = t17 ^ y20
    t22 = t18 ^ y19
    t23 = t19 ^ y21
    t24 = t20 ^ y18
    t25 = t21 ^ t22
    t26 = t21 & t23
    t27 = t24 ^ t26
    t28 = t25 & t27
    t29 = t28 ^ t22
    t30 = t23 ^ t24
    t31 = t22 ^ t26
    t32 = t31 & t30
    t33 = t32 ^ t24
    t34 = t23 ^ t33
    t35 = t27 ^ t33
    t36 = t24 & t35
    t37 = t36 ^ t34
    t38 = t27 ^ t36
    t39 = t29 & t38
    t40 = t25 ^ t39
    t41 = t40 ^ t37
    t42 = t29 ^ t33
    t43 = t29 ^ t40
    t44 = t33 ^ t37
    t45 = t42 ^ t41
    z0 = t44 & y15
    z1 = t37 & y6
    z2 = t33 & x7
    z3 = t43 & y16
    z4 = t40 & y1
    z5 = t29 & y7
    z6 = t42 & y11
    z7 = t45 & y17
    z8 = t41 & y10
    z9 = t44 & y12
    z10 = t37 & y3
    z11 = t33 & y4
    z12 = t43 & y13
    z13 = t40 & y5
    z14 = t29 & y2
    z15 = t42 & y9
    z16 = t45 & y14
    z17 = t41 & y8

    # --- bottom linear transform (shared-XOR output reconstruction) ---
    t46 = z15 ^ z16
    t47 = z10 ^ z11
    t48 = z5 ^ z13
    t49 = z9 ^ z10
    t50 = z2 ^ z12
    t51 = z2 ^ z5
    t52 = z7 ^ z8
    t53 = z0 ^ z3
    t54 = z6 ^ z7
    t55 = z16 ^ z17
    t56 = z12 ^ t48
    t57 = t50 ^ t53
    t58 = z4 ^ t46
    t59 = z3 ^ t54
    t60 = t46 ^ t57
    t61 = z14 ^ t57
    t62 = t52 ^ t58
    t63 = t49 ^ t58
    t64 = z4 ^ t59
    t65 = t61 ^ t62
    t66 = z1 ^ t63
    s0 = t59 ^ t63
    s6 = ~(t56 ^ t62)
    s7 = ~(t48 ^ t60)
    t67 = t64 ^ t65
    s3 = t53 ^ t66
    s4 = t51 ^ t66
    s5 = t47 ^ t65
    s1 = ~(t64 ^ s3)
    s2 = ~(t55 ^ t67)

    return [s0, s1, s2, s3, s4, s5, s6, s7]


def sbox_bp113_lowlive(x):
    """Forward AES S-box, register-budgeted schedule: same GF(2^4) tower
    math as :func:`sbox_bp113`, restructured for a small live set.

    Rationale (measured with scripts/sbox_liveness.py): the plain BP113
    transcription peaks at 29 live values (36 with the 8 inputs pinned)
    because its 22 shared y-signals each have one consumer in the early
    t-products and one in the z-products ~70 gates later, so they stay
    live across the entire nonlinear middle section.  On the TPU VPU each
    live value is a vector register (an (8, 128) vreg in the split
    bit-major kernel); a cut that size spills to VMEM and the kernel runs
    at a third of the chip's demonstrated uint32 op rate
    (README "working set" analysis).

    This schedule rematerializes the y-signals instead of holding them —
    the Käsper-Schwabe register-budget idea (CHES 2009), rederived for a
    3-operand SSA target so the budget shows up as DAG width rather than
    explicit register moves:

      phase A: t-products, consuming freshly computed y's; carries only
               t21..t24 forward,
      phase B: the GF(2^4) inversion core (working set ~10),
      phase C: z-products with each y recomputed from the inputs via
               short XOR identities (e.g. y15 = x0^x3^x4^x6,
               y11 = y16^t0, y10 = y11^y17), interleaved with the shared
               output-XOR tree so each z dies within a few gates.

    ~43 extra XORs (156 ops vs 113) buy a peak cut of 24 live values (26
    inputs-pinned) vs BP113's 29 (36) — recomputation is issue-rate-cheap,
    spills are not.  The binding region is phase C, whose cut is close to
    inherent: 8 pinned inputs + the 9 tower coefficients (t29..t45, each
    feeding two z-products) are live across the whole output
    reconstruction, so ~17 is the floor for any schedule of this DAG.
    Exhaustively verified against the from-first-principles table in
    tests/test_aes_bitslice.py alongside the other circuits.
    """
    (x0, x1, x2, x3, x4, x5, x6, x7) = x

    # --- phase A: shared-signal products, y's computed on demand --------
    y13 = x0 ^ x6
    y14 = x3 ^ x5
    y12 = y13 ^ y14
    y15 = (y12 ^ x4) ^ x5
    t2 = y12 & y15
    t0 = x1 ^ x2
    y8 = x0 ^ x5
    y6 = y15 ^ x7
    y3 = (t0 ^ y8) ^ (x6 ^ x7)
    t3 = y3 & y6
    t4 = t3 ^ t2
    y1 = t0 ^ x7
    y4 = y1 ^ x3
    t5 = y4 & x7
    t6 = t5 ^ t2
    y16 = (x2 ^ x6) ^ (x4 ^ x5)
    t7 = y13 & y16
    y5 = y1 ^ x6
    t8 = y5 & y1
    t9 = t8 ^ t7
    y11 = y16 ^ t0
    y2 = y1 ^ x0
    y7 = y11 ^ x7
    t10 = y2 & y7
    t11 = t10 ^ t7
    y9 = x0 ^ x3
    t12 = y9 & y11
    y17 = y14 ^ (x0 ^ x2)
    t13 = y14 & y17
    t14 = t13 ^ t12
    y10 = y11 ^ y17
    t15 = y8 & y10
    t16 = t15 ^ t12
    t17 = t4 ^ t14
    t18 = t6 ^ t16
    t19 = t9 ^ t14
    t20 = t11 ^ t16
    y20 = y11 ^ y9
    t21 = t17 ^ y20
    y19 = y16 ^ (x1 ^ x3)
    t22 = t18 ^ y19
    y18 = x0 ^ y16
    t24 = t20 ^ y18
    y21 = y18 ^ x6
    t23 = t19 ^ y21

    # --- phase B: GF(2^4) inversion core (identical to BP113) ----------
    t25 = t21 ^ t22
    t26 = t21 & t23
    t27 = t24 ^ t26
    t28 = t25 & t27
    t29 = t28 ^ t22
    t30 = t23 ^ t24
    t31 = t22 ^ t26
    t32 = t31 & t30
    t33 = t32 ^ t24
    t34 = t23 ^ t33
    t35 = t27 ^ t33
    t36 = t24 & t35
    t37 = t36 ^ t34
    t38 = t27 ^ t36
    t39 = t29 & t38
    t40 = t25 ^ t39
    t41 = t40 ^ t37
    t42 = t29 ^ t33
    t43 = t29 ^ t40
    t44 = t33 ^ t37
    t45 = t42 ^ t41

    # --- phase C: z-products with rematerialized y's, streamed into the
    # shared output tree (t46..t67 exactly as in BP113, reordered so each
    # z dies within a few gates of its creation) -------------------------
    c_t0 = x1 ^ x2
    c_y16 = (x2 ^ x6) ^ (x4 ^ x5)
    c_y11 = c_y16 ^ c_t0
    z6 = t42 & c_y11
    c_y9 = x0 ^ x3
    z15 = t42 & c_y9
    c_y14 = x3 ^ x5
    z16 = t45 & c_y14
    c_y17 = c_y14 ^ (x0 ^ x2)
    z7 = t45 & c_y17
    t46 = z15 ^ z16
    t54 = z6 ^ z7
    c_y10 = c_y11 ^ c_y17
    z8 = t41 & c_y10
    c_y8 = x0 ^ x5
    z17 = t41 & c_y8
    t52 = z7 ^ z8
    t55 = z16 ^ z17
    c_y7 = c_y11 ^ x7
    z5 = t29 & c_y7
    c_y1 = c_t0 ^ x7
    c_y2 = c_y1 ^ x0
    z14 = t29 & c_y2
    z4 = t40 & c_y1
    c_y5 = c_y1 ^ x6
    z13 = t40 & c_y5
    t48 = z5 ^ z13
    t58 = z4 ^ t46
    z2 = t33 & x7
    c_y4 = c_y1 ^ x3
    z11 = t33 & c_y4
    t51 = z2 ^ z5
    c2_y16 = (x2 ^ x6) ^ (x4 ^ x5)  # remat: frees c_y16's 40-gate hold
    z3 = t43 & c2_y16
    c_y13 = x0 ^ x6
    z12 = t43 & c_y13
    t50 = z2 ^ z12
    t56 = z12 ^ t48
    t59 = z3 ^ t54
    t64 = z4 ^ t59
    c_y15 = (x0 ^ x3) ^ (x4 ^ x6)
    z0 = t44 & c_y15
    c_y12 = (c_y15 ^ x4) ^ x5
    z9 = t44 & c_y12
    t53 = z0 ^ z3
    t57 = t50 ^ t53
    t60 = t46 ^ t57
    t61 = z14 ^ t57
    s7 = ~(t48 ^ t60)
    c_y6 = c_y15 ^ x7
    z1 = t37 & c_y6
    c_y3 = ((x0 ^ x1) ^ (x2 ^ x5)) ^ (x6 ^ x7)  # remat, not c_y5^c_y8
    z10 = t37 & c_y3
    t47 = z10 ^ z11
    t49 = z9 ^ z10
    t62 = t52 ^ t58
    t63 = t49 ^ t58
    t65 = t61 ^ t62
    t66 = z1 ^ t63
    s0 = t59 ^ t63
    s6 = ~(t56 ^ t62)
    t67 = t64 ^ t65
    s3 = t53 ^ t66
    s4 = t51 ^ t66
    s5 = t47 ^ t65
    s1 = ~(t64 ^ s3)
    s2 = ~(t55 ^ t67)

    return [s0, s1, s2, s3, s4, s5, s6, s7]


# ---------------------------------------------------------------------------
# Fallback circuit derived from first principles: inversion in GF(2^8) via a
# square-and-multiply addition chain for x^254, with bitsliced schoolbook
# GF(2^8) multiplication, followed by the affine map.  ~5x more gates than
# Boyar-Peralta but derivable without trusting a transcribed netlist; kept as
# a cross-check and safety net.  LSB-first convention internally.
# ---------------------------------------------------------------------------


def _gf_reduce(c):
    """Reduce a degree-14 polynomial (15 planes) mod x^8+x^4+x^3+x+1."""
    for k in range(14, 7, -1):
        d = k - 8
        c[d + 4] = c[d + 4] ^ c[k]
        c[d + 3] = c[d + 3] ^ c[k]
        c[d + 1] = c[d + 1] ^ c[k]
        c[d + 0] = c[d + 0] ^ c[k]
    return c[:8]


def _gf_mul_planes(a, b):
    """Bitsliced GF(2^8) multiply mod x^8+x^4+x^3+x+1; a, b are 8 planes
    LSB-first.  Schoolbook partial products then modular reduction."""
    # Partial products: c[k] = XOR_{i+j=k} a[i] & b[j], k = 0..14
    c = [None] * 15
    for i in range(8):
        for j in range(8):
            p = a[i] & b[j]
            k = i + j
            c[k] = p if c[k] is None else (c[k] ^ p)
    return _gf_reduce(c)


def _gf_sq_planes(a):
    """Bitsliced GF(2^8) squaring (linear: spread bits then reduce)."""
    c = [None] * 15
    zero = a[0] ^ a[0]
    for k in range(15):
        c[k] = a[k // 2] if k % 2 == 0 else zero
    return _gf_reduce(c)


def sbox_algebraic(x):
    """Forward AES S-box on 8 planes, MSB-first (same interface as
    :func:`sbox_bp113`), via x^254 then the affine transform."""
    a = list(reversed(x))  # to LSB-first
    t1 = _gf_sq_planes(a)  # x^2
    t2 = _gf_mul_planes(t1, a)  # x^3
    t3 = t2
    for _ in range(2):
        t3 = _gf_sq_planes(t3)  # x^12
    t4 = _gf_mul_planes(t3, t2)  # x^15
    t5 = t4
    for _ in range(4):
        t5 = _gf_sq_planes(t5)  # x^240
    t6 = _gf_mul_planes(t5, t3)  # x^252
    inv = _gf_mul_planes(t6, t1)  # x^254 = x^-1 (and 0 -> 0)
    # Affine: out_i = b_i ^ b_{i+4} ^ b_{i+5} ^ b_{i+6} ^ b_{i+7} ^ c_i, c=0x63
    out = []
    for i in range(8):
        o = (
            inv[i]
            ^ inv[(i + 4) % 8]
            ^ inv[(i + 5) % 8]
            ^ inv[(i + 6) % 8]
            ^ inv[(i + 7) % 8]
        )
        if (0x63 >> i) & 1:
            o = ~o
        out.append(o)
    return list(reversed(out))  # back to MSB-first


# ---------------------------------------------------------------------------
# Circuit selection (single source of truth for every cipher path)
# ---------------------------------------------------------------------------

# "bp113": the plain Boyar-Peralta transcription (113 gates, peak 29 live
# values under emission order / 36 with inputs pinned).  "lowlive": the
# register-budgeted rematerializing schedule (156 ops, peak 24 / 26 pinned
# — scripts/sbox_liveness.py; scripts/sbox_schedule_search.py's randomized
# list scheduling cannot beat its emission order, so the hand schedule IS
# the landed register-budgeted schedule).  The default stays bp113 until
# the on-hardware A/B (tpu_logs/*/DECISIONS.md) flips it.
SBOX_IMPLS = {"bp113": sbox_bp113, "lowlive": sbox_bp113_lowlive}

# The registry's declared choices and the implementation table must agree
# (the knob declaration is what docs/KNOBS.md and the lint pass see) —
# an explicit raise, not an assert, so the check survives python -O.
if set(SBOX_IMPLS) != set(knobs.knob("DPF_TPU_SBOX").choices):
    raise RuntimeError(
        "SBOX_IMPLS and the DPF_TPU_SBOX declaration in core/knobs.py "
        f"disagree: {sorted(SBOX_IMPLS)} vs "
        f"{sorted(knobs.knob('DPF_TPU_SBOX').choices)}"
    )

_SBOX = knobs.get_enum("DPF_TPU_SBOX")


def set_sbox(name: str) -> str:
    """Select the active circuit (A/B scripts); returns the previous name.
    Callers must ``jax.clear_caches()`` afterwards — the selection is a
    trace-time Python global, not a traced value."""
    global _SBOX
    if name not in SBOX_IMPLS:
        raise ValueError(
            f"unknown S-box circuit {name!r}; choose from {sorted(SBOX_IMPLS)}"
        )
    prev, _SBOX = _SBOX, name
    return prev


def active_sbox():
    """The selected circuit function (read at trace time by every kernel
    variant: XLA, canonical Pallas, bit-major, interleaved, walk, fused)."""
    return SBOX_IMPLS[_SBOX]
