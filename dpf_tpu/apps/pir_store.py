"""Device-resident PIR database registry — 2-server PIR as a served
production workload.

``models/pir.py`` owns the math (MXU parity matmuls over a packed
database, one-shot or streamed); this module owns the OPERATIONAL
lifecycle a serving deployment needs:

  * named databases loaded once (``POST /v1/pir/db`` streams the body
    off the socket in ``DPF_TPU_PIR_DB_CHUNK_BYTES`` chunks straight
    into the packed host buffer — no giant intermediate bytes object)
    and resident in device HBM from then on: with the serving mesh
    resolved (``DPF_TPU_MESH``, parallel/serving_mesh.py) the rows shard
    over a ``(keys=1, leaf=shards)`` mesh built on the SAME devices, so
    a multi-GB corpus splits 1/shards per chip and every query batch
    costs exactly one parity all-reduce;
  * per-placement ``PirServer`` views built lazily from one public host
    copy: the sharded view is the production path, the single-device
    view is the degraded fallback the plan layer dispatches inside
    ``serving_mesh.suspended()`` (breaker-not-closed) — byte-identical
    by the PIR answer contract (the DB is public data, so keeping the
    packed host words for re-placement leaks nothing);
  * scan accounting for ``/v1/stats`` / ``/v1/metrics``: databases
    resident, queries answered, database bytes scanned, and the
    streamed-chunks-per-scan histogram.

Trust model (DESIGN §15): the DATABASE is public — both PIR servers hold
identical copies by protocol construction, so names, shapes, and scan
counters are exportable metadata.  The QUERY is the secret: it exists
only as DPF key material, and the scan routes carry obliviousness
certificates (``pir/scan*`` in docs/OBLIVIOUS.md) that no secret ever
steers a branch, index, or shape — the seeded-leaky twin
(``bad_oblivious.leaky_pir_chunk_eval``, a secret-dependent DB chunk
index) is what the verifier must refuse.
"""

from __future__ import annotations

import re
import threading

import numpy as np

from ..core import knobs
from ..models.pir import _LEAF_LOG, PirServer, row_domain

__all__ = ["PirDB", "PirRegistry", "registry", "reset", "validate_name"]

_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


def validate_name(name: str) -> str:
    """Raise ValueError unless ``name`` is a legal database name.  The
    sidecar runs this BEFORE reading an upload body — a bad name must
    cost zero bytes of socket work, not a full-database read."""
    if not _NAME_RE.match(name or ""):
        raise ValueError(
            "pir: db name must be 1-64 chars of [A-Za-z0-9_.-]"
        )
    return name

# Streamed-chunks-per-scan histogram bounds (1 = one-shot scan).
CHUNK_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128)


class PirDB:
    """One named, device-resident database and its scan counters.

    The packed public host words are kept (the one copy serving both
    placement regimes); ``server(shards)`` returns — building lazily —
    the ``PirServer`` for a placement (0 = single-device)."""

    def __init__(self, name: str, db: np.ndarray, profile: str = "compat"):
        validate_name(name)
        db = np.ascontiguousarray(np.asarray(db, dtype=np.uint8))
        if db.ndim != 2:
            raise ValueError("pir: db must be [n_rows, row_bytes]")
        self.name = name
        self.profile = profile
        self.n_rows, self.row_bytes = db.shape
        self.log_n, self.dom = row_domain(self.n_rows, profile)
        self.nu = max(self.log_n - _LEAF_LOG[profile], 0)
        self._db = db
        self._servers: dict[int, PirServer] = {}
        self._lock = threading.Lock()
        # Scan accounting (read by stats()/metrics under the registry).
        self.queries = 0
        self.scans = 0
        self.bytes_scanned = 0
        self.chunk_hist = [0] * (len(CHUNK_BOUNDS) + 1)
        self.chunk_sum = 0  # total streamed chunks across scans

    @property
    def db_bytes(self) -> int:
        """Padded resident bytes — what one full scan reads."""
        return self.dom * self.row_bytes

    def server(self, shards: int = 0) -> PirServer:
        """The ``PirServer`` view for a placement regime (``shards`` = 0
        for single-device; otherwise rows shard over a (1, shards) leaf
        mesh on the serving mesh's devices).  Built once per regime; the
        database words are placed into (mesh) HBM at build."""
        shards = int(shards)
        with self._lock:
            srv = self._servers.get(shards)
        if srv is not None:
            return srv
        # Build OUTSIDE the lock: placement copies the whole database to
        # (mesh) HBM, and holding _lock across it would stall note_scan
        # on every concurrent query and registry().stats() behind it —
        # freezing /v1/stats exactly when a degraded first-build happens.
        mesh = None
        if shards > 1:
            from ..parallel import serving_mesh
            from ..parallel.sharding import make_mesh

            smesh = serving_mesh.serving_mesh()
            devices = (
                list(smesh.devices.reshape(-1)[:shards])
                if smesh is not None
                else None
            )
            mesh = make_mesh(n_keys=1, n_leaf=shards, devices=devices)
        built = PirServer(self._db, mesh=mesh, profile=self.profile)
        with self._lock:
            # Keep-first on a racing build: both are views of the same
            # public rows, but plans' jit caches key on the mesh object,
            # so every caller must converge on ONE server per regime.
            srv = self._servers.setdefault(shards, built)
        return srv

    def dispatch_shards(self) -> int:
        """Shard count for the CURRENT dispatch: the serving mesh's, but
        never more leaf shards than the domain has subtrees (tiny DBs
        stay single-device), and 0 inside ``serving_mesh.suspended()``
        — the degraded fallback the breaker engages."""
        from ..parallel import serving_mesh

        shards = serving_mesh.shards()
        while shards > 1 and (1 << self.nu) < shards:
            shards //= 2
        return 0 if shards < 2 else shards

    def note_scan(self, k: int, stream_chunks: int) -> None:
        """One answered query-batch dispatch: ``k`` queries rode one
        full-database scan of ``stream_chunks`` streamed dispatches."""
        import bisect

        with self._lock:
            self.queries += int(k)
            self.scans += 1
            self.bytes_scanned += self.db_bytes
            self.chunk_sum += int(stream_chunks)
            self.chunk_hist[
                bisect.bisect_left(CHUNK_BOUNDS, int(stream_chunks))
            ] += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "profile": self.profile,
                "log_n": self.log_n,
                "rows": self.n_rows,
                "row_bytes": self.row_bytes,
                "db_bytes": self.db_bytes,
                "placements": sorted(self._servers),
                "queries": self.queries,
                "scans": self.scans,
                "bytes_scanned": self.bytes_scanned,
            }


class PirRegistry:
    """Process-wide name -> :class:`PirDB` map plus the aggregate scan
    counters the stats/metrics surfaces export."""

    def __init__(self):
        self._dbs: dict[str, PirDB] = {}
        self._lock = threading.Lock()

    def load(self, name: str, db: np.ndarray,
             profile: str = "compat") -> PirDB:
        """Register (or replace) a named database.  Placement happens on
        the entry's first ``server()`` call — warm it with
        ``plans.warmup([{"route": "pir", "db": name, ...}])`` so the
        compile never lands on query traffic."""
        entry = PirDB(name, db, profile=profile)
        with self._lock:
            self._dbs[name] = entry
        return entry

    def get(self, name: str) -> PirDB:
        with self._lock:
            entry = self._dbs.get(name)
        if entry is None:
            raise KeyError(f"pir: unknown db {name!r} (load it first: "
                           "POST /v1/pir/db)")
        return entry

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._dbs)

    def drop(self, name: str) -> bool:
        with self._lock:
            return self._dbs.pop(name, None) is not None

    def stats(self) -> dict:
        """The /v1/stats ``pir`` block (and the metrics families): DBs
        resident, bytes scanned, and the streamed-chunk histogram —
        names and shapes are public metadata (the DB is public data)."""
        with self._lock:
            dbs = list(self._dbs.values())
        per_db = [d.stats() for d in dbs]
        hist = [0] * (len(CHUNK_BOUNDS) + 1)
        chunk_sum = 0
        for d in dbs:
            with d._lock:
                chunk_sum += d.chunk_sum
                for i, c in enumerate(d.chunk_hist):
                    hist[i] += c
        return {
            "dbs_resident": len(per_db),
            "db_bytes_resident": sum(d["db_bytes"] for d in per_db),
            "queries": sum(d["queries"] for d in per_db),
            "scans": sum(d["scans"] for d in per_db),
            "bytes_scanned": sum(d["bytes_scanned"] for d in per_db),
            # Histogram of streamed chunks per scan, promtext-shaped
            # (non-cumulative counts; last bucket = overflow).
            "scan_chunks": {
                "bounds": list(CHUNK_BOUNDS),
                "counts": hist,
                "sum": float(chunk_sum),
                "count": sum(hist),
            },
            "resident": per_db,
        }


_REGISTRY = PirRegistry()
_REGISTRY_LOCK = threading.Lock()


def registry() -> PirRegistry:
    # A racing reset() hands the caller the pre-reset registry, which
    # stays fully usable on its own.
    # lock-free-ok: atomic reference read of the singleton
    return _REGISTRY


def reset() -> None:
    """Drop every registered database (tests/benches; frees the host and
    device copies once nothing else references the servers)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = PirRegistry()


def upload_chunk_rows(row_bytes: int) -> int:
    """Rows per socket read of the /v1/pir/db upload: one
    DPF_TPU_PIR_DB_CHUNK_BYTES chunk's worth (>= 1)."""
    chunk = knobs.get_int("DPF_TPU_PIR_DB_CHUNK_BYTES")
    if chunk <= 0:
        chunk = 1 << 22
    return max(1, chunk // max(int(row_bytes), 1))
