"""Protocol applications on the FSS serving stack.

PAPER.md names the applications DPFs exist for — PIR, distributed ORAM,
secure aggregation; this package is the layer that turns the repo's
primitives (batched Gen, grouped pointwise eval, packed wire words, the
plan cache) into whole server-side protocol workloads:

  heavy_hitters  prefix-tree heavy hitters: levelwise descent over a
                 level-major batch of client DPF keys, one grouped
                 device dispatch per round, host-side thresholding of
                 publicly reconstructed counts.
  aggregation    secure aggregation: streamed XOR / additive-mod-2^32
                 folds of client share vectors in device-sized chunks.

Both ride the sidecar (``/v1/hh/*``, ``/v1/agg/*`` in dpf_tpu/server.py)
through the existing batcher / plan-cache / deadline / breaker / trace
machinery, and both carry obliviousness certificates for their device
bodies (docs/OBLIVIOUS.md; protocol flow and trust model: docs/DESIGN.md
§13).
"""

from . import aggregation, heavy_hitters

__all__ = ["aggregation", "heavy_hitters"]
