"""Secure aggregation: streamed folds of client share vectors.

The second protocol workload on the FSS stack (PAPER.md: "privacy
preserving aggregation").  Every client holds a SHARE VECTOR — packed
uint32 words, the repo's native wire format (core/bitpack.py) — and the
aggregator's whole job is a fold over clients:

  ``xor``   bitwise XOR fold.  For XOR-shared bit vectors (what the DPF
            evaluators emit): the two aggregators' folded vectors XOR-
            reconstruct to the XOR of all client vectors — for one-hot
            client contributions, the odd-multiplicity presence bitmap
            over the domain.
  ``add``   elementwise sum mod 2^32.  For additively-shared uint32
            vectors (classic secure-aggregation counters/histograms):
            the aggregators' folds ADD-reconstruct to the true sum.

Both folds are associative with an all-zeros identity, so the aggregator
streams the upload in device-sized chunks (``DPF_TPU_AGG_CHUNK_BYTES``):
each chunk is one jitted dispatch folding [rows, words] into the running
[words] carry — a million-client sum never materializes on host, and the
sidecar's ``/v1/agg/submit`` reads the request body the same way (one
chunk off the socket, one dispatch, repeat).  Chunk dispatches go
through the plan cache (``core/plans.run_agg_fold``; rows/words
bucketed), and the fold bodies carry obliviousness certificates
(``agg/fold_xor`` / ``agg/fold_add`` in docs/OBLIVIOUS.md): a fold is
pure elementwise/reduction dataflow — no secret-dependent branch, index,
or shape.  The fold bodies ALSO carry performance contracts
(docs/PERF_CONTRACTS.md, DESIGN §16): zero collectives single-device,
exactly ONE all-reduce per chunk on the mesh with the dead carry
donated across shards — the "one all-reduce per chunk" headline is a
lint failure to regress, not a docstring.

``aggregate_eval_full`` closes the loop with the DPF layer: the
aggregator holds client KEYS (not vectors) and folds their full-domain
expansions chunk-by-chunk — the 2-server presence-bitmap protocol with
only two [words]-sized vectors ever crossing back to the caller.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import knobs, plans

__all__ = [
    "OPS",
    "chunk_rows",
    "fold_rows",
    "aggregate_chunks",
    "aggregate_rows",
    "aggregate_eval_full",
    "reconstruct",
]

OPS = ("xor", "add")


def _fold_body(op, carry, rows):
    """One chunk of the streamed aggregation: fold uint32[R, W] rows into
    the uint32[W] carry.  ``op`` is static ("xor" | "add"); both folds
    are pure elementwise dataflow over the secret rows (the certified
    property).  Zero rows are the identity for both ops, so plan-bucket
    padding never changes the sum."""
    if op == "xor":
        return carry ^ jax.lax.reduce(
            rows, np.uint32(0), jax.lax.bitwise_xor, (0,)
        )
    if op == "add":
        # uint32 addition wraps: the sum is mod 2^32 by construction.
        return carry + jnp.sum(rows, axis=0, dtype=jnp.uint32)
    raise ValueError(f"aggregation: unknown op {op!r} (use xor|add)")


_fold_jit = partial(jax.jit, static_argnums=(0,))(_fold_body)


def chunk_rows(words: int, chunk_bytes: int | None = None) -> int:
    """Rows per streamed fold dispatch: DPF_TPU_AGG_CHUNK_BYTES worth of
    ``words``-word rows (>= 1)."""
    if chunk_bytes is None:
        chunk_bytes = knobs.get_int("DPF_TPU_AGG_CHUNK_BYTES")
    return max(1, int(chunk_bytes) // max(int(words) * 4, 1))


def fold_rows(
    rows: np.ndarray, op: str, carry: np.ndarray | None = None
) -> np.ndarray:
    """Fold one chunk of share rows uint32[R, W] into ``carry`` (zeros
    when None) -> uint32[W], through the plan cache."""
    return plans.run_agg_fold(op, carry, rows)


def aggregate_chunks(chunks, op: str, words: int) -> np.ndarray:
    """Streamed aggregation driver: fold an iterable of uint32[R_i, W]
    chunks into one uint32[W] vector.  Only the carry and one chunk are
    ever live — the caller streams chunks straight off a socket or an
    expansion pipeline."""
    if op not in OPS:
        raise ValueError(f"aggregation: unknown op {op!r} (use xor|add)")
    carry = np.zeros(int(words), np.uint32)
    for chunk in chunks:
        chunk = np.asarray(chunk, dtype=np.uint32)
        if chunk.ndim != 2 or chunk.shape[1] != words:
            raise ValueError("aggregation: chunk shape mismatch")
        if chunk.shape[0]:
            carry = fold_rows(chunk, op, carry)
    return carry


def aggregate_rows(
    rows: np.ndarray, op: str, rows_per_chunk: int | None = None
) -> np.ndarray:
    """Library convenience: chunk an in-memory uint32[K, W] share matrix
    and stream it through :func:`aggregate_chunks` (identical result to
    one giant fold — the differential the tests pin)."""
    rows = np.asarray(rows, dtype=np.uint32)
    if rows.ndim != 2:
        raise ValueError("aggregation: rows must be [K, W]")
    k, words = rows.shape
    step = rows_per_chunk or chunk_rows(words)
    return aggregate_chunks(
        (rows[i : i + step] for i in range(0, k, step)), op, words
    )


def aggregate_eval_full(kb, op: str = "xor") -> np.ndarray:
    """Fold the full-domain expansions of a client KEY batch (either
    profile) chunk-by-chunk -> one uint32[out_bytes / 4] share vector.
    Two aggregators running this over their halves of the client key
    pairs hold XOR-shares of the domain's odd-multiplicity presence
    bitmap; neither ever materializes the [K, out_bytes] expansion."""
    from ..models.keys_chacha import KeyBatchFast

    if isinstance(kb, KeyBatchFast):
        from ..models.dpf_chacha import eval_full
    else:
        from ..models.dpf import eval_full

    from .heavy_hitters import slice_batch

    row_bytes = max((1 << kb.log_n) >> 3, 4)
    words = max(row_bytes // 4, 1)
    step = chunk_rows(words)
    _, cls, _ = _hh_profile(kb)

    def chunks():
        for i in range(0, kb.k, step):
            sub = slice_batch(kb, cls, slice(i, i + step))
            out = eval_full(sub)  # uint8 [k_chunk, out_bytes]
            yield np.ascontiguousarray(out[:, : words * 4]).view("<u4")

    return aggregate_chunks(chunks(), op, words)


def _hh_profile(kb):
    from .heavy_hitters import _profile_api
    from ..models.keys_chacha import KeyBatchFast

    return _profile_api(
        "fast" if isinstance(kb, KeyBatchFast) else "compat"
    )


def reconstruct(fold_a: np.ndarray, fold_b: np.ndarray, op: str) -> np.ndarray:
    """Combine the two aggregators' folded vectors into the public
    aggregate: XOR for ``xor`` shares, sum mod 2^32 for ``add`` shares."""
    a = np.asarray(fold_a, dtype=np.uint32)
    b = np.asarray(fold_b, dtype=np.uint32)
    if a.shape != b.shape:
        raise ValueError("aggregation: fold shapes differ")
    if op == "xor":
        return a ^ b
    if op == "add":
        return a + b  # uint32 wrap == mod 2^32
    raise ValueError(f"aggregation: unknown op {op!r} (use xor|add)")
