"""Device-resident frontier cache for incremental heavy-hitter descent.

The stateless driver (apps/heavy_hitters.py) re-walks every candidate
from the ROOT each round: a level-``l`` evaluation of G clients x Q
candidates costs ``G * Q * (nu + 1)`` PRG expansions (nu GGM levels plus
the leaf conversion) no matter how deep the descent already is.  But the
descent only ever asks about CHILDREN of prefixes that already survived
— and the GGM walk of a client's level-``(n-1)`` key computes, at every
tree node it visits, a control bit that IS a valid XOR share of "does
this client's value start with this node's prefix" (the level-``(n-1)``
key's point is the full value, so the sign-share invariant holds at
every depth, not just the leaves).  This module caches that walk: the
per-client seeds and control bits at the current surviving frontier stay
RESIDENT ON DEVICE between rounds, and each round extends every cached
parent ONE level (both children in one ``core/plans.run_hh_extend``
dispatch) for ``G * parents`` PRG expansions — a ``~2 * (nu + 1) /
levels_per_round`` reduction in PRG work per descent (>= 4x at
``log_n >= 16``; the tests assert it).

Past the tree depth ``nu`` the cached seeds convert to leaf planes ONCE
(``leaf_first``); deeper rounds are pure XOR folds over the resident
planes (``leaf_fold``, ZERO PRG evaluations): after XOR reconstruction
at most one leaf bit is set per client, so a range-OR over a leaf-bit
range equals the XOR fold the device computes.

Correctness stance: the frontier cache is an OPTIMIZATION of a pure
function — the share rows it produces are exactly the rows a from-root
walk of the same level-``(n-1)`` keys computes, bit for bit.  Whenever
the cache cannot serve a round (:class:`StaleState`: ancestors pruned
beyond recovery, the serving mesh changed — e.g. a circuit-breaker trip
degraded dispatch to single-device — or a dispatch died mid-donation and
poisoned the carried buffers) the owner replants the frontier at the
root and replays the SAME extend pipeline, which is byte-identical by
construction.  Privacy stance (docs/DESIGN.md §19): the frontier is
pruned on the PUBLICLY reconstructed survivor set — the same public
output the stateless protocol reveals — so which columns are kept leaks
nothing beyond the protocol's output; the cached seeds themselves are
secret taint sources (analysis/secret_hygiene_pass.py) and the extend
bodies carry obliviousness certificates like every eval body.

Knobs: ``DPF_TPU_HH_STATE`` (off|auto|on) gates the driver and serving
session registry; ``DPF_TPU_HH_STATE_MAX_SESSIONS`` /
``DPF_TPU_HH_STATE_MAX_BYTES`` / ``DPF_TPU_HH_STATE_TTL_S`` bound the
serving-side :class:`SessionCache`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from ..core import bitpack, knobs, plans

__all__ = [
    "StaleState",
    "PRG_EVALS",
    "FrontierState",
    "SessionCache",
    "serve_extend",
    "warm_ladder",
    "stateless_round_evals",
]


class StaleState(Exception):
    """The cached frontier cannot serve this round — rebuild from root
    (byte-identical by construction; see module docstring)."""


class _EvalCounter:
    """Process-wide PRG level-evaluation odometer (one unit = one PRG
    expansion or leaf conversion of one client's node).  Both the
    stateless from-root path and the incremental path report here, so a
    descent's cost ratio is a plain counter quotient in the tests and
    the bench ledger."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def add(self, n: int) -> None:
        self.value += int(n)

    def reset(self) -> int:
        v, self.value = self.value, 0
        return v


PRG_EVALS = _EvalCounter()


def stateless_round_evals(nu: int, g: int, q: int) -> int:
    """PRG level-evals one from-root round costs one aggregator: every
    (client, candidate) pair walks ``nu`` GGM levels + one leaf
    conversion regardless of the requested level."""
    return int(g) * int(q) * (int(nu) + 1)


def _children(parents: np.ndarray) -> np.ndarray:
    """Sorted depth-(d+1) children of sorted depth-d prefixes, in the
    L,R-interleaved column order the level-step bodies emit."""
    return (
        (parents[:, None] << np.uint64(1))
        | np.arange(2, dtype=np.uint64)[None, :]
    ).reshape(-1)


class FrontierState:
    """One aggregator's device-resident descent frontier over a G-key
    level-``(n-1)`` sub-batch (``HHShare.level_keys(log_n - 1)``).

    The state machine: at tree depth ``d <= nu`` the state is the
    UNPRUNED children of the last round's surviving parents — seeds and
    control bits for ``len(emitted)`` columns (``emitted``: the sorted
    depth-``d`` prefixes those columns hold), padded to the monotone
    plan bucket ``cb``.  Pruning is fused into the NEXT extension: the
    public survivor selector gathers only the surviving parent columns,
    so the consumed state and its replacement share one bucketed shape
    and the dispatch donates the dead frontier in place.  Crossing depth
    ``nu`` converts the gathered seeds to leaf planes once; from then on
    the planes are immutable (never donated) and every round is a pure
    XOR fold addressed by a public gather index.

    Column buckets only ever GROW (``cb`` is monotone per descent):
    parents fit the previous bucket, so each step at most doubles it —
    the executable ladder 32, 64, ..., cap is exactly what
    ``warm_ladder`` pre-compiles, and a repeated descent performs zero
    retraces."""

    def __init__(self, profile: str, kb, *, g: int | None = None):
        if profile not in ("fast", "compat"):
            raise ValueError(f"hh_state: unknown profile {profile!r}")
        self.profile = profile
        self.log_n = int(kb.log_n)
        self.g = int(kb.k if g is None else g)
        self.nu = int(kb.nu)
        self.ibits = self.log_n - self.nu
        _, n_shards = plans._dispatch_mesh()
        self.n_shards = n_shards
        # Compat state lane-packs the key axis (Kp = K/32 words), so a
        # sharded mesh needs whole WORDS per shard, not whole keys.
        quantum = max(n_shards, 1)
        if profile == "compat":
            quantum = 32 * quantum if n_shards else 1
        self.kp = plans._pow2_bucket(kb.k, max(plans.k_floor(), quantum, 32))
        kbp = plans._pad_keys(kb, self.kp - kb.k)
        if profile == "fast":
            (
                self._seeds, self._ts, self._scw, self._tcw, self._fcw,
            ) = kbp.device_args()
            self._fcw_words = None
        else:
            from ..models import dpf

            self._dk = dpf._cached_device_keys(kbp)
        self._lvl_args: dict = {}
        self.reset()

    # -- lifecycle ---------------------------------------------------

    def reset(self) -> None:
        """(Re)plant the frontier at the root: depth 0, one real column
        (the key's root seed + t bit), bucket-padded by repetition.  The
        per-level correction operands are never donated, so reset always
        recovers — including from a dispatch that died mid-donation."""
        import jax.numpy as jnp

        self.depth = 0
        self.cb = 32
        self.dead = False
        self.planes = None
        self.anc = None
        self.emitted = np.zeros(1, np.uint64)
        if self.profile == "fast":
            self.seed_state = tuple(
                jnp.tile(self._seeds[:, i : i + 1], (1, self.cb))
                for i in range(4)
            ) + (jnp.tile(self._ts[:, None], (1, self.cb)),)
        else:
            self.seed_state = (
                jnp.tile(self._dk.seed_planes, (1, self.cb, 1)),
                jnp.tile(self._dk.t_words, (self.cb, 1)),
            )

    @property
    def nbytes(self) -> int:
        """Device bytes held by the resident frontier (uint32 lanes)."""
        return sum(int(a.size) * 4 for a in self.seed_state)

    # -- round API ---------------------------------------------------

    def advance(self, cands: np.ndarray, depth: int) -> np.ndarray:
        """Extend the frontier to ``depth`` and return the packed
        prefix-predicate share rows uint32[G, ceil(Q/32)] for ``cands``
        (depth-``depth`` prefixes, any order, duplicates allowed) — byte
        identical to a from-root ``run_hh_level`` of the same keys.

        Raises :class:`StaleState` when the cache cannot serve (the
        caller rebuilds via :meth:`reset` and retries — a root replant
        serves ANY depth).  Any other dispatch failure marks the state
        dead: the consumed frontier was donated and may be poisoned."""
        cands = np.asarray(cands, dtype=np.uint64).reshape(-1)
        D = int(depth)
        if cands.size == 0 or not 0 < D <= self.log_n:
            raise ValueError("hh_state: bad candidate set or depth")
        if (cands >> np.uint64(D)).any():
            raise ValueError("hh_state: candidate exceeds its depth")
        if self.dead:
            raise StaleState("frontier poisoned by a failed dispatch")
        if plans._dispatch_mesh()[1] != self.n_shards:
            # Mesh changed under us (breaker degraded to single-device,
            # or recovered): the resident shards are laid out for the
            # old mesh AND the plan bucket quantum may differ.
            raise StaleState("serving mesh changed")
        if D <= self.depth and not (self.planes is not None and D > self.nu):
            raise StaleState("descent must deepen")
        try:
            return self._advance(cands, D)
        except StaleState:
            raise
        except Exception:
            self.dead = True
            raise

    def _advance(self, cands: np.ndarray, D: int) -> np.ndarray:
        rows = None
        for di in range(self.depth + 1, min(D, self.nu) + 1):
            parents = np.unique(cands >> np.uint64(D - di + 1))
            sel, cbn = self._sel(parents)
            rows = self._tree_step(di, parents, sel, cbn)
        if D > self.nu:
            m = D - self.nu
            fresh_planes = self.planes is None
            if fresh_planes:
                anc = np.unique(cands >> np.uint64(m))
                sel, cbn = self._sel(anc)
                rows = self._leaf_first(anc, sel, cbn)
            if m > 1 or not fresh_planes:
                out = self._leaf_fold(cands, m)
                self.depth = D
                return out
        self.depth = D
        return self._gather(rows, cands)

    # -- internals ---------------------------------------------------

    def _sel(self, parents: np.ndarray):
        """Survivor selector: positions of ``parents`` in the emitted
        column order, padded to the (monotone) new bucket's parent width
        by repeating column 0 — a valid column, and the resulting
        garbage children are never gathered."""
        pos = np.searchsorted(self.emitted, parents)
        if (pos >= self.emitted.size).any() or (
            self.emitted[np.minimum(pos, self.emitted.size - 1)] != parents
        ).any():
            raise StaleState("round ancestors not in cached frontier")
        cbn = max(self.cb, plans.q_bucket(2 * parents.size))
        sel = np.zeros(cbn // 2, np.int32)
        sel[: pos.size] = pos
        return sel, cbn

    def _level_operands(self, level: int) -> tuple:
        ops = self._lvl_args.get(level)
        if ops is None:
            if self.profile == "fast":
                ops = (
                    self._scw[:, level, 0], self._scw[:, level, 1],
                    self._scw[:, level, 2], self._scw[:, level, 3],
                    self._tcw[:, level, 0], self._tcw[:, level, 1],
                )
            else:
                ops = (
                    self._dk.scw_planes[level],
                    self._dk.tl_words[level],
                    self._dk.tr_words[level],
                )
            self._lvl_args[level] = ops
        return ops

    def _tree_step(self, di: int, parents, sel, cbn: int) -> np.ndarray:
        self.seed_state, rows = plans.run_hh_extend(
            self.profile, self.log_n, self.kp, "tree", self.seed_state,
            (sel,) + self._level_operands(di - 1), q=cbn,
        )
        PRG_EVALS.add(self.g * parents.size)
        self.emitted = _children(parents)
        self.depth = di
        self.cb = cbn
        return rows

    def _leaf_first(self, anc, sel, cbn: int) -> np.ndarray:
        if self.profile == "fast":
            if self._fcw_words is None:
                self._fcw_words = tuple(
                    self._fcw[:, j] for j in range(16)
                )
            args = (sel,) + self._fcw_words
        else:
            args = (sel, self._dk.fcw_planes)
        (planes,), rows = plans.run_hh_extend(
            self.profile, self.log_n, self.kp, "leaf_first", self.seed_state,
            args, q=cbn, ibits=self.ibits,
        )
        PRG_EVALS.add(self.g * anc.size)
        self.planes = planes
        self.seed_state = (planes,)
        self.anc = anc
        self.emitted = _children(anc)
        self.cb = cbn
        return rows

    def _leaf_fold(self, cands: np.ndarray, m: int) -> np.ndarray:
        """Intra-leaf depths: a pure XOR fold over the resident planes,
        addressed per requested candidate — zero PRG evaluations, no
        column gather on host (the index IS the request order)."""
        anc_pos = np.searchsorted(self.anc, cands >> np.uint64(m))
        if (anc_pos >= self.anc.size).any() or (
            self.anc[np.minimum(anc_pos, self.anc.size - 1)]
            != (cands >> np.uint64(m))
        ).any():
            raise StaleState("leaf ancestors not in converted planes")
        cbn = max(self.cb, plans.q_bucket(cands.size))
        idx = np.zeros(cbn, np.int32)
        idx[: cands.size] = (
            anc_pos.astype(np.int64) << m
        ) | (cands & np.uint64((1 << m) - 1)).astype(np.int64)
        self.cb = cbn
        _, rows = plans.run_hh_extend(
            self.profile, self.log_n, self.kp, "leaf_fold", self.seed_state,
            (idx,), q=cbn, m=m, ibits=self.ibits,
        )
        return bitpack.mask_tail(
            np.ascontiguousarray(
                rows[: self.g, : bitpack.packed_words(cands.size)]
            ),
            cands.size,
        )

    def _gather(self, rows: np.ndarray, cands: np.ndarray) -> np.ndarray:
        """Re-pack the requested candidate columns (request order) out of
        the emitted column order of the last device rows."""
        pos = np.searchsorted(self.emitted, cands)
        if (pos >= self.emitted.size).any() or (
            self.emitted[np.minimum(pos, self.emitted.size - 1)] != cands
        ).any():
            raise StaleState("requested candidates not in emitted columns")
        bits = bitpack.unpack_bits(rows[: self.g], self.emitted.size)
        return bitpack.pack_bits(bits[:, pos])


# ---------------------------------------------------------------------------
# Serving-side session registry
# ---------------------------------------------------------------------------


@dataclass
class _Session:
    sid: str
    digest: str
    profile: str
    log_n: int
    state: FrontierState
    created: float
    last_used: float
    rounds: int = 0


class SessionCache:
    """Descent-session registry for the sidecar: session id -> resident
    :class:`FrontierState`, bounded by the ``DPF_TPU_HH_STATE_*`` knobs
    (LRU count + device-byte budget + idle TTL; limits are re-read per
    call so live knob overrides apply without a restart).  All mutation
    happens under the provided lock — serving passes its stats lock so
    ``/v1/stats`` snapshots and evictions serialize with request
    bookkeeping."""

    def __init__(self, lock: threading.RLock | None = None):
        self._lock = lock if lock is not None else threading.RLock()
        self._sessions: dict[str, _Session] = {}  # insertion == LRU order
        self.hits = 0
        self.misses = 0
        self.rebuilds = 0
        self.evicted = 0

    def _evict_locked(self, sid: str) -> None:
        if self._sessions.pop(sid, None) is not None:
            self.evicted += 1

    def evict(self, sid: str) -> None:
        with self._lock:
            self._evict_locked(sid)

    def clear(self) -> None:
        with self._lock:
            self.evicted += len(self._sessions)
            self._sessions.clear()

    def nbytes(self) -> int:
        with self._lock:
            return sum(s.state.nbytes for s in self._sessions.values())

    def sweep(self, now: float | None = None) -> None:
        """Enforce TTL, session-count, and byte budgets (oldest-idle
        first; the budget never evicts the last remaining session — a
        single over-budget descent still completes incrementally)."""
        now = time.time() if now is None else now
        ttl = knobs.get_int("DPF_TPU_HH_STATE_TTL_S")
        max_n = knobs.get_int("DPF_TPU_HH_STATE_MAX_SESSIONS")
        max_b = knobs.get_int("DPF_TPU_HH_STATE_MAX_BYTES")
        with self._lock:
            for sid, s in list(self._sessions.items()):
                if now - s.last_used > ttl:
                    self._evict_locked(sid)
            by_idle = sorted(
                self._sessions, key=lambda k: self._sessions[k].last_used
            )
            while len(self._sessions) > max(max_n, 1):
                self._evict_locked(by_idle.pop(0))
            while (
                len(self._sessions) > 1
                and sum(s.state.nbytes for s in self._sessions.values())
                > max_b
            ):
                self._evict_locked(by_idle.pop(0))

    def lookup(self, sid: str, digest: str, profile: str, log_n: int):
        """The live session for ``sid`` — evicted (and None returned) if
        the caller's key material or shape no longer matches (a reused
        session id with fresh keys is a NEW descent)."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                self.misses += 1
                return None
            if (
                s.digest != digest
                or s.profile != profile
                or s.log_n != int(log_n)
            ):
                self._evict_locked(sid)
                self.misses += 1
                return None
            self.hits += 1
            s.last_used = time.time()
            return s

    def store(self, sid: str, digest: str, state: FrontierState) -> _Session:
        now = time.time()
        s = _Session(
            sid=sid, digest=digest, profile=state.profile,
            log_n=state.log_n, state=state, created=now, last_used=now,
        )
        with self._lock:
            self._sessions[sid] = s
        self.sweep(now)
        return s

    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "bytes": sum(
                    s.state.nbytes for s in self._sessions.values()
                ),
                "hits": self.hits,
                "misses": self.misses,
                "rebuilds": self.rebuilds,
                "evicted": self.evicted,
            }


def serve_extend(
    cache: SessionCache, sid: str, profile: str, kb, digest: str,
    values: np.ndarray, level: int,
) -> np.ndarray:
    """Sidecar round primitive behind ``/v1/hh/eval?session=<sid>``:
    ``kb`` is the G-key LEVEL-``(n-1)`` batch from the request body (the
    session contract — the cached walk needs the full-value key; the
    ``level`` param selects the depth as usual), ``values`` the raw
    shifted candidate values.  Pure-function semantics: the reply equals
    a from-root evaluation of those keys at ``level`` bit for bit,
    whether the cached frontier served, was rebuilt, or was just
    created."""
    depth = int(level) + 1
    prefixes = np.asarray(values, np.uint64) >> np.uint64(kb.log_n - depth)
    sess = cache.lookup(sid, digest, profile, kb.log_n)
    if sess is None:
        sess = cache.store(sid, digest, FrontierState(profile, kb))
    try:
        try:
            rows = sess.state.advance(prefixes, depth)
        except StaleState:
            with cache._lock:
                cache.rebuilds += 1
            sess.state.reset()
            rows = sess.state.advance(prefixes, depth)
    except Exception:
        # The dispatch itself failed — the donated frontier may be
        # poisoned.  Evict so the next round rebuilds from the root,
        # and let the breaker see the failure.
        cache.evict(sid)
        raise
    with cache._lock:
        sess.rounds += 1
    return rows


# ---------------------------------------------------------------------------
# Warmup
# ---------------------------------------------------------------------------


def warm_ladder(profile: str, log_n: int, k: int, q: int) -> None:
    """Drive one synthetic maximal descent (every candidate survives
    until the ``q`` cap, one level per round) over a zero key batch:
    visits the monotone bucket ladder 32, 64, ..., ``q`` of every phase
    executable — tree grow + steady state, the leaf crossing, and every
    intra-leaf fold depth — which is the exact shape set a saturating
    session touches (``core/plans.warmup`` route ``hh_extend``)."""
    from . import heavy_hitters as hh

    gen, _, _ = hh._profile_api(profile)
    ka, _ = gen(
        np.zeros(max(int(k), 1), np.uint64), int(log_n),
        rng=np.random.default_rng(0),
    )
    st = FrontierState(profile, ka)
    q = max(plans.q_bucket(max(int(q), 2)), 32)
    frontier = np.zeros(1, np.uint64)
    for d in range(1, int(log_n) + 1):
        cands = _children(frontier)
        st.advance(cands, d)
        frontier = cands
        if 2 * frontier.size > q:
            frontier = frontier[: q // 2]
