"""Prefix-tree heavy hitters over batched DPF keys.

The protocol (the DPF half of Poplar-style private heavy hitters, in the
repo's trusted-dealer / two-aggregator model): every client ``c`` holds a
private value ``x_c`` in ``[0, 2^n)`` and uploads one DPF key to each of
two aggregators; the aggregators descend the prefix tree level by level,
counting how many clients' values start with each surviving prefix, and
keep only prefixes whose count clears a PUBLIC threshold.  After the leaf
round the survivors ARE the heavy hitters, with exact counts.

Key layout — the models/fss.py comparison-gate layout, reused verbatim:
client ``c``'s share is ``n`` full-domain DPF keys, level-major across
the batch (key ``i * G + c`` is client ``c``'s level-``i`` key), where
the level-``i`` key's point is the client's ``(i+1)``-bit prefix shifted
back up to ``n`` bits (low bits zero).  Testing "does ``x_c`` start with
prefix ``p``" is then ONE pointwise evaluation of the level key at
``p << (n - 1 - i)`` — no subtree expansion — and a whole round is one
``eval_points_level_grouped(..., levels=(i,))`` dispatch of all clients
x all candidates through the plan cache (``core/plans.run_hh_level``:
the jitted walk body is level-independent, so after one warmup per
(K, Q)-bucket the entire descent performs ZERO retraces).

Trust model (docs/DESIGN.md §13): the dealer (or the clients themselves)
generates key pairs; each aggregator alone learns nothing from its share
batch (a single DPF key is pseudorandom).  Reconstruction XORs the two
aggregators' per-(client, candidate) share bits and sums them into
per-candidate counts — the counts, the threshold compare, and the
surviving candidate set are PUBLIC BY CONSTRUCTION (they are the
protocol's output at each round), and the compare runs on HOST over
those public counts: no secret ever feeds a branch, which is exactly
what the obliviousness certificates of the device eval bodies attest
(the seeded-leaky twin — a device-side threshold loop on secret counts —
is ``analysis/fixtures/bad_oblivious.leaky_hh_descend_eval``).  The
reconstructing party additionally sees which CLIENTS hold each surviving
prefix (the per-row bits); deployments that must hide that too put a
shuffler or secure adder in front — out of scope here, stated in §13.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..core import bitpack, knobs, plans
from . import hh_state

__all__ = [
    "HHShare",
    "HHRound",
    "HHResult",
    "gen_shares",
    "share_to_blob",
    "share_from_blob",
    "eval_level_shares",
    "reconstruct_counts",
    "find_heavy_hitters",
]


# The struct-of-arrays key-batch field tuple (KeyBatch and KeyBatchFast
# both declare exactly these, in this order — the same convention
# serving/batcher._concat_key_batches relies on).  Single source for the
# apps layer's sub-batch slicing.
BATCH_FIELDS = ("seeds", "ts", "scw", "tcw", "fcw")


def slice_batch(kb, cls, idx):
    """Row-slice a struct-of-arrays key batch into a new ``cls`` batch
    (``idx``: slice or index array over the key axis)."""
    return cls(
        kb.log_n,
        *(
            np.ascontiguousarray(getattr(kb, f)[idx])
            for f in BATCH_FIELDS
        ),
    )


def _profile_api(profile: str):
    """(gen_batch, batch_cls, key_len) for a profile."""
    if profile == "fast":
        from ..core.chacha_np import key_len
        from ..models.keys_chacha import KeyBatchFast, gen_batch

        return gen_batch, KeyBatchFast, key_len
    if profile == "compat":
        from ..core.keys import KeyBatch, gen_batch
        from ..core.spec import key_len

        return gen_batch, KeyBatch, key_len
    raise ValueError(f"heavy_hitters: unknown profile {profile!r}")


@dataclass
class HHShare:
    """One aggregator's share of G clients' heavy-hitters keys.

    ``levels`` holds ``log_n * G`` DPF keys, level-major (key ``i*G + c``
    is client ``c``'s level-``i`` key — the models/fss.py layout)."""

    log_n: int
    levels: object  # KeyBatch | KeyBatchFast, K = log_n * G
    profile: str = "compat"
    # Level sub-batches are sliced once and cached: each one carries its
    # own device-operand memos (masks / device_args), which must survive
    # across the descent's repeated rounds and protocol runs.
    _level_cache: dict = field(
        default_factory=dict, repr=False, compare=False
    )

    @property
    def g(self) -> int:
        return self.levels.k // self.log_n

    def level_keys(self, level: int):
        """The G-key sub-batch of every client's level-``level`` key."""
        lv = int(level)
        if not 0 <= lv < self.log_n:
            raise ValueError("heavy_hitters: level out of range")
        sub = self._level_cache.get(lv)
        if sub is None:
            G = self.g
            _, cls, _ = _profile_api(self.profile)
            sub = slice_batch(
                self.levels, cls, slice(lv * G, (lv + 1) * G)
            )
            self._level_cache[lv] = sub
        return sub


def gen_shares(
    values: np.ndarray | list[int],
    log_n: int,
    profile: str = "compat",
    rng: np.random.Generator | None = None,
    gen=None,
) -> tuple[HHShare, HHShare]:
    """Trusted-dealer generation of both aggregators' share batches for G
    client values: ONE vectorized ``gen_batch`` over all ``log_n * G``
    level-DPFs (the per-client point of level ``i`` is the client's
    ``(i+1)``-bit prefix, low bits zeroed).  ``gen`` overrides the
    profile's gen_batch — the serving layer injects its coalescing gen
    lane here so /v1/hh/gen rides the same device dealer dispatch as
    /v1/gen."""
    if gen is None:
        gen, _, _ = _profile_api(profile)
    values = np.asarray(values, dtype=np.uint64)
    if values.ndim != 1 or values.shape[0] == 0:
        raise ValueError("heavy_hitters: values must be a non-empty vector")
    if log_n < 1 or log_n > 63:
        raise ValueError("heavy_hitters: log_n out of range")
    if (values >> np.uint64(log_n)).any():
        raise ValueError("heavy_hitters: value out of domain")
    n = log_n
    shifts = (n - 1 - np.arange(n, dtype=np.uint64))[:, None]  # [n, 1]
    points = ((values[None, :] >> shifts) << shifts).reshape(n * values.shape[0])
    ka, kb = gen(points, n, rng=rng)
    return HHShare(n, ka, profile), HHShare(n, kb, profile)


def share_to_blob(share: HHShare) -> bytes:
    """Serialize a share batch CLIENT-major: client ``c``'s blob is its
    ``log_n`` level keys concatenated in level order (so an aggregator —
    or the Go client — slices one client, or one level column, with
    plain offset arithmetic); clients concatenate in order.
    ``len == G * log_n * key_len(log_n)``."""
    rows = share.levels.to_bytes()  # level-major: i*G + c
    G, n = share.g, share.log_n
    return b"".join(
        rows[i * G + c] for c in range(G) for i in range(n)
    )


def share_from_blob(
    data: bytes, log_n: int, g: int, profile: str = "compat"
) -> HHShare:
    """Parse the client-major wire blob back into a level-major share
    batch (inverse of :func:`share_to_blob`)."""
    _, cls, key_len = _profile_api(profile)
    kl = key_len(log_n)
    if len(data) != g * log_n * kl:
        raise ValueError(
            f"heavy_hitters: blob must be {g}*{log_n}*{kl} bytes"
        )
    keys = [
        bytes(data[(c * log_n + i) * kl : (c * log_n + i + 1) * kl])
        for i in range(log_n)
        for c in range(g)
    ]
    return HHShare(log_n, cls.from_bytes(keys, log_n), profile)


def eval_level_shares(
    share: HHShare, level: int, candidates: np.ndarray
) -> np.ndarray:
    """Single-aggregator round primitive: evaluate every client's
    level-``level`` key at every candidate -> packed share words
    uint32[G, ceil(Q/32)] (core/bitpack contract; candidate ``q`` of
    client row ``c`` at word q//32, bit q%32).

    ``candidates`` are RAW n-bit domain values; bits below the level's
    prefix are masked off on the way in (a depth-``level+1`` prefix ``p``
    is passed as ``p << (log_n - 1 - level)``).  The dispatch goes
    through the plan cache (``core/plans.run_hh_level``) — one warmup
    per (G, Q) bucket, zero retraces on the descent."""
    candidates = np.asarray(candidates, dtype=np.uint64).reshape(-1)
    kb = share.level_keys(level)
    xs = np.broadcast_to(candidates[None, :], (kb.k, candidates.shape[0]))
    hh_state.PRG_EVALS.add(
        hh_state.stateless_round_evals(kb.nu, kb.k, candidates.shape[0])
    )
    return plans.run_hh_level(share.profile, kb, xs, int(level))


def reconstruct_counts(
    rows_a: np.ndarray, rows_b: np.ndarray, q: int
) -> np.ndarray:
    """XOR-reconstruct the two aggregators' packed share rows and sum
    over clients -> PUBLIC per-candidate counts int64[q].  This (and the
    threshold compare on it) is the protocol's deliberate host-side,
    public-by-construction step — see the module docstring.

    Counts come from per-bit popcounts over the packed word columns —
    peak host memory is O(clients), never the unpacked [clients, q] bit
    matrix.  Counts are ADDITIVE over disjoint client partitions, so an
    aggregator pair too large for one dispatch evaluates client chunks
    separately and sums the per-chunk counts."""
    if rows_a.shape != rows_b.shape:
        raise ValueError("heavy_hitters: share row shapes differ")
    x = rows_a ^ rows_b
    q = int(q)
    fold = knobs.get_enum("DPF_TPU_HH_FOLD")
    if fold == "auto":
        import jax

        fold = "host" if jax.default_backend() == "cpu" else "mxu"
    if fold == "mxu":
        qq = min(q, x.shape[1] * 32)  # short rows count 0, as on host
        counts = np.zeros(q, np.int64)
        counts[:qq] = plans.run_hh_fold(
            np.ascontiguousarray(x[:, : bitpack.packed_words(qq)]), qq
        )
        return counts
    counts = np.zeros(q, np.int64)
    for w in range(min(x.shape[1], bitpack.packed_words(q))):
        col = x[:, w]
        for j in range(min(32, q - 32 * w)):
            counts[32 * w + j] = np.count_nonzero(
                col & np.uint32(1 << j)
            )
    return counts


@dataclass
class HHRound:
    """Public per-round protocol record (also the bench section's rows)."""

    depth: int  # prefix length AFTER this round
    levels: int  # tree levels descended this round
    n_candidates: int
    n_survivors: int
    truncated: bool  # frontier clipped to DPF_TPU_HH_MAX_CANDIDATES
    eval_s: float  # wall seconds in the two share evaluations
    key_evals: int  # clients x candidates x 2 aggregators
    # PRG level-evaluations actually performed this round (both
    # aggregators; hh_state.PRG_EVALS delta).  Stateless rounds pay
    # clients x candidates x (nu + 1) per aggregator; incremental rounds
    # pay clients x surviving-parents per extended level and ZERO for
    # intra-leaf folds — the >= 4x headline the tests assert.
    prg_level_evals: int = 0


@dataclass
class HHResult:
    values: np.ndarray  # uint64 [H] — the heavy hitters
    counts: np.ndarray  # int64 [H] — their exact client counts
    rounds: list  # list[HHRound]


def _resolve_threshold(threshold) -> int:
    if threshold is None:
        threshold = knobs.get_int("DPF_TPU_HH_THRESHOLD")
    threshold = int(threshold)
    if threshold < 1:
        raise ValueError(
            "heavy_hitters: threshold must be >= 1 (pass one explicitly "
            "or set DPF_TPU_HH_THRESHOLD)"
        )
    return threshold


def find_heavy_hitters(
    eval_a,
    eval_b,
    log_n: int | None = None,
    threshold: int | None = None,
    levels_per_round: int | None = None,
    max_candidates: int | None = None,
    state: bool | None = None,
) -> HHResult:
    """Two-aggregator protocol driver: thresholded prefix-tree descent.

    ``eval_a`` / ``eval_b`` are the aggregators — either :class:`HHShare`
    batches (evaluated in-process via :func:`eval_level_shares`) or
    callables ``(level, candidates) -> packed rows`` (e.g. POSTs to two
    sidecars' ``/v1/hh/eval``; the Go client's ``HHEvalLevel`` is the
    same shape).  ``log_n`` is required for callables.

    Each round descends ``levels_per_round`` levels (knob
    ``DPF_TPU_HH_LEVELS_PER_ROUND``): the frontier's survivors extend to
    ``2^R`` candidates each, both aggregators evaluate all candidates
    against every client in ONE dispatch, the XOR-reconstructed counts
    are thresholded on host, and the survivors become the next frontier.
    ``R`` shrinks (down to 1) when the extension would exceed
    ``DPF_TPU_HH_MAX_CANDIDATES``; if even the 2-way extension exceeds
    the cap at ``R = 1`` the lowest-count survivors are dropped and the
    round is flagged ``truncated`` (the result may then undercount — a
    frontier holds at most ``clients / threshold`` survivors and
    truncation needs ``2 * frontier > max_candidates``, so with
    ``threshold >= 2 * clients / max_candidates`` this cannot trigger).

    ``state`` selects the incremental descent engine (apps/hh_state.py):
    each aggregator's frontier seeds stay resident on device and every
    round extends only the surviving parents, instead of re-walking all
    candidates from the root.  ``None`` resolves ``DPF_TPU_HH_STATE``
    (off disables; auto/on enable).  Incremental needs in-process
    :class:`HHShare` aggregators — callables always evaluate stateless.
    The recovered hitter set and counts are IDENTICAL either way: the
    cached walk is a pure optimization, and any cache failure falls back
    to a from-root rebuild of the same pipeline mid-descent.
    """
    if isinstance(eval_a, HHShare):
        if isinstance(eval_b, HHShare):
            if (
                eval_a.log_n != eval_b.log_n
                or eval_a.g != eval_b.g
                or eval_a.profile != eval_b.profile
            ):
                raise ValueError("heavy_hitters: share batches disagree")
        log_n = eval_a.log_n
    if log_n is None:
        raise ValueError("heavy_hitters: log_n required with callables")
    n = int(log_n)
    threshold = _resolve_threshold(threshold)
    if levels_per_round is None:
        levels_per_round = knobs.get_int("DPF_TPU_HH_LEVELS_PER_ROUND")
    levels_per_round = max(int(levels_per_round), 1)
    if max_candidates is None:
        max_candidates = knobs.get_int("DPF_TPU_HH_MAX_CANDIDATES")
    max_candidates = max(int(max_candidates), 2)

    if state is None:
        state = knobs.get_enum("DPF_TPU_HH_STATE") != "off"
    frontiers: dict = {}
    if state and isinstance(eval_a, HHShare) and isinstance(eval_b, HHShare):
        for agg in (eval_a, eval_b):
            frontiers[id(agg)] = hh_state.FrontierState(
                agg.profile, agg.level_keys(n - 1)
            )

    def advance(fstate, cands, depth):
        try:
            return fstate.advance(cands, depth)
        except hh_state.StaleState:
            fstate.reset()  # replant at root; replay is byte-identical
            return fstate.advance(cands, depth)

    def run_round(level, cands, cand_values):
        # A round's two row sets must come from the SAME key pair: the
        # incremental path evaluates both aggregators' level-(n-1) keys,
        # the stateless path both aggregators' level-`level` keys — each
        # pair XOR-reconstructs the same public predicate, but the pairs
        # do not mix.  So incremental-vs-stateless is decided per ROUND,
        # for both sides atomically.
        if frontiers:
            try:
                return (
                    advance(frontiers[id(eval_a)], cands, level + 1),
                    advance(frontiers[id(eval_b)], cands, level + 1),
                )
            except Exception:
                # Device-side failure mid-extension: the donated frontier
                # is poisoned.  Drop the cache and finish the descent
                # stateless — same keys, same math, same hitters.
                frontiers.clear()
        return (
            run(eval_a, level, cand_values), run(eval_b, level, cand_values)
        )

    def run(agg, level, cand_values):
        if isinstance(agg, HHShare):
            return eval_level_shares(agg, level, cand_values)
        return agg(level, cand_values)

    depth = 0
    frontier = np.zeros(1, np.uint64)  # the empty prefix
    frontier_counts = np.zeros(1, np.int64)
    rounds: list[HHRound] = []
    while depth < n and frontier.size:
        r = min(levels_per_round, n - depth)
        while r > 1 and (frontier.size << r) > max_candidates:
            r -= 1
        truncated = False
        if (frontier.size << r) > max_candidates:  # r == 1, frontier huge
            keep_n = max_candidates >> r
            order = np.argsort(frontier_counts, kind="stable")[::-1][:keep_n]
            sel = np.sort(order)
            frontier = frontier[sel]
            frontier_counts = frontier_counts[sel]
            truncated = True
        ext = np.arange(1 << r, dtype=np.uint64)
        cands = (
            (frontier[:, None] << np.uint64(r)) | ext[None, :]
        ).reshape(-1)
        depth += r
        level = depth - 1
        cand_values = cands << np.uint64(n - depth)
        t0 = time.perf_counter()
        prg0 = hh_state.PRG_EVALS.value
        rows_a, rows_b = run_round(level, cands, cand_values)
        eval_s = time.perf_counter() - t0
        rows_a = _as_words(rows_a, cands.size)
        rows_b = _as_words(rows_b, cands.size)
        counts = reconstruct_counts(rows_a, rows_b, cands.size)
        keep = counts >= threshold
        frontier = cands[keep]
        frontier_counts = counts[keep]
        rounds.append(
            HHRound(
                depth=depth,
                levels=r,
                n_candidates=int(cands.size),
                n_survivors=int(frontier.size),
                truncated=truncated,
                eval_s=eval_s,
                key_evals=2 * int(rows_a.shape[0]) * int(cands.size),
                prg_level_evals=hh_state.PRG_EVALS.value - prg0,
            )
        )
    return HHResult(values=frontier, counts=frontier_counts, rounds=rounds)


def _as_words(rows, q: int) -> np.ndarray:
    """Normalize an aggregator reply to packed words uint32[G, wq]: a
    callable aggregator may return raw ``/v1/hh/eval?format=packed``
    wire bytes (row length infers the client count) or word arrays."""
    if isinstance(rows, (bytes, bytearray)):
        row = bitpack.packed_bytes(q)
        if row == 0 or len(rows) % row:
            raise ValueError("heavy_hitters: packed reply length mismatch")
        return bitpack.wire_to_words(rows, len(rows) // row, q)
    # host-sync: public share rows already left the device in run_hh_level
    return np.asarray(rows)
