"""ctypes bridge to the C++ AES-NI CPU backend (native/dpf_native.cc).

The native library is the framework's host-side fast path — the structural
equivalent of the reference's x86 assembly layer (dpf/aes_amd64.s) — and the
single-core baseline the TPU speedup is measured against.

The shared object is built on demand with g++ (no pip deps); if no compiler
is available the import still succeeds and ``available()`` returns False so
pure-Python/JAX paths keep working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "dpf_native.cc")
_SO = os.path.join(_REPO_ROOT, "native", "libdpf_native.so")

_lock = threading.Lock()
_lib = None
_load_error: str | None = None


def _build(force_soft: bool = False) -> None:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
    if not force_soft:
        cmd = base + ["-maes", "-mssse3", _SRC, "-o", _SO]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            return
        except (subprocess.CalledProcessError, FileNotFoundError):
            pass
    # Software-AES build: non-x86 hosts, or x86 CPUs without the AES flag.
    cmd = base + ["-DDPFN_FORCE_SOFT", _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _load():
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.dpfn_usable.restype = ctypes.c_int
            if not lib.dpfn_usable():
                # AES-NI build on a CPU without the flag: rebuild soft.
                _build(force_soft=True)
                lib = ctypes.CDLL(_SO)
                if not lib.dpfn_usable():
                    raise RuntimeError("native build unusable on this CPU")
        except Exception as e:  # noqa: BLE001 - any failure => backend absent
            _load_error = f"{type(e).__name__}: {e}"
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.dpfn_have_aesni.restype = ctypes.c_int
        lib.dpfn_key_len.restype = ctypes.c_uint64
        lib.dpfn_key_len.argtypes = [ctypes.c_uint64]
        lib.dpfn_output_len.restype = ctypes.c_uint64
        lib.dpfn_output_len.argtypes = [ctypes.c_uint64]
        lib.dpfn_gen.restype = ctypes.c_int
        lib.dpfn_gen.argtypes = [ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p, u8p]
        lib.dpfn_eval.restype = ctypes.c_int
        lib.dpfn_eval.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
        lib.dpfn_eval_full.restype = ctypes.c_int
        lib.dpfn_eval_full.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p, ctypes.c_uint64]
        lib.dpfn_eval_full_batch.restype = ctypes.c_int
        lib.dpfn_eval_full_batch.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ]
        lib.dpfn_eval_points_batch.restype = ctypes.c_int
        lib.dpfn_eval_points_batch.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        lib.dpfn_eval_points_batch_packed.restype = ctypes.c_int
        lib.dpfn_eval_points_batch_packed.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        # Fast profile (ChaCha12, core/chacha_np.py layout).
        lib.dpfn_cc_key_len.restype = ctypes.c_uint64
        lib.dpfn_cc_key_len.argtypes = [ctypes.c_uint64]
        lib.dpfn_cc_output_len.restype = ctypes.c_uint64
        lib.dpfn_cc_output_len.argtypes = [ctypes.c_uint64]
        lib.dpfn_cc_gen.restype = ctypes.c_int
        lib.dpfn_cc_gen.argtypes = [ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p, u8p]
        lib.dpfn_cc_eval.restype = ctypes.c_int
        lib.dpfn_cc_eval.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
        lib.dpfn_cc_eval_full.restype = ctypes.c_int
        lib.dpfn_cc_eval_full.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p, ctypes.c_uint64]
        lib.dpfn_cc_eval_full_batch.restype = ctypes.c_int
        lib.dpfn_cc_eval_full_batch.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ]
        lib.dpfn_cc_eval_points_batch.restype = ctypes.c_int
        lib.dpfn_cc_eval_points_batch.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        lib.dpfn_cc_eval_points_batch_packed.restype = ctypes.c_int
        lib.dpfn_cc_eval_points_batch_packed.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        # DCF (one-key-per-gate comparison, models/dcf.py layout).
        lib.dpfn_dcf_key_len.restype = ctypes.c_uint64
        lib.dpfn_dcf_key_len.argtypes = [ctypes.c_uint64]
        lib.dpfn_dcf_gen.restype = ctypes.c_int
        lib.dpfn_dcf_gen.argtypes = [ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p, u8p]
        lib.dpfn_dcf_eval_points_batch.restype = ctypes.c_int
        lib.dpfn_dcf_eval_points_batch.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        lib.dpfn_dcf_eval_points_batch_packed.restype = ctypes.c_int
        lib.dpfn_dcf_eval_points_batch_packed.argtypes = [
            u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u64p, ctypes.c_uint64, u8p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> str | None:
    _load()
    # lock-free-ok: write-once under _lock; the _load() call above synchronizes
    return _load_error


def _require():
    """The loaded library, or the RuntimeError every native entrypoint
    raises when the build/load failed (single flagged read of the
    write-once error)."""
    lib = _load()
    if lib is None:
        # lock-free-ok: write-once under _lock; stable once _load() returned
        raise RuntimeError(f"native backend unavailable: {_load_error}")
    return lib


def have_aesni() -> bool:
    lib = _load()
    return bool(lib and lib.dpfn_have_aesni())


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def gen(alpha: int, log_n: int, rng: np.random.Generator | None = None) -> tuple[bytes, bytes]:
    """Native Gen; entropy drawn host-side (deterministic with seeded rng)."""
    lib = _require()
    if rng is None:
        seeds = np.frombuffer(os.urandom(32), dtype=np.uint8).copy()
    else:
        seeds = rng.integers(0, 256, size=32, dtype=np.uint8)
    klen = int(lib.dpfn_key_len(log_n))
    ka = np.empty(klen, np.uint8)
    kb = np.empty(klen, np.uint8)
    rc = lib.dpfn_gen(alpha, log_n, _u8ptr(seeds[:16]), _u8ptr(seeds[16:]),
                      _u8ptr(ka), _u8ptr(kb))
    if rc:
        raise ValueError("dpf: invalid parameters")
    return ka.tobytes(), kb.tobytes()


def eval_point(key: bytes, x: int, log_n: int) -> int:
    lib = _require()
    kb = np.frombuffer(bytes(key), dtype=np.uint8)
    rc = lib.dpfn_eval(_u8ptr(kb), len(kb), x, log_n)
    if rc < 0:
        raise ValueError(f"dpf: native eval failed (rc={rc})")
    return rc


def eval_full(key: bytes, log_n: int) -> bytes:
    lib = _require()
    kb = np.frombuffer(bytes(key), dtype=np.uint8)
    out = np.empty(int(lib.dpfn_output_len(log_n)), np.uint8)
    rc = lib.dpfn_eval_full(_u8ptr(kb), len(kb), log_n, _u8ptr(out), out.size)
    if rc:
        raise ValueError(f"dpf: native eval_full failed (rc={rc})")
    return out.tobytes()


def eval_full_batch(keys: list[bytes], log_n: int) -> np.ndarray:
    """Sequential single-core batch (the baseline configuration)."""
    lib = _require()
    klen = int(lib.dpfn_key_len(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError("dpf: bad key length in batch")
    olen = int(lib.dpfn_output_len(log_n))
    out = np.empty((len(keys), olen), np.uint8)
    rc = lib.dpfn_eval_full_batch(_u8ptr(arr), len(keys), klen, log_n, _u8ptr(out), olen)
    if rc:
        raise ValueError(f"dpf: native eval_full_batch failed (rc={rc})")
    return out


# --------------------------------------------------------------------------
# Fast profile (ChaCha12): native mirrors of dpf_tpu.fast
# --------------------------------------------------------------------------


def cc_gen(alpha: int, log_n: int, rng: np.random.Generator | None = None) -> tuple[bytes, bytes]:
    """Native fast-profile Gen (key layout: core/chacha_np.py)."""
    lib = _require()
    if rng is None:
        seeds = np.frombuffer(os.urandom(32), dtype=np.uint8).copy()
    else:
        seeds = rng.integers(0, 256, size=32, dtype=np.uint8)
    klen = int(lib.dpfn_cc_key_len(log_n))
    ka = np.empty(klen, np.uint8)
    kb = np.empty(klen, np.uint8)
    rc = lib.dpfn_cc_gen(alpha, log_n, _u8ptr(seeds[:16]), _u8ptr(seeds[16:]),
                         _u8ptr(ka), _u8ptr(kb))
    if rc:
        raise ValueError("dpf-fast: invalid parameters")
    return ka.tobytes(), kb.tobytes()


def cc_eval_point(key: bytes, x: int, log_n: int) -> int:
    lib = _require()
    kb = np.frombuffer(bytes(key), dtype=np.uint8)
    rc = lib.dpfn_cc_eval(_u8ptr(kb), len(kb), x, log_n)
    if rc < 0:
        raise ValueError(f"dpf-fast: native eval failed (rc={rc})")
    return rc


def cc_eval_full(key: bytes, log_n: int) -> bytes:
    lib = _require()
    kb = np.frombuffer(bytes(key), dtype=np.uint8)
    out = np.empty(int(lib.dpfn_cc_output_len(log_n)), np.uint8)
    rc = lib.dpfn_cc_eval_full(_u8ptr(kb), len(kb), log_n, _u8ptr(out), out.size)
    if rc:
        raise ValueError(f"dpf-fast: native eval_full failed (rc={rc})")
    return out.tobytes()


def cc_eval_full_batch(keys: list[bytes], log_n: int) -> np.ndarray:
    lib = _require()
    klen = int(lib.dpfn_cc_key_len(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError("dpf-fast: bad key length in batch")
    olen = int(lib.dpfn_cc_output_len(log_n))
    out = np.empty((len(keys), olen), np.uint8)
    rc = lib.dpfn_cc_eval_full_batch(_u8ptr(arr), len(keys), klen, log_n, _u8ptr(out), olen)
    if rc:
        raise ValueError(f"dpf-fast: native eval_full_batch failed (rc={rc})")
    return out


def eval_points_batch(keys: list[bytes], xs: np.ndarray, log_n: int) -> np.ndarray:
    lib = _require()
    klen = int(lib.dpfn_key_len(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError("dpf: bad key length in batch")
    xs = np.ascontiguousarray(xs, dtype=np.uint64)
    k, q = xs.shape
    if k != len(keys):
        raise ValueError("xs first axis must match number of keys")
    out = np.empty((k, q), np.uint8)
    rc = lib.dpfn_eval_points_batch(
        _u8ptr(arr), k, klen, log_n,
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), q, _u8ptr(out),
    )
    if rc:
        raise ValueError(f"dpf: native eval_points_batch failed (rc={rc})")
    return out


def _points_batch_packed(
    keys: list[bytes], xs: np.ndarray, log_n: int,
    key_len_fn: str, entry: str, what: str,
) -> np.ndarray:
    """Shared driver for the three packed batch entries -> uint8 rows
    [K, ceil(Q/8)], LSB-first (the core/bitpack wire contract; the bytes
    are the like-for-like baseline of the accelerated packed routes)."""
    lib = _require()
    klen = int(getattr(lib, key_len_fn)(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError(f"{what}: bad key length in batch")
    xs = np.ascontiguousarray(xs, dtype=np.uint64)
    k, q = xs.shape
    if k != len(keys):
        raise ValueError("xs first axis must match number of keys")
    out = np.empty((k, -(-q // 8)), np.uint8)
    rc = getattr(lib, entry)(
        _u8ptr(arr), k, klen, log_n,
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), q, _u8ptr(out),
    )
    if rc:
        raise ValueError(f"{what}: native packed points batch failed (rc={rc})")
    return out


def eval_points_batch_packed(
    keys: list[bytes], xs: np.ndarray, log_n: int
) -> np.ndarray:
    """Packed-output twin of ``eval_points_batch``: uint8[K, ceil(Q/8)]
    rows, bit j of row i = Eval(keys[i], xs[i, j]) at byte j//8, bit j%8."""
    return _points_batch_packed(
        keys, xs, log_n, "dpfn_key_len", "dpfn_eval_points_batch_packed",
        "dpf",
    )


def cc_eval_points_batch(keys: list[bytes], xs: np.ndarray, log_n: int) -> np.ndarray:
    """Fast-profile batched pointwise evaluation (mirror of
    ``eval_points_batch`` over the ChaCha key layout)."""
    lib = _require()
    klen = int(lib.dpfn_cc_key_len(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError("dpf-fast: bad key length in batch")
    xs = np.ascontiguousarray(xs, dtype=np.uint64)
    k, q = xs.shape
    if k != len(keys):
        raise ValueError("xs first axis must match number of keys")
    out = np.empty((k, q), np.uint8)
    rc = lib.dpfn_cc_eval_points_batch(
        _u8ptr(arr), k, klen, log_n,
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), q, _u8ptr(out),
    )
    if rc:
        raise ValueError(f"dpf-fast: native eval_points_batch failed (rc={rc})")
    return out


def cc_eval_points_batch_packed(
    keys: list[bytes], xs: np.ndarray, log_n: int
) -> np.ndarray:
    """Packed-output twin of ``cc_eval_points_batch`` (uint8 wire rows)."""
    return _points_batch_packed(
        keys, xs, log_n, "dpfn_cc_key_len",
        "dpfn_cc_eval_points_batch_packed", "dpf-fast",
    )


# --------------------------------------------------------------------------
# DCF (one-key-per-gate comparison): native mirrors of models/dcf.py
# --------------------------------------------------------------------------


def dcf_gen(
    alpha: int, log_n: int, rng: np.random.Generator | None = None
) -> tuple[bytes, bytes]:
    """Native DCF Gen for one gate ``1{x < alpha}`` (key layout:
    models/dcf.py — seed | t | nu*(sCW|tL|tR|VCW) | FVCW)."""
    lib = _require()
    if rng is None:
        seeds = np.frombuffer(os.urandom(32), dtype=np.uint8).copy()
    else:
        seeds = rng.integers(0, 256, size=32, dtype=np.uint8)
    klen = int(lib.dpfn_dcf_key_len(log_n))
    ka = np.empty(klen, np.uint8)
    kb = np.empty(klen, np.uint8)
    rc = lib.dpfn_dcf_gen(alpha, log_n, _u8ptr(seeds[:16]), _u8ptr(seeds[16:]),
                          _u8ptr(ka), _u8ptr(kb))
    if rc:
        raise ValueError("dcf: invalid parameters")
    return ka.tobytes(), kb.tobytes()


def dcf_eval_points_batch(keys: list[bytes], xs: np.ndarray, log_n: int) -> np.ndarray:
    """Native DCF comparison walk: keys (one per gate) evaluated at xs
    uint64[K, Q] -> uint8[K, Q] shares."""
    lib = _require()
    klen = int(lib.dpfn_dcf_key_len(log_n))
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8)
    if arr.size != klen * len(keys):
        raise ValueError("dcf: bad key length in batch")
    xs = np.ascontiguousarray(xs, dtype=np.uint64)
    k, q = xs.shape
    if k != len(keys):
        raise ValueError("xs first axis must match number of keys")
    out = np.empty((k, q), np.uint8)
    rc = lib.dpfn_dcf_eval_points_batch(
        _u8ptr(arr), k, klen, log_n,
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), q, _u8ptr(out),
    )
    if rc:
        raise ValueError(f"dcf: native eval_points_batch failed (rc={rc})")
    return out


def dcf_eval_points_batch_packed(
    keys: list[bytes], xs: np.ndarray, log_n: int
) -> np.ndarray:
    """Packed-output twin of ``dcf_eval_points_batch`` (uint8 wire rows)."""
    return _points_batch_packed(
        keys, xs, log_n, "dpfn_dcf_key_len",
        "dpfn_dcf_eval_points_batch_packed", "dcf",
    )
