"""Public API of the ChaCha fast profile — the TPU-native performance mode.

Same surface as the reference-compatible API (``dpf_tpu.Gen/Eval/EvalFull``
and the batch functions), but over the fast-profile scheme: ChaCha12 PRG +
512-bit leaves (core/chacha_np.py).  Keys are NOT byte-compatible with the
reference (the reference pins fixed-key AES-128-MMO, dpf/dpf.go:22-44);
use the default profile when interoperating with reference keys.  Measured
on v5e, this profile evaluates ~20x faster than the AES-compat path.

    ka, kb = fast.Gen(alpha, log_n)
    bit    = fast.Eval(ka, x, log_n)
    out    = fast.EvalFull(ka, log_n)

    kba, kbb = fast.gen_batch(alphas, log_n)
    leaves   = fast.eval_full_batch(kba)      # uint8 [K, max(2^(n-3), 64)]
    bits     = fast.eval_points_batch(kba, xs)
"""

from __future__ import annotations

import numpy as np

from .core import chacha_np as _cc
from .core.chacha_np import key_len
from .models.dpf_chacha import eval_full as _eval_full_dev
from .models.dpf_chacha import eval_points as _eval_points_dev
from .models.dcf import (
    DcfKeyBatch,
    eval_interval_points as dcf_eval_interval_points,
    eval_lt_points as dcf_eval_lt_points,
    gen_interval_batch as dcf_gen_interval_batch,
    gen_lt_batch as dcf_gen_lt_batch,
)
from .models.dcf import key_len as dcf_key_len
from .models.keys_chacha import KeyBatchFast, gen_batch

__all__ = [
    "Gen",
    "Eval",
    "EvalFull",
    "KeyBatchFast",
    "gen_batch",
    "eval_full_batch",
    "eval_points_batch",
    "key_len",
    # one-key-per-gate comparison (DCF; models/dcf.py)
    "DcfKeyBatch",
    "dcf_gen_lt_batch",
    "dcf_eval_lt_points",
    "dcf_gen_interval_batch",
    "dcf_eval_interval_points",
    "dcf_key_len",
]


def _native():
    """The C++ backend when it is built and usable, else None."""
    from .backends import cpu_native

    return cpu_native if cpu_native.available() else None


def Gen(alpha: int, log_n: int, rng=None) -> tuple[bytes, bytes]:
    """Generate a fast-profile key pair for ``alpha`` in [0, 2^log_n)."""
    nat = _native()
    if nat is not None:
        return nat.cc_gen(alpha, log_n, rng)
    return _cc.gen(alpha, log_n, rng)


def Eval(key: bytes, x: int, log_n: int, backend: str = "auto") -> int:
    """Evaluate one share at one point -> bit.  Host-side by default (a
    single query does not amortize a device dispatch); native C++ when
    built, NumPy spec otherwise."""
    if backend in ("auto", "cpu"):
        nat = _native()
        if nat is not None:
            return nat.cc_eval_point(key, x, log_n)
        return _cc.eval_point(key, x, log_n)
    kb = KeyBatchFast.from_bytes([key], log_n)
    return int(_eval_points_dev(kb, np.array([[x]], dtype=np.uint64))[0, 0])


def EvalFull(key: bytes, log_n: int, backend: str = "auto") -> bytes:
    """Full-domain evaluation of one share -> bit-packed bytes
    (2^(log_n-3), minimum 64)."""
    if backend == "cpu":
        nat = _native()
        if nat is not None:
            return nat.cc_eval_full(key, log_n)
        return _cc.eval_full(key, log_n)
    kb = KeyBatchFast.from_bytes([key], log_n)
    return eval_full_batch(kb)[0].tobytes()


def eval_full_batch(kb: KeyBatchFast) -> np.ndarray:
    """Accelerated full-domain evaluation -> uint8[K, out_bytes]."""
    return _eval_full_dev(kb)


def eval_points_batch(
    kb: KeyBatchFast, xs: np.ndarray, backend: str = "auto",
    packed: bool = False,
) -> np.ndarray:
    """Batched pointwise evaluation: xs uint64[K, Q] -> uint8[K, Q].

    ``backend="auto"`` runs on the accelerator; ``backend="cpu"`` runs the
    host path (native C++ batch entry when built, NumPy spec otherwise) —
    useful for small batches that don't amortize a dispatch, and as the
    differential-test counterpart of the device path.

    ``packed=True`` returns bit-packed words uint32[K, ceil(Q/32)] (query
    q at word q//32, bit q%32, LSB-first, tail zero — core/bitpack.py)
    with the pack done where the bits are produced (on device, or in the
    native packed batch entry), so the transfer/wire cost drops 8-32x."""
    if backend == "cpu":
        xs = np.asarray(xs, dtype=np.uint64)
        if xs.ndim != 2 or xs.shape[0] != kb.k:
            raise ValueError("dpf-fast: xs must be [K, Q]")
        if (xs >> np.uint64(kb.log_n)).any():
            raise ValueError("dpf-fast: query index out of domain")
        keys = kb.to_bytes()
        nat = _native()
        if nat is not None:
            if packed:
                from .core import bitpack

                rows = nat.cc_eval_points_batch_packed(keys, xs, kb.log_n)
                return bitpack.byte_rows_to_words(rows, xs.shape[1])
            return nat.cc_eval_points_batch(keys, xs, kb.log_n)
        bits = np.array(
            [[_cc.eval_point(k, int(x), kb.log_n) for x in row]
             for k, row in zip(keys, xs)],
            dtype=np.uint8,
        )
        if packed:
            from .core import bitpack

            return bitpack.pack_bits(bits)
        return bits
    return _eval_points_dev(kb, xs, packed=packed)
