"""Structured timing and tracing for the accelerated DPF pipeline.

The reference's observability is a pprof CPU profile flag plus one wall-time
print (dpf_main.go:13,17-24,30).  The TPU-native equivalents here:

- ``PhaseTimer`` — named wall-clock phases (key packing, H2D, compile,
  kernel, D2H) so end-to-end numbers stay honest about where time goes
  versus kernel-only throughput (SURVEY §5.5).
- ``trace`` — context manager around ``jax.profiler`` emitting an XProf
  trace directory for op-level TPU analysis (SURVEY §5.1, the analogue of
  the reference's ``-cpuprofile``).
- ``leaves_per_sec`` — the BASELINE.json headline metric helper.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class PhaseTimer:
    """Accumulates named phase durations; one instance per measured run."""

    phases: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, dt: float, n: int = 1) -> None:
        """Record a duration measured elsewhere (the serving path times
        phases across threads and merges under its own lock)."""
        self.phases[name] = self.phases.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + n

    def as_dict(self) -> dict:
        """JSON-able snapshot (the sidecar's /v1/stats payload): per-phase
        total seconds and event counts."""
        return {
            name: {"seconds": round(dt, 6), "count": self.counts[name]}
            for name, dt in self.phases.items()
        }

    def total(self) -> float:
        return sum(self.phases.values())

    def report(self) -> str:
        """Fixed-width per-phase breakdown with shares of total."""
        tot = self.total() or 1.0
        lines = [
            f"  {name:<16} {dt * 1e3:10.2f} ms  {dt / tot * 100:5.1f}%"
            f"  (x{self.counts[name]})"
            for name, dt in sorted(self.phases.items(), key=lambda kv: -kv[1])
        ]
        lines.append(f"  {'total':<16} {tot * 1e3:10.2f} ms")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str | None):
    """XProf trace around a code region when ``log_dir`` is set; no-op
    otherwise.  View with xprof/tensorboard on the emitted directory."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def leaves_per_sec(n_keys: int, log_n: int, seconds: float) -> float:
    """The BASELINE.json throughput metric: domain leaves produced per
    second across the key batch."""
    return n_keys * float(1 << log_n) / seconds
