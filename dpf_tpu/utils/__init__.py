"""Observability utilities: phase timing, XProf tracing, throughput metrics."""

from .profiling import PhaseTimer, leaves_per_sec, trace

__all__ = ["PhaseTimer", "leaves_per_sec", "trace"]
