#!/bin/sh
# Hermetic CPU test run: 8 virtual JAX CPU devices, axon TPU plugin disabled
# (if the axon tunnel is wedged, jax.devices() hangs in any process where the
# plugin registers — unsetting PALLAS_AXON_POOL_IPS skips registration).
exec env -u PALLAS_AXON_POOL_IPS \
    -u PALLAS_AXON_REMOTE_COMPILE -u PALLAS_AXON_TPU_GEN \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}" \
    python -m pytest tests/ -q "$@"
