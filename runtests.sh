#!/bin/sh
# Hermetic CPU test run: 8 virtual JAX CPU devices, axon TPU plugin disabled
# (if the axon tunnel is wedged, jax.devices() hangs in any process where the
# plugin registers — unsetting PALLAS_AXON_POOL_IPS skips registration).
#
#   ./runtests.sh [pytest args]          full suite (tier-1 lane: slow
#       tests — multi-minute interpret-mode fused-kernel compiles — are
#       excluded by the default -m; append your own -m to override, e.g.
#       `./runtests.sh -m slow` for the fused acceptance sweep, or
#       `./runtests.sh -m ''` for absolutely everything)
#   ./runtests.sh --lint                 static-analysis lane: the ten
#       repo-native passes (knob registry incl. unused-knob detection,
#       secret hygiene, host-sync, pallas/jit discipline, test-suite
#       wiring discipline, tuned-defaults TUNED.json validation,
#       lock-discipline — the declared-lock registry, lock-order graph,
#       guarded-field inference, and held-across-blocking rules over
#       the whole serving plane — surface-contract (the cross-language
#       route/frame/error-code/header/metric/ABI vocabulary vs the
#       committed docs/CONTRACT.json), the oblivious-trace jaxpr verifier
#       with its certificate drift check, and the perf-contract
#       verifier with its collective/donation/dispatch budgets — one
#       shared trace cache, so each route traces once) + the
#       concurrency suite (tests/test_concurrency.py: every rule fires
#       on its seeded fixture, and the deterministic interleaving
#       harness reproduces seeded deadlocks/torn reads byte-for-byte)
#       + docs/KNOBS.md drift + mypy typed-core and Go vet/fmt when
#       those toolchains exist — scripts/lint_all.sh, hermetic, no TPU.
#   ./runtests.sh --fast [pytest args]   kernel differential smoke lane
#       (now incl. the protocol-applications layer, tests/test_apps.py —
#       heavy-hitters recovery + the 10^5-key plan-cached acceptance run,
#       aggregation fold differentials, hh/agg wire identity,
#       deadline/shed on the hh route — the incremental-descent frontier
#       cache (tests/test_hh_state.py — incremental-vs-from-root byte
#       identity on both profiles, the >=4x PRG-eval contract, session
#       registry bounds, fault/eviction fallback, mesh identity) — and
#       the served-PIR suite,
#       tests/test_pir_serving.py — registry/run_pir/native byte
#       identity, the streamed chunk scan, mesh dispatch + degraded
#       fallback, the /v1/pir/* wire — and the device-side dealer
#       (tests/test_gen_device.py — device-vs-host gen byte identity on
#       every key family through every door: entrypoints, run_gen
#       direct, serving mesh, host_only(), forced-failure fallback)):
#       the Pallas kernel suites (fused + walk + expand routes, interpret
#       mode), the S-box circuit invariants, the packed<->unpacked
#       output differentials (every packed route vs its byte-per-bit twin
#       plus the sidecar wire contract), the serving fast path
#       (plan cache / micro-batcher / streaming EvalFull differentials,
#       tests/test_serving.py), the wire2 binary front
#       (tests/test_wire2.py — byte-identical replies HTTP vs wire2 on
#       every compared route, multiplexed streams on one connection,
#       deadline/shed/breaker semantics on the new front, and the
#       zero-copy allocation probe), the observability plane
#       (flight-recorder span trees, strict Prometheus exposition +
#       /v1/stats equality, readyz/profile gating, tests/test_obs.py),
#       the threaded keycache/batcher stress test, and the
#       static-analysis suite's own tests — surfaces kernel + serving
#       regressions in minutes instead of the full-suite half hour.
#   ./runtests.sh --faults [pytest args] fault-injection lane: the
#       load-survival suite (tests/test_load_survival.py — admission
#       control/shedding, deadlines, circuit-breaker trip/recover,
#       degraded-mode byte identity, mid-stream abort, the 4x-overload
#       acceptance scenario) plus the threaded serving stress tests,
#       all under injected faults on CPU.  The load-survival file is
#       timing-sensitive (injected latencies, breaker cooldown sleeps),
#       so it lives ONLY here and in the full tier-1 suite — CI runs
#       this lane as its own job so a loaded fast-lane runner cannot
#       flake it and the fast job stays fast.
#   ./runtests.sh --tune [pytest args]   autotuner lane: the sweep
#       driver on the deterministic sim backend (tests/test_tune.py —
#       convergence to the seeded synthetic optimum over >= 3 routes x 2
#       profiles, wedge-abort mid-sweep + ledger resume re-measuring
#       only the in-flight config, torn-tail tolerance, TUNED.json
#       schema/staleness validation, and byte-identical plan outputs
#       with DPF_TPU_TUNED on vs off) — CPU-only, no TPU, minutes.
#   ./runtests.sh --mesh [pytest args]   mesh-native serving lane: the
#       sharded serving fast path on the 8-virtual-device CPU mesh
#       (tests/test_serving_mesh.py — byte identity of every sharded
#       route vs its single-device twin incl. the packed wire format,
#       one sharded dispatch per coalesced batch, zero retraces after
#       warmup, breaker-open fallback to single-device, the mesh
#       stats/metrics surfaces) plus the sharded-evaluator
#       differentials (tests/test_sharding.py).
# Hang watchdog (tests/conftest.py): dump all thread stacks every N s
# of no progress.  The tier-1 and --faults lanes arm it by default;
# any lane honors an explicit caller value.
HANG_DUMP="${PYTEST_HANG_DUMP_S:-}"
if [ "${1:-}" = "--lint" ]; then
  exec "$(dirname "$0")/scripts/lint_all.sh"
elif [ "${1:-}" = "--mesh" ]; then
  shift
  set -- tests/test_serving_mesh.py tests/test_sharding.py \
      -q -m 'not slow' "$@"
elif [ "${1:-}" = "--tune" ]; then
  shift
  set -- tests/test_tune.py -q -m 'not slow' "$@"
elif [ "${1:-}" = "--faults" ]; then
  shift
  # Fault lane is the hang-prone one (injected latencies, breaker
  # cooldowns, threaded stress): arm the watchdog on a short fuse.
  HANG_DUMP="${PYTEST_HANG_DUMP_S:-120}"
  set -- tests/test_load_survival.py tests/test_serving_stress.py \
      -q -m 'not slow' "$@"
elif [ "${1:-}" = "--fast" ]; then
  shift
  set -- tests/test_aes_pallas.py tests/test_chacha_pallas.py \
      tests/test_fused_expand.py tests/test_aes_bitslice.py \
      tests/test_packed.py tests/test_serving.py tests/test_obs.py \
      tests/test_serving_stress.py tests/test_analysis.py \
      tests/test_oblivious.py tests/test_perf_contracts.py \
      tests/test_apps.py tests/test_hh_state.py tests/test_pir_serving.py \
      tests/test_wire2.py tests/test_gen_device.py \
      tests/test_concurrency.py \
      -q -m 'not slow' "$@"
else
  # -m is last-wins in pytest, so a caller-supplied -m overrides ours.
  # Tier-1 arms the conftest hang watchdog: a wedged threaded test
  # dumps every thread's stack before the outer timeout kills the run.
  HANG_DUMP="${PYTEST_HANG_DUMP_S:-300}"
  set -- tests/ -q -m 'not slow' "$@"
fi
exec env -u PALLAS_AXON_POOL_IPS \
    -u PALLAS_AXON_REMOTE_COMPILE -u PALLAS_AXON_TPU_GEN \
    PYTEST_HANG_DUMP_S="${HANG_DUMP:-}" \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}" \
    python -m pytest "$@"
