// Sidecar conformance test, shaped like the reference's own test file
// (dpf/dpf_test.go: Gen, then Eval/EvalFull XOR reconstruction over the
// domain) but run THROUGH the bridge: every byte crosses the sidecar's
// wire, so a pass pins the whole client -> HTTP -> evaluator -> wire-format
// stack, in both the byte-per-bit and the bit-packed response formats.
//
// The sidecar must be reachable (default http://127.0.0.1:8990, override
// with DPFTPU_URL); otherwise the test skips — this repo's build image has
// no Go toolchain, so the one-command run lives in ../conformance.sh and
// is documented in ../README.md.
package dpftpu

import (
	"bytes"
	"crypto/sha256"
	"encoding/hex"
	"errors"
	"fmt"
	"io"
	"net"
	"net/http"
	"net/http/httptest"
	"os"
	"strings"
	"sync"
	"testing"
	"time"
)

// Frozen golden vector (generated once from the line-verified NumPy spec,
// seed 2026; second-sourced by the C++ native backend — the same pinning
// discipline as tests/test_golden_vectors.py).  The key bytes are the
// reference's serialization layout (dpf/dpf.go:89-92,111-112,165); the
// EvalFull digest pins the bit-packed output bytes (LSB-first,
// dpf/dpf.go:207-209).
const (
	goldenLogN     = 10
	goldenAlpha    = 619
	goldenKeyAHex  = "aaf912da04acce2dbf4cc3066759d1a300328e3198ef5a8188201531c5adb3726000018a70fc6937aed86c13f12d248b1bf44f000102487fd25ee2250614dc530ded5d957c0100dee5170000d98dcf94089551f5b90ddc"
	goldenKeyBHex  = "eaf18f5de5e69e77739c6f145f1fd95e01328e3198ef5a8188201531c5adb3726000018a70fc6937aed86c13f12d248b1bf44f000102487fd25ee2250614dc530ded5d957c0100dee5170000d98dcf94089551f5b90ddc"
	goldenOutASha  = "09bfd0344ab07ea01e1451c79cd643621dc33a9a5b8f16da73627623608270b2"
	goldenOutBSha  = "d752a3df0b7207f2bc609a47256db655db2d6be0c97443e29f729c99b2b53652"
)

func conformanceClient(t *testing.T) *Client {
	t.Helper()
	base := os.Getenv("DPFTPU_URL")
	if base == "" {
		base = "http://127.0.0.1:8990"
	}
	hc := &http.Client{Timeout: 2 * time.Second}
	resp, err := hc.Get(base + "/healthz")
	if err != nil {
		t.Skipf("sidecar not reachable at %s (start it or set DPFTPU_URL): %v",
			base, err)
	}
	resp.Body.Close()
	return New(base)
}

// TestConformanceGenEval mirrors the reference's Gen/Eval usage: a fresh
// key pair's point evaluations must XOR to the indicator of alpha.
func TestConformanceGenEval(t *testing.T) {
	c := conformanceClient(t)
	const logN, alpha = 10, 123
	ka, kb, err := c.Gen(alpha, logN)
	if err != nil {
		t.Fatal(err)
	}
	for _, x := range []uint64{alpha, alpha - 1, alpha + 1, 0, (1 << logN) - 1} {
		ba, err := c.Eval(ka, x, logN)
		if err != nil {
			t.Fatal(err)
		}
		bb, err := c.Eval(kb, x, logN)
		if err != nil {
			t.Fatal(err)
		}
		want := byte(0)
		if x == alpha {
			want = 1
		}
		if ba^bb != want {
			t.Fatalf("Eval reconstruction at x=%d: %d ^ %d != %d", x, ba, bb, want)
		}
	}
}

// TestConformanceGenDealer is the device-dealer gen conformance lane:
// conformance.sh starts the sidecar under DPF_TPU_GEN=on, so every key
// below is dealt by the on-device correction-word tower
// (dpf_tpu/models/keys_gen.py), then reconstruction-checked through the
// wire for both DPF profiles and a batched DCF deal.  Key BYTES cannot
// be pinned here — /v1/gen draws fresh CSPRNG entropy per request by
// design — the frozen-seed byte-identity of the device tower against
// the host tower is pinned server-side (tests/test_gen_device.py,
// injected rng).
func TestConformanceGenDealer(t *testing.T) {
	base := conformanceClient(t).BaseURL
	const logN = 10
	for _, profile := range []string{"compat", "fast"} {
		c := New(base)
		c.Profile = profile
		for _, alpha := range []uint64{0, 331, (1 << logN) - 1} {
			ka, kb, err := c.Gen(alpha, logN)
			if err != nil {
				t.Fatalf("%s dealer gen(alpha=%d): %v", profile, alpha, err)
			}
			for _, x := range []uint64{alpha, alpha ^ 1, 512} {
				ba, err := c.Eval(ka, x, logN)
				if err != nil {
					t.Fatal(err)
				}
				bb, err := c.Eval(kb, x, logN)
				if err != nil {
					t.Fatal(err)
				}
				want := byte(0)
				if x == alpha {
					want = 1
				}
				if ba^bb != want {
					t.Fatalf("%s dealer key broken at alpha=%d x=%d: %d ^ %d != %d",
						profile, alpha, x, ba, bb, want)
				}
			}
		}
	}
	// One batched DCF deal through the same coalesced gen lane.
	c := New(base)
	alphas := []uint64{17, 500, 1023}
	ka, kb, err := c.DcfGen(alphas, logN)
	if err != nil {
		t.Fatal(err)
	}
	xs := [][]uint64{{16, 17, 18}, {0, 499, 500}, {1022, 1023, 512}}
	ra, err := c.DcfEvalPoints(ka, xs, logN)
	if err != nil {
		t.Fatal(err)
	}
	rb, err := c.DcfEvalPoints(kb, xs, logN)
	if err != nil {
		t.Fatal(err)
	}
	for i := range alphas {
		for j, x := range xs[i] {
			want := byte(0)
			if x < alphas[i] {
				want = 1
			}
			if got := ra[i][j] ^ rb[i][j]; got != want {
				t.Fatalf("dcf dealer key %d broken at x=%d: got %d, want %d",
					i, x, got, want)
			}
		}
	}
}

// TestConnectionReuse pins the client's keep-alive behavior without a
// sidecar: sequential requests through one Client must ride ONE TCP
// connection (the pooled Transport; each request fully drains and closes
// the response body, which is what makes the connection reusable).  A
// regression here re-introduces a TCP+HTTP handshake per request on the
// link-bound serving path.
func TestConnectionReuse(t *testing.T) {
	var mu sync.Mutex
	conns := 0
	srv := httptest.NewUnstartedServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			w.Write([]byte{0})
		}))
	srv.Config.ConnState = func(c net.Conn, s http.ConnState) {
		if s == http.StateNew {
			mu.Lock()
			conns++
			mu.Unlock()
		}
	}
	srv.Start()
	defer srv.Close()
	c := New(srv.URL)
	for i := 0; i < 16; i++ {
		if _, err := c.Eval(DPFkey{1}, uint64(i), 10); err != nil {
			t.Fatal(err)
		}
	}
	mu.Lock()
	got := conns
	mu.Unlock()
	if got != 1 {
		t.Fatalf("16 sequential requests opened %d connections; want 1 (keep-alive reuse)", got)
	}
}

// TestConformanceEvalFull mirrors the reference's EvalFull test: the two
// shares' full expansions XOR to exactly one set bit, at alpha, in the
// LSB-first packed layout.
func TestConformanceEvalFull(t *testing.T) {
	c := conformanceClient(t)
	const logN, alpha = 10, 777
	ka, kb, err := c.Gen(alpha, logN)
	if err != nil {
		t.Fatal(err)
	}
	oa, err := c.EvalFull(ka, logN)
	if err != nil {
		t.Fatal(err)
	}
	ob, err := c.EvalFull(kb, logN)
	if err != nil {
		t.Fatal(err)
	}
	if len(oa) != (1<<logN)/8 {
		t.Fatalf("EvalFull length %d != %d", len(oa), (1<<logN)/8)
	}
	ones := 0
	for i := range oa {
		rec := oa[i] ^ ob[i]
		for b := 0; b < 8; b++ {
			if rec>>b&1 == 1 {
				ones++
				if uint64(i*8+b) != alpha {
					t.Fatalf("set bit at %d, want %d", i*8+b, alpha)
				}
			}
		}
	}
	if ones != 1 {
		t.Fatalf("reconstruction has %d set bits, want 1", ones)
	}
}

// TestConformanceGoldenVectors pushes the frozen key bytes through the
// sidecar and pins the returned output bytes — serialization AND
// evaluation cannot drift without failing here.
func TestConformanceGoldenVectors(t *testing.T) {
	c := conformanceClient(t)
	for _, v := range []struct{ keyHex, outSha string }{
		{goldenKeyAHex, goldenOutASha},
		{goldenKeyBHex, goldenOutBSha},
	} {
		key, err := hex.DecodeString(v.keyHex)
		if err != nil {
			t.Fatal(err)
		}
		out, err := c.EvalFull(DPFkey(key), goldenLogN)
		if err != nil {
			t.Fatal(err)
		}
		got := sha256.Sum256(out)
		if hex.EncodeToString(got[:]) != v.outSha {
			t.Fatalf("golden EvalFull digest drifted: %x", got)
		}
		bit, err := c.Eval(DPFkey(key), goldenAlpha, goldenLogN)
		if err != nil {
			t.Fatal(err)
		}
		if bit != out[goldenAlpha/8]>>(goldenAlpha%8)&1 {
			t.Fatalf("Eval disagrees with EvalFull bit at alpha")
		}
	}
}

// TestConformancePointsPackedAndUnpacked pins the two response formats of
// /v1/eval_points_batch against each other and against the wire contract:
// the packed reply is exactly ceil(Q/8) bytes per key (8x smaller), and
// unpacking it reproduces the byte-per-bit reply bit-for-bit.
func TestConformancePointsPackedAndUnpacked(t *testing.T) {
	c := conformanceClient(t)
	const logN, alpha = 10, 321
	const q = 37 // deliberately not a multiple of 8: tail bits must be zero
	ka, kb, err := c.Gen(alpha, logN)
	if err != nil {
		t.Fatal(err)
	}
	keys := []DPFkey{ka, kb}
	xs := make([][]uint64, len(keys))
	for i := range xs {
		xs[i] = make([]uint64, q)
		for j := range xs[i] {
			xs[i][j] = uint64((j * 53) % (1 << logN))
		}
		xs[i][0] = alpha
	}
	bits, err := c.EvalPointsBatch(keys, xs, logN)
	if err != nil {
		t.Fatal(err)
	}
	packed, err := c.EvalPointsBatchPacked(keys, xs, logN)
	if err != nil {
		t.Fatal(err)
	}
	wantRow := (q + 7) / 8
	for i := range keys {
		if len(packed[i]) != wantRow {
			t.Fatalf("packed row %d is %d bytes, want %d", i, len(packed[i]), wantRow)
		}
		got := UnpackBits(packed[i], q)
		for j := 0; j < q; j++ {
			if got[j] != bits[i][j] {
				t.Fatalf("packed/unpacked mismatch at [%d][%d]", i, j)
			}
		}
		// tail bits beyond q are zero by contract
		if tail := packed[i][wantRow-1] >> (q % 8); q%8 != 0 && tail != 0 {
			t.Fatalf("nonzero tail bits in packed row %d", i)
		}
	}
	// XOR reconstruction works directly on the packed rows.
	for j := 0; j < q; j++ {
		want := byte(0)
		if xs[0][j] == alpha {
			want = 1
		}
		ra := packed[0][j/8] >> (j % 8) & 1
		rb := packed[1][j/8] >> (j % 8) & 1
		if ra^rb != want {
			t.Fatalf("packed reconstruction at query %d", j)
		}
	}
}

// TestConformanceHeavyHitters drives the whole prefix-tree heavy-hitters
// protocol round loop through the bridge — HHGen dealer, per-level key
// slicing, two aggregators' HHEvalLevel rounds, HHCounts reconstruction,
// thresholded HHExtend descent — and pins the FROZEN protocol output:
// with these exact client values and threshold, the recovered heavy
// hitters and their counts are deterministic regardless of key
// randomness (the counts are exact, not sampled).
func TestConformanceHeavyHitters(t *testing.T) {
	c := conformanceClient(t)
	const logN, threshold = 10, 3
	// Frozen case: 613 is held by 4 clients (the one heavy hitter), 87
	// by 2 (below threshold), the rest are singletons.
	values := []uint64{613, 613, 613, 613, 87, 87, 100, 1001}
	blobA, blobB, err := c.HHGen(values, logN)
	if err != nil {
		t.Fatal(err)
	}
	round := func(level uint, cands []uint64) []int {
		rows := make([][][]byte, 2)
		for i, blob := range [][]byte{blobA, blobB} {
			keys, err := c.HHLevelKeys(blob, logN, level)
			if err != nil {
				t.Fatal(err)
			}
			if len(keys) != len(values) {
				t.Fatalf("level %d: %d keys, want %d", level, len(keys), len(values))
			}
			rows[i], err = c.HHEvalLevel(keys, cands, logN, level)
			if err != nil {
				t.Fatal(err)
			}
		}
		counts, err := HHCounts(rows[0], rows[1], len(cands))
		if err != nil {
			t.Fatal(err)
		}
		return counts
	}
	frontier := []uint64{0}
	depth := uint(0)
	for depth < logN {
		r := uint(5)
		if depth+r > logN {
			r = logN - depth
		}
		cands := HHExtend(frontier, r)
		depth += r
		counts := round(depth-1, HHQueryValues(cands, logN, depth))
		frontier = frontier[:0]
		for i, n := range counts {
			if n >= threshold {
				frontier = append(frontier, cands[i])
			}
		}
	}
	if len(frontier) != 1 || frontier[0] != 613 {
		t.Fatalf("recovered %v, want [613]", frontier)
	}
	// The leaf round's count for the survivor is the exact client count.
	final := round(logN-1, HHQueryValues(frontier, logN, logN))
	if final[0] != 4 {
		t.Fatalf("heavy hitter count %d, want 4", final[0])
	}
}

// TestConformanceAggregateGolden pins the secure-aggregation fold against
// frozen vectors: fixed uint32 share rows whose XOR and mod-2^32 sums
// are precomputed constants — the wire encoding, the chunked server-side
// fold, and the reply decoding cannot drift without failing here.
func TestConformanceAggregateGolden(t *testing.T) {
	c := conformanceClient(t)
	rows := [][]uint32{
		{0x00000001, 0xFFFFFFFF},
		{0x80000000, 0x00000001},
		{0x00000001, 0x80000000},
		{0xDEADBEEF, 0x12345678},
	}
	for _, tc := range []struct {
		op   string
		want []uint32
	}{
		{"xor", []uint32{0x5EADBEEF, 0x6DCBA986}},
		{"add", []uint32{0x5EADBEF1, 0x92345678}},
	} {
		got, err := c.AggregateSubmit(tc.op, rows)
		if err != nil {
			t.Fatal(err)
		}
		if len(got) != len(tc.want) {
			t.Fatalf("%s: reply has %d words, want %d", tc.op, len(got), len(tc.want))
		}
		for i := range got {
			if got[i] != tc.want[i] {
				t.Fatalf("%s: word %d = %#x, want %#x", tc.op, i, got[i], tc.want[i])
			}
		}
	}
	// Two aggregators' XOR folds of complementary share rows reconstruct
	// the XOR of the clear client vectors: client i's vector v_i splits
	// into (v_i ^ m_i, m_i) for a fixed mask m_i.
	clear := [][]uint32{{0x01020304, 0xA5A5A5A5}, {0xCAFEBABE, 0x0BADF00D}}
	masks := [][]uint32{{0x1111, 0x2222}, {0xFFFF0000, 0x0000FFFF}}
	sharesA := [][]uint32{
		{clear[0][0] ^ masks[0][0], clear[0][1] ^ masks[0][1]},
		{clear[1][0] ^ masks[1][0], clear[1][1] ^ masks[1][1]},
	}
	foldA, err := c.AggregateSubmit("xor", sharesA)
	if err != nil {
		t.Fatal(err)
	}
	foldB, err := c.AggregateSubmit("xor", masks)
	if err != nil {
		t.Fatal(err)
	}
	for i := range foldA {
		want := clear[0][i] ^ clear[1][i]
		if foldA[i]^foldB[i] != want {
			t.Fatalf("xor reconstruction word %d = %#x, want %#x",
				i, foldA[i]^foldB[i], want)
		}
	}
}

// TestConformancePir runs the served 2-server PIR protocol end to end
// through the bridge: register a frozen deterministic database, generate
// both aggregators' query keys, query each through /v1/pir/query, and
// XOR-reconstruct (pir_reconstruct) the rows.  The database bytes come
// from a fixed xorshift stream, so the expected rows are a frozen vector
// computed locally — a drift anywhere in the upload chunking, resident
// placement, MXU parity scan, or reply framing breaks the equality.
func TestConformancePir(t *testing.T) {
	c := conformanceClient(t)
	const (
		nRows    = 300
		rowBytes = 8
		logN     = 9 // row_domain(300, compat) — compat leaf floor 2^7
	)
	// Frozen DB: xorshift64(seed 0x2026) bytes, row-major.
	rows := make([][]byte, nRows)
	s := uint64(0x2026)
	next := func() uint64 {
		s ^= s << 13
		s ^= s >> 7
		s ^= s << 17
		return s
	}
	for i := range rows {
		rows[i] = make([]byte, rowBytes)
		v := next()
		for j := 0; j < rowBytes; j++ {
			rows[i][j] = byte(v >> (8 * j))
		}
	}
	info, err := c.PirRegisterDB("go-conformance", rows)
	if err != nil {
		t.Fatal(err)
	}
	if info.Rows != nRows || info.RowBytes != rowBytes || info.LogN != logN {
		t.Fatalf("db info %+v, want rows=%d row_bytes=%d log_n=%d",
			info, nRows, rowBytes, logN)
	}
	for _, alpha := range []uint64{0, 7, 131, nRows - 1} {
		ka, kb, err := c.Gen(alpha, logN)
		if err != nil {
			t.Fatal(err)
		}
		ansA, err := c.PirQuery("go-conformance", []DPFkey{ka}, rowBytes)
		if err != nil {
			t.Fatal(err)
		}
		ansB, err := c.PirQuery("go-conformance", []DPFkey{kb}, rowBytes)
		if err != nil {
			t.Fatal(err)
		}
		got := make([]byte, rowBytes)
		for j := range got {
			got[j] = ansA[0][j] ^ ansB[0][j]
		}
		if !bytes.Equal(got, rows[alpha]) {
			t.Fatalf("pir row %d = %x, want %x", alpha, got, rows[alpha])
		}
	}
	// Batched queries: one request, K rows back, same reconstruction.
	alphas := []uint64{3, 299, 42}
	keysA := make([]DPFkey, len(alphas))
	keysB := make([]DPFkey, len(alphas))
	for i, a := range alphas {
		ka, kb, err := c.Gen(a, logN)
		if err != nil {
			t.Fatal(err)
		}
		keysA[i], keysB[i] = ka, kb
	}
	ansA, err := c.PirQuery("go-conformance", keysA, rowBytes)
	if err != nil {
		t.Fatal(err)
	}
	ansB, err := c.PirQuery("go-conformance", keysB, rowBytes)
	if err != nil {
		t.Fatal(err)
	}
	for i, a := range alphas {
		got := make([]byte, rowBytes)
		for j := range got {
			got[j] = ansA[i][j] ^ ansB[i][j]
		}
		if !bytes.Equal(got, rows[a]) {
			t.Fatalf("pir batch row %d = %x, want %x", a, got, rows[a])
		}
	}
	// Unknown database -> structured 400, never a crash.
	if _, err := c.PirQuery("no-such-db", keysA, rowBytes); err == nil {
		t.Fatal("query against unknown db succeeded")
	} else {
		var apiErr *APIError
		if !errors.As(err, &apiErr) || apiErr.Status != 400 {
			t.Fatalf("unknown db error = %v, want 400 *APIError", err)
		}
	}
}

// TestStructuredErrorParsing pins the load-survival error contract: a
// 429 shed reply with a {code, detail} JSON body and a Retry-After
// header must surface as *APIError with every field recovered — that is
// what lets a client (the loadgen, a production caller) distinguish
// "back off and retry" from "your request is malformed".
func TestStructuredErrorParsing(t *testing.T) {
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			w.Header().Set("Content-Type", "application/json")
			w.Header().Set("Retry-After", "2")
			w.WriteHeader(http.StatusTooManyRequests)
			w.Write([]byte(`{"code": "shed", "detail": "lane queue full"}`))
		}))
	defer srv.Close()
	c := New(srv.URL)
	_, err := c.Eval(DPFkey{1}, 0, 10)
	var apiErr *APIError
	if !errors.As(err, &apiErr) {
		t.Fatalf("want *APIError, got %T: %v", err, err)
	}
	if apiErr.Status != 429 || apiErr.Code != "shed" ||
		apiErr.Detail != "lane queue full" || apiErr.RetryAfter != 2 {
		t.Fatalf("APIError fields not recovered: %+v", apiErr)
	}
	if !apiErr.Temporary() {
		t.Fatal("429 shed must classify as Temporary")
	}
	// Legacy/plain-text error bodies still produce a usable error.
	srv2 := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			http.Error(w, "ValueError: bad body", http.StatusBadRequest)
		}))
	defer srv2.Close()
	_, err = New(srv2.URL).Eval(DPFkey{1}, 0, 10)
	if !errors.As(err, &apiErr) || apiErr.Status != 400 ||
		!strings.Contains(apiErr.Detail, "bad body") {
		t.Fatalf("plain-text error not preserved: %v", err)
	}
	if apiErr.Temporary() {
		t.Fatal("400 must not classify as Temporary")
	}
}

// TestEvalFullTruncationDetected pins the mid-stream-failure contract
// from the client side: a body shorter than the declared Content-Length
// (the sidecar hard-aborts the connection on a mid-stream dispatch
// error) must be an error, never a silently short expansion.
func TestEvalFullTruncationDetected(t *testing.T) {
	const logN = 10
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			w.Header().Set("Content-Length", "128")
			w.WriteHeader(http.StatusOK)
			w.Write(make([]byte, 64)) // half the declared body, then close
		}))
	defer srv.Close()
	if _, err := New(srv.URL).EvalFull(DPFkey{1}, logN); err == nil {
		t.Fatal("truncated EvalFull body must be an error")
	}
}

// TestEvalFullLengthChecked covers the other truncation shape: a
// complete (Content-Length-consistent) reply of the WRONG length for
// the profile's expansion contract must also fail.
func TestEvalFullLengthChecked(t *testing.T) {
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			w.Write(make([]byte, 5))
		}))
	defer srv.Close()
	c := New(srv.URL)
	if _, err := c.EvalFull(DPFkey{1}, 10); err == nil ||
		!strings.Contains(err.Error(), "want 128") {
		t.Fatalf("wrong-length EvalFull must fail the 128-byte contract, got %v",
			err)
	}
	if _, err := c.EvalFullBatch([]DPFkey{{1}, {2}}, 10); err == nil {
		t.Fatal("wrong-length EvalFullBatch must fail the contract")
	}
}

// TestDeadlineHeaderSent pins the client half of the deadline contract.
func TestDeadlineHeaderSent(t *testing.T) {
	var mu sync.Mutex
	got := []string{}
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			mu.Lock()
			got = append(got, r.Header.Get("X-DPF-Deadline-Ms"))
			mu.Unlock()
			w.Write([]byte{0})
		}))
	defer srv.Close()
	c := New(srv.URL)
	if _, err := c.Eval(DPFkey{1}, 0, 10); err != nil {
		t.Fatal(err)
	}
	c.DeadlineMs = 250
	if _, err := c.Eval(DPFkey{1}, 0, 10); err != nil {
		t.Fatal(err)
	}
	mu.Lock()
	defer mu.Unlock()
	if got[0] != "" || got[1] != "250" {
		t.Fatalf("deadline headers %v, want [\"\" \"250\"]", got)
	}
}

// TestTraceHeaderSent pins the tracing contract: every request carries a
// fresh 16-hex-char X-DPF-Trace id (the sidecar's flight recorder keys
// span trees on it), distinct across requests, and Trace=false drops the
// header entirely.
func TestTraceHeaderSent(t *testing.T) {
	var mu sync.Mutex
	got := []string{}
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			io.Copy(io.Discard, r.Body)
			mu.Lock()
			got = append(got, r.Header.Get("X-DPF-Trace"))
			mu.Unlock()
			w.Write([]byte{0})
		}))
	defer srv.Close()
	c := New(srv.URL)
	for i := 0; i < 2; i++ {
		if _, err := c.Eval(DPFkey{1}, 0, 10); err != nil {
			t.Fatal(err)
		}
	}
	c.Trace = false
	if _, err := c.Eval(DPFkey{1}, 0, 10); err != nil {
		t.Fatal(err)
	}
	mu.Lock()
	defer mu.Unlock()
	for i := 0; i < 2; i++ {
		if len(got[i]) != 16 {
			t.Fatalf("trace id %d = %q, want 16 hex chars", i, got[i])
		}
		if _, err := hex.DecodeString(got[i]); err != nil {
			t.Fatalf("trace id %d = %q is not hex", i, got[i])
		}
	}
	if got[0] == got[1] {
		t.Fatalf("trace ids must be unique per request, got %q twice", got[0])
	}
	if got[2] != "" {
		t.Fatalf("Trace=false must omit the header, got %q", got[2])
	}
}

// TestConcurrentClientRace drives one shared Client from 16 goroutines
// through the pooled Transport against a local double — no sidecar
// needed, so `go test -race ./dpftpu` exercises the connection pool and
// response handling under the race detector in every environment
// (conformance.sh runs the whole suite under -race).  Each goroutine
// checks it got ITS OWN reply byte back: a pooled-transport race that
// crossed response bodies between requests would surface here as a
// wrong byte, not just a detector report.
func TestConcurrentClientRace(t *testing.T) {
	srv := httptest.NewServer(http.HandlerFunc(
		func(w http.ResponseWriter, r *http.Request) {
			body, _ := io.ReadAll(r.Body)
			w.Write(body)
		}))
	defer srv.Close()
	c := New(srv.URL)
	const goroutines, perG = 16, 25
	var wg sync.WaitGroup
	errs := make(chan error, goroutines)
	for g := 0; g < goroutines; g++ {
		wg.Add(1)
		go func(g int) {
			defer wg.Done()
			for i := 0; i < perG; i++ {
				// Unique per request, so ANY crossed reply — even
				// between two in-flight requests — is a wrong byte.
				mark := []byte{byte(g), byte(i)}
				out, err := c.post("/v1/eval?log_n=10&x=0", mark)
				if err != nil {
					errs <- err
					return
				}
				if !bytes.Equal(out, mark) {
					errs <- fmt.Errorf(
						"goroutine %d got %v, want %v — crossed replies",
						g, out, mark)
					return
				}
			}
		}(g)
	}
	wg.Wait()
	close(errs)
	for err := range errs {
		t.Fatal(err)
	}
}
