// Package dpftpu is a Go client for the dpf_tpu evaluation sidecar.
//
// It mirrors the reference library's public surface (dpf/dpf.go: Gen, Eval,
// EvalFull, type DPFkey []byte) over the sidecar's HTTP endpoints
// (dpf_tpu/server.py), keeping the reference's keys-as-bytes wire contract:
// the bytes this client sends and receives are byte-identical to the
// reference implementation's keys and outputs in the default ("compat")
// profile.  Only the execution moved — from in-process AES-NI assembly to a
// TPU evaluator behind a socket.
//
// Start the sidecar, then point the client at it:
//
//	python -m dpf_tpu.server --port 8990
//
//	c := dpftpu.New("http://127.0.0.1:8990")
//	ka, kb, err := c.Gen(123, 20)
//	out, err := c.EvalFull(ka, 20)
package dpftpu

import (
	"bytes"
	crand "crypto/rand"
	"encoding/binary"
	"encoding/hex"
	"encoding/json"
	"fmt"
	"io"
	"net/http"
	"net/url"
	"strconv"
	"time"
)

// DPFkey is an opaque serialized DPF key, byte-compatible with the
// reference's type of the same name (dpf/dpf.go:7).
type DPFkey []byte

// Client talks to one dpf_tpu sidecar.  Profile selects the evaluation
// profile: "compat" (reference-key-compatible AES-MMO; default) or "fast"
// (the TPU-native ChaCha profile — keys are NOT reference-compatible).
//
// DeadlineMs, when positive, is sent as the X-DPF-Deadline-Ms header on
// every request: the sidecar cancels work whose deadline expires while
// queued (before it burns a device slot) and answers 504 — the
// load-survival contract that keeps p99 bounded under overload.
//
// Trace (on by default from New) stamps a fresh X-DPF-Trace id on every
// request, so each request's span tree in the sidecar's flight recorder
// (GET /v1/trace) carries a client-originated id — the handle for
// answering "which of MY requests waited where" after an incident.
type Client struct {
	BaseURL    string
	Profile    string
	DeadlineMs int
	Trace      bool
	HTTP       *http.Client
}

// newTraceID returns a 16-hex-char request trace id.  crypto/rand so
// concurrent goroutines never collide (math/rand's global source would
// need locking anyway).
func newTraceID() string {
	var b [8]byte
	if _, err := crand.Read(b[:]); err != nil {
		return "" // header omitted; the sidecar generates one at ingress
	}
	return hex.EncodeToString(b[:])
}

// APIError is a structured non-200 sidecar reply.  The load-survival
// layer answers with {code, detail} JSON bodies: code "shed" (429, past
// an admission watermark), "unavailable" (503, device circuit open),
// "deadline" (504), "bad_request" (400), or "internal" (500).
// RetryAfter carries the parsed Retry-After header in seconds (0 when
// absent) — the sidecar derives it from observed dispatch latency, so
// honoring it is the fastest route back to goodput.
type APIError struct {
	Status     int
	Code       string
	Detail     string
	RetryAfter float64
}

func (e *APIError) Error() string {
	return fmt.Sprintf("dpftpu: %d %s: %s", e.Status, e.Code, e.Detail)
}

// Temporary reports whether backing off and retrying is expected to
// succeed (shed / open-circuit / missed-deadline replies).
func (e *APIError) Temporary() bool {
	return e.Status == http.StatusTooManyRequests ||
		e.Status == http.StatusServiceUnavailable ||
		e.Status == http.StatusGatewayTimeout
}

func newAPIError(resp *http.Response, body []byte) *APIError {
	e := &APIError{Status: resp.StatusCode, Detail: string(body)}
	var parsed struct {
		Code   string `json:"code"`
		Detail string `json:"detail"`
	}
	if json.Unmarshal(body, &parsed) == nil && parsed.Code != "" {
		e.Code, e.Detail = parsed.Code, parsed.Detail
	}
	if ra := resp.Header.Get("Retry-After"); ra != "" {
		if v, err := strconv.ParseFloat(ra, 64); err == nil {
			e.RetryAfter = v
		}
	}
	return e
}

// New returns a client for the sidecar at baseURL (e.g.
// "http://127.0.0.1:8990") using the compat profile.
//
// The client owns a pooled Transport with HTTP keep-alive: on the
// link-bound serving path a fresh TCP + HTTP handshake per request costs
// more than many evaluations, and the sidecar's micro-batcher can only
// coalesce requests that actually arrive concurrently — connection churn
// serializes them.  The pool keeps enough idle connections per host for
// a busy client's worker fan-out (http.DefaultTransport caps idle
// connections per host at 2, which churns under any real concurrency).
func New(baseURL string) *Client {
	// Clone the default transport so proxy handling and dial/TLS
	// timeouts keep their stdlib behavior; widen only the idle pool.
	tr := http.DefaultTransport.(*http.Transport).Clone()
	tr.MaxIdleConns = 64
	tr.MaxIdleConnsPerHost = 64
	return &Client{
		BaseURL: baseURL,
		Profile: "compat",
		Trace:   true,
		// Full-domain expansions at large n take seconds on first compile.
		HTTP: &http.Client{
			Timeout:   120 * time.Second,
			Transport: tr,
		},
	}
}

func (c *Client) post(path string, body []byte) ([]byte, error) {
	url := c.BaseURL + path + "&profile=" + c.Profile
	req, err := http.NewRequest(http.MethodPost, url, bytes.NewReader(body))
	if err != nil {
		return nil, fmt.Errorf("dpftpu: %w", err)
	}
	req.Header.Set("Content-Type", "application/octet-stream")
	if c.DeadlineMs > 0 {
		req.Header.Set("X-DPF-Deadline-Ms", strconv.Itoa(c.DeadlineMs))
	}
	if c.Trace {
		if id := newTraceID(); id != "" {
			req.Header.Set("X-DPF-Trace", id)
		}
	}
	resp, err := c.HTTP.Do(req)
	if err != nil {
		return nil, fmt.Errorf("dpftpu: %w", err)
	}
	defer resp.Body.Close()
	out, err := io.ReadAll(resp.Body)
	if err != nil {
		// A short body against the declared Content-Length (the
		// sidecar RSTs the connection on a mid-stream dispatch
		// failure) surfaces here as unexpected EOF / connection reset:
		// truncation is always a loud error, never a silent short read.
		return nil, fmt.Errorf("dpftpu: reading response: %w", err)
	}
	if resp.StatusCode != http.StatusOK {
		// Structured {code, detail} JSON errors (429/503/504/400/500)
		// surface as *APIError — a Go error, never a panic (SURVEY
		// §5.3); errors.As recovers status/code/Retry-After.
		return nil, newAPIError(resp, out)
	}
	return out, nil
}

// expansionBytes is the sidecar's EvalFull output-row contract:
// 2^(logN-3) bytes with the profile's leaf-width floor (compat 16,
// fast 64) — dpf_tpu/server.py:_evalfull_out_bytes.
func expansionBytes(logN uint, profile string) int {
	n := (1 << logN) / 8
	floor := 16
	if profile == "fast" {
		floor = 64
	}
	if n < floor {
		n = floor
	}
	return n
}

// Gen generates a key pair hiding alpha in [0, 2^logN), mirroring the
// reference Gen (dpf/dpf.go:71).  The point is a query parameter because
// generation happens server-side (the sidecar holds the CSPRNG).
func (c *Client) Gen(alpha uint64, logN uint) (DPFkey, DPFkey, error) {
	out, err := c.post(
		fmt.Sprintf("/v1/gen?log_n=%d&alpha=%d", logN, alpha), nil)
	if err != nil {
		return nil, nil, err
	}
	if len(out)%2 != 0 || len(out) == 0 {
		return nil, nil, fmt.Errorf("dpftpu: bad gen reply length %d", len(out))
	}
	h := len(out) / 2
	return DPFkey(out[:h]), DPFkey(out[h:]), nil
}

// Eval evaluates one share at point x, mirroring the reference Eval
// (dpf/dpf.go:171): returns 0 or 1.
func (c *Client) Eval(k DPFkey, x uint64, logN uint) (byte, error) {
	out, err := c.post(
		fmt.Sprintf("/v1/eval?log_n=%d&x=%d", logN, x), k)
	if err != nil {
		return 0, err
	}
	if len(out) != 1 {
		return 0, fmt.Errorf("dpftpu: bad eval reply length %d", len(out))
	}
	return out[0], nil
}

// EvalFull expands one share over the whole domain, mirroring the reference
// EvalFull (dpf/dpf.go:243): returns 2^(logN-3) bit-packed bytes (bit x at
// byte x/8, bit x%8 — the reference's LSB-first layout).  The reply length
// is validated against the profile's output contract, so a truncated (or
// corrupt) streamed body can never pass as a short-but-valid expansion.
func (c *Client) EvalFull(k DPFkey, logN uint) ([]byte, error) {
	out, err := c.post(fmt.Sprintf("/v1/evalfull?log_n=%d", logN), k)
	if err != nil {
		return nil, err
	}
	if want := expansionBytes(logN, c.Profile); len(out) != want {
		return nil, fmt.Errorf(
			"dpftpu: evalfull reply is %d bytes, want %d (truncated or corrupt)",
			len(out), want)
	}
	return out, nil
}

// pointsBody serializes K keys plus their K*Q little-endian query indices
// (the shared request body of the points endpoints).
func pointsBody(keys []DPFkey, xs [][]uint64) ([]byte, int, error) {
	if len(xs) != len(keys) {
		return nil, 0, fmt.Errorf("dpftpu: xs rows != key count")
	}
	kl := len(keys[0])
	nq := len(xs[0])
	body := make([]byte, 0, kl*len(keys)+8*nq*len(keys))
	for _, k := range keys {
		if len(k) != kl {
			return nil, 0, fmt.Errorf("dpftpu: inconsistent key lengths")
		}
		body = append(body, k...)
	}
	for _, row := range xs {
		if len(row) != nq {
			return nil, 0, fmt.Errorf("dpftpu: inconsistent query row lengths")
		}
		for _, x := range row {
			body = binary.LittleEndian.AppendUint64(body, x)
		}
	}
	return body, nq, nil
}

// EvalPointsBatch evaluates K shares at Q points each in one round trip:
// xs[i] holds key i's Q query indices; the reply bit [i][j] is
// Eval(keys[i], xs[i][j]).  All keys must have the same logN and every
// row of xs the same length.
func (c *Client) EvalPointsBatch(keys []DPFkey, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	body, nq, err := pointsBody(keys, xs)
	if err != nil {
		return nil, err
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/eval_points_batch?log_n=%d&k=%d&q=%d", logN, len(keys), nq), body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(keys)*nq {
		return nil, fmt.Errorf("dpftpu: bad points reply length %d", len(out))
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*nq : (i+1)*nq]
	}
	return res, nil
}

// EvalPointsBatchPacked is EvalPointsBatch over the bit-packed wire format
// (format=packed): each reply row is ceil(Q/8) bytes with query j at byte
// j/8, bit j%8 — LSB-first, the same convention as EvalFull's output and
// the reference's (dpf/dpf.go:207-209); bits beyond Q are zero.  The
// response is 8x smaller than the byte-per-bit format — on a link-bound
// serving path that is an 8x throughput difference.  Unpack rows with
// UnpackBits, or XOR two parties' packed rows directly (reconstruction
// commutes with the packing).
func (c *Client) EvalPointsBatchPacked(keys []DPFkey, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	body, nq, err := pointsBody(keys, xs)
	if err != nil {
		return nil, err
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/eval_points_batch?log_n=%d&k=%d&q=%d&format=packed",
		logN, len(keys), nq), body)
	if err != nil {
		return nil, err
	}
	row := (nq + 7) / 8
	if len(out) != len(keys)*row {
		return nil, fmt.Errorf("dpftpu: bad packed reply length %d", len(out))
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*row : (i+1)*row]
	}
	return res, nil
}

// UnpackBits expands a packed row (LSB-first, the packed wire format) to
// q bytes of 0/1 bits — the inverse of the server-side packing.
func UnpackBits(row []byte, q int) []byte {
	bits := make([]byte, q)
	for j := 0; j < q; j++ {
		bits[j] = (row[j>>3] >> (j & 7)) & 1
	}
	return bits
}

// DcfGen generates K one-key-per-gate comparison key pairs: evaluating a
// pair's shares at x and XORing them yields 1{x < alphas[i]}
// (models/dcf.py; fast-profile keys, ~30x smaller than per-level FSS
// gates).  Returns the two parties' key slices.
func (c *Client) DcfGen(alphas []uint64, logN uint) ([]DPFkey, []DPFkey, error) {
	if len(alphas) == 0 {
		return nil, nil, nil
	}
	body := make([]byte, 0, 8*len(alphas))
	for _, a := range alphas {
		body = binary.LittleEndian.AppendUint64(body, a)
	}
	out, err := c.post(
		fmt.Sprintf("/v1/dcf_gen?log_n=%d&k=%d", logN, len(alphas)), body)
	if err != nil {
		return nil, nil, err
	}
	n := len(alphas)
	if len(out) == 0 || len(out)%(2*n) != 0 {
		return nil, nil, fmt.Errorf("dpftpu: bad dcf_gen reply length %d", len(out))
	}
	kl := len(out) / (2 * n)
	split := func(off int) []DPFkey {
		keys := make([]DPFkey, n)
		for i := range keys {
			keys[i] = DPFkey(out[off+i*kl : off+(i+1)*kl])
		}
		return keys
	}
	return split(0), split(n * kl), nil
}

// DcfEvalPoints evaluates K comparison shares at Q points each in one
// round trip; reply bit [i][j] XORed across parties is 1{xs[i][j] < alpha_i}.
func (c *Client) DcfEvalPoints(keys []DPFkey, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	body, nq, err := pointsBody(keys, xs)
	if err != nil {
		return nil, err
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/dcf_eval_points?log_n=%d&k=%d&q=%d", logN, len(keys), nq), body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(keys)*nq {
		return nil, fmt.Errorf("dpftpu: bad dcf points reply length %d", len(out))
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*nq : (i+1)*nq]
	}
	return res, nil
}

// DcfIntervalGen generates K interval gates 1{lo[i] <= x <= hi[i]} and
// returns the two parties' shares as opaque blobs (upper+lower DCF key
// sets plus the public wrap-edge constant; pass a blob unchanged to
// DcfIntervalEval).
func (c *Client) DcfIntervalGen(lo, hi []uint64, logN uint) ([]byte, []byte, error) {
	if len(lo) != len(hi) {
		return nil, nil, fmt.Errorf("dpftpu: lo/hi length mismatch")
	}
	if len(lo) == 0 {
		return nil, nil, nil
	}
	body := make([]byte, 0, 16*len(lo))
	for _, v := range lo {
		body = binary.LittleEndian.AppendUint64(body, v)
	}
	for _, v := range hi {
		body = binary.LittleEndian.AppendUint64(body, v)
	}
	out, err := c.post(
		fmt.Sprintf("/v1/dcf_interval_gen?log_n=%d&k=%d", logN, len(lo)), body)
	if err != nil {
		return nil, nil, err
	}
	if len(out) == 0 || len(out)%2 != 0 {
		return nil, nil, fmt.Errorf(
			"dpftpu: bad dcf_interval_gen reply length %d", len(out))
	}
	h := len(out) / 2
	return out[:h], out[h:], nil
}

// DcfIntervalEval evaluates one party's interval blob (from
// DcfIntervalGen) at Q points per gate; XORing the parties' replies
// yields 1{lo_i <= xs[i][j] <= hi_i}.
func (c *Client) DcfIntervalEval(blob []byte, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(xs) == 0 {
		return nil, nil
	}
	nq := len(xs[0])
	body := make([]byte, 0, len(blob)+8*nq*len(xs))
	body = append(body, blob...)
	for _, row := range xs {
		if len(row) != nq {
			return nil, fmt.Errorf("dpftpu: inconsistent query row lengths")
		}
		for _, x := range row {
			body = binary.LittleEndian.AppendUint64(body, x)
		}
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/dcf_interval_eval?log_n=%d&k=%d&q=%d", logN, len(xs), nq), body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(xs)*nq {
		return nil, fmt.Errorf(
			"dpftpu: bad dcf interval reply length %d", len(out))
	}
	res := make([][]byte, len(xs))
	for i := range xs {
		res[i] = out[i*nq : (i+1)*nq]
	}
	return res, nil
}

// hhKeyLen is one serialized DPF key's size for a profile:
// 17 + 18*nu + leafBytes, where nu = logN - log2(leafBits) (compat: 128-bit
// leaves, 16-byte final CW; fast: 512-bit leaves, 64-byte final CW) —
// dpf_tpu/core/spec.key_len and core/chacha_np.key_len.
func hhKeyLen(logN uint, profile string) int {
	leafLog, leafBytes := uint(7), 16
	if profile == "fast" {
		leafLog, leafBytes = 9, 64
	}
	nu := 0
	if logN > leafLog {
		nu = int(logN - leafLog)
	}
	return 17 + 18*nu + leafBytes
}

// HHGen asks the sidecar's trusted dealer for both aggregators' share
// blobs of the prefix-tree heavy-hitters protocol: values[c] is client
// c's private value in [0, 2^logN).  Each blob holds one DPF key per
// (client, tree level), client-major — slice one round's key column out
// with HHLevelKeys.  In a real deployment clients generate their own
// pairs and upload to the two aggregators separately; this endpoint is
// the dealer convenience for tests and benchmarks.
func (c *Client) HHGen(values []uint64, logN uint) ([]byte, []byte, error) {
	if len(values) == 0 {
		return nil, nil, nil
	}
	body := make([]byte, 0, 8*len(values))
	for _, v := range values {
		body = binary.LittleEndian.AppendUint64(body, v)
	}
	out, err := c.post(
		fmt.Sprintf("/v1/hh/gen?log_n=%d&k=%d", logN, len(values)), body)
	if err != nil {
		return nil, nil, err
	}
	want := 2 * len(values) * int(logN) * hhKeyLen(logN, c.Profile)
	if len(out) != want {
		return nil, nil, fmt.Errorf(
			"dpftpu: bad hh gen reply length %d, want %d", len(out), want)
	}
	h := len(out) / 2
	return out[:h], out[h:], nil
}

// HHLevelKeys slices level ``level``'s key column (one key per client)
// out of a client-major share blob from HHGen — the upload body of one
// HHEvalLevel round.
func (c *Client) HHLevelKeys(shareBlob []byte, logN, level uint) ([]DPFkey, error) {
	kl := hhKeyLen(logN, c.Profile)
	per := int(logN) * kl
	if per == 0 || len(shareBlob) == 0 || len(shareBlob)%per != 0 {
		return nil, fmt.Errorf(
			"dpftpu: hh share blob length %d is not a multiple of %d",
			len(shareBlob), per)
	}
	if level >= logN {
		return nil, fmt.Errorf("dpftpu: hh level %d out of range", level)
	}
	keys := make([]DPFkey, len(shareBlob)/per)
	for i := range keys {
		off := i*per + int(level)*kl
		keys[i] = DPFkey(shareBlob[off : off+kl])
	}
	return keys, nil
}

// HHEvalLevel runs one heavy-hitters round at one aggregator: every
// client's level key evaluated at every candidate (candidates are raw
// n-bit domain values — a depth d prefix p goes in as p << (logN - d);
// see HHQueryValues).  The reply is one bit-packed row per client
// (ceil(Q/8) bytes, the packed wire contract); XOR two aggregators'
// rows and popcount with HHCounts for the public per-candidate counts.
func (c *Client) HHEvalLevel(levelKeys []DPFkey, candidates []uint64, logN, level uint) ([][]byte, error) {
	return c.HHEvalLevelSession(levelKeys, candidates, logN, level, "")
}

// HHEvalLevelSession is HHEvalLevel with the incremental-descent session
// contract: a non-empty session id pins a device-resident frontier at
// the aggregator, and every round of that descent uploads the SAME
// level-(logN-1) key column (slice it once with HHLevelKeys at
// level logN-1) — the server re-derives or replays each depth from the
// cached frontier instead of walking the tree from the root.  The reply
// bytes are the same pure function of (keys, candidates, level) whether
// the cache served, rebuilt, or was evicted mid-descent.
func (c *Client) HHEvalLevelSession(levelKeys []DPFkey, candidates []uint64, logN, level uint, session string) ([][]byte, error) {
	if len(levelKeys) == 0 || len(candidates) == 0 {
		return nil, nil
	}
	body, _, err := hhEvalBody(levelKeys, candidates)
	if err != nil {
		return nil, err
	}
	path := fmt.Sprintf(
		"/v1/hh/eval?log_n=%d&k=%d&q=%d&level=%d&format=packed",
		logN, len(levelKeys), len(candidates), level)
	if session != "" {
		path += "&session=" + url.QueryEscape(session)
	}
	out, err := c.post(path, body)
	if err != nil {
		return nil, err
	}
	return hhEvalRows(out, len(levelKeys), len(candidates))
}

// hhEvalBody serializes one hh round's upload: the key column then the
// candidate values, the body layout both fronts share.
func hhEvalBody(levelKeys []DPFkey, candidates []uint64) ([]byte, int, error) {
	kl := len(levelKeys[0])
	body := make([]byte, 0, kl*len(levelKeys)+8*len(candidates))
	for _, k := range levelKeys {
		if len(k) != kl {
			return nil, 0, fmt.Errorf("dpftpu: inconsistent key lengths")
		}
		body = append(body, k...)
	}
	for _, x := range candidates {
		body = binary.LittleEndian.AppendUint64(body, x)
	}
	return body, kl, nil
}

// hhEvalRows splits a packed hh eval reply into per-client rows.
func hhEvalRows(out []byte, k, q int) ([][]byte, error) {
	row := (q + 7) / 8
	if len(out) != k*row {
		return nil, fmt.Errorf("dpftpu: bad hh eval reply length %d", len(out))
	}
	res := make([][]byte, k)
	for i := range res {
		res[i] = out[i*row : (i+1)*row]
	}
	return res, nil
}

// HHCounts XOR-reconstructs two aggregators' packed share rows and sums
// the per-candidate client bits into counts.  The counts — and the
// threshold compare the caller runs on them — are PUBLIC by protocol
// construction (they are each round's output); see docs/DESIGN.md §13.
func HHCounts(rowsA, rowsB [][]byte, q int) ([]int, error) {
	if len(rowsA) != len(rowsB) {
		return nil, fmt.Errorf("dpftpu: hh share row counts differ")
	}
	row := (q + 7) / 8
	counts := make([]int, q)
	for i := range rowsA {
		if len(rowsA[i]) != len(rowsB[i]) {
			return nil, fmt.Errorf("dpftpu: hh share row lengths differ")
		}
		if len(rowsA[i]) < row {
			return nil, fmt.Errorf(
				"dpftpu: hh share row %d is %d bytes, need %d for q=%d",
				i, len(rowsA[i]), row, q)
		}
		for j := 0; j < q; j++ {
			if (rowsA[i][j>>3]^rowsB[i][j>>3])>>(j&7)&1 == 1 {
				counts[j]++
			}
		}
	}
	return counts, nil
}

// HHExtend extends every surviving prefix by r bits: the next round's
// candidate prefixes, depth-relative (pass through HHQueryValues for
// the wire values).
func HHExtend(survivors []uint64, r uint) []uint64 {
	out := make([]uint64, 0, len(survivors)<<r)
	for _, p := range survivors {
		for j := uint64(0); j < 1<<r; j++ {
			out = append(out, p<<r|j)
		}
	}
	return out
}

// HHQueryValues shifts depth-d candidate prefixes up to full n-bit
// domain values (the /v1/hh/eval candidate encoding).
func HHQueryValues(prefixes []uint64, logN, depth uint) []uint64 {
	out := make([]uint64, len(prefixes))
	for i, p := range prefixes {
		out[i] = p << (logN - depth)
	}
	return out
}

// AggregateSubmit streams K client share rows (W uint32 words each) to
// the sidecar's secure-aggregation fold and returns the W folded words.
// op is "xor" (XOR-shared bit vectors) or "add" (additively-shared
// uint32 vectors, summed mod 2^32); the sidecar folds the upload in
// device-sized chunks, so K can be millions of clients.
func (c *Client) AggregateSubmit(op string, rows [][]uint32) ([]uint32, error) {
	if len(rows) == 0 {
		return nil, nil
	}
	w := len(rows[0])
	body := make([]byte, 0, 4*w*len(rows))
	for _, r := range rows {
		if len(r) != w {
			return nil, fmt.Errorf("dpftpu: inconsistent agg row lengths")
		}
		for _, v := range r {
			body = binary.LittleEndian.AppendUint32(body, v)
		}
	}
	return c.AggregateSubmitRaw(op, len(rows), w, body)
}

// AggregateSubmitRaw is AggregateSubmit over a pre-packed body (K rows x
// W little-endian uint32 words) — callers replaying a campaign pack the
// body once and reuse it across requests (cmd/loadgen -mode agg-epoch).
func (c *Client) AggregateSubmitRaw(op string, k, w int, body []byte) ([]uint32, error) {
	out, err := c.post(fmt.Sprintf(
		"/v1/agg/submit?op=%s&k=%d&words=%d", op, k, w), body)
	if err != nil {
		return nil, err
	}
	if len(out) != 4*w {
		return nil, fmt.Errorf(
			"dpftpu: bad agg reply length %d, want %d", len(out), 4*w)
	}
	res := make([]uint32, w)
	for i := range res {
		res[i] = binary.LittleEndian.Uint32(out[4*i:])
	}
	return res, nil
}

// PirDBInfo is the sidecar's reply to a database registration: the
// registered shape plus how the rows were placed (shards > 0 means the
// rows live sharded over the chip mesh's HBM; stream_chunks > 1 means
// queries answer through the streamed chunk scan).
type PirDBInfo struct {
	Name         string `json:"name"`
	Rows         int    `json:"rows"`
	RowBytes     int    `json:"row_bytes"`
	LogN         uint   `json:"log_n"`
	Profile      string `json:"profile"`
	DBBytes      int64  `json:"db_bytes"`
	Shards       int    `json:"shards"`
	StreamChunks int    `json:"stream_chunks"`
}

// PirRegisterDB uploads a named 2-server PIR database to the sidecar
// (POST /v1/pir/db): rows[i] is row i's bytes, all rows the same length
// (a multiple of 4).  The sidecar reads the body in
// DPF_TPU_PIR_DB_CHUNK_BYTES chunks and keeps the packed rows resident
// in device HBM — sharded over the chip mesh when one is resolved —
// until replaced.  The database is PUBLIC protocol data (both PIR
// servers hold identical copies); the query key is the secret.
func (c *Client) PirRegisterDB(name string, rows [][]byte) (*PirDBInfo, error) {
	if len(rows) == 0 {
		return nil, fmt.Errorf("dpftpu: pir db needs >= 1 row")
	}
	rb := len(rows[0])
	body := make([]byte, 0, rb*len(rows))
	for _, r := range rows {
		if len(r) != rb {
			return nil, fmt.Errorf("dpftpu: inconsistent pir row lengths")
		}
		body = append(body, r...)
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/pir/db?name=%s&rows=%d&row_bytes=%d", name, len(rows), rb), body)
	if err != nil {
		return nil, err
	}
	info := &PirDBInfo{}
	if err := json.Unmarshal(out, info); err != nil {
		return nil, fmt.Errorf("dpftpu: bad pir db reply: %w", err)
	}
	return info, nil
}

// PirQuery answers K PIR queries against a registered database
// (POST /v1/pir/query): each key is one query's DPF share (generated at
// the database's profile and log_n — see PirDBInfo.LogN from
// PirRegisterDB).  The reply is one rowBytes-byte row per key: that
// server's XOR of the selected database rows.  XOR the two servers'
// replies to reconstruct the queried rows.
func (c *Client) PirQuery(dbName string, keys []DPFkey, rowBytes int) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	kl := len(keys[0])
	body := make([]byte, 0, kl*len(keys))
	for _, k := range keys {
		if len(k) != kl {
			return nil, fmt.Errorf("dpftpu: inconsistent key lengths")
		}
		body = append(body, k...)
	}
	out, err := c.post(fmt.Sprintf(
		"/v1/pir/query?db=%s&k=%d", dbName, len(keys)), body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(keys)*rowBytes {
		return nil, fmt.Errorf(
			"dpftpu: bad pir reply length %d, want %d*%d",
			len(out), len(keys), rowBytes)
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*rowBytes : (i+1)*rowBytes]
	}
	return res, nil
}

// EvalFullBatch expands K shares in one round trip — the entry point that
// amortizes the device dispatch and where the TPU speedup lives.  All keys
// must have the same logN; the reply is the K concatenated expansions.
func (c *Client) EvalFullBatch(keys []DPFkey, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	kl := len(keys[0])
	body := make([]byte, 0, kl*len(keys))
	for _, k := range keys {
		if len(k) != kl {
			return nil, fmt.Errorf("dpftpu: inconsistent key lengths")
		}
		body = append(body, k...)
	}
	out, err := c.post(
		fmt.Sprintf("/v1/evalfull_batch?log_n=%d&k=%d", logN, len(keys)), body)
	if err != nil {
		return nil, err
	}
	per := expansionBytes(logN, c.Profile)
	if len(out) != per*len(keys) {
		return nil, fmt.Errorf(
			"dpftpu: batch reply is %d bytes, want %d*%d (truncated or corrupt)",
			len(out), len(keys), per)
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*per : (i+1)*per]
	}
	return res, nil
}
