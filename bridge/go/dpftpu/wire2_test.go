// wire2 conformance: the binary multiplexed front must answer
// byte-identically to the HTTP/1.1 front on every compared route, carry
// the same structured errors, and survive concurrent streams on one
// connection under the race detector.
//
// Needs BOTH fronts reachable: the sidecar at DPFTPU_URL (default
// http://127.0.0.1:8990) started with DPF_TPU_WIRE2=on, and the wire2
// address in DPFTPU_WIRE2_ADDR (default 127.0.0.1:8991); otherwise the
// tests skip.  ../conformance.sh --wire2 is the one-command run.
package dpftpu

import (
	"bytes"
	"errors"
	"math/rand"
	"net"
	"net/url"
	"os"
	"sync"
	"testing"
	"time"
)

func wire2Clients(t *testing.T) (*Client, *Wire2Client) {
	t.Helper()
	httpC := conformanceClient(t)
	addr := os.Getenv("DPFTPU_WIRE2_ADDR")
	if addr == "" {
		addr = "127.0.0.1:8991"
	}
	probe, err := net.DialTimeout("tcp", addr, 2*time.Second)
	if err != nil {
		t.Skipf("wire2 front not reachable at %s (start the sidecar with "+
			"DPF_TPU_WIRE2=on or set DPFTPU_WIRE2_ADDR): %v", addr, err)
	}
	probe.Close()
	w2, err := DialWire2(addr)
	if err != nil {
		t.Fatalf("wire2 dial: %v", err)
	}
	t.Cleanup(func() { w2.Close() })
	return httpC, w2
}

// TestWire2ConformancePoints pins byte identity of the packed pointwise
// route across fronts — the dominant serving-traffic reply.
func TestWire2ConformancePoints(t *testing.T) {
	httpC, w2 := wire2Clients(t)
	const logN, q = 10, 33 // q % 8 != 0: the tail-masked packed shape
	rng := rand.New(rand.NewSource(7))
	var keys []DPFkey
	var xs [][]uint64
	for i := 0; i < 3; i++ {
		ka, _, err := httpC.Gen(uint64(rng.Int63n(1<<logN)), logN)
		if err != nil {
			t.Fatalf("gen: %v", err)
		}
		keys = append(keys, ka)
		row := make([]uint64, q)
		for j := range row {
			row[j] = uint64(rng.Int63n(1 << logN))
		}
		xs = append(xs, row)
	}
	viaHTTP, err := httpC.EvalPointsBatchPacked(keys, xs, logN)
	if err != nil {
		t.Fatalf("http points: %v", err)
	}
	viaWire2, err := w2.EvalPointsBatchPacked(keys, xs, logN)
	if err != nil {
		t.Fatalf("wire2 points: %v", err)
	}
	for i := range viaHTTP {
		if !bytes.Equal(viaHTTP[i], viaWire2[i]) {
			t.Fatalf("row %d differs across fronts", i)
		}
	}
}

// TestWire2ConformanceEvalFull pins the full-domain expansion, the
// largest buffered reply (and the route the server may stream).
func TestWire2ConformanceEvalFull(t *testing.T) {
	httpC, w2 := wire2Clients(t)
	const logN = 10
	ka, kb, err := httpC.Gen(619, logN)
	if err != nil {
		t.Fatalf("gen: %v", err)
	}
	for _, k := range []DPFkey{ka, kb} {
		viaHTTP, err := httpC.EvalFull(k, logN)
		if err != nil {
			t.Fatalf("http evalfull: %v", err)
		}
		viaWire2, err := w2.EvalFull(k, logN)
		if err != nil {
			t.Fatalf("wire2 evalfull: %v", err)
		}
		if !bytes.Equal(viaHTTP, viaWire2) {
			t.Fatal("evalfull differs across fronts")
		}
	}
}

// TestWire2ConformanceAgg pins the streamed-upload route: the body
// flows through the server's chunked fold on both fronts.
func TestWire2ConformanceAgg(t *testing.T) {
	httpC, w2 := wire2Clients(t)
	rng := rand.New(rand.NewSource(11))
	rows := make([][]uint32, 257)
	for i := range rows {
		rows[i] = make([]uint32, 16)
		for j := range rows[i] {
			rows[i][j] = rng.Uint32()
		}
	}
	for _, op := range []string{"xor", "add"} {
		viaHTTP, err := httpC.AggregateSubmit(op, rows)
		if err != nil {
			t.Fatalf("http agg %s: %v", op, err)
		}
		viaWire2, err := w2.AggregateSubmit(op, rows)
		if err != nil {
			t.Fatalf("wire2 agg %s: %v", op, err)
		}
		for j := range viaHTTP {
			if viaHTTP[j] != viaWire2[j] {
				t.Fatalf("agg %s word %d differs across fronts", op, j)
			}
		}
	}
}

// TestWire2ConformanceHH pins one heavy-hitters round across fronts —
// the descent primitive the multiplexed connection is built for.
func TestWire2ConformanceHH(t *testing.T) {
	httpC, w2 := wire2Clients(t)
	const logN, nClients = 8, 5
	values := make([]uint64, nClients)
	for i := range values {
		values[i] = uint64(i * 37 % (1 << logN))
	}
	blobA, _, err := httpC.HHGen(values, logN)
	if err != nil {
		t.Fatalf("hh gen: %v", err)
	}
	level := uint(3)
	keys, err := httpC.HHLevelKeys(blobA, logN, level)
	if err != nil {
		t.Fatalf("hh level keys: %v", err)
	}
	cands := HHQueryValues(HHExtend([]uint64{0, 1, 2, 3}, 2), logN, level+1)
	viaHTTP, err := httpC.HHEvalLevel(keys, cands, logN, level)
	if err != nil {
		t.Fatalf("http hh eval: %v", err)
	}
	viaWire2, err := w2.HHEvalLevel(keys, cands, logN, level)
	if err != nil {
		t.Fatalf("wire2 hh eval: %v", err)
	}
	for i := range viaHTTP {
		if !bytes.Equal(viaHTTP[i], viaWire2[i]) {
			t.Fatalf("hh row %d differs across fronts", i)
		}
	}
}

// TestWire2StructuredError: a validation failure surfaces as the same
// *APIError shape the HTTP front produces.
func TestWire2StructuredError(t *testing.T) {
	_, w2 := wire2Clients(t)
	_, err := w2.Do(wire2RouteEvalFull,
		url.Values{"log_n": {"9"}}, []byte{0, 1, 2})
	var apiErr *APIError
	if !errors.As(err, &apiErr) {
		t.Fatalf("want *APIError, got %v", err)
	}
	if apiErr.Status != 400 || apiErr.Code != "bad_request" {
		t.Fatalf("want 400 bad_request, got %d %q", apiErr.Status, apiErr.Code)
	}
}

// TestWire2Multiplexed: N goroutines share ONE connection; every stream
// must come back correct and uncrossed (run under -race, the whole
// point of the conformance lane).
func TestWire2Multiplexed(t *testing.T) {
	httpC, w2 := wire2Clients(t)
	const logN, q, workers, reps = 9, 16, 16, 4
	rng := rand.New(rand.NewSource(3))
	keys := make([]DPFkey, workers)
	xs := make([][][]uint64, workers)
	want := make([][][]byte, workers)
	for i := 0; i < workers; i++ {
		ka, _, err := httpC.Gen(uint64(rng.Int63n(1<<logN)), logN)
		if err != nil {
			t.Fatalf("gen: %v", err)
		}
		keys[i] = ka
		row := make([]uint64, q)
		for j := range row {
			row[j] = uint64(rng.Int63n(1 << logN))
		}
		xs[i] = [][]uint64{row}
		want[i], err = httpC.EvalPointsBatchPacked(
			[]DPFkey{ka}, xs[i], logN)
		if err != nil {
			t.Fatalf("http points: %v", err)
		}
	}
	var wg sync.WaitGroup
	errs := make(chan error, workers*reps)
	for i := 0; i < workers; i++ {
		wg.Add(1)
		go func(i int) {
			defer wg.Done()
			for r := 0; r < reps; r++ {
				got, err := w2.EvalPointsBatchPacked(
					[]DPFkey{keys[i]}, xs[i], logN)
				if err != nil {
					errs <- err
					return
				}
				if !bytes.Equal(got[0], want[i][0]) {
					errs <- errors.New("stream reply crossed")
					return
				}
			}
		}(i)
	}
	wg.Wait()
	close(errs)
	for err := range errs {
		t.Fatal(err)
	}
}
