// wire2: client for the sidecar's zero-copy multiplexed binary front
// (dpf_tpu/serving/wire2.py; enable it server-side with DPF_TPU_WIRE2=on).
//
// One Wire2Client owns ONE persistent connection carrying many concurrent
// streams — HTTP/2-style multiplexing without the HTTP: a whole
// heavy-hitter descent or aggregation campaign rides a single conn, so
// the per-request cost is a 12-byte frame header instead of a TCP
// handshake plus request-line/header parsing.  Replies are byte-identical
// to the HTTP front's (the transport-equivalence suite pins this), and
// non-200 replies carry the same structured {code, detail} JSON mapped
// onto the same *APIError type, so retry/backoff code is front-agnostic.
//
// Frame format (little-endian; docs/DESIGN.md §17):
//
//	preface        8 B: "DPF2" || version 1 || 3 zero bytes
//	frame header  12 B: length:u32 | type:u8 | flags:u8 | route:u16 | stream:u32
//	HEADERS  (1)  body_len:u64 || param string (the HTTP query string)
//	DATA     (2)  body bytes; flag bit 0 marks the last frame
//	RESP     (3)  status:u16 | reserved:u16 | retry_after:f64 | body_len:u64
//	RESP_DATA(4)  reply bytes; flag bit 0 ends the stream
//	GOAWAY   (5)  fatal: every in-flight stream fails loudly
//	PING/PONG(6/7) liveness echo
package dpftpu

import (
	"encoding/binary"
	"encoding/json"
	"fmt"
	"io"
	"math"
	"net"
	"net/url"
	"strconv"
	"sync"
	"time"
)

// Route ids — mirrors dpf_tpu/serving/handlers.ROUTE_IDS (the
// transport-equivalence suite compares replies against the HTTP paths,
// so the two tables cannot silently diverge).
const (
	wire2RouteGen             = 1
	wire2RouteEval            = 2
	wire2RouteEvalFull        = 3
	wire2RouteEvalFullBatch   = 4
	wire2RouteEvalPointsBatch = 5
	wire2RouteDcfGen          = 6
	wire2RouteDcfEvalPoints   = 7
	wire2RouteDcfIntervalGen  = 8
	wire2RouteDcfIntervalEval = 9
	wire2RouteHHGen           = 10
	wire2RouteHHEval          = 11
	wire2RouteAggSubmit       = 12
	wire2RoutePirDB           = 13
	wire2RoutePirQuery        = 14
	wire2RouteWarmup          = 15
)

const (
	wire2THeaders  = 1
	wire2TData     = 2
	wire2TResp     = 3
	wire2TRespData = 4
	wire2TGoaway   = 5
	wire2TPing     = 6
	wire2TPong     = 7

	wire2FEndStream = 1

	wire2HdrLen    = 12
	wire2DataChunk = 1 << 20
	wire2RespHead  = 20
)

var wire2Magic = []byte{'D', 'P', 'F', '2', 1, 0, 0, 0}

type wire2Pending struct {
	done       chan struct{}
	status     int
	retryAfter float64
	body       []byte
	got        int
	err        error
}

// Wire2Client drives the sidecar's wire2 front over one multiplexed
// connection.  All methods are safe for concurrent goroutines — each
// call is an independent stream.  Profile/DeadlineMs/Trace mirror the
// HTTP Client's fields and are applied per request.
type Wire2Client struct {
	Profile    string
	DeadlineMs int
	Trace      bool
	Timeout    time.Duration

	conn    net.Conn
	wmu     sync.Mutex // write side: one request's frames go out atomically
	smu     sync.Mutex // stream table
	streams map[uint32]*wire2Pending
	nextSID uint32
	dead    error
}

// DialWire2 connects to a wire2 front at addr ("host:port") and sends
// the connection preface.  Close the client to release the connection.
func DialWire2(addr string) (*Wire2Client, error) {
	conn, err := net.DialTimeout("tcp", addr, 10*time.Second)
	if err != nil {
		return nil, fmt.Errorf("dpftpu: wire2 dial: %w", err)
	}
	if tc, ok := conn.(*net.TCPConn); ok {
		tc.SetNoDelay(true)
	}
	if _, err := conn.Write(wire2Magic); err != nil {
		conn.Close()
		return nil, fmt.Errorf("dpftpu: wire2 preface: %w", err)
	}
	c := &Wire2Client{
		Profile: "compat",
		Trace:   true,
		Timeout: 120 * time.Second,
		conn:    conn,
		streams: make(map[uint32]*wire2Pending),
		nextSID: 1,
	}
	go c.readLoop()
	return c, nil
}

// Close tears the connection down; every in-flight stream fails.
func (c *Wire2Client) Close() error {
	err := c.conn.Close()
	c.failAll(fmt.Errorf("dpftpu: wire2 client closed"))
	return err
}

func (c *Wire2Client) failAll(err error) {
	c.smu.Lock()
	if c.dead == nil {
		c.dead = err
	}
	pending := make([]*wire2Pending, 0, len(c.streams))
	for sid, p := range c.streams {
		pending = append(pending, p)
		delete(c.streams, sid)
	}
	c.smu.Unlock()
	for _, p := range pending {
		p.err = err
		close(p.done)
	}
}

func (c *Wire2Client) readLoop() {
	hdr := make([]byte, wire2HdrLen)
	for {
		if _, err := io.ReadFull(c.conn, hdr); err != nil {
			c.failAll(fmt.Errorf("dpftpu: wire2 read: %w", err))
			return
		}
		length := binary.LittleEndian.Uint32(hdr[0:4])
		ftype := hdr[4]
		flags := hdr[5]
		sid := binary.LittleEndian.Uint32(hdr[8:12])
		switch ftype {
		case wire2TResp:
			payload := make([]byte, length)
			if _, err := io.ReadFull(c.conn, payload); err != nil {
				c.failAll(fmt.Errorf("dpftpu: wire2 read: %w", err))
				return
			}
			if len(payload) < wire2RespHead {
				c.failAll(fmt.Errorf("dpftpu: wire2 short RESP payload"))
				return
			}
			c.smu.Lock()
			p := c.streams[sid]
			c.smu.Unlock()
			if p == nil {
				continue
			}
			p.status = int(binary.LittleEndian.Uint16(payload[0:2]))
			p.retryAfter = math.Float64frombits(
				binary.LittleEndian.Uint64(payload[4:12]))
			p.body = make([]byte, binary.LittleEndian.Uint64(payload[12:20]))
		case wire2TRespData:
			c.smu.Lock()
			p := c.streams[sid]
			c.smu.Unlock()
			if p == nil || p.body == nil && length > 0 {
				// Reply bytes for a stream we gave up on (or a protocol
				// hiccup): drain to keep the framing.
				if _, err := io.CopyN(io.Discard, c.conn, int64(length)); err != nil {
					c.failAll(fmt.Errorf("dpftpu: wire2 read: %w", err))
					return
				}
				continue
			}
			if p.got+int(length) > len(p.body) {
				c.failAll(fmt.Errorf("dpftpu: wire2 reply overflow"))
				return
			}
			if _, err := io.ReadFull(c.conn, p.body[p.got:p.got+int(length)]); err != nil {
				c.failAll(fmt.Errorf("dpftpu: wire2 read: %w", err))
				return
			}
			p.got += int(length)
			if flags&wire2FEndStream != 0 {
				if p.got != len(p.body) {
					p.err = fmt.Errorf(
						"dpftpu: wire2 reply truncated (%d of %d bytes)",
						p.got, len(p.body))
				}
				// Only the goroutine that removes the entry may close
				// p.done — a concurrent Close()/failAll may have
				// already claimed (and closed) it, and closing twice
				// panics the process.
				c.smu.Lock()
				_, owned := c.streams[sid]
				delete(c.streams, sid)
				c.smu.Unlock()
				if owned {
					close(p.done)
				}
			}
		case wire2TPong:
			if _, err := io.CopyN(io.Discard, c.conn, int64(length)); err != nil {
				c.failAll(fmt.Errorf("dpftpu: wire2 read: %w", err))
				return
			}
		case wire2TGoaway:
			// The server's loud-truncation signal (the RST twin): every
			// in-flight reply is now unreliable.
			c.failAll(fmt.Errorf("dpftpu: wire2 server sent GOAWAY"))
			return
		default:
			c.failAll(fmt.Errorf("dpftpu: wire2 unknown frame type %d", ftype))
			return
		}
	}
}

// Do sends one request on its own stream and blocks for the reply body.
// route is a wire2Route* id; params the same query params the HTTP front
// takes (profile/deadline/trace are appended from the client fields).
// Non-200 replies surface as *APIError, exactly like the HTTP client.
func (c *Wire2Client) Do(route uint16, params url.Values, body []byte) ([]byte, error) {
	// Copy before injecting the client fields: callers reuse one
	// url.Values across concurrent Do calls (the campaign shape), and
	// mutating it here would be a concurrent map write.
	q := make(url.Values, len(params)+3)
	for k, v := range params {
		q[k] = v
	}
	q.Set("profile", c.Profile)
	if c.DeadlineMs > 0 {
		q.Set("_deadline_ms", strconv.Itoa(c.DeadlineMs))
	}
	if c.Trace {
		if id := newTraceID(); id != "" {
			q.Set("_trace", id)
		}
	}
	qs := []byte(q.Encode())

	p := &wire2Pending{done: make(chan struct{})}
	c.smu.Lock()
	if c.dead != nil {
		err := c.dead
		c.smu.Unlock()
		return nil, err
	}
	sid := c.nextSID
	c.nextSID++
	c.streams[sid] = p
	c.smu.Unlock()

	// One request's frames as a single buffered write: HEADERS
	// (body_len + params), then DATA frames split at 1 MiB.
	var headFlags byte
	if len(body) == 0 {
		headFlags = wire2FEndStream
	}
	msg := make([]byte, 0, wire2HdrLen+8+len(qs)+wire2HdrLen+len(body))
	msg = appendWire2Hdr(msg, uint32(8+len(qs)), wire2THeaders, headFlags,
		route, sid)
	msg = binary.LittleEndian.AppendUint64(msg, uint64(len(body)))
	msg = append(msg, qs...)
	for off := 0; off < len(body); {
		take := len(body) - off
		if take > wire2DataChunk {
			take = wire2DataChunk
		}
		var flags byte
		if off+take >= len(body) {
			flags = wire2FEndStream
		}
		msg = appendWire2Hdr(msg, uint32(take), wire2TData, flags, 0, sid)
		msg = append(msg, body[off:off+take]...)
		off += take
	}
	c.wmu.Lock()
	_, err := c.conn.Write(msg)
	c.wmu.Unlock()
	if err != nil {
		c.smu.Lock()
		delete(c.streams, sid)
		c.smu.Unlock()
		return nil, fmt.Errorf("dpftpu: wire2 write: %w", err)
	}

	timeout := c.Timeout
	if timeout <= 0 {
		timeout = 120 * time.Second
	}
	select {
	case <-p.done:
	case <-time.After(timeout):
		c.smu.Lock()
		delete(c.streams, sid)
		c.smu.Unlock()
		return nil, fmt.Errorf("dpftpu: wire2 stream %d timed out", sid)
	}
	if p.err != nil {
		return nil, p.err
	}
	if p.status != 200 {
		e := &APIError{Status: p.status, Detail: string(p.body)}
		var parsed struct {
			Code   string `json:"code"`
			Detail string `json:"detail"`
		}
		if json.Unmarshal(p.body, &parsed) == nil && parsed.Code != "" {
			e.Code, e.Detail = parsed.Code, parsed.Detail
		}
		e.RetryAfter = p.retryAfter
		return nil, e
	}
	return p.body, nil
}

func appendWire2Hdr(b []byte, length uint32, ftype, flags byte,
	route uint16, sid uint32) []byte {
	b = binary.LittleEndian.AppendUint32(b, length)
	b = append(b, ftype, flags)
	b = binary.LittleEndian.AppendUint16(b, route)
	b = binary.LittleEndian.AppendUint32(b, sid)
	return b
}

// Ping round-trips a liveness echo (PONG is drained by the reader).
func (c *Wire2Client) Ping() error {
	c.wmu.Lock()
	defer c.wmu.Unlock()
	msg := appendWire2Hdr(nil, 5, wire2TPing, 0, 0, 0)
	msg = append(msg, []byte("wire2")...)
	_, err := c.conn.Write(msg)
	return err
}

// ---------------------------------------------------------------------------
// Typed wrappers mirroring the HTTP client's surface — same bodies,
// same reply validation, different wire.
// ---------------------------------------------------------------------------

// Gen generates a key pair server-side, like Client.Gen.
func (c *Wire2Client) Gen(alpha uint64, logN uint) (DPFkey, DPFkey, error) {
	out, err := c.Do(wire2RouteGen, url.Values{
		"log_n": {strconv.Itoa(int(logN))},
		"alpha": {strconv.FormatUint(alpha, 10)},
	}, nil)
	if err != nil {
		return nil, nil, err
	}
	if len(out)%2 != 0 || len(out) == 0 {
		return nil, nil, fmt.Errorf("dpftpu: bad gen reply length %d", len(out))
	}
	h := len(out) / 2
	return DPFkey(out[:h]), DPFkey(out[h:]), nil
}

// EvalFull expands one share over the whole domain, like Client.EvalFull.
func (c *Wire2Client) EvalFull(k DPFkey, logN uint) ([]byte, error) {
	out, err := c.Do(wire2RouteEvalFull, url.Values{
		"log_n": {strconv.Itoa(int(logN))},
	}, k)
	if err != nil {
		return nil, err
	}
	if want := expansionBytes(logN, c.Profile); len(out) != want {
		return nil, fmt.Errorf(
			"dpftpu: evalfull reply is %d bytes, want %d (truncated or corrupt)",
			len(out), want)
	}
	return out, nil
}

// EvalPointsBatchPacked evaluates K shares at Q points each over the
// bit-packed wire format, like Client.EvalPointsBatchPacked.
func (c *Wire2Client) EvalPointsBatchPacked(keys []DPFkey, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	body, nq, err := pointsBody(keys, xs)
	if err != nil {
		return nil, err
	}
	out, err := c.Do(wire2RouteEvalPointsBatch, url.Values{
		"log_n":  {strconv.Itoa(int(logN))},
		"k":      {strconv.Itoa(len(keys))},
		"q":      {strconv.Itoa(nq)},
		"format": {"packed"},
	}, body)
	if err != nil {
		return nil, err
	}
	row := (nq + 7) / 8
	if len(out) != len(keys)*row {
		return nil, fmt.Errorf("dpftpu: bad packed reply length %d", len(out))
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*row : (i+1)*row]
	}
	return res, nil
}

// HHEvalLevel runs one heavy-hitters round, like Client.HHEvalLevel —
// the descent primitive a single multiplexed connection is built for.
func (c *Wire2Client) HHEvalLevel(levelKeys []DPFkey, candidates []uint64, logN, level uint) ([][]byte, error) {
	return c.HHEvalLevelSession(levelKeys, candidates, logN, level, "")
}

// HHEvalLevelSession is HHEvalLevel with the incremental-descent session
// contract (see Client.HHEvalLevelSession): a non-empty session id pins
// a device-resident frontier at the aggregator and every round uploads
// the same level-(logN-1) key column.  On a single multiplexed wire2
// connection this is the cheapest full descent the serving stack offers:
// one socket, one session, no per-round tree rebuild.
func (c *Wire2Client) HHEvalLevelSession(levelKeys []DPFkey, candidates []uint64, logN, level uint, session string) ([][]byte, error) {
	if len(levelKeys) == 0 || len(candidates) == 0 {
		return nil, nil
	}
	body, _, err := hhEvalBody(levelKeys, candidates)
	if err != nil {
		return nil, err
	}
	params := url.Values{
		"log_n":  {strconv.Itoa(int(logN))},
		"k":      {strconv.Itoa(len(levelKeys))},
		"q":      {strconv.Itoa(len(candidates))},
		"level":  {strconv.Itoa(int(level))},
		"format": {"packed"},
	}
	if session != "" {
		params.Set("session", session)
	}
	out, err := c.Do(wire2RouteHHEval, params, body)
	if err != nil {
		return nil, err
	}
	return hhEvalRows(out, len(levelKeys), len(candidates))
}

// AggregateSubmit streams K client share rows to the aggregation fold,
// like Client.AggregateSubmit.  rows[i] must all have the same width.
func (c *Wire2Client) AggregateSubmit(op string, rows [][]uint32) ([]uint32, error) {
	if len(rows) == 0 {
		return nil, nil
	}
	w := len(rows[0])
	body := make([]byte, 0, 4*w*len(rows))
	for _, r := range rows {
		if len(r) != w {
			return nil, fmt.Errorf("dpftpu: inconsistent agg row lengths")
		}
		for _, v := range r {
			body = binary.LittleEndian.AppendUint32(body, v)
		}
	}
	return c.AggregateSubmitRaw(op, len(rows), w, body)
}

// AggregateSubmitRaw is AggregateSubmit over a pre-packed body (K rows x
// W little-endian uint32 words) — the loadgen epoch replay packs once
// and reuses the buffer across requests.
func (c *Wire2Client) AggregateSubmitRaw(op string, k, w int, body []byte) ([]uint32, error) {
	out, err := c.Do(wire2RouteAggSubmit, url.Values{
		"op":    {op},
		"k":     {strconv.Itoa(k)},
		"words": {strconv.Itoa(w)},
	}, body)
	if err != nil {
		return nil, err
	}
	if len(out) != 4*w {
		return nil, fmt.Errorf(
			"dpftpu: bad agg reply length %d, want %d", len(out), 4*w)
	}
	res := make([]uint32, w)
	for i := range res {
		res[i] = binary.LittleEndian.Uint32(out[4*i:])
	}
	return res, nil
}

// PirQuery answers K PIR queries against a registered database, like
// Client.PirQuery (register the database over the HTTP front or with
// Wire2Client.Do on wire2RoutePirDB).
func (c *Wire2Client) PirQuery(dbName string, keys []DPFkey, rowBytes int) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	kl := len(keys[0])
	body := make([]byte, 0, kl*len(keys))
	for _, k := range keys {
		if len(k) != kl {
			return nil, fmt.Errorf("dpftpu: inconsistent key lengths")
		}
		body = append(body, k...)
	}
	out, err := c.Do(wire2RoutePirQuery, url.Values{
		"db": {dbName},
		"k":  {strconv.Itoa(len(keys))},
	}, body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(keys)*rowBytes {
		return nil, fmt.Errorf(
			"dpftpu: bad pir reply length %d, want %d*%d",
			len(out), len(keys), rowBytes)
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*rowBytes : (i+1)*rowBytes]
	}
	return res, nil
}

// DcfEvalPoints evaluates K comparison shares at Q points each, like
// Client.DcfEvalPoints (byte-per-bit format).
func (c *Wire2Client) DcfEvalPoints(keys []DPFkey, xs [][]uint64, logN uint) ([][]byte, error) {
	if len(keys) == 0 {
		return nil, nil
	}
	body, nq, err := pointsBody(keys, xs)
	if err != nil {
		return nil, err
	}
	out, err := c.Do(wire2RouteDcfEvalPoints, url.Values{
		"log_n": {strconv.Itoa(int(logN))},
		"k":     {strconv.Itoa(len(keys))},
		"q":     {strconv.Itoa(nq)},
	}, body)
	if err != nil {
		return nil, err
	}
	if len(out) != len(keys)*nq {
		return nil, fmt.Errorf("dpftpu: bad dcf points reply length %d", len(out))
	}
	res := make([][]byte, len(keys))
	for i := range keys {
		res[i] = out[i*nq : (i+1)*nq]
	}
	return res, nil
}
