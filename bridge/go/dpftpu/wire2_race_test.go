// Sidecar-free wire2 concurrency test: a local fake server speaking the
// frame protocol lets the race detector hammer the client's shared
// stream table (smu/streams, the write mutex, the readLoop hand-off)
// without any Python process — so this runs in every `go test -race`,
// not just conformance.sh.
package dpftpu

import (
	"encoding/binary"
	"fmt"
	"io"
	"math"
	"net"
	"net/url"
	"strings"
	"sync"
	"testing"
	"time"
)

// fakeWire2Server accepts ONE connection, answers every stream with
// "echo:" + its marker param, and answers PING with PONG.  Replies go
// out from per-stream goroutines with a stream-dependent delay, so
// completions land out of order — the interleaving the client's stream
// table must survive.
func fakeWire2Server(t *testing.T) string {
	t.Helper()
	ln, err := net.Listen("tcp", "127.0.0.1:0")
	if err != nil {
		t.Fatalf("listen: %v", err)
	}
	t.Cleanup(func() { ln.Close() })
	go func() {
		conn, err := ln.Accept()
		if err != nil {
			return
		}
		defer conn.Close()
		preface := make([]byte, 8)
		if _, err := io.ReadFull(conn, preface); err != nil ||
			string(preface[:4]) != "DPF2" {
			return
		}
		var wmu sync.Mutex
		reply := func(sid uint32, body []byte) {
			// Spread completion order around: stream N's reply waits
			// N%3 ms, so later streams routinely finish first.
			time.Sleep(time.Duration(sid%3) * time.Millisecond)
			msg := appendWire2Hdr(nil, wire2RespHead, wire2TResp, 0, 0, sid)
			msg = binary.LittleEndian.AppendUint16(msg, 200)
			msg = binary.LittleEndian.AppendUint16(msg, 0)
			msg = binary.LittleEndian.AppendUint64(msg,
				math.Float64bits(0))
			msg = binary.LittleEndian.AppendUint64(msg, uint64(len(body)))
			msg = appendWire2Hdr(msg, uint32(len(body)), wire2TRespData,
				wire2FEndStream, 0, sid)
			msg = append(msg, body...)
			wmu.Lock()
			conn.Write(msg)
			wmu.Unlock()
		}
		markers := map[uint32]string{}
		hdr := make([]byte, wire2HdrLen)
		for {
			if _, err := io.ReadFull(conn, hdr); err != nil {
				return
			}
			length := binary.LittleEndian.Uint32(hdr[0:4])
			ftype := hdr[4]
			flags := hdr[5]
			sid := binary.LittleEndian.Uint32(hdr[8:12])
			payload := make([]byte, length)
			if _, err := io.ReadFull(conn, payload); err != nil {
				return
			}
			switch ftype {
			case wire2THeaders:
				q, _ := url.ParseQuery(string(payload[8:]))
				markers[sid] = q.Get("marker")
				if flags&wire2FEndStream != 0 {
					go reply(sid, []byte("echo:"+markers[sid]))
				}
			case wire2TData:
				if flags&wire2FEndStream != 0 {
					go reply(sid, []byte("echo:"+markers[sid]))
				}
			case wire2TPing:
				pong := appendWire2Hdr(nil, length, wire2TPong, 0, 0, 0)
				pong = append(pong, payload...)
				wmu.Lock()
				conn.Write(pong)
				wmu.Unlock()
			default:
				return
			}
		}
	}()
	return ln.Addr().String()
}

// TestStreamTableRace: 16 goroutines multiplex requests and pings on
// ONE client; every reply must match ITS stream's marker, and the
// pending-stream table must drain to empty (a leaked entry is a reply
// delivered to the wrong waiter or dropped).  Run under -race this
// covers the smu/streams handoff between Do, readLoop, and Ping.
func TestStreamTableRace(t *testing.T) {
	addr := fakeWire2Server(t)
	c, err := DialWire2(addr)
	if err != nil {
		t.Fatalf("dial: %v", err)
	}
	defer c.Close()
	c.Trace = false

	const workers, reps = 16, 8
	var wg sync.WaitGroup
	errs := make(chan error, workers*reps)
	for i := 0; i < workers; i++ {
		wg.Add(1)
		go func(i int) {
			defer wg.Done()
			for r := 0; r < reps; r++ {
				marker := fmt.Sprintf("w%d-r%d", i, r)
				var body []byte
				if r%2 == 1 { // odd reps exercise the DATA path too
					body = []byte(strings.Repeat("x", 64))
				}
				got, err := c.Do(wire2RouteWarmup,
					url.Values{"marker": {marker}}, body)
				if err != nil {
					errs <- err
					return
				}
				if string(got) != "echo:"+marker {
					errs <- fmt.Errorf(
						"stream crossed: want echo:%s, got %q", marker, got)
					return
				}
				if r%3 == 0 {
					if err := c.Ping(); err != nil {
						errs <- err
						return
					}
				}
			}
		}(i)
	}
	wg.Wait()
	close(errs)
	for err := range errs {
		t.Fatal(err)
	}
	c.smu.Lock()
	leaked := len(c.streams)
	c.smu.Unlock()
	if leaked != 0 {
		t.Fatalf("stream table leaked %d entries", leaked)
	}
}
