// Command contract-dump emits the Go bridge's wire surface as JSON on
// stdout: route consts, client paths, wire2 frame types/flags/sizes,
// the connection preface, header names, the APIError code vocabulary,
// and the wire2 pseudo-params.
//
// It is the go/ast twin of the Python regex fallback in
// dpf_tpu/analysis/contract/go_extract.py — both emit the exact same
// JSON shape, pinned against each other by the committed golden dump
// (dpf_tpu/analysis/fixtures/bad_contract/go_dump_golden.json).  The
// `contract` step of bridge/go/conformance.sh pipes this output into
// `python -m dpf_tpu.analysis.contract --check-go-dump -`, which diffs
// it against the committed docs/CONTRACT.json.
//
// Run from bridge/go:  go run ./cmd/contract-dump
package main

import (
	"encoding/json"
	"fmt"
	"go/ast"
	"go/parser"
	"go/token"
	"os"
	"regexp"
	"sort"
	"strconv"
	"strings"
	"unicode"
)

var sourceFiles = []string{"dpftpu/client.go", "dpftpu/wire2.go"}

type dump struct {
	Routes      map[string]int `json:"routes"`
	ClientPaths []string       `json:"client_paths"`
	FrameTypes  map[string]int `json:"frame_types"`
	Flags       map[string]int `json:"flags"`
	HdrLen      int            `json:"hdr_len"`
	RespHeadLen int            `json:"resp_head_len"`
	DataChunk   int            `json:"data_chunk"`
	Magic       string         `json:"magic"`
	Headers     []string       `json:"headers"`
	ErrorCodes  map[string]int `json:"error_codes"`
	Params      []string       `json:"params"`
}

// camelToUpperSnake mirrors go_extract.camel_to_upper_snake:
// RespData -> RESP_DATA, EndStream -> END_STREAM, Goaway -> GOAWAY.
func camelToUpperSnake(s string) string {
	r := []rune(s)
	var b strings.Builder
	for i, c := range r {
		if i > 0 && unicode.IsUpper(c) {
			prev := r[i-1]
			boundary := unicode.IsLower(prev) || unicode.IsDigit(prev)
			if !boundary && unicode.IsUpper(prev) && i+1 < len(r) {
				boundary = unicode.IsLower(r[i+1])
			}
			if boundary {
				b.WriteByte('_')
			}
		}
		b.WriteRune(unicode.ToUpper(c))
	}
	return b.String()
}

// isUpperSuffix reports whether id is prefix followed by an upper-case
// camel suffix — mirrors the fallback's `wire2T([A-Z]\w*)` patterns so
// a future lower-camel const (wire2Timeout) cannot classify as a frame
// type in one extractor and not the other.
func isUpperSuffix(id, prefix string) bool {
	if !strings.HasPrefix(id, prefix) || len(id) == len(prefix) {
		return false
	}
	return unicode.IsUpper(rune(id[len(prefix)]))
}

// evalInt handles the two const-expression forms the bridge uses:
// plain int literals and `1 << 20`-style shifts.
func evalInt(e ast.Expr) (int, bool) {
	switch v := e.(type) {
	case *ast.BasicLit:
		if v.Kind == token.INT {
			n, err := strconv.Atoi(v.Value)
			return n, err == nil
		}
	case *ast.BinaryExpr:
		if v.Op == token.SHL {
			l, lok := evalInt(v.X)
			r, rok := evalInt(v.Y)
			if lok && rok {
				return l << r, true
			}
		}
	case *ast.ParenExpr:
		return evalInt(v.X)
	}
	return 0, false
}

func litByte(e ast.Expr) (byte, bool) {
	if lit, ok := e.(*ast.BasicLit); ok {
		switch lit.Kind {
		case token.CHAR:
			c, _, _, err := strconv.UnquoteChar(
				strings.Trim(lit.Value, "'"), '\'')
			return byte(c), err == nil
		case token.INT:
			n, err := strconv.Atoi(lit.Value)
			return byte(n), err == nil
		}
	}
	return 0, false
}

var (
	pathRe  = regexp.MustCompile(`^(/v1/[a-z_/]+)(\?|$)`)
	codeRe  = regexp.MustCompile(`"(\w+)"\s*\((\d+)`)
	hdrRe   = regexp.MustCompile(`^(X-DPF-[\w-]+|Retry-After)$`)
	paramRe = regexp.MustCompile(`^_\w+$`)
)

func sortedKeys(set map[string]bool) []string {
	out := make([]string, 0, len(set))
	for k := range set {
		out = append(out, k)
	}
	sort.Strings(out)
	return out
}

func main() {
	d := dump{
		Routes:     map[string]int{},
		FrameTypes: map[string]int{},
		Flags:      map[string]int{},
		ErrorCodes: map[string]int{},
	}
	paths := map[string]bool{}
	headers := map[string]bool{}
	params := map[string]bool{}

	fset := token.NewFileSet()
	for _, file := range sourceFiles {
		f, err := parser.ParseFile(fset, file, nil, parser.ParseComments)
		if err != nil {
			fmt.Fprintf(os.Stderr, "contract-dump: %v\n", err)
			os.Exit(1)
		}
		ast.Inspect(f, func(n ast.Node) bool {
			switch node := n.(type) {
			case *ast.ValueSpec:
				for i, name := range node.Names {
					if i >= len(node.Values) {
						continue
					}
					val, ok := evalInt(node.Values[i])
					if !ok {
						continue
					}
					id := name.Name
					switch {
					case strings.HasPrefix(id, "wire2Route"):
						d.Routes[strings.TrimPrefix(id, "wire2Route")] = val
					case isUpperSuffix(id, "wire2T"):
						d.FrameTypes[camelToUpperSnake(
							strings.TrimPrefix(id, "wire2T"))] = val
					case isUpperSuffix(id, "wire2F"):
						d.Flags[camelToUpperSnake(
							strings.TrimPrefix(id, "wire2F"))] = val
					case id == "wire2HdrLen":
						d.HdrLen = val
					case id == "wire2RespHead":
						d.RespHeadLen = val
					case id == "wire2DataChunk":
						d.DataChunk = val
					}
				}
				// var wire2Magic = []byte{'D', 'P', 'F', '2', 1, 0, 0, 0}
				for i, name := range node.Names {
					if name.Name != "wire2Magic" || i >= len(node.Values) {
						continue
					}
					lit, ok := node.Values[i].(*ast.CompositeLit)
					if !ok {
						continue
					}
					raw := make([]byte, 0, len(lit.Elts))
					for _, el := range lit.Elts {
						if b, ok := litByte(el); ok {
							raw = append(raw, b)
						}
					}
					d.Magic = fmt.Sprintf("%x", raw)
				}
			case *ast.BasicLit:
				if node.Kind != token.STRING {
					return true
				}
				s, err := strconv.Unquote(node.Value)
				if err != nil {
					return true
				}
				if m := pathRe.FindStringSubmatch(s); m != nil {
					paths[m[1]] = true
				}
				if hdrRe.MatchString(s) {
					headers[s] = true
				}
			case *ast.CallExpr:
				sel, ok := node.Fun.(*ast.SelectorExpr)
				if !ok || sel.Sel.Name != "Set" || len(node.Args) == 0 {
					return true
				}
				if lit, ok := node.Args[0].(*ast.BasicLit); ok &&
					lit.Kind == token.STRING {
					if s, err := strconv.Unquote(lit.Value); err == nil &&
						paramRe.MatchString(s) {
						params[s] = true
					}
				}
			case *ast.GenDecl:
				// The APIError doc comment is the Go side's statement
				// of the error vocabulary.
				if node.Tok != token.TYPE || node.Doc == nil {
					return true
				}
				for _, spec := range node.Specs {
					ts, ok := spec.(*ast.TypeSpec)
					if !ok || ts.Name.Name != "APIError" {
						continue
					}
					for _, m := range codeRe.FindAllStringSubmatch(
						node.Doc.Text(), -1) {
						status, err := strconv.Atoi(m[2])
						if err == nil {
							d.ErrorCodes[m[1]] = status
						}
					}
				}
			}
			return true
		})
	}

	d.ClientPaths = sortedKeys(paths)
	d.Headers = sortedKeys(headers)
	d.Params = sortedKeys(params)

	enc := json.NewEncoder(os.Stdout)
	if err := enc.Encode(d); err != nil {
		fmt.Fprintf(os.Stderr, "contract-dump: %v\n", err)
		os.Exit(1)
	}
}
