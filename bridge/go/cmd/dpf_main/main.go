// Driver mirroring the reference CLI (dpf_main.go: Gen(123, 27) then 100
// timed EvalFull calls) against the dpf_tpu sidecar instead of the
// in-process library.  Also exercises the batched entry point, which is
// where the TPU backend's throughput actually shows.
//
// Usage:
//
//	python -m dpf_tpu.server --port 8990 &
//	go run ./cmd/dpf_main -addr http://127.0.0.1:8990 -logn 20 -reps 10
package main

import (
	"flag"
	"fmt"
	"log"
	"time"

	"github.com/dpf-tpu/bridge/go/dpftpu"
)

func main() {
	addr := flag.String("addr", "http://127.0.0.1:8990", "sidecar base URL")
	logN := flag.Uint("logn", 20, "domain size log2 (reference used 27)")
	reps := flag.Int("reps", 100, "EvalFull repetitions (reference used 100)")
	batch := flag.Int("batch", 0, "if >0, also run one EvalFullBatch of this many keys")
	profile := flag.String("profile", "compat", "evaluation profile: compat | fast")
	flag.Parse()

	c := dpftpu.New(*addr)
	c.Profile = *profile

	a, b, err := c.Gen(123, *logN)
	if err != nil {
		log.Fatal(err)
	}

	// Sanity: the two shares must reconstruct the point function at 123.
	bitA, err := c.Eval(a, 123, *logN)
	if err != nil {
		log.Fatal(err)
	}
	bitB, err := c.Eval(b, 123, *logN)
	if err != nil {
		log.Fatal(err)
	}
	if bitA^bitB != 1 {
		log.Fatalf("reconstruction failed: %d ^ %d != 1", bitA, bitB)
	}

	evalStart := time.Now()
	for i := 0; i < *reps; i++ {
		if _, err := c.EvalFull(a, *logN); err != nil {
			log.Fatal(err)
		}
	}
	fmt.Println("EvalFull time", time.Since(evalStart))

	if *batch > 0 {
		keys := make([]dpftpu.DPFkey, *batch)
		for i := range keys {
			keys[i] = a
		}
		t0 := time.Now()
		if _, err := c.EvalFullBatch(keys, *logN); err != nil {
			log.Fatal(err)
		}
		dt := time.Since(t0)
		leaves := float64(*batch) * float64(uint64(1)<<*logN)
		fmt.Printf("EvalFullBatch k=%d time %v (%.2f Gleaves/s incl. transfer)\n",
			*batch, dt, leaves/dt.Seconds()/1e9)
	}
}
